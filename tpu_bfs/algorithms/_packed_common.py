"""Shared machinery of the 4096-lane packed MS-BFS engines.

msbfs_wide.py (gather-only) and msbfs_hybrid.py (MXU dense tiles + gather
residual) differ in their frontier-table height, lane-to-(word, bit) map, and
per-level hit computation — everything else (fori-loop bucket expansion,
seeding, device-side lane stats, lazy per-word distance extraction, the
generic batch ``run``) lives here once.

Engines plug in via a small protocol: attributes ``arrs``, ``lanes``,
``max_levels_cap``, ``num_planes``, ``undirected``, ``_rank``, ``_warmed``,
``num_vertices``; jitted callables ``_core`` (returning planes, vis, levels,
alive, truncated), ``_seed_dev``, ``_lane_stats`` (degree data captured at
build, make_state_kernels), ``_extract_word``; and the two lane-map hooks
``_word_col`` / ``_lane_order``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs
from tpu_bfs.graph.csr import INF_DIST
from tpu_bfs.algorithms.msbfs_packed import UNREACHED, ripple_increment


def floor_lanes(lanes: int) -> int:
    """Largest REACHABLE lane count <= ``lanes``: a power-of-two uint32
    word count times 32 (all auto sizing can ever select). The one
    definition of "reachable width" shared by auto_lanes, the hybrid
    engine's width ladder, and the bench's env clamp."""
    w = max(lanes // 32, 1)
    return 32 << (w.bit_length() - 1)


def tpu_padded_words(w: int) -> int:
    """Physical minor-dim words XLA allocates for a [rows, w] 32-bit table
    on TPU: the native tile is (8, 128), so the minor dimension pads up to
    a multiple of 128. Measured, not theoretical: the round-4 LJ OOM
    report shows u32[2591042,64] allocated at 1.24G — 2.0x its 632.58M
    unpadded size ("Extra memory due to padding ... (2.0x expansion)").
    Sizing that ignores this believes narrow rows save HBM they don't:
    below 128 words (4096 lanes), narrowing the batch buys NOTHING on
    TPU — only fewer planes or fewer rows shrink the state."""
    return -(-w // 128) * 128


class PackedStateDoesntFitError(ValueError):
    """Even the narrowest packed table cannot fit the HBM budget: on TPU a
    32-lane [rows, 1]-word table occupies the same physical HBM as 128
    words (tpu_padded_words), so no width shrink can help — the real
    levers are fewer planes, fewer rows (shard over a mesh), or shedding
    optional state (push table, dense-tile budget)."""


def auto_lanes(
    rows: int,
    num_planes: int,
    *,
    fixed_bytes: int = 0,
    hbm_budget_bytes: int = int(14.0e9),
    max_lanes: int = 4096,
    on_unfit: str = "floor",
) -> int:
    """Largest lane count whose packed state fits the HBM budget.

    The level loop keeps ~(num_planes + 6) live [rows, w] uint32 tables
    (frontier, next, hit(s), visited, planes, expansion transients —
    calibrated against the scale-21 runs on a 16 GB v5e); ``fixed_bytes``
    covers lane-independent residents (ELL indices, dense tiles). Each
    table is priced at its PHYSICAL width (:func:`tpu_padded_words`:
    sub-128-word rows pad to 128 on TPU — the round-4 LJ run OOM'd
    because the previous byte-exact model credited w=64 with a halving
    it doesn't get). Returns the largest power-of-two word count times 32
    that fits, floored at 32 lanes. Below 128 words the TPU need no
    longer shrinks, so when w=128 doesn't fit the walk falls through to
    the 32-lane floor: the small batch is still cheaper to RUN (and
    genuinely smaller on CPU), but on TPU the caller's real levers are
    fewer planes, sharding over a mesh, or shedding optional state.

    ``on_unfit='raise'`` turns that fall-through into a
    :class:`PackedStateDoesntFitError` at SIZING time when even the
    floor's physical footprint exceeds the budget (ADVICE r4: the engine
    constructors otherwise accept the unfit width and die minutes later
    in an opaque runtime RESOURCE_EXHAUSTED); ``'floor'`` (default) keeps
    the legacy estimate semantics for callers that only compare widths
    (auto_planes' probe, the bench's engine-selection pre-check).
    """
    if on_unfit not in ("floor", "raise"):
        raise ValueError(f"on_unfit must be floor|raise, got {on_unfit!r}")
    w = floor_lanes(max_lanes) // 32
    while w > 1:
        need = (num_planes + 6) * rows * tpu_padded_words(w) * 4 + fixed_bytes
        if need <= hbm_budget_bytes:
            break
        w //= 2
    if on_unfit == "raise" and w == 1:
        need = (num_planes + 6) * rows * tpu_padded_words(1) * 4 + fixed_bytes
        if need > hbm_budget_bytes:
            raise PackedStateDoesntFitError(
                f"packed state cannot fit: {rows} rows x {num_planes} "
                f"planes needs {need/1e9:.2f} GB at the narrowest physical "
                f"width (32 lanes pads to 128 words on TPU) vs the "
                f"{hbm_budget_bytes/1e9:.2f} GB budget "
                f"({fixed_bytes/1e9:.2f} GB fixed residents). Levers: "
                f"fewer planes, shard rows over more chips, or shed "
                f"optional state (adaptive push table, dense-tile budget)."
            )
    return 32 * w


def auto_planes(
    rows: int,
    *,
    fixed_bytes: int = 0,
    hbm_budget_bytes: int = int(14.0e9),
    preferred: int = 5,
    min_planes: int = 4,
    max_lanes: int = 4096,
) -> int:
    """Largest plane count <= ``preferred`` whose packed state still fits
    ``max_lanes`` lanes in the HBM budget (same memory model as
    :func:`auto_lanes`).

    Each plane halves-or-doubles nothing about correctness — it bounds the
    traversal depth at 2**planes levels — so trading planes for lanes is the
    right call on low-diameter (power-law) graphs: 4 planes still label 16
    levels, ample for RMAT/social graphs, while keeping the full 4096-lane
    batch at one scale step larger than ``preferred`` planes would allow.
    When even ``min_planes`` cannot reach ``max_lanes``, returns
    ``preferred`` — depth capacity is worth more than lanes once the width
    has to shrink anyway (the engine then lowers lanes or falls back).
    """
    for p in range(preferred, min_planes - 1, -1):
        if (
            auto_lanes(
                rows, p, fixed_bytes=fixed_bytes,
                hbm_budget_bytes=hbm_budget_bytes, max_lanes=max_lanes,
            )
            == max_lanes
        ):
            return p
    return preferred


# --- The pull gate (ISSUE 1): frontier-aware pull expansion. -------------
#
# The pull phases are frontier-independent by construction — the whole lane
# table is scanned every level (the roofline byte model names this, see
# utils/roofline.py phase_bytes). The gate keys every level's pull work on
# a SETTLED mask instead: a row is settled once every ACTIVE lane (batch
# entries that actually seeded a device row) has visited it, i.e.
# ``vis[r] == lane_mask``. A settled row can never claim again
# (``hit & ~vis`` is empty on every active lane, and frontier words only
# ever carry seeded lanes' bits), so all work producing its hit — bucket
# gathers, the fold pyramid, the permutation, the claim and plane ripple —
# is skippable with bit-identical distances/parents. The skipped work is
# compacted away with the exact mechanism the adaptive push already uses
# (``jnp.where(..., size=cap)`` index tables + a dynamically-bounded fori),
# at GATE_TILE-row block granularity so slices stay TPU-tileable.

GATE_TILE = 128  # settled-mask granularity: rows per gate block
# The block-compacted serial loop only wins when most blocks are settled;
# at peak levels (everything active) the vectorized pass is strictly
# better, so each gated pass falls back densely above this active
# fraction. Pure performance policy — both branches are bit-identical.
GATE_DENSE_DEN = 4  # gated path only when active blocks <= total / 4


def host_lane_mask(rows_of_sources: np.ndarray, act: int, w: int) -> np.ndarray:
    """[w] uint32 active-lane mask for the pull gate: the OR of every
    non-isolated batch entry's (word, bit) seed slot (same keep rule as
    seed_scatter_args; word-major, the lane map every gated engine uses).
    Lanes outside the batch — and isolated-source lanes, which never touch
    the device — are vacuously settled. All-ones is always a SAFE
    fallback: an over-wide mask only delays settling, never changes
    results (a too-NARROW mask would skip live claims, so the mask must
    cover every seeded lane)."""
    ranks = np.asarray(rows_of_sources, dtype=np.int64)
    lanes = np.arange(len(ranks))
    keep = ranks < act
    mask = np.zeros(w, np.uint32)
    np.bitwise_or.at(
        mask,
        lanes[keep] // 32,
        np.uint32(1) << (lanes[keep] % 32).astype(np.uint32),
    )
    return mask


def row_unsettled(vis, act: int, lane_mask):
    """[rows] bool: True where a real row (< ``act``) still has an active
    lane unvisited — the row can still claim, so its pull work must run."""
    uns = jnp.any((~vis & lane_mask[None, :]) != 0, axis=1)
    rows = vis.shape[0]
    return uns & (jax.lax.iota(jnp.int32, rows) < act)


def make_gated_fori_expand(spec: "ExpandSpec", w: int, *, combine=None,
                           identity: int = 0):
    """Frontier-gated bucketed-ELL expansion — make_fori_expand's shape,
    keyed on a per-bucket-output-row ``needed`` vector.

    Light buckets process only the GATE_TILE-row blocks holding a needed
    row (compacted block ids + a dynamically-bounded fori, each block
    sliced out of the padded ``light{i}_gt`` table —
    graph/ell.pad_gate_blocks); the heavy virtual/fold-pyramid section is
    skipped outright once every heavy destination row has settled (hubs
    settle first on power-law graphs, so the whole-section skip captures
    the win without per-virtual-row bookkeeping). Every gated pass falls
    back to the dense form via lax.cond when most blocks are still active
    (GATE_DENSE_DEN). Skipped rows come out as ``identity`` — exactly the
    value whose claim the caller masks away.

    Returns ``expand(arrs, fw, needed) -> (outputs, skipped_blocks)``.
    """
    if combine is None:
        combine = jnp.bitwise_or
    ident = jnp.uint32(identity)
    T = GATE_TILE

    def _full(shape):
        return jnp.full(shape, ident, jnp.uint32)

    heavy_blocks = -(-spec.num_virtual // T) if spec.heavy else 0

    def expand(arrs, fw, needed):
        parts = []
        skipped = jnp.int32(0)
        off = 0
        if spec.heavy:
            nh = arrs["heavy_pick"].shape[0]
            vr_t = arrs["virtual_t"]

            def heavy_section():
                def vbody(kk, acc):
                    return combine(acc, fw[vr_t[kk]])

                acc = jax.lax.fori_loop(
                    0, spec.kcap, vbody, _full((spec.num_virtual, w))
                )
                vr_ext = jnp.concatenate([acc, _full((1, w))])
                cur = vr_ext[arrs["fold_pad_map"]]
                pyramid = [cur]
                for _ in range(spec.fold_steps):
                    pairs = cur.reshape(-1, 2, w)
                    cur = combine(pairs[:, 0], pairs[:, 1])
                    pyramid.append(cur)
                pyr = (
                    jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
                )
                return pyr[arrs["heavy_pick"]]

            h_need = jnp.any(needed[:nh])
            parts.append(
                jax.lax.cond(h_need, heavy_section, lambda: _full((nh, w)))
            )
            skipped = skipped + jnp.where(h_need, 0, heavy_blocks)
            off = nh
        for i, (k, n) in enumerate(spec.light_meta):
            bt = arrs[f"light{i}_t"]  # [k, n]
            gt = arrs[f"light{i}_gt"]  # [k, nb*T] sentinel-padded
            nb = gt.shape[1] // T
            need = needed[off : off + n]
            pad = nb * T - n
            if pad:
                need = jnp.concatenate([need, jnp.zeros((pad,), bool)])
            blk = jnp.any(need.reshape(nb, T), axis=1)
            nzb = jnp.sum(blk.astype(jnp.int32))
            take_gated = nzb * GATE_DENSE_DEN <= nb

            def dense_pass(bt=bt, k=k, n=n):
                def lbody(kk, acc):
                    return combine(acc, fw[bt[kk]])

                return jax.lax.fori_loop(0, k, lbody, _full((n, w)))

            def gated_pass(gt=gt, k=k, n=n, nb=nb, blk=blk, nzb=nzb):
                idx = jnp.where(blk, size=nb, fill_value=0)[0]

                def bbody(j, acc):
                    b = idx[j]
                    cols = jax.lax.dynamic_slice(gt, (0, b * T), (k, T))

                    def kbody(kk, a):
                        return combine(a, fw[cols[kk]])

                    ablk = jax.lax.fori_loop(0, k, kbody, _full((T, w)))
                    return jax.lax.dynamic_update_slice(acc, ablk, (b * T, 0))

                acc = jax.lax.fori_loop(0, nzb, bbody, _full((nb * T, w)))
                return acc[:n]

            parts.append(jax.lax.cond(take_gated, gated_pass, dense_pass))
            skipped = skipped + jnp.where(take_gated, nb - nzb, 0)
            off += n
        if spec.tail_rows:
            parts.append(_full((spec.tail_rows, w)))
        out = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        return out, skipped

    return expand


def gated_state_update(hit, vis, planes, need_rows):
    """Claim + visited-OR + plane ripple over only the GATE_TILE row blocks
    still holding an unsettled row — the pull gate's state pass.

    Skipped blocks are bit-identical to the dense update on everything any
    extraction reads: their claim is zero (settled rows' ``hit & ~vis`` is
    empty on every active lane), visited is unchanged, and the only plane
    bits the dense ripple would still move there belong to inactive lanes
    or pad rows — positions no distance extraction ever decodes. The
    ragged tail block (< GATE_TILE rows; sentinel/pad rows live there)
    always updates densely. Falls back to the one-shot dense update via
    lax.cond when most blocks are active (GATE_DENSE_DEN).

    Returns ``(nxt, vis2, planes2)``.
    """
    T = GATE_TILE
    rows, w = vis.shape
    nt = rows // T
    tail = rows - nt * T

    def dense():
        nxt = hit & ~vis
        vis2 = vis | nxt
        return nxt, vis2, ripple_increment(planes, ~vis2)

    if nt == 0:
        return dense()
    blk = jnp.any(need_rows[: nt * T].reshape(nt, T), axis=1)
    nzt = jnp.sum(blk.astype(jnp.int32))

    def gated():
        idx = jnp.where(blk, size=nt, fill_value=0)[0]

        def bbody(j, carry):
            nxt, vis2, pl = carry
            off = idx[j] * T
            h = jax.lax.dynamic_slice(hit, (off, 0), (T, w))
            v = jax.lax.dynamic_slice(vis2, (off, 0), (T, w))
            nx = h & ~v
            v2 = v | nx
            p_t = tuple(
                jax.lax.dynamic_slice(p, (off, 0), (T, w)) for p in pl
            )
            p2 = ripple_increment(p_t, ~v2)
            return (
                jax.lax.dynamic_update_slice(nxt, nx, (off, 0)),
                jax.lax.dynamic_update_slice(vis2, v2, (off, 0)),
                tuple(
                    jax.lax.dynamic_update_slice(p, q, (off, 0))
                    for p, q in zip(pl, p2)
                ),
            )

        nxt, vis2, pl = jax.lax.fori_loop(
            0, nzt, bbody, (jnp.zeros_like(vis), vis, planes)
        )
        if tail:
            h = hit[nt * T :]
            v = vis2[nt * T :]
            nx = h & ~v
            v2 = v | nx
            p2 = ripple_increment(tuple(p[nt * T :] for p in pl), ~v2)
            nxt = jax.lax.dynamic_update_slice(nxt, nx, (nt * T, 0))
            vis2 = jax.lax.dynamic_update_slice(vis2, v2, (nt * T, 0))
            pl = tuple(
                jax.lax.dynamic_update_slice(p, q, (nt * T, 0))
                for p, q in zip(pl, p2)
            )
        return nxt, vis2, pl

    return jax.lax.cond(nzt * GATE_DENSE_DEN <= nt, gated, dense)


class PullGateHost:
    """Mixin for pull-gated packed engines: host-side lane-mask bookkeeping
    plus the single-chip core wrappers that thread the mask into the gated
    jitted loop and record the per-level skipped-block counters
    (``last_gate_level_counts`` — same host-attribute idiom as the
    distributed engines' exchange accounting, collectives.py). Hosts set
    ``pull_gate``, ``_gate_core_jit`` / ``_gate_core_from_jit`` /
    ``_gate_core_from_donate_jit`` (make_packed_loop gated entries),
    ``_lane_mask_dev`` (all-ones until the first batch refines it —
    always safe, see host_lane_mask), and the engine-protocol attributes
    ``_rank`` / ``_act`` / ``w``."""

    pull_gate = False
    last_gate_level_counts = None

    def _note_batch_sources(self, sources) -> None:
        if not self.pull_gate:
            return
        rows = np.asarray(self._rank)[np.asarray(sources, dtype=np.int64)]
        self._lane_mask_dev = jnp.asarray(
            host_lane_mask(rows, self._act, self.w)
        )

    def _gated_core(self, arrs, fw0, max_levels):
        planes, vis, levels, alive, truncated, gc = self._gate_core_jit(
            arrs, fw0, max_levels, self._lane_mask_dev
        )
        # Kept as a device array so the record costs nothing inside a
        # timed batch; np.asarray it at read time (stats/CLI do).
        self.last_gate_level_counts = gc
        return planes, vis, levels, alive, truncated

    def _gated_core_from(self, arrs, fw, vis, planes, level0, max_levels):
        fw_f, vis_f, planes_f, level, alive, gc = self._gate_core_from_jit(
            arrs, fw, vis, planes, level0, max_levels, self._lane_mask_dev
        )
        self.last_gate_level_counts = gc
        return fw_f, vis_f, planes_f, level, alive

    def _gated_core_from_donate(self, arrs, fw, vis, planes, level0,
                                max_levels):
        """The donating resume entry (ISSUE 13): same loop, carry
        donated — advance_packed_batch's path, whose converted
        checkpoint carries are dead after the call."""
        fw_f, vis_f, planes_f, level, alive, gc = (
            self._gate_core_from_donate_jit(
                arrs, fw, vis, planes, level0, max_levels,
                self._lane_mask_dev
            )
        )
        self.last_gate_level_counts = gc
        return fw_f, vis_f, planes_f, level, alive

    def _core_from_probe(self, arrs, fw, vis, planes, level0, max_levels):
        """advance's cap-boundary probe entry: the same gated loop, minus
        the counter record — the probe's one boundary body must not
        clobber the real run's per-level counts. Ungated instances
        delegate to the exact pre-gate probe resolution (raw jitted loop
        where the engine has one, else _core_from)."""
        if not self.pull_gate:
            fn = getattr(self, "_core_from_jit", None) or self._core_from
            return fn(arrs, fw, vis, planes, level0, max_levels)
        return self._gate_core_from_jit(
            arrs, fw, vis, planes, level0, max_levels, self._lane_mask_dev
        )[:5]


def make_packed_loop(hit_of, num_planes: int, *, gate_levels: int = 0,
                     act: int | None = None):
    """The level loop shared by the wide and hybrid engines, as two jitted
    entry points over one body:

    - ``core(arrs, fw0, max_levels)`` — a fresh traversal (the historical
      signature): visited starts as the seed table, planes at zero;
    - ``core_from(arrs, fw, vis, planes, level0, max_levels)`` — resume from
      mid-traversal state, the checkpoint/restart entry (the reference has
      no checkpointing at all, SURVEY.md §5). Because the while-loop carry
      IS the traversal state, resuming from a saved carry is bit-identical
      to never having stopped.

    ``hit_of(arrs, fw)`` is the engine's one-level frontier expansion
    (gather-only for the wide engine; MXU tiles + gather residual +
    permutation for the hybrid).

    With ``gate_levels`` > 0 the loop runs in PULL-GATED mode (``act``
    required): ``hit_of(arrs, fw, vis, lane_mask)`` returns
    ``(hit, skipped_blocks)``, both entry points take a trailing
    ``lane_mask`` argument (host_lane_mask), the state pass runs gated
    over unsettled GATE_TILE blocks (gated_state_update), and both return
    a trailing [gate_levels] int32 per-level skipped-block array.

    Returns ``(core, core_from, core_from_donate)`` — the third is
    ``core_from`` with the carry (fw/vis/planes) DONATED (ISSUE 13,
    analysis pass 5): the resume path's outputs alias its inputs instead
    of doubling the table residency per chunk. ``advance_packed_batch``
    rides the donating entry (its converted checkpoint carries are dead
    after the call by construction); ``core_from`` stays copying for the
    callers that re-read their carries — the cap-boundary probe (which
    must keep the pre-probe tables) and the roofline's CPU stepping
    (which warms by double-calling the same arguments).
    """
    gated = gate_levels > 0
    if gated and act is None:
        raise ValueError("gated make_packed_loop needs act (real row count)")

    def call_hit(arrs, fw, vis, lane_mask):
        if gated:
            return hit_of(arrs, fw, vis, lane_mask)
        return hit_of(arrs, fw), jnp.int32(0)

    def _run(arrs, fw, vis, planes, level0, max_levels, lane_mask, gc):
        def cond(carry):
            _, _, _, level, alive, _ = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _, gc = carry
            hit, skipped = call_hit(arrs, fw, vis, lane_mask)
            if gated:
                need = row_unsettled(vis, act, lane_mask)
                nxt, vis2, planes = gated_state_update(hit, vis, planes, need)
                gc = gc.at[jnp.minimum(level, gate_levels - 1)].set(skipped)
            else:
                nxt = hit & ~vis
                vis2 = vis | nxt
                # Pad/sentinel rows count up harmlessly (never visited,
                # sliced off at extraction).
                planes = ripple_increment(planes, ~vis2)
            alive = jnp.any(nxt != 0)
            return nxt, vis2, planes, level + 1, alive, gc

        return jax.lax.while_loop(
            cond, body, (fw, vis, planes, level0, jnp.bool_(True), gc)
        )

    def _truncated(arrs, fw_f, vis_f, levels, alive, max_levels, lane_mask):
        # `alive` only says the last body claimed something. When the loop
        # exits at the cap, distances <= max_levels are all labeled
        # correctly; the traversal is incomplete only if one MORE level
        # would claim vertices. Decide that with a single claim-free
        # expand, so a traversal whose eccentricity lands exactly on the
        # cap does not falsely report truncation.
        def deeper():
            hit = call_hit(arrs, fw_f, vis_f, lane_mask)[0]
            return jnp.any((hit & ~vis_f) != 0)

        return jax.lax.cond(
            alive & (levels >= max_levels), deeper, lambda: jnp.bool_(False)
        )

    def _gc0():
        return jnp.zeros((max(gate_levels, 1),), jnp.int32)

    if gated:

        @jax.jit  # no-donate: fw0 doubles as the batch's src-bits view (fetch reads it after the loop)
        def core(arrs, fw0, max_levels, lane_mask):
            planes0 = tuple(jnp.zeros_like(fw0) for _ in range(num_planes))
            fw_f, vis_f, planes_f, levels, alive, gc = _run(
                arrs, fw0, fw0, planes0, jnp.int32(0), max_levels,
                lane_mask, _gc0(),
            )
            truncated = _truncated(
                arrs, fw_f, vis_f, levels, alive, max_levels, lane_mask
            )
            return planes_f, vis_f, levels, alive, truncated, gc

        @jax.jit  # no-donate: the cap-boundary probe and roofline re-read their carries; advance rides core_from_donate
        def core_from(arrs, fw, vis, planes, level0, max_levels, lane_mask):
            return _run(
                arrs, fw, vis, planes, level0, max_levels, lane_mask, _gc0()
            )

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def core_from_donate(arrs, fw, vis, planes, level0, max_levels,
                             lane_mask):
            return _run(
                arrs, fw, vis, planes, level0, max_levels, lane_mask, _gc0()
            )

        core_from_donate._donate_argnums = (1, 2, 3)
        return core, core_from, core_from_donate

    @jax.jit  # no-donate: fw0 doubles as the batch's src-bits view (fetch reads it after the loop)
    def core(arrs, fw0, max_levels):
        planes0 = tuple(jnp.zeros_like(fw0) for _ in range(num_planes))
        fw_f, vis_f, planes_f, levels, alive, _ = _run(
            arrs, fw0, fw0, planes0, jnp.int32(0), max_levels, None, _gc0()
        )
        truncated = _truncated(
            arrs, fw_f, vis_f, levels, alive, max_levels, None
        )
        return planes_f, vis_f, levels, alive, truncated

    @jax.jit  # no-donate: the cap-boundary probe and roofline re-read their carries; advance rides core_from_donate
    def core_from(arrs, fw, vis, planes, level0, max_levels):
        out = _run(arrs, fw, vis, planes, level0, max_levels, None, _gc0())
        return out[:5]

    @partial(jax.jit, donate_argnums=(1, 2, 3))
    def core_from_donate(arrs, fw, vis, planes, level0, max_levels):
        out = _run(arrs, fw, vis, planes, level0, max_levels, None, _gc0())
        return out[:5]

    core_from_donate._donate_argnums = (1, 2, 3)
    return core, core_from, core_from_donate


class ExpandSpec(NamedTuple):
    """Shape metadata of a bucketed-ELL expansion (see graph/ell.py)."""

    kcap: int
    heavy: bool
    num_virtual: int
    fold_steps: int
    light_meta: tuple  # ((k, n), ...)
    tail_rows: int  # all-zero rows appended after the buckets


def make_fori_expand(spec: ExpandSpec, w: int, *, combine=None,
                     identity: int = 0):
    """Bucketed-ELL expansion with fori-loop accumulation.

    ``fw`` is the packed frontier table; returns the concatenated bucket
    outputs (heavy rows, then light buckets, then ``tail_rows`` identity
    rows). Only one gather result is live at a time — the unrolled form kept
    ~20 padded [n, w] intermediates alive and OOM'd at w >= 64.

    ``combine``/``identity`` default to bitwise OR over 0 (the BFS frontier
    expansion). Any associative-commutative u32 op with an identity works
    over the same bucket structure — parent_scan.py runs this with
    ``jnp.minimum`` over 0xFFFFFFFF to min-reduce per-lane parent keys,
    because the fold pyramid and pad rows only assume those two algebraic
    properties (pads/sentinels must be absorbed, order must not matter).
    """
    if combine is None:
        combine = jnp.bitwise_or
    ident = jnp.uint32(identity)

    def _full(shape):
        return jnp.full(shape, ident, jnp.uint32)

    def expand(arrs, fw):
        parts = []
        if spec.heavy:
            vr_t = arrs["virtual_t"]  # [kcap, M]

            def vbody(kk, acc):
                return combine(acc, fw[vr_t[kk]])

            acc = jax.lax.fori_loop(
                0, spec.kcap, vbody, _full((spec.num_virtual, w))
            )
            vr_ext = jnp.concatenate([acc, _full((1, w))])
            cur = vr_ext[arrs["fold_pad_map"]]
            pyramid = [cur]
            for _ in range(spec.fold_steps):
                pairs = cur.reshape(-1, 2, w)
                cur = combine(pairs[:, 0], pairs[:, 1])
                pyramid.append(cur)
            pyr = jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
            parts.append(pyr[arrs["heavy_pick"]])
        for i, (k, n) in enumerate(spec.light_meta):
            bt = arrs[f"light{i}_t"]  # [k, n]

            def lbody(kk, acc, bt=bt):
                return combine(acc, fw[bt[kk]])

            acc = jax.lax.fori_loop(0, k, lbody, _full((n, w)))
            parts.append(acc)
        if spec.tail_rows:
            parts.append(_full((spec.tail_rows, w)))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return expand


def expand_arrays(ell_like) -> dict:
    """Device-ready (transposed) bucket index arrays for make_fori_expand.

    ``ell_like`` needs attributes ``virtual`` (EllBucket or None),
    ``fold_pad_map``, ``heavy_pick``, ``light`` (list of EllBucket)."""
    arrs = {}
    if ell_like.virtual is not None:
        arrs["virtual_t"] = jnp.asarray(
            np.ascontiguousarray(ell_like.virtual.idx.T)
        )
        arrs["fold_pad_map"] = jnp.asarray(ell_like.fold_pad_map)
        arrs["heavy_pick"] = jnp.asarray(ell_like.heavy_pick)
    for i, b in enumerate(ell_like.light):
        arrs[f"light{i}_t"] = jnp.asarray(np.ascontiguousarray(b.idx.T))
    return arrs


#: Legal ``expand_impl`` values (ISSUE 16). ``xla`` is the fori-loop jnp
#: form XLA fuses; ``pallas`` is the hand-written gather-combine kernel
#: (ops/ell_expand.py) — bit-identical by construction, selected per
#: engine and A/B-priced by the roofline before any default flips.
EXPAND_IMPLS = ("xla", "pallas")

#: jnp combine per symbolic kernel op (the fold pyramid runs outside the
#: kernel and needs the callable form back).
_OP_COMBINE = {"or": jnp.bitwise_or, "min": jnp.minimum,
               "minplus": jnp.minimum}


def validate_expand_impl(impl: str, *, who: str = "expand_impl") -> str:
    if impl not in EXPAND_IMPLS:
        raise ValueError(
            f"{who} must be one of {EXPAND_IMPLS}, got {impl!r}"
        )
    return impl


def _pallas_op_of(combine, identity: int) -> str:
    """Map make_fori_expand's combine/identity callable contract onto the
    kernel's symbolic op names (a Pallas kernel cannot close over a jnp
    callable, so the contract goes symbolic at this boundary)."""
    if (combine is None or combine is jnp.bitwise_or) and identity == 0:
        return "or"
    if combine is jnp.minimum and identity == 0xFFFFFFFF:
        return "min"
    raise ValueError(
        "expand_impl='pallas' supports combine/identity pairs "
        "(bitwise_or, 0), (minimum, 0xFFFFFFFF) and the SSSP min-plus "
        f"form; got ({combine}, {identity:#x})"
    )


def pallas_expand_arrays(ell_like, sentinel: int) -> dict:
    """Host-side sentinel-padded whole-block index tables for the Pallas
    expansion tier (numpy int32; callers device-put/stack as their layout
    needs). Same pad_gate_blocks layout the pull gate's light tables use
    — when both tiers are on, the ``light{i}_gt`` tables are shared —
    plus ``virtual_gt`` so the heavy section runs through the kernel too.
    ``sentinel`` must gather the engine's identity frontier row."""
    from tpu_bfs.graph.ell import pad_gate_blocks

    arrs = {}
    if ell_like.virtual is not None:
        arrs["virtual_gt"] = pad_gate_blocks(
            np.ascontiguousarray(ell_like.virtual.idx.T), sentinel
        )
    for i, b in enumerate(ell_like.light):
        arrs[f"light{i}_gt"] = pad_gate_blocks(
            np.ascontiguousarray(b.idx.T), sentinel
        )
    return arrs


def make_pallas_expand(spec: "ExpandSpec", w: int, *, op: str = "or",
                       interpret: bool = False, wsuf: str | None = None):
    """make_fori_expand's drop-in built on the fused Pallas kernel
    (ops/ell_expand.py): per bucket, ONE kernel launch whose accumulator
    stays VMEM-resident across all k ELL slots with double-buffered row
    gathers — each output row tile hits HBM once per level. The heavy
    fold pyramid and heavy_pick stay jnp (cheap permutation work over the
    kernel's virtual-row output). Requires the ``virtual_gt``/
    ``light{i}_gt`` tables (pallas_expand_arrays); ``wsuf`` selects the
    SSSP min-plus weight planes (``{name}_{wsuf}_gt``) when op='minplus'.

    Returns ``expand(arrs, fw)`` — same signature, bit-identical output.
    """
    from tpu_bfs.ops.ell_expand import KERNEL_OPS, TILE, ell_expand

    combine = _OP_COMBINE[op]
    ident_val, dt = KERNEL_OPS[op]
    T = TILE

    def _full(shape):
        return jnp.full(shape, ident_val, dt)

    def _bucket(arrs, fw, name, k, n, need_blk=None):
        gt = arrs[f"{name}_gt"]  # [k, nb*T]
        nb = gt.shape[1] // T
        if need_blk is None:
            need_blk = jnp.ones((nb,), jnp.int32)
        wt = arrs[f"{name}_{wsuf}_gt"] if op == "minplus" else None
        out = ell_expand(
            need_blk, gt, fw, wt, w=w, op=op, interpret=interpret
        )
        return out[:n]

    def expand(arrs, fw):
        parts = []
        if spec.heavy:
            acc = _bucket(
                arrs, fw, "virtual", spec.kcap, spec.num_virtual
            )
            vr_ext = jnp.concatenate([acc, _full((1, w))])
            cur = vr_ext[arrs["fold_pad_map"]]
            pyramid = [cur]
            for _ in range(spec.fold_steps):
                pairs = cur.reshape(-1, 2, w)
                cur = combine(pairs[:, 0], pairs[:, 1])
                pyramid.append(cur)
            pyr = jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
            parts.append(pyr[arrs["heavy_pick"]])
        for i, (k, n) in enumerate(spec.light_meta):
            parts.append(_bucket(arrs, fw, f"light{i}", k, n))
        if spec.tail_rows:
            parts.append(_full((spec.tail_rows, w)))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return expand


def make_gated_pallas_expand(spec: "ExpandSpec", w: int, *, op: str = "or",
                             interpret: bool = False):
    """make_gated_fori_expand's drop-in on the Pallas tier: the PR 1
    settled-mask gate moves INSIDE the kernel — the per-GATE_TILE block
    mask rides the scalar-prefetch channel and a gated-out tile skips its
    index DMA and gathers entirely, writing the combine identity. The
    gate POLICY is unchanged and computed outside in jnp (same block
    mask, same GATE_DENSE_DEN dense fallback — expressed as an all-ones
    mask rather than a lax.cond branch — and the same whole-section heavy
    skip), so ``skipped_blocks`` matches the XLA tier count-for-count and
    ``last_gate_level_counts`` stays comparable across impls.

    Returns ``expand(arrs, fw, needed) -> (outputs, skipped_blocks)``.
    """
    from tpu_bfs.ops.ell_expand import KERNEL_OPS, TILE, ell_expand

    combine = _OP_COMBINE[op]
    ident_val, dt = KERNEL_OPS[op]
    T = TILE

    def _full(shape):
        return jnp.full(shape, ident_val, dt)

    heavy_blocks = -(-spec.num_virtual // T) if spec.heavy else 0

    def expand(arrs, fw, needed):
        parts = []
        skipped = jnp.int32(0)
        off = 0
        if spec.heavy:
            nh = arrs["heavy_pick"].shape[0]
            gt = arrs["virtual_gt"]
            nvb = gt.shape[1] // T

            def heavy_section():
                acc = ell_expand(
                    jnp.ones((nvb,), jnp.int32), gt, fw,
                    w=w, op=op, interpret=interpret,
                )[: spec.num_virtual]
                vr_ext = jnp.concatenate([acc, _full((1, w))])
                cur = vr_ext[arrs["fold_pad_map"]]
                pyramid = [cur]
                for _ in range(spec.fold_steps):
                    pairs = cur.reshape(-1, 2, w)
                    cur = combine(pairs[:, 0], pairs[:, 1])
                    pyramid.append(cur)
                pyr = (
                    jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
                )
                return pyr[arrs["heavy_pick"]]

            h_need = jnp.any(needed[:nh])
            parts.append(
                jax.lax.cond(h_need, heavy_section, lambda: _full((nh, w)))
            )
            skipped = skipped + jnp.where(h_need, 0, heavy_blocks)
            off = nh
        for i, (k, n) in enumerate(spec.light_meta):
            gt = arrs[f"light{i}_gt"]  # [k, nb*T] sentinel-padded
            nb = gt.shape[1] // T
            need = needed[off : off + n]
            pad = nb * T - n
            if pad:
                need = jnp.concatenate([need, jnp.zeros((pad,), bool)])
            blk = jnp.any(need.reshape(nb, T), axis=1)
            nzb = jnp.sum(blk.astype(jnp.int32))
            take_gated = nzb * GATE_DENSE_DEN <= nb
            # Dense fallback = an all-ones mask: the kernel computes every
            # tile, which IS the dense pass (identical combines, one
            # output write either way) — no second code path to diverge.
            mask = jnp.where(take_gated, blk, True).astype(jnp.int32)
            out = ell_expand(
                mask, gt, fw, w=w, op=op, interpret=interpret
            )
            parts.append(out[:n])
            skipped = skipped + jnp.where(take_gated, nb - nzb, 0)
            off += n
        if spec.tail_rows:
            parts.append(_full((spec.tail_rows, w)))
        out = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        return out, skipped

    return expand


def make_expand(spec: "ExpandSpec", w: int, *, combine=None,
                identity: int = 0, impl: str = "xla",
                interpret: bool = False):
    """The expand_impl dispatcher every packed engine builds through:
    ``xla`` -> make_fori_expand (ignores ``interpret``), ``pallas`` ->
    make_pallas_expand with the combine contract mapped to a kernel op.
    Same ``expand(arrs, fw)`` either way."""
    validate_expand_impl(impl)
    if impl == "xla":
        return make_fori_expand(spec, w, combine=combine, identity=identity)
    return make_pallas_expand(
        spec, w, op=_pallas_op_of(combine, identity), interpret=interpret
    )


def make_gated_expand(spec: "ExpandSpec", w: int, *, combine=None,
                      identity: int = 0, impl: str = "xla",
                      interpret: bool = False):
    """Gated twin of make_expand: ``expand(arrs, fw, needed) ->
    (outputs, skipped_blocks)`` with identical gate policy across impls."""
    validate_expand_impl(impl)
    if impl == "xla":
        return make_gated_fori_expand(
            spec, w, combine=combine, identity=identity
        )
    return make_gated_pallas_expand(
        spec, w, op=_pallas_op_of(combine, identity), interpret=interpret
    )


def build_push_table(host_graph, rank: np.ndarray, act: int, deg_cap: int):
    """Out-CSR push table in rank space for the level-adaptive expansion:
    ``([act+1, deg_cap] int32 out-neighbor rows (pad/sentinel = act),
    [act] bool ineligibility mask — rows with out-degree > deg_cap)``.
    Rank space must be active-first (every edge endpoint < act)."""
    src, dst = host_graph.coo
    rs = rank[src].astype(np.int64)
    rd = rank[dst].astype(np.int32)
    out_deg = np.bincount(rs, minlength=act)[:act]
    elig = out_deg <= deg_cap
    order = np.argsort(rs, kind="stable")
    rs_s, rd_s = rs[order], rd[order]
    rp = np.zeros(act + 1, np.int64)
    np.cumsum(out_deg, out=rp[1:])
    pos = np.arange(len(rs_s), dtype=np.int64) - rp[rs_s]
    keep = elig[rs_s]
    pt = np.full((act + 1, deg_cap), act, np.int32)
    pt[rs_s[keep], pos[keep]] = rd_s[keep]
    return pt, ~elig


def make_adaptive_hit(hit_of, act: int, w: int, out_rows: int, push_cfg):
    """Wrap a pull expansion with the level-adaptive push gate (VERDICT r3
    #8, experimental): a level whose packed union frontier has <= row_cap
    active rows, all with out-degree <= deg_cap, takes a push-style pass —
    a fori over the compacted active rows (trip count = the actual count,
    lowered to a while loop), each step OR-scattering its frontier words
    into its out-neighbors' hit rows — instead of the full ELL/tile scan.
    Push-over-out-edges equals pull-over-in-edges by construction (the
    push table is edge-exact, directed or not). Every other level rides
    ``hit_of`` unchanged via lax.cond.

    ``out_rows`` is the pull expansion's output height ([act+1] for the
    wide engine, [vt*TILE] for the hybrid); row ``act`` doubles as the
    pad-slot dump row and is re-zeroed after the scatter pass (it is a
    zero sentinel/pad row in every packed engine's table).
    Requires arrs keys ``push_t`` / ``push_inelig`` (build_push_table).
    """
    row_cap, _ = push_cfg

    def adaptive(arrs, fw):
        rows_active = jnp.any(fw[:act] != 0, axis=1)
        nz = jnp.sum(rows_active.astype(jnp.int32))
        bad = jnp.any(rows_active & arrs["push_inelig"])
        light = (nz <= row_cap) & ~bad

        def push_fn():
            idx = jnp.where(rows_active, size=row_cap, fill_value=act)[0]
            pt = arrs["push_t"]

            def pbody(i, hit):
                r = idx[i]  # act when padding: fw[act] is a zero row
                nb = pt[r]  # [deg_cap], pad slots -> dump row act
                return hit.at[nb].set(hit[nb] | fw[r][None, :])

            hit = jax.lax.fori_loop(
                0, nz, pbody, jnp.zeros((out_rows, w), jnp.uint32)
            )
            # Pad slots OR real frontier words into the dump row; restore
            # its all-zero invariant (later levels gather/claim from it).
            return hit.at[act].set(0)

        return jax.lax.cond(light, push_fn, lambda: hit_of(arrs, fw))

    return adaptive


def seed_scatter_args(rows_of_sources: np.ndarray, act: int):
    """(rows, words, bits) device args for word-major lane seeding.

    ``rows_of_sources`` maps each batch entry to its table row; entries with
    no row (>= ``act`` — isolated sources) get their bit zeroed (a 0-OR is a
    no-op) and the row clamped, and run_packed_batch patches their lane
    results host-side. One copy of the protocol for every packed engine.
    """
    ranks = rows_of_sources.astype(np.int64)
    lanes = np.arange(len(ranks), dtype=np.int32)
    words = (lanes // 32).astype(np.int32)
    bits = np.uint32(1) << (lanes % 32).astype(np.uint32)
    keep = ranks < act
    return (
        jnp.asarray(np.where(keep, ranks, 0).astype(np.int32)),
        jnp.asarray(words),
        jnp.asarray(np.where(keep, bits, np.uint32(0))),
    )


def degree_sum_blocks(
    in_deg_host: np.ndarray, act: int, *, cap: int = 1 << 30
) -> tuple:
    """Static row-block boundaries for exact int32 degree summation.

    Greedy split of rows [0, act) so each block's total degree stays under
    ``cap`` (< 2**31): a per-block int32 sum of (visited_bit * degree) can
    then never overflow, making the TEPS numerator exact at any scale —
    the block partials are summed in int64 on host. A single vertex's
    degree is < V < 2**31, so a one-row block is always safe."""
    deg = np.asarray(in_deg_host[:act], dtype=np.int64)
    csum = np.cumsum(deg)  # one O(act) pass; blocks then binary-search it
    blocks = []
    s = 0
    while s < act:
        base = csum[s - 1] if s else 0
        e = int(np.searchsorted(csum, base + cap, side="left"))
        e = min(max(e, s + 1), act)  # at least one row per block
        blocks.append((s, e))
        s = e
    return tuple(blocks) if blocks else ((0, 0),)


def make_state_kernels(
    v: int,
    rows: int,
    w: int,
    num_planes: int,
    *,
    active: int | None = None,
    in_deg_host: np.ndarray | None = None,
):
    """Jitted (seed, lane_stats, extract_word) over a [rows, w] packed table
    whose first ``act`` rows are real vertices (in rank order).

    ``active`` (default: v) is the number of real rows when the table is
    trimmed to non-isolated vertices; stats and extraction scan only those.
    ``in_deg_host`` (table row order, length >= act) is captured by
    lane_stats — it both sizes the static degree-sum blocks and provides
    the summed values, so the overflow-safety analysis and the data can
    never diverge. Required for lane_stats; seed/extract_word/lane_ecc
    work without it.

    Returns ``(seed, lane_stats, extract_word, lane_ecc)``; ``lane_ecc``
    is the on-device per-lane eccentricity reduction (ISSUE 3): max
    finite distance per lane as [w, 32] int32, so distance-free serving
    queries read one [w, 32] summary instead of the O(V * lanes)
    distance table.
    """
    act = v if active is None else min(active, v)
    if in_deg_host is not None:
        blocks = degree_sum_blocks(in_deg_host, act)
        in_deg = jnp.asarray(np.asarray(in_deg_host, dtype=np.int32))
    else:
        blocks, in_deg = ((0, act),), None

    @jax.jit
    def seed(rws, words, bits):
        # Distinct lanes own distinct (word, bit) pairs, so scatter-add == OR.
        fw0 = jnp.zeros((rows, w), jnp.uint32)
        return fw0.at[rws, words].add(bits)

    @jax.jit
    def lane_stats(vis):
        """Per-word-column reached count and degree sum, on device.

        Returns (reached [w,32] i32, deg_sum [w, nblocks, 32] i32) — both
        EXACT: TPU has no int64, so the degree sum accumulates per static
        row-block (each bounded under 2**31 by degree_sum_blocks) and the
        caller reduces the block axis in int64 on host. Replaces the old
        f32 pairwise sum whose ~7 digits went inexact past ~10^7 edges
        per lane. The degree array is the captured ``in_deg_host`` — the
        same array the blocks were sized from, by construction."""
        if in_deg is None:
            raise ValueError("make_state_kernels needs in_deg_host for lane_stats")
        shifts = jnp.arange(32, dtype=jnp.uint32)

        def wbody(wi, acc):
            r_acc, d_acc = acc
            col = jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act]  # [act,1]
            bits = (col >> shifts) & 1  # [act, 32] u32
            rr = jnp.sum(bits.astype(jnp.int32), axis=0)
            dd = jnp.stack([
                jnp.sum(
                    bits[s:e].astype(jnp.int32) * in_deg[s:e, None], axis=0
                )
                for s, e in blocks
            ])  # [nblocks, 32] i32, each block exact
            return (
                jax.lax.dynamic_update_slice(r_acc, rr[None], (wi, 0)),
                jax.lax.dynamic_update_slice(d_acc, dd[None], (wi, 0, 0)),
            )

        r0 = jnp.zeros((w, 32), jnp.int32)
        d0 = jnp.zeros((w, len(blocks), 32), jnp.int32)
        return jax.lax.fori_loop(0, w, wbody, (r0, d0))

    @jax.jit
    def extract_word(planes, vis, src_bits, wi):
        """Distances of word-column wi's 32 lanes as [act, 32] uint8."""
        shifts = jnp.arange(32, dtype=jnp.uint32)
        cnt = jnp.zeros((act, 32), jnp.uint8)
        for i, p in enumerate(planes):
            col = jax.lax.dynamic_slice(p, (0, wi), (rows, 1))[:act]
            bit = ((col >> shifts) & 1).astype(jnp.uint8)
            cnt = cnt + (bit << i)
        visw = ((jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act] >> shifts) & 1) != 0
        srcw = (
            (jax.lax.dynamic_slice(src_bits, (0, wi), (rows, 1))[:act] >> shifts) & 1
        ) != 0
        return jnp.where(
            srcw, jnp.uint8(0), jnp.where(visw, cnt + jnp.uint8(1), UNREACHED)
        )

    @jax.jit
    def lane_ecc(planes, vis, src_bits):
        """Per-lane eccentricity (max finite distance) as [w, 32] int32.

        The same bit-sliced decode as extract_word, but reduced over rows
        on device: unvisited rows contribute 0 (a lane whose component is
        only its source has eccentricity 0), sources contribute 0, every
        other visited row its distance cnt + 1."""
        if act == 0:
            # Edgeless tables (every vertex isolated): no row is ever
            # visited, and the row-max below has no identity over zero
            # rows. Every lane's component is at most its source: ecc 0.
            return jnp.zeros((w, 32), jnp.int32)
        shifts = jnp.arange(32, dtype=jnp.uint32)

        def wbody(wi, acc):
            cnt = jnp.zeros((act, 32), jnp.int32)
            for i, p in enumerate(planes):
                col = jax.lax.dynamic_slice(p, (0, wi), (rows, 1))[:act]
                bit = ((col >> shifts) & 1).astype(jnp.int32)
                cnt = cnt + (bit << i)
            visw = (
                (jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act] >> shifts) & 1
            ) != 0
            srcw = (
                (jax.lax.dynamic_slice(src_bits, (0, wi), (rows, 1))[:act] >> shifts)
                & 1
            ) != 0
            dist = jnp.where(
                srcw, 0, jnp.where(visw, cnt + 1, 0)
            )  # [act, 32]
            return jax.lax.dynamic_update_slice(
                acc, jnp.max(dist, axis=0)[None], (wi, 0)
            )

        return jax.lax.fori_loop(0, w, wbody, jnp.zeros((w, 32), jnp.int32))

    return seed, lane_stats, extract_word, lane_ecc


@dataclasses.dataclass
class PackedBatchResult:
    """Batch result with lazy per-word distance extraction.

    Distances stay bit-sliced on device; ``distances_int32(i)`` unpacks the
    one 32-lane word-column containing lane i (then caches it), so querying a
    few lanes never materializes the full [S, V] array.
    """

    sources: np.ndarray  # [S] int32
    num_levels: int  # max distance over all lanes
    reached: np.ndarray  # [S] int64
    edges_traversed: np.ndarray  # [S] int64, exact (block-summed on device)
    elapsed_s: float | None
    _engine: object
    _planes: tuple
    _vis: jax.Array
    _src_bits: jax.Array
    # Lanes whose source is an isolated vertex (no table row; traversal is
    # trivially {source}); None when the engine's tables cover all vertices.
    _iso: np.ndarray | None = None
    _ecc_cache: np.ndarray | None = None
    _word_cache: dict = dataclasses.field(default_factory=dict)
    _parent_cache: dict = dataclasses.field(default_factory=dict)
    # Decoded parent columns of ONE word (32 lanes) from the cached-scanner
    # single-lane path; see _parent_lane_scan.
    _pword_cache: dict = dataclasses.field(default_factory=dict)

    @property
    def teps(self) -> float | None:
        """Harmonic-mean per-source TEPS under the batch time share."""
        if not self.elapsed_s:
            return None
        per_source_time = self.elapsed_s / len(self.sources)
        t = self.edges_traversed / per_source_time
        return float(len(t) / np.sum(1.0 / np.maximum(t, 1e-9)))

    @property
    def ecc(self) -> np.ndarray | None:
        """[S] int32 per-lane eccentricity (max finite distance), reduced
        ON DEVICE (make_state_kernels lane_ecc) and cached on first
        access — one [w, 32] summary transfer instead of decoding
        distance words host-side, so distance-free consumers (the serve
        path's want_distances=false) answer ``levels`` without ever
        pulling a distance row. Lazy: one-shot callers that never read it
        never pay the kernel. None when the engine predates the kernel."""
        if self._ecc_cache is None:
            lane_ecc = getattr(self._engine, "_lane_ecc", None)
            if lane_ecc is None:
                return None
            eng = self._engine
            e = eng._lane_order(
                np.asarray(lane_ecc(self._planes, self._vis, self._src_bits))
            )[: len(self.sources)].astype(np.int32)
            if self._iso is not None:
                # Isolated sources never touch the device; their component
                # is {source} — eccentricity 0.
                e[self._iso] = 0
            self._ecc_cache = e
        return self._ecc_cache

    def distance_u8_lane(self, i: int) -> np.ndarray:
        """[V] uint8 distances of batch entry i (UNREACHED where unreached)."""
        if not (0 <= i < len(self.sources)):
            raise IndexError(i)
        eng = self._engine
        if self._iso is not None and self._iso[i]:
            # Isolated source: never seeded on device; its component is {src}.
            d = np.full(eng.num_vertices, UNREACHED, np.uint8)
            d[self.sources[i]] = 0
            return d
        wi, col = eng._word_col(i)
        if wi not in self._word_cache:
            dr = np.asarray(
                eng._extract_word(self._planes, self._vis, self._src_bits, wi)
            )
            act = getattr(eng, "_act", None)
            if act is not None:
                # Trimmed tables: a vertex has a row iff _rank[v] < _act;
                # isolated vertices map past the end and stay UNREACHED.
                full = np.full((eng.num_vertices, 32), UNREACHED, np.uint8)
                m = eng._rank < act
                full[m] = dr[eng._rank[m]]
            else:
                full = dr[eng._rank]  # old-id order
            self._word_cache[wi] = full
        return self._word_cache[wi][:, col]

    def distances_int32(self, i: int) -> np.ndarray:
        d8 = self.distance_u8_lane(i)
        return np.where(d8 == UNREACHED, INF_DIST, d8.astype(np.int32))

    def parents_int32(self, i: int) -> np.ndarray:
        """BFS tree of batch entry i: [V] int32 parents (source maps to
        itself, unreached to NO_PARENT).

        The packed level loop labels distances only (bit-sliced planes);
        the tree is extracted post-loop as one O(E) scatter-min per
        REQUESTED lane — lazy and cached like distance_u8_lane, so
        querying a few lanes never pays for the whole batch. The result
        is the deterministic min-parent tree (the same definition every
        single-source engine emits, validate.min_parent_from_dist),
        replacing the reference's nondeterministic atomic-race parent
        which it could never validate (bfs.cu:146-147, 940)."""
        if not (0 <= i < len(self.sources)):
            raise IndexError(i)
        if i not in self._parent_cache:
            self._parent_cache[i] = self._parent_lane(i)
        return self._parent_cache[i]

    def _parent_lane(self, i: int) -> np.ndarray:
        """One lane's tree: the cached-scanner fast path when available,
        with the guaranteed host scatter-min fallback — a device OOM here
        must degrade to the pre-scanner behavior, never propagate, as long
        as the host path can serve this result."""
        scanner = self._cached_scanner()
        if scanner is not None:
            try:
                return self._parent_lane_scan(i, scanner)
            except Exception as exc:  # noqa: BLE001 — OOM-only fallback
                if "RESOURCE_EXHAUSTED" not in str(exc) or (
                    getattr(self._engine, "host_graph", None) is None
                ):
                    raise
        return self._parent_lane_host(i)

    def _parent_lane_host(self, i: int) -> np.ndarray:
        """The device-free O(E) host scatter-min — the path every OOM
        fallback must bottom out in."""
        return min_parents_lane(
            getattr(self._engine, "host_graph", None),
            int(self.sources[i]),
            self.distances_int32(i),
        )

    def _cached_scanner(self):
        """An ALREADY-CACHED borrowed scanner, or None. Single-lane queries
        never trigger a scanner build (that can allocate a full ELL on
        device); they just reuse one a bulk export or an earlier query on
        a borrowing engine left behind. Guarded to scanners built from the
        engine's OWN ell (identity row space — true for every borrowing
        engine today); anything else takes the general host path."""
        scanner = getattr(self._engine, "_parent_scanner_cache", None) or None
        if scanner is not None and scanner.ell is not getattr(
            self._engine, "ell", None
        ):
            return None
        return scanner

    def _parent_lane_scan(self, i: int, scanner) -> np.ndarray:
        """One lane's tree via the cached scanner: scan the lane's 32-lane
        word column (UNREACHED-padded to a full pass) instead of an O(E)
        host scatter-min — the same deterministic tree, bit-equal. The
        word's decoded [act, 32] columns are cached (one word at a time,
        like distance_u8_lane's word cache), so querying 32 lanes of one
        word runs one scan, not 32."""
        eng = self._engine
        ell = scanner.ell
        act = ell.num_active
        src = int(self.sources[i])
        out = np.full(eng.num_vertices, -1, np.int32)
        if self._iso is not None and self._iso[i]:
            out[src] = src
            return out
        wi, col = eng._word_col(i)
        pc = self._pword_cache.get(wi)
        if pc is None:
            dist_cols = eng._extract_word(
                self._planes, self._vis, self._src_bits, wi
            )
            L = scanner.lanes_per_pass
            if L > 32:
                dist_cols = jnp.concatenate(
                    [dist_cols, jnp.full((act, L - 32), UNREACHED, jnp.uint8)],
                    axis=1,
                )
            pc = np.asarray(scanner.scan(dist_cols))[:, :32]
            self._pword_cache.clear()  # one word resident at a time
            self._pword_cache[wi] = pc
        out[ell.old_of_new[:act]] = pc[:, col]
        return out

    def parents_into(self, out: np.ndarray, *, device: str = "auto") -> np.ndarray:
        """Fill ``out[i]`` with every lane's parent tree.

        ``device='auto'`` (default) runs the batched min-key scan on device
        when the engine can supply a full-coverage ELL (parent_scan.py —
        one bucketed min-expansion per 128 lanes, replacing an O(S*E) host
        pass that cost ~an hour for the 4096-lane flagship batch), falling
        back to the per-lane host path otherwise or on device OOM.
        ``'host'`` forces the host path; ``'device'`` raises when the scan
        is unavailable instead of falling back (tests pin each path)."""
        n = len(self.sources)
        if out.shape != (n, self._engine.num_vertices):
            raise ValueError(
                f"out is {out.shape}, need ({n}, {self._engine.num_vertices})"
            )
        host_serves = getattr(self._engine, "host_graph", None) is not None
        # Above ~1e5 lanes x vertices the host path stops being interactive
        # (the flagship 8192-lane scale-21 batch prices at ~an hour); an
        # OOM fallback there must be loud (VERDICT r4 weak #4).
        work_desc = (
            f"{n} lanes x {self._engine.num_vertices} vertices"
            if n * self._engine.num_vertices > 100_000 else None
        )
        scanner = acquire_parent_scanner(
            self._engine, device, host_serves=host_serves,
            work_desc=work_desc,
        )
        if scanner is not None:
            return parents_scan_with_fallback(
                lambda: self._parents_into_scan(out, scanner),
                lambda: self._parents_into_host(out),
                device,
                host_serves=host_serves,
                work_desc=work_desc,
            )
        return self._parents_into_host(out)

    def _parents_into_host(self, out: np.ndarray) -> np.ndarray:
        """Per-lane host extraction (the guaranteed device-free path — the
        scan's OOM fallback lands here, so it must not re-enter the
        cached-scanner fast path), evicting each 32-lane distance word
        column once its lanes are done — peak host memory is ``out`` plus
        one word column, not a second cached [S, V] copy."""
        n = len(self.sources)
        prev_word = None
        for i in range(n):
            # Reuse (then evict) an already-cached tree; compute misses via
            # the device-free host scatter-min — NOT parents_int32, whose
            # fast path would re-enter the possibly-failing scan.
            cached = self._parent_cache.pop(i, None)
            out[i] = cached if cached is not None else self._parent_lane_host(i)
            wi = self._engine._word_col(i)[0]
            if prev_word is not None and wi != prev_word:
                self._word_cache.pop(prev_word, None)
            prev_word = wi
        if prev_word is not None:
            self._word_cache.pop(prev_word, None)
        return out

    def _parents_into_scan(self, out: np.ndarray, scanner) -> np.ndarray:
        """Device min-key scan over 128-lane column groups (parent_scan.py)."""
        eng = self._engine
        n = len(self.sources)
        ell = scanner.ell
        act = ell.num_active
        # Map engine extraction rows -> scanner rows through ORIGINAL ids,
        # so any engine row space works: the single-chip engines share the
        # scanner's active-first rank (identity, no gather), while the
        # distributed engines extract over chip-major padded tables of a
        # different height and order (dist_msbfs_wide.py: every vertex has
        # a row; tau order in the hybrid) — the perm pulls exactly the
        # scanner's active vertices out of whatever table the engine has.
        perm = None
        if eng._act != act or not np.array_equal(
            np.asarray(eng._rank), np.asarray(ell.rank)
        ):
            perm_np = np.asarray(eng._rank)[ell.old_of_new[:act]]
            if perm_np.min() < 0 or perm_np.max() >= eng._act:
                raise RuntimeError(
                    "engine row map does not cover the scanner's active "
                    f"vertices (rows [{perm_np.min()}, {perm_np.max()}] vs "
                    f"{eng._act} extraction rows)"
                )
            perm = jnp.asarray(perm_np)
        id_of_row = ell.old_of_new[:act]
        w = eng.w
        # lane_ids[l] = flat (word, bit) slot of batch entry l; inv is the
        # inverse map. Word-major engines make both the identity, but the
        # scan is lane-map-generic (the hybrid was bit-major until round 2).
        lane_ids = eng._lane_order(np.arange(w * 32).reshape(w, 32))
        inv = np.argsort(lane_ids)
        iso = self._iso
        L = scanner.lanes_per_pass
        nw = L // 32
        words = np.unique(lane_ids[:n] // 32)
        for c0 in range(0, len(words), nw):
            chunk = words[c0 : c0 + nw]
            cols = [
                eng._extract_word(self._planes, self._vis, self._src_bits, wi)
                for wi in chunk
            ]
            dist_cols = (
                jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
            )
            if perm is not None:
                dist_cols = dist_cols[perm]
            if len(chunk) * 32 < L:
                dist_cols = jnp.concatenate(
                    [
                        dist_cols,
                        jnp.full(
                            (act, L - len(chunk) * 32), UNREACHED, jnp.uint8
                        ),
                    ],
                    axis=1,
                )
            pc = np.asarray(scanner.scan(dist_cols))  # [act, L] int32
            for j, wi in enumerate(chunk):
                for b in range(32):
                    lane = int(inv[32 * wi + b])
                    if lane >= n or (iso is not None and iso[lane]):
                        continue
                    row = out[lane]
                    row.fill(-1)
                    row[id_of_row] = pc[:, 32 * j + b]
        if iso is not None:
            # Isolated sources never reach the device; their component is
            # {source} (same convention as distance_u8_lane).
            for lane in np.flatnonzero(iso[:n]):
                out[lane].fill(-1)
                out[lane][self.sources[lane]] = self.sources[lane]
        return out


def parent_scanner_of(engine):
    """Lazy per-engine ParentScanner; None when unavailable (no
    full-coverage ELL source, or V too large for the 32-bit key encoding
    at the engine's level cap).

    Caching policy follows who owns the device tables: a scanner that
    BORROWS the engine's existing ELL arrays (the wide engines — zero
    extra HBM) is cached on the engine; a scanner that had to build and
    transfer its OWN full-ELL tables (the hybrid, whose dense-tile design
    exists to avoid holding a full ELL) is returned uncached, so its
    device memory is released with the scanner after the bulk export
    instead of growing the engine's footprint for its whole lifetime.
    Unavailability is cached either way."""
    cached = getattr(engine, "_parent_scanner_cache", None)
    if cached is not None:
        return cached or None  # False marks a probed-and-unavailable engine
    from tpu_bfs.algorithms.parent_scan import (
        ParentScanner,
        ParentScanUnavailable,
    )

    scanner = None
    borrowed = False
    get = getattr(engine, "_full_parent_ell", None)
    if get is not None:
        ell, arrs = get()
        borrowed = arrs is not None
        if ell is not None:
            try:
                scanner = ParentScanner(
                    ell, arrs=arrs, max_dist=engine.max_levels_cap
                )
            except ParentScanUnavailable:
                scanner = None
    if scanner is None:
        engine._parent_scanner_cache = False
    elif borrowed:
        engine._parent_scanner_cache = scanner
    return scanner


def _warn_host_fallback(stage: str, work_desc: str | None) -> None:
    """Loud OOM-fallback notice (VERDICT r4 weak #4: at flagship scale the
    silent fallback is an ~hour/batch host scatter-min a user triggers
    with one flag). Emitted only when the caller judged the work big
    enough to matter (work_desc set); tiny exports stay quiet."""
    if work_desc:
        import sys

        print(
            f"WARNING: device parent scan unavailable ({stage}: "
            f"RESOURCE_EXHAUSTED); falling back to the per-lane host "
            f"scatter-min for {work_desc} — potentially hours at flagship "
            f"scale. Pass device='host' to choose the host path "
            f"explicitly, or device='device' to fail fast.",
            file=sys.stderr,
            flush=True,
        )


def acquire_parent_scanner(engine, device: str, *, host_serves: bool = True,
                           work_desc: str | None = None):
    """Shared scanner-acquisition policy of the packed result classes
    (PackedBatchResult here, PackedBfsResult in msbfs_packed.py): validate
    the ``device`` argument, return the engine's scanner or None for the
    host path, raise when ``'device'`` is forced but unavailable, and
    swallow a RESOURCE_EXHAUSTED during the scanner build in auto mode
    (the build itself may transfer full-ELL tables) — but ONLY when the
    host path can actually serve the result (``host_serves``; masking a
    build-time OOM behind the host path's 'needs the edge list' error
    would discard the real cause, the same rule
    parents_scan_with_fallback applies at scan time). One copy of the OOM
    policy, so the contracts cannot drift."""
    if device not in ("auto", "host", "device"):
        raise ValueError(f"device must be auto|host|device, got {device!r}")
    scanner = None
    if device != "host" and engine is not None:
        try:
            scanner = parent_scanner_of(engine)
        except Exception as exc:  # noqa: BLE001 — OOM-only fallback
            if (
                device == "device"
                or "RESOURCE_EXHAUSTED" not in str(exc)
                or not host_serves
            ):
                raise
            _warn_host_fallback("scanner build", work_desc)
    if scanner is None and device == "device":
        raise ValueError(
            "device parent scan unavailable for this engine (needs a "
            "full-coverage ELL or a retained host graph, and V small "
            "enough for the 32-bit key encoding)"
        )
    return scanner


def parents_scan_with_fallback(scan_fn, host_fn, device: str, *,
                               host_serves: bool = True,
                               work_desc: str | None = None):
    """Shared scan-time OOM policy of the packed result classes: run the
    device scan; in auto mode a RESOURCE_EXHAUSTED falls back to the host
    path — but ONLY when the host path can actually serve this result
    (``host_serves``; a prebuilt-ELL result has no edge list, and masking
    the OOM behind the host path's 'needs the edge list' error would
    discard the real cause). Forced-device mode and non-OOM errors always
    propagate. ``work_desc`` (set by callers for big exports) makes the
    fallback LOUD — the host path can be hours at flagship scale."""
    try:
        return scan_fn()
    except Exception as exc:  # noqa: BLE001 — OOM-only fallback
        if (
            device == "device"
            or "RESOURCE_EXHAUSTED" not in str(exc)
            or not host_serves
        ):
            raise
        _warn_host_fallback("scan", work_desc)
    # Partial scan output is harmless: the host path overwrites every row.
    return host_fn()


def lazy_full_parent_ell(host_graph, kcap: int = 64):
    """Shared `_full_parent_ell` body for engines whose own structures
    cannot serve the parent scan (dense tiles, per-chip residual shards):
    a fresh single-device full in-neighbor ELL from the retained host
    graph, with owned (engine-uncached) device tables."""
    if host_graph is None:
        return None, None
    from tpu_bfs.graph.ell import build_ell

    return build_ell(host_graph, kcap=kcap), None


def min_parents_lane(graph, source: int, dist: np.ndarray) -> np.ndarray:
    """One lane's deterministic min-parent tree from its distances — the
    shared core of PackedBatchResult.parents_int32 and
    PackedBfsResult.parents_int32 (msbfs_packed.py). ``graph`` is the
    engine's ``host_graph``; None means the engine was built from a
    prebuilt ELL/sharded graph that no longer has the edge list."""
    if graph is None:
        raise ValueError(
            "parent extraction needs the edge list: construct the engine "
            "from a Graph (a prebuilt ELL/sharded graph does not retain it)"
        )
    from tpu_bfs import validate

    return validate.min_parent_from_dist(graph, source, dist)


def _check_batch_sources(engine, sources) -> np.ndarray:
    sources = np.asarray(sources, dtype=np.int64)
    if sources.ndim != 1 or len(sources) == 0 or len(sources) > engine.lanes:
        raise ValueError(f"need 1..{engine.lanes} sources, got {sources.shape}")
    if sources.min() < 0 or sources.max() >= engine.num_vertices:
        raise ValueError("source out of range")
    return sources


def packed_table_to_real(engine, table) -> np.ndarray:
    """Engine-layout [rows, w] packed table -> real-vertex-id [V, w] host
    array. Rows of isolated vertices (no table row) and the engine's
    pad/sentinel rows come out all-zero — exactly their live information
    content. The real-id layout is what checkpoints store, so a checkpoint
    taken on one packed engine resumes on any other over the same graph."""
    t = np.asarray(table)
    real = np.zeros((engine.num_vertices, engine.w), np.uint32)
    m = engine._rank < engine._act
    real[m] = t[engine._rank[m]]
    return real


def packed_real_to_table(engine, real):
    """Real-vertex-id [V, w] checkpoint array -> engine-layout [rows, w]."""
    if real.shape != (engine.num_vertices, engine.w):
        raise ValueError(
            f"checkpoint table is {real.shape}, engine expects "
            f"({engine.num_vertices}, {engine.w}) — lane count and graph "
            "must match the engine the checkpoint resumes on"
        )
    t = np.zeros((engine._table_rows, engine.w), np.uint32)
    m = engine._rank < engine._act
    t[engine._rank[m]] = real[m]
    return jnp.asarray(t)


def _fw_hooks(engine):
    """Frontier layout-conversion hooks: engines whose loop carries the
    frontier in a different layout than their visited/plane tables (the
    distributed wide engine: replicated rank-order + sentinel row vs
    chip-major shards) provide ``_fw_table_from_real``/``_fw_real_from_table``;
    everyone else uses the shared real<->table conversion."""
    to_fw = getattr(
        engine, "_fw_table_from_real", None
    ) or (lambda real: packed_real_to_table(engine, real))
    from_fw = getattr(
        engine, "_fw_real_from_table", None
    ) or (lambda table: packed_table_to_real(engine, table))
    return to_fw, from_fw


def start_packed_batch(engine, sources):
    """Level-0 packed traversal state as a host checkpoint.

    The packed analog of the single-source engines' ``start`` (SURVEY.md §5:
    the reference has no checkpointing; a failed rank loses the whole
    traversal). State = frontier/visited tables + ``num_planes`` bit-sliced
    distance planes, all in real-vertex-id row order."""
    from tpu_bfs.utils.checkpoint import PackedCheckpoint, _new_nonce

    sources = _check_batch_sources(engine, sources)
    # The seed table may use a different row order than the result tables
    # (the distributed wide engine); the _src_bits_view hook converts.
    seed_view = getattr(engine, "_src_bits_view", lambda x: x)
    seed_real = packed_table_to_real(engine, seed_view(engine._seed_dev(sources)))
    planes = np.zeros(
        (engine.num_planes, engine.num_vertices, engine.w), np.uint32
    )
    # The starting engine knows its isolated lanes exactly; persist the
    # mask so ANY finishing engine applies the patch — including one whose
    # own _iso_mask is unknowable (prebuilt directed shard sets).
    iso = getattr(engine, "_iso_of", lambda s: None)(sources)
    return PackedCheckpoint(
        sources=sources,
        level=0,
        alive=True,
        frontier=seed_real,
        visited=seed_real.copy(),
        planes=planes,
        iso=None if iso is None else np.asarray(iso, dtype=bool),
        nonce=_new_nonce(),
    )


def advance_packed_batch(engine, ckpt, levels: int | None = None):
    """Run at most ``levels`` more level-steps from a packed checkpoint.

    The while-loop carry is restored exactly, so chunked advancing labels
    the same distances bit-for-bit as one uninterrupted run."""
    from tpu_bfs.utils.checkpoint import PackedCheckpoint

    if ckpt.planes.shape[0] != engine.num_planes:
        raise ValueError(
            f"checkpoint has {ckpt.planes.shape[0]} planes, engine has "
            f"{engine.num_planes}"
        )
    if not ckpt.alive:
        return ckpt
    # Pull-gated engines derive their active-lane mask from the batch's
    # sources (host_lane_mask) before any core dispatch; other engines
    # have no hook and skip this.
    note = getattr(engine, "_note_batch_sources", None)
    if note is not None:
        note(ckpt.sources)
    cap = engine.max_levels_cap
    ml = min(ckpt.level + levels, cap) if levels is not None else cap
    to_fw, from_fw = _fw_hooks(engine)
    # Chain identity for the distributed engines' exchange accounting
    # (read by RowGatherExchangeAccounting._core_from; a plain attribute
    # because the single-chip engines' _core_from is the raw jitted loop).
    engine._pending_chain_nonce = getattr(ckpt, "nonce", None)
    # visited converts first: packed_real_to_table raises the descriptive
    # lane-count/graph mismatch error before any custom frontier hook can
    # hit a raw broadcast failure.
    vis = packed_real_to_table(engine, ckpt.visited)
    planes = tuple(packed_real_to_table(engine, p) for p in ckpt.planes)
    fw = to_fw(ckpt.frontier)
    # The donating resume entry (ISSUE 13) where the engine provides one:
    # fw/vis/planes are fresh conversions of the host checkpoint, dead
    # after this call — donating them lets the loop's outputs alias their
    # buffers instead of doubling the table residency per chunk. Engines
    # without a donating twin (the 512-lane packed engine) keep copying.
    core_from = getattr(engine, "_core_from_donate", None) or engine._core_from
    fw_f, vis_f, planes_f, level, alive = core_from(
        engine.arrs, fw, vis, planes, jnp.int32(ckpt.level), jnp.int32(ml)
    )
    if bool(alive) and int(level) >= cap:
        # At the plane cap with the last body still claiming: run ONE
        # boundary body purely as a probe. An eccentricity that lands
        # exactly on the cap claims nothing more and terminates cleanly;
        # anything else is a genuine truncation and must raise rather than
        # let callers' advance loops spin forever on a level counter that
        # can no longer move. The probe's table mutations are DISCARDED:
        # its ripple_increment would bump still-unvisited rows' planes
        # past what an uninterrupted run (which stops at the cap) holds,
        # so keeping the pre-probe tables preserves bit-identical
        # checkpoints; only the probe's level/alive bookkeeping is kept
        # (level cap+1, alive False — matching the uninterrupted
        # num_levels accounting in _assemble_packed_result). The raw
        # jitted loop is used where the engine wraps it with exchange
        # accounting (_core_from_jit): re-recording at the probe's level
        # would collapse a restarted chain's counters, and the probe's one
        # extra gather is the same documented modeling gap as the
        # distributed hybrid's claim-free check
        # (collectives.record_row_gather_exchange).
        # Gated engines expose _core_from_probe for the same reason (their
        # raw jitted loop takes the extra lane-mask argument, and the
        # probe must not clobber the run's gate counters).
        probe_fn = (
            getattr(engine, "_core_from_probe", None)
            or getattr(engine, "_core_from_jit", None)
            or engine._core_from
        )
        out = probe_fn(
            engine.arrs, fw_f, vis_f, planes_f,
            jnp.int32(int(level)), jnp.int32(int(level) + 1),
        )
        p_level, p_alive = out[3], out[4]
        if bool(p_alive):
            raise RuntimeError(
                f"traversal truncated at {cap} levels; "
                f"num_planes={engine.num_planes} caps at {cap} — construct "
                "the engine with more planes for this graph"
            )
        level, alive = p_level, p_alive
    return PackedCheckpoint(
        sources=ckpt.sources,
        level=int(level),
        alive=bool(alive),
        frontier=from_fw(fw_f),
        visited=packed_table_to_real(engine, vis_f),
        planes=np.stack(
            [packed_table_to_real(engine, p) for p in planes_f]
        ),
        iso=ckpt.iso,
        nonce=getattr(ckpt, "nonce", None),
    )


def _assemble_packed_result(
    engine, sources, planes, vis, src_bits_raw, levels, alive, elapsed,
    iso_override=None,
) -> PackedBatchResult:
    """Result assembly shared by run_packed_batch and finish_packed_batch:
    device-side lane stats, isolated-lane patching, sentinel-row src-bits
    view, and the final-empty-frontier level adjustment. ``iso_override``
    (from a checkpoint's persisted mask) wins over the engine's own
    isolated-lane reckoning — the finishing engine may not be able to
    reconstruct it (prebuilt directed shard sets)."""
    s = len(sources)
    r, d = engine._lane_stats(vis)
    reached = engine._lane_order(np.asarray(r))[:s].astype(np.int64)
    # d is [w, nblocks, 32] int32 block partials; the int64 block reduction
    # happens here on host, so edges_traversed is exact at any scale.
    slot_sum = engine._lane_order(np.asarray(d).astype(np.int64).sum(axis=1))[:s]
    edges = slot_sum // 2 if engine.undirected else slot_sum

    # Engines whose result tables use a different row order than their seed
    # table (the distributed wide engine) provide a converting view.
    src_bits = getattr(engine, "_src_bits_view", lambda x: x)(src_bits_raw)

    # Lanes seeded at isolated sources have no device row: the table scan
    # sees nothing, but the source itself is trivially reached.
    iso = (
        iso_override
        if iso_override is not None
        else getattr(engine, "_iso_of", lambda s: None)(sources)
    )
    if iso is not None and iso.any():
        reached[iso] = 1
        edges[iso] = 0
    else:
        iso = None

    res = PackedBatchResult(
        sources=sources.astype(np.int32),
        num_levels=levels,
        reached=reached,
        edges_traversed=edges,
        elapsed_s=elapsed,
        _engine=engine,
        _planes=planes,
        _vis=vis,
        _src_bits=src_bits,
        _iso=iso,
    )
    # The loop's last body found an empty frontier iff not alive; then the
    # max eccentricity is one less than the body count.
    if levels > 0 and not alive:
        res.num_levels = levels - 1
    return res


def finish_packed_batch(engine, ckpt) -> PackedBatchResult:
    """Package a (finished or partial) packed checkpoint as a batch result,
    with the same lazy per-word distance extraction as a direct run. The
    checkpoint's persisted isolated-lane mask (stamped at start) is used
    when present, so lanes at isolated sources report reached=1 even on a
    finishing engine that cannot reconstruct the mask itself."""
    sources = _check_batch_sources(engine, ckpt.sources)
    vis = packed_real_to_table(engine, ckpt.visited)
    planes = tuple(packed_real_to_table(engine, p) for p in ckpt.planes)
    return _assemble_packed_result(
        engine, sources, planes, vis, engine._seed_dev(sources),
        ckpt.level, ckpt.alive, None,
        iso_override=getattr(ckpt, "iso", None),
    )


@dataclasses.dataclass
class PackedDispatch:
    """An in-flight packed batch: the level loop is LAUNCHED (JAX dispatch
    is async) but nothing host-side has blocked on it yet.

    The dispatch/fetch split exists for the serving pipeline (ISSUE 3):
    ``dispatch_packed_batch`` returns immediately with the device output
    references, so the serve executor can hand a completed batch to an
    extraction worker and form/dispatch the next batch while this one's
    results are still being pulled. ``fetch_packed_batch`` is the blocking
    half — level-count readback, plane-cap check, result assembly.
    Device-side failures of an async dispatch (OOM included) surface at
    the fetch, so callers must run their failure classifier on BOTH
    halves."""

    sources: np.ndarray
    fw0: object  # seed table (device)
    planes: tuple
    vis: object
    levels: object  # device scalar; int() blocks on the loop
    alive: object
    truncated: object
    max_levels: int
    t0: float


def _engine_dispatch_lock(engine):
    """Per-engine lock serializing the note-mask -> core-launch window.

    The pull gate's lane mask is a host attribute the gated core reads at
    call time; with the serve pipeline, a transient-retry re-dispatch can
    run on the extraction worker while the scheduler dispatches the next
    batch on the SAME engine — without the lock their note/core pairs
    could interleave and bind the wrong batch's mask. dict.setdefault is
    atomic under the GIL, so both racers agree on one lock."""
    lock = getattr(engine, "_dispatch_lock", None)
    if lock is None:
        lock = engine.__dict__.setdefault("_dispatch_lock", threading.Lock())
    return lock


def dispatch_packed_batch(
    engine, sources, *, max_levels: int | None = None
) -> PackedDispatch:
    """Launch one packed batch without blocking on its result."""
    if _faults.ACTIVE is not None:
        # Chaos-harness injection site (tpu_bfs/faults.py): the guard is
        # one attribute check, so the un-armed hot path pays nothing.
        # ``devices`` context lets mesh-qualified rules (device_lost@
        # rank=K, ISSUE 12) target the distributed engines' dispatches.
        _faults.ACTIVE.hit(
            "dispatch", lanes=engine.lanes,
            devices=_faults.mesh_devices(engine),
        )
    sources = _check_batch_sources(engine, sources)
    cap = engine.max_levels_cap
    max_levels = cap if max_levels is None else min(max_levels, cap)
    with _engine_dispatch_lock(engine):
        # Same pull-gate hook as advance_packed_batch: the gated cores
        # need the batch's active-lane mask before dispatch. The mask is
        # bound into the core call inside the lock, so a concurrent
        # dispatch (serve pipeline retry vs scheduler) cannot interleave
        # its note between this batch's note and core launch.
        note = getattr(engine, "_note_batch_sources", None)
        if note is not None:
            note(sources)
        fw0 = engine._seed_dev(sources)
        t0 = time.perf_counter()
        planes, vis, levels, alive, truncated = engine._core(
            engine.arrs, fw0, jnp.int32(max_levels)
        )
    return PackedDispatch(
        sources=sources, fw0=fw0, planes=planes, vis=vis, levels=levels,
        alive=alive, truncated=truncated, max_levels=max_levels, t0=t0,
    )


def fetch_packed_batch(
    engine, pend: PackedDispatch, *, check_cap: bool = True,
    time_it: bool = False,
) -> PackedBatchResult:
    """Block on a dispatched batch and assemble its result."""
    if _faults.ACTIVE is not None:
        # Chaos-harness injection site: slow_extract sleeps here; a
        # transient/oom/mesh kind raised here surfaces on the blocking
        # half exactly like a real async-dispatch failure
        # (tpu_bfs/faults.py; devices context as at the dispatch site).
        _faults.ACTIVE.hit(
            "fetch", lanes=engine.lanes,
            devices=_faults.mesh_devices(engine),
        )
    levels = int(pend.levels)  # blocks until the loop finishes
    elapsed = (time.perf_counter() - pend.t0) if time_it else None
    engine._warmed = True
    if (
        check_cap
        and bool(pend.truncated)
        and pend.max_levels == engine.max_levels_cap
    ):
        raise RuntimeError(
            f"traversal truncated at {levels} levels; "
            f"num_planes={engine.num_planes} caps at "
            f"{engine.max_levels_cap} — construct the engine with more "
            "planes for this graph"
        )
    result = _assemble_packed_result(
        engine, pend.sources, pend.planes, pend.vis, pend.fw0, levels,
        bool(pend.alive), elapsed
    )
    if _obs.ACTIVE is not None:
        # Engine-trace assembly (tpu_bfs/obs/engine_trace) reads the gate
        # counter — a device array whose transfer must stay behind the
        # ACTIVE guard: disarmed fetches pay this one attribute check and
        # nothing else (pinned by tests/test_obs.py's spy counter).
        from tpu_bfs.obs.engine_trace import record_packed_run

        record_packed_run(engine, levels, recorder=_obs.ACTIVE)
    return result


def run_packed_batch(
    engine,
    sources,
    *,
    max_levels: int | None = None,
    time_it: bool = False,
    check_cap: bool = True,
) -> PackedBatchResult:
    """Generic batch driver shared by the wide and hybrid engines: one
    dispatch immediately fetched (the split halves above are the serving
    pipeline's entry points; this is everyone else's)."""
    if time_it and not engine._warmed:
        int(dispatch_packed_batch(engine, sources, max_levels=max_levels).levels)
    pend = dispatch_packed_batch(engine, sources, max_levels=max_levels)
    return fetch_packed_batch(
        engine, pend, check_cap=check_cap, time_it=time_it
    )


# Re-exported here so the packed engine family imports its whole shared
# protocol surface from one module (the class itself lives in utils/aot
# so msbfs_packed — which _packed_common imports at its top — can
# inherit it without a cycle).
from tpu_bfs.utils.aot import AotProgramProtocol  # noqa: E402


def packed_aot_programs(engine):
    """The serving-path program inventory shared by the single-chip
    packed MS engines (wide + hybrid): the level-loop core (the
    30-second compile a cold start is mostly made of), seeding, the
    on-device lane reductions, and the lazy per-word distance
    extraction. Example args are ShapeDtypeStructs derived from the
    engine's own tables, so the export shapes are THE serving shapes
    (the executor always pads dispatches to exactly ``lanes``
    sources). The frontier-table shape comes from ``jax.eval_shape``
    over the seed kernel — a trace, never a compile: this inventory is
    enumerated on the preheat path, whose whole point is zero compiles
    (and on an adopted engine the unwrapped original is shaped, so the
    probe can't pollute the runtime-fallback audit)."""
    import jax

    def sds(x):
        return jax.ShapeDtypeStruct(tuple(np.shape(x)), x.dtype)

    one_i = jax.ShapeDtypeStruct((1,), jnp.int32)
    one_u = jax.ShapeDtypeStruct((1,), jnp.uint32)
    seed_fn = getattr(engine._seed, "_aot_original", engine._seed)
    fw_s = sds(jax.eval_shape(seed_fn, one_i, one_i, one_u))
    arrs_s = {k: sds(v) for k, v in engine.arrs.items()}
    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    lanes = engine.lanes
    planes_s = (fw_s,) * engine.num_planes
    progs = []
    if getattr(engine, "pull_gate", False):
        lane_mask = jax.ShapeDtypeStruct((engine.w,), jnp.uint32)
        progs.append(("core", "_gate_core_jit", engine._gate_core_jit,
                      (arrs_s, fw_s, i32, lane_mask)))
    else:
        progs.append(("core", "_core", engine._core, (arrs_s, fw_s, i32)))
    lane_i32 = jax.ShapeDtypeStruct((lanes,), jnp.int32)
    lane_u32 = jax.ShapeDtypeStruct((lanes,), jnp.uint32)
    progs += [
        ("seed", "_seed", engine._seed, (lane_i32, lane_i32, lane_u32)),
        ("lane_stats", "_lane_stats", engine._lane_stats, (fw_s,)),
        ("extract_word", "_extract_word", engine._extract_word,
         (planes_s, fw_s, fw_s, i32)),
        ("lane_ecc", "_lane_ecc", engine._lane_ecc, (planes_s, fw_s, fw_s)),
    ]
    return progs


def packed_analysis_programs(engine):
    """Static-analyzer inventory for the single-chip packed engines
    (tpu_bfs/analysis/configs.iter_programs contract): the level-loop
    core under the engine's ACTUAL expansion tier, so a pallas-tier
    core exposes its fused ``pallas_call`` body to the jaxpr walks and
    compiled audits (ISSUE 16). Unlike the AOT inventory above, the
    example args must be REAL device-resident arrays — the analyzer's
    transfer-guard pass EXECUTES each program under
    ``jax.transfer_guard('disallow')``, it does not just trace it."""
    sources = np.arange(engine.lanes, dtype=np.int64) % engine.num_vertices
    fw0 = engine._seed_dev(sources)
    ml = jnp.int32(8)
    if getattr(engine, "pull_gate", False):
        rows = np.asarray(engine._rank)[sources]
        mask = jnp.asarray(host_lane_mask(rows, engine._act, engine.w))
        return [("core", engine._gate_core_jit,
                 (engine.arrs, fw0, ml, mask))]
    return [("core", engine._core, (engine.arrs, fw0, ml))]


class PackedRunProtocol:
    """The packed-family batch entry points, defined once for every engine
    built on the shared level-loop machinery (wide, hybrid, and their
    distributed forms): blocking ``run``, and the async ``dispatch`` /
    ``fetch`` halves the serve pipeline overlaps (dispatch_packed_batch /
    fetch_packed_batch above)."""

    def run(self, sources, *, max_levels=None, time_it=False,
            check_cap=True):
        return run_packed_batch(
            self, sources, max_levels=max_levels, time_it=time_it,
            check_cap=check_cap,
        )

    def dispatch(self, sources, *, max_levels=None):
        """Launch a batch without blocking (JAX dispatch is async)."""
        return dispatch_packed_batch(self, sources, max_levels=max_levels)

    def fetch(self, pend, *, check_cap=True):
        """Block on a :meth:`dispatch` handle and assemble its result."""
        return fetch_packed_batch(self, pend, check_cap=check_cap)
