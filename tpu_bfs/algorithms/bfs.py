"""Single-device BFS driver.

The analog of the reference's host level loops (runCudaSimpleBfsMulti
bfs.cu:475-539, runCudaQueueBfs bfs.cu:542-629) — but device-resident: the
reference crosses the host<->device boundary four times per level (launch,
sync, peer copy, counter read — SURVEY.md §3.1); here the entire level loop is
a ``lax.while_loop`` compiled into one XLA program, and only the final
distance array comes back to the host.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph, DeviceGraph, INF_DIST
from tpu_bfs.algorithms.frontier import (
    EdgeData,
    INT32_MAX,
    default_dopt_caps,
    extract_parents,
    level_step,
)
from tpu_bfs.utils.timing import run_timed


@partial(jax.jit, static_argnames=("backend", "caps"), donate_argnums=(1, 2, 3))
def _bfs_core(edges, frontier0, visited0, dist0, level0, max_levels, *, backend, caps=()):
    """The compiled level loop. All shapes static; source/levels traced.

    ``level0`` is the level counter of the incoming state (0 for a fresh
    traversal, >0 when resuming from a checkpoint); the loop stops when the
    frontier empties or the counter reaches ``max_levels``. Returns the full
    state so callers can checkpoint and resume.

    The carry (frontier/visited/dist) is DONATED: the outputs alias the
    input buffers instead of doubling the state's residency for the call
    (pass 5 of tpu_bfs/analysis verifies the aliasing from the compiled
    HLO). Callers must treat those three arguments as consumed — both
    call sites below construct them fresh per call, and ``_init_state``
    materializes ``visited0`` as its own buffer (donating one array
    through two donated parameters is rejected by PJRT at execute
    time)."""

    def cond(state):
        frontier, _, _, level = state
        return jnp.any(frontier) & (level < max_levels)

    def body(state):
        frontier, visited, dist, level = state
        new = level_step(edges, frontier, visited, backend=backend, caps=caps)
        dist = jnp.where(new, level + 1, dist)
        visited = visited | new
        return new, visited, dist, level + 1

    frontier, visited, dist, level = jax.lax.while_loop(
        cond, body, (frontier0, visited0, dist0, jnp.int32(level0))
    )
    return frontier, visited, dist, level


# Donation tag for the analysis layer (pass 5's HLO aliasing certificate)
# and the AOT store (the adopting wrapper re-applies donation — jax.export
# does not carry it through deserialization by itself).
_bfs_core._donate_argnums = (1, 2, 3)


@dataclasses.dataclass
class BfsResult:
    source: int
    distance: np.ndarray  # [V] int32, INF_DIST if unreached
    parent: np.ndarray | None  # [V] int32, -1 if unreached, source->source
    num_levels: int  # eccentricity of the source (max distance reached)
    reached: int  # vertices reached (incl. source)
    edges_traversed: int  # input edges with both endpoints reached (Graph500 TEPS convention)
    elapsed_s: float | None = None

    @property
    def teps(self) -> float | None:
        if not self.elapsed_s:
            return None
        return self.edges_traversed / self.elapsed_s

    def level_sizes(self) -> np.ndarray:
        """Frontier size per level, recovered from the distance histogram —
        replaces the reference's per-level managed-counter reads (bfs.cu:617)."""
        reached = self.distance[self.distance != INF_DIST]
        return np.bincount(reached, minlength=self.num_levels + 1)


class BfsEngine:
    """Holds a device-resident graph and runs BFS from any source.

    Analog of initCuda2 (bfs.cu:308-360) + runCudaQueueBfs: construction
    uploads the (padded, dst-sorted) edge arrays once; ``run`` executes the
    compiled level loop for a traced source, so changing source does NOT
    recompile (the reference recompiles to change DeviceNum and re-uploads per
    source, bfs.cu:402-422).
    """

    def __init__(
        self,
        graph: Graph | DeviceGraph,
        *,
        backend: str = "scan",
        device=None,
        caps: tuple[int, ...] | None = None,
    ):
        dg = DeviceGraph.from_graph(graph) if isinstance(graph, Graph) else graph
        if dg.ep >= 2**31 - 1:
            raise ValueError(
                f"{dg.ep} edge slots overflow the int32 device row pointers; "
                "use DistBfsEngine to shard edges across chips"
            )
        self.dg = dg
        self.backend = backend
        put = partial(jax.device_put, device=device) if device else jax.device_put
        self.src = put(jnp.asarray(dg.src))
        self.dst = put(jnp.asarray(dg.dst))
        self.in_row_ptr = put(jnp.asarray(dg.in_row_ptr.astype(np.int32)))
        need_delta = backend == "delta"
        need_dopt = backend == "dopt"
        nbr_sm = None
        if need_dopt:
            # Neighbor ids in src-major order: dst_sm[perm_ds[i]] = dst[i].
            dst_sm = np.empty(dg.ep, dtype=np.int32)
            dst_sm[dg.perm_ds] = dg.dst
            nbr_sm = put(jnp.asarray(dst_sm))
        if caps is None:
            caps = default_dopt_caps(dg.ep) if need_dopt else ()
        self.caps = tuple(sorted(set(caps)))
        self.edges = EdgeData(
            src=self.src,
            dst=self.dst,
            in_rp=self.in_row_ptr,
            out_rp=put(jnp.asarray(dg.out_row_ptr.astype(np.int32)))
            if (need_delta or need_dopt)
            else None,
            perm_ds=put(jnp.asarray(dg.perm_ds)) if need_delta else None,
            nbr_sm=nbr_sm,
        )
        self._warmed = False

    @property
    def vp(self) -> int:
        return self.dg.vp

    def _init_state(self, source):
        vp = self.vp
        frontier0 = jnp.zeros((vp,), jnp.bool_).at[source].set(True)
        # A distinct buffer, not an alias of frontier0: both flow into
        # donated parameters of _bfs_core, and PJRT rejects one buffer
        # donated through two parameters at execute time (the same rule
        # utils/roofline.py documents for the packed step).
        visited0 = frontier0.copy()
        dist0 = jnp.full((vp,), INT32_MAX, jnp.int32).at[source].set(0)
        return frontier0, visited0, dist0

    def distances(self, source: int, *, max_levels: int | None = None):
        """Device distance array [vp] + level count; no host transfer."""
        frontier0, visited0, dist0 = self._init_state(source)
        ml = jnp.int32(max_levels if max_levels is not None else self.vp)
        _, _, dist, level = _bfs_core(
            self.edges, frontier0, visited0, dist0, jnp.int32(0), ml,
            backend=self.backend, caps=self.caps,
        )
        return dist, level

    # --- checkpoint/resume (SURVEY.md §5: the reference has none) ---

    def start(self, source: int):
        """Level-0 traversal state as a host checkpoint (no device work).

        Checkpoints hold real-vertex-id arrays [V], so they are portable
        between engines, backends, and mesh shapes (see
        tpu_bfs/utils/checkpoint.py)."""
        from tpu_bfs.utils.checkpoint import initial_checkpoint

        return initial_checkpoint(self.dg.num_vertices, source)

    def _pad_state(self, ckpt):
        v, vp = self.dg.num_vertices, self.vp
        f = np.zeros(vp, dtype=bool)
        f[:v] = ckpt.frontier
        vis = np.zeros(vp, dtype=bool)
        vis[:v] = ckpt.visited
        d = np.full(vp, INF_DIST, dtype=np.int32)
        d[:v] = ckpt.distance
        return f, vis, d

    def advance(self, ckpt, levels: int | None = None):
        """Run at most ``levels`` more BFS levels from a checkpoint.

        Returns a new host-side checkpoint; ``ckpt.done`` is True once the
        frontier is empty. The device loop is the same compiled `_bfs_core` —
        resuming N times produces bit-identical distances to one full run."""
        from tpu_bfs.utils.checkpoint import BfsCheckpoint

        if len(ckpt.frontier) != self.dg.num_vertices:
            raise ValueError(
                f"checkpoint has {len(ckpt.frontier)} vertices, graph has "
                f"{self.dg.num_vertices}"
            )
        f0, vis0, d0 = self._pad_state(ckpt)
        cap = ckpt.level + levels if levels is not None else self.vp
        frontier, visited, dist, level = _bfs_core(
            self.edges,
            jnp.asarray(f0),
            jnp.asarray(vis0),
            jnp.asarray(d0),
            jnp.int32(ckpt.level),
            jnp.int32(min(cap, self.vp)),
            backend=self.backend,
            caps=self.caps,
        )
        v = self.dg.num_vertices
        return BfsCheckpoint(
            source=ckpt.source,
            level=int(level),
            frontier=np.asarray(frontier)[:v],
            visited=np.asarray(visited)[:v],
            distance=np.asarray(dist)[:v],
            nonce=getattr(ckpt, "nonce", None),  # chain identity survives chunks
        )

    def finish(self, ckpt, *, with_parents: bool = True) -> BfsResult:
        """Convert a (finished or partial) checkpoint into a BfsResult."""
        _, _, d0 = self._pad_state(ckpt)
        return self._package(jnp.asarray(d0), ckpt.source, with_parents, None)

    def run(
        self,
        source: int,
        *,
        max_levels: int | None = None,
        with_parents: bool = True,
        time_it: bool = False,
    ) -> BfsResult:
        if not (0 <= source < self.dg.num_vertices):
            raise ValueError(f"source {source} out of range")
        elapsed = None
        if time_it:
            (dist_dev, level), elapsed = run_timed(
                lambda: self.distances(source, max_levels=max_levels),
                warm=not self._warmed,
            )
            self._warmed = True
        else:
            dist_dev, level = self.distances(source, max_levels=max_levels)
        return self._package(dist_dev, source, with_parents, elapsed)

    def _package(self, dist_dev, source, with_parents, elapsed) -> BfsResult:
        parent = None
        if with_parents:
            parent_dev = extract_parents(self.src, self.dst, dist_dev, source)
            parent = np.asarray(parent_dev)[: self.dg.num_vertices]

        v = self.dg.num_vertices
        dist = np.asarray(dist_dev)[:v]
        reached_mask = dist != INF_DIST
        reached = int(reached_mask.sum())
        # The loop's level counter includes the final step that finds an empty
        # frontier; the source eccentricity is the max distance.
        num_levels = int(dist[reached_mask].max()) if reached else 0
        edges_traversed = self._count_traversed_edges(reached_mask)
        return BfsResult(
            source=source,
            distance=dist,
            parent=parent,
            num_levels=num_levels,
            reached=reached,
            edges_traversed=edges_traversed,
            elapsed_s=elapsed,
        )

    def _count_traversed_edges(self, reached_mask: np.ndarray) -> int:
        """Graph500 TEPS numerator: input edges with both endpoints reached.

        Counted over directed slots, halved only for undirected graphs (where
        each input edge contributes two slots, bfs.cu:860-861)."""
        e = self.dg.num_edges
        slots = int(
            (reached_mask[self.dg.src[:e]] & reached_mask[self.dg.dst[:e]]).sum()
        )
        return slots // 2 if self.dg.undirected else slots


def bfs(
    graph: Graph,
    source: int,
    *,
    backend: str = "scan",
    with_parents: bool = True,
    max_levels: int | None = None,
) -> BfsResult:
    """One-shot BFS convenience wrapper (builds a BfsEngine per call)."""
    return BfsEngine(graph, backend=backend).run(
        source, with_parents=with_parents, max_levels=max_levels
    )
