"""Single-stream BFS with the dense-tile bitset expansion (backend='tiled').

The reference's live path is a single-source traversal (queueBfs,
bfs.cu:134-165). On TPU a single stream cannot batch the random gather
away (BENCHMARKS.md "Single-stream": ~13 ns per gathered edge regardless
of fetch width), so the heavy mid-BFS levels were the wall: dopt's best
was 0.0126 GTEPS at scale 21, with the one giant level costing ~0.9 s.

This engine attacks the dense PART of that level without gathers and
without the MXU: the hybrid engines' bit-packed 128x128 adjacency tiles
(2 KB each, ops/tile_spmm.py layout) admit a pure-VPU formulation of
boolean frontier expansion

    hit_bits[tile] = OR over columns c with frontier[c] of A_tile[:, c]

as u32 AND + OR-reduce over contiguous words — measured ~1.3 ns per dense
edge on v5e (10x the gather path) because the only indexed access is one
[TILE]-row lookup per tile. (The Pallas MXU kernel needs w to be a
multiple of 128 on hardware: Mosaic rejects narrower frontier slabs,
measured round 3 — so the narrow-batch MXU variant VERDICT r2 #2
proposed is closed off at the compiler, and this bitset pass is the
working replacement on the same tiles. The restriction is now enforced
at the call boundary with the legal widths named —
ops/ell_expand.validate_kernel_width, shared with the ISSUE 16
expansion kernel; any width still runs under interpret=True.)

Level structure = direction-optimizing ladder (frontier.level_step_dopt's
shape): light levels run sparse_topdown over the FULL adjacency; heavy
levels run the tile bitset pass plus an edge-centric scan over the
RESIDUAL (non-tiled) edges only. The residual scan still pays the gather
tax — the measured floor that keeps single-stream short of the batched
engines; see BENCHMARKS.md for the honest accounting.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_bfs.algorithms.bfs import BfsResult
from tpu_bfs.algorithms.frontier import (
    EdgeData,
    INT32_MAX,
    default_dopt_caps,
    level_step_dopt,
)
from tpu_bfs.graph.csr import Graph, INF_DIST, NO_PARENT, _lexsort_pairs
from tpu_bfs.graph.ell import rank_vertices
from tpu_bfs.algorithms.msbfs_hybrid import fill_a_tiles, select_dense_tiles
from tpu_bfs.ops.tile_spmm import TILE
from tpu_bfs.utils.timing import run_timed


def make_tiles_expand(vt: int):
    """Gather-free boolean expansion over bit-packed dense tiles.

    ``a_tiles`` [NT, AW, TILE] u32 (A[r, c] at word r % AW, bit r // AW),
    ``col_t`` [NT] column-tile ids, ``seg`` [NT] row-tile ids
    (non-decreasing), ``fb`` [vt, TILE] bool frontier. Returns [vt*TILE]
    bool hits. One [TILE]-row lookup per tile is the only indexed access;
    everything else is contiguous u32 AND / OR-reduce / shift — VPU
    bandwidth, not gather latency."""

    def tiles_expand(a_tiles, col_t, seg, fb):
        shifts = jnp.arange(32, dtype=jnp.uint32)
        fm = fb[col_t]  # [NT, TILE] bool
        sel = a_tiles & jnp.where(
            fm[:, None, :], jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
        )
        # OR-reduce the 128 columns by tree halving (7 strided ORs — XLA
        # lowers these better than a rank-3 lax.reduce with a custom
        # combiner).
        red = sel
        while red.shape[-1] > 1:
            half = red.shape[-1] // 2
            red = red[..., :half] | red[..., half:]
        red = red[..., 0]  # [NT, AW]
        bits = ((red[:, None, :] >> shifts[None, :, None]) & 1).astype(
            jnp.int32
        )
        # Row r of a tile lives at word r % AW, bit r // AW: the [32, AW]
        # C-order reshape lands index bit*AW + word = r.
        contrib = bits.reshape(-1, TILE)  # [NT, TILE]
        hit = jax.ops.segment_sum(
            contrib, seg, num_segments=vt, indices_are_sorted=True
        )
        return (hit > 0).reshape(-1)  # [vt*TILE]

    return tiles_expand


def make_gated_tiles_expand(vt: int, num_tiles: int):
    """Pull-gated form of make_tiles_expand (ISSUE 1): process only tiles
    whose source column-tile holds a frontier bit AND whose destination
    row-tile is not fully visited.

    The source half is EXACT (an empty frontier column-tile contributes
    nothing); the destination half is claim-masked like the packed
    engines' settled rows (the caller ANDs the pass with ``~visited``), so
    both gates are bit-identical to the dense pass. Tiles are compacted
    with the shared ``jnp.where(size=...)`` + bounded-fori mechanism; the
    dense pass takes over via lax.cond when most tiles are active
    (_packed_common.GATE_DENSE_DEN — at peak levels the serial per-tile
    loop would forfeit the vectorized pass's throughput).

    Returns ``expand(a_tiles, col_t, seg, fb, visited) ->
    ([vt*TILE] bool hits, skipped_tiles int32)``.
    """
    from tpu_bfs.algorithms._packed_common import GATE_DENSE_DEN

    dense_expand = make_tiles_expand(vt)

    def expand(a_tiles, col_t, seg, fb, visited):
        shifts = jnp.arange(32, dtype=jnp.uint32)
        src_on = jnp.any(fb, axis=1)[col_t]
        dst_done = jnp.all(visited.reshape(vt, TILE), axis=1)[seg]
        on = src_on & ~dst_done
        nz = jnp.sum(on.astype(jnp.int32))

        def dense():
            return dense_expand(a_tiles, col_t, seg, fb), jnp.int32(0)

        def gated():
            idx = jnp.where(on, size=num_tiles, fill_value=0)[0]

            def body(j, hit):
                t = idx[j]
                sel = a_tiles[t] & jnp.where(
                    fb[col_t[t]][None, :],
                    jnp.uint32(0xFFFFFFFF),
                    jnp.uint32(0),
                )
                red = sel  # [AW, TILE] -> [AW] by tree halving
                while red.shape[-1] > 1:
                    half = red.shape[-1] // 2
                    red = red[..., :half] | red[..., half:]
                red = red[..., 0]
                # Same r = bit*AW + word layout as the dense pass.
                contrib = (
                    ((red[None, :] >> shifts[:, None]) & 1) > 0
                ).reshape(TILE)
                rt = seg[t]
                return hit.at[rt].set(hit[rt] | contrib)

            hit = jax.lax.fori_loop(
                0, nz, body, jnp.zeros((vt, TILE), jnp.bool_)
            )
            return hit.reshape(-1), num_tiles - nz

        return lax.cond(nz * GATE_DENSE_DEN <= num_tiles, gated, dense)

    return expand


class TiledBfsEngine:
    """Single-source BFS: dopt ladder + dense-tile bitset heavy levels.

    API mirrors BfsEngine (run -> BfsResult). State lives in rank-row
    space (descending-degree rank, padded to 128-row tiles); distances
    map back to vertex ids at extraction.
    """

    def __init__(
        self,
        graph: Graph,
        *,
        tile_thr: int = 32,
        a_budget_bytes: int = int(0.8e9),
        dopt_caps: tuple[int, ...] | None = None,
        pull_gate: bool = False,
    ):
        # Defaults are the measured scale-21 knee (BENCHMARKS.md): thr=32 /
        # 0.8 GB reaches 67% dense coverage at hmean 0.030 GTEPS; doubling
        # the budget again (thr=16 / 2 GB, 73%) is flat — the tile pass
        # grows with NT as fast as the residual shrinks.
        g = graph
        self.host_graph = g  # parent extraction (min_parent_from_dist)
        self.graph_meta = (g.num_input_edges, g.undirected)
        self._degrees = g.degrees
        src, dst = g.coo
        in_deg, act, order, rank = rank_vertices(src, dst, g.num_vertices)
        self._rank = rank
        self._act = act
        self.num_vertices = g.num_vertices
        vt = -(-(act + 1) // TILE)
        rows = vt * TILE
        self.vt, self.rows = vt, rows
        r = rank[dst]
        c = rank[src]

        dense_edge, uniq, tid = select_dense_tiles(
            r, c, vt, tile_thr=tile_thr, a_budget_bytes=a_budget_bytes
        )
        self.num_tiles = len(uniq)
        self.num_dense_edges = int(dense_edge.sum())
        a_tiles = fill_a_tiles(dense_edge, uniq, tid, r, c)
        self._a = jnp.asarray(a_tiles)
        self._col_t = jnp.asarray((uniq % vt).astype(np.int32))
        self._seg = jnp.asarray((uniq // vt).astype(np.int32))
        # Pull gate (ISSUE 1): frontier/visited-aware tile pass; default
        # off until chip-measured. ``last_gate_skipped_tiles`` records the
        # skipped-tile total of the most recent loop dispatch — a whole
        # run(), or ONE advance() segment of a checkpointed traversal
        # (segments overwrite, they do not accumulate across a chain).
        self.pull_gate = pull_gate
        self.last_gate_skipped_tiles: int | None = None
        self._tiles_expand = make_tiles_expand(vt)
        self._gated_tiles_expand = (
            make_gated_tiles_expand(vt, self.num_tiles)
            if pull_gate and self.num_tiles
            else None
        )

        # Full adjacency, src-major: the sparse top-down branches.
        order_sm = _lexsort_pairs(c, r, rows, rows)
        out_rp = np.zeros(rows + 1, dtype=np.int32)
        np.cumsum(np.bincount(c, minlength=rows), out=out_rp[1:])
        nbr_sm = r[order_sm].astype(np.int32)

        # Residual edges, dst-major: the heavy levels' scan complement.
        re = np.flatnonzero(~dense_edge)
        rr, cc = r[re], c[re]
        order_dm = _lexsort_pairs(rr, cc, rows, rows)
        res_rp = np.zeros(rows + 1, dtype=np.int32)
        np.cumsum(np.bincount(rr, minlength=rows), out=res_rp[1:])
        self._edges = EdgeData(
            src=jnp.asarray(cc[order_dm].astype(np.int32)),
            dst=jnp.asarray(rr[order_dm].astype(np.int32)),
            in_rp=jnp.asarray(res_rp),
            out_rp=jnp.asarray(out_rp),
            nbr_sm=jnp.asarray(nbr_sm),
        )
        if dopt_caps is None:
            dopt_caps = default_dopt_caps(g.num_edges)
        self.dopt_caps = tuple(sorted(set(dopt_caps)))
        self._loop = self._make_loop()
        self._warmed = False

    def _make_loop(self):
        rows, vt = self.rows, self.vt
        tiles_expand = self._tiles_expand
        gated_tiles_expand = self._gated_tiles_expand
        caps = self.dopt_caps
        has_tiles = self.num_tiles > 0
        gated = gated_tiles_expand is not None

        def level(edges, tiles, frontier, visited):
            # The shared dopt rung ladder (frontier.level_step_dopt): sparse
            # rungs cover ALL edges via the full out-CSR; the dense fallback
            # is the edge-centric scan over the RESIDUAL in-CSR only (this
            # engine's edges.src/dst/in_rp hold just the residual edges).
            hit = level_step_dopt(edges, frontier, visited, caps=caps)
            skipped = jnp.int32(0)
            if has_tiles:
                # The tile pass sits in its own single cond, firing exactly
                # when the dense fallback fires (no rung fits — fits() is
                # monotone in cap, so testing the TOP rung suffices): its
                # hits are always valid frontier neighbors, and on rung
                # levels the rung already found them. Skipping it on light
                # levels is what makes large tile budgets affordable.
                out_deg = edges.out_rp[1:] - edges.out_rp[:-1]
                fsum = jnp.sum(jnp.where(frontier, out_deg, 0))
                nfront = jnp.sum(frontier.astype(jnp.int32))
                top = max(caps)
                dense_level = ~(
                    (fsum <= top) & (nfront <= min(top, rows))
                )
                a, col_t, seg = tiles
                if gated:
                    def tile_pass():
                        th, sk = gated_tiles_expand(
                            a, col_t, seg, frontier.reshape(vt, TILE),
                            visited,
                        )
                        return hit | (th & ~visited), sk

                    hit, skipped = lax.cond(
                        dense_level, tile_pass,
                        lambda: (hit, jnp.int32(0)),
                    )
                else:
                    hit = lax.cond(
                        dense_level,
                        lambda: hit
                        | (
                            tiles_expand(
                                a, col_t, seg, frontier.reshape(vt, TILE)
                            )
                            & ~visited
                        ),
                        lambda: hit,
                    )
            return hit, skipped

        # Edge/tile arrays are jit ARGUMENTS, not closure constants: baked-in
        # constants get serialized into the compile request (hundreds of MB
        # here — the remote compile service rejects them outright).
        # ``level0`` makes this the checkpoint-resume entry too: the
        # while-loop carry IS the traversal state, so resuming from a saved
        # (frontier, visited, dist, level) is bit-identical to no stop.
        # In gated mode the carry (and return) grows a skipped-tile total.
        @jax.jit
        def loop(edges, tiles, frontier0, visited0, dist0, level0, max_levels):
            def cond(state):
                lvl, count = state[3], state[4]
                return (count > 0) & (lvl < max_levels)

            def body(state):
                frontier, visited, dist, lvl, _ = state[:5]
                nxt, skipped = level(edges, tiles, frontier, visited)
                dist = jnp.where(nxt, lvl + 1, dist)
                visited = visited | nxt
                out = (
                    nxt, visited, dist, lvl + 1,
                    jnp.sum(nxt.astype(jnp.int32)),
                )
                if gated:
                    out = out + (state[5] + skipped,)
                return out

            init = jnp.sum(frontier0.astype(jnp.int32))
            state0 = (frontier0, visited0, dist0, level0, init)
            if gated:
                state0 = state0 + (jnp.int32(0),)
            out = lax.while_loop(cond, body, state0)
            frontier, visited, dist, lvl = out[:4]
            if gated:
                return frontier, visited, dist, lvl, out[5]
            return frontier, visited, dist, lvl

        return loop

    def run(
        self,
        source: int,
        *,
        max_levels: int | None = None,
        with_parents: bool = True,
        time_it: bool = False,
    ) -> BfsResult:
        if not (0 <= source < self.num_vertices):
            raise ValueError(f"source {source} out of range")
        rs = int(self._rank[source])
        dist_v = np.full(self.num_vertices, INF_DIST, np.int32)
        dist_v[source] = 0
        if rs >= self._act:  # isolated source: component == {source}
            parent = None
            if with_parents:
                parent = np.full(self.num_vertices, NO_PARENT, np.int32)
                parent[source] = source
            return BfsResult(
                source=source, distance=dist_v, parent=parent, num_levels=0,
                reached=1, edges_traversed=0, elapsed_s=None,
            )

        def go():
            f0 = jnp.zeros((self.rows,), jnp.bool_).at[rs].set(True)
            d0 = jnp.full((self.rows,), INT32_MAX, jnp.int32).at[rs].set(0)
            ml = jnp.int32(max_levels if max_levels is not None else self.rows)
            return self._loop(
                self._edges, (self._a, self._col_t, self._seg), f0, f0, d0,
                jnp.int32(0), ml,
            )

        elapsed = None
        if time_it:
            out, elapsed = run_timed(go, warm=not self._warmed)
            self._warmed = True
        else:
            out = go()
        dist_dev = out[2]
        if self.pull_gate and self.num_tiles:
            self.last_gate_skipped_tiles = int(out[4])

        dr = np.asarray(dist_dev)
        live = self._rank < self._act
        dist_v[live] = dr[self._rank[live]]
        return self._package(dist_v, source, with_parents, elapsed)

    def _package(self, dist_v, source, with_parents, elapsed) -> BfsResult:
        dist_v = np.where(dist_v == INT32_MAX, INF_DIST, dist_v)
        reached_mask = dist_v != INF_DIST
        reached = int(reached_mask.sum())
        num_levels = int(dist_v[reached_mask].max()) if reached else 0
        _, undirected = self.graph_meta
        slots = int(self._degrees[reached_mask].sum()) if reached else 0
        parent = None
        if with_parents:
            # One O(E) host scatter-min (outside the timed loop), the same
            # deterministic tree every engine emits.
            from tpu_bfs import validate

            parent = validate.min_parent_from_dist(self.host_graph, source, dist_v)
        return BfsResult(
            source=source,
            distance=dist_v,
            parent=parent,
            num_levels=num_levels,
            reached=reached,
            edges_traversed=slots // 2 if undirected else slots,
            elapsed_s=elapsed,
        )

    # --- checkpoint/resume (tpu_bfs/utils/checkpoint.py; SURVEY.md §5:
    # the reference has none). Checkpoints hold REAL-vertex-id arrays [V]
    # like every other single-source engine, so a checkpoint taken here
    # resumes on BfsEngine / DistBfsEngine / Dist2DBfsEngine and back. ---

    def start(self, source: int):
        """Level-0 traversal state as a host checkpoint (no device work)."""
        from tpu_bfs.utils.checkpoint import initial_checkpoint

        return initial_checkpoint(self.num_vertices, source)

    def advance(self, ckpt, levels: int | None = None):
        """Run at most ``levels`` more levels; bit-identical to no stop."""
        from tpu_bfs.utils.checkpoint import BfsCheckpoint

        if len(ckpt.frontier) != self.num_vertices:
            raise ValueError(
                f"checkpoint has {len(ckpt.frontier)} vertices, graph has "
                f"{self.num_vertices}"
            )
        live = self._rank < self._act
        rows_live = self._rank[live]
        f0 = np.zeros(self.rows, dtype=bool)
        f0[rows_live] = ckpt.frontier[live]
        vis0 = np.zeros(self.rows, dtype=bool)
        vis0[rows_live] = ckpt.visited[live]
        d0 = np.full(self.rows, INT32_MAX, np.int32)
        d0[rows_live] = ckpt.distance[live]  # INF_DIST == INT32_MAX
        cap = ckpt.level + levels if levels is not None else self.rows
        out = self._loop(
            self._edges, (self._a, self._col_t, self._seg),
            jnp.asarray(f0), jnp.asarray(vis0), jnp.asarray(d0),
            jnp.int32(ckpt.level), jnp.int32(min(cap, self.rows)),
        )
        frontier, visited, dist, level = out[:4]
        if self.pull_gate and self.num_tiles:
            self.last_gate_skipped_tiles = int(out[4])
        fr, vr, dr = (np.asarray(a) for a in (frontier, visited, dist))
        f_v = np.zeros(self.num_vertices, dtype=bool)
        f_v[live] = fr[rows_live]
        vis_v = np.zeros(self.num_vertices, dtype=bool)
        vis_v[live] = vr[rows_live]
        d_v = np.full(self.num_vertices, INF_DIST, np.int32)
        d_v[live] = dr[rows_live]
        # An isolated source has no rank row; its state lives only in the
        # checkpoint (component == {source}, done after this advance).
        if not live[ckpt.source]:
            vis_v[ckpt.source] = True
            d_v[ckpt.source] = 0
        return BfsCheckpoint(
            source=ckpt.source,
            level=int(level),
            frontier=f_v,
            visited=vis_v,
            distance=d_v,
            nonce=getattr(ckpt, "nonce", None),
        )

    def finish(self, ckpt, *, with_parents: bool = True) -> BfsResult:
        """Convert a (finished or partial) checkpoint into a BfsResult."""
        return self._package(
            ckpt.distance.copy(), ckpt.source, with_parents, None
        )
