"""Edge-centric, atomics-free BFS level primitives.

The reference has two kernel formulations:

- dense level-synchronous ``multiBfs`` (bfs.cu:101-130): one thread per owned
  vertex, racy peer stores, a shared ``changed`` flag;
- frontier-queue ``queueBfs`` (bfs.cu:134-165): ``atomicMin`` visited-claim +
  ``atomicAdd`` queue append.

Neither maps to TPU (no atomics, no dynamic shapes — SURVEY.md §7 "hard
parts"). The TPU-native formulation here is edge-centric and race-free by
construction:

    active[e]  = frontier[src[e]]                  (gather)
    hit[v]     = OR over edges e with dst[e]==v of active[e]   (scatter-or /
                                                    segment-or; edges are
                                                    dst-sorted in DeviceGraph)
    next       = hit & ~visited

Distances come from the level counter; parents are extracted AFTER the level
loop in one O(E) pass (``extract_parents``) — they are a pure function of the
final distance array, so the hot loop carries no parent state at all. The
deterministic min-parent rule replaces the reference's atomic-race winner
(bfs.cu:146-147).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

INT32_MAX = jnp.iinfo(jnp.int32).max

# Registry of frontier-expansion backends; 'pallas' is registered by
# tpu_bfs.ops when available.
_EXPAND_BACKENDS = {}


def expand_or(active, dst, vp: int, *, backend: str = "segment"):
    """hit[v] = OR_{e: dst[e]==v} active[e].  ``dst`` must be non-decreasing
    for the 'segment' backend (DeviceGraph guarantees this)."""
    return _EXPAND_BACKENDS[backend](active, dst, vp)


def _expand_scatter(active, dst, vp):
    return jnp.zeros((vp,), jnp.bool_).at[dst].max(active, mode="drop")


def _expand_segment(active, dst, vp):
    seg = jax.ops.segment_max(
        active.astype(jnp.int32), dst, num_segments=vp, indices_are_sorted=True
    )
    return seg > 0


_EXPAND_BACKENDS["scatter"] = _expand_scatter
_EXPAND_BACKENDS["segment"] = _expand_segment


def level_step(src, dst, frontier, visited, *, backend: str = "segment"):
    """One BFS level: returns the next frontier mask.

    Semantics of one iteration of the reference's level loop
    (runCudaQueueBfs, bfs.cu:569-621 / multiBfs, bfs.cu:101-130), with the
    visited test folded in (`& ~visited` replaces the atomicMin claim).
    """
    active = frontier[src]
    hit = expand_or(active, dst, frontier.shape[0], backend=backend)
    return hit & ~visited


@partial(jax.jit, static_argnames=("vp",))
def _extract_parents_impl(src, dst, dist, source, vp: int):
    du = dist[src]
    dv = dist[dst]
    ok = (du != INT32_MAX) & (du + 1 == dv)
    cand = jnp.where(ok, src, INT32_MAX)
    parent = jnp.full((vp,), INT32_MAX, jnp.int32).at[dst].min(cand, mode="drop")
    parent = jnp.where(parent == INT32_MAX, -1, parent)
    parent = jnp.where(dist == INT32_MAX, -1, parent)
    return parent.at[source].set(source)


def extract_parents(src, dst, dist, source):
    """Deterministic min-parent tree from the final distance array.

    parent[v] = min{ u : (u,v) in E, dist[u] = dist[v]-1 }; source -> itself;
    unreached -> -1. One O(E) scatter-min, outside the hot loop.
    """
    return _extract_parents_impl(src, dst, dist, source, dist.shape[0])
