"""Edge-centric, atomics-free BFS level primitives.

The reference has two kernel formulations:

- dense level-synchronous ``multiBfs`` (bfs.cu:101-130): one thread per owned
  vertex, racy peer stores, a shared ``changed`` flag;
- frontier-queue ``queueBfs`` (bfs.cu:134-165): ``atomicMin`` visited-claim +
  ``atomicAdd`` queue append.

Neither maps to TPU (no atomics, no dynamic shapes — SURVEY.md §7 "hard
parts"). The TPU-native formulation here is edge-centric and race-free by
construction:

    active[e]  = frontier[src[e]]                  (gather)
    hit[v]     = OR over edges e with dst[e]==v of active[e]   (scatter-or /
                                                    segment-or; edges are
                                                    dst-sorted in DeviceGraph)
    next       = hit & ~visited

Distances come from the level counter; parents are extracted AFTER the level
loop in one O(E) pass (``extract_parents``) — they are a pure function of the
final distance array, so the hot loop carries no parent state at all. The
deterministic min-parent rule replaces the reference's atomic-race winner
(bfs.cu:146-147).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

INT32_MAX = jnp.iinfo(jnp.int32).max


class EdgeData(NamedTuple):
    """Device-resident edge arrays for one chip (see DeviceGraph).

    out_rp / perm_ds / nbr_sm may be None for backends that don't need them."""

    src: jax.Array  # [ep] dst-major
    dst: jax.Array  # [ep] non-decreasing
    in_rp: jax.Array  # [vp+1] CSR-by-dst boundaries
    out_rp: jax.Array | None = None  # [vp+1] CSR-by-src boundaries (src-major order)
    perm_ds: jax.Array | None = None  # [ep] src-major position of dst-major edge i
    nbr_sm: jax.Array | None = None  # [ep] neighbor (dst) ids in src-major order

# Registry of frontier-expansion backends; 'pallas' is registered by
# tpu_bfs.ops when available.
_EXPAND_BACKENDS = {}


def expand_or(active, dst, in_row_ptr, vp: int, *, backend: str = "scan"):
    """hit[v] = OR_{e: dst[e]==v} active[e].

    ``active`` is [ep] or [ep, K] (batched multi-source); the edge axis is
    leading either way. ``dst`` must be non-decreasing for the
    'scan'/'segment' backends (DeviceGraph guarantees this); ``in_row_ptr``
    is the [vp+1] CSR-by-dst row pointer ('scan' backend only — pass None
    otherwise).
    """
    if backend not in _EXPAND_BACKENDS:
        raise KeyError(
            f"unknown expansion backend {backend!r}; have {sorted(_EXPAND_BACKENDS)}"
        )
    return _EXPAND_BACKENDS[backend](active, dst, in_row_ptr, vp)


def _expand_scatter(active, dst, in_row_ptr, vp):
    out_shape = (vp,) + active.shape[1:]
    return jnp.zeros(out_shape, jnp.bool_).at[dst].max(active, mode="drop")


def _expand_segment(active, dst, in_row_ptr, vp):
    seg = jax.ops.segment_max(
        active.astype(jnp.int32), dst, num_segments=vp, indices_are_sorted=True
    )
    return seg > 0


def _expand_scan(active, dst, in_row_ptr, vp):
    """Scatter-free segment-OR: cumulative sum of active flags differenced at
    CSR-by-dst row boundaries. hit[v] = csum[rp[v+1]] - csum[rp[v]] > 0.

    This is the TPU-idiomatic revival of the reference's dead scan-BFS
    pipeline (runCudaScanBfs, bfs.cu:706-781): its block prefix-sums + CPU
    fix-up become one dense cumsum; no scatter, no atomics (SURVEY.md §3.5).
    """
    csum = jnp.cumsum(active.astype(jnp.int32), axis=0)
    zero = jnp.zeros((1,) + active.shape[1:], jnp.int32)
    csum0 = jnp.concatenate([zero, csum], axis=0)
    return jnp.diff(csum0[in_row_ptr], axis=0) > 0


_EXPAND_BACKENDS["scatter"] = _expand_scatter
_EXPAND_BACKENDS["segment"] = _expand_segment
_EXPAND_BACKENDS["scan"] = _expand_scan


def active_bits_delta(frontier, out_rp, ep: int):
    """Frontier expansion into *src-major* edge space without a per-edge
    frontier gather.

    Marks +-1 at each frontier vertex's out-row boundaries and prefix-sums:
    active[e] = 1 iff edge e's source is in the frontier. The two scatters are
    vp-sized (small); the expansion itself is one dense O(ep) cumsum. (The
    caller still pays one per-edge permutation gather to reach dst order —
    see level_step.) frontier may be [vp] or [vp, K].
    """
    f = frontier.astype(jnp.int32)
    zeros = jnp.zeros((ep + 1,) + frontier.shape[1:], jnp.int32)
    delta = zeros.at[out_rp[:-1]].add(f).at[out_rp[1:]].add(-f)
    return jnp.cumsum(delta, axis=0)[:ep] > 0


def sparse_topdown(
    edges: EdgeData, frontier, visited=None, *, edge_cap: int, vert_cap: int,
    out_size: int | None = None,
):
    """One top-down level over ONLY the frontier's out-edges, in static shapes.

    The direction-optimizing counterpart of the dense step: compaction
    (``nonzero`` = cumsum + scatter, the TPU form of the reference's dead
    scan-BFS queue generation, bfs.cu:706-781) lays the frontier's adjacency
    lists head-to-head in a fixed ``edge_cap``-slot buffer, one gather
    fetches the neighbors, one scatter-or marks the hits. Work is
    O(edge_cap + vert_cap) regardless of E — callers pick this branch only
    when the frontier's out-degree sum fits (see level_step_dopt).

    ``out_size`` sets the hit-vector length when neighbor ids live in a
    different index space than the frontier (the distributed engines:
    frontier is the owned/column-gathered slice, neighbors are global padded
    or row-block-local ids); ``visited=None`` skips the claim — distributed
    callers claim after the exchange collective instead.
    """
    vp = out_size if out_size is not None else frontier.shape[0]
    out_rp = edges.out_rp
    nfront = jnp.sum(frontier.astype(jnp.int32))
    (vids,) = jnp.nonzero(frontier, size=vert_cap, fill_value=0)
    slot_ok = jnp.arange(vert_cap, dtype=jnp.int32) < nfront
    deg = jnp.where(slot_ok, out_rp[vids + 1] - out_rp[vids], 0)
    ends = jnp.cumsum(deg)
    starts = ends - deg
    total = ends[-1]
    # owner[j] = which compacted row edge-slot j belongs to: +1 at each row
    # start, prefix-summed (deg-0 rows collapse harmlessly: they own no slots).
    delta = (
        jnp.zeros((edge_cap + 1,), jnp.int32)
        .at[jnp.minimum(starts, edge_cap)]
        .add(slot_ok.astype(jnp.int32))
    )
    owner = jnp.cumsum(delta[:edge_cap]) - 1
    eslot = jnp.arange(edge_cap, dtype=jnp.int32)
    valid = eslot < total
    owner = jnp.clip(owner, 0, vert_cap - 1)
    eidx = out_rp[vids[owner]] + (eslot - starts[owner])
    nbr = edges.nbr_sm[jnp.where(valid, eidx, 0)]
    hit = (
        jnp.zeros((vp,), jnp.bool_)
        .at[jnp.where(valid, nbr, vp - 1)]
        .max(valid, mode="drop")
    )
    # The guard writes at vp-1 may alias a real phantom-free graph's last
    # vertex only when valid is False there, so the value written is False.
    return hit if visited is None else hit & ~visited


def default_dopt_caps(ep: int) -> tuple[int, ...]:
    """Capacity ladder for the sparse top-down branches: ~E/64 and ~E/8,
    lane-aligned. Levels whose frontier out-degree sum exceeds the top rung
    run the dense step. Shared by the single-device and distributed engines
    (``ep`` = the edge count the ladder scales against — per chip for the
    distributed engines)."""
    return tuple(max(1024, (ep >> s) // 1024 * 1024) for s in (6, 3))


def make_dopt_expand(edata: EdgeData, caps, *, vert_limit: int, out_size: int,
                     dense_fn):
    """Claim-free direction-optimizing expansion for the distributed engines.

    Returns ``expand(frontier) -> hit [out_size]``: the smallest ``caps``
    rung covering the frontier's local out-degree sum runs sparse_topdown,
    otherwise ``dense_fn(frontier)``. All branches are collective-free, so
    distributed callers may let chips diverge per level — the exchange and
    termination collectives sit outside the `lax.cond`. (The single-device
    engine uses level_step_dopt instead, which folds the visited claim in.)
    """
    out_deg = edata.out_rp[1:] - edata.out_rp[:-1]

    def expand(frontier):
        fsum = jnp.sum(jnp.where(frontier, out_deg, 0))
        nfront = jnp.sum(frontier.astype(jnp.int32))
        step = lambda: dense_fn(frontier)
        for edge_cap in sorted(caps, reverse=True):
            vert_cap = min(edge_cap, vert_limit)
            fits = (fsum <= edge_cap) & (nfront <= vert_cap)
            step = partial(
                lax.cond,
                fits,
                (lambda ec=edge_cap, vc=vert_cap: sparse_topdown(
                    edata, frontier, None,
                    edge_cap=ec, vert_cap=vc, out_size=out_size,
                )),
                step,
            )
        return step()

    return expand


def level_step_dopt(
    edges: EdgeData, frontier, visited, *, caps: tuple, dense_backend: str = "scan"
):
    """Direction-optimizing level step: Beamer's top-down/bottom-up switch in
    static-shape form.

    ``caps`` is an ascending ladder of edge capacities; the smallest sparse
    branch whose capacity covers the frontier's out-degree sum runs top-down
    (sparse_topdown), otherwise the dense edge-centric step runs — the
    bottom-up analog, whose cost is frontier-independent. ``lax.cond``
    executes exactly one branch at runtime, so light levels (BFS start/tail,
    high-diameter graphs) cost O(cap) instead of O(E).
    """
    out_deg = edges.out_rp[1:] - edges.out_rp[:-1]
    fsum = jnp.sum(jnp.where(frontier, out_deg, 0))
    nfront = jnp.sum(frontier.astype(jnp.int32))

    def dense_fn():
        active = frontier[edges.src]
        return expand_or(
            active, edges.dst, edges.in_rp, frontier.shape[0], backend=dense_backend
        ) & ~visited

    def make_sparse(edge_cap, vert_cap):
        return lambda: sparse_topdown(
            edges, frontier, visited, edge_cap=edge_cap, vert_cap=vert_cap
        )

    step = dense_fn
    for edge_cap in sorted(caps, reverse=True):
        vert_cap = min(edge_cap, frontier.shape[0])
        fits = (fsum <= edge_cap) & (nfront <= vert_cap)
        step = partial(
            lax.cond, fits, make_sparse(edge_cap, vert_cap), step
        )
    return step()


def level_step(edges: EdgeData, frontier, visited, *, backend: str = "scan", caps=()):
    """One BFS level: returns the next frontier mask.

    Semantics of one iteration of the reference's level loop
    (runCudaQueueBfs, bfs.cu:569-621 / multiBfs, bfs.cu:101-130), with the
    visited test folded in (`& ~visited` replaces the atomicMin claim).

    backend='delta' trades the data-dependent frontier[src] gather for a
    *static* permutation gather (act_src[perm_ds]): same O(ep) element count,
    but the index vector is fixed at build time and data-independent, which a
    compiler/kernel can exploit (and which the other backends cannot). Whether
    it wins over 'scan' is hardware-dependent — benchmark both.

    backend='dopt' is the direction-optimizing step (level_step_dopt) with
    the static edge-capacity ladder ``caps``.
    """
    vp = frontier.shape[0]
    if backend == "dopt":
        return level_step_dopt(edges, frontier, visited, caps=caps)
    if backend == "delta":
        act_src = active_bits_delta(frontier, edges.out_rp, edges.perm_ds.shape[0])
        active = act_src[edges.perm_ds]
        return _expand_scan(active, edges.dst, edges.in_rp, vp) & ~visited
    active = frontier[edges.src]
    hit = expand_or(active, edges.dst, edges.in_rp, vp, backend=backend)
    return hit & ~visited


def min_parent_candidates(src, dst, dist):
    """Deterministic min-parent from a distance array, without source fixup.

    dist is [vp] or [vp, K]; parent[v] = min{u : (u,v) in E, dist[u] ==
    dist[v]-1}, -1 where unreached or parentless. The single scatter-min
    replaces the reference's atomic-race parent claim (bfs.cu:146-147)."""
    du = dist[src]
    dv = dist[dst]
    ok = (du != INT32_MAX) & (du + 1 == dv)
    src_b = src if dist.ndim == 1 else src[:, None]
    cand = jnp.where(ok, src_b, INT32_MAX)
    parent = jnp.full(dist.shape, INT32_MAX, jnp.int32).at[dst].min(cand, mode="drop")
    parent = jnp.where(parent == INT32_MAX, -1, parent)
    return jnp.where(dist == INT32_MAX, -1, parent)


@jax.jit
def _extract_parents_impl(src, dst, dist, source):
    return min_parent_candidates(src, dst, dist).at[source].set(source)


def extract_parents(src, dst, dist, source):
    """Deterministic min-parent tree from the final distance array.

    parent[v] = min{ u : (u,v) in E, dist[u] = dist[v]-1 }; source -> itself;
    unreached -> -1. One O(E) scatter-min, outside the hot loop.
    """
    return _extract_parents_impl(src, dst, dist, source)
