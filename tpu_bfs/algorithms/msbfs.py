"""Multi-source (batched) BFS.

No analog in the reference — its driver runs one source per process launch
(bfs.cu:786). On TPU, batching K concurrent traversals is the natural way to
feed the vector units: the frontier becomes a [vp, K] bit-plane, the per-edge
gather fetches a K-wide row (lane-aligned, amortizing the random access that
dominates single-source BFS), and the level step is identical in structure.
Graph500's required 64-source run maps to one msbfs call.

Semantics per source are exactly `algorithms.bfs`: level-synchronous,
atomics-free, deterministic. Distances come out as [K, V]; parents (optional)
via the same post-loop min-parent extraction, vectorized over sources.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph, DeviceGraph, INF_DIST
from tpu_bfs.algorithms.frontier import EdgeData, INT32_MAX, level_step, min_parent_candidates
from tpu_bfs.utils.timing import run_timed


@partial(jax.jit, static_argnames=("backend",))
def _msbfs_core(edges, frontier0, visited0, dist0, max_levels, *, backend):
    """Batched level loop. frontier/visited: [vp, K] bool; dist: [vp, K] int32."""

    def cond(state):
        frontier, _, _, level = state
        return jnp.any(frontier) & (level < max_levels)

    def body(state):
        frontier, visited, dist, level = state
        new = level_step(edges, frontier, visited, backend=backend)
        dist = jnp.where(new, level + 1, dist)
        visited = visited | new
        return new, visited, dist, level + 1

    _, _, dist, level = jax.lax.while_loop(
        cond, body, (frontier0, visited0, dist0, jnp.int32(0))
    )
    return dist, level


@jax.jit
def _msbfs_parents(src, dst, dist, sources):
    """Vectorized min-parent extraction: [vp, K] dist -> [vp, K] parents."""
    parent = min_parent_candidates(src, dst, dist)
    k_idx = jnp.arange(sources.shape[0])
    return parent.at[sources, k_idx].set(sources)


@dataclasses.dataclass
class MsBfsResult:
    sources: np.ndarray  # [K]
    distance: np.ndarray  # [K, V]
    parent: np.ndarray | None  # [K, V]
    elapsed_s: float | None = None


class MsBfsEngine:
    """Batched-source BFS over a device-resident graph."""

    def __init__(self, graph: Graph | DeviceGraph, *, backend: str = "scan"):
        dg = DeviceGraph.from_graph(graph) if isinstance(graph, Graph) else graph
        if dg.ep >= 2**31 - 1:
            raise ValueError("edge slots overflow int32 row pointers")
        self.dg = dg
        self.backend = backend
        self.src = jnp.asarray(dg.src)
        self.dst = jnp.asarray(dg.dst)
        self.in_row_ptr = jnp.asarray(dg.in_row_ptr.astype(np.int32))
        need_delta = backend == "delta"
        self.edges = EdgeData(
            src=self.src,
            dst=self.dst,
            in_rp=self.in_row_ptr,
            out_rp=jnp.asarray(dg.out_row_ptr.astype(np.int32)) if need_delta else None,
            perm_ds=jnp.asarray(dg.perm_ds) if need_delta else None,
        )
        self._warmed_k = set()

    def _init_state(self, sources: jnp.ndarray):
        vp, k = self.dg.vp, sources.shape[0]
        k_idx = jnp.arange(k)
        frontier0 = jnp.zeros((vp, k), jnp.bool_).at[sources, k_idx].set(True)
        dist0 = (
            jnp.full((vp, k), INT32_MAX, jnp.int32).at[sources, k_idx].set(0)
        )
        return frontier0, frontier0, dist0

    def distances(self, sources, *, max_levels: int | None = None):
        sources = jnp.asarray(np.asarray(sources, dtype=np.int32))
        frontier0, visited0, dist0 = self._init_state(sources)
        ml = jnp.int32(max_levels if max_levels is not None else self.dg.vp)
        return _msbfs_core(
            self.edges, frontier0, visited0, dist0, ml, backend=self.backend
        )

    def run(
        self,
        sources,
        *,
        with_parents: bool = False,
        time_it: bool = False,
        max_levels: int | None = None,
    ) -> MsBfsResult:
        sources = np.asarray(sources, dtype=np.int32)
        if sources.ndim != 1 or len(sources) == 0:
            raise ValueError("sources must be a non-empty 1D array")
        if sources.min() < 0 or sources.max() >= self.dg.num_vertices:
            raise ValueError("source out of range")
        elapsed = None
        if time_it:
            k = len(sources)
            (dist_dev, _), elapsed = run_timed(
                lambda: self.distances(sources, max_levels=max_levels),
                warm=k not in self._warmed_k,
            )
            self._warmed_k.add(k)
        else:
            dist_dev, _ = self.distances(sources, max_levels=max_levels)

        parent = None
        if with_parents:
            parent_dev = _msbfs_parents(
                self.src, self.dst, dist_dev, jnp.asarray(sources)
            )
            parent = np.asarray(parent_dev)[: self.dg.num_vertices].T
        dist = np.asarray(dist_dev)[: self.dg.num_vertices].T
        return MsBfsResult(
            sources=sources, distance=dist, parent=parent, elapsed_s=elapsed
        )
