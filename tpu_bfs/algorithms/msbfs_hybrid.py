"""Hybrid dense-MXU + sparse-gather wide multi-source BFS.

The wide engine (msbfs_wide.py) pays ~13 ns of random-gather tax per edge
slot, every level, for every edge. But on a degree-sorted power-law graph the
edge mass is bimodal: measured on RMAT scale-21, 128x128 adjacency tiles
holding >= 64 edges cover ~57% of all edges in ~2% of the occupied tiles.
This engine splits the graph once at build time:

- **dense part**: tiles with >= ``tile_thr`` edges (trimmed to an HBM
  budget; tiles are bit-packed at 2 KB each), expanded per level by the
  Pallas MXU kernel
  (tpu_bfs/ops/tile_spmm.py) at ~0.5 us/tile — replacing ~128 x 13 ns of
  gather tax per tile;
- **residual part**: everything else, expanded by the same bucketed-ELL
  fori-loop gathers as the wide engine.

Row space is "rank0" order (active vertices first, by descending full
in-degree; isolated vertices get no row at all) padded to VT*128 rows
so the dense kernel's frontier DMAs are contiguous slabs. The residual ELL
buckets rows by *residual* degree, so its outputs come out in a different
(bucket) order; one static permutation gather per level routes them back to
rank0 before the claim. Everything else — packed claim ``& ~visited``,
bit-sliced distance planes, device-side stats, lazy extraction — is the
shared machinery in _packed_common.py.

Batch entries map to (word, bit) coordinates word-major, exactly like the
wide engine — tile_spmm's internal bit-major unpack/pack preserves every
(word, bit) position end-to-end, so the kernel imposes no constraint on how
entries are assigned to lanes.

Reference mapping: this is the capability of the reference's whole kernel
layer (queueBfs, bfs.cu:134-165; multiBfs, bfs.cu:101-130) re-planned around
the TPU's MXU/VPU split instead of CUDA thread divergence. Measured flagship:
45.3 GTEPS harmonic-mean per-source on RMAT scale-21 (37.0 at scale 22 with
auto-traded planes), 1 v5e chip — see BENCHMARKS.md.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import (
    EllBucket,
    bucketize_rows,
    gate_forward_map,
    pad_gate_blocks,
    rank_vertices,
)
from tpu_bfs.algorithms._packed_common import (
    AotProgramProtocol,
    ExpandSpec,
    PullGateHost,
    advance_packed_batch,
    auto_lanes,
    auto_planes,
    PackedRunProtocol,
    build_push_table,
    expand_arrays,
    finish_packed_batch,
    floor_lanes,
    make_adaptive_hit,
    make_expand,
    make_gated_expand,
    make_packed_loop,
    make_state_kernels,
    pallas_expand_arrays,
    validate_expand_impl,
    packed_analysis_programs,
    packed_aot_programs,
    row_unsettled,
    seed_scatter_args,
    start_packed_batch,
    tpu_padded_words,
)
from tpu_bfs.ops.tile_spmm import AW, TILE, tile_spmm

W = 128
LANES = 32 * W
# The dense kernel needs w to be a MULTIPLE of 128 (Mosaic: the frontier
# slab's minor dim must be 128-aligned), so wider batches come in steps of
# 4096 lanes up to MAX_LANES.
MAX_LANES = 4 * LANES
# Default width cap: 8192 lanes (w=256), decided by the round-4 v5e sweep —
# RMAT scale-21 flagship measured 45.68 GTEPS hmean at 4096 lanes vs 55.96
# at 8192 (1.22x: the per-index gather cost stays near-flat past 128-word
# rows, so the wider batch amortizes the same index traffic over 2x the
# sources). A 16384-lane request auto-settled back at 8192 on the 16 GB
# chip (state doesn't fit), so 2*LANES is also the widest width that
# actually materializes there. Auto sizing still walks DOWN from the cap
# whenever the packed state doesn't fit next to the tiles.
DEFAULT_MAX_LANES = 2 * LANES


class LanesDontFitError(ValueError):
    """The graph's packed state cannot fit the 4096 lanes the dense kernel
    requires; callers fall back to the gather-only wide engine."""


@dataclasses.dataclass(frozen=True)
class HybridGraph:
    """Build-time split of a graph into dense MXU tiles + residual ELL.

    Rank0 space: row r of the frontier table is vertex ``old_of_new[r]``;
    rows [V, VT*128) are zero padding (the ELL pad sentinel is VT*128-1).
    Residual bucket space: output row p of the residual expansion is rank0
    row ``r_order[p]``; ``inv_perm_ext`` routes rank0 row -> bucket output
    row (pad/empty rows -> the appended all-zero row).
    """

    num_vertices: int
    num_edges: int
    undirected: bool
    kcap: int
    num_active: int  # non-isolated vertices; ranks >= num_active have no row
    vt: int  # frontier slabs of 128 rows; table height = vt * 128
    old_of_new: np.ndarray  # [V] int32
    rank: np.ndarray  # [V] int32
    in_degree: np.ndarray  # [V] int64, original ids
    # dense part
    num_dense_edges: int  # directed slots routed to tiles (duplicates collapse)
    row_start: np.ndarray  # [vt+1] int32 CSR over row-tiles
    col_tile: np.ndarray  # [NT] int32
    a_tiles: np.ndarray  # [NT, AW, TILE] u32 bit-packed, rows-in-bits (tile_spmm layout)
    # residual part (build_ell-style buckets over residual degree)
    res_heavy: int
    res_num_virtual: int
    res_fold_steps: int
    res_virtual: EllBucket | None
    res_fold_pad_map: np.ndarray | None
    res_heavy_pick: np.ndarray | None
    res_light: list[EllBucket]
    res_tail_rows: int  # zero rows appended after buckets (incl. the map target)
    inv_perm_ext: np.ndarray  # [vt*128] int32 rank0 row -> bucket output row

    # expand_arrays protocol
    @property
    def virtual(self):
        return self.res_virtual

    @property
    def fold_pad_map(self):
        return self.res_fold_pad_map

    @property
    def heavy_pick(self):
        return self.res_heavy_pick

    @property
    def light(self):
        return self.res_light

    @property
    def num_tiles(self) -> int:
        return len(self.col_tile)



def select_dense_tiles(r, c, vt, *, tile_thr: int, a_budget_bytes: int):
    """Pick dense 128x128 tiles over rank-space endpoints (r = dst rank,
    c = src rank): tiles holding >= tile_thr edges, trimmed to the bit-packed
    storage budget (2 KB/tile) by descending edge count.

    Returns (dense_edge mask [E], dense_uniq sorted tile ids, tid per edge).
    Shared by the single-chip and distributed hybrid builders.
    """
    max_tiles = max(a_budget_bytes // (TILE * AW * 4), 0)

    def select(counts):
        eligible = np.flatnonzero(counts >= max(tile_thr, 1))
        if len(eligible) > max_tiles:
            order = eligible[
                np.argsort(-counts[eligible], kind="stable")
            ][:max_tiles]
            eligible = np.sort(order)
        return eligible

    if vt * vt <= 3 * 10**8:
        # Dense tile-count histogram: one bincount over int32 tile ids beats
        # np.unique's 67M-element sort by ~20s at scale 21. The vt*vt count
        # array (~2 GiB at scale 21) only exists on host during the build.
        tid = (r // TILE).astype(np.int32) * np.int32(vt) + (
            c // TILE
        ).astype(np.int32)
        eligible = select(np.bincount(tid, minlength=vt * vt))
        dense_tile_mask = np.zeros(vt * vt, dtype=bool)
        dense_tile_mask[eligible] = True
        dense_edge = dense_tile_mask[tid]
        dense_uniq = eligible.astype(np.int64)
    else:
        # Graph500-scale vertex counts: vt*vt is too large to histogram.
        tid = (r.astype(np.int64) // TILE) * vt + (c.astype(np.int64) // TILE)
        uniq, inv, cnt = np.unique(tid, return_inverse=True, return_counts=True)
        eligible = select(cnt)
        is_dense_tile = np.zeros(len(uniq), dtype=bool)
        is_dense_tile[eligible] = True
        dense_edge = is_dense_tile[inv]
        dense_uniq = uniq[eligible]
    return dense_edge, dense_uniq, tid


def fill_a_tiles(dense_edge, dense_uniq, tid, r, c):
    """Bit-packed tiles, rows-in-bits (tile_spmm layout): A[row, col] at
    [t, row % AW, col] bit row // AW — 2 KB/tile instead of 16 KB dense int8.
    Bits OR via sort + reduceat (np.bitwise_or.at is ~40x slower at
    Graph500 scale)."""
    nt = len(dense_uniq)
    a_tiles = np.zeros((max(nt, 1), AW, TILE), dtype=np.uint32)
    if nt:
        de = np.flatnonzero(dense_edge)
        slot = np.searchsorted(dense_uniq, tid[de])
        rin = (r[de] % TILE).astype(np.int64)
        flat = slot * (AW * TILE) + (rin % AW) * TILE + c[de] % TILE
        comb = (flat << np.int64(5)) | (rin // AW)
        comb.sort()
        vals = np.uint32(1) << (comb & 31).astype(np.uint32)
        f2 = comb >> np.int64(5)
        starts = np.flatnonzero(np.r_[True, np.diff(f2) != 0])
        a_tiles.reshape(-1)[f2[starts]] = np.bitwise_or.reduceat(vals, starts)
    return a_tiles


def build_hybrid(
    g: Graph,
    *,
    kcap: int = 64,
    tile_thr: int = 64,
    a_budget_bytes: int = int(0.2e9),
) -> HybridGraph:
    """Split ``g`` into dense 128x128 tiles (>= tile_thr edges, trimmed to the
    bit-packed storage budget of 2 KB/tile by descending edge count) and a
    residual ELL. Defaults (thr=64, ~98k-tile budget) are the measured v5e
    optimum on RMAT scale-21: marginal tiles below ~64 edges cost more in
    kernel time (~2.3 us measured marginal, incl. DMA + grid effects) than
    their edges cost as gathers."""
    v = g.num_vertices
    src, dst = g.coo
    in_deg, num_active, rank_order, rank = rank_vertices(src, dst, v)

    # Table height covers only active (non-isolated) rows + the sentinel:
    # on RMAT graphs ~40% of vertices are isolated, and every [rows, w]
    # state table was paying for them. All edge endpoints rank < num_active
    # by construction, so tiles and residual gathers are unaffected.
    vt = -(-(num_active + 1) // TILE)
    r = rank[dst]  # int32 rank ids
    c = rank[src]
    dense_edge, dense_uniq, tid = select_dense_tiles(
        r, c, vt, tile_thr=tile_thr, a_budget_bytes=a_budget_bytes
    )

    # --- dense arrays (dense_uniq sorted: row-tile-major then col-tile) ---
    nt = len(dense_uniq)
    row_tiles = (dense_uniq // vt).astype(np.int64)
    col_tile = (dense_uniq % vt).astype(np.int32)
    row_start = np.searchsorted(row_tiles, np.arange(vt + 1)).astype(np.int32)
    a_tiles = fill_a_tiles(dense_edge, dense_uniq, tid, r, c)

    # --- residual ELL, bucketed by residual in-degree, targets in rank0 ids ---
    re_mask = ~dense_edge
    res_dst_rank = r[re_mask]
    res_src_rank = c[re_mask].astype(np.int32)
    res_deg_rank = np.bincount(res_dst_rank, minlength=v).astype(np.int64)

    r_order = np.argsort(-res_deg_rank, kind="stable").astype(np.int64)
    bucket_pos = np.empty(v, dtype=np.int64)
    bucket_pos[r_order] = np.arange(v)

    # Flatten residual in-neighbors grouped by destination row, in r_order —
    # native O(E) counting sort when built, np.lexsort otherwise (the minor
    # src key additionally makes within-row neighbor order deterministic).
    from tpu_bfs.graph.csr import _lexsort_pairs

    order_e = _lexsort_pairs(bucket_pos[res_dst_rank], res_src_rank, v)
    nbrs = res_src_rank[order_e]  # rank0-space sources, grouped by bucket row
    lens = res_deg_rank[r_order]
    new_rp = np.zeros(v + 1, dtype=np.int64)
    np.cumsum(lens, out=new_rp[1:])

    sentinel = vt * TILE - 1
    (
        num_heavy, num_nonzero, num_virtual, fold_steps,
        virtual, fold_pad_map, heavy_pick, light,
    ) = bucketize_rows(lens, nbrs, new_rp, kcap, sentinel)

    # Bucket outputs cover rows 0..num_nonzero in r_order; rows with zero
    # residual degree and pad rows all map to the appended zero row.
    inv_perm_ext = np.full(vt * TILE, num_nonzero, dtype=np.int32)
    real = r_order[:num_nonzero]
    inv_perm_ext[real] = np.arange(num_nonzero, dtype=np.int32)

    return HybridGraph(
        num_vertices=v,
        num_edges=g.num_edges,
        undirected=g.undirected,
        kcap=kcap,
        num_active=num_active,
        vt=vt,
        old_of_new=rank_order,
        rank=rank,
        in_degree=in_deg,
        num_dense_edges=int(dense_edge.sum()),
        row_start=row_start,
        col_tile=col_tile,
        a_tiles=a_tiles if nt else a_tiles[:0],
        res_heavy=num_heavy,
        res_num_virtual=num_virtual,
        res_fold_steps=fold_steps,
        res_virtual=virtual,
        res_fold_pad_map=fold_pad_map,
        res_heavy_pick=heavy_pick,
        res_light=light,
        res_tail_rows=1,  # one shared all-zero output row
        inv_perm_ext=inv_perm_ext,
    )


def expand_spec(hg: HybridGraph) -> ExpandSpec:
    """Residual-ELL expansion spec of a hybrid graph (shared between the
    engine core and the roofline phase slices, utils/roofline.py — one
    definition so attribution measures exactly what the loop runs)."""
    return ExpandSpec(
        kcap=hg.kcap,
        heavy=hg.res_heavy > 0,
        num_virtual=hg.res_num_virtual,
        fold_steps=hg.res_fold_steps,
        light_meta=tuple((b.k, b.n) for b in hg.res_light),
        tail_rows=hg.res_tail_rows,
    )


def _make_core(hg: HybridGraph, w: int, num_planes: int, interpret: bool,
               push_cfg=None, gate_levels: int = 0,
               expand_impl: str = "xla"):
    has_dense = hg.num_tiles > 0

    def dense_pass(arrs, fw):
        return tile_spmm(
            arrs["row_start"], arrs["col_tile"], arrs["a_tiles"], fw,
            num_row_tiles=hg.vt, w=w, interpret=interpret,
        )

    if gate_levels:
        # Pull gate (ISSUE 1): residual bucket outputs live in r_order, so
        # the per-rank0-row unsettled mask routes through the build-time
        # forward map (gate_forward_map) before keying the gated buckets.
        # The dense MXU pass stays ungated — its tiles are already the
        # compacted hot set, and the Pallas grid takes no dynamic tile
        # list; its hits on settled rows are claim-masked like any other.
        gated_residual = make_gated_expand(
            expand_spec(hg), w, impl=expand_impl, interpret=interpret
        )

        def hit_of(arrs, fw, vis, lane_mask):
            need = row_unsettled(vis, hg.num_active, lane_mask)
            need_ext = jnp.concatenate([need, jnp.zeros((1,), bool)])
            res, skipped = gated_residual(
                arrs, fw, need_ext[arrs["gate_fwd"]]
            )
            hit = res[arrs["inv_perm_ext"]]
            if has_dense:
                hit = hit | dense_pass(arrs, fw)
            return hit, skipped

        return make_packed_loop(
            hit_of, num_planes, gate_levels=gate_levels, act=hg.num_active
        )

    expand_residual = make_expand(
        expand_spec(hg), w, impl=expand_impl, interpret=interpret
    )

    def hit_of(arrs, fw):
        hit = expand_residual(arrs, fw)[arrs["inv_perm_ext"]]
        if has_dense:
            hit = hit | dense_pass(arrs, fw)
        return hit

    if push_cfg is not None:
        # Level-adaptive expansion (experimental): light levels skip BOTH
        # the residual scan and the dense tile pass — see
        # _packed_common.make_adaptive_hit, shared with the wide engine.
        hit_of = make_adaptive_hit(
            hit_of, hg.num_active, w, hg.vt * TILE, push_cfg
        )
    return make_packed_loop(hit_of, num_planes)


class HybridMsBfsEngine(PackedRunProtocol, PullGateHost,
                        AotProgramProtocol):
    """Up to 8192 concurrent BFS sources by default (DEFAULT_MAX_LANES,
    the round-4 measured optimum; ``max_lanes`` moves the cap in 4096-lane
    steps up to MAX_LANES, and auto sizing walks down when the state
    doesn't fit); dense tiles on the MXU, residual on gathers. API mirrors
    WidePackedMsBfsEngine; results are PackedBatchResult.

    ``pull_gate=True`` (default off until chip-measured) keys the residual
    scan and the state passes on the per-row settled mask — late levels
    stop paying the whole-table pull bill; per-level skipped blocks land
    in ``last_gate_level_counts``. Bit-identical to the plain scan; the
    dense MXU pass stays ungated (see _make_core)."""

    def __init__(
        self,
        graph: Graph | HybridGraph,
        *,
        lanes: int | str = "auto",
        kcap: int = 64,
        tile_thr: int = 64,
        a_budget_bytes: int = int(0.2e9),
        num_planes: int | str = "auto",
        interpret: bool | None = None,
        undirected: bool | None = None,
        hbm_budget_bytes: int = int(14.0e9),
        max_lanes: int = DEFAULT_MAX_LANES,
        adaptive_push: tuple[int, int] | None = None,
        pull_gate: bool = False,
        expand_impl: str = "xla",
    ):
        validate_expand_impl(expand_impl)
        self.expand_impl = expand_impl
        if num_planes != "auto" and not (1 <= num_planes <= 8):
            # Validate the explicit case before the minutes-long build.
            raise ValueError("num_planes must be in [1, 8]")
        if pull_gate and adaptive_push is not None:
            # Same rule as the wide engine: both gate the per-level scan,
            # by different keys — measure the pull gate against the plain
            # scan first (ISSUE 1's A/B stage) before composing.
            raise ValueError(
                "pull_gate and adaptive_push cannot combine (yet): pick one"
            )
        if max_lanes % 32 or not (32 <= max_lanes <= MAX_LANES):
            # Same early-validation rule: a bad width cap must fail in
            # seconds, not after the build (and auto_lanes would otherwise
            # happily return an out-of-range width).
            raise ValueError(
                f"max_lanes must be a multiple of 32 in [32, {MAX_LANES}]"
            )
        # Floor once to a reachable width (power-of-two word count — all
        # auto sizing can ever select): a non-pow2 cap would otherwise make
        # auto_planes' full-width check unsatisfiable in EVERY auto branch.
        max_lanes = floor_lanes(max_lanes)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.hg = (
            build_hybrid(
                graph, kcap=kcap, tile_thr=tile_thr, a_budget_bytes=a_budget_bytes
            )
            if isinstance(graph, Graph)
            else graph
        )
        # Host-side edge list for post-loop parent extraction
        # (PackedBatchResult.parents_int32); a prebuilt HybridGraph dropped it.
        self.host_graph = graph if isinstance(graph, Graph) else None
        hg = self.hg
        if adaptive_push is not None and self.host_graph is None:
            raise ValueError(
                "adaptive_push needs the edge list: construct the engine "
                "from a Graph (a prebuilt HybridGraph has dropped it)"
            )
        res_slots = (
            hg.res_virtual.idx.size if hg.res_virtual is not None else 0
        ) + sum(b.idx.size for b in hg.res_light)
        fixed_bytes = hg.a_tiles.nbytes + int(res_slots * 4.4)
        if adaptive_push is not None:
            # The push table is a lane-independent resident, like the ELL;
            # its [act+1, deg_cap] int32 minor dim pads to 128 on TPU
            # (tpu_padded_words — the round-4 LJ OOM billed it at 2.0x).
            fixed_bytes += (
                (hg.num_active + 1)
                * (tpu_padded_words(adaptive_push[1]) * 4 + 1)
            )
        if num_planes == "auto" and lanes == "auto":
            # Trade depth capacity (2**planes levels) for batch width: on a
            # graph one scale step too big for 5 planes at 4096 lanes, 4
            # planes (16 levels — ample for power-law graphs) keeps the
            # dense MXU path instead of falling off to the gather engine.
            # With a raised max_lanes, walk the width ladder DOWN: a wider
            # cap that doesn't fit must degrade to exactly the default
            # 4096-lane sizing, never to a narrower width than the default
            # cap would have chosen (auto_planes only trades planes when
            # the full target width is reachable).
            cand = max_lanes  # already floored to a reachable width above
            while True:
                num_planes = auto_planes(
                    hg.vt * TILE,
                    fixed_bytes=fixed_bytes,
                    hbm_budget_bytes=hbm_budget_bytes,
                    max_lanes=cand,
                )
                lanes = auto_lanes(
                    hg.vt * TILE,
                    num_planes,
                    fixed_bytes=fixed_bytes,
                    hbm_budget_bytes=hbm_budget_bytes,
                    max_lanes=cand,
                )
                if lanes == cand or cand <= LANES:
                    break
                cand //= 2
        elif num_planes == "auto":
            num_planes = auto_planes(
                hg.vt * TILE,
                fixed_bytes=fixed_bytes,
                hbm_budget_bytes=hbm_budget_bytes,
                max_lanes=max_lanes,
            )
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        self.num_planes = num_planes
        self.max_levels_cap = min(1 << num_planes, 254)
        if lanes == "auto":
            lanes = auto_lanes(
                hg.vt * TILE,
                num_planes,
                fixed_bytes=fixed_bytes,
                hbm_budget_bytes=hbm_budget_bytes,
                max_lanes=max_lanes,
            )
        if lanes % 32 or not (32 <= lanes <= MAX_LANES):
            raise ValueError(
                f"lanes must be a multiple of 32 in [32, {MAX_LANES}]"
            )
        if lanes % LANES and not interpret and hg.num_tiles:
            # Mosaic requires the frontier-slab DMA's minor dimension to be
            # 128-aligned, so the dense kernel exists only at w multiples
            # of 128 (4096-lane steps).
            raise LanesDontFitError(
                f"hybrid dense kernel requires a multiple of {LANES} lanes "
                f"(w % 128 == 0); the packed state for this graph only fits "
                f"{lanes} lanes — use WidePackedMsBfsEngine (gather-only, "
                "any width) or shard over more chips (DistWideMsBfsEngine)"
            )
        self.w = lanes // 32
        self.lanes = lanes
        self.interpret = interpret
        if expand_impl == "pallas":
            from tpu_bfs.ops.ell_expand import validate_kernel_width

            # The residual kernel shares the dense kernel's width law
            # (w % 128 on real TPUs) but applies even on tile-free
            # graphs, where the LanesDontFitError check above doesn't.
            validate_kernel_width(
                self.w, interpret, kernel="hybrid expand_impl='pallas'"
            )
        self.adaptive_push = adaptive_push
        self.undirected = hg.undirected if undirected is None else undirected
        arrs = expand_arrays(hg)
        arrs["inv_perm_ext"] = jnp.asarray(hg.inv_perm_ext)
        if hg.num_tiles:
            arrs["row_start"] = jnp.asarray(hg.row_start)
            arrs["col_tile"] = jnp.asarray(hg.col_tile)
            arrs["a_tiles"] = jnp.asarray(hg.a_tiles)
        if adaptive_push is not None:
            pt, inelig = build_push_table(
                self.host_graph, hg.rank, hg.num_active, adaptive_push[1]
            )
            arrs["push_t"] = jnp.asarray(pt)
            arrs["push_inelig"] = jnp.asarray(inelig)
        self._act = hg.num_active
        self._table_rows = hg.vt * TILE
        if expand_impl == "pallas":
            # Kernel-side whole-block index tables for the residual
            # buckets (sentinel = the all-zero pad row vt*TILE-1; the
            # pull-gate branch below rebuilds the light tables
            # identically when both tiers are on).
            for name, tbl in pallas_expand_arrays(
                hg, hg.vt * TILE - 1
            ).items():
                arrs[name] = jnp.asarray(tbl)
        self.pull_gate = pull_gate
        if pull_gate:
            # Gate tables: sentinel-padded whole-block bucket indices (the
            # residual pad row vt*TILE-1 stays all-zero) and the forward
            # routing map bucket-position -> rank0 row (graph/ell.py).
            sentinel = hg.vt * TILE - 1
            for i, b in enumerate(hg.res_light):
                arrs[f"light{i}_gt"] = jnp.asarray(
                    pad_gate_blocks(np.ascontiguousarray(b.idx.T), sentinel)
                )
            num_real = hg.res_heavy + sum(b.n for b in hg.res_light)
            out_height = num_real + hg.res_tail_rows
            arrs["gate_fwd"] = jnp.asarray(
                gate_forward_map(hg.inv_perm_ext, out_height, num_real)
            )
            self._lane_mask_dev = jnp.full(
                (self.w,), 0xFFFFFFFF, jnp.uint32
            )
            (
                self._gate_core_jit, self._gate_core_from_jit,
                self._gate_core_from_donate_jit,
            ) = _make_core(
                hg, self.w, num_planes, interpret,
                gate_levels=self.max_levels_cap, expand_impl=expand_impl,
            )
            self._core = self._gated_core
            self._core_from = self._gated_core_from
            self._core_from_donate = self._gated_core_from_donate
        else:
            self._core, self._core_from, self._core_from_donate = _make_core(
                hg, self.w, num_planes, interpret, adaptive_push,
                expand_impl=expand_impl,
            )
        self.arrs = arrs
        in_deg_ranked = hg.in_degree[hg.old_of_new].astype(np.int32)
        (
            self._seed, self._lane_stats, self._extract_word, self._lane_ecc,
        ) = make_state_kernels(
            hg.num_vertices, hg.vt * TILE, self.w, num_planes,
            active=self._act, in_deg_host=in_deg_ranked,
        )
        self._rank = hg.rank
        self._warmed = False

    @property
    def num_vertices(self) -> int:
        return self.hg.num_vertices

    # Word-major lane map (same as the wide engine): batch entry i at word
    # i // 32, bit i % 32 — so 32 consecutive entries share one extraction.
    @staticmethod
    def _word_col(i: int):
        return i // 32, i % 32

    @staticmethod
    def _lane_order(mat: np.ndarray) -> np.ndarray:
        return mat.reshape(-1)

    def _iso_of(self, sources: np.ndarray):
        return self.hg.rank[sources] >= self._act

    def _seed_dev(self, sources: np.ndarray):
        return self._seed(*seed_scatter_args(self.hg.rank[sources], self._act))

    def _full_parent_ell(self):
        """Structure for the batched parent scan (parent_scan.py). The
        residual ELL alone cannot derive parents — dense-tile edges are
        missing from it — so build a full in-neighbor ELL lazily from the
        retained host graph (same rank_vertices row space by construction).
        Owned tables — released after the export."""
        from tpu_bfs.algorithms._packed_common import lazy_full_parent_ell

        return lazy_full_parent_ell(self.host_graph, self.hg.kcap)

    # run/dispatch/fetch come from PackedRunProtocol (_packed_common).

    def export_programs(self):
        """AOT inventory (ISSUE 9; utils/aot.py): the shared packed
        serving set — the MXU level-loop core (gated form carries the
        lane-mask arg), seed, lane stats, word extraction, lane ecc."""
        return packed_aot_programs(self)

    def analysis_programs(self):
        """Static-analyzer inventory (tpu_bfs/analysis): the level-loop
        core with REAL example args, under the engine's ACTUAL
        residual-expansion tier, so a pallas-tier core exposes its
        ``pallas_call`` body to the jaxpr walks and compiled audits
        (ISSUE 16)."""
        return packed_analysis_programs(self)

    # --- checkpoint/resume (_packed_common; SURVEY.md §5: reference has none) ---

    def start(self, sources):
        """Level-0 packed batch state as a host checkpoint (real-id rows)."""
        return start_packed_batch(self, sources)

    def advance(self, ckpt, levels: int | None = None):
        """Run at most ``levels`` more levels; bit-identical to no stop."""
        return advance_packed_batch(self, ckpt, levels)

    def finish(self, ckpt):
        """Package a (finished or partial) checkpoint as a batch result."""
        return finish_packed_batch(self, ckpt)
