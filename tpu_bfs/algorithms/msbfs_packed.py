"""Bit-packed multi-source BFS — the TPU flagship engine.

Measured on TPU v5e: a random gather costs ~8 ns *per index* no matter how
little it fetches, while fetching a whole 32-byte row at each index costs the
same (dense ops run 30-60x faster per byte). So the one thing this engine
never does is spend a gather on a single frontier bit: the frontier is a
[V, W] uint32 table — 32*W sources bit-packed per vertex — and every gather
in the level loop retrieves one *row* (32*W lanes at once), amortizing the
per-index tax to ~0.03 ns per (edge, source).

This replaces the reference's one-BFS-at-a-time driver loop (main,
bfs.cu:783-823, one source per process run) with the Graph500 usage pattern
(64 search keys per run) executed as one fused device program:

- expansion: bucketed ELL column gathers + dense OR-fold pyramid
  (tpu_bfs/graph/ell.py) — no atomics (queueBfs's atomicMin/atomicAdd,
  bfs.cu:146-150, have no TPU analog), no scatters, no dynamic shapes;
- visited/claim: ``next = hit & ~visited`` on packed words — the race-free
  reformulation of the atomicMin claim protocol;
- per-lane distances: bit-sliced counters (8 uint32 planes) incremented by
  ripple-carry on the still-unvisited mask each level — dist stays packed in
  the loop and is unpacked once at the end;
- termination: ``any(next != 0)`` inside ``lax.while_loop`` — the device-side
  analog of the host-side queueSize sum (bfs.cu:569) and MPI_Allreduce
  (bfs_mpi.cu:621), with zero host round-trips per level.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.graph.ell import EllGraph, build_ell
from tpu_bfs.utils.aot import AotProgramProtocol

UNREACHED = np.uint8(255)  # uint8 sentinel; convert with distances_int32()
MAX_LEVELS = 254  # bit-sliced counters are 8 planes wide


@dataclasses.dataclass
class _PackedPending:
    """An in-flight packed batch (dispatch/fetch split — see
    _packed_common.PackedDispatch for the serving-pipeline rationale)."""

    sources: np.ndarray
    src_bits: object  # seed table minus the sentinel row (device)
    planes: tuple
    vis: object
    levels: object  # device scalar; int() blocks
    t0: float


@dataclasses.dataclass
class PackedBfsResult:
    sources: np.ndarray  # [S] int32
    num_levels: int  # joint level count (max over sources)
    reached: np.ndarray  # [S] int64
    edges_traversed: np.ndarray  # [S] int64 (Graph500 TEPS numerator per source)
    elapsed_s: float | None = None  # wall time for the whole batch
    # [S] int32 per-lane eccentricity, reduced on device (ISSUE 3): levels
    # and reached are answerable without any distance transfer.
    ecc: np.ndarray | None = None
    # Host edge list for parents_int32; None when built from a prebuilt ELL.
    _graph: object = None
    # Engine backref for the device parent scan (parent_scan.py) and the
    # lazy distance materialization; None on results deserialized without
    # one (host path still works off a materialized _dist_u8).
    _engine: object = None
    # Bit-sliced device state (planes, vis, src_bits) the distance table
    # materializes from on first access — distance-free consumers (the
    # serve path's want_distances=false) never pay the O(V * lanes)
    # device->host transfer.
    _dist_state: tuple | None = None
    _dist_u8: np.ndarray | None = None  # materialized [S, V] cache
    _parent_cache: dict = dataclasses.field(default_factory=dict)

    @property
    def distance_u8(self) -> np.ndarray:
        """[S, V] uint8 distances, UNREACHED where not reached. Lazily
        unpacked from the bit-sliced device state on first access; the
        device state is released once the host copy exists (reached/ecc/
        edges were already reduced at fetch), so a retained result stops
        pinning ~(planes + 2) [act, w] tables in device memory."""
        if self._dist_u8 is None:
            if self._dist_state is None or self._engine is None:
                raise ValueError(
                    "distances were not materialized and no engine is "
                    "attached to unpack them"
                )
            self._dist_u8 = self._engine._materialize_distances(
                self.sources, *self._dist_state
            )
            self._dist_state = None
        return self._dist_u8

    @property
    def teps(self) -> float | None:
        """Harmonic-mean per-source TEPS: each source's TEPS under the batch
        time share (total time / S per source)."""
        if not self.elapsed_s:
            return None
        per_source_time = self.elapsed_s / len(self.sources)
        t = self.edges_traversed / per_source_time
        return float(len(t) / np.sum(1.0 / np.maximum(t, 1e-9)))

    def distances_int32(self, s: int) -> np.ndarray:
        """Distance row for batch entry s, INF_DIST where unreached."""
        d = self.distance_u8[s].astype(np.int32)
        return np.where(self.distance_u8[s] == UNREACHED, INF_DIST, d)

    def parents_int32(self, s: int) -> np.ndarray:
        """BFS tree of batch entry s: [V] int32 deterministic min-parents
        (source maps to itself, unreached to NO_PARENT). One O(E)
        scatter-min per requested lane, cached — see
        PackedBatchResult.parents_int32 (_packed_common.py) for the
        protocol rationale vs the reference's unvalidatable atomic-race
        parent (bfs.cu:146-147, 940)."""
        if not (0 <= s < len(self.sources)):
            raise IndexError(s)
        if s not in self._parent_cache:
            from tpu_bfs.algorithms._packed_common import min_parents_lane

            self._parent_cache[s] = min_parents_lane(
                self._graph, int(self.sources[s]), self.distances_int32(s)
            )
        return self._parent_cache[s]

    def parents_into(self, out: np.ndarray, *, device: str = "auto") -> np.ndarray:
        """Fill ``out[s]`` with every lane's parent tree.

        Same contract as PackedBatchResult.parents_into: ``auto`` runs the
        batched device min-key scan when available (this engine's own ELL
        tables are borrowed — zero extra HBM — so the scan also serves
        prebuilt-ELL results the host path cannot), falling back to the
        per-lane host scatter-min; ``host``/``device`` force a path."""
        n = len(self.sources)
        v = self.distance_u8.shape[1]
        if out.shape != (n, v):
            raise ValueError(f"out is {out.shape}, need ({n}, {v})")
        from tpu_bfs.algorithms._packed_common import (
            acquire_parent_scanner,
            parents_scan_with_fallback,
        )

        def host() -> np.ndarray:
            for s in range(n):
                out[s] = self.parents_int32(s)
                self._parent_cache.pop(s, None)
            return out

        host_serves = self._graph is not None
        # Same loud-fallback gate as PackedBatchResult.parents_into: above
        # ~1e5 lanes x vertices the host path stops being interactive.
        work_desc = (
            f"{n} lanes x {v} vertices" if n * v > 100_000 else None
        )
        scanner = acquire_parent_scanner(
            self._engine, device, host_serves=host_serves,
            work_desc=work_desc,
        )
        if scanner is None:
            return host()
        return parents_scan_with_fallback(
            lambda: self._parents_into_scan(out, scanner),
            host,
            device,
            host_serves=host_serves,
            work_desc=work_desc,
        )

    def _parents_into_scan(self, out: np.ndarray, scanner) -> np.ndarray:
        n = len(self.sources)
        ell = scanner.ell
        act = ell.num_active
        ids = ell.old_of_new[:act]
        # Distances are already materialized host-side in old-id order;
        # transpose the active rows into scanner row space per pass.
        dist_rank = np.ascontiguousarray(self.distance_u8[:, ids].T)
        L = scanner.lanes_per_pass
        for c0 in range(0, n, L):
            cols = dist_rank[:, c0 : c0 + L]
            real = cols.shape[1]
            if real < L:
                cols = np.concatenate(
                    [cols, np.full((act, L - real), UNREACHED, np.uint8)],
                    axis=1,
                )
            pc = np.asarray(scanner.scan(jnp.asarray(cols)))
            for j in range(real):
                row = out[c0 + j]
                row.fill(-1)
                row[ids] = pc[:, j]
                # Sources always map to themselves — including isolated
                # sources, which have no scanner row at all.
                src = int(self.sources[c0 + j])
                row[src] = src
        return out


def make_packed_expand(
    *, w: int, kcap: int, fold_steps: int, num_virtual: int,
    light_meta: list[tuple[int, int]], heavy: bool, tail_rows: int,
):
    """Build the bucketed-ELL expansion: frontier table ``fw`` [rows+1, w] ->
    OR of the frontier words of each row's in-neighbors.

    Shared by the single-chip engine (rows = V) and each chip of the
    distributed engine (rows = its v_loc owned rows); ``light_meta`` is a list
    of (k, n) bucket shapes, ``tail_rows`` the appended all-zero rows.
    """

    def expand(arrs, fw):
        parts = []
        if heavy:
            vr_t = arrs["virtual_t"]  # [kcap, M]
            acc = jnp.zeros((num_virtual, w), jnp.uint32)
            for k in range(kcap):
                acc = acc | fw[vr_t[k]]
            vr_ext = jnp.concatenate([acc, jnp.zeros((1, w), jnp.uint32)])
            cur = vr_ext[arrs["fold_pad_map"]]
            pyramid = [cur]  # level 0: the padded layout itself
            for _ in range(fold_steps):
                pairs = cur.reshape(-1, 2, w)
                cur = pairs[:, 0] | pairs[:, 1]
                pyramid.append(cur)
            pyr = jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
            parts.append(pyr[arrs["heavy_pick"]])
        for i, (k, n) in enumerate(light_meta):
            bt = arrs[f"light{i}_t"]  # [k, n]
            acc = jnp.zeros((n, w), jnp.uint32)
            for kk in range(k):
                acc = acc | fw[bt[kk]]
            parts.append(acc)
        if tail_rows:
            parts.append(jnp.zeros((tail_rows, w), jnp.uint32))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return expand


def ripple_increment(planes, carry_bits):
    """Bit-sliced ripple-carry: planes + 1 wherever carry_bits is set."""
    new_planes = []
    for p in planes:
        new_planes.append(p ^ carry_bits)
        carry_bits = p & carry_bits
    return tuple(new_planes)


def _make_core(ell: EllGraph, w: int):
    """Build the jitted level loop for one ELL structure; arrays are passed as
    a pytree so they live on device once and never get baked into the HLO."""
    # Tables cover active rows only; isolated vertices (rank >= num_active)
    # have no row — the engine patches their lanes host-side.
    act = ell.num_active
    expand = make_packed_expand(
        w=w,
        kcap=ell.kcap,
        fold_steps=ell.fold_steps,
        num_virtual=ell.num_virtual,
        light_meta=[(b.k, b.n) for b in ell.light],
        heavy=ell.num_heavy > 0,
        tail_rows=act - ell.num_nonzero,
    )

    @jax.jit
    def core(arrs, fw0, vis0, max_levels):
        planes0 = tuple(jnp.zeros((act, w), jnp.uint32) for _ in range(8))

        def cond(carry):
            _, _, _, level, alive = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _ = carry
            hit = expand(arrs, fw)
            nxt = hit & ~vis
            vis2 = vis | nxt
            # Increment the per-lane level counter wherever the lane is still
            # unvisited after this level.
            planes = ripple_increment(planes, ~vis2)
            fw_next = jnp.concatenate([nxt, jnp.zeros((1, w), jnp.uint32)])
            alive = jnp.any(nxt != 0)
            return fw_next, vis2, planes, level + 1, alive

        fw_f, vis_f, planes_f, levels, _ = jax.lax.while_loop(
            cond, body, (fw0, vis0, planes0, jnp.int32(0), jnp.bool_(True))
        )
        return planes_f, vis_f, levels

    @jax.jit
    def extract(planes, vis, src_bits):
        """Unpack bit-sliced counters to per-lane uint8 distances [act, 32w]."""
        shifts = jnp.arange(32, dtype=jnp.uint32)
        cols = []
        for wi in range(w):
            cnt = jnp.zeros((act, 32), jnp.uint8)
            for i, p in enumerate(planes):
                bit = ((p[:, wi, None] >> shifts) & 1).astype(jnp.uint8)
                cnt = cnt + (bit << i)
            visw = ((vis[:, wi, None] >> shifts) & 1) != 0
            srcw = ((src_bits[:, wi, None] >> shifts) & 1) != 0
            dist_w = jnp.where(
                srcw,
                jnp.uint8(0),
                jnp.where(visw, cnt + jnp.uint8(1), UNREACHED),
            )
            cols.append(dist_w)
        return jnp.concatenate(cols, axis=1)

    return core, extract


class PackedMsBfsEngine(AotProgramProtocol):
    """Runs up to ``lanes`` BFS sources concurrently, bit-packed.

    ``lanes`` must be a multiple of 32; 256 (w=8 words) is the measured
    sweet spot on v5e — wider rows gather no faster, narrower waste lanes.
    """

    def export_programs(self):
        # AOT inventory (ISSUE 9; utils/aot.py): custom rather than the
        # shared packed_aot_programs (this engine's ``_seed`` is a
        # host-numpy pass, not a compiled program — deliberately absent).
        import jax

        act = self.ell.num_active
        u32 = jnp.uint32
        fw_s = jax.ShapeDtypeStruct((act + 1, self.w), u32)
        vis_s = jax.ShapeDtypeStruct((act, self.w), u32)
        arrs_s = {
            k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
            for k, v in self.arrs.items()
        }
        planes_s = (vis_s,) * 8
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        return [
            ("core", "_core", self._core, (arrs_s, fw_s, vis_s, i32)),
            ("extract", "_extract", self._extract,
             (planes_s, vis_s, vis_s)),
            ("lane_stats", "_lane_stats", self._lane_stats, (vis_s,)),
            ("lane_ecc", "_lane_ecc", self._lane_ecc,
             (planes_s, vis_s, vis_s)),
        ]

    def __init__(
        self,
        graph: Graph | EllGraph,
        *,
        lanes: int = 256,
        kcap: int = 64,
        undirected: bool | None = None,
    ):
        if lanes % 32:
            raise ValueError("lanes must be a multiple of 32")
        self.w = lanes // 32
        self.lanes = lanes
        if isinstance(graph, Graph):
            self.ell = build_ell(graph, kcap=kcap)
        else:
            self.ell = graph
        # Host-side edge list for post-loop parent extraction
        # (PackedBfsResult.parents_int32); a prebuilt ELL has dropped it.
        self.host_graph = graph if isinstance(graph, Graph) else None
        self.undirected = self.ell.undirected if undirected is None else undirected
        ell = self.ell
        arrs = {}
        if ell.num_heavy:
            arrs["virtual_t"] = jnp.asarray(np.ascontiguousarray(ell.virtual.idx.T))
            arrs["fold_pad_map"] = jnp.asarray(ell.fold_pad_map)
            arrs["heavy_pick"] = jnp.asarray(ell.heavy_pick)
        for i, b in enumerate(ell.light):
            arrs[f"light{i}_t"] = jnp.asarray(np.ascontiguousarray(b.idx.T))
        self.arrs = arrs
        self._core, self._extract = _make_core(ell, self.w)
        # Shared per-lane device reductions (reached / degree sum / ecc) —
        # the same state kernels the wide/hybrid engines use; lazy import
        # because _packed_common imports this module at its top.
        from tpu_bfs.algorithms._packed_common import make_state_kernels

        act = self.ell.num_active
        _, self._lane_stats, _, self._lane_ecc = make_state_kernels(
            self.ell.num_vertices, act, self.w, 8, active=act,
            in_deg_host=self.ell.in_degree[self.ell.old_of_new].astype(
                np.int32
            ),
        )
        # Depth cap of the 8-plane bit-sliced counters; the parent scan's
        # key encoding sizes its distance field from this.
        self.max_levels_cap = MAX_LEVELS
        self._warmed = False

    def _full_parent_ell(self):
        """Full-coverage ELL + device arrays for the batched parent scan
        (parent_scan.py) — this engine's own, borrowed for free."""
        return self.ell, self.arrs

    @property
    def num_vertices(self) -> int:
        return self.ell.num_vertices

    def _seed(self, sources: np.ndarray):
        act = self.ell.num_active
        fw0 = np.zeros((act + 1, self.w), np.uint32)
        ranks = self.ell.rank[sources]
        for i, r in enumerate(ranks):
            if r < act:  # isolated sources have no row; patched in run()
                fw0[r, i // 32] |= np.uint32(1 << (i % 32))
        return fw0

    def dispatch(self, sources, *, max_levels: int = MAX_LEVELS):
        """Launch one packed batch without blocking on it (JAX dispatch is
        async) — the serve pipeline's entry; ``fetch`` is the blocking
        half. Returns an opaque pending handle."""
        sources = np.asarray(sources, dtype=np.int64)
        if sources.ndim != 1 or len(sources) == 0 or len(sources) > self.lanes:
            raise ValueError(f"need 1..{self.lanes} sources, got {sources.shape}")
        if sources.min() < 0 or sources.max() >= self.ell.num_vertices:
            raise ValueError("source out of range")
        max_levels = min(max_levels, MAX_LEVELS)
        fw0 = jnp.asarray(self._seed(sources))
        vis0 = fw0[:-1]
        t0 = time.perf_counter()
        planes, vis, levels = self._core(
            self.arrs, fw0, vis0, jnp.int32(max_levels)
        )
        return _PackedPending(
            sources=sources, src_bits=vis0, planes=planes, vis=vis,
            levels=levels, t0=t0,
        )

    def fetch(self, pend, *, time_it: bool = False) -> PackedBfsResult:
        """Block on a :meth:`dispatch` handle and assemble its result.

        ``reached``/``ecc``/``edges_traversed`` reduce on device
        (lane_stats / lane_ecc); the distance table stays bit-sliced on
        device and unpacks lazily on first ``distance_u8`` access, so
        distance-free consumers never pay the O(V * lanes) transfer."""
        int(pend.levels)  # blocks until the loop finishes
        elapsed = (time.perf_counter() - pend.t0) if time_it else None
        self._warmed = True

        sources = pend.sources
        s = len(sources)
        act = self.ell.num_active
        r, d = self._lane_stats(pend.vis)
        e = self._lane_ecc(pend.planes, pend.vis, pend.src_bits)
        reached = np.asarray(r).reshape(-1)[:s].astype(np.int64)
        ecc = np.asarray(e).reshape(-1)[:s].astype(np.int32)
        slot_sum = (
            np.asarray(d).astype(np.int64).sum(axis=1).reshape(-1)[:s]
        )
        edges = slot_sum // 2 if self.undirected else slot_sum
        # Isolated sources were never seeded; their component is {source}.
        iso = np.flatnonzero(self.ell.rank[sources] >= act)
        reached[iso], ecc[iso], edges[iso] = 1, 0, 0
        return PackedBfsResult(
            sources=sources.astype(np.int32),
            # Max eccentricity over lanes, not loop iterations (which
            # include the final empty-frontier step) — BfsEngine semantics.
            num_levels=int(ecc.max()) if s else 0,
            reached=reached,
            edges_traversed=edges.astype(np.int64),
            elapsed_s=elapsed,
            ecc=ecc,
            _graph=self.host_graph,
            _engine=self,
            _dist_state=(pend.planes, pend.vis, pend.src_bits),
        )

    def run(
        self,
        sources,
        *,
        max_levels: int = MAX_LEVELS,
        time_it: bool = False,
    ) -> PackedBfsResult:
        if time_it and not self._warmed:
            int(self.dispatch(sources, max_levels=max_levels).levels)
        return self.fetch(
            self.dispatch(sources, max_levels=max_levels), time_it=time_it
        )

    def _materialize_distances(self, sources, planes, vis, src_bits):
        """[S, V] uint8 distance table in old-id order — the one full
        unpack + transfer, deferred until someone asks for distances."""
        dn = np.asarray(self._extract(planes, vis, src_bits))  # rank space
        s = len(sources)
        act = self.ell.num_active
        v = self.ell.num_vertices
        ranks = self.ell.rank
        if act < v:
            full = np.full((v, dn.shape[1]), UNREACHED, np.uint8)
            m = ranks < act
            full[m] = dn[ranks[m]]
        else:
            full = dn[ranks]
        dist = np.ascontiguousarray(full[:, :s].T)  # [S, V], old ids
        # Isolated sources were never seeded; their component is {source}.
        for i in np.flatnonzero(ranks[sources] >= act):
            dist[i, sources[i]] = 0
        return dist
