"""Wide bit-packed multi-source BFS: 4096 lanes per traversal batch.

Why a second packed engine: measured on TPU v5e, a chained random row-gather
(gather + OR, the level-loop's inner op) costs ~13 ns/index at row widths of
64 or 128 uint32 words, ~19 ns at 16 words, and ~30 ns at 32 words — the
per-index cost is set by tile padding (every [n, w<128] uint32 intermediate is
physically padded to 128 lanes), not by the bytes fetched. 128-word rows
(4096 bit-lanes) are therefore the native shape: the gather tax is amortized
over 8x more sources than the 512-lane engine for the same index count.

Differences from PackedMsBfsEngine (tpu_bfs/algorithms/msbfs_packed.py):

- Bucket OR-accumulation runs in ``lax.fori_loop`` instead of an unrolled
  Python loop, so only one gather result is live at a time (the unrolled form
  kept ~20 padded [n, w] intermediates alive and OOM'd at w >= 64).
- The frontier table keeps its sentinel row inside the loop state ([V+1, w]
  throughout), removing the reference-style per-level re-upload analog — the
  1 GiB/level concatenate copy XLA emitted for the old shape dance.
- Bit-sliced distance counters are ``num_planes`` wide (default 5 -> max 32
  levels) instead of a fixed 8, saving 3 GiB of HBM at w=128; the engine
  raises if the traversal outlives the cap instead of mislabeling.
- Per-lane reached / traversed-edge counts reduce on device; distances unpack
  lazily one 32-lane word at a time (a [V, 4096] uint8 materialization would
  be 8 GiB of traffic before any host transfer).

Replaces the reference's one-source-per-process loop (main, bfs.cu:783-823)
with the Graph500 many-key pattern in one fused device program; claim protocol
is ``next = hit & ~visited`` on packed words — the race-free reformulation of
the atomicMin claim (bfs.cu:146-150), which has no TPU analog.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.graph.ell import EllGraph, build_ell
from tpu_bfs.algorithms.msbfs_packed import UNREACHED, ripple_increment

W = 128  # uint32 words per row: the measured v5e sweet spot (no tile padding)
LANES = 32 * W


def make_wide_expand(ell: EllGraph, w: int):
    """Bucketed-ELL expansion with fori-loop OR accumulation.

    fw is the [V+1, w] frontier table (sentinel last row all-zero, targeted by
    ELL padding slots); returns the [V+1, w] hit table (sentinel row zero).
    """
    v = ell.num_vertices
    tail_rows = v - ell.num_nonzero + 1  # zero-degree rows + sentinel row

    def expand(arrs, fw):
        parts = []
        if ell.num_heavy:
            vr_t = arrs["virtual_t"]  # [kcap, M]

            def vbody(kk, acc):
                return acc | fw[vr_t[kk]]

            acc = jax.lax.fori_loop(
                0, ell.kcap, vbody,
                jnp.zeros((ell.num_virtual, w), jnp.uint32),
            )
            vr_ext = jnp.concatenate([acc, jnp.zeros((1, w), jnp.uint32)])
            cur = vr_ext[arrs["fold_pad_map"]]
            pyramid = [cur]
            for _ in range(ell.fold_steps):
                pairs = cur.reshape(-1, 2, w)
                cur = pairs[:, 0] | pairs[:, 1]
                pyramid.append(cur)
            pyr = jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
            parts.append(pyr[arrs["heavy_pick"]])
        for i, b in enumerate(ell.light):
            bt = arrs[f"light{i}_t"]  # [k, n]

            def lbody(kk, acc, bt=bt):
                return acc | fw[bt[kk]]

            acc = jax.lax.fori_loop(
                0, b.k, lbody, jnp.zeros((b.n, w), jnp.uint32)
            )
            parts.append(acc)
        parts.append(jnp.zeros((tail_rows, w), jnp.uint32))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return expand


def _make_core(ell: EllGraph, w: int, num_planes: int):
    v = ell.num_vertices
    expand = make_wide_expand(ell, w)

    @jax.jit
    def core(arrs, fw0, max_levels):
        # fw0 [v+1, w]: frontier bits; sentinel row v is all-zero and is never
        # written (expand emits zero there, and `& ~vis` keeps it zero).
        planes0 = tuple(jnp.zeros((v + 1, w), jnp.uint32) for _ in range(num_planes))

        def cond(carry):
            _, _, _, level, alive = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _ = carry
            hit = expand(arrs, fw)
            nxt = hit & ~vis
            vis2 = vis | nxt
            # Sentinel row counts up harmlessly (never visited, sliced off).
            planes = ripple_increment(planes, ~vis2)
            alive = jnp.any(nxt != 0)
            return nxt, vis2, planes, level + 1, alive

        fw_f, vis_f, planes_f, levels, alive = jax.lax.while_loop(
            cond, body, (fw0, fw0, planes0, jnp.int32(0), jnp.bool_(True))
        )
        # `alive` only says the last body claimed something. When the loop
        # exits at the cap, distances <= max_levels are all labeled correctly;
        # the traversal is incomplete only if one MORE level would claim
        # vertices. Decide that with a single claim-free expand, so a
        # traversal whose eccentricity lands exactly on the cap does not
        # falsely report truncation.
        def deeper():
            return jnp.any((expand(arrs, fw_f) & ~vis_f) != 0)

        truncated = jax.lax.cond(
            alive & (levels >= max_levels), deeper, lambda: jnp.bool_(False)
        )
        return planes_f, vis_f, levels, alive, truncated

    @jax.jit
    def seed(rows, words, bits):
        # Distinct lanes own distinct (word, bit) pairs, so scatter-add == OR.
        fw0 = jnp.zeros((v + 1, w), jnp.uint32)
        return fw0.at[rows, words].add(bits)

    @jax.jit
    def lane_stats(vis, in_deg):
        """Per-lane reached count and degree sum, on device.

        vis [v+1, w] u32; in_deg [v] f32 (rank order). Returns
        (reached [w,32] i32 exact, deg_sum [w,32] f32 — f32 because TPU has no
        int64 and the per-lane degree sum can exceed int32 at Graph500 scale;
        pairwise summation keeps the TEPS numerator accurate to ~7 digits)."""
        shifts = jnp.arange(32, dtype=jnp.uint32)

        def wbody(wi, acc):
            r_acc, d_acc = acc
            col = jax.lax.dynamic_slice(vis, (0, wi), (v + 1, 1))[:v]  # [v,1]
            bits = (col >> shifts) & 1  # [v, 32] u32
            r = jnp.sum(bits.astype(jnp.int32), axis=0)
            d = jnp.sum(bits.astype(jnp.float32) * in_deg[:, None], axis=0)
            return (
                jax.lax.dynamic_update_slice(r_acc, r[None], (wi, 0)),
                jax.lax.dynamic_update_slice(d_acc, d[None], (wi, 0)),
            )

        r0 = jnp.zeros((w, 32), jnp.int32)
        d0 = jnp.zeros((w, 32), jnp.float32)
        return jax.lax.fori_loop(0, w, wbody, (r0, d0))

    @jax.jit
    def extract_word(planes, vis, src_bits, wi):
        """Distances of lanes [32*wi, 32*wi+32) as [v, 32] uint8."""
        shifts = jnp.arange(32, dtype=jnp.uint32)
        cnt = jnp.zeros((v, 32), jnp.uint8)
        for i, p in enumerate(planes):
            col = jax.lax.dynamic_slice(p, (0, wi), (v + 1, 1))[:v]
            bit = ((col >> shifts) & 1).astype(jnp.uint8)
            cnt = cnt + (bit << i)
        visw = ((jax.lax.dynamic_slice(vis, (0, wi), (v + 1, 1))[:v] >> shifts) & 1) != 0
        srcw = ((jax.lax.dynamic_slice(src_bits, (0, wi), (v + 1, 1))[:v] >> shifts) & 1) != 0
        return jnp.where(
            srcw, jnp.uint8(0), jnp.where(visw, cnt + jnp.uint8(1), UNREACHED)
        )

    return core, seed, lane_stats, extract_word


@dataclasses.dataclass
class WideBfsResult:
    """Batch result with lazy per-lane distance extraction.

    Distances stay bit-sliced on device; ``distances_int32(i)`` unpacks the
    one 32-lane word containing lane i (then caches it), so querying a few
    lanes never materializes the full [S, V] array.
    """

    sources: np.ndarray  # [S] int32
    num_levels: int  # max distance over all lanes
    reached: np.ndarray  # [S] int64
    edges_traversed: np.ndarray  # [S] int64
    elapsed_s: float | None
    _engine: "WidePackedMsBfsEngine"
    _planes: tuple
    _vis: jax.Array
    _src_bits: jax.Array
    _word_cache: dict = dataclasses.field(default_factory=dict)

    @property
    def teps(self) -> float | None:
        if not self.elapsed_s:
            return None
        per_source_time = self.elapsed_s / len(self.sources)
        t = self.edges_traversed / per_source_time
        return float(len(t) / np.sum(1.0 / np.maximum(t, 1e-9)))

    def distance_u8_lane(self, i: int) -> np.ndarray:
        """[V] uint8 distances of batch entry i (UNREACHED where not reached)."""
        if not (0 <= i < len(self.sources)):
            raise IndexError(i)
        wi = i // 32
        if wi not in self._word_cache:
            eng = self._engine
            dr = eng._extract_word(self._planes, self._vis, self._src_bits, wi)
            self._word_cache[wi] = np.asarray(dr)[eng.ell.rank]  # old-id order
        return self._word_cache[wi][:, i % 32]

    def distances_int32(self, i: int) -> np.ndarray:
        d8 = self.distance_u8_lane(i)
        return np.where(d8 == UNREACHED, INF_DIST, d8.astype(np.int32))


class WidePackedMsBfsEngine:
    """Runs up to 4096 BFS sources concurrently, bit-packed 128 words wide.

    ``num_planes`` bit-sliced counter planes bound the level count at
    ``2**num_planes``; the default 5 (32 levels) fits scale-21+ RMAT and
    social graphs in HBM at w=128. ``run`` raises if the traversal is still
    alive at the cap (pass more planes for high-diameter graphs — or use the
    512-lane PackedMsBfsEngine, whose 8 planes reach 254 levels).
    """

    def __init__(
        self,
        graph: Graph | EllGraph,
        *,
        kcap: int = 64,
        num_planes: int = 5,
        undirected: bool | None = None,
    ):
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        self.w = W
        self.lanes = LANES
        self.num_planes = num_planes
        # A vertex claimed in body i carries counter value i (incremented once
        # per body while unvisited) and distance i+1, so p planes label
        # distances up to 2**p; 254 keeps every distance below UNREACHED=255.
        self.max_levels_cap = min(1 << num_planes, 254)
        self.ell = build_ell(graph, kcap=kcap) if isinstance(graph, Graph) else graph
        self.undirected = self.ell.undirected if undirected is None else undirected
        ell = self.ell
        arrs = {}
        if ell.num_heavy:
            arrs["virtual_t"] = jnp.asarray(np.ascontiguousarray(ell.virtual.idx.T))
            arrs["fold_pad_map"] = jnp.asarray(ell.fold_pad_map)
            arrs["heavy_pick"] = jnp.asarray(ell.heavy_pick)
        for i, b in enumerate(ell.light):
            arrs[f"light{i}_t"] = jnp.asarray(np.ascontiguousarray(b.idx.T))
        self.arrs = arrs
        self._core, self._seed, self._lane_stats, self._extract_word = _make_core(
            ell, self.w, num_planes
        )
        self._in_deg_ranked = jnp.asarray(
            ell.in_degree[ell.old_of_new].astype(np.float32)
        )
        self._warmed = False

    @property
    def num_vertices(self) -> int:
        return self.ell.num_vertices

    def _seed_dev(self, sources: np.ndarray):
        ranks = self.ell.rank[sources].astype(np.int32)
        lanes = np.arange(len(sources), dtype=np.int32)
        words = lanes // 32
        bits = np.uint32(1) << (lanes % 32).astype(np.uint32)
        return self._seed(
            jnp.asarray(ranks), jnp.asarray(words), jnp.asarray(bits)
        )

    def run(
        self,
        sources,
        *,
        max_levels: int | None = None,
        time_it: bool = False,
        check_cap: bool = True,
    ) -> WideBfsResult:
        sources = np.asarray(sources, dtype=np.int64)
        if sources.ndim != 1 or len(sources) == 0 or len(sources) > self.lanes:
            raise ValueError(f"need 1..{self.lanes} sources, got {sources.shape}")
        if sources.min() < 0 or sources.max() >= self.ell.num_vertices:
            raise ValueError("source out of range")
        cap = self.max_levels_cap
        max_levels = cap if max_levels is None else min(max_levels, cap)

        fw0 = self._seed_dev(sources)
        if time_it and not self._warmed:
            int(self._core(self.arrs, fw0, jnp.int32(max_levels))[2])
        t0 = time.perf_counter()
        planes, vis, levels, alive, truncated = self._core(
            self.arrs, fw0, jnp.int32(max_levels)
        )
        levels = int(levels)  # blocks until the loop finishes
        elapsed = (time.perf_counter() - t0) if time_it else None
        self._warmed = True
        if check_cap and bool(truncated) and max_levels == cap:
            raise RuntimeError(
                f"traversal truncated at {levels} levels; "
                f"num_planes={self.num_planes} caps at {cap} — construct the "
                "engine with more planes for this graph"
            )

        s = len(sources)
        r, d = self._lane_stats(vis, self._in_deg_ranked)
        reached = np.asarray(r).reshape(-1)[:s].astype(np.int64)
        slot_sum = np.asarray(d, dtype=np.float64).reshape(-1)[:s]
        edges = (slot_sum / 2 if self.undirected else slot_sum).astype(np.int64)

        res = WideBfsResult(
            sources=sources.astype(np.int32),
            num_levels=levels,
            reached=reached,
            edges_traversed=edges,
            elapsed_s=elapsed,
            _engine=self,
            _planes=planes,
            _vis=vis,
            _src_bits=fw0,
        )
        # Report the true max eccentricity over lanes, not loop iterations:
        # the distance histogram of one lane is cheap; take max over sampled
        # lanes only when asked — loop count minus 1 is exact when the last
        # body found an empty frontier.
        if levels > 0 and not bool(alive):
            res.num_levels = levels - 1
        return res
