"""Wide bit-packed multi-source BFS: thousands of lanes per traversal
batch (default cap 8192 lanes = 256-word rows since the round-4 sweep).

Why a second packed engine: measured on TPU v5e, a chained random row-gather
(gather + OR, the level-loop's inner op) is latency-dominated — narrow rows
pay physical tile padding (every [n, w<128] uint32 intermediate is padded to
128 lanes: ~19 ns/index at 16 words, ~30 at 32), while widening past 128
words costs only ~1.2x per doubling (fence-corrected round-4 sweep: 14.5 /
16.5 / 19.7 / 26.8 ns/index at 64 / 128 / 256 / 512 words). Wide rows are
therefore the native shape: the same index traffic is amortized over up to
32x more sources than the 512-lane engine, and each width doubling buys
~1.67x more lane-bytes per second until HBM stops fitting the state.

Differences from PackedMsBfsEngine (tpu_bfs/algorithms/msbfs_packed.py):

- Bucket OR-accumulation runs in ``lax.fori_loop`` (one live gather result
  instead of ~20 padded intermediates — see _packed_common.make_fori_expand).
- The frontier table keeps its sentinel row inside the loop state ([V+1, w]
  throughout), removing the 1 GiB/level concatenate copy XLA emitted for the
  old shape dance.
- Bit-sliced distance counters are ``num_planes`` wide (default 5 -> max 32
  levels) instead of a fixed 8, saving 3 GiB of HBM at w=128; the engine
  raises if the traversal outlives the cap instead of mislabeling.
- Per-lane reached / traversed-edge counts reduce on device; distances unpack
  lazily one 32-lane word at a time (a [V, 4096] uint8 materialization would
  be 8 GiB of traffic before any host transfer).

Replaces the reference's one-source-per-process loop (main, bfs.cu:783-823)
with the Graph500 many-key pattern in one fused device program; claim protocol
is ``next = hit & ~visited`` on packed words — the race-free reformulation of
the atomicMin claim (bfs.cu:146-150), which has no TPU analog.

Lane convention: word-major — lane ``l`` at word ``l // 32``, bit ``l % 32``.
(The hybrid engine is bit-major instead, as its MXU kernel requires.)

Opt-in ``adaptive_push=(row_cap, deg_cap)`` gates light levels onto a
push-style pass over just the active rows' out-edges instead of the full
ELL scan (_packed_common.make_adaptive_hit; BENCHMARKS.md "Level-adaptive
expansion" for the measured keep-or-kill).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import EllGraph, build_ell, pad_gate_blocks
from tpu_bfs.algorithms._packed_common import (
    AotProgramProtocol,
    ExpandSpec,
    PackedRunProtocol,
    advance_packed_batch,
    auto_lanes,
    build_push_table,
    expand_arrays,
    finish_packed_batch,
    PullGateHost,
    make_adaptive_hit,
    make_expand,
    make_gated_expand,
    make_packed_loop,
    pallas_expand_arrays,
    validate_expand_impl,
    make_state_kernels,
    packed_analysis_programs,
    packed_aot_programs,
    row_unsettled,
    seed_scatter_args,
    start_packed_batch,
    tpu_padded_words,
)

W = 128  # uint32 words per row (narrower rows pay physical tile padding)
LANES = 32 * W
# Wider rows are legal (any multiple of 32 lanes up to MAX_LANES; the shared
# machinery in _packed_common is width-generic).
MAX_LANES = 4 * LANES
# Default width cap: 8192 lanes (w=256) — the round-4 v5e sweep measured the
# per-index gather cost near-flat from 128- to 256-word rows, and the hybrid
# flagship gained 1.22x (45.68 -> 55.96 GTEPS hmean) from the doubled batch.
# Auto sizing walks down from the cap whenever the packed state doesn't fit
# HBM (msbfs_hybrid.py has the full measurement note).
DEFAULT_MAX_LANES = 2 * LANES

# Re-exported for callers that consumed these from here before the
# _packed_common refactor.
from tpu_bfs.algorithms._packed_common import PackedBatchResult as WideBfsResult  # noqa: E402


def _make_core(ell: EllGraph, w: int, num_planes: int, push_cfg=None,
               gate_levels: int = 0, expand_impl: str = "xla",
               interpret: bool = False, overlay: bool = False):
    act = ell.num_active
    spec = ExpandSpec(
        kcap=ell.kcap,
        heavy=ell.num_heavy > 0,
        num_virtual=ell.num_virtual,
        fold_steps=ell.fold_steps,
        light_meta=tuple((b.k, b.n) for b in ell.light),
        # Zero-in-degree active rows + sentinel row. Isolated vertices get
        # no row at all (rank space is active-first, graph/ell.py).
        tail_rows=act - ell.num_nonzero + 1,
    )
    if gate_levels:
        # Pull gate (ISSUE 1): bucket outputs are table rows in order here
        # (no permutation), so the per-row unsettled mask IS the per-
        # bucket-output-row needed vector, no forward map required.
        gated_expand = make_gated_expand(
            spec, w, impl=expand_impl, interpret=interpret
        )

        def hit_of(arrs, fw, vis, lane_mask):
            need = row_unsettled(vis, act, lane_mask)
            return gated_expand(arrs, fw, need)

        return make_packed_loop(
            hit_of, num_planes, gate_levels=gate_levels, act=act
        )
    # fw is [act+1, w]: frontier bits; sentinel row act is all-zero and is
    # never written (expand emits zero there, and `& ~vis` keeps it zero).
    expand = make_expand(spec, w, impl=expand_impl, interpret=interpret)
    if overlay:
        # Dynamic-graph delta overlay (ISSUE 19): fold the bounded
        # mutation tables over the base expansion output — a jnp
        # epilogue outside either expansion tier's kernel, so xla and
        # pallas engines share one fold and one compiled-shape contract.
        from tpu_bfs.graph.dynamic import make_overlay_fold

        expand = make_overlay_fold(expand, op="or")
    if push_cfg is None:
        return make_packed_loop(expand, num_planes)
    # Level-adaptive expansion (experimental): see
    # _packed_common.make_adaptive_hit — the gate/push machinery is shared
    # with the hybrid engine.
    return make_packed_loop(
        make_adaptive_hit(expand, act, w, act + 1, push_cfg), num_planes
    )


class WidePackedMsBfsEngine(PackedRunProtocol, PullGateHost,
                            AotProgramProtocol):
    """Runs up to 4096 BFS sources concurrently, bit-packed 128 words wide.

    ``num_planes`` bit-sliced counter planes bound the level count at
    ``2**num_planes``; the default 5 (32 levels) fits scale-21+ RMAT and
    social graphs in HBM at w=128. ``run`` raises if the traversal is still
    alive at the cap (pass more planes for high-diameter graphs — or use the
    512-lane PackedMsBfsEngine, whose 8 planes reach 254 levels).

    ``pull_gate=True`` (default off until chip-measured) turns on the
    frontier-aware pull gate: settled rows' bucket blocks and state tiles
    are skipped per level (_packed_common.make_gated_fori_expand /
    gated_state_update), bit-identical to the plain scan; per-level skipped
    blocks land in ``last_gate_level_counts``.
    """

    def __init__(
        self,
        graph: Graph | EllGraph,
        *,
        lanes: int | str = "auto",
        kcap: int = 64,
        num_planes: int = 5,
        undirected: bool | None = None,
        hbm_budget_bytes: int = int(14.0e9),
        max_lanes: int = DEFAULT_MAX_LANES,
        adaptive_push: tuple[int, int] | None = None,
        pull_gate: bool = False,
        expand_impl: str = "xla",
        interpret: bool | None = None,
        overlay: tuple = (),
    ):
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        validate_expand_impl(expand_impl)
        self.overlay = tuple(int(x) for x in overlay) if overlay else ()
        if self.overlay and (pull_gate or adaptive_push is not None):
            # Both gate which rows/blocks the per-level scan touches by
            # BASE-graph keys; overlay edges would escape the gate and
            # silently go untraversed. The delta overlay serves the
            # plain scan only (ISSUE 19).
            raise ValueError(
                "overlay does not compose with pull_gate or adaptive_push"
            )
        if interpret is None:
            # Same resolution as the hybrid engine's tile kernel: emulate
            # the Pallas tier off-TPU so CPU tests drive the real kernel.
            interpret = jax.default_backend() != "tpu"
        self.expand_impl = expand_impl
        self._interpret = bool(interpret)
        if pull_gate and adaptive_push is not None:
            # Both gate the same per-level scan, by different keys (settled
            # destinations vs light frontiers); composing them is a
            # measurement question, not a wiring one — measure the pull
            # gate against the plain scan first (ISSUE 1's A/B stage).
            raise ValueError(
                "pull_gate and adaptive_push cannot combine (yet): pick one"
            )
        if max_lanes % 32 or not (32 <= max_lanes <= MAX_LANES):
            # Fail before the ELL build, like the num_planes check above.
            raise ValueError(
                f"max_lanes must be a multiple of 32 in [32, {MAX_LANES}]"
            )
        self.num_planes = num_planes
        # A vertex claimed in body i carries counter value i (incremented once
        # per body while unvisited) and distance i+1, so p planes label
        # distances up to 2**p; 254 keeps every distance below UNREACHED=255.
        self.max_levels_cap = min(1 << num_planes, 254)
        self.ell = build_ell(graph, kcap=kcap) if isinstance(graph, Graph) else graph
        # Host-side edge list for post-loop parent extraction
        # (PackedBatchResult.parents_int32); a prebuilt ELL has dropped it.
        self.host_graph = graph if isinstance(graph, Graph) else None
        self._act = self.ell.num_active
        if lanes == "auto":
            # Halve from max_lanes until the packed state fits HBM next to
            # the ELL (and the push table, when the adaptive path is on —
            # its [act+1, deg_cap] int32 rows are lane-independent
            # residents just like the ELL indices). The push table's minor
            # dim pads to 128 on TPU like every 2-D 32-bit table
            # (tpu_padded_words; the round-4 LJ OOM report billed the
            # s32[act, 64] table at 2.0x its logical bytes).
            push_bytes = (
                (self._act + 1) * (tpu_padded_words(adaptive_push[1]) * 4 + 1)
                if adaptive_push is not None
                else 0
            )
            # on_unfit='raise': when even the 32-lane floor's PHYSICAL
            # footprint exceeds the budget, fail here with the real levers
            # named instead of minutes later in an opaque runtime
            # RESOURCE_EXHAUSTED (ADVICE r4).
            lanes = auto_lanes(
                self._act + 1,
                num_planes,
                fixed_bytes=int(self.ell.total_slots * 4.4) + push_bytes,
                hbm_budget_bytes=hbm_budget_bytes,
                max_lanes=max_lanes,
                on_unfit="raise",
            )
        if lanes % 32 or not (32 <= lanes <= MAX_LANES):
            raise ValueError(
                f"lanes must be a multiple of 32 in [32, {MAX_LANES}]"
            )
        self.w = lanes // 32
        self.lanes = lanes
        self.undirected = self.ell.undirected if undirected is None else undirected
        ell = self.ell
        self.arrs = expand_arrays(ell)
        if expand_impl == "pallas":
            from tpu_bfs.ops.ell_expand import validate_kernel_width

            # Fail at build with the legal widths named, not at first
            # dispatch inside Mosaic lowering.
            validate_kernel_width(
                self.w, self._interpret, kernel="wide expand_impl='pallas'"
            )
            # Sentinel-padded whole-block tables the kernel DMAs (shared
            # layout with the pull gate's light tables; sentinel = the
            # all-zero row act).
            for name, tbl in pallas_expand_arrays(ell, self._act).items():
                self.arrs[name] = jnp.asarray(tbl)
        if self.overlay:
            # Arm the fold with all-pad tables (every row scatters the
            # combine identity into the sentinel row): the overlay keys
            # are part of the arrs pytree from the FIRST compile, so a
            # later mutation swaps values without a retrace.
            from tpu_bfs.graph.dynamic import empty_overlay_tables

            for name, tbl in empty_overlay_tables(
                self.overlay, self._act
            ).items():
                self.arrs[name] = jnp.asarray(tbl)
        if adaptive_push is not None:
            self._build_push_table(adaptive_push)
        self._table_rows = self._act + 1  # + the all-zero sentinel row
        self.pull_gate = pull_gate
        if pull_gate:
            # Sentinel-padded whole-block bucket tables for the gated
            # expansion (graph/ell.pad_gate_blocks; sentinel = the all-zero
            # row act, the buckets' own pad convention).
            for i, b in enumerate(ell.light):
                self.arrs[f"light{i}_gt"] = jnp.asarray(
                    pad_gate_blocks(
                        np.ascontiguousarray(b.idx.T), self._act
                    )
                )
            self._lane_mask_dev = jnp.full(
                (self.w,), 0xFFFFFFFF, jnp.uint32
            )
            (
                self._gate_core_jit, self._gate_core_from_jit,
                self._gate_core_from_donate_jit,
            ) = _make_core(
                ell, self.w, num_planes, gate_levels=self.max_levels_cap,
                expand_impl=expand_impl, interpret=self._interpret,
            )
            self._core = self._gated_core
            self._core_from = self._gated_core_from
            self._core_from_donate = self._gated_core_from_donate
        else:
            self._core, self._core_from, self._core_from_donate = _make_core(
                ell, self.w, num_planes, adaptive_push,
                expand_impl=expand_impl, interpret=self._interpret,
                overlay=bool(self.overlay),
            )
        in_deg_ranked = ell.in_degree[ell.old_of_new].astype(np.int32)
        (
            self._seed, self._lane_stats, self._extract_word, self._lane_ecc,
        ) = make_state_kernels(
            ell.num_vertices, self._act + 1, self.w, num_planes,
            active=self._act, in_deg_host=in_deg_ranked,
        )
        self._rank = ell.rank
        self._warmed = False

    def _build_push_table(self, push_cfg):
        """Device push arrays for the adaptive light-level path (the
        shared build_push_table); needs the retained host edge list."""
        if self.host_graph is None:
            raise ValueError(
                "adaptive_push needs the edge list: construct the engine "
                "from a Graph (a prebuilt ELL has dropped it)"
            )
        pt, inelig = build_push_table(
            self.host_graph, self.ell.rank, self._act, push_cfg[1]
        )
        self.arrs["push_t"] = jnp.asarray(pt)
        self.arrs["push_inelig"] = jnp.asarray(inelig)

    def set_overlay(self, tables) -> None:
        """Swap the delta-overlay tables under the already-compiled core
        (ISSUE 19): shapes must match the armed capacity (the compiled
        pytree is fixed — a shape change would be a silent retrace), and
        the swap is one atomic dict rebind so a concurrently-running
        batch sees either the old tables or the new, never a mix."""
        if not self.overlay:
            raise ValueError(
                "engine built without an overlay — pass overlay=(rows, "
                "kcap) at construction to serve a dynamic graph"
            )
        rows, kcap = self.overlay
        new = {}
        for name in ("ov_rows", "ov_idx", "ov_override"):
            arr = np.asarray(tables[name], np.int32)
            want = (rows, kcap) if name == "ov_idx" else (rows,)
            if arr.shape != want:
                raise ValueError(
                    f"{name} shape {arr.shape} != armed capacity {want}"
                )
            new[name] = jnp.asarray(arr)
        self.arrs = {**self.arrs, **new}

    @property
    def num_vertices(self) -> int:
        return self.ell.num_vertices

    # Word-major lane map: lane l at word l // 32, bit l % 32.
    @staticmethod
    def _word_col(i: int):
        return i // 32, i % 32

    @staticmethod
    def _lane_order(mat: np.ndarray) -> np.ndarray:
        return mat.reshape(-1)

    def _iso_of(self, sources: np.ndarray):
        return self.ell.rank[sources] >= self._act

    def _seed_dev(self, sources: np.ndarray):
        return self._seed(*seed_scatter_args(self.ell.rank[sources], self._act))

    def _full_parent_ell(self):
        """Full-coverage ELL + device arrays for the batched parent scan
        (parent_scan.py): the gather-only engine expands over every edge
        already, so the scan borrows its tables for free — this also makes
        bulk parent extraction work for prebuilt-ELL engines, which the
        host path cannot serve (no retained edge list)."""
        return self.ell, self.arrs

    # run/dispatch/fetch come from PackedRunProtocol (_packed_common).

    def export_programs(self):
        """AOT inventory (ISSUE 9; utils/aot.py): the shared packed
        serving set — level-loop core (gated form carries the lane-mask
        arg), seed, lane stats, lazy word extraction, lane ecc."""
        return packed_aot_programs(self)

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the level-loop core
        with REAL example args, under the engine's ACTUAL expansion tier
        — a pallas engine's core carries the fused ``pallas_call``, so
        the dtype/uniformity jaxpr walks and the compiled audits see
        inside the kernel body (ISSUE 16)."""
        return packed_analysis_programs(self)

    # --- checkpoint/resume (_packed_common; SURVEY.md §5: reference has none) ---

    def start(self, sources):
        """Level-0 packed batch state as a host checkpoint (real-id rows)."""
        return start_packed_batch(self, sources)

    def advance(self, ckpt, levels: int | None = None):
        """Run at most ``levels`` more levels; bit-identical to no stop."""
        return advance_packed_batch(self, ckpt, levels)

    def finish(self, ckpt):
        """Package a (finished or partial) checkpoint as a batch result."""
        return finish_packed_batch(self, ckpt)
