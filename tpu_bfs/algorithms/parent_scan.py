"""Device-side batched BFS-tree extraction for the packed MS-BFS engines.

The packed level loop labels distances only (bit-sliced planes); parent
trees are derived afterwards. The old derivation was one host-side
O(E) ``np.minimum.at`` per lane (~0.5-1 s at scale 21) — fine for sampling
a few lanes, but the full 4096-lane flagship batch cost ~an hour of host
time (VERDICT r3 weak #3). This module moves the whole batch onto the
device as a handful of bucketed-ELL *min*-expansions.

Why one pass with no per-level loop works: along any edge u->v the BFS
relaxation guarantees ``dist(u) >= dist(v) - 1`` (directed in-neighbors
included — BFS relaxes along edge direction), and every reached v with
``dist(v) >= 1`` has at least one in-neighbor at exactly ``dist(v) - 1``.
Therefore the lexicographic minimum over v's in-neighbors of the 32-bit key

    key(u) = (dist(u) << idbits) | orig_id(u)

is attained at a neighbor with the minimum distance ``dist(v) - 1``, and —
among those — the minimum ORIGINAL id: precisely the deterministic
min-parent tree every engine emits (validate.min_parent_from_dist), the
race-free replacement for the reference's nondeterministic atomicMin winner
(bfs.cu:146-147, 940). A min-reduction over in-neighbors is exactly the
shape of the engines' frontier expansion (OR over in-neighbors), so the
scan reuses the same bucketed-ELL machinery (_packed_common.make_fori_expand
with ``jnp.minimum`` over 0xFFFFFFFF) — same gathers, same fold pyramid,
same cost profile as ONE BFS level per 128 lanes.

Decode per (row, lane): valid iff the best key's distance field equals
``dist(v) - 1``; unreached rows and rows whose neighbors are all unreached
fail that check and come out -1. Sources (dist 0) map to themselves; a
level-1 child's min-key neighbor at distance 0 IS the lane's source, so no
special case is needed for it.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpu_bfs.graph.ell import EllGraph
from tpu_bfs.algorithms.msbfs_packed import UNREACHED
from tpu_bfs.algorithms._packed_common import (
    ExpandSpec,
    expand_arrays,
    make_fori_expand,
)

# Lanes decoded per device pass: 32-lane word columns are extracted in
# groups of this many u32 key columns. 128 matches the engines' native
# [.., 128] uint32 tile shape, so each pass costs about one BFS level.
LANES_PER_PASS = 128


class ParentScanUnavailable(ValueError):
    """The key encoding cannot represent this graph (id field too wide for
    the distance field). Callers fall back to the host path."""


class ParentScanner:
    """Batched min-key parent extraction over a full in-neighbor ELL.

    ``ell`` must cover ALL edges (the wide/512-lane engines' own ELL
    qualifies and its device arrays can be shared via ``arrs``; the hybrid
    engine's residual ELL does NOT — build a fresh full ELL for it).
    ``max_dist`` is the largest distance the key must represent exactly
    (the engine's level cap); ids and distances share 32 bits, so huge
    graphs with deep caps can be unrepresentable -> ParentScanUnavailable.
    """

    def __init__(self, ell: EllGraph, *, arrs=None, max_dist: int = 254,
                 lanes_per_pass: int = LANES_PER_PASS):
        act = ell.num_active
        self.ell = ell
        self.lanes_per_pass = lanes_per_pass
        self.idbits = max(int(ell.num_vertices - 1).bit_length(), 1)
        # Distances live in the top (32 - idbits) bits. Anything the field
        # cannot hold (UNREACHED above all) clamps to the field max, which
        # must exceed every REAL distance so clamped garbage never decodes
        # as a valid parent (valid needs du == dv - 1 <= max_dist - 1).
        self.dumax = (1 << (32 - self.idbits)) - 1
        if self.dumax < max_dist + 1:
            raise ParentScanUnavailable(
                f"V={ell.num_vertices} needs {self.idbits} id bits, leaving "
                f"a distance field of at most {self.dumax} < cap {max_dist}+1"
            )
        spec = ExpandSpec(
            kcap=ell.kcap,
            heavy=ell.num_heavy > 0,
            num_virtual=ell.num_virtual,
            fold_steps=ell.fold_steps,
            light_meta=tuple((b.k, b.n) for b in ell.light),
            tail_rows=act - ell.num_nonzero + 1,
        )
        expand_min = make_fori_expand(
            spec, lanes_per_pass, combine=jnp.minimum, identity=0xFFFFFFFF
        )
        # Copy the (possibly borrowed) dict so adding the id array never
        # mutates the engine's own arrs — that would change the pytree
        # structure of the engine's compiled calls. The underlying device
        # buffers are shared either way. The id array rides in arrs as a
        # jit ARGUMENT, not a closure constant: baked-in [act]-sized
        # constants get serialized into the compile request, which the
        # remote compile service rejects at flagship scales (the same
        # constraint bfs_tiled.py documents for its edge/tile arrays).
        self.arrs = dict(expand_arrays(ell) if arrs is None else arrs)
        self.arrs["pscan_ids"] = jnp.asarray(
            ell.old_of_new[:act].astype(np.uint32)
        )
        idbits, dumax = self.idbits, self.dumax
        idmask = jnp.uint32((1 << idbits) - 1)

        @jax.jit
        def scan_pass(arrs, dist_cols):
            """[act, L] u8 distances -> [act, L] int32 original-id parents
            (-1 where none; sources map to themselves)."""
            ids = arrs["pscan_ids"]
            du = jnp.minimum(dist_cols.astype(jnp.uint32), jnp.uint32(dumax))
            keys = (du << idbits) | ids[:, None]
            # Sentinel row `act` (the pad gather target) must be the min
            # identity so padded slots never win.
            keys = jnp.concatenate(
                [keys, jnp.full((1, lanes_per_pass), 0xFFFFFFFF, jnp.uint32)]
            )
            mk = expand_min(arrs, keys)[:act]
            dv = dist_cols.astype(jnp.int32)
            valid = (
                (dv != UNREACHED)
                & ((mk >> idbits).astype(jnp.int32) == dv - 1)
            )
            pid = (mk & idmask).astype(jnp.int32)
            return jnp.where(
                dv == 0,
                ids.astype(jnp.int32)[:, None],
                jnp.where(valid, pid, jnp.int32(-1)),
            )

        self._scan_pass = scan_pass

    def scan(self, dist_cols) -> jax.Array:
        """Run one device pass. ``dist_cols`` is [num_active, lanes_per_pass]
        uint8 (UNREACHED-padded when fewer real columns remain)."""
        return self._scan_pass(self.arrs, dist_cols)
