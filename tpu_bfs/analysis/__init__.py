"""Static verification of the mesh programs and the serve tier (ISSUE 8).

Four passes, one shared HLO/jaxpr walking core (:mod:`tpu_bfs.analysis.hlo`
— refactored out of ``utils/wirecheck.py``, which is now a client), a
``tpu-bfs-analyze`` CLI (``make analyze``), and a baseline-suppression
file so findings gate CI:

- **uniformity** (:mod:`.uniformity`): the PR 7 exchange planner made
  branch choice a per-level runtime decision whose safety rests on an
  invariant nothing previously proved — every rank must select the same
  branch wherever the branches' collective schedules differ, or the mesh
  deadlocks mid-BFS. The pass is a mesh-uniformity taint analysis over
  the traced jaxpr (branch-selection scalars may flow only through
  mesh-uniform lineage: pmax/psum outputs, replicated inputs,
  loop-carried uniform state) plus a compiled-HLO audit that every
  ``conditional``'s arms carry an identical ordered collective signature,
  are collective-free, or were certified uniform by the taint pass.
- **transfer** (:mod:`.transfer`): zero device-to-host round-trips inside
  hot loops — an HLO infeed/outfeed/host-callback scan over every
  compiled level program, a ``jax.transfer_guard`` drive of the warmed
  loops, a jit trace-count sentinel that fails on shape-driven recompiles
  (protects the serve width ladder), and the lazy ``distance_u8``
  contract (fetch materializes nothing until asked).
- **locks** (:mod:`.locks`): an AST lint over ``serve/`` and ``obs/``
  enforcing ``# guarded-by: <lock>`` annotations (annotated attributes
  may only be touched inside the matching ``with`` block) plus a
  cross-module lock-acquisition-order graph that must stay acyclic.
- **dtype** (:mod:`.dtypes`): no f64 / accidental 64-bit widening in any
  compiled hot program.
- **memory** (:mod:`.memory`, ISSUE 13): the static HBM budget — a
  peak-bytes estimate per compiled engine program (jax
  ``memory_analysis()`` where available, HLO buffer walk fallback), an
  analytic ladder model proving modeled peak STRICTLY monotone in rung
  width for every EngineSpec family the serve registry can build (the
  OOM/mesh-degrade ladders provably shrink memory), and a buffer-
  donation lint (undonated loop carries, dead ``donate_argnums=()``)
  with an HLO input-output-alias certificate for applied donations.
- **lifecycle** (:mod:`.lifecycle`, ISSUE 13): path-sensitive
  exception-flow verification over serve/obs/resilience — every span
  ``begin`` reaches an ``end`` on all paths including raises
  (``# span-outlives:`` documents deliberate cross-function ownership),
  every bare lock acquire a release, every ResumeCache put a drop.
- **faultcov** (:mod:`.faultcov`, ISSUE 13): ``faults.SITES`` vs the
  actual consultation call sites (undeclared consults, never-consulted
  declared sites) plus a site x kind coverage map over tests/ and the
  chaos smokes — a new fault site cannot land untested.

Findings are stable-fingerprinted (``pass:where``); the baseline file
(one fingerprint per line, ``#`` comments) suppresses known findings so
the CLI can gate on NEW ones only. A baseline entry matching nothing is
reported as stale — suppressions must not outlive their findings.
``tpu-bfs-analyze --json`` emits the whole report (per-pass findings,
certificates, fingerprints) as machine-readable JSON — the
chip-session pre-flight consumes that instead of scraping exit text.
"""

from __future__ import annotations

import dataclasses

DEFAULT_BASELINE = "analysis-baseline.txt"

#: Pass registry order — also the CLI's execution and report order.
PASSES = (
    "uniformity", "transfer", "locks", "dtype",
    "memory", "lifecycle", "faultcov",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One verified defect: which pass, a stable location key, and an
    actionable message naming the offending module/branch/attribute."""

    pass_name: str  # one of PASSES (plus sub-pass suffixes like
    #                 "uniformity/collective-signature")
    where: str  # stable location key, e.g. "serve/metrics.py:ServeMetrics.completed"
    message: str

    @property
    def fingerprint(self) -> str:
        """The baseline-suppression key: pass + location, message-free so
        rewording a diagnostic does not un-suppress it."""
        return f"{self.pass_name}:{self.where}"

    def render(self) -> str:
        return f"FINDING [{self.pass_name}] {self.where}: {self.message}"


def load_baseline(path: str) -> set[str]:
    """Fingerprints suppressed by the baseline file; a missing file is an
    empty baseline (the common clean-tree case)."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except FileNotFoundError:
        return set()
    out = set()
    for line in lines:
        line = line.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


def apply_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], list[Finding], set[str]]:
    """Split ``findings`` into (new, suppressed) and report the stale
    baseline entries that matched nothing — a suppression whose finding
    was fixed must be deleted, not carried forever."""
    new, suppressed, hit = [], [], set()
    for f in findings:
        if f.fingerprint in baseline:
            suppressed.append(f)
            hit.add(f.fingerprint)
        else:
            new.append(f)
    return new, suppressed, baseline - hit
