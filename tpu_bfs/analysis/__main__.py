"""``python -m tpu_bfs.analysis`` — the tpu-bfs-analyze entry point."""

import sys

from tpu_bfs.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
