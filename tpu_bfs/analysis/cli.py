"""``tpu-bfs-analyze`` — run the static-verification passes and gate on
findings (``make analyze``).

Exit status: 0 when every finding is baseline-suppressed (or none
exist), 1 on new findings, so the target gates CI and the chip-session
pre-flight. The baseline file holds one finding fingerprint per line
(``pass:where``; ``#`` comments); stale entries — suppressions whose
finding no longer exists — are reported so they get deleted, and
``--write-baseline`` rewrites the file from the current findings when a
known issue must be parked rather than fixed.

``--fast`` runs the compile-free subset (the uniformity taint + dtype
walks over the planner programs, the AST lock lint, the donation lint +
ladder budget model, the lifecycle exception-flow walk, and the
fault-coverage audit) — seconds, no XLA compile. The default runs
everything: all engine configs compiled, their HLO conditional/host-op/
dtype audits, the per-program peak-memory estimates and donation-alias
certificates, the transfer-guard drives, and the retrace/lazy-distance
sentinels.

``--json`` writes the whole report to stdout as one JSON object
(``ok``, per-finding pass/where/message/fingerprint, suppressed/stale
lists, per-pass info, and the memory/fault-coverage certificates) — the
chip-session pre-flight consumes this instead of scraping exit text.
Exit status semantics are unchanged.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_bfs.analysis import (
    DEFAULT_BASELINE,
    Finding,
    apply_baseline,
    load_baseline,
)

#: Flagship modeling point for the ladder budget check: the scale-21
#: RMAT shape the perf series runs (ROADMAP "Perf trajectory") — the
#: monotonicity verdict is structural per family, not graph-specific,
#: but the logged byte figures should be read at a real operating point.
MODEL_VERTICES = 1 << 21
MODEL_EDGES = 1 << 25
#: The canonical virtual-mesh width every distributed test/config uses.
MODEL_DEVICES = 8


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def run_locks() -> tuple[list[Finding], dict]:
    from tpu_bfs.analysis.locks import lint_tree, repo_root

    findings, info = lint_tree(repo_root())
    _log(
        f"locks: {info['classes']} classes, {info['guarded_attrs']} "
        f"guarded attrs, {len(info['edges'])} lock-order edges, "
        f"{len(findings)} finding(s)"
    )
    return findings, {
        "classes": info["classes"],
        "guarded_attrs": info["guarded_attrs"],
        "edges": len(info["edges"]),
    }


def run_memory_static() -> tuple[list[Finding], dict]:
    """Pass 5's compile-free half: the donation lint over the engine-core
    modules and the ladder budget model over every registry-buildable
    EngineSpec family."""
    from tpu_bfs.analysis.locks import repo_root
    from tpu_bfs.analysis.memory import (
        check_registry_ladders,
        lint_donation_tree,
    )

    findings, lint_info = lint_donation_tree(repo_root())
    ladder_findings, ladders = check_registry_ladders(
        num_vertices=MODEL_VERTICES, num_edges=MODEL_EDGES,
        device_count=MODEL_DEVICES,
    )
    findings += ladder_findings
    _log(
        f"memory: {lint_info['jit_defs']} jit defs "
        f"({lint_info['donating']} donating, "
        f"{lint_info['carry_style']} carry-style, "
        f"{lint_info['no_donate']} annotated no-donate), "
        f"{len(ladders)} ladder families "
        f"({sum(len(v) for v in ladders.values())} rungs), "
        f"{len(findings)} finding(s)"
    )
    info = dict(lint_info)
    info["ladders"] = {
        fam: [{"lanes": w, "model_bytes": b} for w, b in entries]
        for fam, entries in ladders.items()
    }
    return findings, info


def run_lifecycle() -> tuple[list[Finding], dict]:
    from tpu_bfs.analysis.lifecycle import check_tree
    from tpu_bfs.analysis.locks import repo_root

    findings, info = check_tree(repo_root())
    _log(
        f"lifecycle: {info['functions']} functions walked, "
        f"{info['span_outlives']} annotated span escapes, "
        f"{len(findings)} finding(s)"
    )
    return findings, info


def run_faultcov() -> tuple[list[Finding], dict]:
    from tpu_bfs.analysis.faultcov import check_tree
    from tpu_bfs.analysis.locks import repo_root

    findings, info = check_tree(repo_root())
    _log(
        f"faultcov: {len(info['sites'])} consulted sites, "
        f"{sum(len(v) for v in info['coverage'].values())} covered "
        f"site-kind pairs, {len(findings)} finding(s)"
    )
    info = {
        "sites": info["sites"],
        "coverage": info["coverage"],
    }
    return findings, info


def _ensure_mesh() -> None:
    """The engine sweep needs the 8-virtual-device CPU mesh the tests run
    on (tests/conftest.py does this for pytest; the standalone CLI does
    it here — same bootstrap, shared with __graft_entry__)."""
    from tpu_bfs.utils.virtual_mesh import ensure_virtual_devices

    ensure_virtual_devices(8)


def run_program_passes(
    configs, skip: set, *, compiled: bool
) -> tuple[list[Finding], dict]:
    """One sweep over the engine-program inventory, each engine built and
    traced ONCE: the uniformity taint + dtype walks share the trace, and
    in ``compiled`` mode the same spec is lowered once for the HLO
    conditional/host-op/dtype audits, the peak-memory estimate +
    donation-alias certificate (pass 5's compiled half), and the
    transfer-guard drive. Each check family honors its entry in ``skip``
    — a skipped pass emits no findings (in particular, skipping
    uniformity also skips the HLO conditional audit, which without taint
    certificates would flag the planner's legitimately-differing arms)."""
    import jax

    from tpu_bfs.analysis import dtypes, transfer, uniformity
    from tpu_bfs.analysis.configs import iter_programs
    from tpu_bfs.analysis.hlo import wide_dtype_lines
    from tpu_bfs.analysis.memory import (
        check_program_donation,
        estimate_compiled,
    )

    do_uni = "uniformity" not in skip
    do_dtype = "dtype" not in skip
    do_transfer = compiled and "transfer" not in skip
    do_memory = compiled and "memory" not in skip
    findings: list[Finding] = []
    estimates: list[dict] = []
    for spec in iter_programs(configs):
        closed = jax.make_jaxpr(spec.fn)(*spec.args)
        rep = None
        if do_uni:
            rep = uniformity.analyze_jaxpr(spec.name, closed)
            findings.extend(rep.findings)
            _log(
                f"uniformity[{spec.name}]: {rep.shard_maps} shard_map(s), "
                f"{rep.conds_checked} cond(s), "
                f"{rep.certified_divergent_safe} certified divergent-safe, "
                f"{len(rep.findings)} finding(s)"
            )
        if do_dtype:
            findings.extend(dtypes.check_jaxpr(spec.name, closed))
        if not compiled:
            continue
        compiled_obj = spec.lower_compiled()
        hlo = compiled_obj.as_text()
        cond_f = (
            uniformity.check_hlo_conditionals(spec.name, hlo, rep)
            if do_uni else []
        )
        host_f = (
            transfer.check_hlo_host_ops(spec.name, hlo)
            if do_transfer else []
        )
        dtype_f = [
            Finding(
                "dtype",
                f"{spec.name}:{hit['source'] or hit['computation']}",
                f"compiled program carries a {hit['dtype']} result: "
                f"{hit['line']}",
            )
            for hit in wide_dtype_lines(hlo)
        ] if do_dtype else []
        guard_f = (
            transfer.check_loop_transfer_guard(spec.name, spec.fn, spec.args)
            if do_transfer else []
        )
        mem_f: list[Finding] = []
        if do_memory:
            est = estimate_compiled(spec.name, compiled_obj)
            estimates.append(est)
            mem_f = check_program_donation(spec.name, spec.fn, hlo)
            peak = est.get("peak_bytes")
            _log(
                f"memory[{spec.name}]: peak~"
                f"{peak / 1e6:.2f} MB ({est['source']}"
                f"{', donated' if est.get('donated') else ''})"
                if peak is not None
                else f"memory[{spec.name}]: estimate unavailable"
            )
        findings.extend(cond_f + host_f + dtype_f + guard_f + mem_f)
        _log(
            f"hlo[{spec.name}]: {len(cond_f)} conditional, "
            f"{len(host_f)} host-op, {len(dtype_f)} dtype, "
            f"{len(guard_f)} transfer-guard, {len(mem_f)} donation "
            f"finding(s)"
        )
    return findings, {"program_estimates": estimates}


def run_sentinels() -> list[Finding]:
    from tpu_bfs.analysis import transfer
    from tpu_bfs.analysis.configs import packed_retrace_drive

    eng, drive = packed_retrace_drive()
    findings = transfer.check_engine_retrace("wide-sparse-rows", eng, drive)
    import numpy as np

    sources = np.arange(eng.lanes, dtype=np.int64) % eng.num_vertices
    findings += transfer.check_lazy_distances(
        "wide-sparse-rows", eng, sources
    )
    _log(f"sentinels: retrace+lazy-distance, {len(findings)} finding(s)")
    return findings


def _finding_json(f: Finding) -> dict:
    return {
        "pass": f.pass_name,
        "where": f.where,
        "message": f.message,
        "fingerprint": f.fingerprint,
    }


def main(argv=None) -> int:
    from tpu_bfs.analysis import PASSES

    ap = argparse.ArgumentParser(
        prog="tpu-bfs-analyze",
        description="Static verification of the mesh programs and the "
        "serve tier: collective-uniformity taint + HLO signatures, "
        "transfer/retrace guards, lock-discipline lint, dtype lint, "
        "HBM budget + donation lint, exception-path lifecycle "
        "verification, fault-site coverage audit.",
    )
    ap.add_argument("--fast", action="store_true",
                    help="compile-free subset (no XLA compiles): the "
                    "uniformity/dtype walks over the planner programs, "
                    "the AST lock + donation lints, the ladder budget "
                    "model, the lifecycle walk, and the fault-coverage "
                    "audit — the tier-1 shape")
    ap.add_argument("--configs", default=None, metavar="A,B",
                    help="restrict the engine-config sweep (names from "
                    "tpu_bfs/analysis/configs.py; default: all, or the "
                    "fast subset under --fast)")
    ap.add_argument("--skip", default="", metavar="PASS,..",
                    help=f"skip passes: any of {','.join(PASSES)} "
                    "(skipping uniformity also skips the HLO conditional "
                    "audit, which needs its taint certificates)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"suppression file (default {DEFAULT_BASELINE}; "
                    "missing = empty)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline file from the current "
                    "findings and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="write the full machine-readable report (per-"
                    "pass findings, certificates, fingerprints) to "
                    "stdout as one JSON object; exit status unchanged "
                    "(the chip-session pre-flight consumes this)")
    args = ap.parse_args(argv)

    skip = {tok.strip() for tok in args.skip.split(",") if tok.strip()}
    unknown_skips = skip - set(PASSES)
    if unknown_skips:
        _log(f"unknown pass(es) in --skip: {sorted(unknown_skips)}; "
             f"have: {', '.join(PASSES)}")
        return 2
    if args.fast:
        from tpu_bfs.analysis.configs import FAST_CONFIGS

        configs = FAST_CONFIGS
    else:
        configs = None
    if args.configs:
        from tpu_bfs.analysis.configs import ALL_CONFIGS

        configs = tuple(
            tok.strip() for tok in args.configs.split(",") if tok.strip()
        )
        unknown = [c for c in configs if c not in ALL_CONFIGS]
        if unknown:
            _log(f"unknown config(s) {unknown}; have: "
                 f"{', '.join(ALL_CONFIGS)}")
            return 2

    findings: list[Finding] = []
    pass_info: dict = {}
    if "locks" not in skip:
        lock_f, pass_info["locks"] = run_locks()
        findings += lock_f
    if "memory" not in skip:
        mem_f, pass_info["memory"] = run_memory_static()
        findings += mem_f
    if "lifecycle" not in skip:
        life_f, pass_info["lifecycle"] = run_lifecycle()
        findings += life_f
    if "faultcov" not in skip:
        cov_f, pass_info["faultcov"] = run_faultcov()
        findings += cov_f
    program_passes = {"uniformity", "dtype"} | (
        set() if args.fast else {"transfer", "memory"}
    )
    if not (program_passes <= skip):
        _ensure_mesh()
        prog_f, prog_info = run_program_passes(
            configs, skip, compiled=not args.fast
        )
        findings += prog_f
        if "memory" not in skip:
            # The report must not claim a skipped pass ran and found
            # nothing — estimates only land when the pass was on.
            pass_info.setdefault("memory", {}).update(prog_info)
    if not args.fast and "transfer" not in skip:
        findings += run_sentinels()

    if args.write_baseline:
        with open(args.baseline, "w") as f:
            f.write("# tpu-bfs-analyze baseline: one suppressed finding "
                    "fingerprint per line.\n")
            for fp in sorted({x.fingerprint for x in findings}):
                f.write(fp + "\n")
        _log(f"baseline written: {len(findings)} fingerprint(s) -> "
             f"{args.baseline}")
        return 0

    new, suppressed, stale = apply_baseline(
        findings, load_baseline(args.baseline)
    )
    if args.as_json:
        print(json.dumps({
            "ok": not new,
            "findings": [_finding_json(f) for f in new],
            "suppressed": [_finding_json(f) for f in suppressed],
            "stale_baseline": sorted(stale),
            "passes": pass_info,
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
    for fp in sorted(stale):
        _log(f"STALE baseline entry (no matching finding — delete it): {fp}")
    _log(
        f"analyze: {len(findings)} finding(s) total, "
        f"{len(suppressed)} suppressed, {len(new)} new, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    if new:
        _log("FAIL: new findings above — fix them or (for a parked known "
             "issue) add their fingerprints to the baseline")
        return 1
    _log("OK: all passes clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
