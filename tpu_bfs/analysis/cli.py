"""``tpu-bfs-analyze`` — run the static-verification passes and gate on
findings (``make analyze``).

Exit status: 0 when every finding is baseline-suppressed (or none
exist), 1 on new findings, so the target gates CI and the chip-session
pre-flight. The baseline file holds one finding fingerprint per line
(``pass:where``; ``#`` comments); stale entries — suppressions whose
finding no longer exists — are reported so they get deleted, and
``--write-baseline`` rewrites the file from the current findings when a
known issue must be parked rather than fixed.

``--fast`` runs the trace-only subset (the uniformity taint + dtype
walks over the planner programs, and the whole AST lock lint) — seconds,
no XLA compile. The default runs everything: all engine configs
compiled, their HLO conditional/host-op/dtype audits, the
transfer-guard drives, and the retrace/lazy-distance sentinels.
"""

from __future__ import annotations

import argparse
import sys

from tpu_bfs.analysis import (
    DEFAULT_BASELINE,
    Finding,
    apply_baseline,
    load_baseline,
)


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def run_locks() -> list[Finding]:
    from tpu_bfs.analysis.locks import lint_tree, repo_root

    findings, info = lint_tree(repo_root())
    _log(
        f"locks: {info['classes']} classes, {info['guarded_attrs']} "
        f"guarded attrs, {len(info['edges'])} lock-order edges, "
        f"{len(findings)} finding(s)"
    )
    return findings


def _ensure_mesh() -> None:
    """The engine sweep needs the 8-virtual-device CPU mesh the tests run
    on (tests/conftest.py does this for pytest; the standalone CLI does
    it here — same bootstrap, shared with __graft_entry__)."""
    from tpu_bfs.utils.virtual_mesh import ensure_virtual_devices

    ensure_virtual_devices(8)


def run_program_passes(configs, skip: set, *, compiled: bool) -> list[Finding]:
    """One sweep over the engine-program inventory, each engine built and
    traced ONCE: the uniformity taint + dtype walks share the trace, and
    in ``compiled`` mode the same spec is lowered once for the HLO
    conditional/host-op/dtype audits plus the transfer-guard drive. Each
    check family honors its entry in ``skip`` — a skipped pass emits no
    findings (in particular, skipping uniformity also skips the HLO
    conditional audit, which without taint certificates would flag the
    planner's legitimately-differing arms)."""
    import jax

    from tpu_bfs.analysis import dtypes, transfer, uniformity
    from tpu_bfs.analysis.configs import iter_programs
    from tpu_bfs.analysis.hlo import wide_dtype_lines

    do_uni = "uniformity" not in skip
    do_dtype = "dtype" not in skip
    do_transfer = compiled and "transfer" not in skip
    findings: list[Finding] = []
    for spec in iter_programs(configs):
        closed = jax.make_jaxpr(spec.fn)(*spec.args)
        rep = None
        if do_uni:
            rep = uniformity.analyze_jaxpr(spec.name, closed)
            findings.extend(rep.findings)
            _log(
                f"uniformity[{spec.name}]: {rep.shard_maps} shard_map(s), "
                f"{rep.conds_checked} cond(s), "
                f"{rep.certified_divergent_safe} certified divergent-safe, "
                f"{len(rep.findings)} finding(s)"
            )
        if do_dtype:
            findings.extend(dtypes.check_jaxpr(spec.name, closed))
        if not compiled:
            continue
        hlo = spec.lower_hlo()
        cond_f = (
            uniformity.check_hlo_conditionals(spec.name, hlo, rep)
            if do_uni else []
        )
        host_f = (
            transfer.check_hlo_host_ops(spec.name, hlo)
            if do_transfer else []
        )
        dtype_f = [
            Finding(
                "dtype",
                f"{spec.name}:{hit['source'] or hit['computation']}",
                f"compiled program carries a {hit['dtype']} result: "
                f"{hit['line']}",
            )
            for hit in wide_dtype_lines(hlo)
        ] if do_dtype else []
        guard_f = (
            transfer.check_loop_transfer_guard(spec.name, spec.fn, spec.args)
            if do_transfer else []
        )
        findings.extend(cond_f + host_f + dtype_f + guard_f)
        _log(
            f"hlo[{spec.name}]: {len(cond_f)} conditional, "
            f"{len(host_f)} host-op, {len(dtype_f)} dtype, "
            f"{len(guard_f)} transfer-guard finding(s)"
        )
    return findings


def run_sentinels() -> list[Finding]:
    from tpu_bfs.analysis import transfer
    from tpu_bfs.analysis.configs import packed_retrace_drive

    eng, drive = packed_retrace_drive()
    findings = transfer.check_engine_retrace("wide-sparse-rows", eng, drive)
    import numpy as np

    sources = np.arange(eng.lanes, dtype=np.int64) % eng.num_vertices
    findings += transfer.check_lazy_distances(
        "wide-sparse-rows", eng, sources
    )
    _log(f"sentinels: retrace+lazy-distance, {len(findings)} finding(s)")
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-bfs-analyze",
        description="Static verification of the mesh programs and the "
        "serve tier: collective-uniformity taint + HLO signatures, "
        "transfer/retrace guards, lock-discipline lint, dtype lint.",
    )
    ap.add_argument("--fast", action="store_true",
                    help="trace-only subset (no XLA compiles): the "
                    "uniformity/dtype walks over the planner programs "
                    "plus the full AST lock lint — the tier-1 shape")
    ap.add_argument("--configs", default=None, metavar="A,B",
                    help="restrict the engine-config sweep (names from "
                    "tpu_bfs/analysis/configs.py; default: all, or the "
                    "fast subset under --fast)")
    ap.add_argument("--skip", default="", metavar="PASS,..",
                    help="skip passes: any of uniformity,transfer,"
                    "locks,dtype (skipping uniformity also skips the "
                    "HLO conditional audit, which needs its taint "
                    "certificates)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"suppression file (default {DEFAULT_BASELINE}; "
                    "missing = empty)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline file from the current "
                    "findings and exit 0")
    args = ap.parse_args(argv)

    skip = {tok.strip() for tok in args.skip.split(",") if tok.strip()}
    if args.fast:
        from tpu_bfs.analysis.configs import FAST_CONFIGS

        configs = FAST_CONFIGS
    else:
        configs = None
    if args.configs:
        from tpu_bfs.analysis.configs import ALL_CONFIGS

        configs = tuple(
            tok.strip() for tok in args.configs.split(",") if tok.strip()
        )
        unknown = [c for c in configs if c not in ALL_CONFIGS]
        if unknown:
            _log(f"unknown config(s) {unknown}; have: "
                 f"{', '.join(ALL_CONFIGS)}")
            return 2

    findings: list[Finding] = []
    if "locks" not in skip:
        findings += run_locks()
    program_passes = {"uniformity", "dtype"} | (
        set() if args.fast else {"transfer"}
    )
    if not (program_passes <= skip):
        _ensure_mesh()
        findings += run_program_passes(
            configs, skip, compiled=not args.fast
        )
    if not args.fast and "transfer" not in skip:
        findings += run_sentinels()

    if args.write_baseline:
        with open(args.baseline, "w") as f:
            f.write("# tpu-bfs-analyze baseline: one suppressed finding "
                    "fingerprint per line.\n")
            for fp in sorted({x.fingerprint for x in findings}):
                f.write(fp + "\n")
        _log(f"baseline written: {len(findings)} fingerprint(s) -> "
             f"{args.baseline}")
        return 0

    new, suppressed, stale = apply_baseline(
        findings, load_baseline(args.baseline)
    )
    for f in new:
        print(f.render())
    for fp in sorted(stale):
        _log(f"STALE baseline entry (no matching finding — delete it): {fp}")
    _log(
        f"analyze: {len(findings)} finding(s) total, "
        f"{len(suppressed)} suppressed, {len(new)} new, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    if new:
        _log("FAIL: new findings above — fix them or (for a parked known "
             "issue) add their fingerprints to the baseline")
        return 1
    _log("OK: all passes clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
