"""The analyzed-program inventory: every distributed engine config whose
compiled level program the static passes verify.

Each engine family exposes ``analysis_programs()`` — its jit entry
points with example device-resident arguments — so the passes never poke
engine privates. The inventory mirrors the exchange configurations that
exist in the tree (ISSUE 8: 1D ring/allreduce/sparse/planner, 2D
dense/sparse, the dist-wide/hybrid row gathers), each built over one
small shared graph on the 8-virtual-device CPU mesh (the same graph
shapes the wirecheck audits compile).

``FAST_CONFIGS`` is the trace-only tier-1 subset (the two planner
programs — the richest branch spaces); the full list is the
``make analyze`` sweep.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache


@dataclasses.dataclass
class ProgramSpec:
    config: str  # engine config, e.g. "1d-sparse-planner"
    label: str  # program within it, e.g. "level_loop"
    fn: object  # the jit entry
    args: tuple  # device-resident example arguments
    engine: object

    @property
    def name(self) -> str:
        return f"{self.config}/{self.label}"

    def lower_compiled(self):
        """The compiled executable object — one compile shared by the
        HLO text audits and the memory estimator (pass 5)."""
        return self.fn.lower(*self.args).compile()

    def lower_hlo(self) -> str:
        return self.lower_compiled().as_text()


@lru_cache(maxsize=1)
def _graph():
    from tpu_bfs.graph.generate import random_graph

    # The wirecheck calibration shape: small, connected, 8-chip partition
    # still lands a real vloc.
    return random_graph(96, 480, seed=3)


@lru_cache(maxsize=1)
def _graph_weighted():
    from tpu_bfs.graph.generate import random_graph

    # The same calibration shape with the deterministic weight plane —
    # the sssp workload config's substrate (ISSUE 14).
    return random_graph(96, 480, seed=3, weights=5)


def _mesh(p: int = 8):
    from tpu_bfs.parallel.dist_bfs import make_mesh

    return make_mesh(p)


def _build_engine(config: str):
    g = _graph()
    if config.startswith("1d-"):
        from tpu_bfs.parallel.dist_bfs import DistBfsEngine

        kw: dict = {}
        if config == "1d-ring":
            kw = dict(exchange="ring")
        elif config == "1d-allreduce":
            kw = dict(exchange="allreduce")
        elif config == "1d-sparse":
            kw = dict(exchange="sparse")
        elif config == "1d-sparse-planner":
            kw = dict(exchange="sparse", delta_bits=(8, 16), sieve=True,
                      predict=True)
        elif config == "1d-dopt":
            kw = dict(exchange="ring", backend="dopt")
        else:
            raise KeyError(config)
        return DistBfsEngine(g, _mesh(), **kw)
    if config.startswith("2d-"):
        from tpu_bfs.parallel.dist_bfs2d import Dist2DBfsEngine, make_mesh_2d

        mesh = make_mesh_2d(2, 4)
        if config == "2d-ring":
            kw = dict(exchange="ring")
        elif config == "2d-allreduce":
            kw = dict(exchange="allreduce")
        elif config == "2d-dopt":
            kw = dict(exchange="ring", backend="dopt")
        elif config == "2d-sparse":
            kw = dict(exchange="sparse")
        elif config == "2d-sparse-planner":
            kw = dict(exchange="sparse", delta_bits=(8, 16), sieve=True,
                      predict=True)
        else:
            raise KeyError(config)
        return Dist2DBfsEngine(g, mesh, **kw)
    if config.startswith("wide-"):
        from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine

        if config == "wide-sparse-rows":
            kw = dict(exchange="sparse")
        elif config == "wide-delta-rows":
            kw = dict(exchange="sparse", delta_bits=(8, 16))
        else:
            raise KeyError(config)
        return DistWideMsBfsEngine(g, _mesh(), lanes=64, **kw)
    if config.startswith("hybrid-"):
        from tpu_bfs.parallel.dist_msbfs_hybrid import DistHybridMsBfsEngine

        exchange = config.split("-", 1)[1]
        return DistHybridMsBfsEngine(g, _mesh(), exchange=exchange)
    if config.startswith("serve-"):
        # Distributed serving configs (ISSUE 11) and the workload kinds
        # (ISSUE 14): built through the REGISTRY itself — the sweep then
        # verifies the exact engine the serve tier constructs (mesh
        # keys, exchange config, serving planes, kind adapters), not a
        # hand-assembled twin.
        from tpu_bfs.serve.registry import EngineRegistry, EngineSpec

        kw = {
            "serve-dist-wide": dict(
                engine="wide", devices=8, lanes=64,
                exchange="sparse", delta_bits=(8, 16),
            ),
            "serve-dist-hybrid": dict(
                engine="hybrid", devices=8, lanes=4096, exchange="sparse",
            ),
            "serve-dist2d": dict(
                engine="dist2d", devices=8, lanes=32, exchange="sparse",
                delta_bits=(8, 16), sieve=True, predict=True,
            ),
            # Workload-kind serving configs (ISSUE 14): the adapters'
            # analysis_programs expose the delta-stepping core (dtype +
            # donation certificate), the khop-bounded base core, the CC
            # label fold, and the p2p pair reductions.
            # Pallas kernel-tier configs (ISSUE 16): the SAME serve specs
            # with expand_impl='pallas' — the analyzed core then carries
            # the fused bucketed-ELL ``pallas_call`` (interpret mode on
            # the CPU mesh), so every pass walks the kernel body: 'or'
            # accumulate via serve-wide-pallas, min-plus via
            # serve-sssp-pallas.
            "serve-wide-pallas": dict(
                engine="wide", lanes=64, expand_impl="pallas",
            ),
            "serve-sssp-pallas": dict(
                kind="sssp", engine="wide", lanes=32, expand_impl="pallas",
            ),
            "serve-sssp": dict(kind="sssp", engine="wide", lanes=32),
            "serve-khop": dict(kind="khop", engine="wide", lanes=64),
            "serve-cc": dict(kind="cc", engine="wide", lanes=64),
            "serve-p2p": dict(kind="p2p", engine="wide", lanes=64),
            # The landmark warm-up program (ISSUE 18): the flagship
            # MS-BFS batch that computes the K distance columns rides
            # the wide bfs engine at the rung the warm-up routes K
            # onto (K=16 -> the 32 rung) — analyze the exact compile
            # the serve warm-up dispatches.
            "serve-landmark-warm": dict(engine="wide", lanes=32),
            # Dynamic-graph programs (ISSUE 19): the SAME serve specs
            # with an overlay capacity — the compiled core then carries
            # the delta-overlay fold (add plane OR'd in / min-plus'd
            # in, tombstone plane masked out), so every pass walks the
            # folded expansion the mutation flip actually serves.
            "serve-dynamic": dict(
                engine="wide", lanes=32, overlay=(64, 32),
            ),
            "serve-dynamic-pallas": dict(
                engine="wide", lanes=32, expand_impl="pallas",
                overlay=(64, 32),
            ),
            "serve-dynamic-sssp": dict(
                kind="sssp", engine="wide", lanes=32, overlay=(64, 32),
            ),
            # Semiring exchanges (ISSUE 20): every workload kind on the
            # full mesh. The dist-sssp configs analyze the sharded
            # delta-stepping core's min-exchange branch space (planner
            # variant 1D, hierarchical pmin 2D); the cc/khop/p2p rows
            # ride the distributed wide/2D substrates, so their adapters'
            # programs are the dist cores plus the replicated reductions.
            "serve-dist-sssp": dict(
                kind="sssp", engine="wide", lanes=32, devices=8,
                exchange="sparse", delta_bits=(8, 16), predict=True,
            ),
            "serve-dist-sssp-2d": dict(
                kind="sssp", engine="wide", lanes=32, devices=8,
                mesh_shape=(2, 4),
            ),
            "serve-dist-cc": dict(
                kind="cc", engine="wide", lanes=64, devices=8,
                exchange="sparse",
            ),
            "serve-dist-khop": dict(
                kind="khop", engine="dist2d", lanes=32, devices=8,
                exchange="sparse", delta_bits=(8, 16), sieve=True,
                predict=True,
            ),
            "serve-dist-p2p": dict(
                kind="p2p", engine="wide", lanes=64, devices=8,
                exchange="sparse", delta_bits=(8, 16),
            ),
        }.get(config)
        if kw is None:
            raise KeyError(config)
        if kw.get("kind") == "sssp":
            g = _graph_weighted()
        reg = EngineRegistry(capacity=1, warm=False)
        key = reg.add_graph("g", g)
        return reg.get(EngineSpec(graph_key=key, **kw))
    raise KeyError(config)


#: Trace-only tier-1 subset: the two planner programs — the richest
#: branch spaces, where a uniformity regression would actually land.
FAST_CONFIGS = ("1d-sparse-planner", "2d-sparse-planner")

ALL_CONFIGS = (
    "1d-ring", "1d-allreduce", "1d-sparse", "1d-sparse-planner", "1d-dopt",
    "2d-ring", "2d-allreduce", "2d-dopt", "2d-sparse", "2d-sparse-planner",
    "wide-sparse-rows", "wide-delta-rows",
    "hybrid-dense", "hybrid-sparse", "hybrid-sliced",
    "serve-dist-wide", "serve-dist-hybrid", "serve-dist2d",
    "serve-sssp", "serve-khop", "serve-cc", "serve-p2p",
    "serve-dist-sssp", "serve-dist-sssp-2d",
    "serve-dist-cc", "serve-dist-khop", "serve-dist-p2p",
    "serve-landmark-warm",
    "serve-wide-pallas", "serve-sssp-pallas",
    "serve-dynamic", "serve-dynamic-pallas", "serve-dynamic-sssp",
)


def iter_programs(configs=None):
    """Yield :class:`ProgramSpec` for every program of every requested
    config (engines built lazily, one at a time — the full sweep holds
    one engine's tables resident, not fifteen)."""
    for config in configs or ALL_CONFIGS:
        eng = _build_engine(config)
        for label, fn, args in eng.analysis_programs():
            yield ProgramSpec(config, label, fn, args, eng)


def packed_retrace_drive():
    """(engine, drive) for the retrace sentinel: the dist-wide packed
    engine driven twice with same-shape different-value batches — the
    serve executor's padded-dispatch pattern."""
    import numpy as np

    eng = _build_engine("wide-sparse-rows")
    n = eng.num_vertices
    state = {"i": 0}

    def drive(engine):
        state["i"] += 1  # same shape, different sources each drive
        sources = (np.arange(engine.lanes, dtype=np.int64) + state["i"]) % n
        return engine.fetch(engine.dispatch(sources))

    return eng, drive
