"""Pass 4: dtype lint — no f64 / accidental 64-bit widening in any
compiled hot program.

The engines are sized to 32-bit arithmetic end to end (distances u8/i32,
frontiers pred/u32 words, ids s32); one accidental f64 (a Python float
folding through an un-annotated op under x64) doubles a hot buffer and
halves VPU throughput on chip. The jaxpr-level walk below is the primary
scan (trace-only — no compile needed); :func:`tpu_bfs.analysis.hlo.
wide_dtype_lines` re-checks the compiled artifact in the full sweep for
widening XLA itself introduces."""

from __future__ import annotations

from tpu_bfs.analysis import Finding

_WIDE = ("float64", "int64", "uint64", "complex128")


def _is_wide(aval) -> str | None:
    dt = getattr(aval, "dtype", None)
    name = getattr(dt, "name", None)
    return name if name in _WIDE else None


def _jaxprs_in(v):
    """Yield every (possibly nested) jaxpr inside a param value: bare
    Jaxprs, ClosedJaxprs, and tuples/lists of either."""
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _jaxprs_in(x)


def _sub_jaxprs(eqn):
    # Generic param walk, not a fixed key list: the lint must see INSIDE
    # every sub-program — scan/while/cond carry theirs under jaxpr/
    # cond_jaxpr/body_jaxpr/branches, ``pallas_call`` carries the kernel
    # body under 'jaxpr' (ISSUE 16: an f64 seeded inside a kernel must
    # be flagged like any other hot-path widening), and future
    # primitives pick their own names.
    for v in eqn.params.values():
        yield from _jaxprs_in(v)


def scan_jaxpr(name: str, jaxpr, findings: list[Finding],
               _seen: set | None = None) -> None:
    from tpu_bfs.analysis.uniformity import _source_of

    if _seen is None:
        _seen = set()
    if id(jaxpr) in _seen:
        return
    _seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            wide = _is_wide(getattr(v, "aval", None))
            if wide:
                where = f"{name}:{_source_of(eqn)}"
                if not any(f.where == where for f in findings):
                    findings.append(Finding(
                        "dtype",
                        where,
                        f"`{eqn.primitive.name}` produces a {wide} value "
                        f"in a compiled hot program — 64-bit never "
                        f"belongs on the device hot path (distances are "
                        f"u8/i32, frontiers pred/u32). Cast explicitly "
                        f"or fix the widening input.",
                    ))
                break
        for sub in _sub_jaxprs(eqn):
            scan_jaxpr(name, sub, findings, _seen)


def check_program(name: str, fn, args) -> list[Finding]:
    """Trace ``fn(*args)`` and flag every 64-bit intermediate."""
    import jax

    findings: list[Finding] = []
    closed = jax.make_jaxpr(fn)(*args)
    scan_jaxpr(name, closed.jaxpr, findings)
    return findings


def check_jaxpr(name: str, closed) -> list[Finding]:
    """The same scan over an already-traced jaxpr (the runner traces each
    program once and shares it across passes)."""
    findings: list[Finding] = []
    scan_jaxpr(name, closed.jaxpr, findings)
    return findings
