"""Pass 4: dtype lint — no f64 / accidental 64-bit widening in any
compiled hot program.

The engines are sized to 32-bit arithmetic end to end (distances u8/i32,
frontiers pred/u32 words, ids s32); one accidental f64 (a Python float
folding through an un-annotated op under x64) doubles a hot buffer and
halves VPU throughput on chip. The jaxpr-level walk below is the primary
scan (trace-only — no compile needed); :func:`tpu_bfs.analysis.hlo.
wide_dtype_lines` re-checks the compiled artifact in the full sweep for
widening XLA itself introduces."""

from __future__ import annotations

from tpu_bfs.analysis import Finding

_WIDE = ("float64", "int64", "uint64", "complex128")


def _is_wide(aval) -> str | None:
    dt = getattr(aval, "dtype", None)
    name = getattr(dt, "name", None)
    return name if name in _WIDE else None


def _sub_jaxprs(eqn):
    for key in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
        v = eqn.params.get(key)
        if v is not None:
            yield v.jaxpr if hasattr(v, "jaxpr") else v
    for b in eqn.params.get("branches", ()):
        yield b.jaxpr


def scan_jaxpr(name: str, jaxpr, findings: list[Finding],
               _seen: set | None = None) -> None:
    from tpu_bfs.analysis.uniformity import _source_of

    if _seen is None:
        _seen = set()
    if id(jaxpr) in _seen:
        return
    _seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            wide = _is_wide(getattr(v, "aval", None))
            if wide:
                where = f"{name}:{_source_of(eqn)}"
                if not any(f.where == where for f in findings):
                    findings.append(Finding(
                        "dtype",
                        where,
                        f"`{eqn.primitive.name}` produces a {wide} value "
                        f"in a compiled hot program — 64-bit never "
                        f"belongs on the device hot path (distances are "
                        f"u8/i32, frontiers pred/u32). Cast explicitly "
                        f"or fix the widening input.",
                    ))
                break
        for sub in _sub_jaxprs(eqn):
            scan_jaxpr(name, sub, findings, _seen)


def check_program(name: str, fn, args) -> list[Finding]:
    """Trace ``fn(*args)`` and flag every 64-bit intermediate."""
    import jax

    findings: list[Finding] = []
    closed = jax.make_jaxpr(fn)(*args)
    scan_jaxpr(name, closed.jaxpr, findings)
    return findings


def check_jaxpr(name: str, closed) -> list[Finding]:
    """The same scan over an already-traced jaxpr (the runner traces each
    program once and shares it across passes)."""
    findings: list[Finding] = []
    scan_jaxpr(name, closed.jaxpr, findings)
    return findings
