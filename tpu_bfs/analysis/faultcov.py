"""Pass 7: fault-site coverage audit (ISSUE 13).

The chaos harness's whole value is that ``faults.SITES`` and the
injection sites in production code agree — and that every site is
actually drivable from a test. Nothing enforced either: a typo'd site
string in a consultation would silently never fire (the schedule
matches nothing), a declared site whose consultation was refactored
away would silently stop injecting, and a NEW site could land with no
chaos test ever visiting it. Three checks close the loop:

- **undeclared consults**: every ``ACTIVE.hit("<site>", ...)`` /
  ``sched.take("<site>", ...)`` call in the tree must name a site in
  ``faults.SITES`` — an unknown literal is a finding (the consult can
  never fire).
- **never-consulted sites**: every name in ``faults.SITES`` must appear
  as a consult literal somewhere in production code — a site with no
  consultation is dead grammar (specs naming it silently no-op).
- **coverage map**: fault-spec strings in ``tests/`` and the chaos
  smokes (``scripts/``) are parsed with the REAL spec parser
  (:func:`tpu_bfs.faults.FaultSchedule.from_spec` semantics via
  ``_parse_clause``), plus direct ``hit``/``take``/``FaultRule`` uses,
  into a site x kind map. A consulted site with zero test coverage is a
  finding — a new fault site cannot land untested. The full map rides
  the ``--json`` report (``faultcov`` certificates).
"""

from __future__ import annotations

import ast
import os
import re

from tpu_bfs.analysis import Finding

#: Production packages whose consultation sites the cross-check scans.
PROD_DIRS = ("tpu_bfs",)
#: Where drivability coverage may come from.
TEST_DIRS = ("tests", "scripts")

_CONSULT_ATTRS = ("hit", "take")
# Receivers that are fault schedules: ACTIVE (module global), a local
# named sched/schedule, or the faults-module attribute chain.
_SCHED_NAMES = re.compile(r"(ACTIVE|sched|schedule|faults)", re.IGNORECASE)


def _iter_py(root: str, subdirs) -> list[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, _dirs, files in os.walk(base):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(files):
                if fname.endswith(".py"):
                    out.append(os.path.join(dirpath, fname))
    return out


def _recv_text(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def consult_sites_in_source(source: str) -> list[tuple[str, int]]:
    """``(site_literal, lineno)`` for every schedule consultation in one
    module: ``<schedule>.hit("<site>", ...)`` and ``<schedule>.take(
    "<site>", "<kind>", ...)`` calls whose receiver looks like a fault
    schedule and whose first argument is a string literal."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr not in _CONSULT_ATTRS or not node.args:
            continue
        if not _SCHED_NAMES.search(_recv_text(node.func.value)):
            continue
        a = node.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            out.append((a.value, node.lineno))
    return out


# --- test coverage ----------------------------------------------------------

_KIND_TOKEN_CACHE = None


def _kind_token():
    """Pre-filter regex built from the REAL kind vocabulary
    (``faults.KINDS``, longest-first so 'slow_extract' wins over 'slow')
    — a kind added to the grammar is recognized here automatically, so
    the coverage scan and the spec parser cannot drift."""
    global _KIND_TOKEN_CACHE
    if _KIND_TOKEN_CACHE is None:
        from tpu_bfs.faults import KINDS

        _KIND_TOKEN_CACHE = re.compile(
            r"\b(" + "|".join(sorted(KINDS, key=len, reverse=True)) + r")\b"
        )
    return _KIND_TOKEN_CACHE


def _clauses_from_string(text: str):
    """Parsed ``FaultRule``s from one string literal that looks like a
    fault spec (contains a kind token). Invalid candidates — prose,
    error messages, deliberately-bad grammar fixtures — parse to
    nothing and are skipped."""
    from tpu_bfs.faults import FaultSchedule

    if not _kind_token().search(text) or len(text) > 400:
        return []
    try:
        return FaultSchedule.from_spec(text).rules
    except (ValueError, TypeError):
        return []


def coverage_from_source(source: str) -> dict[str, set]:
    """site -> kinds a test/smoke module can drive: parsed spec-string
    literals, direct ``hit("<site>")``/``take("<site>", "<kind>")``
    consultations, and explicit ``FaultRule(kind=..., site=...)``
    constructions."""
    cov: dict[str, set] = {}

    def add(site: str, kind: str | None) -> None:
        cov.setdefault(site, set())
        if kind:
            cov[site].add(kind)

    try:
        tree = ast.parse(source)
    except SyntaxError:
        return cov
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for rule in _clauses_from_string(node.value):
                add(rule.site, rule.kind)
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _CONSULT_ATTRS \
                    and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    kind = None
                    if fn.attr == "take" and len(node.args) > 1 and (
                        isinstance(node.args[1], ast.Constant)
                    ):
                        kind = node.args[1].value
                    add(a.value, kind)
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name == "FaultRule":
                kind = site = None
                for kw in node.keywords:
                    if kw.arg in ("kind", "site") and isinstance(
                        kw.value, ast.Constant
                    ):
                        if kw.arg == "kind":
                            kind = kw.value.value
                        else:
                            site = kw.value.value
                if site:
                    add(site, kind)
                elif kind:
                    from tpu_bfs.faults import DEFAULT_SITE

                    d = DEFAULT_SITE.get(kind)
                    if d:
                        add(d, kind)
    return cov


# --- the pass ---------------------------------------------------------------


def check_tree(root: str) -> tuple[list[Finding], dict]:
    """The full audit. Returns ``(findings, info)``; info carries the
    consult census and the site x kind coverage map for the report."""
    from tpu_bfs.faults import SITES

    findings: list[Finding] = []
    consulted: dict[str, list] = {}
    for path in _iter_py(root, PROD_DIRS):
        rel = os.path.relpath(path, root)
        with open(path) as f:
            src = f.read()
        for site, lineno in consult_sites_in_source(src):
            consulted.setdefault(site, []).append(f"{rel}:{lineno}")
            if site not in SITES:
                findings.append(Finding(
                    "faultcov",
                    f"{rel}:{lineno}@undeclared:{site}",
                    f"fault consultation names site {site!r} which is "
                    f"not declared in faults.SITES {tuple(SITES)} — no "
                    f"spec clause can ever fire here. Declare the site "
                    f"(and its DEFAULT_SITE row if a kind should land "
                    f"on it) or fix the typo.",
                ))
    # The corrupt hooks consult via take() INSIDE faults.py itself
    # (maybe_corrupt_file/payload) — already collected by the walk above
    # since tpu_bfs/faults.py is in the production scan.
    for site in SITES:
        if site not in consulted:
            findings.append(Finding(
                "faultcov",
                f"faults.SITES@never-consulted:{site}",
                f"site {site!r} is declared in faults.SITES but no "
                f"production code consults it — a spec naming it "
                f"silently no-ops, which is exactly how an injection "
                f"site rots. Wire the consultation or retire the site.",
            ))
    cov: dict[str, set] = {}
    for path in _iter_py(root, TEST_DIRS):
        with open(path) as f:
            src = f.read()
        for site, kinds in coverage_from_source(src).items():
            cov.setdefault(site, set()).update(kinds)
    for site in SITES:
        if site in consulted and not cov.get(site):
            findings.append(Finding(
                "faultcov",
                f"tests@uncovered:{site}",
                f"fault site {site!r} is consulted in production but no "
                f"test or chaos smoke drives a fault through it — a "
                f"regression in its recovery path would land untested. "
                f"Add a spec clause targeting it (e.g. "
                f"`transient@{site}:n=1`) to a chaos arm.",
            ))
    info = {
        "sites": {s: sorted(v) for s, v in consulted.items()},
        "coverage": {
            s: sorted(cov.get(s, ())) for s in sorted(set(cov) | set(SITES))
        },
    }
    return findings, info


def check_sources(
    prod: dict[str, str], tests: dict[str, str], sites=None
) -> tuple[list[Finding], dict]:
    """Fixture-friendly form over in-memory sources (``sites`` defaults
    to the real ``faults.SITES``)."""
    from tpu_bfs.faults import SITES

    sites = tuple(sites) if sites is not None else SITES
    findings: list[Finding] = []
    consulted: dict[str, list] = {}
    for rel, src in prod.items():
        for site, lineno in consult_sites_in_source(src):
            consulted.setdefault(site, []).append(f"{rel}:{lineno}")
            if site not in sites:
                findings.append(Finding(
                    "faultcov", f"{rel}:{lineno}@undeclared:{site}",
                    f"fault consultation names undeclared site {site!r}.",
                ))
    for site in sites:
        if site not in consulted:
            findings.append(Finding(
                "faultcov", f"faults.SITES@never-consulted:{site}",
                f"declared site {site!r} is never consulted.",
            ))
    cov: dict[str, set] = {}
    for src in tests.values():
        for site, kinds in coverage_from_source(src).items():
            cov.setdefault(site, set()).update(kinds)
    for site in sites:
        if site in consulted and not cov.get(site):
            findings.append(Finding(
                "faultcov", f"tests@uncovered:{site}",
                f"consulted site {site!r} has no test coverage.",
            ))
    return findings, {
        "sites": {s: sorted(v) for s, v in consulted.items()},
        "coverage": {s: sorted(v) for s, v in cov.items()},
    }
