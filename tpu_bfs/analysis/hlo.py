"""The shared compiled-HLO walking core (ISSUE 8).

Refactored out of ``utils/wirecheck.py`` (now a client): the shape/byte
parsing and collective inventory every wirecheck audit was built on, plus
the structural walkers the static-analysis passes need — computation
graphs, per-computation transitive collective *signatures* (op kind,
operand shape, replica/source-target grouping, in program order),
``conditional`` arm comparison, host-transfer instruction scans, and
wide-dtype scans.

Everything here is pure text analysis of ``compiled().as_text()`` output:
the same program XLA runs on TPU, modulo backend lowering, parsed on the
8-virtual-device CPU mesh.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(shape: str) -> int:
    """Bytes of one 'dtype[d0,d1]' shape string."""
    m = _SHAPE_RE.match(shape)
    if not m or m.group(1) not in _DTYPE_BYTES:
        raise ValueError(f"unparsable HLO shape {shape!r}")
    dims = [int(d) for d in m.group(2).split(",") if d] or [1]
    return _DTYPE_BYTES[m.group(1)] * int(np.prod(dims))


@dataclass(frozen=True)
class Collective:
    op: str  # all-to-all | collective-permute | all-reduce | all-gather | reduce-scatter
    # Bytes of the instruction's RESULT shape (the LHS — what the parser
    # sees). Equal to the operand for permute/all-to-all/all-reduce, the
    # ops audited by wirecheck; for all-gather the result is Px the
    # operand and for reduce-scatter 1/Px, so a check over those must
    # convert before deriving wire bytes.
    result_bytes: int
    pieces: int  # tuple arity (1 for array-shaped ops)


_COLLECTIVE_OPS = (
    "all-to-all", "collective-permute", "all-reduce", "all-gather",
    "reduce-scatter",
)
_COLL_PAT = re.compile(
    r"=\s+(\([^)]*\)|\S+)\s+(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?\("
)


def hlo_collectives(hlo_text: str) -> list[Collective]:
    """All communication instructions of a compiled HLO module, with the
    byte sizes read from their own result shapes. Async ``-start`` forms
    count once (their ``-done`` halves carry no new transfer)."""
    out = []
    for line in hlo_text.splitlines():
        m = _COLL_PAT.search(line)
        if not m:
            continue
        shape, op = m.group(1), m.group(2)
        if shape.startswith("("):
            # Tuple elements look like 's32[1,16]{1,0}' with commas both
            # between elements AND inside the dims — token-scan for shape
            # atoms instead of splitting on commas.
            parts = [
                t.group(0)
                for t in _SHAPE_RE.finditer(shape)
                if t.group(1) in _DTYPE_BYTES
            ]
            out.append(
                Collective(op, sum(shape_bytes(p) for p in parts), len(parts))
            )
        else:
            out.append(Collective(op, shape_bytes(shape), 1))
    return out


# --- computation graph ------------------------------------------------------

# '%region_1.26 (Arg_0.27: s32[]) -> s32[] {' / 'ENTRY %main.42 (...) ... {'
_COMP_HEADER = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")
_CALLEE_ATTRS = re.compile(
    r"(?:branch_computations=\{([^}]*)\}"
    r"|true_computation=%?([\w.\-]+)"
    r"|false_computation=%?([\w.\-]+)"
    r"|condition=%?([\w.\-]+)"
    r"|body=%?([\w.\-]+)"
    r"|calls=%?([\w.\-]+)"
    r"|to_apply=%?([\w.\-]+))"
)
_SOURCE_META = re.compile(r'source_file="([^"]+)"(?:.*?source_line=(\d+))?')


def hlo_computations(hlo_text: str) -> dict[str, list[str]]:
    """Computation name -> its instruction lines, in program order."""
    comps: dict[str, list[str]] = {}
    current: list[str] | None = None
    for line in hlo_text.splitlines():
        m = _COMP_HEADER.match(line)
        if m:
            current = comps.setdefault(m.group(1), [])
            continue
        if line.strip().startswith("}"):
            current = None
            continue
        if current is not None and line.strip():
            current.append(line)
    return comps


def line_callees(line: str) -> list[str]:
    """Computation names one instruction line calls into (conditional
    branches, while condition/body, fusion calls, reducer to_apply)."""
    out = []
    for m in _CALLEE_ATTRS.finditer(line):
        if m.group(1) is not None:  # branch_computations={%a, %b}
            out.extend(
                tok.strip().lstrip("%")
                for tok in m.group(1).split(",") if tok.strip()
            )
        else:
            out.append(next(g for g in m.groups()[1:] if g is not None))
    return out


def source_of_line(line: str) -> str | None:
    """'file.py:123' from an instruction's metadata, when present."""
    m = _SOURCE_META.search(line)
    if not m:
        return None
    path = m.group(1).rsplit("/", 1)[-1]
    return f"{path}:{m.group(2)}" if m.group(2) else path


_INSTR = re.compile(r"=\s+(\([^)]*\)|\S+)\s+([\w\-]+)\(")
_LAYOUT = re.compile(r"\{[\d,\s]*\}")


def _strip_layout(shape: str) -> str:
    return _LAYOUT.sub("", shape)


_GROUP_ATTRS = re.compile(
    r"(replica_groups=(?:\{\{[^}]*\}\}|\[[^\]]*\](?:<=\[[^\]]*\])?)"
    r"|source_target_pairs=\{[^}]*\}"
    r"|dimensions=\{[^}]*\})"
)


def collective_signature(
    comp: str, comps: dict[str, list[str]], _memo: dict | None = None
) -> tuple:
    """The ordered collective schedule a computation executes, transitively
    through everything it calls: one entry per collective — (op, result
    shape sans layout, replica/source-target grouping attrs) — plus
    structural markers for control flow whose schedule is iteration- or
    branch-shaped (('while', cond_sig, body_sig), ('conditional',
    (arm_sig, ...))). Two ``conditional`` arms are deadlock-compatible
    under a divergent predicate iff their signatures are equal (channel
    ids deliberately excluded — XLA numbers each instruction uniquely, so
    ids never match across arms; ORDER is the signature)."""
    if _memo is None:
        _memo = {}
    if comp in _memo:
        return _memo[comp]
    _memo[comp] = ()  # cycle guard (HLO call graphs are acyclic anyway)
    sig: list = []
    for line in comps.get(comp, ()):
        m = _INSTR.search(line)
        op = m.group(2) if m else ""
        base = op[:-6] if op.endswith("-start") else op
        if base in _COLLECTIVE_OPS:
            groups = tuple(g.group(1) for g in _GROUP_ATTRS.finditer(line))
            sig.append((base, _strip_layout(m.group(1)), groups))
            continue
        if op == "conditional":
            arms = tuple(
                collective_signature(c, comps, _memo)
                for c in line_callees(line)
            )
            if any(arms):
                sig.append(("conditional", arms))
            continue
        if op == "while":
            callees = line_callees(line)
            subs = tuple(
                collective_signature(c, comps, _memo) for c in callees
            )
            if any(subs):
                sig.append(("while", subs))
            continue
        for callee in line_callees(line):
            sig.extend(collective_signature(callee, comps, _memo))
    _memo[comp] = tuple(sig)
    return _memo[comp]


def mismatched_conditionals(hlo_text: str) -> list[dict]:
    """Every ``conditional`` whose arms do NOT share one collective
    signature (and are not all collective-free) — the instruction class
    that deadlocks a mesh when its predicate diverges across ranks.
    Each entry carries the source location (when XLA kept metadata) and
    the per-arm signatures for the report."""
    comps = hlo_computations(hlo_text)
    memo: dict = {}
    out = []
    for comp, lines in comps.items():
        for line in lines:
            m = _INSTR.search(line)
            if not m or m.group(2) != "conditional":
                continue
            arms = line_callees(line)
            sigs = [collective_signature(a, comps, memo) for a in arms]
            if len(set(sigs)) > 1:
                out.append({
                    "computation": comp,
                    "arms": arms,
                    "signatures": sigs,
                    "source": source_of_line(line),
                })
    return out


# --- host transfers ---------------------------------------------------------

_HOST_OPS = re.compile(
    r"=\s+(?:\([^)]*\)|\S+)\s+"
    r"(outfeed|infeed|send|send-done|recv|recv-done|copy-to-host|"
    r"copy-from-host)\("
)
# Host-callback custom-calls: jax.debug.print / io_callback / pure_callback
# lower to these targets (CPU: xla_python_cpu_callback / xla_ffi_...;
# TPU: tpu_host / host callback custom-calls).
_HOST_CALLBACK = re.compile(
    r'custom_call_target="[^"]*(callback|host)[^"]*"', re.IGNORECASE
)


def host_transfer_lines(hlo_text: str) -> list[dict]:
    """Instructions that cross the device-host boundary inside a compiled
    program: infeed/outfeed/send/recv/host copies, and custom-calls into
    host callbacks (``jax.debug.print`` inside a level loop lands here).
    A hot-loop program must have NONE — each is a per-invocation (or
    per-iteration) host sync."""
    comps = hlo_computations(hlo_text)
    out = []
    for comp, lines in comps.items():
        for line in lines:
            m = _HOST_OPS.search(line)
            cb = _HOST_CALLBACK.search(line)
            if not m and not cb:
                continue
            op = m.group(1) if m else "custom-call(host callback)"
            out.append({
                "computation": comp,
                "op": op,
                "source": source_of_line(line),
                "line": line.strip()[:160],
            })
    return out


# --- buffer donation (input/output aliasing) --------------------------------

# HloModule header: 'input_output_alias={ {0}: (1, {}, may-alias), ... }' —
# one entry per donated parameter the compiler actually aliased into an
# output. The braces nest, so the body is extracted by brace counting.
_ALIAS_ENTRY = re.compile(r"\{[\d,\s]*\}:\s*\((\d+)")


def input_output_aliases(hlo_text: str) -> list[int]:
    """Parameter numbers the compiled module aliases into outputs — the
    proof a ``donate_argnums`` actually landed (XLA silently drops
    donations it cannot use; a dropped donation doubles the carry's
    footprint exactly where the donor expected it halved)."""
    for line in hlo_text.splitlines():
        start = line.find("input_output_alias={")
        if start < 0:
            continue
        depth = 0
        body = []
        for ch in line[start + len("input_output_alias=") :]:
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    break
            body.append(ch)
        return [int(x) for x in _ALIAS_ENTRY.findall("".join(body))]
    return []


# --- static buffer walk (peak-HBM fallback) ---------------------------------


def hlo_buffer_estimate(hlo_text: str) -> dict:
    """Static peak-memory MODEL from HLO text alone — the fallback when
    ``compiled.memory_analysis()`` is unavailable on a backend.

    The walk prices (a) the entry computation's parameters, (b) its root
    shape, and (c) the largest per-computation live-set proxy: the sum of
    distinct result shapes a single computation produces (an overestimate
    of its live set — every buffer counted at once — which is the safe
    direction for a budget check). Donated aliases are REPORTED
    (``alias_count``) but deliberately not credited against the peak:
    the text walk cannot see which temp the alias saved, and an
    overestimate stays on the safe side of a budget gate — so for
    donating cores this fallback reads systematically higher than
    ``memory_analysis`` (which does credit ``alias_size_in_bytes``)."""
    comps = hlo_computations(hlo_text)
    entry = None
    for line in hlo_text.splitlines():
        m = re.match(r"^ENTRY\s+%?([\w.\-]+)", line.strip())
        if m:
            entry = m.group(1)
            break

    def _result_bytes(line: str) -> int:
        m = _INSTR.search(line)
        if not m:
            return 0
        total = 0
        for t in _SHAPE_RE.finditer(m.group(1)):
            if t.group(1) in _DTYPE_BYTES:
                try:
                    total += shape_bytes(t.group(0))
                except ValueError:
                    pass
        return total

    arg_bytes = 0
    out_bytes = 0
    if entry is not None:
        for line in comps.get(entry, ()):
            s = line.strip()
            if re.search(r"=\s+(?:\([^)]*\)|\S+)\s+parameter\(", s):
                arg_bytes += _result_bytes(s)
            if s.startswith("ROOT"):
                out_bytes = _result_bytes(s)
    temp_proxy = 0
    for comp, lines in comps.items():
        total = sum(_result_bytes(ln) for ln in lines)
        temp_proxy = max(temp_proxy, total)
    aliased = input_output_aliases(hlo_text)
    return {
        "argument_bytes": arg_bytes,
        "output_bytes": out_bytes,
        "temp_bytes": temp_proxy,
        "alias_count": len(aliased),
        "peak_bytes": arg_bytes + max(temp_proxy, out_bytes),
        "source": "hlo-walk",
    }


# --- wide dtypes ------------------------------------------------------------

_WIDE_SHAPE = re.compile(r"\b(f64|s64|u64|c128)\[")


def wide_dtype_lines(hlo_text: str) -> list[dict]:
    """Instructions whose result shape is 64-bit (f64/s64/u64/c128) — the
    accidental-widening scan over a compiled hot program (the jaxpr-level
    scan in :mod:`tpu_bfs.analysis.dtypes` is the primary; this catches
    widening XLA itself introduces). The result shape sits RIGHT of the
    ``=`` ('%x = f64[4]{0} multiply(...)'), captured by the same
    instruction pattern the signature walker uses — tuple results
    included."""
    comps = hlo_computations(hlo_text)
    out = []
    for comp, lines in comps.items():
        for line in lines:
            instr = _INSTR.search(line)
            if not instr:
                continue
            m = _WIDE_SHAPE.search(instr.group(1))
            if m:
                out.append({
                    "computation": comp,
                    "dtype": m.group(1),
                    "source": source_of_line(line),
                    "line": line.strip()[:160],
                })
    return out
