"""Pass 6: exception-path resource-leak verification (ISSUE 13).

The review logs of PRs 6, 9, and 11 each hand-fixed the same defect
class: an obs span left open when an error rode up through an exception
path, a resume snapshot never dropped once its query terminally
resolved, a lock released on the happy path only. PR 8's lock pass
checks *where* guarded attributes are touched; this pass checks *flow*
— a path-sensitive walk of every function's statement graph, exception
edges included, verifying that what gets opened gets closed on EVERY
path out:

- **spans**: each ``<recorder>.begin(<name>, ...)`` must reach a
  matching ``.end(<name>, ...)`` on every exit — normal returns,
  fall-off, and explicit ``raise`` paths (a dangling Perfetto ``b``
  event is exactly the PR 6 review catch). Span keys are the first-
  argument literal (or the variable name when the site names the span
  dynamically, e.g. the registry's ``engine_adopt``/``engine_build``
  pick — begin and end share the variable). A span whose ownership
  deliberately crosses functions (the query span opens at admission and
  closes at resolve) is annotated ``# span-outlives: <who closes it>``
  on its begin line — the annotation is the documented transfer of
  ownership, not a suppression.
- **locks**: a bare ``<lock>.acquire(...)`` must reach ``.release()``
  on every path (the ``if not lock.acquire(timeout=..): return`` idiom
  is modeled: the lock is held only on the fall-through). ``with``
  blocks need no checking — the context manager is the proof.
- **resume snapshots**: a class that ``put``s into a ResumeCache must
  also ``drop`` — a put-only class pins ~3x[V] host arrays per source
  forever (the PR 11 review catch). Receivers are typed from their
  ``ResumeCache(...)``/``cache_for_graph(...)`` construction sites or a
  ``resume``-named attribute.

The walk models explicit ``raise`` statements and ``try``/``except``/
``finally`` edges (handler entry receives the union of open-sets from
every point of the try body — the standard conservative approximation).
Implicit raises from arbitrary calls are NOT modeled: flagging every
call as a potential raise would demand try/finally around every span,
which is not the codebase's (correct) shape — the historical bugs were
all on explicit raise/handler paths, which this pass covers exactly.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from tpu_bfs.analysis import Finding

SPAN_OUTLIVES_RE = re.compile(r"#\s*span-outlives:\s*(.+)")

#: The modules the repo-level pass covers (ISSUE 13: the serve tier, the
#: obs layer, the resilience machinery, and the 2D serve adapter whose
#: chunked drive owns the real snapshot put/drop pair).
DEFAULT_MODULES = (
    "tpu_bfs/serve/scheduler.py",
    "tpu_bfs/serve/frontend.py",
    "tpu_bfs/serve/executor.py",
    "tpu_bfs/serve/registry.py",
    "tpu_bfs/serve/metrics.py",
    "tpu_bfs/obs/__init__.py",
    "tpu_bfs/obs/recorder.py",
    "tpu_bfs/obs/engine_trace.py",
    "tpu_bfs/obs/exporters.py",
    "tpu_bfs/resilience/failover.py",
    "tpu_bfs/resilience/probe.py",
    "tpu_bfs/resilience/resume.py",
    "tpu_bfs/parallel/dist_bfs2d.py",
    # ISSUE 15: the integrity tier (audit worker lifecycle, quarantine
    # flight-dump path) — exception flow here must never leave a lock
    # held or a span open on the serving threads it observes.
    "tpu_bfs/integrity/__init__.py",
    "tpu_bfs/integrity/shadow.py",
    "tpu_bfs/integrity/structural.py",
    # ISSUE 18: the answer tier — the landmark warm-up opens an obs
    # span that must close on the warm-up failure path too.
    "tpu_bfs/serve/answercache.py",
    # ISSUE 19: dynamic graphs — a compaction crash must never
    # leave the flip lock held or a half-written generation
    # admitted; the staleness audit path must shed, not leak.
    "tpu_bfs/graph/dynamic.py",
    "tpu_bfs/integrity/staleness.py",
    "tpu_bfs/workloads/landmarks.py",
)


def _line_comments(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenizeError:
        pass
    return out


def _span_key(call: ast.Call) -> str | None:
    """Span identity of a begin/end call: the literal name, or the
    variable carrying it (begin/end sharing one variable still match)."""
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value
    if isinstance(a, ast.Name):
        return f"${a.id}"
    return None


def _recv_key(node) -> str | None:
    """Stable key of a lock/cache receiver: 'self.X' or a bare name."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclasses.dataclass
class _Effect:
    kind: str  # "open" | "close"
    res: str  # resource key, e.g. "span:dispatch" / "lock:self._lock"
    lineno: int
    outlives: str | None = None  # span-outlives annotation text


def _guard_key(test) -> tuple[str, bool] | None:
    """``(name, truth_when_taken)`` for the recorder-guard test shapes:
    ``X``, ``not X``, ``X is None``, ``X is not None`` — X a Name or a
    dotted attribute (``_obs.ACTIVE``). The walker correlates branches
    on the same key, so `if rec is not None: begin(...)` and a later
    `if rec is not None: end(...)` take consistent arms instead of
    manufacturing a phantom begun-but-never-ended path."""
    neg = False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        test, neg = test.operand, True
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and isinstance(
        test.comparators[0], ast.Constant
    ) and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.IsNot):
            pass  # `X is not None` == truthy X
        elif isinstance(test.ops[0], ast.Is):
            neg = not neg  # `X is None` == falsy X
        else:
            return None
        test = test.left
    key = _recv_key(test)
    if key is None:
        return None
    return key, not neg


class _FnChecker:
    """Path-sensitive resource walk of one function.

    A state is ``(resources, guards)``: the open-resource set plus the
    truth assignments of the guard names branched on so far — the
    minimum correlation needed for the codebase's pervasive
    ``rec = _obs.ACTIVE; if rec is not None: begin/end`` idiom."""

    def __init__(self, module: str, qualname: str, comments: dict,
                 findings: list):
        self.module = module
        self.qualname = qualname
        self.comments = comments
        self.findings = findings
        self.open_sites: dict[str, int] = {}  # resource -> first-open line
        self.reported: set = set()  # (resource, how) already reported

    # --- effects ------------------------------------------------------------

    def _effects(self, node) -> list[_Effect]:
        out: list[_Effect] = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or not isinstance(
                sub.func, ast.Attribute
            ):
                continue
            attr = sub.func.attr
            if attr in ("begin", "end"):
                key = _span_key(sub)
                if key is None:
                    continue
                m = SPAN_OUTLIVES_RE.search(
                    self.comments.get(sub.lineno, "")
                )
                out.append(_Effect(
                    "open" if attr == "begin" else "close",
                    f"span:{key}", sub.lineno,
                    outlives=m.group(1).strip() if m else None,
                ))
            elif attr == "acquire":
                key = _recv_key(sub.func.value)
                if key is not None:
                    out.append(_Effect("open", f"lock:{key}", sub.lineno))
            elif attr == "release":
                key = _recv_key(sub.func.value)
                if key is not None:
                    out.append(_Effect("close", f"lock:{key}", sub.lineno))
        return out

    def _apply(self, states: set, node) -> set:
        effs = self._effects(node)
        if not effs:
            return states
        out = set()
        for res, guards in states:
            cur = set(res)
            for e in effs:
                if e.kind == "open":
                    if e.outlives is not None:
                        continue  # documented ownership transfer
                    cur.add(e.res)
                    self.open_sites.setdefault(e.res, e.lineno)
                else:
                    cur.discard(e.res)
            out.add((frozenset(cur), guards))
        return out

    @staticmethod
    def _invalidate_guards(states: set, node) -> set:
        """An assignment to a guard name forgets its recorded truth."""
        names = set()
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                key = _recv_key(tgt)
                if key:
                    names.add(key)
        if not names:
            return states
        return {
            (res, frozenset(
                (k, v) for k, v in guards
                if k not in names and k.split(".", 1)[0] not in names
            ))
            for res, guards in states
        }

    # --- block walk ---------------------------------------------------------

    def run(self, fn) -> None:
        res = self._block(fn.body, {(frozenset(), frozenset())})
        for st, _guards in res["normal"] | res["returned"]:
            self._report(st, "on a normal exit")
        for st, _guards in res["raised"]:
            self._report(st, "across a raise")

    def _report(self, st: frozenset, how: str) -> None:
        for resource in sorted(st):
            if (resource, how) in self.reported:
                continue  # one finding per resource/exit kind per fn
            self.reported.add((resource, how))
            kind, _, key = resource.partition(":")
            line = self.open_sites.get(resource, 0)
            noun = "span" if kind == "span" else "lock"
            fix = (
                "close it on every path (end in the handler/finally "
                "before the raise propagates), or annotate the begin "
                "`# span-outlives: <who closes it>` if ownership "
                "deliberately crosses functions"
                if kind == "span"
                else "release in a try/finally"
            )
            self.findings.append(Finding(
                "lifecycle",
                f"{self.module}:{self.qualname}@{kind}:{key}",
                f"{noun} `{key}` opened at line {line} is still open "
                f"{how} of `{self.qualname}` — {fix}.",
            ))

    def _block(self, stmts, states: set) -> dict:
        res = {
            "normal": set(states), "raised": set(), "returned": set(),
            "broke": set(), "continued": set(), "seen": set(states),
        }
        for stmt in stmts:
            if not res["normal"]:
                break
            step = self._stmt(stmt, res["normal"])
            res["normal"] = step["normal"]
            for k in ("raised", "returned", "broke", "continued", "seen"):
                res[k] |= step[k]
        res["seen"] |= res["normal"]
        return res

    def _leaf(self, states: set, stmt) -> dict:
        out = self._invalidate_guards(self._apply(states, stmt), stmt)
        return {
            "normal": out, "raised": set(), "returned": set(),
            "broke": set(), "continued": set(), "seen": set(out),
        }

    def _stmt(self, stmt, states: set) -> dict:
        if isinstance(stmt, ast.Return):
            out = (
                self._apply(states, stmt.value)
                if stmt.value is not None else states
            )
            return {
                "normal": set(), "raised": set(), "returned": set(out),
                "broke": set(), "continued": set(), "seen": set(out),
            }
        if isinstance(stmt, ast.Raise):
            out = (
                self._apply(states, stmt.exc)
                if stmt.exc is not None else states
            )
            return {
                "normal": set(), "raised": set(out), "returned": set(),
                "broke": set(), "continued": set(), "seen": set(out),
            }
        if isinstance(stmt, ast.Break):
            return {
                "normal": set(), "raised": set(), "returned": set(),
                "broke": set(states), "continued": set(), "seen": set(),
            }
        if isinstance(stmt, ast.Continue):
            return {
                "normal": set(), "raised": set(), "returned": set(),
                "broke": set(), "continued": set(states), "seen": set(),
            }
        if isinstance(stmt, ast.If):
            return self._if(stmt, states)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, states)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, states)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # Context managers close themselves; item expressions may
            # still carry effects (rare; e.g. a begin used as a value).
            entry = states
            for item in stmt.items:
                entry = self._apply(entry, item.context_expr)
            return self._block(stmt.body, entry)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested function runs later; its body is checked as its
            # own function, with a fresh open-set.
            _FnChecker(
                self.module, f"{self.qualname}.{stmt.name}", self.comments,
                self.findings,
            ).run(stmt)
            return {
                "normal": set(states), "raised": set(), "returned": set(),
                "broke": set(), "continued": set(), "seen": set(),
            }
        if isinstance(stmt, ast.ClassDef):
            return {
                "normal": set(states), "raised": set(), "returned": set(),
                "broke": set(), "continued": set(), "seen": set(),
            }
        return self._leaf(states, stmt)

    def _if(self, stmt: ast.If, states: set) -> dict:
        # The timeout-acquire idiom: `if not X.acquire(..): return` —
        # the lock is held only on the fall-through.
        acq = [
            e for e in self._effects(stmt.test)
            if e.kind == "open" and e.res.startswith("lock:")
        ]
        negated = isinstance(stmt.test, ast.UnaryOp) and isinstance(
            stmt.test.op, ast.Not
        )
        body_terminates = stmt.body and all(
            isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
            for s in stmt.body
        ) and not stmt.orelse
        if acq and negated and body_terminates:
            fail = self._stmt_seq(stmt.body, states)  # lock NOT held
            held = set()
            for res, guards in states:
                cur = set(res)
                for e in acq:
                    cur.add(e.res)
                    self.open_sites.setdefault(e.res, e.lineno)
                held.add((frozenset(cur), guards))
            fail["normal"] |= held
            return fail
        gk = _guard_key(stmt.test)
        if gk is not None:
            key, truth = gk
            body_states: set = set()
            else_states: set = set()
            for res, guards in states:
                known = dict(guards).get(key)
                if known is None:
                    body_states.add(
                        (res, frozenset(guards | {(key, truth)}))
                    )
                    else_states.add(
                        (res, frozenset(guards | {(key, not truth)}))
                    )
                elif known == truth:
                    body_states.add((res, guards))
                else:
                    else_states.add((res, guards))
            body = self._block(stmt.body, body_states)
            orelse = self._block(stmt.orelse, else_states)
            return _merge(body, orelse)
        entry = self._apply(states, stmt.test)
        body = self._block(stmt.body, entry)
        orelse = self._block(stmt.orelse, entry)
        return _merge(body, orelse)

    def _stmt_seq(self, stmts, states: set) -> dict:
        return self._block(stmts, states)

    def _loop(self, stmt, states: set) -> dict:
        if isinstance(stmt, ast.While):
            entry = self._apply(states, stmt.test)
            infinite = isinstance(stmt.test, ast.Constant) and bool(
                stmt.test.value
            )
        else:
            entry = self._apply(states, stmt.iter)
            infinite = False
        res = {
            "normal": set(), "raised": set(), "returned": set(),
            "broke": set(), "continued": set(), "seen": set(),
        }
        reach = set(entry)
        for _ in range(8):  # resource sets are tiny; fixed point is fast
            body = self._block(stmt.body, reach)
            res["raised"] |= body["raised"]
            res["returned"] |= body["returned"]
            res["broke"] |= body["broke"]
            res["seen"] |= body["seen"]
            nxt = reach | body["normal"] | body["continued"]
            if nxt == reach:
                break
            reach = nxt
        # Python runs a loop's `else` only on NON-break exhaustion; break
        # states bypass it and merge after (a close placed only in the
        # else clause must not count for the break path).
        exits = set() if infinite else set(reach)
        if stmt.orelse:
            exits = self._block(stmt.orelse, exits)["normal"]
        exits |= res["broke"]
        return {
            "normal": exits, "raised": res["raised"],
            "returned": res["returned"], "broke": set(),
            "continued": set(), "seen": res["seen"],
        }

    def _try(self, stmt: ast.Try, states: set) -> dict:
        body = self._block(stmt.body, states)
        # Handler entry: the union of every open-set reachable anywhere
        # in the try body (an exception can fire between any two
        # statements), plus the explicit-raise states.
        handler_entry = body["seen"] | body["raised"] | set(states)
        out = {
            "normal": set(body["normal"]), "raised": set(),
            "returned": set(body["returned"]), "broke": set(body["broke"]),
            "continued": set(body["continued"]), "seen": set(body["seen"]),
        }
        if stmt.handlers:
            for h in stmt.handlers:
                hr = self._block(h.body, handler_entry)
                out["normal"] |= hr["normal"]
                out["raised"] |= hr["raised"]
                out["returned"] |= hr["returned"]
                out["broke"] |= hr["broke"]
                out["continued"] |= hr["continued"]
                out["seen"] |= hr["seen"]
        else:
            out["raised"] |= body["raised"] | body["seen"]
        if stmt.orelse:
            els = self._block(stmt.orelse, out["normal"])
            out["normal"] = els["normal"]
            out["raised"] |= els["raised"]
            out["returned"] |= els["returned"]
            out["seen"] |= els["seen"]
        if stmt.finalbody:
            for key in ("normal", "raised", "returned", "broke",
                        "continued"):
                out[key] = self._block(stmt.finalbody, out[key])["normal"] \
                    if out[key] else out[key]
        return out


def _merge(a: dict, b: dict) -> dict:
    return {k: a[k] | b[k] for k in a}


# --- resume-snapshot protocol ----------------------------------------------

_RESUME_CTORS = ("ResumeCache", "cache_for_graph")


def _is_resume_recv(key: str | None, typed: set) -> bool:
    if key is None:
        return False
    return key in typed or "resume" in key.lower()


def _check_snapshots(module: str, tree: ast.Module,
                     findings: list) -> None:
    """Per class (and per module top level): a ``.put(`` on a
    ResumeCache-typed receiver demands a reachable ``.drop(`` in the
    same scope — the terminal-resolution half of the snapshot protocol."""

    def scan(scope_name: str, nodes) -> None:
        typed: set = set()
        puts: list = []
        drops = 0
        for node in nodes:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Call
                ):
                    fn = sub.value.func
                    ctor = (
                        fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else None
                    )
                    if ctor in _RESUME_CTORS:
                        for tgt in sub.targets:
                            key = _recv_key(tgt)
                            if key:
                                typed.add(key)
                if not isinstance(sub, ast.Call) or not isinstance(
                    sub.func, ast.Attribute
                ):
                    continue
                recv = _recv_key(sub.func.value)
                if sub.func.attr == "put" and _is_resume_recv(recv, typed):
                    puts.append((recv, sub.lineno))
                elif sub.func.attr == "drop" and _is_resume_recv(
                    recv, typed
                ):
                    drops += 1
        if puts and not drops:
            recv, lineno = puts[0]
            findings.append(Finding(
                "lifecycle",
                f"{module}:{scope_name}@snapshot:{recv}",
                f"`{scope_name}` puts resume snapshots into `{recv}` "
                f"(line {lineno}) but never drops any: terminally "
                f"resolved queries keep ~3x[V] host arrays pinned in the "
                f"per-graph cache forever (the PR 11 review catch). Drop "
                f"on terminal resolution.",
            ))

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    for cls in classes:
        scan(cls.name, cls.body)
    class_ids = {id(c) for c in classes}
    top = [
        n for n in tree.body
        if not (isinstance(n, ast.ClassDef) and id(n) in class_ids)
    ]
    scan("<module>", top)


# --- entry points -----------------------------------------------------------


def check_sources(sources: dict[str, str]) -> tuple[list[Finding], dict]:
    """The pass over ``{module_label: source}``. Returns ``(findings,
    info)``; info counts functions walked and annotated escapes."""
    findings: list[Finding] = []
    functions = 0
    outlives = 0
    for module, src in sources.items():
        comments = _line_comments(src)
        outlives += sum(
            1 for c in comments.values() if SPAN_OUTLIVES_RE.search(c)
        )
        try:
            tree = ast.parse(src)
        except SyntaxError as exc:
            findings.append(Finding(
                "lifecycle", f"{module}:<parse>",
                f"unparsable module: {exc}",
            ))
            continue
        # Top-level and method functions; nested defs are walked by their
        # parents (fresh open-set — they run on another thread/later).
        def walk_scope(prefix: str, body) -> None:
            nonlocal functions
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    functions += 1
                    _FnChecker(
                        module, f"{prefix}{node.name}", comments, findings
                    ).run(node)
                elif isinstance(node, ast.ClassDef):
                    walk_scope(f"{node.name}.", node.body)

        walk_scope("", tree.body)
        _check_snapshots(module, tree, findings)
    return findings, {"functions": functions, "span_outlives": outlives}


def check_tree(root: str, modules=DEFAULT_MODULES) -> tuple[list, dict]:
    sources = {}
    for rel in modules:
        with open(os.path.join(root, rel)) as f:
            sources[rel] = f.read()
    return check_sources(sources)
