"""Pass 3: lock-discipline lint over the serve tier and the obs layer.

The serve/obs threads (scheduler, extraction worker, statsz emitter,
client threads, watchdog fetchers) share state behind half a dozen small
locks, and several paths hold a lock while calling into another module
(registry build under the registry lock emits recorder spans; the
breaker logs under its lock). Two machine-checkable disciplines keep
that safe, both enforced from source by this AST pass — no runtime, no
imports of the linted modules:

**guarded-by annotations.** An attribute whose every access must happen
under a lock is annotated where it is first assigned::

    self._items: deque = deque()  # guarded-by: _cond

After that, any ``self._items`` access outside a ``with self._cond:``
block (or outside a method annotated ``# requires-lock: _cond`` on its
``def`` line — the caller-holds-the-lock contract) is a finding.
``__init__``/``__new__`` are exempt (construction happens-before
publication), and the ``acquire(timeout=...)/try/finally: release()``
idiom is recognized (the try body counts as guarded). Annotations are
opt-in: deliberately lock-free flags (drain bools, immutable config)
simply stay unannotated.

**lock-order acyclicity.** Every annotated or ``with``-acquired lock is
a node ``Class.lockattr``; an edge A -> B is recorded when code holding
A may acquire B — directly (nested ``with``), or transitively through
calls: same-class method calls, and calls on attributes whose class is
inferred from their ``self.attr = ClassName(...)`` construction site
(cross-module: the service's ``self.metrics = ServeMetrics()`` types
``self.metrics.*`` calls; ``rec = _obs.ACTIVE`` locals type as Recorder).
Method acquisition summaries are closed under the call graph before
edges are drawn, so holding the registry lock through ``_build`` into a
recorder span still records registry._lock -> Recorder._lock.
Re-acquiring a lock constructed as ``threading.RLock()`` is allowed
(reentrant); any other cycle in the graph is a finding listing the
cycle — the deadlock shape no test on a fast machine ever hits.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize

from tpu_bfs.analysis import Finding

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_]\w*)")

#: Locals assigned from these module attributes get a known class — the
#: process-global singletons the serve tier calls under its own locks.
GLOBAL_TYPE_HINTS = {
    ("_obs", "ACTIVE"): "Recorder",
    ("obs", "ACTIVE"): "Recorder",
}


def _line_comments(source: str) -> dict[int, str]:
    """line number -> comment text (tokenize keeps what ast drops)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenizeError:
        pass
    return out


def _self_attr(node) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _calls_in_value(val):
    """Call nodes a value expression may construct from (handles the
    ``registry or EngineRegistry(...)`` default-construction idiom)."""
    if isinstance(val, ast.Call):
        yield val
    elif isinstance(val, ast.BoolOp):
        for v in val.values:
            yield from _calls_in_value(v)
    elif isinstance(val, ast.IfExp):
        yield from _calls_in_value(val.body)
        yield from _calls_in_value(val.orelse)


class ClassModel:
    """Everything the lint learned about one class."""

    def __init__(self, module: str, name: str):
        self.module = module
        self.name = name
        self.guarded: dict[str, str] = {}  # attr -> lock attr
        self.requires: dict[str, str] = {}  # method -> lock attr
        self.rlocks: set[str] = set()  # lock attrs built as RLock()
        self.attr_types: dict[str, str] = {}  # attr/local -> class name
        self.methods: dict[str, ast.FunctionDef] = {}

    def key(self, lock: str) -> str:
        return f"{self.name}.{lock}"


def _collect_class(module: str, cls: ast.ClassDef, comments) -> ClassModel:
    model = ClassModel(module, cls.name)
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        model.methods[item.name] = item
        m = REQUIRES_RE.search(comments.get(item.lineno, ""))
        if m:
            model.requires[item.name] = m.group(1)
        for node in ast.walk(item):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                cm = GUARDED_RE.search(comments.get(node.lineno, ""))
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        # Typed locals from known globals (rec = _obs.ACTIVE).
                        if (
                            isinstance(tgt, ast.Name)
                            and isinstance(node.value, ast.Attribute)
                            and isinstance(node.value.value, ast.Name)
                        ):
                            hint = GLOBAL_TYPE_HINTS.get(
                                (node.value.value.id, node.value.attr)
                            )
                            if hint:
                                model.attr_types[f"<local>{tgt.id}"] = hint
                        continue
                    if cm:
                        model.guarded[attr] = cm.group(1)
                    for call in _calls_in_value(getattr(node, "value", None)):
                        fn = call.func
                        if isinstance(fn, ast.Name):
                            model.attr_types.setdefault(attr, fn.id)
                        elif isinstance(fn, ast.Attribute):
                            model.attr_types.setdefault(attr, fn.attr)
                            if fn.attr == "RLock":
                                model.rlocks.add(attr)
    return model


def _with_locks(stmt: ast.With) -> list[str]:
    """Lock attrs acquired by a ``with self.<lock>[:]`` statement."""
    out = []
    for item in stmt.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.append(attr)
    return out


def _release_locks(stmts) -> list[str]:
    """Lock attrs released by ``self.<lock>.release()`` calls in stmts."""
    out = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    out.append(attr)
    return out


def _callees_of(model: ClassModel, fn) -> set[tuple[str, str]]:
    """(class, method) targets a method may call, through self and typed
    attributes/locals — the call graph the acquisition closure runs on."""
    out: set[tuple[str, str]] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        target = _self_attr(node.func)
        if target is not None:
            out.add((model.name, target))
            continue
        owner = node.func.value
        owner_attr = _self_attr(owner)
        if owner_attr is not None:
            cls = model.attr_types.get(owner_attr)
        elif isinstance(owner, ast.Name):
            cls = model.attr_types.get(f"<local>{owner.id}")
        else:
            cls = None
        if cls:
            out.add((cls, node.func.attr))
    return out


def _direct_acquires(model: ClassModel, fn) -> set[str]:
    """Node keys a method acquires directly (with blocks + the
    acquire/try/finally-release idiom)."""
    locks: set[str] = set()

    def visit(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                locks.update(_with_locks(stmt))
                visit(stmt.body)
            elif isinstance(stmt, ast.Try):
                locks.update(_release_locks(stmt.finalbody))
                visit(stmt.body)
                for h in stmt.handlers:
                    visit(h.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(stmt.body)  # nested fns acquire on whatever thread
            else:
                for node in ast.iter_child_nodes(stmt):
                    if isinstance(node, ast.stmt):
                        visit([node])
                    elif isinstance(node, (ast.If, ast.While, ast.For)):
                        visit([node])

    visit(fn.body)
    return {model.key(lk) for lk in locks}


def _acquisition_closure(classes: dict[str, ClassModel]) -> dict:
    """(class, method) -> node keys it may acquire, closed under the call
    graph (fixed point; the graphs here are tiny)."""
    direct: dict[tuple[str, str], set[str]] = {}
    calls: dict[tuple[str, str], set[tuple[str, str]]] = {}
    for model in classes.values():
        for name, fn in model.methods.items():
            direct[(model.name, name)] = _direct_acquires(model, fn)
            calls[(model.name, name)] = _callees_of(model, fn)
    acq = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for k, callees in calls.items():
            for c in callees:
                extra = acq.get(c, ())
                if not set(extra) <= acq[k]:
                    acq[k].update(extra)
                    changed = True
    return acq


class _MethodWalker:
    """Walk one method tracking the held-lock set, reporting guarded-attr
    accesses outside their lock and lock-acquisition edges."""

    def __init__(self, model: ClassModel, classes: dict[str, ClassModel],
                 acquires: dict, findings: list[Finding], edges: set):
        self.model = model
        self.classes = classes
        self.acquires = acquires
        self.findings = findings
        self.edges = edges
        self.exempt = False

    def walk_method(self, name: str, fn) -> None:
        held: set[str] = set()
        req = self.model.requires.get(name)
        if req:
            held.add(req)
        self.exempt = name in ("__init__", "__new__")
        self._stmts(fn.body, held, name)

    # --- statements ---------------------------------------------------------

    def _stmts(self, stmts, held: set, method: str) -> None:
        for stmt in stmts:
            self._stmt(stmt, held, method)

    def _stmt(self, stmt, held: set, method: str) -> None:
        if isinstance(stmt, ast.With):
            locks = _with_locks(stmt)
            for lk in locks:
                self._acquire_lock(lk, held, method, stmt.lineno)
            for item in stmt.items:
                self._expr(item.context_expr, held, method)
            self._stmts(stmt.body, held | set(locks), method)
            return
        if isinstance(stmt, ast.Try):
            released = set(_release_locks(stmt.finalbody))
            if released:
                # The acquire(timeout)/try/finally-release idiom
                # (EngineRegistry.resident): the try body runs with the
                # released locks held.
                for lk in released:
                    self._acquire_lock(lk, held, method, stmt.lineno)
                self._stmts(stmt.body, held | released, method)
            else:
                self._stmts(stmt.body, held, method)
            for h in stmt.handlers:
                self._stmts(h.body, held, method)
            self._stmts(stmt.orelse, held, method)
            self._stmts(stmt.finalbody, held, method)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested function runs later on whatever thread calls it —
            # the lexically-held locks are NOT held there.
            self._stmts(stmt.body, set(), method)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held, method)
            self._stmts(stmt.body, held, method)
            self._stmts(stmt.orelse, held, method)
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter, held, method)
            self._expr(stmt.target, held, method)
            self._stmts(stmt.body, held, method)
            self._stmts(stmt.orelse, held, method)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._expr(node, held, method)
            elif isinstance(node, ast.stmt):
                self._stmt(node, held, method)

    # --- expressions --------------------------------------------------------

    def _expr(self, node, held: set, method: str) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                attr = _self_attr(sub)
                if attr is None:
                    continue
                lock = self.model.guarded.get(attr)
                if lock is not None and lock not in held and not self.exempt:
                    self.findings.append(Finding(
                        "locks",
                        f"{self.model.module}:{self.model.name}."
                        f"{attr}@{method}",
                        f"attribute `{attr}` is `# guarded-by: {lock}` "
                        f"but `{self.model.name}.{method}` touches it at "
                        f"line {sub.lineno} without holding "
                        f"`self.{lock}` — wrap the access in "
                        f"`with self.{lock}:` or mark the method "
                        f"`# requires-lock: {lock}`.",
                    ))
            elif isinstance(sub, ast.Call):
                self._call(sub, held, method)

    def _call(self, call: ast.Call, held: set, method: str) -> None:
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return
        target = _self_attr(fn)
        if target is not None and target in self.model.methods:
            req = self.model.requires.get(target)
            if req is not None and req not in held:
                self.findings.append(Finding(
                    "locks",
                    f"{self.model.module}:{self.model.name}."
                    f"{target}@{method}",
                    f"`{self.model.name}.{target}` is "
                    f"`# requires-lock: {req}` but `{method}` calls it "
                    f"at line {call.lineno} without holding "
                    f"`self.{req}`.",
                ))
            self._edges_for(held, (self.model.name, target))
            return
        owner = fn.value
        owner_attr = _self_attr(owner)
        if owner_attr is not None:
            cls = self.model.attr_types.get(owner_attr)
        elif isinstance(owner, ast.Name):
            cls = self.model.attr_types.get(f"<local>{owner.id}")
        else:
            cls = None
        if cls in self.classes:
            self._edges_for(held, (cls, fn.attr))

    # --- edges --------------------------------------------------------------

    def _acquire_lock(self, lock: str, held: set, method: str,
                      lineno: int) -> None:
        if lock in held and lock not in self.model.rlocks:
            self.findings.append(Finding(
                "locks",
                f"{self.model.module}:{self.model.name}.{lock}@{method}",
                f"`self.{lock}` re-acquired at line {lineno} while "
                f"already held and not an RLock — self-deadlock.",
            ))
        dst = self.model.key(lock)
        for h in held:
            src = self.model.key(h)
            if src != dst:
                self.edges.add((src, dst))

    def _edges_for(self, held: set, callee: tuple[str, str]) -> None:
        for dst in self.acquires.get(callee, ()):
            for h in held:
                src = self.model.key(h)
                if src != dst:
                    self.edges.add((src, dst))


def find_cycles(edges: set) -> list[list[str]]:
    """Elementary cycles of the lock graph via DFS (tiny graphs)."""
    graph: dict[str, set] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen_cycles = [], set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
                continue
            dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def lint_sources(sources: dict[str, str]) -> tuple[list[Finding], dict]:
    """Lint a set of ``{module_label: source_text}``. Returns (findings,
    info) where info carries the annotated-attr count and the lock-order
    edge list for the report."""
    findings: list[Finding] = []
    classes: dict[str, ClassModel] = {}
    for module, src in sources.items():
        comments = _line_comments(src)
        try:
            tree = ast.parse(src)
        except SyntaxError as exc:
            findings.append(Finding(
                "locks", f"{module}:<parse>", f"unparsable module: {exc}"
            ))
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                model = _collect_class(module, node, comments)
                classes[model.name] = model
    acquires = _acquisition_closure(classes)
    edges: set = set()
    for model in classes.values():
        walker = _MethodWalker(model, classes, acquires, findings, edges)
        for name, fn in model.methods.items():
            walker.walk_method(name, fn)
    for cyc in find_cycles(edges):
        findings.append(Finding(
            "locks",
            "lock-order:" + "->".join(cyc),
            f"lock-acquisition-order cycle {' -> '.join(cyc)}: two "
            f"threads taking these locks in opposite orders deadlock. "
            f"Pick one global order (or drop a lock from the inner "
            f"call).",
        ))
    info = {
        "classes": len(classes),
        "guarded_attrs": sum(len(c.guarded) for c in classes.values()),
        "edges": sorted(edges),
    }
    return findings, info


#: The modules the repo-level lint covers (ISSUE 8: the serve tier + the
#: recorder — every class that holds a lock across a callback boundary).
DEFAULT_MODULES = (
    "tpu_bfs/serve/scheduler.py",
    "tpu_bfs/serve/frontend.py",
    "tpu_bfs/serve/executor.py",
    "tpu_bfs/serve/metrics.py",
    "tpu_bfs/serve/registry.py",
    "tpu_bfs/obs/recorder.py",
    # ISSUE 15: the integrity tier's threaded pieces — the shadow
    # auditor's queue/worker, the structural auditor's lazy device
    # tables, and the quarantine escalation counters.
    "tpu_bfs/integrity/__init__.py",
    "tpu_bfs/integrity/shadow.py",
    "tpu_bfs/integrity/structural.py",
    # ISSUE 18: the answer tier — LRU cache state and the landmark hit
    # counters are mutated from every client thread at once.
    "tpu_bfs/serve/answercache.py",
    "tpu_bfs/workloads/landmarks.py",
    # ISSUE 19: dynamic graphs — the overlay apply/compact state
    # machine and the staleness auditor's sample ring are mutated
    # by the mutation thread while serving threads read them.
    "tpu_bfs/graph/dynamic.py",
    "tpu_bfs/integrity/staleness.py",
)


def lint_tree(root: str, modules=DEFAULT_MODULES) -> tuple[list[Finding], dict]:
    sources = {}
    for rel in modules:
        path = os.path.join(root, rel)
        with open(path) as f:
            sources[rel] = f.read()
    return lint_sources(sources)


def repo_root() -> str:
    """The checkout root (two levels above this package)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
