"""Pass 5: static HBM budget + buffer-donation lint (ISSUE 13).

The OOM halving ladder, the AOT preheat store, and the mesh-failover
rungs all ASSUME narrower configs fit in less HBM; nothing proved it.
Three checks make the assumption a theorem:

- **per-program peak estimate** (:func:`estimate_compiled`): jax's
  ``compiled.memory_analysis()`` where the backend provides it
  (CompiledMemoryStats: argument/output/temp/alias bytes), the HLO
  buffer walk (:func:`tpu_bfs.analysis.hlo.hlo_buffer_estimate`) as the
  fallback — every engine program in the sweep gets a peak-bytes
  certificate in the report.
- **ladder budget model** (:func:`model_spec_peak_bytes` /
  :func:`check_ladder_entries` / :func:`check_registry_ladders`): an
  analytic per-engine-family peak model (the ``auto_lanes`` pricing the
  engines already size themselves with, plus per-lane and fixed
  residents) evaluated at every rung of every width ladder the serve
  registry can build — modeled peak must be STRICTLY monotone in rung
  width, so walking the OOM/mesh-degrade ladder down provably shrinks
  memory. The model prices TPU-physical table widths
  (``tpu_padded_words``: sub-128-word tables pad up), so the monotone
  margin below 4096 lanes comes from the honest per-lane terms — the
  model never credits a narrow rung with table savings TPU doesn't give.
- **donation lint** (:func:`lint_donation_sources`): an AST pass over
  the engine-core modules. A jit definition whose parameters feed a
  ``lax.while_loop``/``fori_loop`` carry (directly or through one local
  helper) is *carry-style*: without ``donate_argnums`` covering at least
  one carried parameter, the old and new carries are simultaneously live
  — the exact double-residency utils/roofline.py documents OOM'ing at
  flagship scale. Findings: an undonated carry, and the dead
  ``donate_argnums=()`` annotation (satisfies a grep, donates nothing).
  A deliberate non-donating entry is annotated ``# no-donate: <why>`` on
  its def/assignment line (e.g. the packed ``core``, whose seed table
  doubles as the batch's src-bits view and MUST survive the call).
  Applied donations are verified from the artifact:
  :func:`check_program_donation` fails when a tagged-donating program's
  compiled HLO carries no ``input_output_alias`` entry (XLA silently
  drops unusable donations).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from tpu_bfs.analysis import Finding

NO_DONATE_RE = re.compile(r"#\s*no-donate:\s*(.+)")

#: The engine-core modules the repo-level donation lint covers: every
#: module defining a level-loop jit whose carry the serve/checkpoint
#: paths hand back in (ISSUE 13 tentpole scope).
DEFAULT_DONATION_MODULES = (
    "tpu_bfs/algorithms/bfs.py",
    "tpu_bfs/algorithms/_packed_common.py",
    "tpu_bfs/parallel/dist_bfs.py",
    "tpu_bfs/parallel/dist_bfs2d.py",
    "tpu_bfs/utils/roofline.py",
    # The Pallas kernel wrappers (ISSUE 16): their jitted entries take
    # the standing tables, never a loop carry — the lint proves no
    # carry-style jit hides in them as the kernel tier grows.
    "tpu_bfs/ops/tile_spmm.py",
    "tpu_bfs/ops/ell_expand.py",
)


# --- compiled-program peak estimate ----------------------------------------


def estimate_compiled(name: str, compiled) -> dict:
    """Peak-memory estimate of one compiled program: jax's own
    ``memory_analysis()`` when the backend reports it, the HLO buffer
    walk otherwise. Returns the certificate dict the JSON report
    carries; never raises (an estimator must not fail the program it
    measures)."""
    from tpu_bfs.analysis.hlo import hlo_buffer_estimate, input_output_aliases

    stats = None
    try:
        stats = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend-optional API
        stats = None
    text = None
    if stats is not None:
        try:
            arg = int(stats.argument_size_in_bytes)
            out = int(stats.output_size_in_bytes)
            tmp = int(stats.temp_size_in_bytes)
            alias = int(stats.alias_size_in_bytes)
            return {
                "program": name,
                "argument_bytes": arg,
                "output_bytes": out,
                "temp_bytes": tmp,
                "alias_bytes": alias,
                "donated": alias > 0,
                # Peak live set: arguments resident + temps + the output
                # share not aliased back onto donated arguments.
                "peak_bytes": arg + tmp + max(out - alias, 0),
                "source": "memory_analysis",
            }
        except (AttributeError, TypeError):
            stats = None
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — estimator must not fail the sweep
        return {"program": name, "peak_bytes": None, "source": "unavailable"}
    est = hlo_buffer_estimate(text)
    est["program"] = name
    # The text walk knows which parameters aliased but not their bytes;
    # the boolean `donated` is the signal both branches share (the CLI's
    # "donated" label and any report consumer key on it, never on
    # alias_bytes truthiness).
    est["donated"] = est.get("alias_count", 0) > 0
    return est


def check_program_donation(name: str, fn, hlo_text: str) -> list[Finding]:
    """A program tagged donating (``fn._donate_argnums``) must show at
    least one ``input_output_alias`` entry in its compiled HLO — the
    donation actually landed. XLA drops donations it cannot alias
    (shape/layout mismatch) WITHOUT failing the compile, which would
    silently re-inflate the carry's footprint."""
    from tpu_bfs.analysis.hlo import input_output_aliases

    donated = getattr(fn, "_donate_argnums", ())
    if not donated:
        return []
    if input_output_aliases(hlo_text):
        return []
    return [Finding(
        "memory/donation",
        f"{name}:input-output-alias",
        f"program is tagged donating (argnums {tuple(donated)}) but its "
        f"compiled HLO carries no input_output_alias entry — XLA dropped "
        f"the donation (shape/layout mismatch between the donated "
        f"parameter and every output), so the carry is double-resident "
        f"again. Align the donated parameter's shape with the output it "
        f"should alias.",
    )]


# --- ladder budget model ----------------------------------------------------

# Bytes per lane of lane-indexed host/device residents outside the packed
# tables: the seed triplet (rows/words/bits, i32+i32+u32), the per-lane
# reached/edges results (2 x i64), and the [w, 32] ecc summary's share.
LANE_BYTES = 36
# Bytes per edge slot of the resident graph structures (bucketed-ELL
# index tables plus their transpose padding, ~2 x i32 per slot).
EDGE_BYTES = 8
# The hybrid engine's dense-tile budget (MXU tiles resident next to the
# residual ELL — sized once, lane-independent).
HYBRID_TILE_BYTES = 1 << 27
# dist2d per-vertex loop state on one chip: frontier + visited (pred)
# + distance (i32) per concurrently-launched single-source loop.
DIST2D_STATE_BYTES = 6


def model_spec_peak_bytes(
    engine: str, lanes: int, *, planes: int = 8, devices: int = 1,
    num_vertices: int, num_edges: int,
) -> dict:
    """Modeled peak HBM of one serving engine config on ONE chip.

    The packed-table term is exactly the ``auto_lanes`` sizing model the
    engines construct themselves with ((planes + 6) live [rows, w]
    uint32 tables at TPU-physical width); the per-lane and fixed terms
    make the model strictly monotone in lane count even where the
    physical table width plateaus (below 128 words every width pads to
    128 — the round-4 LJ OOM lesson, ``tpu_padded_words``). dist2d has
    no packed table: its per-chip state is one (frontier, visited,
    distance) vector triple per concurrently-launched source loop.
    CPU-safe: pure arithmetic, no engine build, no compile."""
    from tpu_bfs.algorithms._packed_common import tpu_padded_words

    rows_local = -(-int(num_vertices) // max(int(devices), 1)) + 1
    edges_local = -(-int(num_edges) // max(int(devices), 1))
    fixed = EDGE_BYTES * edges_local
    if engine == "hybrid":
        fixed += HYBRID_TILE_BYTES
    if engine == "dist2d":
        state = DIST2D_STATE_BYTES * rows_local * int(lanes)
    else:
        w = max(int(lanes) // 32, 1)
        state = (int(planes) + 6) * rows_local * tpu_padded_words(w) * 4
    lane_term = LANE_BYTES * int(lanes)
    return {
        "engine": engine,
        "lanes": int(lanes),
        "devices": int(devices),
        "state_bytes": int(state),
        "lane_bytes": int(lane_term),
        "fixed_bytes": int(fixed),
        "total_bytes": int(state + lane_term + fixed),
    }


def check_ladder_entries(family: str, entries) -> list[Finding]:
    """``entries`` = ``[(width, modeled_bytes), ...]``: modeled peak must
    be STRICTLY monotone in rung width, or the OOM/mesh-degrade ladder
    walks to a rung that frees nothing — the halving ladder's core
    assumption, now checked instead of believed."""
    entries = sorted(entries)
    out: list[Finding] = []
    for (w0, b0), (w1, b1) in zip(entries, entries[1:]):
        if w0 == w1:
            out.append(Finding(
                "memory/ladder",
                f"{family}:w{w0}",
                f"ladder family {family} lists rung width {w0} twice — "
                f"the degrade walk cannot make progress between equal "
                f"rungs.",
            ))
        elif b1 <= b0:
            out.append(Finding(
                "memory/ladder",
                f"{family}:w{w0}->w{w1}",
                f"modeled peak is not strictly monotone in rung width for "
                f"{family}: {w1} lanes models {b1} bytes <= {w0} lanes' "
                f"{b0} bytes — degrading {w1} -> {w0} would free nothing. "
                f"Check the family's per-lane terms (a width-independent "
                f"model cannot justify a halving ladder).",
            ))
    return out


def registry_ladder_families(
    *, num_vertices: int, num_edges: int, device_count: int = 8,
) -> dict:
    """``{family: [(width, modeled_bytes), ...]}`` for every EngineSpec
    family the serve registry can build (``ENGINE_KINDS`` x mesh), each
    over the exact rung grid ``build_width_ladder`` would warm — the
    same floors and quanta the OOM halving and the mesh degrade walk.
    """
    from tpu_bfs.serve.frontend import build_width_ladder
    from tpu_bfs.serve.registry import DEFAULT_PLANES, HYBRID_LANE_QUANTUM

    # (engine, devices, top width): the widest serving rung per family.
    families = [
        ("wide", 1, 4096),
        ("packed", 1, 512),
        ("hybrid", 1, 2 * HYBRID_LANE_QUANTUM),
    ]
    if device_count > 1:
        families += [
            ("wide", device_count, 4096),
            ("hybrid", device_count, 2 * HYBRID_LANE_QUANTUM),
            ("dist2d", device_count, 1024),
        ]
    out = {}
    for engine, devices, lanes in families:
        rungs = build_width_ladder(
            lanes, "auto", devices=devices, engine=engine
        )
        out[f"{engine}-d{devices}"] = [
            (
                w,
                model_spec_peak_bytes(
                    engine, w, planes=DEFAULT_PLANES, devices=devices,
                    num_vertices=num_vertices, num_edges=num_edges,
                )["total_bytes"],
            )
            for w in rungs
        ]
    return out


def check_registry_ladders(
    *, num_vertices: int, num_edges: int, device_count: int = 8,
) -> tuple[list[Finding], dict]:
    """The acceptance check: every registry-buildable EngineSpec family's
    modeled ladder is strictly monotone in rung width. Returns
    ``(findings, {family: entries})`` — the entries double as the JSON
    report's ladder certificates."""
    ladders = registry_ladder_families(
        num_vertices=num_vertices, num_edges=num_edges,
        device_count=device_count,
    )
    findings: list[Finding] = []
    for family, entries in ladders.items():
        findings.extend(check_ladder_entries(family, entries))
    return findings, ladders


# --- donation lint ----------------------------------------------------------


@dataclasses.dataclass
class JitDef:
    """One jit-wrapped program the lint located in source."""

    module: str
    name: str
    lineno: int
    donate: tuple | None  # literal donate_argnums, None when absent
    no_donate: str | None  # reason text of a `# no-donate:` annotation
    carry_argnums: tuple  # parameter indices feeding a loop carry

    @property
    def where(self) -> str:
        return f"{self.module}:{self.name}"


def _line_comments(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenizeError:
        pass
    return out


def _is_jax_jit(node) -> bool:
    """``jax.jit`` / bare ``jit`` as a Name/Attribute node."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_info(call: ast.Call):
    """``(inner, donate)`` when ``call`` is ``jax.jit(inner, ...)`` or
    ``partial(jax.jit, ...)``; None otherwise. ``donate`` is the literal
    donate_argnums tuple, or None when the kwarg is absent."""
    fn = call.func
    target = None
    if _is_jax_jit(fn):
        target = call.args[0] if call.args else None
    elif (
        (isinstance(fn, ast.Name) and fn.id == "partial")
        or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
    ) and call.args and _is_jax_jit(call.args[0]):
        target = call.args[1] if len(call.args) > 1 else None
    else:
        return None
    donate = None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                donate = ast.literal_eval(kw.value)
            except (ValueError, TypeError):
                donate = None  # computed donation: not lintable
            else:
                if isinstance(donate, int):
                    donate = (donate,)  # jax accepts a bare int
                else:
                    try:
                        donate = tuple(donate)
                    except TypeError:
                        donate = None
    return target, donate


_LOOP_FNS = {"while_loop": 2, "fori_loop": 3}  # fn name -> init arg index


def _carry_param_map(tree: ast.Module) -> dict[str, set[int]]:
    """function name -> parameter indices that flow into a
    ``lax.while_loop``/``fori_loop`` carry, directly or through one
    level of local-call indirection (the ``core -> _run -> while_loop``
    shape of the packed loop factory). Closed to a fixed point over the
    module's local call graph."""
    fns: dict[str, ast.FunctionDef] = {}

    def collect(node):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(sub.name, sub)

    collect(tree)
    carry: dict[str, set[int]] = {name: set() for name in fns}
    # Direct: names inside a loop call's init expression. An init bound
    # to a local first (`init = (f, vis, d, ...); while_loop(c, b, init)`
    # — the dist loop shape) resolves through one simple assignment.
    for name, fn in fns.items():
        params = [a.arg for a in fn.args.args]
        assigns: dict[str, ast.expr] = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                assigns.setdefault(node.targets[0].id, node.value)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            attr = (
                callee.attr if isinstance(callee, ast.Attribute)
                else callee.id if isinstance(callee, ast.Name) else None
            )
            init_idx = _LOOP_FNS.get(attr)
            if init_idx is None or len(node.args) <= init_idx:
                continue
            init = node.args[init_idx]
            if isinstance(init, ast.Name) and init.id in assigns:
                init = assigns[init.id]
            names = {
                n.id for n in ast.walk(init) if isinstance(n, ast.Name)
            }
            carry[name].update(
                i for i, p in enumerate(params) if p in names
            )
    # One fixed point of indirection: a param passed positionally into a
    # local function at a carry position carries too.
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            params = [a.arg for a in fn.args.args]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = node.func
                cname = callee.id if isinstance(callee, ast.Name) else None
                if cname not in carry or not carry[cname]:
                    continue
                for pos, arg in enumerate(node.args):
                    if pos in carry[cname] and isinstance(arg, ast.Name):
                        try:
                            i = params.index(arg.id)
                        except ValueError:
                            continue
                        if i not in carry[name]:
                            carry[name].add(i)
                            changed = True
    return carry


def collect_jit_defs(module: str, source: str) -> list[JitDef]:
    """Every jit-wrapped program the lint can see in one module:
    decorated defs, ``x = jax.jit(f, ...)`` assignments, and
    ``return jax.jit(shard_map(f, ...))`` factory returns (the dist
    loop shape — the shard_map wrapper is looked through)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    comments = _line_comments(source)
    carry = _carry_param_map(tree)
    fns: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)

    def annotation(lineno: int) -> str | None:
        m = NO_DONATE_RE.search(comments.get(lineno, ""))
        return m.group(1).strip() if m else None

    def resolve_target(node) -> str | None:
        """Function name a jit call wraps: a Name, or the first
        positional arg of an intermediate wrapper call (shard_map)."""
        if isinstance(node, ast.Name):
            return node.id if node.id in fns else None
        if isinstance(node, ast.Call) and node.args:
            return resolve_target(node.args[0])
        return None

    out: list[JitDef] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                info = None
                if isinstance(dec, ast.Call):
                    info = _jit_call_info(dec)
                elif _is_jax_jit(dec):
                    info = (None, None)
                if info is None:
                    continue
                _, donate = info
                out.append(JitDef(
                    module=module, name=node.name, lineno=node.lineno,
                    donate=donate,
                    no_donate=annotation(node.lineno)
                    or annotation(dec.lineno),
                    carry_argnums=tuple(sorted(carry.get(node.name, ()))),
                ))
                break
            continue
        if not isinstance(node, (ast.Assign, ast.Return)):
            continue
        val = node.value
        if not isinstance(val, ast.Call):
            continue
        info = _jit_call_info(val)
        if info is None:
            continue
        target, donate = info
        tname = resolve_target(target) if target is not None else None
        if isinstance(node, ast.Assign) and node.targets and isinstance(
            node.targets[0], (ast.Name, ast.Attribute)
        ):
            label = (
                node.targets[0].id
                if isinstance(node.targets[0], ast.Name)
                else node.targets[0].attr
            )
        else:
            label = tname or "<jit>"
        out.append(JitDef(
            module=module, name=label, lineno=node.lineno, donate=donate,
            no_donate=annotation(node.lineno),
            carry_argnums=tuple(sorted(carry.get(tname, ())))
            if tname else (),
        ))
    return out


def lint_donation_sources(
    sources: dict[str, str]
) -> tuple[list[Finding], dict]:
    """The donation lint over ``{module_label: source}``: dead
    ``donate_argnums=()`` annotations and carry-style jit programs that
    donate none of their carried parameters (``# no-donate: <why>``
    exempts a deliberate non-donating entry). Returns ``(findings,
    info)`` with the per-module jit census for the report."""
    findings: list[Finding] = []
    defs: list[JitDef] = []
    for module, src in sources.items():
        defs.extend(collect_jit_defs(module, src))
    donating = 0
    for d in defs:
        if d.donate == ():
            findings.append(Finding(
                "memory/donation",
                f"{d.where}@dead-annotation",
                f"`donate_argnums=()` on `{d.name}` (line {d.lineno}) "
                f"donates nothing — it reads as a donation to a reviewer "
                f"and as none to XLA. Donate the loop carry for real or "
                f"drop the parameter.",
            ))
        if d.donate:
            donating += 1
        if not d.carry_argnums or d.no_donate:
            continue
        if d.donate and set(d.donate) & set(d.carry_argnums):
            continue
        findings.append(Finding(
            "memory/donation",
            f"{d.where}@undonated-carry",
            f"jit program `{d.name}` (line {d.lineno}) loop-carries "
            f"parameters {d.carry_argnums} but donates none of them: the "
            f"old and new carries are simultaneously live — double the "
            f"state residency at exactly the widths the HBM ladder is "
            f"sized for. Add `donate_argnums` covering the carry (the "
            f"caller must treat those arguments as consumed), or mark a "
            f"deliberate copy `# no-donate: <why>`.",
        ))
    info = {
        "jit_defs": len(defs),
        "donating": donating,
        "carry_style": sum(1 for d in defs if d.carry_argnums),
        "no_donate": sum(1 for d in defs if d.no_donate),
    }
    return findings, info


def lint_donation_tree(
    root: str, modules=DEFAULT_DONATION_MODULES
) -> tuple[list[Finding], dict]:
    sources = {}
    for rel in modules:
        with open(os.path.join(root, rel)) as f:
            sources[rel] = f.read()
    return lint_donation_sources(sources)
