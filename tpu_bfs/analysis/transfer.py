"""Pass 2: transfer/retrace guard — the hot loops must stay on device,
compiled once per shape.

Four checks, ordered cheapest first:

- **HLO host-op scan** (:func:`check_hlo_host_ops`): the compiled level
  program must contain NO infeed/outfeed/send/recv/host-callback
  instruction — a ``jax.debug.print`` left inside the level loop lowers
  to a host callback custom-call and syncs the mesh to the host every
  level; this catches it from the artifact.
- **transfer-guard drive** (:func:`check_loop_transfer_guard`): the
  warmed loop, invoked with pre-device-put arguments under
  ``jax.transfer_guard("disallow")`` — any implicit host round-trip the
  driver slipped into the per-run path (a ``np.asarray`` on a device
  array, a Python ``int()`` forcing a mid-pipeline pull) raises and
  becomes a finding.
- **trace-count sentinel** (:func:`TraceSentinel`): jit entry points are
  enumerated generically (any engine attribute with a compilation
  cache); after warm-up, re-driving with same-shape inputs must add ZERO
  cache entries — a shape-driven retrace on the serve path means some
  dispatch is not reusing the padded ladder shapes and will pay a
  multi-second compile mid-traffic.
- **lazy-distance contract** (:func:`check_lazy_distances`): a packed
  dispatch+fetch must materialize no distance words and no ecc summary
  until asked — the ``want_distances=false`` serve path depends on the
  fetch half transferring only scalars.
- **adopted-executable sentinel** (:func:`check_adopted_retrace`): the
  trace-count sentinel applied to an AOT-preheated engine (ISSUE 9) —
  the adoption must have actually installed deserialized programs, and
  dispatching through them must add zero jit cache entries.
"""

from __future__ import annotations

from tpu_bfs.analysis import Finding
from tpu_bfs.analysis.hlo import host_transfer_lines


def check_hlo_host_ops(name: str, hlo_text: str) -> list[Finding]:
    out = []
    for hit in host_transfer_lines(hlo_text):
        src = hit["source"] or hit["computation"]
        out.append(Finding(
            "transfer",
            f"{name}:{src}",
            f"compiled hot program contains a host-boundary instruction "
            f"`{hit['op']}` (in {hit['computation']}): every invocation "
            f"(or loop iteration) now syncs device->host. Remove the "
            f"debug callback / host op from the compiled path: "
            f"{hit['line']}",
        ))
    return out


def donation_safe_args(fn, args) -> tuple:
    """A fresh copy of every donated argument of ``fn`` (tagged
    ``_donate_argnums`` — the ISSUE 13 donating cores), so an analyzer
    that invokes the same program twice with one argument tuple does not
    hand deleted buffers to the second call. Device-to-device copies
    only (``Array.copy()`` preserves sharding) — legal under
    ``jax.transfer_guard('disallow')``."""
    donated = getattr(fn, "_donate_argnums", ())
    if not donated:
        return tuple(args)
    import jax

    out = list(args)
    for i in donated:
        if i < len(out):
            out[i] = jax.tree_util.tree_map(
                lambda x: x.copy() if isinstance(x, jax.Array) else x,
                out[i],
            )
    return tuple(out)


def check_loop_transfer_guard(name: str, fn, args) -> list[Finding]:
    """Drive a (warmed) jit entry under ``jax.transfer_guard('disallow')``.
    Arguments must already be on device (the configs pre-put them); the
    warm call outside the guard absorbs compile-time constant placement,
    so anything the guarded call trips on is a genuine per-run
    transfer. Donating programs get a fresh carry per invocation
    (:func:`donation_safe_args`) — the spec's example arguments survive
    for the passes that run after this drive."""
    import jax

    # warm (compile + constant placement) outside the guard
    out = fn(*donation_safe_args(fn, args))
    jax.block_until_ready(out)
    try:
        with jax.transfer_guard("disallow"):
            jax.block_until_ready(fn(*donation_safe_args(fn, args)))
    except Exception as exc:  # noqa: BLE001 — the guard raises RuntimeError-ish
        return [Finding(
            "transfer",
            f"{name}:transfer-guard",
            f"warmed hot-loop invocation performs an implicit host "
            f"transfer per run: {str(exc)[:200]} — pre-place the "
            f"offending operand (jax.device_put) or move the pull out "
            f"of the per-run path.",
        )]
    return []


def jit_entries(obj) -> dict[str, object]:
    """Every jit entry point an engine object holds, found generically:
    any attribute exposing a compilation-cache size (pjit functions do).
    Works for every engine family without per-engine plumbing."""
    out = {}
    for attr, val in vars(obj).items():
        if callable(getattr(val, "_cache_size", None)):
            out[attr] = val
    return out


class TraceSentinel:
    """Per-config trace-count sentinel on jit entry points.

    Snapshot the compilation-cache sizes of every jit entry after warm-up,
    drive the workload again, and fail on any growth: a shape-driven
    recompile on the serving path is a multi-second stall the width
    ladder exists to prevent (every dispatch pads to a resident rung's
    exact shape)."""

    def __init__(self, name: str, *objs):
        self.name = name
        self._entries = {}
        for obj in objs:
            label = type(obj).__name__
            for attr, fn in jit_entries(obj).items():
                self._entries[f"{label}.{attr}"] = fn
        self._baseline: dict[str, int] | None = None

    def snapshot(self) -> None:
        self._baseline = {
            k: fn._cache_size() for k, fn in self._entries.items()
        }

    def check(self) -> list[Finding]:
        assert self._baseline is not None, "snapshot() before check()"
        out = []
        for k, fn in self._entries.items():
            now = fn._cache_size()
            was = self._baseline[k]
            if now > was:
                out.append(Finding(
                    "transfer/retrace",
                    f"{self.name}:{k}",
                    f"jit entry `{k}` retraced under a same-shape "
                    f"re-drive ({was} -> {now} cache entries): some "
                    f"input's shape/dtype/static argument varies per "
                    f"call. Pad to the fixed serving shape (pad_batch) "
                    f"or hoist the varying value out of the traced "
                    f"signature.",
                ))
        return out


def check_engine_retrace(name: str, engine, drive) -> list[Finding]:
    """``drive(engine)`` once to warm every shape, snapshot, drive again
    (callers pass a drive that varies batch FILL but not shape), and fail
    on any new trace."""
    sentinel = TraceSentinel(name, engine)
    drive(engine)
    sentinel.snapshot()
    drive(engine)
    return sentinel.check()


def check_adopted_retrace(name: str, engine, drive) -> list[Finding]:
    """The trace-count sentinel over ADOPTED executables (ISSUE 9): the
    engine must actually hold AOT-installed programs (utils/aot's
    AdoptedProgram wrappers expose ``_cache_size`` exactly like pjit
    entries, so :func:`jit_entries` enumerates them with no extra
    plumbing), and a same-shape re-drive after warm-up must add ZERO
    jit cache entries — deserialized dispatch provably compiles nothing
    new. A preheat whose adoption silently failed (empty ``_aot_adopted``)
    is itself a finding: the service would pay the full JIT cold start
    the artifact store exists to eliminate."""
    adopted = getattr(engine, "_aot_adopted", ())
    if not adopted:
        return [Finding(
            "transfer/retrace",
            f"{name}:aot-adopt",
            "engine holds no AOT-adopted programs — preheat did not "
            "install deserialized executables (missing/stale/corrupt "
            "store, or the engine family lacks export_programs).",
        )]
    return check_engine_retrace(name, engine, drive)


def check_lazy_distances(name: str, engine, sources) -> list[Finding]:
    """Dispatch+fetch must transfer summaries only; the distance planes
    stay on device until ``distances_int32`` (or the u8 path) is called —
    the contract the serve tier's metadata-only queries depend on."""
    out: list[Finding] = []
    pend = engine.dispatch(sources)
    res = engine.fetch(pend)
    if getattr(res, "_word_cache", None):
        out.append(Finding(
            "transfer",
            f"{name}:distance_u8",
            "fetch materialized distance word-columns before any lane "
            "was asked for — the lazy distance_u8 path must transfer "
            "only when materialized (metadata-only serve queries pull "
            "zero distance words).",
        ))
    if getattr(res, "_ecc_cache", None) is not None:
        out.append(Finding(
            "transfer",
            f"{name}:ecc",
            "fetch materialized the lane-ecc summary eagerly — ecc is "
            "a lazy on-demand transfer.",
        ))
    # The lazy path must still WORK: materialize one lane and check the
    # source's own distance decodes to 0.
    d = res.distances_int32(0)
    if int(d[int(sources[0])]) != 0:
        out.append(Finding(
            "transfer",
            f"{name}:distance_u8-decode",
            f"lazy materialization decoded distance "
            f"{int(d[int(sources[0])])} for the source itself "
            f"(expected 0) — the deferred transfer path is corrupting "
            f"results.",
        ))
    return out
