"""Pass 1: collective-uniformity verification (ISSUE 8 tentpole).

The exchange planner (PR 7) and the cap ladder before it make branch
choice a per-level RUNTIME decision inside a `lax.cond` whose arms issue
*different* collective schedules (a delta all-to-all on one arm, the
dense ring on another). On a real mesh that is only safe when every rank
selects the same branch — a divergent selection leaves rank A parked in
an all-to-all that rank B never enters, hanging the whole mesh mid-BFS.
Nothing crashes on the single-host CPU test mesh (XLA emulates all ranks
in one process), so the invariant must be PROVEN, not tested:

- **jaxpr taint analysis** (:func:`analyze_program`): for every traced
  mesh program, every value is tagged with the set of mesh axes over
  which it is provably UNIFORM (identical on all ranks along that axis).
  Sources of uniformity: replicated shard_map inputs, literals/constants,
  full-axis psum/pmax/pmin/all_gather outputs; sinks: `axis_index`,
  sharded inputs. Uniformity propagates through pure ops by set
  intersection, through `while`/`scan` carries by fixed point, and
  through `cond` outputs gated by the predicate's own uniformity. THE
  CHECK: every `cond` whose branches' collective signatures differ, and
  every `while` whose body communicates, must have a predicate uniform
  over every axis those collectives use. Violations name the offending
  equation's source line (the planner scalar that skipped its pmax).
- **compiled-HLO audit** (:func:`check_hlo_conditionals`): the same
  invariant re-checked on the artifact XLA actually emits — every
  ``conditional``'s arms carry an identical ordered collective signature
  (op kind, operand shape, replica grouping, program order) or are
  collective-free; arms that differ are acceptable ONLY when the taint
  pass certified every differing-collective branch point of the same
  program as uniformly selected.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tpu_bfs.analysis import Finding
from tpu_bfs.analysis.hlo import mismatched_conditionals

#: Communication primitives at the jaxpr level. psum2 is psum's
#: post-0.4.30 spelling on some paths; pbroadcast rides shard_map's
#: replication rewrite.
COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_to_all", "all_gather", "reduce_scatter",
}
#: Full-axis reductions whose OUTPUT is definitionally identical on every
#: rank of the reduced axes (when axis_index_groups is None).
_UNIFORMIZING = {"psum", "psum2", "pmax", "pmin", "all_gather"}
#: Collectives whose output is per-rank DIFFERENT even from mesh-uniform
#: inputs: all_to_all hands rank r the r-th chunk of every sender, and
#: reduce_scatter the r-th reduced chunk — their axes must LEAVE the
#: output's uniform set (a scalar derived from either must re-reduce
#: before it may select a branch). ppermute is NOT here: permuting
#: values that are identical along the axis yields identical values, so
#: the plain input-meet is exact for it.
_DIVERGING = {"all_to_all", "reduce_scatter"}
_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr")


def _axes_of(eqn) -> tuple[str, ...]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name"))
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _source_of(eqn) -> str:
    """'collectives.py:702 (planned_sparse_exchange_or)' — the innermost
    user frame of the equation's provenance, so a finding names the exact
    branch-selection site."""
    try:
        from jax._src import source_info_util

        frames = list(source_info_util.user_frames(eqn.source_info))
        if frames:
            fr = frames[0]
            fname = fr.file_name.rsplit("/", 1)[-1]
            return f"{fname}:{fr.start_line} ({fr.function_name})"
    except Exception:  # noqa: BLE001 — provenance is best-effort
        pass
    return "<unknown source>"


def _is_literal(atom) -> bool:
    return hasattr(atom, "val")  # jax.core.Literal ducks; Vars don't


def _inner_jaxpr(obj):
    """Jaxpr of a param that may be a ClosedJaxpr or an open Jaxpr."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _sub_jaxprs(eqn):
    for key in _SUBJAXPR_PARAMS:
        v = eqn.params.get(key)
        if v is not None and hasattr(_inner_jaxpr(v), "eqns"):
            yield _inner_jaxpr(v)


# --- collective signatures at the jaxpr level --------------------------------


def jaxpr_collective_signature(jaxpr, _memo: dict | None = None) -> tuple:
    """Ordered communication schedule of a jaxpr, transitively: one entry
    per collective (primitive, axes, operand avals) in program order, with
    structural markers for branch-/iteration-shaped control flow. Two
    `cond` arms are deadlock-compatible under a divergent predicate iff
    their signatures are equal."""
    if _memo is None:
        _memo = {}
    key = id(jaxpr)
    if key in _memo:
        return _memo[key]
    sig: list = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            avals = tuple(
                str(v.aval) for v in eqn.invars if hasattr(v, "aval")
            )
            sig.append((name, _axes_of(eqn), avals))
        elif name == "cond":
            arms = tuple(
                jaxpr_collective_signature(b.jaxpr, _memo)
                for b in eqn.params["branches"]
            )
            if any(arms):
                sig.append(("cond", arms))
        elif name == "while":
            subs = tuple(
                jaxpr_collective_signature(
                    _inner_jaxpr(eqn.params[k]), _memo
                )
                for k in ("cond_jaxpr", "body_jaxpr")
            )
            if any(subs):
                sig.append(("while", subs))
        elif name == "scan":
            inner = jaxpr_collective_signature(
                _inner_jaxpr(eqn.params["jaxpr"]), _memo
            )
            if inner:
                sig.append(("scan", eqn.params.get("length"), inner))
        else:
            for sub in _sub_jaxprs(eqn):
                sig.extend(jaxpr_collective_signature(sub, _memo))
    _memo[key] = tuple(sig)
    return _memo[key]


def signature_axes(sig) -> frozenset:
    """Every mesh axis a signature communicates over."""
    axes: set = set()

    def walk(s):
        for entry in s:
            if not entry:
                continue
            if entry[0] in COLLECTIVE_PRIMS:
                axes.update(entry[1])
            elif entry[0] in ("cond", "while"):
                for sub in entry[1]:
                    walk(sub)
            elif entry[0] == "scan":
                walk(entry[2])

    walk(sig)
    return frozenset(axes)


# --- the taint analysis ------------------------------------------------------


@dataclasses.dataclass
class UniformityReport:
    program: str
    findings: list[Finding]
    conds_checked: int = 0
    certified_divergent_safe: int = 0  # differing-collective branch points
    #                                    whose predicate proved uniform
    shard_maps: int = 0


class _Taint:
    """Per-var uniform-axis sets over one shard_map body."""

    def __init__(self, full: frozenset):
        self.full = full
        self.env: dict[Any, frozenset] = {}

    def read(self, atom) -> frozenset:
        if _is_literal(atom):
            return self.full
        return self.env.get(atom, self.full)  # trace consts are replicated

    def write(self, var, taint: frozenset) -> None:
        self.env[var] = taint

    def meet_inputs(self, eqn) -> frozenset:
        out = self.full
        for v in eqn.invars:
            out = out & self.read(v)
        return out


def _analyze_body(jaxpr, taint: _Taint, report: UniformityReport,
                  seen: set) -> None:
    """One pass over a (sub)jaxpr propagating uniform-axis sets and
    checking every divergence-sensitive control-flow equation."""
    full = taint.full
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        meet = taint.meet_inputs(eqn)
        outs: list[frozenset] | None = None

        if name == "axis_index":
            outs = [full - set(_axes_of(eqn))]
        elif name in _DIVERGING:
            outs = [meet - set(_axes_of(eqn)) for _ in eqn.outvars]
        elif name in _UNIFORMIZING and eqn.params.get(
            "axis_index_groups"
        ) is None:
            outs = [meet | set(_axes_of(eqn)) for _ in eqn.outvars]
        elif name == "cond":
            outs = _analyze_cond(eqn, taint, report, seen)
        elif name == "while":
            outs = _analyze_while(eqn, taint, report, seen)
        elif name == "scan":
            outs = _analyze_scan(eqn, taint, report, seen)
        elif name == "shard_map":
            # Nested shard_map inside a body — not a shape this repo
            # compiles; treat conservatively as fully divergent.
            outs = [frozenset() for _ in eqn.outvars]
        else:
            subs = list(_sub_jaxprs(eqn))
            if subs and name in ("pjit", "closed_call", "core_call",
                                 "custom_jvp_call", "custom_vjp_call",
                                 "remat2", "checkpoint"):
                sub = subs[0]
                for var, inv in zip(sub.invars, eqn.invars):
                    taint.write(var, taint.read(inv))
                _analyze_body(sub, taint, report, seen)
                outs = [taint.read(v) for v in sub.outvars]
            else:
                outs = [meet for _ in eqn.outvars]

        for var, t in zip(eqn.outvars, outs):
            taint.write(var, t)


def _check_divergence(eqn, pred_taint: frozenset, arm_sigs, report,
                      seen: set, kind: str) -> bool:
    """The core invariant: where collective schedules differ across the
    runtime decision, the deciding scalar must be uniform over every axis
    those collectives use. Returns True when the branch point has
    differing collective arms (certified or not)."""
    distinct = len(set(arm_sigs)) > 1
    has_colls = any(arm_sigs)
    if kind == "while":
        # A while's arms are its iterations: any communication in the body
        # makes trip-count divergence a deadlock.
        differs = has_colls
    else:
        differs = distinct
    if not differs:
        return False
    used = frozenset()
    for s in arm_sigs:
        used = used | signature_axes(s)
    if used <= pred_taint:
        report.certified_divergent_safe += 1
        return True
    where = f"{report.program}:{_source_of(eqn)}"
    if where not in seen:
        seen.add(where)
        missing = sorted(used - pred_taint)
        report.findings.append(Finding(
            "uniformity",
            where,
            f"{kind} selects between collective schedules but its "
            f"selection scalar is NOT mesh-uniform over axis(es) "
            f"{missing}: ranks can take different arms and deadlock the "
            f"mesh mid-level. Route the scalar through a full-axis "
            f"psum/pmax (or loop-carry an already-uniform value) before "
            f"branching.",
        ))
    return True


def _analyze_cond(eqn, taint, report, seen):
    branches = eqn.params["branches"]
    pred_t = taint.read(eqn.invars[0])
    op_taints = [taint.read(v) for v in eqn.invars[1:]]
    sigs = [jaxpr_collective_signature(b.jaxpr) for b in branches]
    report.conds_checked += 1
    _check_divergence(eqn, pred_t, sigs, report, seen, "cond")
    outs = None
    for b in branches:
        sub = b.jaxpr
        for var, t in zip(sub.invars, op_taints):
            taint.write(var, t)
        _analyze_body(sub, taint, report, seen)
        branch_outs = [taint.read(v) for v in sub.outvars]
        outs = branch_outs if outs is None else [
            a & c for a, c in zip(outs, branch_outs)
        ]
    # A divergent predicate makes even identical-schedule arms produce
    # rank-divergent VALUES wherever the arms' outputs differ.
    return [t & pred_t for t in outs]


def _analyze_while(eqn, taint, report, seen):
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_jx = _inner_jaxpr(eqn.params["cond_jaxpr"])
    body_jx = _inner_jaxpr(eqn.params["body_jaxpr"])
    cond_consts = [taint.read(v) for v in eqn.invars[:cn]]
    body_consts = [taint.read(v) for v in eqn.invars[cn:cn + bn]]
    carry = [taint.read(v) for v in eqn.invars[cn + bn:]]
    body_sig = jaxpr_collective_signature(body_jx)
    cond_sig = jaxpr_collective_signature(cond_jx)
    pred_t: frozenset = taint.full
    converged = False
    # Meets only shrink, so the fixed point lands within
    # carries x axes rounds; the hard bound guards pathological shapes —
    # a non-converged walk bottoms out below (sound, never optimistic).
    for _ in range(len(carry) * max(len(taint.full), 1) + 2):
        for var, t in zip(cond_jx.invars, cond_consts + carry):
            taint.write(var, t)
        _analyze_body(cond_jx, taint, report, seen)
        pred_t = taint.read(cond_jx.outvars[0])
        for var, t in zip(body_jx.invars, body_consts + carry):
            taint.write(var, t)
        _analyze_body(body_jx, taint, report, seen)
        new_carry = [
            c & taint.read(v) & pred_t
            for c, v in zip(carry, body_jx.outvars)
        ]
        if new_carry == carry:
            converged = True
            break
        carry = new_carry
    if not converged:
        carry = [frozenset() for _ in carry]
        pred_t = frozenset()
    _check_divergence(eqn, pred_t, (cond_sig, body_sig), report, seen,
                      "while")
    return carry


def _analyze_scan(eqn, taint, report, seen):
    nc = eqn.params["num_consts"]
    ncar = eqn.params["num_carry"]
    jx = _inner_jaxpr(eqn.params["jaxpr"])
    consts = [taint.read(v) for v in eqn.invars[:nc]]
    carry = [taint.read(v) for v in eqn.invars[nc:nc + ncar]]
    xs = [taint.read(v) for v in eqn.invars[nc + ncar:]]
    ys: list[frozenset] = []
    converged = False
    for _ in range(max(ncar, 1) * max(len(taint.full), 1) + 2):
        for var, t in zip(jx.invars, consts + carry + xs):
            taint.write(var, t)
        _analyze_body(jx, taint, report, seen)
        outs = [taint.read(v) for v in jx.outvars]
        new_carry = [c & o for c, o in zip(carry, outs[:ncar])]
        ys = outs[ncar:]
        if new_carry == carry:
            converged = True
            break
        carry = new_carry
    if not converged:
        carry = [frozenset() for _ in carry]
        ys = [frozenset() for _ in ys]
    # Trip count is static — no divergence check needed; a scan cannot
    # run different iteration counts on different ranks.
    return carry + ys


def find_shard_maps(jaxpr):
    """Every shard_map equation reachable from a jaxpr (through pjit /
    control-flow sub-jaxprs)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            yield eqn
        else:
            for key in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
                v = eqn.params.get(key)
                if v is not None and hasattr(_inner_jaxpr(v), "eqns"):
                    yield from find_shard_maps(_inner_jaxpr(v))
            for b in eqn.params.get("branches", ()):
                yield from find_shard_maps(b.jaxpr)


def analyze_jaxpr(name: str, closed) -> UniformityReport:
    """Taint-verify every shard_map region of an already-traced program
    (the runner traces once and shares the jaxpr with the dtype pass)."""
    report = UniformityReport(program=name, findings=[])
    seen: set = set()
    for sm in find_shard_maps(closed.jaxpr):
        report.shard_maps += 1
        full = frozenset(sm.params["mesh"].axis_names)
        body = _inner_jaxpr(sm.params["jaxpr"])
        taint = _Taint(full)
        for var, names in zip(body.invars, sm.params["in_names"]):
            sharded: set = set()
            for axes in names.values():
                sharded.update(axes)
            taint.write(var, full - sharded)
        _analyze_body(body, taint, report, seen)
    return report


def analyze_program(name: str, fn, args) -> UniformityReport:
    """Trace ``fn(*args)`` (no compile) and taint-verify every shard_map
    region found: the jaxpr half of the uniformity pass."""
    import jax

    return analyze_jaxpr(name, jax.make_jaxpr(fn)(*args))


# --- the compiled-HLO half ---------------------------------------------------


def check_hlo_conditionals(
    name: str, hlo_text: str, jaxpr_report: UniformityReport | None
) -> list[Finding]:
    """Audit the compiled artifact: every ``conditional``'s arms must share
    one ordered collective signature or be collective-free. Arms that
    differ are certified ONLY by a clean taint pass over the same program
    that proved at least one uniformly-selected differing-collective
    branch point (the cap ladder / planner case); without that
    certificate each mismatched conditional is a finding."""
    mism = mismatched_conditionals(hlo_text)
    if not mism:
        return []
    certified = (
        jaxpr_report is not None
        and not jaxpr_report.findings
        and jaxpr_report.certified_divergent_safe > 0
    )
    if certified:
        return []
    out = []
    for m in mism:
        where = f"{name}:{m['source'] or m['computation']}"
        arms = ", ".join(
            f"arm{i}={len(s)} collective(s)" for i, s in
            enumerate(m["signatures"])
        )
        out.append(Finding(
            "uniformity/collective-signature",
            where,
            f"conditional arms issue MISMATCHED collective schedules "
            f"({arms}) and no taint certificate proves the predicate "
            f"mesh-uniform — a divergent selection deadlocks the mesh. "
            f"Make the arms' collective schedules identical, keep the "
            f"arms collective-free, or derive the predicate from a "
            f"full-axis reduction.",
        ))
    return out
