"""Command-line entrypoint.

The reference CLI is ``./a.out <srcVertex> <graphfile>`` (README.md:13), whose
main() runs: load graph -> CPU golden BFS -> GPU BFS -> validate -> print
timings (bfs.cu:783-823). This CLI keeps that exact flow and argument order,
with runtime (not compile-time) configuration of device count, algorithm
backend, and exchange — the reference hardwires DeviceNum at compile time
(bfs.cu:19).

Graph sources: a file path, or generator specs ``rmat:scale=20,ef=16,seed=1``
/ ``random:n=100000,m=1000000,seed=12345`` (the capability of readGraph's
generator mode, bfs.cu:892-907).

Usage:
    python -m tpu_bfs.cli 2 graph.txt
    python -m tpu_bfs.cli 0 rmat:scale=18 --devices 1 --stats

Sibling entry points: ``tpu-bfs-serve`` (the query server),
``tpu-bfs-graph500`` (the Graph500 harness), and ``tpu-bfs-analyze``
(static verification of every distributed exchange program + the serve
tier — `make analyze`; run it before any multi-chip session, it proves
the branch-selection uniformity a real mesh deadlocks without).
"""

from __future__ import annotations

import argparse
import sys
import time


def _parse_spec(spec: str):
    kind, _, rest = spec.partition(":")
    kw = {}
    if rest:
        for item in rest.split(","):
            k, _, v = item.partition("=")
            kw[k.strip()] = int(v)
    return kind, kw


def load_graph(spec: str):
    from tpu_bfs.graph import generate, io

    if spec.startswith("rmat:") or spec == "rmat":
        _, kw = _parse_spec(spec)
        return generate.rmat_graph(
            kw.get("scale", 16),
            kw.get("ef", 16),
            seed=kw.get("seed", 1),
            # weights=W attaches the deterministic per-edge weight plane
            # (ISSUE 14: the sssp serving kind needs it).
            weights=kw.get("weights") or None,
        )
    if spec.startswith("random:"):
        _, kw = _parse_spec(spec)
        return generate.random_graph(
            kw.get("n", 1024), kw.get("m", 8192), seed=kw.get("seed", 12345),
            weights=kw.get("weights") or None,
        )
    if spec == "-":
        return io.read_stdin()
    return io.load_edge_list(spec)


def _maybe_profile(profile_dir):
    """jax.profiler trace context, or a no-op when no dir is given."""
    import contextlib

    if not profile_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(profile_dir)


def _arm_obs(args):
    """Arm the telemetry recorder (tpu_bfs/obs) for a one-shot run —
    the shared ``--obs``-wins / ``--trace-out``-implies precedence."""
    from tpu_bfs import obs as obs_mod

    rec = obs_mod.arm_for_run(getattr(args, "obs", None),
                              getattr(args, "trace_out", None))
    if rec is not None:
        print(f"[obs] telemetry recorder armed (flight window "
              f"{rec.window_s:.0f}s, dump dir {rec.dump_dir!r})",
              file=sys.stderr)
    return rec


def _finish_obs(args, engine, label: str) -> None:
    """One-shot run epilogue: --stats prints the engine-trace summary
    line, --trace-out writes the Perfetto JSON (recorder span stream +
    the engine's per-level trace as its own track)."""
    import json

    from tpu_bfs import obs as obs_mod

    trace = getattr(engine, "last_run_trace", None)
    if args.stats and trace:
        from tpu_bfs.obs.engine_trace import trace_summary

        # Same stable-prefix-plus-JSON shape as the statsz/recovery
        # lines: grep "^trace " and parse the rest.
        print("trace " + json.dumps(trace_summary(trace, engine)))
    rec = obs_mod.ACTIVE
    if getattr(args, "trace_out", None) and rec is not None:
        from tpu_bfs.obs.exporters import write_perfetto

        write_perfetto(
            rec.snapshot(), args.trace_out, t0=rec.t0,
            level_traces=[(label, trace)] if trace else [],
            meta={"tool": "tpu-bfs-cli", "graph": args.graph},
        )
        print(f"[obs] trace written -> {args.trace_out}", file=sys.stderr)


def _make_ms_engine(args, g, n_sources: int):
    """Select the multi-source engine for --multi-source / --engine.

    Default (no --engine): size to the workload — the 512-lane packed engine
    for small batches (lane tables scale with lane count; 254-level depth
    cap), the hybrid flagship (8192-lane default cap since the round-4
    hardware sweep; auto sizing walks down when the state doesn't fit) once
    the batch is big enough to fill its packed rows. With --devices N the sharded-state distributed
    engines run instead (hybrid flagship by default, '--engine wide' for
    gather-only) — the reference reaches every capability from its one
    binary (README.md:13,22); so does this one.
    """
    engine = args.engine
    planes = args.planes if args.planes is not None else 5
    # --lanes: explicit batch width (w = lanes/32 packed words per row).
    # None -> each engine's own default/auto sizing (single-chip cap 8192
    # since round 4; distributed default 4096 — the scale-26 budget's row
    # width; msbfs_wide/msbfs_hybrid MAX_LANES bounds both). Validated
    # here so flag misuse gets the CLI's clean SystemExit, not an engine
    # traceback (engines apply their own stricter constraints on top, e.g.
    # whole 4096-lane steps for the dense kernel on TPU).
    if args.lanes is not None:
        from tpu_bfs.algorithms.msbfs_wide import MAX_LANES

        if args.lanes % 32 or not (32 <= args.lanes <= MAX_LANES):
            raise SystemExit(
                f"--lanes must be a multiple of 32 in [32, {MAX_LANES}], "
                f"got {args.lanes}"
            )
    lanes_kw = {} if args.lanes is None else {"lanes": args.lanes}
    if args.pull_gate:
        lanes_kw["pull_gate"] = True
    if args.expand_impl != "xla":
        lanes_kw["expand_impl"] = args.expand_impl
    if args.devices > 1 and args.wire_pack:
        # The packed MS engines' wire format is already one bit per
        # (vertex, lane); the flag is accepted for knob uniformity and
        # recorded (a validated no-op — see the engines' docstrings).
        lanes_kw["wire_pack"] = True
    if args.devices > 1 and args.sparse_delta:
        # Sparse row gather: the id stream delta-encodes (ISSUE 7); the
        # lane-word payload is already bit-packed.
        from tpu_bfs.parallel.collectives import DELTA_BITS_DEFAULT

        lanes_kw["delta_bits"] = DELTA_BITS_DEFAULT
    if args.devices > 1:
        if engine == "packed":
            raise SystemExit(
                "--engine packed is single-device; use --engine hybrid or "
                "wide with --devices"
            )
        # The distributed MS engines exchange frontier words by ring
        # collectives: 'dense' (always-full bitmap) or 'sparse' (two-phase
        # queue-style). The single-source-only exchanges map: ring (the
        # default) -> dense; allreduce has no packed analog.
        if args.exchange == "allreduce":
            raise SystemExit(
                "--exchange allreduce applies to single-source --devices "
                "runs; the packed engines exchange 'ring' (dense), "
                "'sparse', or 'sliced' (hybrid)"
            )
        exchange = (
            args.exchange if args.exchange in ("sparse", "sliced") else "dense"
        )
        from tpu_bfs.parallel.dist_bfs import make_mesh

        mesh = make_mesh(args.devices)
        if engine == "wide":
            if exchange == "sliced":
                raise SystemExit(
                    "--exchange sliced is a hybrid-engine layout (ring-"
                    "rotated expansion over dense tiles + pair ELL); use "
                    "--engine hybrid"
                )
            if args.pull_gate:
                raise SystemExit(
                    "--pull-gate on a mesh runs through the distributed "
                    "hybrid engine; drop --engine wide"
                )
            from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine

            return DistWideMsBfsEngine(
                g, mesh, num_planes=planes, exchange=exchange, **lanes_kw
            )
        from tpu_bfs.parallel.dist_msbfs_hybrid import DistHybridMsBfsEngine

        return DistHybridMsBfsEngine(
            g, mesh, num_planes=planes, exchange=exchange, **lanes_kw
        )
    if engine is None:
        engine = "packed" if n_sources <= 512 else "hybrid"
        if engine == "packed" and (args.ckpt or args.resume):
            # Checkpointing needs resumable packed state (wide/hybrid).
            engine = "wide"
        if engine == "packed" and (args.pull_gate or args.expand_impl != "xla"):
            # The gate and the kernel tier live in the wide/hybrid
            # machinery only.
            engine = "hybrid"
    if engine == "packed":
        from tpu_bfs.algorithms.msbfs_packed import PackedMsBfsEngine

        if args.pull_gate:
            raise SystemExit(
                "--pull-gate applies to the wide/hybrid engines (the "
                "512-lane packed engine keeps no settled-mask state); use "
                "--engine wide or hybrid"
            )
        if args.expand_impl != "xla":
            raise SystemExit(
                "--expand-impl pallas applies to the wide/hybrid engines "
                "(the 512-lane packed engine runs no bucketed-ELL pull "
                "loop); use --engine wide or hybrid"
            )
        lanes = (
            args.lanes
            if args.lanes is not None
            else max(32, -(-n_sources // 32) * 32)
        )
        return PackedMsBfsEngine(g, lanes=lanes)
    if args.adaptive_push:
        if g.num_input_edges < 10_000:
            # Measured: 0.35x on a 240-vertex path graph (BENCHMARKS.md
            # "Level-adaptive expansion") — the push pass wins by skipping
            # the full-table scan, and tiny tables cost nothing to scan.
            print(
                f"WARNING: --adaptive-push on a tiny graph "
                f"({g.num_input_edges} edges < 1e4) usually LOSES (0.35x "
                f"measured on a 240-vertex path graph); it pays off when "
                f"light levels skip a large table scan.",
                file=sys.stderr,
                flush=True,
            )
        lanes_kw = dict(lanes_kw, adaptive_push=args.adaptive_push)
    if engine == "wide":
        from tpu_bfs.algorithms.msbfs_wide import WidePackedMsBfsEngine

        return WidePackedMsBfsEngine(g, num_planes=planes, **lanes_kw)
    from tpu_bfs.algorithms.msbfs_hybrid import HybridMsBfsEngine

    return HybridMsBfsEngine(g, num_planes=planes, **lanes_kw)


def _run_multi_source(args, g, golden) -> int:
    """--multi-source path: <source> plus the listed keys, one packed batch."""
    import numpy as np

    from tpu_bfs import validate
    from tpu_bfs.utils.stats import level_stats

    try:
        extra = [int(t) for t in args.multi_source.split(",") if t.strip()]
    except ValueError:
        raise SystemExit(f"--multi-source must be comma-separated ints, got "
                         f"{args.multi_source!r}")
    sources = np.asarray([args.source] + extra)
    resume_st = None
    if args.resume:
        # Packed-batch resume: the checkpoint carries the whole batch's
        # sources; the command-line list is ignored in its favor.
        from tpu_bfs.utils import checkpoint as ck

        try:
            resume_st = ck.load_packed_checkpoint(args.resume)
        except ValueError as exc:
            # e.g. a single-source checkpoint resumed with --multi-source.
            raise SystemExit(f"--resume: {exc}")
        sources = resume_st.sources
        if args.lanes is None:
            # Rebuild the engine at the CHECKPOINT's width, not today's
            # default: the default moved 4096 -> 8192 lanes in round 4,
            # and a width mismatch is (correctly) rejected downstream —
            # without this, resuming a pre-round-4 checkpoint would demand
            # a manual --lanes. An explicit --lanes still wins (and a
            # mismatch still gets the descriptive rejection).
            args.lanes = int(resume_st.frontier.shape[1]) * 32
        print(f"resumed {len(sources)} sources at level {resume_st.level} "
              f"({args.lanes} lanes)")
        if golden is None and not args.skip_cpu:
            from tpu_bfs.reference import bfs_golden

            golden = bfs_golden(g, int(sources[0]))
    bad = sources[(sources < 0) | (sources >= g.num_vertices)]
    if len(bad):
        raise SystemExit(
            f"--multi-source vertices {bad.tolist()} out of range "
            f"[0, {g.num_vertices})"
        )
    from tpu_bfs import obs as obs_mod

    with obs_mod.maybe_span("engine_build", "cli", cat="cli",
                            lanes=args.lanes, engine=args.engine):
        engine = _make_ms_engine(args, g, len(sources))
    aot_store = aot_spec = None
    if args.aot:
        # One-shot AOT (ISSUE 9): adopt this engine's programs from the
        # store when a previous run exported them (the compile-skipping
        # preheat), and export them back after the run either way.
        from tpu_bfs.utils import aot as aot_mod

        def aot_log(msg):
            print(f"[aot] {msg}", file=sys.stderr, flush=True)

        aot_store = aot_mod.ArtifactStore(args.aot, log=aot_log)
        aot_spec = {
            "graph_key": args.graph,
            "engine": type(engine).__name__,
            "lanes": engine.lanes,
            "planes": getattr(engine, "num_planes", 8),
            "pull_gate": bool(getattr(engine, "pull_gate", False)),
            "devices": args.devices,
        }
        adopted = aot_mod.adopt_engine_programs(
            engine, aot_spec, aot_store, log=aot_log
        )
        if not adopted:
            aot_log(f"no adoptable artifacts in {args.aot}; running JIT "
                    f"(the store is populated after this run)")
    res = None
    if args.ckpt or args.resume:
        # Chunked batch traversal with durable packed state
        # (tpu_bfs/utils/checkpoint.py::PackedCheckpoint): resume continues
        # bit-identically to an uninterrupted batch run, and transient
        # device/compile failures mid-run rebuild the engine and resume
        # from the last chunk (utils/recovery.py).
        from tpu_bfs.utils import checkpoint as ck
        from tpu_bfs.utils.recovery import advance_with_recovery

        st = resume_st if resume_st is not None else engine.start(sources)
        save = None
        if args.ckpt:
            def save(c):
                ck.save_packed_checkpoint(args.ckpt, c)
                print(f"checkpoint @ level {c.level} -> {args.ckpt}")
        try:
            engine, st, _ = advance_with_recovery(
                lambda: _make_ms_engine(args, g, len(sources)), st,
                engine=engine,
                levels_per_chunk=max(1, args.ckpt_every) if args.ckpt else None,
                max_level=args.max_levels,
                save=save,
                log=lambda m: print(f"[recovery] {m}"),
            )
        except RuntimeError as exc:
            if "truncated" not in str(exc):
                raise
            raise SystemExit(
                f"{exc}\nhint: restart with --planes 8 (depth 254); a "
                "checkpoint's plane count is fixed at start, so existing "
                "checkpoints from this run cannot be resumed deeper"
            )
        res = engine.finish(st)
    else:
        try:
            for rep in range(max(1, args.repeat)):
                rec = obs_mod.ACTIVE
                if rec is not None:
                    rec.begin("run", "cli", cat="cli", rep=rep,
                              sources=len(sources))
                try:
                    with _maybe_profile(args.profile_dir):
                        res = engine.run(
                            sources,
                            max_levels=args.max_levels if args.max_levels is not None else 254,
                            time_it=True,
                        )
                finally:
                    # finally, not success-path: a handled truncation
                    # must not leave the span dangling in the trace.
                    if rec is not None:
                        rec.end("run", "cli", cat="cli", rep=rep,
                                levels=None if res is None else res.num_levels)
        except RuntimeError as exc:
            if "truncated" not in str(exc):
                raise
            alt = "" if args.devices > 1 else " or --engine packed"
            raise SystemExit(
                f"{exc}\nhint: rerun with --planes 8 (depth 254){alt}"
            )
    if aot_store is not None:
        # Export AFTER the run: the engine is warmed, and an engine
        # rebuilt mid-run by the recovery path still exports its final
        # (serving) programs. Adopted entries re-export their originals.
        from tpu_bfs.utils import aot as aot_mod

        names = aot_mod.export_engine_programs(
            engine, aot_spec, aot_store,
            log=lambda m: print(f"[aot] {m}", file=sys.stderr, flush=True),
        )
        print(f"[aot] exported {len(names)} programs -> {args.aot}",
              file=sys.stderr, flush=True)
    if res.elapsed_s is not None:
        print(f"Elapsed time in milliseconds (device): "
              f"{res.elapsed_s * 1e3:.3f} ({len(sources)} sources)")
    for i, s in enumerate(sources):
        print(f"source {int(s)}: reached {int(res.reached[i])} vertices, "
              f"traversed edges {int(res.edges_traversed[i])}")
    if res.teps:
        print(f"Harmonic-mean GTEPS/source: {res.teps / 1e9:.4f}")
    if args.stats:
        gated_counts = getattr(engine, "last_gate_level_counts", None)
        if gated_counts is not None:
            # Trim the cap-length counter array to the BATCH's level count
            # (not lane 0's eccentricity — level_stats keeps the deeper
            # levels other lanes ran, where the gate skips the most).
            gated_counts = np.asarray(gated_counts)[: res.num_levels + 1]
        stats = level_stats(
            res.distances_int32(0), g.degrees, gated_tiles=gated_counts
        )
        for line in stats.json_lines():
            print(line)
        from tpu_bfs.utils.stats import recovery_stats_line

        rline = recovery_stats_line()
        if rline:
            # Post-hoc incident visibility: retries/rebuilds/OOM degrades
            # that fired this process (utils/recovery.COUNTERS).
            print(rline)
    if args.certify:
        # Oracle-free certificate for the primary lane (see the
        # single-source path); no CPU golden run at any scale. The message
        # is qualified: like the golden path, only lane 0 is checked.
        validate.certify_bfs(
            g, int(sources[0]), res.distances_int32(0), res.parents_int32(0)
        )
        print(f"Output certified (oracle-free, lane 0 of {len(sources)})")
    elif golden is not None:
        validate.check_distances(res.distances_int32(0), golden)
        if not args.no_parents:
            # Also validate the engine-emitted BFS tree for the primary
            # lane — the check the reference could never run on its parent
            # output (bfs.cu:940; checkOutput compares distances only).
            validate.check_parents(
                g, int(sources[0]), res.distances_int32(0),
                res.parents_int32(0),
            )
        print("Output OK")
    if args.save_dist:
        np.save(args.save_dist, np.stack([
            res.distances_int32(i) for i in range(len(sources))
        ]))
    if args.save_parent:
        # Bulk export: the batched device min-key scan when the engine can
        # serve it (one expansion pass per 128 lanes, single-chip or
        # distributed — parent_scan.py), host scatter-min otherwise; peak
        # host memory stays near the one output array either way.
        out = np.empty((len(sources), g.num_vertices), np.int32)
        np.save(args.save_parent, res.parents_into(out))
    _finish_obs(args, engine, type(engine).__name__)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_bfs",
        description="TPU-native distributed BFS (capabilities of Distributed-CUDA-BFS).",
    )
    ap.add_argument("source", type=int, help="source vertex (reference argv[1])")
    ap.add_argument(
        "graph",
        help="graph file path, '-' for stdin, or generator spec "
        "(rmat:scale=20,ef=16 | random:n=...,m=...) (reference argv[2])",
    )
    ap.add_argument("--devices", type=int, default=1,
                    help="device count; >1 uses the distributed engine (default 1)")
    ap.add_argument("--mesh", default=None, metavar="RxC",
                    help="2D mesh shape (e.g. 2x4): uses the 2D edge partition "
                    "engine instead of the 1D vertex partition")
    ap.add_argument("--backend", default="scan",
                    choices=["scan", "segment", "scatter", "delta", "dopt",
                             "tiled"],
                    help="frontier-expansion backend ('dopt' = direction-"
                    "optimizing top-down/bottom-up switch; works single-"
                    "device, --devices N, and --mesh RxC; 'delta' and "
                    "'tiled' are single-device only — 'tiled' adds the "
                    "dense-tile bitset pass, the fastest measured "
                    "single-stream)")
    ap.add_argument("--exchange", default="ring",
                    choices=["ring", "allreduce", "sparse", "sliced"],
                    help="multi-device frontier exchange implementation "
                    "('sparse' = two-phase queue-style id exchange with "
                    "dense-bitmap fallback; 1D --devices meshes). With "
                    "--multi-source, 'ring' maps to the packed engines' "
                    "dense word exchange; 'sliced' (hybrid engine only) is "
                    "the ring-rotation expansion with O(A/P) transients")
    ap.add_argument("--max-levels", type=int, default=None)
    ap.add_argument("--skip-cpu", action="store_true",
                    help="skip the CPU golden run + validation (reference always validates, bfs.cu:798-815)")
    ap.add_argument("--certify", action="store_true",
                    help="validate with the oracle-free BFS certificate "
                    "(two O(E) host passes, validate.certify_bfs) instead "
                    "of the CPU golden rerun — feasible at scales where "
                    "the sequential run is not; implies --skip-cpu")
    ap.add_argument("--no-parents", action="store_true")
    ap.add_argument("--stats", action="store_true", help="print per-level JSON stats")
    ap.add_argument("--repeat", type=int, default=1, help="timed repetitions")
    ap.add_argument("--save-dist", default=None, help="save distances to .npy")
    ap.add_argument("--save-parent", default=None, help="save parents to .npy")
    ap.add_argument("--multi-source", default=None, metavar="V1,V2,...",
                    help="run these sources concurrently with <source> via a "
                    "bit-packed multi-source engine; --devices N shards "
                    "state over the mesh (DistHybrid/DistWide engines)")
    ap.add_argument("--engine", default=None,
                    choices=["hybrid", "wide", "packed"],
                    help="--multi-source engine: 'hybrid' = MXU dense "
                    "tiles + gathers (flagship; 8192-lane default cap), "
                    "'wide' = gather-only (same widths), 'packed' = "
                    "512-lane (254-level depth cap; "
                    "single-device). Default: 'packed' for <=512 sources, "
                    "else 'hybrid'; with --devices N always the sharded "
                    "hybrid unless 'wide' is chosen")
    ap.add_argument("--planes", type=int, default=None, metavar="P",
                    choices=range(1, 9),
                    help="bit-plane count for the wide/hybrid engines; caps "
                    "traversal depth at 2**P levels (default 5)")
    ap.add_argument("--lanes", type=int, default=None, metavar="N",
                    help="packed batch width for --multi-source engines "
                    "(default: engine auto sizing — single-chip cap 8192, "
                    "distributed 4096; wider rows trade proportionally "
                    "more HBM for more concurrent sources. NB on TPU, "
                    "widths below 4096 pad to the same physical tables)")
    ap.add_argument("--wire-pack", action="store_true",
                    help="bit-pack the boolean frontier exchanges to uint32 "
                    "words, 32 vertices/word (experimental, default off "
                    "until chip-measured): 1D --devices ring/allreduce/"
                    "sparse-fallback and both 2D --mesh collectives ship "
                    "1 bit per vertex instead of 1-4 bytes, bit-identical "
                    "results (utils/wirecheck.check_packed_exchange proves "
                    "the byte ratios from the compiled HLO). The "
                    "--multi-source packed engines already exchange "
                    "bit-packed lane words; there the flag is a recorded "
                    "no-op")
    ap.add_argument("--sparse-delta", action="store_true",
                    help="delta-encode the sparse exchange's id buffers "
                    "(ISSUE 7; experimental, default off until "
                    "chip-measured): first-id + fixed-width 8/16-bit "
                    "bit-packed deltas in uint32 words instead of 4-byte "
                    "ids, width picked per level by the same mesh-uniform "
                    "pmax discipline as the cap rungs. Needs --exchange "
                    "sparse on a multi-device run; with --multi-source it "
                    "compresses the sparse row gather's id stream. "
                    "Bit-identical results (fuzz-pinned); "
                    "utils/wirecheck proves the byte ratios from the "
                    "compiled HLO (make wirecheck)")
    ap.add_argument("--sparse-sieve", action="store_true",
                    help="visited sieve for the sparse exchange (ISSUE 7, "
                    "experimental): on high-reuse levels each receiver's "
                    "packed vis chunk ships backward once (1 bit/vertex) "
                    "so senders drop already-visited ids before "
                    "compaction — taken only when the modeled id savings "
                    "beat the transfer's own ~vloc/8 cost. Single-source "
                    "--devices/--mesh runs with --exchange sparse")
    ap.add_argument("--sparse-predict", action="store_true",
                    help="history-predictive exchange selection (ISSUE 7, "
                    "experimental): confidently-dense mid-BFS levels "
                    "(previous biggest above every cap, frontier still "
                    "growing) skip the per-level pmax entirely, "
                    "direction-optimizing style. Single-source "
                    "--devices/--mesh runs with --exchange sparse")
    ap.add_argument("--pull-gate", action="store_true",
                    help="frontier-aware pull expansion (experimental, "
                    "default off): settled rows' bucket blocks, state "
                    "tiles, and (single-source 'tiled') dense-tile passes "
                    "are skipped per level, bit-identical to the plain "
                    "scan. Applies to --multi-source wide/hybrid engines "
                    "(single device or --devices N hybrid) and --backend "
                    "tiled; --stats adds per-level gated_tiles counts")
    ap.add_argument("--expand-impl", default="xla",
                    choices=("xla", "pallas"),
                    help="pull-expansion tier for the packed MS engines "
                    "(default xla): 'xla' keeps the fori-loop gather the "
                    "compiler fuses; 'pallas' runs the fused bucketed-ELL "
                    "kernel (ops/ell_expand) — double-buffered index-slab "
                    "DMA, VMEM-resident accumulator, one HBM write per "
                    "128-row tile per level, settled-mask gating inside "
                    "the kernel under --pull-gate. Bit-identical output; "
                    "--multi-source wide/hybrid engines (single device or "
                    "--devices N)")
    ap.add_argument("--adaptive-push", default=None, metavar="ROWS,DEG",
                    help="experimental level-adaptive expansion for "
                    "--engine wide|hybrid (single device): levels with "
                    "<= ROWS active rows, all with out-degree <= DEG, "
                    "take a push-style pass instead of the full ELL/tile "
                    "scan (BENCHMARKS.md 'Level-adaptive expansion')")
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the timed run here")
    ap.add_argument("--ckpt", default=None, metavar="PATH",
                    help="checkpoint the traversal state to PATH (npz "
                    "format) every --ckpt-every levels (single-source "
                    "modes and single-device --multi-source batches)")
    ap.add_argument("--ckpt-every", type=int, default=4, metavar="N",
                    help="levels per checkpoint chunk (default 4)")
    ap.add_argument("--resume", default=None, metavar="PATH",
                    help="resume a traversal from a checkpoint written by "
                    "--ckpt (overrides <source> with the saved one)")
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    help="arm a deterministic fault-injection schedule "
                    "(chaos testing, tpu_bfs/faults.py), e.g. "
                    "'seed=7:transient@advance:n=1,corrupt_ckpt:n=1'; "
                    "default: the TPU_BFS_FAULTS env var, else disabled. "
                    "Injected faults exercise the real recovery paths; "
                    "--stats surfaces the counters")
    ap.add_argument("--obs", default=None, metavar="SPEC", nargs="?",
                    const="1",
                    help="arm the telemetry recorder (tpu_bfs/obs): span "
                    "tracing, per-level engine traces, and the flight "
                    "recorder. SPEC e.g. 'dump_dir=/tmp/fr,window=60'; "
                    "bare --obs uses defaults; default: the TPU_BFS_OBS "
                    "env var, else disabled. --stats adds the engine "
                    "trace-summary line")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome/Perfetto trace-event JSON of the "
                    "run here (host spans + a per-level engine-trace "
                    "track: frontier count, direction, gated tiles, "
                    "exchange choice, modeled wire bytes; implies --obs)")
    ap.add_argument("--aot", default=None, metavar="DIR",
                    help="AOT artifact store (utils/aot): install this "
                    "run's engine programs from DIR when exported there "
                    "before (skipping trace/lower/compile), and export "
                    "them back after the run — the one-shot analog of "
                    "tpu-bfs-serve --preheat/--export-aot (multi-source "
                    "packed engines; stale/corrupt artifacts fall back "
                    "to JIT)")
    args = ap.parse_args(argv)
    from tpu_bfs import faults as faults_mod

    sched = faults_mod.arm_from_spec_or_env(args.faults)
    if sched is not None:
        print(f"[faults] schedule armed: {sched.to_spec()}", file=sys.stderr)
    recorder = _arm_obs(args)
    if args.aot is not None and not args.multi_source:
        ap.error("--aot pairs with --multi-source (the packed MS engines "
                 "are the AOT-exportable family; single-source engines "
                 "compile in seconds)")
    if args.adaptive_push is not None:
        if (
            args.engine not in ("wide", "hybrid")
            or args.devices > 1
            or not args.multi_source
        ):
            ap.error("--adaptive-push pairs with --multi-source --engine "
                     "wide|hybrid on a single device")
        try:
            r, d = (int(t) for t in args.adaptive_push.split(","))
            if r < 1 or d < 1:
                raise ValueError
        except ValueError:
            ap.error(f"--adaptive-push must be ROWS,DEG positive ints, got "
                     f"{args.adaptive_push!r}")
        args.adaptive_push = (r, d)
    if args.pull_gate and args.adaptive_push is not None:
        ap.error("--pull-gate and --adaptive-push cannot combine (both "
                 "gate the per-level scan; measure them separately)")
    if args.expand_impl != "xla" and not args.multi_source:
        ap.error("--expand-impl pallas fuses the packed MS engines' "
                 "bucketed-ELL pull expansion; pair it with --multi-source "
                 "(single-source backends run no ELL pull loop)")
    if args.pull_gate and not args.multi_source and (
        args.backend != "tiled" or args.mesh or args.devices > 1
    ):
        ap.error("--pull-gate for single-source runs needs --backend "
                 "tiled on a single device (the other single-source "
                 "backends have no tile pass to gate)")
    if (args.mesh or args.devices > 1) and args.backend in ("delta", "tiled"):
        ap.error(f"--backend {args.backend} is single-device only")
    if args.wire_pack and args.devices == 1 and not args.mesh:
        ap.error("--wire-pack packs multi-device exchanges; add --devices N "
                 "or --mesh RxC (a single chip moves nothing over the wire)")
    if args.sparse_delta or args.sparse_sieve or args.sparse_predict:
        if args.devices == 1 and not args.mesh:
            ap.error("--sparse-delta/--sparse-sieve/--sparse-predict reshape "
                     "multi-device exchanges; add --devices N or --mesh RxC")
        if args.exchange != "sparse":
            ap.error("--sparse-delta/--sparse-sieve/--sparse-predict apply "
                     "to the queue-style id exchange; add --exchange sparse")
    if (args.sparse_sieve or args.sparse_predict) and args.multi_source:
        ap.error("--sparse-sieve/--sparse-predict are single-source "
                 "exchange-planner features (1D --devices or --mesh RxC); "
                 "--multi-source row gathers support --sparse-delta only")
    if args.exchange == "sliced" and not (args.multi_source and args.devices > 1):
        ap.error("--exchange sliced is the packed hybrid engine's ring-"
                 "rotation layout; use it with --multi-source --devices N")
    if args.multi_source and args.mesh:
        ap.error("--multi-source shards 1D (row-tile round-robin); pass "
                 "--devices N instead of a 2D mesh")
    if (args.ckpt or args.resume) and args.multi_source and args.engine == "packed":
        ap.error("--ckpt/--resume with --multi-source needs the wide or "
                 "hybrid engine (the 512-lane packed engine keeps no "
                 "resumable state)")
    if (args.ckpt or args.resume) and (args.repeat > 1 or args.profile_dir):
        ap.error("--repeat/--profile-dir do not apply to checkpointed runs")

    import numpy as np

    from tpu_bfs import validate
    from tpu_bfs.algorithms.bfs import BfsEngine

    t0 = time.perf_counter()
    from tpu_bfs import obs as obs_mod

    with obs_mod.maybe_span("graph_load", "cli", cat="cli", graph=args.graph):
        g = load_graph(args.graph)
    print(f"Number of vertices {g.num_vertices}")  # reference prints these (bfs.cu:789-790)
    print(f"Number of edges {g.num_edges}")
    print(f"[load] {time.perf_counter() - t0:.3f}s")
    if not (0 <= args.source < g.num_vertices):
        raise SystemExit(
            f"source {args.source} out of range [0, {g.num_vertices})"
        )

    # On --resume the traversal's source comes from the checkpoint; load it
    # before the golden run so the CPU BFS happens once, for the right source.
    # (Multi-source batches resume from a packed checkpoint inside
    # _run_multi_source instead — their golden is computed there.)
    resume_st = None
    if args.resume and not args.multi_source:
        from tpu_bfs.utils import checkpoint as ck

        try:
            resume_st = ck.load_checkpoint(args.resume)
        except ValueError as exc:
            # e.g. a packed-batch checkpoint resumed without --multi-source.
            raise SystemExit(f"--resume: {exc}")
        print(f"resumed source {resume_st.source} at level {resume_st.level}")

    golden = None
    # A resumed multi-source batch learns its sources from the packed
    # checkpoint; _run_multi_source computes the golden itself.
    if args.certify:
        args.skip_cpu = True  # the certificate replaces the golden rerun
    if not args.skip_cpu and not (args.multi_source and args.resume):
        from tpu_bfs.reference import bfs_golden

        t0 = time.perf_counter()
        golden = bfs_golden(
            g, resume_st.source if resume_st is not None else args.source
        )
        # Reference prints CPU elapsed ms (runCpu, bfs.cu:211-219).
        print(f"Elapsed time in milliseconds (CPU): {(time.perf_counter() - t0) * 1e3:.2f}")

    if args.multi_source:
        return _run_multi_source(args, g, golden)

    def make_engine():
        if args.mesh:
            from tpu_bfs.parallel.dist_bfs2d import Dist2DBfsEngine, make_mesh_2d

            try:
                r, c = (int(t) for t in args.mesh.lower().split("x"))
            except ValueError:
                ap.error(f"--mesh must look like RxC (e.g. 2x4), got {args.mesh!r}")
            from tpu_bfs.parallel.collectives import DELTA_BITS_DEFAULT

            return Dist2DBfsEngine(
                g, make_mesh_2d(r, c), exchange=args.exchange,
                backend=args.backend, wire_pack=args.wire_pack,
                delta_bits=DELTA_BITS_DEFAULT if args.sparse_delta else (),
                sieve=args.sparse_sieve, predict=args.sparse_predict,
            )
        if args.devices > 1:
            from tpu_bfs.parallel.collectives import DELTA_BITS_DEFAULT
            from tpu_bfs.parallel.dist_bfs import DistBfsEngine, make_mesh

            return DistBfsEngine(
                g, make_mesh(args.devices), exchange=args.exchange,
                backend=args.backend, wire_pack=args.wire_pack,
                delta_bits=DELTA_BITS_DEFAULT if args.sparse_delta else (),
                sieve=args.sparse_sieve, predict=args.sparse_predict,
            )
        if args.backend == "tiled":
            from tpu_bfs.algorithms.bfs_tiled import TiledBfsEngine

            return TiledBfsEngine(g, pull_gate=args.pull_gate)
        return BfsEngine(g, backend=args.backend)

    with obs_mod.maybe_span("engine_build", "cli", cat="cli",
                            backend=args.backend, devices=args.devices):
        engine = make_engine()

    if args.ckpt or args.resume:
        # Chunked traversal with durable state (tpu_bfs/utils/checkpoint.py):
        # resume continues bit-identically to an uninterrupted run, and a
        # transient device/compile failure mid-run rebuilds the engine and
        # resumes from the last chunk (utils/recovery.py — the reference's
        # failed rank instead hangs the MPI_Allreduce, bfs_mpi.cu:621).
        from tpu_bfs.utils import checkpoint as ck
        from tpu_bfs.utils.recovery import advance_with_recovery

        st = resume_st if resume_st is not None else engine.start(args.source)
        save = None
        if args.ckpt:
            def save(c):
                ck.save_checkpoint(args.ckpt, c)
                print(f"checkpointed at level {c.level}")
        engine, st, _ = advance_with_recovery(
            make_engine, st, engine=engine,
            levels_per_chunk=max(1, args.ckpt_every) if args.ckpt else None,
            max_level=args.max_levels,
            save=save,
            log=lambda m: print(f"[recovery] {m}"),
        )
        res = engine.finish(st, with_parents=not args.no_parents)
    else:
        res = None
        for rep in range(max(1, args.repeat)):
            if recorder is not None:
                recorder.begin("run", "cli", cat="cli", source=args.source,
                               rep=rep)
            try:
                with _maybe_profile(args.profile_dir):
                    res = engine.run(
                        args.source,
                        max_levels=args.max_levels,
                        with_parents=not args.no_parents,
                        time_it=True,
                    )
            finally:
                if recorder is not None:
                    recorder.end(
                        "run", "cli", cat="cli", rep=rep,
                        levels=None if res is None else res.num_levels,
                        reached=None if res is None else res.reached,
                    )
            # Reference prints device elapsed ms (bfs.cu:624-626).
            print(f"Elapsed time in milliseconds (device): {res.elapsed_s * 1e3:.3f}")
    if res.teps:
        print(f"Traversed edges: {res.edges_traversed}  GTEPS: {res.teps / 1e9:.4f}")
    print(f"Reached {res.reached} vertices in {res.num_levels} levels")
    skipped = getattr(engine, "last_gate_skipped_tiles", None)
    if skipped is not None:
        print(f"Pull gate skipped {skipped} dense-tile passes")

    if args.stats:
        from tpu_bfs.utils.stats import level_stats, recovery_stats_line

        for line in level_stats(res.distance, g.degrees).json_lines():
            print(line)
        rline = recovery_stats_line()
        if rline:
            # Retry/OOM-degrade counters, when any fired (post-hoc
            # visibility for checkpointed runs' recovery loops).
            print(rline)

    if args.certify:
        # Oracle-free certificate: parent chains + edge-level property
        # prove the distances exactly (validate.certify_bfs) with two
        # O(E) passes — no sequential rerun, so it works at scales the
        # reference's self-validation (bfs.cu:798-815) can never reach.
        parent = (
            res.parent
            if res.parent is not None
            else validate.min_parent_from_dist(g, res.source, res.distance)
        )
        validate.certify_bfs(g, res.source, res.distance, parent)
        print("Output certified (oracle-free)")
    elif golden is not None:
        # checkOutput analog (bfs.cu:374-384) — but also validates parents,
        # which the reference never does.
        validate.check_distances(res.distance, golden)
        if res.parent is not None:
            validate.check_parents(g, res.source, res.distance, res.parent)
        print("Output OK")

    if args.save_dist:
        np.save(args.save_dist, res.distance)
    if args.save_parent and res.parent is not None:
        np.save(args.save_parent, res.parent)
    _finish_obs(args, engine, type(engine).__name__)
    return 0


if __name__ == "__main__":
    sys.exit(main())
