"""Deterministic fault injection: one auditable mechanism for every
recovery path in the stack.

The reference has no failure story at all — a failed rank hangs the
MPI_Allreduce (bfs_mpi.cu:621) and the traversal is lost. This repo's
recovery machinery (transient classifier in utils/recovery.py, the serve
OOM width ladder, checkpoint/resume) used to be exercised only by ad-hoc
monkeypatch spies scattered across tests. This module replaces those
with a seeded, replayable :class:`FaultSchedule` armed process-wide and
consulted at NAMED INJECTION SITES inside the production code itself:

========== =======================================================
site        where it lives
========== =======================================================
dispatch    _packed_common.dispatch_packed_batch (engine level loop)
fetch       _packed_common.fetch_packed_batch (blocking result half)
serve_batch serve/executor.BatchExecutor.dispatch_batch (any engine)
engine_build serve/registry.EngineRegistry._build
ckpt_save   utils/checkpoint._atomic_savez (corruption happens here)
ckpt_load   utils/checkpoint load paths
advance     utils/recovery.advance_with_recovery (chunk step)
aot_load    utils/aot.ArtifactStore payload read (AOT preheat path)
sssp_dispatch workloads/sssp.SsspEngine.dispatch (weighted workload)
sssp_fetch  workloads/sssp.SsspEngine.fetch (blocking result half)
audit_structural integrity/structural.StructuralAuditor.audit
audit_shadow integrity/shadow.ShadowAuditor replay (background)
cache_lookup serve/answercache.AnswerCache.get (hit verification)
generation_flip serve/frontend.BfsService.apply_edge_updates (overlay swap)
compact     graph/dynamic.DynamicGraph.compact (fold into new generation)
========== =======================================================

Production code never pays for this when disabled: every site guard is
one module-attribute check (``if faults.ACTIVE is not None``) against a
global that is ``None`` unless a schedule was explicitly armed via
``--faults`` (CLI and serve), the ``TPU_BFS_FAULTS`` env var, or
:func:`arm` in tests.

Spec grammar (``--faults`` / ``TPU_BFS_FAULTS``)::

    spec    := [ "seed=" INT ":" ] clause ("," clause)*
    clause  := kind ( "@" target )* ( ":" param )*
    target  := SITE                 (e.g. "@fetch")
             | QUAL "=" INT         (e.g. "@rung=512" — context match)
               (targets compose: at most one site + any qualifiers,
                e.g. "oom@fetch@rung=64")
    param   := "p=" FLOAT | "n=" INT | "ms=" FLOAT | "skip=" INT
    kind    := "transient" | "oom" | "slow" | "slow_extract"
             | "corrupt_ckpt" | "corrupt_aot"
             | "corrupt_result" | "corrupt_wire"
             | "stale_cache" | "corrupt_cache_entry"
             | "torn_flip" | "corrupt_overlay" | "compaction_crash"
             | "device_lost" | "collective_hang" | "backend_restart"

Examples::

    seed=7:transient@dispatch:p=0.05,oom@rung=512:n=2,slow_extract:ms=200,corrupt_ckpt:n=1
    seed=3:device_lost@rank=3:n=1,backend_restart@probe:n=1

MESH FAULT KINDS (ISSUE 12): ``device_lost`` / ``collective_hang`` /
``backend_restart`` raise with the REAL jaxlib mesh-death markers
(``DATA_LOSS``, "Program hung", "slice health") so the shared classifier
(utils/recovery.is_mesh_fault) routes an injection exactly like a live
TPU slice loss — the serve tier then runs its degraded-mesh failover
ladder instead of a plain in-place retry. The ``rank`` qualifier is
RANGE-matched against the site's ``devices`` context (``device_lost@
rank=3`` fires at any mesh site whose mesh CONTAINS rank 3, i.e.
``devices > 3``): losing chip 3 takes down every collective the 8-chip
mesh runs, but a 2-chip mesh never had chip 3 to lose — which is exactly
how a degraded re-dispatch escapes the same injected fault. The
``probe`` site is the mesh health heartbeat (tpu_bfs/resilience/probe);
a mesh kind scheduled there makes the heartbeat report the mesh dead,
which keeps a degraded service from promoting back onto it.

``n`` bounds how many times a clause fires (default 1 when no ``p``
given); ``p`` is a per-visit probability drawn from the schedule's own
seeded RNG, so the same seed over the same visit sequence injects the
same faults — the determinism the chaos soak's bit-identical acceptance
bar rests on. ``rung`` matches the dispatch width (``lanes`` in site
context); ``ms`` is the sleep for the slow kinds; ``skip=K`` passes over
the first K matching site visits — deterministic targeting of "the
(K+1)-th event" (e.g. the final checkpoint save of a run). Injected transients
carry an ``INTERNAL:`` message and injected OOMs a ``RESOURCE_EXHAUSTED``
one, so the ONE classifier the whole repo shares (utils/recovery.py)
routes them exactly like the real thing. Every firing is recorded in
``schedule.events`` and bumps ``RecoveryCounters.faults_injected``.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time

SITES = (
    "dispatch",
    "fetch",
    "serve_batch",
    "engine_build",
    "ckpt_save",
    "ckpt_load",
    "advance",
    "aot_load",
    "probe",
    # ISSUE 14: the SSSP workload engine's dispatch/fetch halves
    # (tpu_bfs/workloads/sssp.py) — the delta-stepping twin of the
    # packed engines' dispatch/fetch sites, so chaos schedules can
    # target the weighted path without touching bfs traffic.
    "sssp_dispatch",
    "sssp_fetch",
    # ISSUE 15: the integrity tier's own consultation points
    # (tpu_bfs/integrity) — chaos schedules targeting the AUDITORS
    # (a transient during a shadow replay, a slow structural kernel)
    # prove the tier degrades to audit errors, never to serving
    # failures or false corruption findings.
    "audit_structural",
    "audit_shadow",
    # ISSUE 18: the answer cache's hit path (serve/answercache.py) —
    # corrupt_cache_entry flips a stored payload byte so the CRC32
    # verification fires (hit degrades to a miss + eviction);
    # stale_cache serves a CRC-valid but WRONG answer so only the
    # sampled shadow audit can catch it (the generation-quarantine
    # drive).
    "cache_lookup",
    # ISSUE 19: the dynamic-graph mutation path. generation_flip is the
    # serve tier's overlay swap (frontend.apply_edge_updates) — torn_flip
    # bumps the generation WITHOUT swapping the engines' overlay tables
    # (the stale serving the staleness auditor must catch), and
    # corrupt_overlay rots the staged tables between CRC computation and
    # device upload (the pre-upload verification's red). compact is the
    # compactor's crash window (graph/dynamic.DynamicGraph.compact) —
    # compaction_crash raises AFTER the new generation's files hit disk
    # but BEFORE the CURRENT pointer advances, the exact torn state the
    # rollback guarantee covers.
    "generation_flip",
    "compact",
)

# Where a clause lands when it names no "@site". slow_extract is the
# spec-friendly alias for slowing the blocking result half. The mesh
# kinds default to fetch: async dispatch returns before any collective
# runs, so a real mesh death surfaces at the blocking result half.
DEFAULT_SITE = {
    "transient": "dispatch",
    "oom": "dispatch",
    "slow": "fetch",
    "slow_extract": "fetch",
    "corrupt_ckpt": "ckpt_save",
    "corrupt_aot": "aot_load",
    # ISSUE 15 corruption kinds: seeded bit-flips at the RESULT
    # boundary (corrupt_result flips a just-extracted answer in the
    # serve executor; corrupt_wire flips the audited copy between the
    # two checksum folds) — every integrity detector's red-before-green.
    "corrupt_result": "fetch",
    "corrupt_wire": "fetch",
    # ISSUE 18 cache kinds: in-place mutations of a cache hit, consulted
    # at the answer cache's lookup site only.
    "stale_cache": "cache_lookup",
    "corrupt_cache_entry": "cache_lookup",
    # ISSUE 19 dynamic-graph kinds: torn_flip/corrupt_overlay act in
    # place at the serve flip; compaction_crash raises mid-compaction.
    "torn_flip": "generation_flip",
    "corrupt_overlay": "generation_flip",
    "compaction_crash": "compact",
    "device_lost": "fetch",
    "collective_hang": "fetch",
    "backend_restart": "fetch",
}
KINDS = tuple(DEFAULT_SITE)

#: The ISSUE 12 mesh fault kinds: injected errors carry the live jaxlib
#: mesh-death markers (utils/recovery.MESH_FAULT_MARKERS) so detection,
#: degrade, and resume run the exact path a real slice loss takes.
MESH_KINDS = ("device_lost", "collective_hang", "backend_restart")

# Raising kinds produce messages the shared classifier (utils/recovery.py)
# routes like real infrastructure failures; the non-raising kinds act in
# place (sleep / corrupt-after-write).
_RAISING_KINDS = ("transient", "oom", "compaction_crash", *MESH_KINDS)

# Context-qualifier aliases: "rung" reads the site's "lanes" context key
# (the spec grammar talks about ladder rungs; the sites report widths).
_QUAL_ALIASES = {"rung": "lanes"}

# Range-matched qualifiers: "rank=K" matches when the site's mesh
# CONTAINS rank K (ctx devices > K) — a lost chip fails every mesh that
# includes it, while a degraded re-dispatch on a mesh too small to
# include it escapes (the failover ladder's escape hatch).
_QUAL_RANGES = {"rank": "devices"}


@dataclasses.dataclass
class FaultRule:
    """One parsed spec clause plus its runtime budget."""

    kind: str
    site: str
    qual: tuple = ()  # ((ctx_key, int_value), ...) — all must match
    p: float | None = None  # per-visit probability (None = always)
    n: int | None = None  # firing budget (None = unlimited)
    ms: float | None = None  # sleep for slow kinds
    skip: int = 0  # matching visits to pass over before becoming eligible
    remaining: int | None = dataclasses.field(default=None, compare=False)
    fired: int = dataclasses.field(default=0, compare=False)
    visits: int = dataclasses.field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {KINDS})"
            )
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (one of {SITES})"
            )
        if self.kind in ("slow", "slow_extract") and self.ms is None:
            raise ValueError(f"{self.kind} needs an ms= parameter")
        if self.p is not None and not (0.0 <= self.p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {self.p}")
        if self.remaining is None:
            self.remaining = self.n

    def matches(self, site: str, ctx: dict) -> bool:
        """Site + context-qualifier match (budget/skip/probability are the
        schedule's concern — see ``FaultSchedule._select``)."""
        if site != self.site:
            return False
        for key, want in self.qual:
            rng = _QUAL_RANGES.get(key)
            if rng is not None:
                # Range semantics: "rank=K" matches meshes CONTAINING
                # rank K — the injected chip loss follows the chip, not
                # one mesh shape, so a degraded (smaller) mesh escapes.
                got = ctx.get(rng)
                if got is None or int(got) <= want:
                    return False
                continue
            got = ctx.get(_QUAL_ALIASES.get(key, key))
            if got is None or int(got) != want:
                return False
        return True

    def to_clause(self) -> str:
        out = self.kind
        if self.site != DEFAULT_SITE[self.kind]:
            out += f"@{self.site}"
        out += "".join(f"@{k}={v}" for k, v in self.qual)
        if self.p is not None:
            out += f":p={self.p:g}"
        if self.n is not None:
            out += f":n={self.n}"
        if self.ms is not None:
            out += f":ms={self.ms:g}"
        if self.skip:
            out += f":skip={self.skip}"
        return out


def _parse_clause(clause: str) -> FaultRule:
    head, *params = clause.split(":")
    head = head.strip()
    kind, _, target = head.partition("@")
    kind = kind.strip()
    if kind not in KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} in clause {clause!r} "
            f"(one of {KINDS})"
        )
    site = DEFAULT_SITE[kind]
    qual = []
    explicit_site = False
    # "@" targets compose: at most one site plus any context qualifiers
    # (e.g. "oom@fetch@rung=64" — OOM the fetch half of 64-wide batches).
    for tok in target.split("@") if target else ():
        tok = tok.strip()
        if "=" in tok:
            qk, _, qv = tok.partition("=")
            try:
                qual.append((qk.strip(), int(qv)))
            except ValueError:
                raise ValueError(
                    f"qualifier {tok!r} in clause {clause!r} must be "
                    f"name=int"
                ) from None
        elif explicit_site:
            raise ValueError(
                f"clause {clause!r} names two sites ({site!r}, {tok!r})"
            )
        else:
            site = tok
            explicit_site = True
    qual = tuple(qual)
    p = n = ms = None
    skip = 0
    for param in params:
        k, eq, v = param.partition("=")
        k = k.strip()
        if not eq:
            raise ValueError(f"parameter {param!r} in clause {clause!r} "
                             f"must be key=value")
        try:
            if k == "p":
                p = float(v)
            elif k == "n":
                n = int(v)
            elif k == "ms":
                ms = float(v)
            elif k == "skip":
                skip = int(v)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"unknown/invalid parameter {param!r} in clause {clause!r} "
                "(p=FLOAT, n=INT, ms=FLOAT, skip=INT)"
            ) from None
    if p is None and n is None:
        n = 1  # a bare clause fires exactly once — deterministic by default
    return FaultRule(kind=kind, site=site, qual=qual, p=p, n=n, ms=ms,
                     skip=skip)


class FaultSchedule:
    """A seeded set of :class:`FaultRule` consulted at injection sites.

    Thread-safe: the serve scheduler, extraction worker, and client
    threads may all hit sites concurrently; rule budgets and the RNG are
    guarded by one lock. Probability draws consume the schedule's own
    ``random.Random(seed)``, so the injection sequence is a pure function
    of (seed, site-visit sequence)."""

    def __init__(self, rules, *, seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._seq = 0
        self.events: list[dict] = []  # audit log of every firing

    # --- construction -----------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str) -> "FaultSchedule":
        spec = spec.strip()
        if not spec:
            raise ValueError("empty fault spec")
        seed = 0
        if spec.startswith("seed="):
            head, _, rest = spec.partition(":")
            try:
                seed = int(head[len("seed="):])
            except ValueError:
                raise ValueError(f"bad seed in fault spec {spec!r}") from None
            spec = rest
        clauses = [c for c in spec.split(",") if c.strip()]
        if not clauses:
            raise ValueError("fault spec has no clauses")
        return cls([_parse_clause(c) for c in clauses], seed=seed)

    def to_spec(self) -> str:
        """Canonical spec string; ``from_spec(to_spec())`` round-trips."""
        return f"seed={self.seed}:" + ",".join(
            r.to_clause() for r in self.rules
        )

    # --- runtime ----------------------------------------------------------

    def _select(self, site: str, ctx: dict, kinds=None) -> list[FaultRule]:
        """Consume budgets/RNG for matching rules; returns fired rules."""
        fired = []
        with self._lock:
            for rule in self.rules:
                if kinds is not None and rule.kind not in kinds:
                    continue
                if not rule.matches(site, ctx):
                    continue
                rule.visits += 1
                if rule.visits <= rule.skip:
                    continue  # not eligible yet (skip=K targets visit K+1)
                if rule.remaining is not None and rule.remaining <= 0:
                    continue
                if rule.p is not None and self._rng.random() >= rule.p:
                    continue
                rule.fired += 1
                if rule.remaining is not None:
                    rule.remaining -= 1
                self._seq += 1
                self.events.append({
                    "seq": self._seq,
                    "site": site,
                    "kind": rule.kind,
                    "clause": rule.to_clause(),
                    "ctx": {k: v for k, v in ctx.items()},
                })
                fired.append(rule)
                if rule.kind in _RAISING_KINDS:
                    break  # one raise per visit; later rules keep budget
        for rule in fired:
            self._count_injected()
            self._record_obs(site, rule, ctx)
        return fired

    @staticmethod
    def _record_obs(site: str, rule: FaultRule, ctx: dict) -> None:
        # Telemetry cross-link (lazy import, same stdlib-only discipline
        # as _count_injected): when the obs recorder is armed, every
        # firing lands in the span stream — a flight-recorder dump of a
        # chaos incident then names the injected fault's site alongside
        # the spans it broke.
        from tpu_bfs import obs as _obs

        if _obs.ACTIVE is not None:
            _obs.ACTIVE.event(
                "fault_injected", cat="faults", site=site, kind=rule.kind,
                clause=rule.to_clause(), **ctx,
            )

    @staticmethod
    def _count_injected() -> None:
        # Lazy import: recovery counters live under tpu_bfs.utils and this
        # module must stay stdlib-only at import time.
        from tpu_bfs.utils.recovery import COUNTERS

        COUNTERS.bump("faults_injected")

    def hit(self, site: str, **ctx) -> None:
        """Consult the schedule at ``site``. Sleeps for slow rules, then
        raises for at most one transient/oom rule — messages routed by the
        shared classifier exactly like real infrastructure failures."""
        raising = None
        # Only the kinds hit() can act on — in-place kinds (corrupt_ckpt)
        # keep their budget for the dedicated take() consultation.
        kinds = (*_RAISING_KINDS, "slow", "slow_extract")
        for rule in self._select(site, ctx, kinds=kinds):
            if rule.kind in ("slow", "slow_extract"):
                time.sleep((rule.ms or 0.0) / 1e3)
            elif raising is None and rule.kind in _RAISING_KINDS:
                raising = rule
        if raising is None:
            return
        where = f"site={site}" + "".join(
            f" {k}={v}" for k, v in sorted(ctx.items())
        )
        tail = f"({where}, clause {raising.to_clause()!r}) [tpu_bfs.faults]"
        if raising.kind == "transient":
            raise RuntimeError(f"INTERNAL: injected transient fault {tail}")
        if raising.kind == "device_lost":
            # The live jaxlib shape of a chip dropping out of the mesh
            # (the r03/r04 bench outage class): DATA_LOSS status + the
            # restart hint. utils/recovery.is_mesh_fault keys on it.
            raise RuntimeError(
                f"DATA_LOSS: injected device loss — a mesh participant "
                f"disappeared mid-collective; the remaining replicas "
                f"cannot complete the exchange {tail}"
            )
        if raising.kind == "collective_hang":
            raise RuntimeError(
                f"INTERNAL: injected collective hang — Program hung "
                f"(awaiting completion of an all-reduce that a lost "
                f"participant will never join) {tail}"
            )
        if raising.kind == "compaction_crash":
            # The compactor dying mid-fold: new generation files are on
            # disk, CURRENT still points at the old one. INTERNAL so the
            # shared classifier treats it as a crash, not a retryable
            # transient — the caller's contract is rollback, not retry.
            raise RuntimeError(
                f"INTERNAL: injected compactor crash — the compaction "
                f"process died after writing the new generation but "
                f"before the commit pointer advanced {tail}"
            )
        if raising.kind == "backend_restart":
            raise RuntimeError(
                f"UNAVAILABLE: injected backend restart — slice health "
                f"check failed; the TPU runtime is restarting the slice "
                f"{tail}"
            )
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: injected out-of-memory fault {tail}"
        )

    def take(self, site: str, kind: str, **ctx) -> bool:
        """Non-raising consultation for in-place kinds (corrupt_ckpt):
        True when a matching rule fired (budget consumed)."""
        return bool(self._select(site, ctx, kinds=(kind,)))

    def counts(self) -> dict:
        """Fired-count per kind — the statsz/audit summary."""
        with self._lock:
            out: dict = {}
            for rule in self.rules:
                out[rule.kind] = out.get(rule.kind, 0) + rule.fired
            return out

    def exhausted(self) -> bool:
        """True once every bounded rule has spent its budget."""
        with self._lock:
            return all(
                r.remaining is not None and r.remaining <= 0
                for r in self.rules
            )


# --- process-wide arming ---------------------------------------------------

# THE guard production sites check: None (the default) keeps every
# injection site a single attribute test with no further work.
ACTIVE: FaultSchedule | None = None

ENV_VAR = "TPU_BFS_FAULTS"


def arm(schedule: FaultSchedule) -> FaultSchedule:
    global ACTIVE
    ACTIVE = schedule
    return schedule


def arm_from_spec(spec: str) -> FaultSchedule:
    return arm(FaultSchedule.from_spec(spec))


def arm_from_env(env: str = ENV_VAR) -> FaultSchedule | None:
    spec = os.environ.get(env, "").strip()
    return arm_from_spec(spec) if spec else None


def arm_from_spec_or_env(spec: str | None,
                         env: str = ENV_VAR) -> FaultSchedule | None:
    """The entry points' shared precedence: an explicit ``--faults`` spec
    wins over the environment variable; neither set = stay disarmed."""
    return arm_from_spec(spec) if spec else arm_from_env(env)


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def mesh_devices(engine) -> int:
    """Mesh span of an engine (1 when single-chip) — THE ``devices``
    context every mesh fault site reports (rank qualifiers range-match
    on it), and the partition-aware half of the serve breaker key
    (serve/executor.engine_devices delegates here). One definition so
    the rank-qualifier semantics cannot drift between sites."""
    mesh = getattr(engine, "mesh", None)
    return 1 if mesh is None else int(mesh.devices.size)


def corruption_offset(path: str) -> int:
    """A byte offset guaranteed to sit inside REAL payload: the first
    byte of a zip archive's last member's compressed data (checkpoints
    are npz = zip). A flip at an arbitrary offset can land in zip dead
    space — padding, central directory slack — leaving the file
    semantically intact, which would make a corruption drill silently
    vacuous. Falls back to the file midpoint for non-zip files."""
    try:
        import struct
        import zipfile

        with zipfile.ZipFile(path) as z:
            info = z.infolist()[-1]
        with open(path, "rb") as f:
            f.seek(info.header_offset + 26)
            nlen, elen = struct.unpack("<HH", f.read(4))
        return info.header_offset + 30 + nlen + elen
    except Exception:  # noqa: BLE001 — not a zip: best-effort midpoint
        return os.path.getsize(path) // 2


def maybe_corrupt_payload(payload: bytes, **ctx) -> bytes:
    """``aot_load`` site hook for ``corrupt_aot`` rules: flip one byte of
    a just-read artifact payload IN MEMORY, so the load-side CRC check
    fires and the store's quarantine + JIT-fallback arm runs — the
    deterministic chaos drive of the AOT degrade path (the on-disk file
    is quarantined by the store exactly as a genuinely-rotten one would
    be). Returns the (possibly corrupted) payload."""
    sched = ACTIVE
    if sched is None or not sched.take("aot_load", "corrupt_aot", **ctx):
        return payload
    if not payload:
        return b"\x00"  # an empty payload corrupts to a non-empty one
    off = len(payload) // 2
    return payload[:off] + bytes([payload[off] ^ 0xFF]) + payload[off + 1:]


def maybe_corrupt_result(dist, extras, reached, **ctx):
    """``fetch``-site hook for ``corrupt_result`` rules (ISSUE 15): flip
    one low bit of a finite distance of a just-extracted per-query
    answer — or, for table-free kinds, bump the first numeric extras
    field (falling back to the reached count) — so the CLIENT-VISIBLE
    result is wrong by exactly one seeded mutation. The integrity tier's
    detectors (structural tree checks, shadow bit-compare) must then go
    red: this is every auditor's red-before-green drive, and the
    corruption the quarantine path attributes to the serving rung.
    Returns ``(dist, extras, reached, fired)``; the inputs are never
    mutated in place (the distance row is copied before the flip)."""
    sched = ACTIVE
    if sched is None or not sched.take("fetch", "corrupt_result", **ctx):
        return dist, extras, reached, False
    import numpy as np

    from tpu_bfs.graph.csr import INF_DIST

    if dist is not None:
        dist = np.array(dist, copy=True)
        fin = np.flatnonzero(dist != INF_DIST)
        i = int(fin[len(fin) // 2]) if len(fin) else 0
        dist[i] ^= 1
        return dist, extras, reached, True
    if extras:
        extras = dict(extras)
        for key, val in extras.items():
            if isinstance(val, int) and not isinstance(val, bool):
                extras[key] = val + 1
                return dist, extras, reached, True
    return dist, extras, (reached if reached is None else reached + 1), True


def maybe_corrupt_cache_blob(blob: bytes, **ctx) -> tuple[bytes, bool]:
    """``cache_lookup`` site hook for ``corrupt_cache_entry`` rules
    (ISSUE 18): flip one byte of a cache entry's stored payload blob at
    hit time, so the entry's CRC32 verification fires and the hit
    degrades to a miss + eviction — the cache's storage-rot
    red-before-green. Returns ``(blob, fired)``."""
    sched = ACTIVE
    if sched is None or not sched.take("cache_lookup",
                                       "corrupt_cache_entry", **ctx):
        return blob, False
    if not blob:
        return b"\x00", True
    off = len(blob) // 2
    return (blob[:off] + bytes([blob[off] ^ 0xFF]) + blob[off + 1:]), True


def maybe_stale_cache(dist, extras, reached, **ctx):
    """``cache_lookup`` site hook for ``stale_cache`` rules (ISSUE 18):
    mutate a CRC-VALID cache hit the same way ``maybe_corrupt_result``
    mutates a fresh answer — the checksum discipline cannot catch a
    stale-but-intact entry, so this is the drive that proves the sampled
    shadow audit quarantines the cache GENERATION. Returns
    ``(dist, extras, reached, fired)``; inputs are never mutated in
    place."""
    sched = ACTIVE
    if sched is None or not sched.take("cache_lookup", "stale_cache",
                                       **ctx):
        return dist, extras, reached, False
    import numpy as np

    from tpu_bfs.graph.csr import INF_DIST

    if dist is not None:
        dist = np.array(dist, copy=True)
        fin = np.flatnonzero(dist != INF_DIST)
        i = int(fin[len(fin) // 2]) if len(fin) else 0
        dist[i] ^= 1
        return dist, extras, reached, True
    if extras:
        extras = dict(extras)
        for key, val in extras.items():
            if isinstance(val, int) and not isinstance(val, bool):
                extras[key] = val + 1
                return dist, extras, reached, True
    return dist, extras, (reached if reached is None else reached + 1), True


def maybe_corrupt_overlay(tables: dict, **ctx) -> tuple[dict, bool]:
    """``generation_flip`` site hook for ``corrupt_overlay`` rules
    (ISSUE 19): flip one neighbor-slot value of the STAGED overlay
    tables between the host's CRC computation and the device upload, so
    the pre-swap CRC re-verification fires and the serve tier restages
    from host truth instead of swapping a torn table under the compiled
    cores. Returns ``(tables, fired)``; the input dict's arrays are
    never mutated in place (the touched plane is copied)."""
    sched = ACTIVE
    if sched is None or not sched.take("generation_flip",
                                       "corrupt_overlay", **ctx):
        return tables, False
    import numpy as np

    out = dict(tables)
    idx = np.array(out["ov_idx"], copy=True)
    idx.flat[idx.size // 2] ^= 1
    out["ov_idx"] = idx
    return out, True


def maybe_corrupt_file(path: str) -> bool:
    """``ckpt_save`` site hook for ``corrupt_ckpt`` rules: flip one
    payload byte after a completed atomic write, simulating
    storage-level corruption the load-side CRC must catch. True when it
    fired."""
    sched = ACTIVE
    if sched is None or not sched.take("ckpt_save", "corrupt_ckpt",
                                       path=path):
        return False
    off = corruption_offset(path)
    with open(path, "r+b") as f:
        f.seek(off)
        byte = f.read(1) or b"\x00"
        f.seek(off)
        f.write(bytes([byte[0] ^ 0xFF]))
    return True
