from tpu_bfs.graph.csr import Graph, DeviceGraph  # noqa: F401
from tpu_bfs.graph.io import load_edge_list, read_edge_list_text, from_edges  # noqa: F401
from tpu_bfs.graph.generate import random_graph, rmat_graph  # noqa: F401
