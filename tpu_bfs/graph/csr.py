"""Graph representations.

``Graph`` is the host-side CSR graph, the analog of the reference's global ``Graph``
struct (bfs.cu:21-28: ``adjacencyList`` / ``edgesOffset`` / ``edgesSize`` /
``numVertices`` / ``numEdges``) — but immutable, NumPy-backed, and never global.

``DeviceGraph`` is the padded, device-ready form consumed by the JAX/Pallas level
kernels: static shapes (vertex and edge counts rounded up to TPU-friendly
multiples), edge-centric COO view sorted by destination, and a phantom vertex
range absorbing padding. The reference instead replicates raw CSR pointers to
every device (initCuda2, bfs.cu:346-351); here padding/layout is done once on
host so everything downstream is static-shaped for XLA.
"""

from __future__ import annotations

import dataclasses
from functools import cached_property

import numpy as np

# Sentinel for "unreached" distance; reference uses INT_MAX (bfs.cu:404-406).
INF_DIST = np.int32(np.iinfo(np.int32).max)
NO_PARENT = np.int32(-1)

# Pad vertex counts to a multiple of this (TPU lane width x sublanes for int32).
VERTEX_PAD = 1024
EDGE_PAD = 1024


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _lexsort_pairs(
    major: np.ndarray, minor: np.ndarray, n: int, n_minor: int | None = None
) -> np.ndarray:
    """Permutation ordering by (major, minor): native O(E) counting sort when
    built (native/loader.cpp), np.lexsort otherwise. ``n``/``n_minor`` bound
    the key value ranges (both default n); undersized bounds make the native
    path reject and silently fall back to the O(E log E) sort."""
    try:
        from tpu_bfs.utils.native import lexsort_pairs

        perm = lexsort_pairs(major, minor, n, n if n_minor is None else n_minor)
        if perm is not None:
            return perm
    except Exception:
        pass
    return np.lexsort((minor, major))


@dataclasses.dataclass(frozen=True)
class Graph:
    """Host-side CSR graph (0-indexed, directed edge slots).

    An undirected input edge (u, v) is stored as two directed slots, matching
    the reference loader's double-insert (bfs.cu:860-861), so ``num_edges`` is
    2m for an undirected graph with m input edges.
    """

    row_ptr: np.ndarray  # [V+1] int64 — reference: edgesOffset (bfs.cu:24)
    col_idx: np.ndarray  # [E]   int32 — reference: adjacencyList (bfs.cu:23)
    num_input_edges: int  # m as given in the input (before direction doubling)
    undirected: bool = True  # True when edge slots are the double-insert of input edges
    # Optional per-edge-slot weights aligned with col_idx (ISSUE 14: the
    # SSSP workload's plane). int32, >= 1; an undirected double-insert
    # stores the SAME weight on both directed slots of an input edge.
    weights: np.ndarray | None = None

    def __post_init__(self):
        assert self.row_ptr.ndim == 1 and self.col_idx.ndim == 1
        assert self.row_ptr[0] == 0 and self.row_ptr[-1] == len(self.col_idx)
        if self.weights is not None:
            assert self.weights.shape == self.col_idx.shape

    @property
    def num_vertices(self) -> int:
        return len(self.row_ptr) - 1

    @property
    def num_edges(self) -> int:
        """Directed edge slots (reference: numEdges = adjacencyList.size(), bfs.cu:875)."""
        return len(self.col_idx)

    @cached_property
    def degrees(self) -> np.ndarray:
        """Per-vertex out-degree (reference: edgesSize, bfs.cu:25)."""
        return np.diff(self.row_ptr).astype(np.int64)

    @cached_property
    def coo(self) -> tuple[np.ndarray, np.ndarray]:
        """Edge-centric (src, dst) view, row-major (sorted by src)."""
        src = np.repeat(
            np.arange(self.num_vertices, dtype=np.int32), self.degrees
        )
        return src, self.col_idx.astype(np.int32)

    def has_edge(self, u: int, v: int) -> bool:
        lo, hi = self.row_ptr[u], self.row_ptr[u + 1]
        sl = self.col_idx[lo:hi]
        j = np.searchsorted(sl, v)
        if j < len(sl) and sl[j] == v:
            return True
        # Adjacency may be unsorted when built with sort_neighbors=False.
        return bool(np.any(sl == v))

    def to_scipy(self, *, weighted: bool = False):
        import scipy.sparse as sp

        if weighted:
            if self.weights is None:
                raise ValueError("graph has no weights plane")
            data = self.weights.astype(np.int64)
        else:
            data = np.ones(self.num_edges, dtype=np.int8)
        return sp.csr_matrix(
            (data, self.col_idx, self.row_ptr),
            shape=(self.num_vertices, self.num_vertices),
        )


def build_csr(
    src: np.ndarray,
    dst: np.ndarray,
    num_vertices: int,
    *,
    num_input_edges: int | None = None,
    sort_neighbors: bool = True,
    undirected: bool = True,
    weights: np.ndarray | None = None,
) -> Graph:
    """Build a CSR Graph from directed edge slots.

    The reference builds CSR by concatenating per-vertex adjacency vectors
    (readGraphFromFile, bfs.cu:866-872); here it is a vectorized counting sort.
    ``sort_neighbors`` additionally orders each adjacency list, enabling
    O(log d) edge-existence checks in validation. ``weights`` (per directed
    edge slot, aligned with src/dst) ride the same permutation so the
    stored plane stays slot-aligned with ``col_idx``.
    """
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    assert src.shape == dst.shape
    if len(src) and (src.min() < 0 or src.max() >= num_vertices):
        raise ValueError("src vertex id out of range")
    if len(dst) and (dst.min() < 0 or dst.max() >= num_vertices):
        raise ValueError("dst vertex id out of range")
    if weights is not None:
        weights = np.asarray(weights, dtype=np.int32)
        if weights.shape != src.shape:
            raise ValueError(
                f"weights shape {weights.shape} != edge count {src.shape}"
            )
        if len(weights) and weights.min() < 1:
            raise ValueError("edge weights must be >= 1")

    if sort_neighbors:
        order = _lexsort_pairs(src, dst, num_vertices)
    else:
        order = np.argsort(src, kind="stable")
    src_sorted = src[order]
    col_idx = dst[order].astype(np.int32)
    counts = np.bincount(src_sorted, minlength=num_vertices)
    row_ptr = np.zeros(num_vertices + 1, dtype=np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    return Graph(
        row_ptr=row_ptr,
        col_idx=col_idx,
        num_input_edges=num_input_edges if num_input_edges is not None else len(src),
        undirected=undirected,
        weights=None if weights is None else weights[order],
    )


@dataclasses.dataclass(frozen=True)
class DeviceGraph:
    """Padded, static-shape, device-ready edge-centric graph.

    - Vertex ids in [num_vertices, vp) are phantoms: no real edge touches them,
      padding edges are phantom->phantom self-loops, and phantoms are never in
      the frontier, so they are inert in every level step.
    - Edges are sorted by (dst, src): destination-major order makes the
      scatter-min in the level step segment-local, which the scan/Pallas
      backends exploit; the min-src tie-break makes parents deterministic
      (unlike the reference's atomic-race winner, bfs.cu:146-147).
    """

    src: np.ndarray  # [ep] int32, dst-major order
    dst: np.ndarray  # [ep] int32, non-decreasing
    num_vertices: int  # real V
    num_edges: int  # real directed edge slots
    num_input_edges: int
    undirected: bool
    vp: int  # padded vertex count (>= V+1, multiple of VERTEX_PAD)
    ep: int  # padded edge count (multiple of EDGE_PAD)
    # CSR-by-destination over the padded arrays: in_row_ptr[v] is the first
    # padded-edge index with dst == v. Used for segment boundaries.
    in_row_ptr: np.ndarray  # [vp+1] int64
    # CSR-by-source over the src-major padded edge order (real edges in
    # (src, dst) order, then phantom padding). Used by the gather-free
    # 'delta' backend to mark frontier rows in edge space.
    out_row_ptr: np.ndarray  # [vp+1] int64
    # perm_ds[i] = src-major position of the i-th dst-major edge; the fixed
    # permutation routing src-order activity bits to dst-order.
    perm_ds: np.ndarray  # [ep] int32

    @classmethod
    def from_graph(cls, g: Graph, *, vertex_pad: int = VERTEX_PAD,
                   edge_pad: int = EDGE_PAD) -> "DeviceGraph":
        v, e = g.num_vertices, g.num_edges
        # Always leave at least one phantom vertex so padding edges have a target.
        vp = _round_up(v + 1, vertex_pad)
        ep = _round_up(max(e, 1), edge_pad)
        src, dst = g.coo  # src-major (CSR) order
        order = _lexsort_pairs(dst, src, v)  # dst-major, src-minor
        src_p = np.full(ep, vp - 1, dtype=np.int32)
        dst_p = np.full(ep, vp - 1, dtype=np.int32)
        src_p[:e] = src[order]
        dst_p[:e] = dst[order]
        counts = np.bincount(dst_p.astype(np.int64), minlength=vp)
        in_row_ptr = np.zeros(vp + 1, dtype=np.int64)
        np.cumsum(counts, out=in_row_ptr[1:])
        # Src-major structures: real edges occupy [0, e) in g.coo order;
        # padding rows belong to the final phantom vertex.
        out_counts = np.bincount(src.astype(np.int64), minlength=vp)
        out_counts[vp - 1] += ep - e  # padding edges
        out_row_ptr = np.zeros(vp + 1, dtype=np.int64)
        np.cumsum(out_counts, out=out_row_ptr[1:])
        perm_ds = np.empty(ep, dtype=np.int32)
        perm_ds[:e] = order
        perm_ds[e:] = np.arange(e, ep)
        return cls(
            src=src_p,
            dst=dst_p,
            num_vertices=v,
            num_edges=e,
            num_input_edges=g.num_input_edges,
            undirected=g.undirected,
            vp=vp,
            ep=ep,
            in_row_ptr=in_row_ptr,
            out_row_ptr=out_row_ptr,
            perm_ds=perm_ds,
        )
