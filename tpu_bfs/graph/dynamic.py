"""Dynamic graphs: streaming edge updates over the frozen ELL base
(ISSUE 19).

Every engine's tiled-ELL tables are immutable — rebuilding them per edge
update would cost an ELL build plus an XLA compile per batch. This
module adds the two-layer representation the serve tier mutates through:

- the **base**: the immutable ELL generation every engine compiled over
  (untouched by updates);
- a **bounded dense delta overlay**: up to ``rows`` mutated rank-rows of
  up to ``kcap`` neighbor slots each, uploaded as fixed-shape device
  tables (``ov_rows``/``ov_idx``/``ov_override`` + the ``ov_w`` weights
  plane for sssp), which the expansion tiers fold in AFTER the base
  expansion: an *augment* row OR's (min's) its added neighbors into the
  base row's output, an *override* row REPLACES the base row's output
  with its full current neighbor list — the only sound encoding of a
  removal, since an OR/min contribution cannot be subtracted.

Fixed shapes are the point: a mutation batch swaps table VALUES under
the engines' already-compiled cores (one atomic ``arrs`` dict rebind,
no recompile, no dispatch stall). The overlay is bounded; when a batch
would exceed it — or touch a vertex the base ranked inactive (no table
row exists to override) — the mutation forces a COMPACTION: the overlay
folds into a new base generation persisted through the PR 4 atomic-save
+ payload-CRC machinery (:class:`GenerationStore`), engines rebuild over
the verified artifact, and the overlay empties. A crash mid-compaction
leaves the previous generation's files and ``CURRENT`` pointer intact;
a corrupt new generation is quarantined ``.corrupt`` at load and
serving rolls back to base + overlay.

``generation`` bumps on EVERY applied mutation batch — it is the serve
tier's cache/landmark invalidation key (answercache keys carry it;
landmark columns recompute on flip), not a compaction counter.
Compaction itself is answer-neutral: it rebases the representation
without changing the graph the queries see.

Correctness contract of the fold (tested bit-identical against a
from-scratch rebuild in tests/test_dynamic.py and the fuzz arm):

- overlay neighbor ids are RANKS of the base ranking (graph/ell.py
  ``rank_vertices`` — a pure function of the base edge set, shared by
  every engine over the same base), all ``< num_active``;
- pad rows carry ``row = act`` (the engines' all-identity sentinel row)
  with override=1 and all-sentinel neighbor slots, so a pad row folds
  to the combine identity and scatters identity back into the sentinel
  row — a self-healing no-op;
- real overlay rows are unique (host-side guarantee), so the scatter's
  only duplicate targets are pad rows writing identical identity values.

Scope (v1): undirected bases, single-chip engines (the wide substrate
bfs/cc/khop ride plus SsspEngine). The mesh generalization follows the
partitioned tiles (Buluç & Madduri, arXiv:1104.4518, stays the overlay
partition reference). ``pull_gate``/``adaptive_push`` do not compose
with an overlay (their push/gate passes would miss overlay edges) and
raise at engine construction.
"""

from __future__ import annotations

import os
import threading
import zlib

import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs
from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import rank_vertices

#: Overlay table keys every folding engine consumes ("or" kinds).
OVERLAY_KEYS = ("ov_rows", "ov_idx", "ov_override")
#: The sssp engine additionally consumes the versioned weights plane
#: (it derives its own light plane ``ov_wl`` from ``ov_w`` and delta).
WEIGHTED_OVERLAY_KEYS = OVERLAY_KEYS + ("ov_w",)

#: Default overlay capacity: (mutated rows, neighbor slots per row).
DEFAULT_CAPACITY = (256, 16)


class OverlayCapacityError(RuntimeError):
    """A mutation batch does not fit the bounded overlay (too many dirty
    rows, a row past ``kcap`` slots, or a base-inactive vertex touched):
    the caller must compact into a new base generation and retry."""


def empty_overlay_tables(capacity, act: int, *, weighted: bool = False):
    """All-pad host tables for an engine built with an overlay but no
    mutations yet: every row targets the sentinel row ``act`` with
    override=1 and all-sentinel slots — the fold computes the combine
    identity and writes it back into the row that is already identity."""
    rows, kcap = int(capacity[0]), int(capacity[1])
    out = {
        "ov_rows": np.full(rows, act, np.int32),
        "ov_idx": np.full((rows, kcap), act, np.int32),
        "ov_override": np.ones(rows, np.int32),
    }
    if weighted:
        # Pad weight 0: the slot gathers the all-INF sentinel row and
        # INF + 0 absorbs under min.
        out["ov_w"] = np.zeros((rows, kcap), np.int32)
    return out


def overlay_crc32(tables: dict) -> int:
    """CRC32 over the staged overlay tables (the PR 4 payload-CRC rule
    applied pre-upload): computed when the host stages a mutation batch,
    re-verified just before the device swap, so a corruption in between
    (the ``corrupt_overlay`` chaos kind, or a real host-memory flip) is
    caught before any engine folds a torn table."""
    crc = 0
    for name in sorted(tables):
        arr = np.ascontiguousarray(tables[name])
        crc = zlib.crc32(
            f"{name}:{arr.dtype.str}:{arr.shape}".encode(), crc
        )
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc


def make_overlay_fold(expand, *, op: str, weights_key: str | None = None):
    """Wrap a bucketed-ELL ``expand(arrs, fw) -> [rows, w]`` (either
    tier — the fold is a jnp epilogue over the expansion output, outside
    any Pallas kernel, exactly like the heavy fold pyramid) with the
    overlay fold:

    - gather the base output at the overlay rows;
    - override rows replace it with the combine identity;
    - fold the overlay neighbor slots (``op='or'``: OR of frontier rows;
      ``op='minplus'``: min of ``dist[nbr] + w`` over the ``weights_key``
      plane);
    - scatter ``combine(current, folded)`` back into the overlay rows.

    Pad rows (sentinel row, override=1, all-sentinel slots) compute the
    identity and write it into the already-identity sentinel row."""
    import jax
    import jax.numpy as jnp

    if op not in ("or", "minplus"):
        raise ValueError(f"op must be 'or' or 'minplus', got {op!r}")
    if op == "minplus" and not weights_key:
        raise ValueError("op='minplus' needs a weights_key plane")

    def folded(arrs, fw):
        base = expand(arrs, fw)
        rows = arrs["ov_rows"]  # [D]
        idx = arrs["ov_idx"]  # [D, ko]
        ovr = arrs["ov_override"].astype(bool)  # [D]
        ko = idx.shape[1]
        if op == "or":
            ident = jnp.zeros((idx.shape[0], base.shape[1]), base.dtype)

            def body(kk, acc):
                return acc | fw[idx[:, kk]]

        else:
            from tpu_bfs.workloads.sssp import INF_W

            wts = arrs[weights_key]  # [D, ko]
            ident = jnp.full(
                (idx.shape[0], base.shape[1]), INF_W, jnp.int32
            )

            def body(kk, acc):
                return jnp.minimum(acc, fw[idx[:, kk]] + wts[:, kk][:, None])

        add = jax.lax.fori_loop(0, ko, body, ident)
        cur = jnp.where(ovr[:, None], ident, base[rows])
        if op == "or":
            merged = cur | add
        else:
            merged = jnp.minimum(cur, add)
        return base.at[rows].set(merged)

    return folded


class DynamicGraph:
    """Host-side truth of a mutating graph: the immutable base plus the
    current overlay rows, with ``apply`` staging bounded device tables
    and ``compact`` folding them into a new persisted base generation.

    Thread-safe: the serve tier applies mutation batches from request
    threads while the staleness auditor materializes oracles; one lock
    guards the host state."""

    def __init__(self, graph: Graph, *, capacity=DEFAULT_CAPACITY,
                 log=None):
        if not graph.undirected:
            raise ValueError(
                "dynamic graphs support undirected bases (v1): the "
                "overlay encodes symmetric row updates; a directed "
                "in-neighbor overlay needs the reverse-CSR plumbing"
            )
        rows, kcap = int(capacity[0]), int(capacity[1])
        if rows < 1 or kcap < 1:
            raise ValueError(
                f"overlay capacity must be >= (1, 1), got {capacity}"
            )
        self.capacity = (rows, kcap)
        self.log = log or (lambda msg: None)
        self.generation = 0
        self.compactions = 0
        self._lock = threading.RLock()
        with self._lock:
            self._set_base(graph)

    # --- base bookkeeping -------------------------------------------------

    def _set_base(self, graph: Graph) -> None:  # requires-lock: _lock
        self.base = graph
        src, dst = graph.coo
        _, self._act, _, self._rank = rank_vertices(
            src, dst, graph.num_vertices
        )
        self._cur: dict = {}  # dirty vertex -> {neighbor: weight}
        self._edges_delta = 0
        self._graph_cache = graph

    def _base_row(self, v: int) -> dict:
        """Canonical base neighbor map of ``v``: parallel slots collapse
        to their minimum weight (combine-idempotent, so the collapsed
        row answers identically under OR and min-plus)."""
        g = self.base
        lo, hi = int(g.row_ptr[v]), int(g.row_ptr[v + 1])
        nbrs = g.col_idx[lo:hi]
        if g.weights is None:
            return {int(n): 1 for n in nbrs}
        row: dict = {}
        wts = g.weights[lo:hi]
        for n, w in zip(nbrs.tolist(), wts.tolist()):
            n = int(n)
            if n not in row or w < row[n]:
                row[n] = int(w)
        return row

    def _row(self, v: int) -> dict:  # requires-lock: _lock
        row = self._cur.get(v)
        if row is None:
            row = self._cur[v] = self._base_row(v)
        return row

    @property
    def weighted(self) -> bool:
        return self.base.weights is not None

    def overlay_rows_used(self) -> int:
        with self._lock:
            return sum(
                1 for v in self._cur if self._cur[v] != self._base_row(v)
            )

    # --- mutation ---------------------------------------------------------

    def apply(self, add=(), remove=()):
        """Apply one mutation batch to the host truth and stage the full
        overlay device tables. ``add`` items are ``(u, v)`` or
        ``(u, v, w)``; ``remove`` items are ``(u, v)`` (all parallel
        slots of the pair go). Adding an existing edge with a new weight
        re-weights it. Returns ``(tables, stats)``; bumps ``generation``
        — the serve flip key. Raises :class:`OverlayCapacityError`
        WITHOUT mutating anything when the batch needs a compaction
        first (the caller compacts and re-applies)."""
        with self._lock:
            staged = self._stage(add, remove)
            tables, used = self._build_tables(staged)
            # Commit only after staging fit: host truth and the staged
            # tables flip together or not at all.
            self._cur = staged
            self.generation += 1
            self._graph_cache = None
            stats = {
                "generation": self.generation,
                "overlay_rows": used,
                "capacity": self.capacity,
            }
            return tables, stats

    def _stage(self, add, remove) -> dict:  # requires-lock: _lock
        n = self.base.num_vertices
        staged = {v: dict(row) for v, row in self._cur.items()}

        def row_of(v):
            row = staged.get(v)
            if row is None:
                row = staged[v] = self._base_row(v)
            return row

        def check_active(v):
            v = int(v)
            if not (0 <= v < n):
                raise ValueError(f"vertex {v} out of range [0, {n})")
            if self._rank[v] >= self._act:
                raise OverlayCapacityError(
                    f"vertex {v} is inactive in the base ranking (no "
                    f"table row to override) — compaction required"
                )
            return v

        for edge in add:
            u, v = check_active(edge[0]), check_active(edge[1])
            w = int(edge[2]) if len(edge) > 2 else 1
            if w < 1:
                raise ValueError(f"edge weight must be >= 1, got {w}")
            if self.weighted:
                if row_of(u).get(v) != w:
                    row_of(u)[v] = w
                    row_of(v)[u] = w
                    self._edges_delta += 1
            else:
                if v not in row_of(u):
                    row_of(u)[v] = 1
                    row_of(v)[u] = 1
                    self._edges_delta += 1
        for edge in remove:
            u, v = check_active(edge[0]), check_active(edge[1])
            if v in row_of(u):
                row_of(u).pop(v, None)
                row_of(v).pop(u, None)
                self._edges_delta -= 1
        # Drop rows that reverted to their base content.
        return {
            v: row for v, row in staged.items()
            if row != self._base_row(v)
        }

    def _build_tables(self, cur: dict):  # requires-lock: _lock
        rows_cap, kcap = self.capacity
        act = self._act
        weighted = self.weighted
        tables = empty_overlay_tables(
            self.capacity, act, weighted=weighted
        )
        used = 0
        for v, row in sorted(cur.items()):
            base_row = self._base_row(v)
            if row == base_row:
                continue
            added = {n: w for n, w in row.items()
                     if base_row.get(n) != w}
            removed = any(n not in row for n in base_row)
            override = removed or any(
                n in base_row and base_row[n] != w
                for n, w in added.items()
            )
            slots = row if override else added
            if len(slots) > kcap:
                raise OverlayCapacityError(
                    f"vertex {v} needs {len(slots)} overlay slots "
                    f"(kcap={kcap}) — compaction required"
                )
            if used >= rows_cap:
                raise OverlayCapacityError(
                    f"mutation set needs more than {rows_cap} overlay "
                    f"rows — compaction required"
                )
            tables["ov_rows"][used] = self._rank[v]
            tables["ov_override"][used] = 1 if override else 0
            for j, (nbr, w) in enumerate(sorted(slots.items())):
                tables["ov_idx"][used, j] = self._rank[nbr]
                if weighted:
                    tables["ov_w"][used, j] = w
            used += 1
        return tables, used

    # --- the from-scratch oracle -----------------------------------------

    def materialize(self) -> Graph:
        """The current graph as an immutable :class:`Graph` — what a
        from-scratch rebuild would serve. The fuzz/oracle bit-identical
        bar compares engine answers against engines built over THIS."""
        with self._lock:
            if self._graph_cache is not None:
                return self._graph_cache
            g = self.base
            src_parts = []
            dst_parts = []
            wts_parts = [] if self.weighted else None
            dirty = set(self._cur)
            # Untouched rows stream straight from the base CSR slots.
            keep = np.ones(len(g.col_idx), dtype=bool)
            for v in dirty:
                keep[int(g.row_ptr[v]):int(g.row_ptr[v + 1])] = False
            row_ids = np.repeat(
                np.arange(g.num_vertices, dtype=np.int64),
                np.diff(g.row_ptr),
            )
            src_parts.append(row_ids[keep])
            dst_parts.append(g.col_idx[keep].astype(np.int64))
            if wts_parts is not None:
                wts_parts.append(g.weights[keep])
            for v in sorted(dirty):
                row = self._cur[v]
                if not row:
                    continue
                nbrs = np.fromiter(sorted(row), dtype=np.int64)
                src_parts.append(np.full(len(nbrs), v, np.int64))
                dst_parts.append(nbrs)
                if wts_parts is not None:
                    wts_parts.append(np.asarray(
                        [row[int(n)] for n in nbrs], np.int32
                    ))
            from tpu_bfs.graph.io import build_csr

            out = build_csr(
                np.concatenate(src_parts),
                np.concatenate(dst_parts),
                g.num_vertices,
                num_input_edges=max(
                    g.num_input_edges + self._edges_delta, 0
                ),
                undirected=True,
                weights=(np.concatenate(wts_parts)
                         if wts_parts is not None else None),
            )
            self._graph_cache = out
            return out

    # --- compaction -------------------------------------------------------

    def compact(self, store: "GenerationStore") -> Graph:
        """Fold the overlay into a new persisted base generation:
        materialize -> atomic CRC save -> load-verified -> adopt as base
        (overlay empties). The ``CURRENT`` pointer only advances after
        the reloaded artifact verified, so every failure mode rolls
        back: a crash (or the raising ``compaction_crash`` chaos kind at
        the ``compact`` site) before the pointer leaves the previous
        generation intact, and a corrupt new generation quarantines
        ``.corrupt`` at load (CorruptCheckpointError) with the pointer
        still on the old files. The caller keeps serving base + overlay
        on any raise. Returns the VERIFIED loaded graph — engines must
        rebuild from the artifact that proved round-trippable, not the
        in-memory twin."""
        with self._lock:
            gen_id = store.next_generation_id()
            g = self.materialize()
            with _obs.maybe_span("compact", f"gen{gen_id}",
                                 cat="graph.dynamic", generation=gen_id):
                path = store.save(gen_id, g)
                if _faults.ACTIVE is not None:
                    # Chaos site (ISSUE 19): compaction_crash raises
                    # HERE — after the new generation's files hit disk,
                    # before CURRENT advances — the exact window a real
                    # compactor crash leaves behind.
                    _faults.ACTIVE.hit("compact", generation=gen_id)
                loaded = store.load(gen_id)  # raises CorruptCheckpointError
                store.set_current(gen_id)
            self.compactions += 1
            self._set_base(loaded)
            self._graph_cache = loaded
            self.log(
                f"compacted into generation artifact {path} "
                f"(gen_id={gen_id}, V={loaded.num_vertices}, "
                f"E={loaded.num_edges})"
            )
            return loaded

    def overlay_tables(self):
        """Re-stage the CURRENT overlay from host truth (no generation
        bump) — the recovery path after a staged-table corruption was
        caught by :func:`overlay_crc32`, and the torn-flip self-heal."""
        with self._lock:
            tables, _used = self._build_tables(self._cur)
            return tables


class GenerationStore:
    """On-disk base generations through the PR 4 checkpoint machinery:
    ``gen_NNNN.npz`` written by ``_atomic_savez`` (tmp + fsync + rename,
    payload CRC embedded, ``ckpt_save`` fault site inside), loaded by
    ``_load_npz_verified`` (decode/CRC failures rename ``.corrupt`` and
    raise), with a ``CURRENT`` pointer file replaced atomically LAST —
    the commit point a crash can only land before."""

    def __init__(self, root: str, *, log=None):
        self.root = root
        self.log = log or (lambda msg: None)
        os.makedirs(root, exist_ok=True)

    def _path(self, gen_id: int) -> str:
        return os.path.join(self.root, f"gen_{gen_id:04d}.npz")

    def next_generation_id(self) -> int:
        cur = self.current()
        return (cur if cur is not None else 0) + 1

    def save(self, gen_id: int, graph: Graph) -> str:
        from tpu_bfs.utils.checkpoint import _atomic_savez

        path = self._path(gen_id)
        arrays = {
            "row_ptr": np.asarray(graph.row_ptr),
            "col_idx": np.asarray(graph.col_idx),
            "meta": np.asarray(
                [graph.num_input_edges, int(graph.undirected)], np.int64
            ),
        }
        if graph.weights is not None:
            arrays["weights"] = np.asarray(graph.weights)
        _atomic_savez(path, **arrays)
        return path

    def load(self, gen_id: int) -> Graph:
        from tpu_bfs.utils.checkpoint import _load_npz_verified

        arrays = _load_npz_verified(self._path(gen_id))
        meta = arrays["meta"]
        return Graph(
            row_ptr=np.asarray(arrays["row_ptr"]),
            col_idx=np.asarray(arrays["col_idx"]),
            num_input_edges=int(meta[0]),
            undirected=bool(meta[1]),
            weights=(np.asarray(arrays["weights"])
                     if "weights" in arrays else None),
        )

    def set_current(self, gen_id: int) -> None:
        """Advance the commit pointer — atomically, and only ever AFTER
        the generation's payload verified (the caller's contract)."""
        tmp = os.path.join(self.root, ".CURRENT.tmp")
        with open(tmp, "w") as f:
            f.write(f"{gen_id}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, "CURRENT"))

    def current(self) -> int | None:
        try:
            with open(os.path.join(self.root, "CURRENT")) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def quarantine_orphans(self) -> list:
        """Crash recovery: a compactor that died after ``save`` but
        before ``set_current`` (the ``compaction_crash`` window) leaves
        generation files NEWER than the commit pointer. They never
        verified round-trippable, so they are renamed ``.corrupt`` (the
        PR 4 quarantine rule) and must never be served; the returned
        paths are what the flight dump names."""
        cur = self.current() or 0
        out = []
        for name in sorted(os.listdir(self.root)):
            if not (name.startswith("gen_") and name.endswith(".npz")):
                continue
            try:
                gen_id = int(name[4:-4])
            except ValueError:
                continue
            if gen_id <= cur:
                continue
            path = os.path.join(self.root, name)
            corrupt = path + ".corrupt"
            try:
                os.replace(path, corrupt)
            except OSError:
                continue
            self.log(
                f"quarantined orphan generation artifact {name} -> "
                f"{corrupt} (newer than the CURRENT pointer: a dead "
                f"compactor's uncommitted write)"
            )
            out.append(corrupt)
        return out
