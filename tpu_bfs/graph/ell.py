"""Degree-sorted bucketed ELL representation of in-neighborhoods.

The reference's frontier expansion walks CSR rows with one CUDA thread per
frontier entry (queueBfs, bfs.cu:134-165) — variable-degree rows are fine
there because threads diverge independently. On TPU, variable-degree rows are
the enemy: every op is a fixed-shape vector op, and a random gather costs
~8ns/index regardless of how few bits it fetches (measured; see
msbfs_packed.py). The layout here makes the per-level work a short, static
sequence of *column* gathers over rectangular tiles plus dense folds:

- Vertices are relabeled by descending in-degree ("rank" order), so vertices
  of similar degree are contiguous and each degree bucket is a contiguous row
  range — bucket outputs concatenate back into a full vertex vector with no
  scatter at all.
- Each light bucket holds rows with in-degree in (k/2, k], padded to k
  columns with a sentinel vertex whose frontier words are always zero.
- Vertices with in-degree > kcap ("heavy") are split into ceil(deg/kcap)
  *virtual rows* of kcap columns each. Virtual-row results are OR-combined
  per vertex by a dense fold pyramid: rows are replayed into a layout where
  each vertex owns an aligned power-of-two run (``fold_pad_map``), the whole
  array is OR-folded pairwise ``fold_steps`` times (dense, gather-free), and
  each heavy vertex's finished value is picked from the pyramid at a static
  position (``heavy_pick``). Two bounded stages replace the reference's
  unbounded per-thread degree loop (bfs.cu:143).

Total padded slots are typically 1.1-1.5x the edge count on power-law graphs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_bfs.graph.csr import Graph, _lexsort_pairs


@dataclasses.dataclass(frozen=True)
class EllBucket:
    """Rows [row_start, row_start + n) in rank order, padded to width k."""

    row_start: int
    n: int
    k: int
    idx: np.ndarray  # [n, k] int32 — rank-space neighbor ids, pad = V


@dataclasses.dataclass(frozen=True)
class EllGraph:
    """Bucketed ELL over in-neighborhoods, in descending-in-degree rank space.

    Rank space: row r corresponds to original vertex ``old_of_new[r]``;
    ``rank[v]`` is the row of original vertex v. Rows [0, num_heavy) are
    heavy (in-degree > kcap); rows [num_nonzero, num_active) have in-degree
    0 but appear as edge sources; rows >= num_active are isolated and get no
    table row at all. The neighbor-id sentinel is ``num_active``: callers
    gather from a frontier table of num_active+1 rows whose last row is
    all-zero. ``fold_pad_map``'s sentinel is ``num_virtual`` (an appended
    all-zero virtual-result row).
    """

    num_vertices: int
    num_edges: int  # directed edge slots represented (== sum of in-degrees)
    undirected: bool  # carried from Graph for TEPS edge accounting
    kcap: int
    num_active: int  # rows 0..num_active are non-isolated; tables stop there
    old_of_new: np.ndarray  # [V] int32
    rank: np.ndarray  # [V] int32
    in_degree: np.ndarray  # [V] int64, original-id order
    num_heavy: int
    num_nonzero: int  # rows with in-degree > 0
    num_virtual: int  # virtual rows (0 when no heavy vertices)
    virtual: EllBucket | None  # [M, kcap] neighbor ids (rank space)
    fold_pad_map: np.ndarray | None  # [M2] int32 into virtual results, pad = M
    heavy_pick: np.ndarray | None  # [H] int32 into the fold pyramid
    fold_steps: int
    light: list[EllBucket]  # rows with 0 < deg <= kcap

    @property
    def total_slots(self) -> int:
        m = 0 if self.virtual is None else self.virtual.idx.size
        return m + sum(b.idx.size for b in self.light)


def pad_gate_blocks(idx_t: np.ndarray, sentinel: int, tile: int = 128) -> np.ndarray:
    """Pad a transposed [k, n] bucket index table to whole ``tile``-row
    blocks ([k, ceil(n/tile)*tile], pad = ``sentinel``) for the pull gate's
    block-compacted expansion (_packed_common.make_gated_fori_expand).
    The sentinel must gather the engine's all-zero frontier row, so a
    processed block's pad columns contribute identity — exactly like the
    in-bucket column pads _ell_fill writes."""
    k, n = idx_t.shape
    nb = max(-(-n // tile), 1)
    out = np.full((k, nb * tile), sentinel, dtype=np.int32)
    out[:, :n] = idx_t
    return out


def gate_forward_map(routing: np.ndarray, out_height: int, num_real: int) -> np.ndarray:
    """Forward form of a bucket routing map for the pull gate.

    ``routing`` maps each table row to its bucket-output position (the
    hybrid's ``inv_perm_ext``; positions >= ``num_real`` are the shared
    zero row). Returns ``fwd`` [out_height] int32 with ``fwd[p]`` = the
    table row whose bucket output is position p, and ``len(routing)``
    (one past the table) at pad/tail positions — callers gather from a
    per-row needed vector extended with one trailing False, so pad rows
    are never "needed"."""
    fwd = np.full(out_height, len(routing), dtype=np.int32)
    pos = routing.astype(np.int64)
    m = pos < num_real
    fwd[pos[m]] = np.flatnonzero(m).astype(np.int32)
    return fwd


def _ell_fill(lens: np.ndarray, flat: np.ndarray, k: int, pad: int) -> np.ndarray:
    """Pack concatenated variable-length rows (lengths ``lens``, data ``flat``)
    into a dense [len(lens), k] matrix padded with ``pad``."""
    n = len(lens)
    out = np.full((n, k), pad, dtype=np.int32)
    if n:
        mask = np.arange(k, dtype=np.int64)[None, :] < lens[:, None]
        out[mask] = flat
    return out


def _flat_positions(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenate ranges [starts[i], starts[i]+lens[i]) into one index array."""
    total = int(lens.sum())
    ends = np.cumsum(lens)
    return (
        starts.repeat(lens)
        + np.arange(total, dtype=np.int64)
        - (ends - lens).repeat(lens)
    )


def _heavy_pick(rp2, pstart, m2: int, fold_steps: int) -> np.ndarray:
    """Pyramid positions of finished heavy rows. The pyramid is the concat of
    fold levels s = 0..fold_steps (level s has m2 >> s rows; level 0 is the
    padded layout itself); vertex h is finished at level log2(rp2[h])."""
    lvl = np.log2(rp2).astype(np.int64)
    lvl_offset = np.zeros(fold_steps + 1, dtype=np.int64)
    off = 0
    for s in range(fold_steps + 1):
        lvl_offset[s] = off
        off += m2 >> s
    return (lvl_offset[lvl] + (pstart >> lvl)).astype(np.int32)


def pad_heavy_shards(hlens_list, flat_list, kcap: int, sentinel: int):
    """Common-shape heavy sections across shards.

    Each shard's heavy rows (``hlens_list[p]``, non-increasing, with
    concatenated neighbor lists ``flat_list[p]``) split into kcap-wide
    virtual rows plus an aligned-power-of-two fold pyramid — the same layout
    as :func:`bucketize_rows` — but every shape is padded to the maximum
    across shards so one jitted program serves all shards under shard_map.
    ``m2`` always includes a padded level-0 slot, so shards with fewer heavy
    rows can pad ``heavy_pick`` safely (a padded pick lands on an all-zero
    pyramid slot; padded output rows are never selected downstream anyway).

    Returns ``(nh, num_virtual, fold_steps, m2, virtual [P, M, kcap],
    fold_pad_map [P, m2], heavy_pick [P, nh])``, or all-zeros/None shapes
    when no shard has heavy rows (``nh == 0``).
    """
    nh = max((len(h) for h in hlens_list), default=0)
    if nh == 0:
        return 0, 0, 0, 0, None, None, None
    r_per_all = [np.maximum(-(-h // kcap), 1) for h in hlens_list]
    num_virtual = max(max((int(r.sum()) for r in r_per_all), default=1), 1)
    rp2_all = [
        1 << np.ceil(np.log2(r)).astype(np.int64)
        if len(r)
        else np.zeros(0, np.int64)
        for r in r_per_all
    ]
    fold_steps = max((int(np.log2(r[0])) for r in rp2_all if len(r)), default=0)
    block = 1 << fold_steps
    m2 = _round_up(max((int(r.sum()) for r in rp2_all), default=0) + 1, block)
    v_parts, f_parts, h_parts = [], [], []
    for hlens, flat, r_per, rp2 in zip(hlens_list, flat_list, r_per_all, rp2_all):
        n_h = len(hlens)
        vlens = np.zeros(num_virtual, dtype=np.int64)
        fpm = np.full(m2, num_virtual, dtype=np.int32)
        hpick = np.zeros(nh, dtype=np.int32)
        if n_h:
            m_p = int(r_per.sum())
            vlens[:m_p] = kcap
            vr_last = np.cumsum(r_per) - 1
            vlens[vr_last] = hlens - kcap * (r_per - 1)
            pstart = np.concatenate([[0], np.cumsum(rp2)[:-1]]).astype(np.int64)
            vr_start = vr_last - r_per + 1
            fpm[_flat_positions(pstart, r_per)] = _flat_positions(
                vr_start, r_per
            ).astype(np.int32)
            hpick[:n_h] = _heavy_pick(rp2, pstart, m2, fold_steps)
        v_parts.append(_ell_fill(vlens, flat, kcap, sentinel))
        f_parts.append(fpm)
        h_parts.append(hpick)
    return (
        nh, num_virtual, fold_steps, m2,
        np.stack(v_parts), np.stack(f_parts), np.stack(h_parts),
    )


@dataclasses.dataclass(frozen=True)
class ShardedEllGraph:
    """Per-shard ELL structures with identical shapes, stackable on a mesh.

    Global rank space is padded to ``num_shards * v_loc`` rows; shard p owns
    rows {r : r % num_shards == p} (round-robin over the degree-sorted order,
    so every shard sees the same degree distribution — the load-balance the
    reference's contiguous ``getDev`` split lacks, bfs.cu:29-32). All bucket
    boundaries are multiples of num_shards, so every shard has the same
    bucket row counts and one jitted program serves all shards under
    shard_map. Neighbor ids are *global* ranks (sentinel = v_pad); shards
    gather from a replicated frontier table of v_pad+1 rows.
    """

    num_vertices: int
    num_edges: int
    undirected: bool
    kcap: int
    num_shards: int
    v_loc: int  # rows per shard; v_pad = num_shards * v_loc
    old_of_new: np.ndarray  # [V] int32
    rank: np.ndarray  # [V] int32
    in_degree: np.ndarray  # [V] int64, original-id order
    heavy_per_shard: int
    num_virtual: int  # shared per-shard virtual row count (max, padded)
    m2: int
    fold_steps: int
    virtual: np.ndarray | None  # [P, M, kcap] int32
    fold_pad_map: np.ndarray | None  # [P, m2] int32
    heavy_pick: np.ndarray | None  # [P, heavy_per_shard] int32
    light: list[tuple[int, np.ndarray]]  # (k, [P, n_k, k] int32)
    tail_rows: int  # zero rows appended per shard

    @property
    def v_pad(self) -> int:
        return self.num_shards * self.v_loc


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def build_ell_sharded(g: Graph, num_shards: int, *, kcap: int = 64) -> ShardedEllGraph:
    """Build per-shard ELL structures for a ``num_shards``-way 1D partition."""
    p_count = num_shards
    v_count = g.num_vertices
    src, dst = g.coo
    order_ds = _lexsort_pairs(dst, src, v_count)
    in_col = src[order_ds]
    in_deg, rank_order, rank = rank_by_in_degree(dst, v_count)

    v_loc = -(-v_count // p_count)
    v_pad = p_count * v_loc

    in_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(in_deg, out=in_rp[1:])
    # Rank-space arrays padded with empty rows.
    lens = np.zeros(v_pad, dtype=np.int64)
    lens[:v_count] = in_deg[rank_order]
    starts = np.zeros(v_pad, dtype=np.int64)
    starts[:v_count] = in_rp[rank_order]
    new_rp = np.zeros(v_pad + 1, dtype=np.int64)
    np.cumsum(lens, out=new_rp[1:])
    e = int(new_rp[-1])
    nbrs = rank[in_col[_flat_positions(starts, lens)]].astype(np.int32)

    num_heavy = int(np.searchsorted(-lens, -kcap, side="left"))
    h_bound = min(_round_up(num_heavy, p_count), v_pad)

    def shard_rows(lo: int, hi: int, p: int) -> np.ndarray:
        return np.arange(lo + p, hi, p_count, dtype=np.int64)

    # --- Heavy section (identical shapes across shards). ---
    virtual = fold_pad_map = heavy_pick = None
    num_virtual = m2 = fold_steps = 0
    heavy_per_shard = h_bound // p_count
    if h_bound:
        hlens_list, flat_list = [], []
        for p in range(p_count):
            rows = shard_rows(0, h_bound, p)
            hlens_list.append(lens[rows])
            flat_list.append(
                nbrs[_flat_positions(starts_of(rows, new_rp), lens[rows])]
            )
        (
            _, num_virtual, fold_steps, m2,
            virtual, fold_pad_map, heavy_pick,
        ) = pad_heavy_shards(hlens_list, flat_list, kcap, v_pad)

    # --- Light ladder with num_shards-aligned global boundaries. ---
    light = []
    prev = h_bound
    k = kcap
    while prev < v_pad and k >= 1:
        lo_deg = k // 2
        hi = int(np.searchsorted(-lens, -(lo_deg + 1), side="right"))
        hi = min(max(_round_up(hi, p_count), prev), v_pad)
        if k == 1:
            # Final bucket absorbs all remaining nonzero rows.
            nz = int(np.searchsorted(-lens, 0, side="left"))
            hi = min(max(_round_up(nz, p_count), prev), v_pad)
        if hi > prev:
            blocks = []
            for p in range(p_count):
                rows = shard_rows(prev, hi, p)
                flat = nbrs[_flat_positions(starts_of(rows, new_rp), lens[rows])]
                blocks.append(_ell_fill(lens[rows], flat, k, v_pad))
            light.append((k, np.stack(blocks)))
            prev = hi
        k //= 2

    return ShardedEllGraph(
        num_vertices=v_count,
        num_edges=e,
        undirected=g.undirected,
        kcap=kcap,
        num_shards=p_count,
        v_loc=v_loc,
        old_of_new=rank_order,
        rank=rank,
        in_degree=in_deg,
        heavy_per_shard=heavy_per_shard,
        num_virtual=num_virtual,
        m2=m2,
        fold_steps=fold_steps,
        virtual=virtual,
        fold_pad_map=fold_pad_map,
        heavy_pick=heavy_pick,
        light=light,
        tail_rows=v_loc - heavy_per_shard - sum(b.shape[1] for _, b in light),
    )


def starts_of(rows: np.ndarray, new_rp: np.ndarray) -> np.ndarray:
    """Flat-neighbor start offsets for the given rank rows."""
    return new_rp[rows]



def rank_by_in_degree(dst: np.ndarray, v_count: int):
    """(in_degree, rank_order, rank) for descending-in-degree relabeling.

    ``kind="stable"`` is load-bearing: every builder must produce the same
    tie-break so cross-engine results stay bit-identical.
    """
    in_deg = np.bincount(dst, minlength=v_count).astype(np.int64)
    rank_order = np.argsort(-in_deg, kind="stable").astype(np.int32)  # new -> old
    rank = np.empty(v_count, dtype=np.int32)
    rank[rank_order] = np.arange(v_count, dtype=np.int32)
    return in_deg, rank_order, rank


def rank_vertices(src: np.ndarray, dst: np.ndarray, v_count: int):
    """(in_degree, num_active, rank_order, rank): active-first relabeling.

    Like :func:`rank_by_in_degree` (descending in-degree, stable), but every
    *active* vertex — one touching any edge as source or destination — ranks
    before every isolated one. Packed engines then allocate frontier /
    visited / plane tables of only ``num_active`` rows: on RMAT graphs
    ~40% of vertices are isolated (measured 40.6% at scale 21, 42.9% at
    scale 22), pure dead weight in every O(V)-row table. For the undirected
    double-insert representation in-degree == degree, so this order equals
    rank_by_in_degree's exactly; it only differs for directed graphs with
    out-only vertices (which must keep a row: their frontier bits are
    gathered as in-neighbors of other rows).
    """
    in_deg = np.bincount(dst, minlength=v_count).astype(np.int64)
    inactive = in_deg == 0
    if len(src):
        inactive &= np.bincount(src, minlength=v_count) == 0
    num_active = v_count - int(inactive.sum())
    # lexsort: primary key last — inactive ascending (actives first), then
    # in-degree descending; stable on ties like rank_by_in_degree.
    rank_order = np.lexsort((-in_deg, inactive)).astype(np.int32)
    rank = np.empty(v_count, dtype=np.int32)
    rank[rank_order] = np.arange(v_count, dtype=np.int32)
    return in_deg, num_active, rank_order, rank


def bucketize_rows(lens: np.ndarray, nbrs: np.ndarray, new_rp: np.ndarray,
                   kcap: int, pad: int):
    """Split degree-sorted rows into the heavy virtual-row + fold-pyramid
    section and the light width ladder.

    ``lens`` must be non-increasing; ``nbrs`` is the concatenated neighbor
    lists in row order with ``new_rp`` boundaries; ``pad`` is the sentinel
    neighbor id for unused slots. Returns ``(num_heavy, num_nonzero,
    num_virtual, fold_steps, virtual, fold_pad_map, heavy_pick, light)`` —
    the bucket structure shared by build_ell, build_ell_sharded's per-shard
    logic, and the hybrid engine's residual split.
    """
    num_heavy = int(np.searchsorted(-lens, -kcap, side="left"))
    num_nonzero = int(np.searchsorted(-lens, 0, side="left"))

    # --- Heavy rows -> virtual rows of exactly kcap columns + fold pyramid. ---
    virtual = None
    fold_pad_map = None
    heavy_pick = None
    fold_steps = 0
    num_virtual = 0
    if num_heavy:
        hlens = lens[:num_heavy]
        r_per = -(-hlens // kcap)  # ceil(deg / kcap), sorted non-increasing
        num_virtual = int(r_per.sum())
        vlens = np.full(num_virtual, kcap, dtype=np.int64)
        vr_last = np.cumsum(r_per) - 1  # last virtual row of each heavy vertex
        vlens[vr_last] = hlens - kcap * (r_per - 1)
        heavy_flat = nbrs[: int(new_rp[num_heavy])]
        virtual = EllBucket(
            row_start=0,
            n=num_virtual,
            k=kcap,
            idx=_ell_fill(vlens, heavy_flat, kcap, pad),
        )
        # Aligned power-of-two layout: vertex h owns rows
        # [pstart[h], pstart[h] + rp2[h]) with rp2 = next_pow2(r_per).
        # Descending powers of two keep every start aligned to its own size.
        rp2 = 1 << np.ceil(np.log2(r_per)).astype(np.int64)
        fold_steps = int(np.log2(rp2[0]))
        m2 = int(rp2.sum())
        m2 = -(-m2 // (1 << fold_steps)) * (1 << fold_steps)
        pstart = np.concatenate([[0], np.cumsum(rp2)[:-1]])
        fold_pad_map = np.full(m2, num_virtual, dtype=np.int32)
        vr_start = vr_last - r_per + 1
        fold_pad_map[_flat_positions(pstart, r_per)] = _flat_positions(
            vr_start, r_per
        ).astype(np.int32)
        heavy_pick = _heavy_pick(rp2, pstart, m2, fold_steps)

    # --- Light buckets: 0 < deg <= kcap, widths kcap, kcap/2, ..., 1. ---
    light: list[EllBucket] = []
    row = num_heavy
    k = kcap
    while row < num_nonzero and k >= 1:
        lo_deg = k // 2  # this bucket: lo_deg < deg <= k
        hi = int(np.searchsorted(-lens, -(lo_deg + 1), side="right"))
        if hi > row:
            sl = slice(row, hi)
            flat = nbrs[int(new_rp[row]) : int(new_rp[hi])]
            light.append(
                EllBucket(
                    row_start=row, n=hi - row, k=k,
                    idx=_ell_fill(lens[sl], flat, k, pad),
                )
            )
            row = hi
        k //= 2

    return (
        num_heavy, num_nonzero, num_virtual, fold_steps,
        virtual, fold_pad_map, heavy_pick, light,
    )


def bucketize_values(lens: np.ndarray, vals: np.ndarray, new_rp: np.ndarray,
                     kcap: int, pad: int):
    """Per-bucket VALUE tables slot-aligned with :func:`bucketize_rows`'s
    idx tables (ISSUE 14: the SSSP weights plane).

    ``vals`` carries one value per edge slot in the same flat order as
    ``nbrs`` (rank-row-major concatenated neighbor lists); the heavy
    virtual-row split and the light width ladder replay bucketize_rows's
    exact slicing, so slot (row, col) of each returned table is the value
    of the neighbor ``idx[row, col]`` names. Unused slots hold ``pad``.
    Returns ``(virtual_vals | None, [light value tables])``."""
    num_heavy = int(np.searchsorted(-lens, -kcap, side="left"))
    num_nonzero = int(np.searchsorted(-lens, 0, side="left"))

    virtual_vals = None
    if num_heavy:
        hlens = lens[:num_heavy]
        r_per = -(-hlens // kcap)
        num_virtual = int(r_per.sum())
        vlens = np.full(num_virtual, kcap, dtype=np.int64)
        vr_last = np.cumsum(r_per) - 1
        vlens[vr_last] = hlens - kcap * (r_per - 1)
        heavy_flat = vals[: int(new_rp[num_heavy])]
        virtual_vals = _ell_fill(vlens, heavy_flat, kcap, pad)

    light_vals: list[np.ndarray] = []
    row = num_heavy
    k = kcap
    while row < num_nonzero and k >= 1:
        lo_deg = k // 2
        hi = int(np.searchsorted(-lens, -(lo_deg + 1), side="right"))
        if hi > row:
            sl = slice(row, hi)
            flat = vals[int(new_rp[row]) : int(new_rp[hi])]
            light_vals.append(_ell_fill(lens[sl], flat, k, pad))
            row = hi
        k //= 2

    return virtual_vals, light_vals


def build_ell_weights(g: Graph, ell: EllGraph, *, pad: int = 0):
    """The per-slot weight tables of ``ell``'s buckets (ISSUE 14).

    ``ell`` must be ``build_ell(g)`` over the same graph, which must
    carry a weights plane. Returns ``(virtual_w | None, [light_w])``:
    each table has exactly the shape of the matching bucket's ``idx``,
    with slot (row, col) holding the weight of the in-edge whose source
    ``idx[row, col]`` names, and ``pad`` in unused slots (pad slots
    gather the engines' all-INF sentinel row, so their weight is inert
    under min-plus)."""
    if g.weights is None:
        raise ValueError("graph has no weights plane (build it with weights=W)")
    v_count = g.num_vertices
    src, dst = g.coo
    order_ds = _lexsort_pairs(dst, src, v_count)
    in_deg = np.bincount(dst, minlength=v_count).astype(np.int64)
    in_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(in_deg, out=in_rp[1:])
    rank_order = ell.old_of_new
    lens = in_deg[rank_order]
    new_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(lens, out=new_rp[1:])
    # Same flat order as build_ell's nbrs: in-edge weights, dst-major,
    # rows replayed in rank order.
    wflat = g.weights[order_ds][_flat_positions(in_rp[rank_order], lens)]
    virtual_w, light_w = bucketize_values(
        lens, wflat, new_rp, ell.kcap, pad
    )
    # Shape pin: the value tables must be slot-aligned with the ell's own
    # buckets or every downstream gather-add is silently wrong.
    if (virtual_w is None) != (ell.virtual is None) or (
        virtual_w is not None and virtual_w.shape != ell.virtual.idx.shape
    ):
        raise AssertionError("weight plane misaligned with ell heavy bucket")
    if len(light_w) != len(ell.light) or any(
        w.shape != b.idx.shape for w, b in zip(light_w, ell.light)
    ):
        raise AssertionError("weight plane misaligned with ell light buckets")
    return virtual_w, light_w


def build_ell(g: Graph, *, kcap: int = 64) -> EllGraph:
    """Build the bucketed in-neighbor ELL from a host CSR graph.

    Rank space is active-first (``rank_vertices``), so the engines' packed
    tables need only ``num_active + 1`` rows (actives + the all-zero
    sentinel row, which doubles as the pad gather target)."""
    v_count = g.num_vertices
    # In-CSR: neighbors-by-destination. For the undirected double-insert
    # representation this equals the out-CSR, but build it generally.
    src, dst = g.coo
    order_ds = _lexsort_pairs(dst, src, v_count)
    in_col = src[order_ds]
    in_deg, num_active, rank_order, rank = rank_vertices(src, dst, v_count)

    # Flatten in-neighbor lists in rank order, neighbor ids mapped to rank space.
    in_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(in_deg, out=in_rp[1:])
    lens = in_deg[rank_order]
    new_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(lens, out=new_rp[1:])
    e = int(new_rp[-1])
    nbrs = rank[in_col[_flat_positions(in_rp[rank_order], lens)]]

    (
        num_heavy, num_nonzero, num_virtual, fold_steps,
        virtual, fold_pad_map, heavy_pick, light,
    ) = bucketize_rows(lens, nbrs, new_rp, kcap, num_active)

    return EllGraph(
        num_vertices=v_count,
        num_edges=e,
        undirected=g.undirected,
        kcap=kcap,
        num_active=num_active,
        old_of_new=rank_order,
        rank=rank,
        in_degree=in_deg,
        num_heavy=num_heavy,
        num_nonzero=num_nonzero,
        num_virtual=num_virtual,
        virtual=virtual,
        fold_pad_map=fold_pad_map,
        heavy_pick=heavy_pick,
        fold_steps=fold_steps,
        light=light,
    )


def build_ell_weights_sharded(g: Graph, sell: ShardedEllGraph, *, pad: int = 0):
    """Per-shard per-slot weight tables aligned with ``sell``'s bucketized
    index slabs (ISSUE 20: the sharded weights plane).

    ``sell`` must be ``build_ell_sharded(g)`` over the same graph, which
    must carry a weights plane. Replays build_ell_sharded's exact slicing
    — same rank order, same num_shards-aligned bucket boundaries, same
    pad_heavy_shards virtual-row layout — with the edge weights as the
    flat payload, so slot (p, row, col) of each returned table is the
    weight of the in-edge whose source ``sell``'s matching idx slot names.
    Unused slots hold ``pad`` (0 by default: pad index slots gather the
    engines' all-INF sentinel row, so their weight is inert under
    min-plus). Returns ``(virtual_w [P, M, kcap] | None, [light_w
    [P, n_k, k]])``, shape-pinned against ``sell``."""
    if g.weights is None:
        raise ValueError("graph has no weights plane (build it with weights=W)")
    p_count = sell.num_shards
    v_count = g.num_vertices
    src, dst = g.coo
    order_ds = _lexsort_pairs(dst, src, v_count)
    in_deg = np.bincount(dst, minlength=v_count).astype(np.int64)
    in_rp = np.zeros(v_count + 1, dtype=np.int64)
    np.cumsum(in_deg, out=in_rp[1:])
    rank_order = sell.old_of_new
    v_pad = sell.v_pad
    kcap = sell.kcap
    lens = np.zeros(v_pad, dtype=np.int64)
    lens[:v_count] = in_deg[rank_order]
    starts = np.zeros(v_pad, dtype=np.int64)
    starts[:v_count] = in_rp[rank_order]
    new_rp = np.zeros(v_pad + 1, dtype=np.int64)
    np.cumsum(lens, out=new_rp[1:])
    wflat = np.asarray(g.weights)[order_ds][
        _flat_positions(starts, lens)
    ].astype(np.int32)

    num_heavy = int(np.searchsorted(-lens, -kcap, side="left"))
    h_bound = min(_round_up(num_heavy, p_count), v_pad)

    def shard_rows(lo: int, hi: int, p: int) -> np.ndarray:
        return np.arange(lo + p, hi, p_count, dtype=np.int64)

    virtual_w = None
    if h_bound:
        hlens_list, flat_list = [], []
        for p in range(p_count):
            rows = shard_rows(0, h_bound, p)
            hlens_list.append(lens[rows])
            flat_list.append(
                wflat[_flat_positions(starts_of(rows, new_rp), lens[rows])]
            )
        # pad_heavy_shards' exact vlens layout, weight payload instead of
        # neighbor ids; the shared (padded) virtual-row count is sell's.
        r_per_all = [np.maximum(-(-h // kcap), 1) for h in hlens_list]
        v_parts = []
        for hlens, flat, r_per in zip(hlens_list, flat_list, r_per_all):
            vlens = np.zeros(sell.num_virtual, dtype=np.int64)
            if len(hlens):
                m_p = int(r_per.sum())
                vlens[:m_p] = kcap
                vr_last = np.cumsum(r_per) - 1
                vlens[vr_last] = hlens - kcap * (r_per - 1)
            v_parts.append(_ell_fill(vlens, flat, kcap, pad))
        virtual_w = np.stack(v_parts)

    light_w: list[np.ndarray] = []
    prev = h_bound
    k = kcap
    while prev < v_pad and k >= 1:
        lo_deg = k // 2
        hi = int(np.searchsorted(-lens, -(lo_deg + 1), side="right"))
        hi = min(max(_round_up(hi, p_count), prev), v_pad)
        if k == 1:
            nz = int(np.searchsorted(-lens, 0, side="left"))
            hi = min(max(_round_up(nz, p_count), prev), v_pad)
        if hi > prev:
            blocks = []
            for p in range(p_count):
                rows = shard_rows(prev, hi, p)
                flat = wflat[
                    _flat_positions(starts_of(rows, new_rp), lens[rows])
                ]
                blocks.append(_ell_fill(lens[rows], flat, k, pad))
            light_w.append(np.stack(blocks))
            prev = hi
        k //= 2

    # Shape pin: the value slabs must be slot-aligned with sell's own
    # buckets or every downstream gather-add is silently wrong.
    if (virtual_w is None) != (sell.virtual is None) or (
        virtual_w is not None and virtual_w.shape != sell.virtual.shape
    ):
        raise AssertionError("weight plane misaligned with sharded heavy bucket")
    if len(light_w) != len(sell.light) or any(
        w.shape != blk.shape for w, (_k, blk) in zip(light_w, sell.light)
    ):
        raise AssertionError("weight plane misaligned with sharded light buckets")
    return virtual_w, light_w
