"""Seeded graph generators.

- ``random_graph``: the capability of the reference's seeded random generator
  (readGraph, bfs.cu:892-907: ``srand(12345)``, m uniform edges, undirected
  double-insert) — reproducible from a seed, vectorized.
- ``rmat_graph``: Graph500-style RMAT generator (absent from the reference;
  required by the scale-22/26 target configs in BASELINE.json).
"""

from __future__ import annotations

import numpy as np

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.io import from_edges


def edge_weights(
    u: np.ndarray, v: np.ndarray, *, seed: int, wmax: int = 8, wmin: int = 1
) -> np.ndarray:
    """Deterministic per-edge int32 weights in [wmin, wmax] (ISSUE 14).

    The weight is a pure splitmix-style hash of the UNORDERED endpoint
    pair and the seed — not a position in any RNG stream — so: (a) the
    same (graph seed, edge) always draws the same weight, regardless of
    generator impl or batch order; (b) (u, v) and (v, u) agree, which the
    undirected double-insert requires; (c) parallel edges of a multigraph
    collapse to one weight, so min-dedup and keep-duplicates builds agree
    on every shortest path."""
    if not (1 <= wmin <= wmax):
        raise ValueError(f"need 1 <= wmin <= wmax, got [{wmin}, {wmax}]")
    u = np.asarray(u, dtype=np.uint64)
    v = np.asarray(v, dtype=np.uint64)
    a, b = np.minimum(u, v), np.maximum(u, v)
    with np.errstate(over="ignore"):  # uint64 wraparound is the mixer
        h = (
            a * np.uint64(0x9E3779B97F4A7C15)
            + b * np.uint64(0xC2B2AE3D27D4EB4F)
            + np.uint64(seed & 0xFFFFFFFFFFFFFFFF) * np.uint64(0xD6E8FEB86659FD93)
        )
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(33)
    span = np.uint64(wmax - wmin + 1)
    return (np.uint64(wmin) + h % span).astype(np.int32)


def random_graph(
    num_vertices: int,
    num_edges: int,
    *,
    seed: int = 12345,
    directed: bool = False,
    drop_self_loops: bool = False,
    weights: int | None = None,
) -> Graph:
    """Uniform random multigraph, seeded and reproducible.

    Mirrors readGraph's generator mode (bfs.cu:892-907): m uniform (u, v)
    pairs, undirected double-insert, self-loops allowed (the reference allows
    them too). ``weights=W`` adds the deterministic per-edge weight plane
    (:func:`edge_weights`, values in [1, W]).
    """
    rng = np.random.default_rng(seed)
    u = rng.integers(0, num_vertices, size=num_edges, dtype=np.int64)
    v = rng.integers(0, num_vertices, size=num_edges, dtype=np.int64)
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    w = None
    if weights is not None:
        w = edge_weights(u, v, seed=seed, wmax=int(weights))
    return from_edges(
        u, v, num_vertices=num_vertices, directed=directed,
        num_input_edges=num_edges, weights=w,
    )


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    impl: str = "numpy",
) -> tuple[np.ndarray, np.ndarray]:
    """Graph500 RMAT edge list: 2^scale vertices, edge_factor * 2^scale edges.

    Each of the `scale` bits of (u, v) is drawn from the quadrant
    distribution (a, b, c, d); vertex ids are then permuted, as the Graph500
    spec requires, to destroy the locality the recursion creates.

    ``impl``: 'numpy' (default — reproducible everywhere), 'native' (the
    threaded generator in native/rmat.cpp, ~20x faster at scale 21; raises if
    the library is unbuilt), or 'auto' (native when built, else numpy). The
    two implementations are deterministic in the seed but are DIFFERENT
    streams: the same seed yields a different (equally distributed) graph per
    impl — callers that persist or compare results should pin one.
    """
    return _rmat_edges_m(
        scale, edge_factor << scale, seed=seed, impl=impl, a=a, b=b, c=c
    )


# Published soc-LiveJournal1 shape (SNAP): the reference's one named
# real-world workload (README.md:22). The benchmark environment has no
# network route to fetch the real file (see NONETWORK.md), so lj_standin_*
# generate a clearly-labeled synthetic stand-in with the exact V/E counts.
LJ_V = 4_847_571
LJ_E = 68_993_773


def lj_standin_edges(
    *, seed: int = 1, impl: str = "auto"
) -> tuple[np.ndarray, np.ndarray]:
    """Directed power-law edge list with soc-LiveJournal1's exact shape.

    NOT the real graph — a deterministic stand-in: Graph500-parameter RMAT
    drawn on the enclosing 2^23 grid, restricted to ids < LJ_V by rejection
    (keeps the RMAT degree structure intact — no modulo folding artifacts),
    trimmed/topped-up to exactly LJ_E directed edges. Self-loops stay, as in
    the real SNAP file's reference treatment (bfs.cu:860-861 inserts
    whatever it reads).
    """
    scale = 23  # smallest power of two covering LJ_V
    p_keep = (LJ_V / (1 << scale)) ** 2
    # ONE vertex permutation shared by every top-up batch: raw recursion ids
    # from all batches refer to the same underlying RMAT node, so hubs keep
    # one identity across draws and the degree structure stays intact. The
    # permutation comes from a DISTINCT rng stream (seed sequence spawn key)
    # so the relabeling is independent of batch 1's quadrant draws — both
    # would otherwise replay the same PCG64 stream.
    perm = np.random.default_rng((seed, 0x4C4A)).permutation(1 << scale)
    u_parts, v_parts, total = [], [], 0
    s = seed
    while total < LJ_E:
        want = LJ_E - total
        draw = int(want / p_keep * 1.02) + 1024
        u, v = _rmat_edges_m(scale, draw, seed=s, impl=impl, permute=False)
        u, v = perm[u], perm[v]
        keep = (u < LJ_V) & (v < LJ_V)
        u, v = u[keep], v[keep]
        u_parts.append(u)
        v_parts.append(v)
        total += len(u)
        s += 1
    u = np.concatenate(u_parts)[:LJ_E]
    v = np.concatenate(v_parts)[:LJ_E]
    return u, v


def _rmat_edges_m(
    scale: int, m: int, *, seed: int, impl: str,
    a: float = 0.57, b: float = 0.19, c: float = 0.19,
    permute: bool = True,
):
    """RMAT draw of exactly ``m`` edges — the core behind ``rmat_edges``
    (which sizes by edge_factor). ``permute=False`` returns raw recursion
    ids so callers drawing multiple batches can apply ONE shared vertex
    permutation over all of them (lj_standin_edges)."""
    if impl not in ("auto", "numpy", "native"):
        raise ValueError(f"unknown impl {impl!r}")
    if not (a > 0 and b >= 0 and c >= 0 and a + b + c < 1):
        # d = 1-a-b-c must stay positive; a+b >= 1 makes c_norm a division
        # by zero. Phrased positively so NaN quadrants fail too (NaN makes
        # every comparison False). Same guard as native/rmat.cpp rc=3.
        raise ValueError(f"invalid RMAT quadrants a={a} b={b} c={c}")
    rng = np.random.default_rng(seed)
    uv = None
    if impl in ("auto", "native"):
        from tpu_bfs.utils.native import rmat_edges_native

        uv = rmat_edges_native(scale, m, seed, a, b, c)
        if uv is None and impl == "native":
            raise RuntimeError("native library not built (make -C tpu_bfs/native)")
    if uv is None:
        u = np.zeros(m, dtype=np.int64)
        v = np.zeros(m, dtype=np.int64)
        ab = a + b
        a_norm = a / ab
        c_norm = c / (1.0 - ab)
        for _ in range(scale):
            u <<= 1
            v <<= 1
            r_u = rng.random(m)
            r_v = rng.random(m)
            u_bit = r_u > ab
            v_bit = np.where(u_bit, r_v > c_norm, r_v > a_norm)
            u |= u_bit
            v |= v_bit
        uv = u, v
    if not permute:
        return uv
    perm = rng.permutation(1 << scale)
    return perm[uv[0]], perm[uv[1]]


def write_mtx(path: str, u: np.ndarray, v: np.ndarray, n: int,
              comment: str = "") -> None:
    """Write a 1-indexed MatrixMarket coordinate-pattern file — the format
    of the reference's named workload (soc-LiveJournal1.mtx, README.md:22),
    consumed here by the native loader's .mtx path."""
    with open(path, "w") as f:
        f.write("%%MatrixMarket matrix coordinate pattern general\n")
        if comment:
            f.write(f"% {comment}\n")
        f.write(f"{n} {n} {len(u)}\n")
        # Chunked vectorized int->text: ~50x faster than np.savetxt.
        chunk = 4_000_000
        for i in range(0, len(u), chunk):
            a = (u[i : i + chunk] + 1).astype(np.int64)
            b = (v[i : i + chunk] + 1).astype(np.int64)
            pairs = np.char.add(
                np.char.add(a.astype("U10"), " "), b.astype("U10")
            )
            f.write("\n".join(pairs))
            f.write("\n")


def rmat_graph(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    drop_self_loops: bool = True,
    dedup: bool = False,
    impl: str = "numpy",
    weights: int | None = None,
    **quadrants,
) -> Graph:
    """``weights=W`` is the weighted-RMAT mode (ISSUE 14): the Graph500
    topology plus the deterministic per-edge weight plane
    (:func:`edge_weights`, values in [1, W]) — the same seed always
    yields the same weighted graph, and dedup preserves shortest paths
    because parallel edges hash to one weight."""
    u, v = rmat_edges(scale, edge_factor, seed=seed, impl=impl, **quadrants)
    m = len(u)
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    w = None
    if weights is not None:
        w = edge_weights(u, v, seed=seed, wmax=int(weights))
    return from_edges(
        u, v, num_vertices=1 << scale, directed=False, num_input_edges=m,
        dedup=dedup, weights=w,
    )
