"""Seeded graph generators.

- ``random_graph``: the capability of the reference's seeded random generator
  (readGraph, bfs.cu:892-907: ``srand(12345)``, m uniform edges, undirected
  double-insert) — reproducible from a seed, vectorized.
- ``rmat_graph``: Graph500-style RMAT generator (absent from the reference;
  required by the scale-22/26 target configs in BASELINE.json).
"""

from __future__ import annotations

import numpy as np

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.io import from_edges


def random_graph(
    num_vertices: int,
    num_edges: int,
    *,
    seed: int = 12345,
    directed: bool = False,
    drop_self_loops: bool = False,
) -> Graph:
    """Uniform random multigraph, seeded and reproducible.

    Mirrors readGraph's generator mode (bfs.cu:892-907): m uniform (u, v)
    pairs, undirected double-insert, self-loops allowed (the reference allows
    them too).
    """
    rng = np.random.default_rng(seed)
    u = rng.integers(0, num_vertices, size=num_edges, dtype=np.int64)
    v = rng.integers(0, num_vertices, size=num_edges, dtype=np.int64)
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    return from_edges(
        u, v, num_vertices=num_vertices, directed=directed, num_input_edges=num_edges
    )


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    impl: str = "numpy",
) -> tuple[np.ndarray, np.ndarray]:
    """Graph500 RMAT edge list: 2^scale vertices, edge_factor * 2^scale edges.

    Each of the `scale` bits of (u, v) is drawn from the quadrant
    distribution (a, b, c, d); vertex ids are then permuted, as the Graph500
    spec requires, to destroy the locality the recursion creates.

    ``impl``: 'numpy' (default — reproducible everywhere), 'native' (the
    threaded generator in native/rmat.cpp, ~20x faster at scale 21; raises if
    the library is unbuilt), or 'auto' (native when built, else numpy). The
    two implementations are deterministic in the seed but are DIFFERENT
    streams: the same seed yields a different (equally distributed) graph per
    impl — callers that persist or compare results should pin one.
    """
    n = 1 << scale
    m = edge_factor << scale
    rng = np.random.default_rng(seed)
    if impl not in ("auto", "numpy", "native"):
        raise ValueError(f"unknown impl {impl!r}")
    if not (a > 0 and b >= 0 and c >= 0 and a + b + c < 1):
        # d = 1-a-b-c must stay positive; a+b >= 1 makes c_norm a division
        # by zero. Phrased positively so NaN quadrants fail too (NaN makes
        # every comparison False). Same guard as native/rmat.cpp rc=3.
        raise ValueError(f"invalid RMAT quadrants a={a} b={b} c={c}")
    uv = None
    if impl in ("auto", "native"):
        from tpu_bfs.utils.native import rmat_edges_native

        uv = rmat_edges_native(scale, m, seed, a, b, c)
        if uv is None and impl == "native":
            raise RuntimeError("native library not built (make -C native)")
    if uv is None:
        u = np.zeros(m, dtype=np.int64)
        v = np.zeros(m, dtype=np.int64)
        ab = a + b
        a_norm = a / ab
        c_norm = c / (1.0 - ab)
        for _ in range(scale):
            u <<= 1
            v <<= 1
            r_u = rng.random(m)
            r_v = rng.random(m)
            u_bit = r_u > ab
            v_bit = np.where(u_bit, r_v > c_norm, r_v > a_norm)
            u |= u_bit
            v |= v_bit
        uv = u, v
    perm = rng.permutation(n)
    return perm[uv[0]], perm[uv[1]]


def rmat_graph(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    drop_self_loops: bool = True,
    dedup: bool = False,
    impl: str = "numpy",
    **quadrants,
) -> Graph:
    u, v = rmat_edges(scale, edge_factor, seed=seed, impl=impl, **quadrants)
    m = len(u)
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    return from_edges(
        u, v, num_vertices=1 << scale, directed=False, num_input_edges=m, dedup=dedup
    )
