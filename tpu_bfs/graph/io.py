"""Graph loaders.

Reproduces the reference's two loaders with stricter parsing:

- ``load_edge_list`` / ``read_edge_list_text``: the text format consumed by
  ``readGraphFromFile`` (bfs.cu:829-880): a header line ``n m`` followed by m
  lines ``u v`` (0-indexed), inserted in BOTH directions (undirected,
  bfs.cu:860-861). Unlike the reference — which has no comment handling and
  consumes ``.mtx`` files as raw edge lists (README.md:22) — this loader skips
  ``%``/``#`` comment lines and auto-detects MatrixMarket-style 3-int headers
  (``rows cols nnz``, 1-indexed body).
- ``read_stdin``: edge list on stdin, directed single-insert, matching
  ``readGraph``'s stdin mode (bfs.cu:898-903).
"""

from __future__ import annotations

import sys

import numpy as np

from tpu_bfs.graph.csr import Graph, build_csr


def _parse_nums(text: str) -> np.ndarray:
    # Fast-enough pure-NumPy parse; the native C++ loader (tpu_bfs.utils.native)
    # replaces this on large files. float64 so .mtx weight columns (possibly
    # non-integer) parse; vertex ids are exact in float64 up to 2^53.
    return np.array(text.split(), dtype=np.float64)


def read_edge_list_text(
    text: str,
    *,
    directed: bool = False,
    drop_self_loops: bool = False,
) -> Graph:
    """Parse an edge-list string into a Graph. See module docstring for format."""
    lines = []
    for ln in text.splitlines():
        s = ln.strip()
        if not s or s[0] in "%#":
            continue
        lines.append(s)
    if not lines:
        raise ValueError("empty graph file")

    header = lines[0].split()
    one_indexed = False
    if len(header) == 3:
        # MatrixMarket size line: rows cols nnz; body is 1-indexed.
        n = max(int(header[0]), int(header[1]))
        m = int(header[2])
        one_indexed = True
        body_start = 1
    elif len(header) == 2:
        # Reference format: "n m" (bfs.cu:845), 0-indexed body.
        n, m = int(header[0]), int(header[1])
        body_start = 1
    else:
        raise ValueError(f"unrecognized header line: {lines[0]!r}")

    nums = _parse_nums("\n".join(lines[body_start:]))
    if len(nums) < 2 * m:
        raise ValueError(f"expected {m} edges, found {len(nums) // 2}")
    # Tolerate .mtx bodies with a weight column: take the first 2 of each row
    # when the token count says 3 per line.
    if len(nums) == 3 * m:
        nums = nums.reshape(m, 3)[:, :2].ravel()
    else:
        nums = nums[: 2 * m]
    uv = nums.astype(np.int64).reshape(m, 2)
    if one_indexed:
        uv = uv - 1
    u, v = uv[:, 0], uv[:, 1]
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    return from_edges(u, v, num_vertices=n, directed=directed, num_input_edges=m)


def from_edges(
    u: np.ndarray,
    v: np.ndarray,
    *,
    num_vertices: int | None = None,
    directed: bool = False,
    num_input_edges: int | None = None,
    dedup: bool = False,
    weights: np.ndarray | None = None,
) -> Graph:
    """Build a Graph from input edge endpoints (undirected -> double-insert).

    ``weights`` (one int per INPUT edge, >= 1) stores a per-edge weight
    plane: the undirected double-insert carries the same weight on both
    directed slots. ``dedup`` with weights keeps each surviving slot's
    MINIMUM weight (the shortest-path-relevant one for parallel edges)."""
    u = np.asarray(u, dtype=np.int64)
    v = np.asarray(v, dtype=np.int64)
    if num_vertices is None:
        num_vertices = int(max(u.max(initial=-1), v.max(initial=-1)) + 1)
    if weights is not None:
        weights = np.asarray(weights, dtype=np.int32)
        if weights.shape != u.shape:
            raise ValueError(
                f"weights shape {weights.shape} != input edge count {u.shape}"
            )
    if directed:
        src, dst = u, v
        wts = weights
    else:
        src = np.concatenate([u, v])
        dst = np.concatenate([v, u])
        wts = None if weights is None else np.concatenate([weights, weights])
    if dedup:
        packed = src * np.int64(num_vertices) + dst
        if wts is None:
            packed = np.unique(packed)
        else:
            # Keep each surviving slot's minimum weight: sort by (slot,
            # weight), take the first of each slot run.
            order = np.lexsort((wts, packed))
            packed, wts = packed[order], wts[order]
            first = np.ones(len(packed), dtype=bool)
            first[1:] = packed[1:] != packed[:-1]
            packed, wts = packed[first], wts[first]
        src, dst = packed // num_vertices, packed % num_vertices
    return build_csr(
        src,
        dst,
        num_vertices,
        num_input_edges=num_input_edges if num_input_edges is not None else len(u),
        undirected=not directed,
        weights=wts,
    )


def load_edge_list(path: str, **kw) -> Graph:
    """Load the reference's text format from a file (readGraphFromFile, bfs.cu:829)."""
    try:
        from tpu_bfs.utils.native import load_edge_list_native

        g = load_edge_list_native(path, **kw)
        if g is not None:
            return g
    except Exception:
        pass  # fall back to pure-Python parsing
    with open(path, "r") as f:
        return read_edge_list_text(f.read(), **kw)


def read_stdin(stream=None, *, directed: bool = True) -> Graph:
    """Edge list from stdin: header ``n m`` then m ``u v`` lines, directed
    single-insert (reference readGraph stdin mode, bfs.cu:898-903)."""
    stream = stream if stream is not None else sys.stdin
    text = stream.read() if hasattr(stream, "read") else str(stream)
    return read_edge_list_text(text, directed=directed)


def save_npz(path: str, g: Graph) -> None:
    extra = {} if g.weights is None else {"weights": g.weights}
    np.savez_compressed(
        path,
        row_ptr=g.row_ptr,
        col_idx=g.col_idx,
        num_input_edges=np.int64(g.num_input_edges),
        undirected=np.bool_(g.undirected),
        **extra,
    )


def load_npz(path: str) -> Graph:
    d = np.load(path)
    return Graph(
        row_ptr=d["row_ptr"],
        col_idx=d["col_idx"],
        num_input_edges=int(d["num_input_edges"]),
        undirected=bool(d["undirected"]) if "undirected" in d else True,
        weights=d["weights"] if "weights" in d else None,
    )
