"""Graph500-style BFS benchmark harness.

Beyond-parity capability (SURVEY.md §7 checklist item 8; BASELINE.json
configs): seeded Kronecker/RMAT generation, 64 random search keys, per-search
validation (the reference validates only against a CPU rerun of the same
traversal, bfs.cu:798-815; Graph500 validation checks the BFS-tree properties
directly), and harmonic-mean TEPS reporting.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_bfs import validate
from tpu_bfs.algorithms.bfs import BfsEngine
from tpu_bfs.algorithms.msbfs import MsBfsEngine
from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.graph.generate import rmat_graph


@dataclasses.dataclass
class Graph500Result:
    scale: int
    edge_factor: int
    num_searches: int
    teps: list[float]  # per-search TEPS
    validated: bool
    mode: str  # 'single' | 'batched'

    @property
    def harmonic_mean_teps(self) -> float:
        return len(self.teps) / sum(1.0 / t for t in self.teps)


def sample_search_keys(g: Graph, n: int, *, seed: int = 2) -> np.ndarray:
    """Graph500 samples search keys uniformly among vertices with degree > 0."""
    rng = np.random.default_rng(seed)
    candidates = np.flatnonzero(g.degrees > 0)
    return rng.choice(candidates, size=min(n, len(candidates)), replace=False)


def traversed_edges(g: Graph, dist: np.ndarray) -> int:
    """Graph500 TEPS numerator: input edges with both endpoints reached."""
    reached = dist != INF_DIST
    slots = int(reached[g.coo[0]].sum())  # dst also reached for a full BFS
    return slots // 2 if g.undirected else slots


def run_graph500(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    num_searches: int = 64,
    mode: str = "single",
    validate_searches: int = 4,
    validate_mode: str = "oracle",
    num_planes: int = 5,
    lanes: int | None = None,
    engine_cls=None,
    verbose: bool = False,
    devices: int = 1,
    mesh2d: tuple[int, int] | None = None,
    backend: str = "scan",
    exchange: str | None = None,
) -> Graph500Result:
    """Generate, run, validate, and score a Graph500-style BFS benchmark.

    mode='single': one traversal at a time (the official kernel-2 shape).
    mode='batched': all searches in one MsBfs batch; per-search TEPS is then
    the aggregate time split evenly (reported as such — not comparable with
    official single-stream numbers, but the right way to use a TPU when the
    workload has many sources).
    mode='hybrid': the 4096-lane MXU+gather flagship engine, same equal-share
    accounting as 'batched'; ``num_planes`` caps depth at 2**planes levels.

    ``devices`` / ``mesh2d`` distribute the run: single mode shards over a
    1D mesh (or the 2D edge partition with ``mesh2d``; ``backend='dopt'`` on
    a 2D mesh is the BASELINE scale-26 config, rehearsable at reduced scale
    on the virtual CPU mesh), hybrid mode uses the sharded-state
    DistHybridMsBfsEngine.
    """
    g = rmat_graph(scale, edge_factor, seed=seed)
    keys = sample_search_keys(g, num_searches)
    distributed = devices > 1 or mesh2d is not None
    if distributed and mode == "batched":
        raise ValueError(
            "mode='batched' is single-device; use mode='hybrid' (sharded "
            "DistHybridMsBfsEngine) or mode='single' on a mesh"
        )

    teps = []
    # lanes=None -> engine auto sizing; multiples of 4096 past the default
    # opt into wider rows (more searches per batch; see msbfs_hybrid).
    lanes_kw = {} if lanes is None else {"lanes": lanes}
    if mode == "hybrid":
        if engine_cls is not None:
            eng = engine_cls(g)
        elif distributed:
            if mesh2d is not None:
                raise ValueError(
                    "hybrid mode shards 1D (row-tile round-robin); pass "
                    "devices=N instead of a 2D mesh"
                )
            from tpu_bfs.parallel.dist_msbfs_hybrid import DistHybridMsBfsEngine

            eng = DistHybridMsBfsEngine(
                g, devices, num_planes=num_planes,
                exchange=exchange or "dense", **lanes_kw,
            )
        else:
            from tpu_bfs.algorithms.msbfs_hybrid import HybridMsBfsEngine

            eng = HybridMsBfsEngine(g, num_planes=num_planes, **lanes_kw)
        res = eng.run(keys, time_it=True)
        per_search = res.elapsed_s / len(keys)
        # One lane at a time — res extracts lazily; only the rows needed for
        # validation are retained (the full [S, V] matrix would be ~17 GB at
        # Graph500 scale 26). Parents come from the engine's own result
        # (post-loop min-parent extraction, PackedBatchResult.parents_int32)
        # — the BFS-tree output artifact Graph500 requires, which the
        # reference's kernel emitted but could never validate (bfs.cu:940).
        dists = []
        parents = []
        for i in range(len(keys)):
            d = res.distances_int32(i)
            teps.append(traversed_edges(g, d) / per_search)
            if i < validate_searches:
                dists.append(d)
                parents.append(res.parents_int32(i))
    elif mode == "batched":
        eng = MsBfsEngine(g) if engine_cls is None else engine_cls(g)
        res = eng.run(keys, time_it=True)
        per_search = res.elapsed_s / len(keys)  # equal time share per search
        for i in range(len(keys)):
            teps.append(traversed_edges(g, res.distance[i]) / per_search)
        dists = res.distance
    else:
        if engine_cls is not None:
            eng = engine_cls(g)
        elif mesh2d is not None:
            from tpu_bfs.parallel.dist_bfs2d import Dist2DBfsEngine, make_mesh_2d

            eng = Dist2DBfsEngine(
                g, make_mesh_2d(*mesh2d), backend=backend,
                **({"exchange": exchange} if exchange else {}),
            )
        elif devices > 1:
            from tpu_bfs.parallel.dist_bfs import DistBfsEngine, make_mesh

            eng = DistBfsEngine(
                g, make_mesh(devices), backend=backend,
                **({"exchange": exchange} if exchange else {}),
            )
        else:
            eng = BfsEngine(g, backend=backend)
        dists = []
        for s in keys:
            r = eng.run(int(s), with_parents=False, time_it=True)
            teps.append(r.edges_traversed / r.elapsed_s)
            dists.append(r.distance)
            if verbose:
                print(
                    f"  src={int(s)} t={r.elapsed_s * 1e3:.2f}ms "
                    f"GTEPS={teps[-1] / 1e9:.3f}"
                )
        dists = np.stack(dists)

    # Validation: distances against the scipy oracle + parent properties via
    # the deterministic min-parent tree, on a sample of searches.
    from tpu_bfs.reference import bfs_scipy

    if validate_mode not in ("oracle", "certify"):
        raise ValueError(
            f"unknown validate_mode {validate_mode!r}; have 'oracle', 'certify'"
        )
    n_validate = min(validate_searches, len(keys))
    for i in range(n_validate):
        s = int(keys[i])
        if validate_mode == "oracle":
            # Small/medium scales: elementwise compare against an
            # independent implementation (the reference's own pattern,
            # bfs.cu:798-815) — strongest but needs a CPU BFS per search.
            validate.check_distances(dists[i], bfs_scipy(g, s))
        # Hybrid mode validates the tree through the result's parents_int32
        # API — the artifact callers receive. By construction it is the
        # deterministic min-parent tree implied by the engine's distances
        # (the same definition the other modes validate directly), so this
        # branch exercises the artifact path, not extra coverage.
        mp = (
            parents[i]
            if mode == "hybrid"
            else validate.min_parent_from_dist(g, s, dists[i])
        )
        # Oracle-free certificate (parent chains + edge-level property,
        # validate.certify_bfs): with validate_mode='certify' this is the
        # WHOLE validation — two O(E) host passes, feasible at scales
        # where the SciPy rerun is not (the Graph500 validator design).
        validate.certify_bfs(g, s, dists[i], mp)
    return Graph500Result(
        scale=scale,
        edge_factor=edge_factor,
        num_searches=len(keys),
        teps=teps,
        validated=n_validate > 0,  # checks raise on mismatch
        mode=mode,
    )


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="tpu_bfs.graph500")
    ap.add_argument("--scale", type=int, default=16)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--searches", type=int, default=64)
    ap.add_argument(
        "--mode", choices=["single", "batched", "hybrid"], default="single"
    )
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--validate", type=int, default=4, metavar="N",
                    help="validate the first N searches (0 to skip)")
    ap.add_argument("--validate-mode", default="oracle",
                    choices=["oracle", "certify"],
                    help="'oracle' = SciPy compare + certificate; 'certify' "
                    "= oracle-free property certificate only (two O(E) "
                    "passes — use at scales where a CPU BFS is infeasible)")
    ap.add_argument("--planes", type=int, default=5, metavar="P",
                    choices=range(1, 9),
                    help="hybrid mode: bit-plane count (depth cap 2**P)")
    ap.add_argument("--lanes", type=int, default=None, metavar="N",
                    help="hybrid mode: packed batch width (default: engine "
                    "auto sizing, 4096; multiples of 4096 opt into wider "
                    "rows — raise --searches to fill them)")
    ap.add_argument("--devices", type=int, default=1,
                    help="shard over N devices (single: 1D vertex "
                    "partition; hybrid: sharded-state engine)")
    ap.add_argument("--mesh", default=None, metavar="RxC",
                    help="single mode: 2D edge partition over an RxC mesh "
                    "(with --backend dopt = the scale-26 target config)")
    ap.add_argument("--backend", default="scan",
                    choices=["scan", "segment", "scatter", "dopt"],
                    help="single mode: frontier-expansion backend")
    ap.add_argument("--exchange", default=None,
                    choices=["ring", "allreduce", "sparse", "dense", "sliced"],
                    help="distributed frontier exchange (single mode: "
                    "ring/allreduce/sparse; hybrid mode: dense/sparse/"
                    "sliced — 'sliced' is the ring-rotation expansion with "
                    "O(A/P) transients)")
    args = ap.parse_args(argv)
    mesh2d = None
    if args.mesh:
        try:
            mesh2d = tuple(int(t) for t in args.mesh.lower().split("x"))
            if len(mesh2d) != 2:
                raise ValueError(mesh2d)
        except ValueError:
            ap.error(f"--mesh must look like RxC (e.g. 2x4), got {args.mesh!r}")
    res = run_graph500(
        args.scale,
        args.ef,
        seed=args.seed,
        num_searches=args.searches,
        mode=args.mode,
        validate_searches=args.validate,
        validate_mode=args.validate_mode,
        num_planes=args.planes,
        lanes=args.lanes,
        verbose=True,
        devices=args.devices,
        mesh2d=mesh2d,
        backend=args.backend,
        exchange=args.exchange,
    )
    print(
        f"graph500 scale={res.scale} ef={res.edge_factor} mode={res.mode} "
        f"searches={res.num_searches} validated={res.validated} "
        f"harmonic_mean_GTEPS={res.harmonic_mean_teps / 1e9:.4f}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
