"""tpu_bfs/integrity — the online result-integrity tier (ISSUE 15).

The reference validates every run against a CPU golden (checkOutput,
bfs.cu:374-384); the Graph500 discipline (Buluç & Madduri,
arXiv:1104.4518) validates by tree properties at scales where no oracle
fits. Until this package, BOTH only ran in bench/one-shot mode — the
serve tier shipped answers to clients with zero in-band verification,
so a silent corruption (bad HBM word, miscompiled rung, wire bit-flip)
between engine and client was undetectable. The integrity tier audits
continuously, in the serve path, without touching serving latency:

- **structural audits** (structural.py): the validate.py/graph500.py
  tree predicates as fused device kernels, run on sampled lanes of
  every served batch — parent-edge/level properties for bfs, weighted
  relaxation for sssp, path validity for p2p, consistency for cc/khop.
- **shadow re-execution** (shadow.py): a deterministic sample of
  resolved queries replayed on a DISJOINT engine config (another width
  rung, or the alternate exchange family on a mesh) and bit-compared.
- **wire checksums** (wire.py): an order-sensitive uint32 fold shared
  by the exchange frame codec (HLO byte cost proven in wirecheck) and
  the extraction-transfer check behind the ``audit_checksum`` flag.
- **staleness audits** (staleness.py, ISSUE 19): on a dynamic-graph
  service, sampled served answers replay against CPU oracles of a
  bounded ring of recent generation snapshots, measuring how many
  flips behind the served answer sits — the only detector that can
  catch a torn generation flip (a stale answer passes every structural
  predicate, and a shadow replay on the same torn service reproduces
  it); over-bound staleness quarantines the stale serving state.
- **quarantine** (this module): a confirmed finding evicts the suspect
  rung from the registry (the rebuild clears wedged device state),
  force-opens its (width, devices, kind) circuit breaker so routing
  stops offering it, dumps the flight recorder naming the corrupted
  query chain, and — on repeated device-attributed findings on a mesh —
  escalates to the PR 11 degraded-mesh failover ladder.

Everything here runs on the extraction worker or the dedicated audit
thread; the scheduler's dispatch hot path and client-visible latency
pay only the per-batch sampling decision. Audit failures are CONFIRMED
corruption (exact property violations / exact replays disagreeing);
audit-infrastructure errors count separately and never quarantine.
New fault kinds ``corrupt_result``/``corrupt_wire`` (tpu_bfs/faults.py)
drive every detector red-before-green; ``make integrity-smoke`` is the
end-to-end proof.
"""

from __future__ import annotations

import threading
import time

from tpu_bfs import obs as _obs
from tpu_bfs.integrity.shadow import (  # noqa: F401 — package API
    AuditSampler,
    ShadowAuditor,
    ShadowJob,
    compare_payloads,
)
from tpu_bfs.integrity.staleness import (  # noqa: F401 — package API
    StalenessAuditor,
)
from tpu_bfs.integrity.structural import (  # noqa: F401 — package API
    StructuralAuditor,
    StructuralFinding,
)


class QuarantineManager:
    """Corruption findings -> rung eviction + breaker + escalation.

    The service binds the three actions (``quarantine_rung``,
    ``escalate_mesh`` and its metrics); this class owns only the
    policy: every confirmed finding quarantines its rung, and
    ``escalate_after`` device-attributed findings on the same mesh span
    (devices > 1) escalate to the mesh-degrade ladder — a whole mesh
    corrupting repeatedly is a hardware incident, not a bad compile."""

    def __init__(self, *, quarantine_rung, escalate_mesh, metrics, log=None,
                 escalate_after: int = 3):
        self._quarantine_rung = quarantine_rung  # (width, kind) -> None
        self._escalate_mesh = escalate_mesh  # (devices, cause) -> None
        self._metrics = metrics
        self._log = log or (lambda msg: None)
        self._escalate_after = max(int(escalate_after), 1)
        self._lock = threading.Lock()
        self._mesh_findings: dict = {}  # guarded-by: _lock — devices -> count

    def report(self, *, width: int, devices: int, kind: str, query_id,
               detail: str, source: str) -> None:
        """One CONFIRMED corruption finding from ``source`` (structural |
        shadow | checksum) against the rung that served ``query_id``."""
        from tpu_bfs.utils.recovery import COUNTERS

        self._metrics.record_quarantine()
        COUNTERS.bump("quarantines")
        self._log(
            f"CORRUPTION ({source}) on query {query_id!r}: {detail[:300]} "
            f"— quarantining the {width}-lane {kind} rung "
            f"(devices={devices})"
        )
        rec = _obs.ACTIVE
        if rec is not None:
            # Flight-recorder trigger: a corruption finding is exactly
            # the incident whose run-up (the serving batch's span chain,
            # the fault injection if chaos is armed) the ring buffer
            # holds; the dump names the corrupted query, and its label
            # names the DETECTOR that fired — a real-hardware corruption
            # must not masquerade as a chaos fault kind.
            rec.event("corruption", cat="serve.integrity", query=query_id,
                      kind=kind, width=width, devices=devices,
                      source=source, detail=detail[:300])
            rec.flight_dump(f"corruption_{source}")
        self._quarantine_rung(width, kind)
        if devices > 1:
            with self._lock:
                n = self._mesh_findings.get(devices, 0) + 1
                self._mesh_findings[devices] = n
            if n >= self._escalate_after:
                with self._lock:
                    self._mesh_findings[devices] = 0
                self._log(
                    f"ESCALATING: {n} corruption findings attributed to "
                    f"the {devices}-device mesh — running the mesh "
                    f"degrade ladder"
                )
                self._escalate_mesh(devices, RuntimeError(
                    f"repeated result corruption on the {devices}-device "
                    f"mesh ({source}: {detail[:200]})"
                ))


class IntegrityTier:
    """The serve-side composition: sampling, structural checks, shadow
    replays, and quarantine, bound to one :class:`BfsService`.

    Constructed (and started) only when armed — ``audit_rate > 0`` or a
    structural/checksum flag — so un-audited services pay nothing."""

    def __init__(self, service, *, rate: float = 0.0,
                 structural: bool = False, checksum: bool = False,
                 seed: int = 0, structural_lanes: int = 1,
                 escalate_after: int = 3, max_pending: int = 64):
        self._service = service
        self.rate = float(rate)
        self.checksum = bool(checksum)
        self._structural_lanes = max(int(structural_lanes), 0)
        self._sampler = AuditSampler(rate, seed)
        self._structural = (
            StructuralAuditor(service._graph, checksum=checksum)
            if structural or checksum else None
        )
        self.quarantine = QuarantineManager(
            quarantine_rung=service._quarantine_rung,
            escalate_mesh=service._escalate_mesh,
            metrics=service.metrics,
            log=service._log,
            escalate_after=escalate_after,
        )
        self._shadow = (
            ShadowAuditor(
                acquire_engine=service._acquire_shadow_engine,
                on_mismatch=self._on_shadow_mismatch,
                metrics=service.metrics,
                log=service._log,
                max_pending=max_pending,
                current_state=lambda: (
                    getattr(service, "graph_generation", 0),
                    getattr(service, "_overlay_epoch", 0),
                ),
            )
            if self.rate > 0 else None
        )

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "IntegrityTier":
        """Start the audit worker AND pay the tier's one-time costs here,
        on the cold-start path, instead of lazily at the first audit:

        - the structural auditor's device edge tables (a host->device
          transfer plus kernel compiles that would otherwise stall the
          extraction worker mid-traffic);
        - the shadow rung, when it is not already a warm serving rung
          (single-rung ladders / the mesh alternate-exchange fallback):
          ``registry.get`` holds the global registry lock for the whole
          build, and a mid-traffic build there would freeze dispatch —
          exactly the hot path the tier promises never to touch. With a
          multi-rung ladder the disjoint rung IS a serving rung and
          this is a cache hit; non-primary kinds' shadow engines still
          build lazily on their first sampled audit (documented)."""
        svc = self._service
        if self._structural is not None:
            try:
                self._structural.prepare()
            except Exception as exc:  # noqa: BLE001 — degrade, don't block serving
                svc._log(f"structural-audit prepare failed "
                         f"({type(exc).__name__}: {str(exc)[:200]}); "
                         f"kernels will build on first audit")
        if self._shadow is not None:
            if len(svc.width_ladder) == 1:
                try:
                    svc._acquire_shadow_engine(
                        svc.width_ladder[0], svc._primary_kind
                    )
                except Exception as exc:  # noqa: BLE001 — lazy fallback
                    svc._log(f"shadow-rung prewarm failed "
                             f"({type(exc).__name__}: {str(exc)[:200]}); "
                             f"building on first audit")
            self._shadow.start()
        return self

    def close(self) -> None:
        if self._shadow is not None:
            self._shadow.close()

    def flush(self, timeout: float = 60.0) -> bool:
        """Barrier: every batch already handed to the extraction path
        has finished its finish+observe window, the pipeline handoff is
        empty, and every enqueued shadow audit has been processed — the
        point after which the audit counters are complete for all
        RESOLVED queries (the bench and the smokes read them here)."""
        svc = self._service
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pipe = svc._pipe_q
            with svc._audit_quiesce:
                busy = svc._finishing
            if busy == 0 and (pipe is None or pipe.empty()):
                break
            time.sleep(0.005)
        else:
            return False
        if self._shadow is None:
            return True
        return self._shadow.flush(max(deadline - time.monotonic(), 0.01))

    def config_summary(self) -> dict:
        return {
            "rate": self.rate,
            "structural": self._structural is not None,
            "checksum": self.checksum,
        }

    # --- the per-batch hook (extraction worker) ---------------------------

    def observe_batch(self, pending) -> None:
        """Audit one successfully-finished batch: structural checks on up
        to ``structural_lanes`` sampled ok-lanes, shadow enqueue for the
        sampled fraction of resolutions. Runs AFTER every query resolved
        — audits never add client-visible latency — and must never let
        an exception reach the serving path (the caller treats any
        escape as a bug; everything is caught and counted here)."""
        now = time.monotonic()
        structural_left = self._structural_lanes
        for q in pending.queries:
            try:
                r = q.result(0)
                if not r.ok:
                    continue
                if self._structural is not None and structural_left > 0:
                    structural_left -= 1
                    self._audit_structural(pending, q, r)
                if self._shadow is not None and self._sampler.should_sample():
                    job = ShadowJob(
                        query_id=q.id, kind=r.kind, source=q.source,
                        k=getattr(q, "k", None),
                        target=getattr(q, "target", None),
                        width=pending.lanes, devices=pending.devices,
                        distances=r.distances, levels=r.levels,
                        reached=r.reached,
                        extras=dict(r.extras) if r.extras else None,
                        t_resolved=now,
                        generation=int(getattr(pending, "generation", 0)),
                        epoch=int(getattr(pending, "overlay_epoch", 0)),
                    )
                    self._shadow.offer(job)
            except Exception as exc:  # noqa: BLE001 — the seal: audits never
                # become serving incidents. This catches what the inner
                # handlers can't — a quarantine action itself failing
                # (flight dump on a full disk, a mesh escalation's
                # rebuild erroring) — and files it as an audit error
                # instead of letting _finish's executor-error path
                # misattribute a SERVED batch as failed.
                self._service.metrics.record_audit_error()
                self._service._log(
                    f"audit pipeline errored (query "
                    f"{getattr(q, 'id', None)!r}): "
                    f"{type(exc).__name__}: {str(exc)[:200]}"
                )

    def _audit_structural(self, pending, q, r) -> None:
        svc = self._service
        # Generation gate (ISSUE 19): the auditor's edge tables track
        # the LIVE generation (the flip path rebinds them), so a batch
        # stamped with a superseded generation cannot be structurally
        # judged — its removed edges would read as violations. Skip;
        # the staleness auditor owns cross-generation correctness.
        gen = int(getattr(pending, "generation", 0))
        if gen != int(getattr(svc, "graph_generation", 0)):
            return
        t0 = time.monotonic()
        try:
            self._structural.audit(r.kind, r)
        except StructuralFinding as exc:
            if gen != int(getattr(svc, "graph_generation", 0)):
                # The flip landed DURING the audit — the tables may have
                # been rebound mid-check, so the finding indicts the
                # graph changing, not the rung. Shed it.
                svc.metrics.record_audit_dropped()
                return
            svc.metrics.record_audit(
                (time.monotonic() - t0) * 1e3, failed=True
            )
            self.quarantine.report(
                width=pending.lanes, devices=pending.devices, kind=r.kind,
                query_id=q.id, detail=str(exc), source="structural",
            )
            return
        except Exception as exc:  # noqa: BLE001 — audit infra, not corruption
            svc.metrics.record_audit_error()
            svc._log(
                f"structural audit errored (query {q.id!r}): "
                f"{type(exc).__name__}: {str(exc)[:200]}"
            )
            return
        svc.metrics.record_audit((time.monotonic() - t0) * 1e3)

    # --- the answer-tier hook (ISSUE 18, client thread) -------------------

    def observe_answer(self, q, *, origin: str) -> None:
        """Audit one answer served WITHOUT a traversal (cache hit or
        exact landmark bound): the same deterministic shadow sample as
        batch resolutions, replayed on the disjoint rung. The ShadowJob
        carries ``origin`` so a confirmed mismatch quarantines the cache
        generation / landmark index — the replay rung told the truth."""
        if self._shadow is None:
            return
        svc = self._service
        try:
            if not self._sampler.should_sample():
                return
            r = q.result(0)
            if not r.ok:
                return
            job = ShadowJob(
                query_id=q.id, kind=r.kind, source=q.source,
                k=getattr(q, "k", None),
                target=getattr(q, "target", None),
                width=svc.width_ladder[0],
                devices=svc._mesh_cfg.devices,
                distances=r.distances, levels=r.levels,
                reached=r.reached,
                extras=dict(r.extras) if r.extras else None,
                t_resolved=time.monotonic(),
                origin=origin,
                generation=int(getattr(svc, "graph_generation", 0)),
                epoch=int(getattr(svc, "_overlay_epoch", 0)),
            )
            self._shadow.offer(job)
        except Exception as exc:  # noqa: BLE001 — audits never become
            # serving incidents (same seal as observe_batch).
            svc.metrics.record_audit_error()
            svc._log(
                f"answer-tier audit errored (query "
                f"{getattr(q, 'id', None)!r}): "
                f"{type(exc).__name__}: {str(exc)[:200]}"
            )

    def _on_shadow_mismatch(self, job: ShadowJob, detail: str) -> None:
        origin = getattr(job, "origin", "serve")
        if origin in ("cache", "landmark"):
            # The replay ran on a healthy rung and disagreed with a
            # bypass answer: the stale/corrupt thing is the CACHED
            # payload (or the landmark columns), not the rung — indict
            # the answer tier's generation, never the replay rung (the
            # ``quarantines`` counter stays rung-only; the cache tier
            # counts its own ``cache_quarantines``).
            self._service._log(
                f"CORRUPTION (shadow/{origin}) on query {job.query_id!r}: "
                f"{detail[:300]} — quarantining the {origin} tier"
            )
            self._service.quarantine_answer_tier(origin, detail=detail)
            return
        self.quarantine.report(
            width=job.width, devices=job.devices, kind=job.kind,
            query_id=job.query_id, detail=detail, source="shadow",
        )
