"""Sampled shadow re-execution: replay served queries on a DISJOINT
engine config and bit-compare the answers.

The structural checks (integrity/structural.py) prove a served answer is
*a* valid BFS/SSSP labeling — but a miscompiled rung that computes a
correct-shaped wrong tree, or a corrupted reduction that misreports a
count, can pass properties while still lying. The shadow auditor closes
that hole the way the fuzz suite does, continuously and in production:
a deterministic sample of resolved queries is re-executed on a warm
engine built from a DIFFERENT compiled program — another width rung of
the ladder, or the alternate exchange family on a mesh — and the two
answers are compared bit-for-bit per kind (distances for bfs/sssp,
reached counts and extras for the metadata kinds, met/distance for p2p,
whose meet vertex is legitimately batch-composition-dependent). Two
independent programs agreeing bit-exactly is as close to an oracle as a
system serving graphs no CPU golden can hold gets (the Graph500
validation stance, arXiv:1104.4518).

Replays run on ONE background worker off the serving threads, through
the same registry (the disjoint rung stays warm after its first build);
a bounded queue sheds audits — never queries — under overload. Audit
failures are CONFIRMED corruption (the comparison is exact and the
sampler replays the served payload, not a re-extraction) and feed the
quarantine path; replay infrastructure failures (a transient during the
shadow run) retry once, then count as audit errors and never quarantine.
"""

from __future__ import annotations

import dataclasses
import queue as _queue
import threading
import time

import numpy as np

from tpu_bfs import faults as _faults


def splitmix32(x: int) -> int:
    """Deterministic 32-bit mix (the graph generator's family): the
    sampler's coin, a pure function of (seed, sequence number)."""
    x = (x + 0x9E3779B9) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


class AuditSampler:
    """Deterministic Bernoulli sampler over the resolve sequence.

    ``should_sample()`` consumes one sequence slot and answers whether
    that resolution is audited: ``splitmix32(seed ^ seq) / 2^32 <
    rate``. Pure function of (seed, seq), so the same serve run samples
    the same queries — the determinism the chaos soaks replay on."""

    def __init__(self, rate: float, seed: int = 0):
        if not (0.0 <= rate <= 1.0):
            raise ValueError(f"audit rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self.seed = int(seed) & 0xFFFFFFFF
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock

    def should_sample(self) -> bool:
        if self.rate <= 0.0:
            return False
        with self._lock:
            seq = self._seq
            self._seq += 1
        if self.rate >= 1.0:
            return True
        return splitmix32(self.seed ^ seq) < self.rate * 4294967296.0

    def picks(self, n: int) -> list:
        """The sample decisions for sequence slots [0, n) WITHOUT
        consuming them — test/inspection helper."""
        if self.rate >= 1.0:
            return [True] * n
        if self.rate <= 0.0:
            return [False] * n
        bar = self.rate * 4294967296.0
        return [splitmix32(self.seed ^ i) < bar for i in range(n)]


@dataclasses.dataclass
class ShadowJob:
    """One sampled resolution: the served payload plus where it came
    from (the suspect rung the quarantine path indicts on mismatch)."""

    query_id: object
    kind: str
    source: int
    k: int | None
    target: int | None
    width: int
    devices: int
    distances: np.ndarray | None
    levels: int | None
    reached: int | None
    extras: dict | None
    t_resolved: float
    # Where the served answer came from (ISSUE 18): "serve" for a batch
    # resolution, "cache"/"landmark" for the answer tier's bypass paths.
    # The quarantine routing keys on this — a stale cached answer
    # indicts the cache generation, never the replay rung.
    origin: str = "serve"
    # Graph generation the answer was served under (ISSUE 19). The
    # replay worker drops jobs whose generation no longer matches the
    # live service: a replay engine always syncs to the CURRENT overlay,
    # so comparing a pre-flip answer against it would indict a healthy
    # rung for the graph having legitimately changed. Cross-generation
    # correctness is the staleness auditor's jurisdiction, not shadow's.
    generation: int = 0
    # Overlay install epoch at resolution (ISSUE 19). The epoch bumps on
    # events the generation number cannot see — a restage healing a torn
    # flip, a compaction folding the overlay away — and a replay across
    # either compares answers from two different table installs: a
    # torn-state answer vs a healed engine is STALENESS (already
    # quarantined by that auditor), not rung corruption.
    epoch: int = 0


#: Extras keys that legitimately vary with batch composition (the sssp
#: round count is the WHOLE batch's fixed-point iteration count) — the
#: shadow compare must not read them as corruption. The answer tier's
#: provenance stamps (ISSUE 18: cache_hit/landmark/exact/bounds) are
#: metadata about HOW the answer was served, not part of the payload,
#: so a replay legitimately lacks them.
from tpu_bfs.serve.answercache import PROVENANCE_EXTRAS  # noqa: E402

_BATCH_DEPENDENT_EXTRAS = frozenset(("sssp_rounds",)) | PROVENANCE_EXTRAS


def compare_payloads(job: ShadowJob, res) -> str | None:
    """Bit-compare the served payload against a shadow result's lane 0.
    Returns a human-readable mismatch description, or None when they
    agree. p2p compares met/distance/target (the meet vertex and path
    depend on batch composition — structural.py validates the path)."""
    if job.kind == "p2p":
        ex = dict(res.extras(0) or {})
        served = dict(job.extras or {})
        for key in ("met", "distance", "target"):
            if served.get(key) != ex.get(key):
                return (
                    f"p2p {key} mismatch: served {served.get(key)!r} vs "
                    f"shadow {ex.get(key)!r}"
                )
        return None
    if job.reached is not None:
        shadow_reached = int(np.asarray(res.reached)[0])
        if int(job.reached) != shadow_reached:
            return (
                f"reached mismatch: served {job.reached} vs shadow "
                f"{shadow_reached}"
            )
    extras_fn = getattr(res, "extras", None)
    if extras_fn is not None and job.extras is not None:
        shadow_ex = extras_fn(0) or {}
        for key, val in job.extras.items():
            if key in _BATCH_DEPENDENT_EXTRAS:
                continue
            if key in shadow_ex and shadow_ex[key] != val:
                return (
                    f"extras[{key!r}] mismatch: served {val!r} vs shadow "
                    f"{shadow_ex[key]!r}"
                )
    if job.distances is not None:
        shadow_d = res.distances_int32(0)
        if not np.array_equal(np.asarray(job.distances), shadow_d):
            i = int(np.flatnonzero(
                np.asarray(job.distances) != shadow_d
            )[0])
            return (
                f"distance mismatch at vertex {i}: served "
                f"{int(np.asarray(job.distances)[i])} vs shadow "
                f"{int(shadow_d[i])}"
            )
    elif job.levels is not None and job.kind in ("bfs", "sssp"):
        shadow_levels = int(np.asarray(res.ecc)[0])
        if int(job.levels) != shadow_levels:
            return (
                f"levels mismatch: served {job.levels} vs shadow "
                f"{shadow_levels}"
            )
    return None


class ShadowAuditor:
    """The background replay worker. ``replay(spec_fn, registry)`` are
    bound by the integrity tier; this class owns only the queue, the
    thread, and the compare."""

    def __init__(self, *, acquire_engine, on_mismatch, metrics, log=None,
                 max_pending: int = 64, retries: int = 1,
                 max_pending_bytes: int = 256 * 1024 * 1024,
                 current_state=None):
        self._acquire_engine = acquire_engine  # (width, kind) -> engine
        self._on_mismatch = on_mismatch  # (job, detail) -> None
        # () -> (generation, epoch): the service's live overlay state
        # (ISSUE 19). None on static services — every job's stamps are
        # (0, 0) and nothing is ever dropped.
        self._current_state = current_state
        self._metrics = metrics
        self._log = log or (lambda msg: None)
        self._retries = max(int(retries), 0)
        self._q: _queue.Queue = _queue.Queue(maxsize=max(1, int(max_pending)))
        # Byte budget next to the count bound: each bfs/sssp job pins a
        # full [V] int32 distance row, so at serving scales the 64-deep
        # backlog alone could hold gigabytes of host arrays (the same
        # [V]-pinning class the resume cache bounds) — past the budget,
        # audits shed, serving never notices.
        self._max_pending_bytes = max(int(max_pending_bytes), 1)
        self._pending_lock = threading.Lock()
        self._pending_bytes = 0  # guarded-by: _pending_lock
        self._thread: threading.Thread | None = None
        self._stopped = False  # lock-free flag (submit-side shed only)

    @staticmethod
    def _job_bytes(job: ShadowJob) -> int:
        d = job.distances
        return 256 + (0 if d is None else int(np.asarray(d).nbytes))

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "ShadowAuditor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="bfs-serve-audit", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Drain every queued audit, then stop the worker. Idempotent."""
        self._stopped = True
        thread = self._thread
        if thread is None:
            return
        self._q.put(None)  # sentinel AFTER the queued jobs: full drain
        thread.join()
        self._thread = None

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until every enqueued audit has been processed (the
        bench/smoke barrier before reading the audit counters). True on
        a complete flush."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._q.unfinished_tasks == 0

    # --- submission (extraction-worker side) ------------------------------

    def offer(self, job: ShadowJob) -> bool:
        """Enqueue one sampled resolution; sheds (False) when the audit
        backlog is full or the auditor stopped — audits degrade, serving
        never blocks."""
        if self._stopped:
            return False
        cost = self._job_bytes(job)
        with self._pending_lock:
            if self._pending_bytes + cost > self._max_pending_bytes:
                over = True
            else:
                over = False
                self._pending_bytes += cost
        if over:
            self._metrics.record_audit_dropped()
            return False
        try:
            self._q.put_nowait(job)
            return True
        except _queue.Full:
            with self._pending_lock:
                self._pending_bytes -= cost
            self._metrics.record_audit_dropped()
            return False

    # --- the worker -------------------------------------------------------

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                self._audit(job)
            except Exception as exc:  # noqa: BLE001 — audit must not die
                self._metrics.record_audit_error()
                self._log(f"shadow audit errored (query "
                          f"{job.query_id!r}): {type(exc).__name__}: "
                          f"{str(exc)[:200]}")
            finally:
                cost = self._job_bytes(job)
                with self._pending_lock:
                    self._pending_bytes -= cost
                self._q.task_done()

    def _replay(self, job: ShadowJob):
        if _faults.ACTIVE is not None:
            # Chaos site: kinds scheduled here target the audit tier
            # itself (a transient shadow replay must degrade to an audit
            # error, never a serving failure — tests pin it).
            _faults.ACTIVE.hit("audit_shadow", lanes=job.width,
                               devices=job.devices)
        engine = self._acquire_engine(job.width, job.kind)
        kwargs = {}
        if job.kind == "khop":
            kwargs["k"] = int(job.k)
        elif job.kind == "p2p":
            kwargs["targets"] = np.asarray([int(job.target)], dtype=np.int64)
        return engine.run(
            np.asarray([job.source], dtype=np.int64), time_it=False, **kwargs
        )

    def _audit(self, job: ShadowJob) -> None:
        if (self._current_state is not None
                and (job.generation, job.epoch) != self._current_state()):
            # A flip, restage, or compaction landed between resolution
            # and replay: the served bits came from a different table
            # install than any engine we could replay on. Not a finding
            # — shed it (the staleness auditor replays such answers
            # against their own generation's host truth).
            self._metrics.record_audit_dropped()
            self._log(
                f"shadow audit shed (query {job.query_id!r}): served "
                f"overlay state (gen {job.generation}, epoch "
                f"{job.epoch}) superseded"
            )
            return
        attempt = 0
        while True:
            try:
                res = self._replay(job)
                break
            except Exception as exc:  # noqa: BLE001 — retried, then counted
                from tpu_bfs.utils.recovery import is_transient_failure

                if is_transient_failure(exc) and attempt < self._retries:
                    attempt += 1
                    continue
                raise
        detail = compare_payloads(job, res)
        if (detail is not None and self._current_state is not None
                and (job.generation, job.epoch) != self._current_state()):
            # The flip/restage landed DURING the replay (after the entry
            # check, before the compare): the replay engine may have
            # synced to the new overlay mid-acquire, so the mismatch is
            # the graph changing, not corruption. Shed, don't indict.
            self._metrics.record_audit_dropped()
            self._log(
                f"shadow audit shed (query {job.query_id!r}): "
                f"overlay state changed mid-replay"
            )
            return
        lag_ms = (time.monotonic() - job.t_resolved) * 1e3
        self._metrics.record_audit(lag_ms, failed=detail is not None)
        if detail is not None:
            self._on_mismatch(job, detail)
