"""Staleness auditor for dynamic-graph serving (ISSUE 19).

The dynamic tier's failure mode the other integrity detectors cannot
see: a TORN FLIP. A mutation batch advances the served generation (the
registry rekeys, the cache adopts the new key, the response metadata
says generation G) but some engine's overlay swap never landed — the
device tables still encode G-1 (or older). Every structural predicate
passes (the answer IS a valid BFS over *some* graph) and a shadow
replay on a disjoint rung of the same torn service reproduces the same
stale answer, so both existing detectors certify it. Only a replay
against the GENERATION'S OWN host truth can tell.

This auditor keeps a bounded ring of recent generation snapshots (host
:class:`~tpu_bfs.graph.csr.Graph` objects, pushed by the serve flip
path) and replays a deterministic sample of resolved queries against
CPU oracles (the reference discipline — bfsCPU/checkOutput,
bfs.cu:374-384 — applied per generation): queue BFS for bfs, binary-heap
Dijkstra for sssp. For each sampled answer it walks the ring newest
generation first and reports how many flips behind the newest matching
generation sits:

    staleness = (generation the batch was stamped with at dispatch)
              - (newest generation whose oracle reproduces the answer)

A correct service always measures 0 — batches are stamped inside the
flip lock, so the stamp names the exact tables the traversal read, and
in-flight queries pinned to an older generation match that older
generation's stamp. Anything > ``bound`` (default 0) is a CONFIRMED
over-bound stale answer: the ``on_over_bound`` callback quarantines the
stale serving state (the frontend restages the overlay onto every
resident engine, quarantines the answer cache, and flight-dumps naming
the stale generation's artifact). An answer matching NO ringed
generation is not a staleness finding — it is corruption, the shadow /
structural tier's jurisdiction — and is counted separately.

Runs synchronously on the extraction worker inside the observe hook
(the IntegrityTier seal applies: an auditor bug must never become a
serving incident), so the cost is one host-oracle traversal per sampled
query — bounded by the sampling rate, zero on un-audited services.
"""

from __future__ import annotations

import heapq
import threading
from collections import OrderedDict, deque

import numpy as np

from tpu_bfs.graph.csr import INF_DIST

#: Default ring depth: how many recent generations a stale answer can be
#: attributed to. Older-than-the-ring answers report as unmatched.
DEFAULT_WINDOW = 4


def oracle_bfs(graph, source: int) -> np.ndarray:
    """Queue BFS distances (int32, INF_DIST unreached) — the bfsCPU
    analog, independent of every device code path."""
    n = graph.num_vertices
    dist = np.full(n, INF_DIST, np.int32)
    dist[source] = 0
    q = deque([int(source)])
    row_ptr, col_idx = graph.row_ptr, graph.col_idx
    while q:
        u = q.popleft()
        du = dist[u] + 1
        for v in col_idx[row_ptr[u]:row_ptr[u + 1]]:
            if dist[v] == INF_DIST:
                dist[v] = du
                q.append(int(v))
    return dist


def oracle_sssp(graph, source: int) -> np.ndarray:
    """Binary-heap Dijkstra over the int32 weights plane (int32,
    INF_DIST unreached) — matches SsspBatchResult.distances_int32's
    sentinel convention."""
    n = graph.num_vertices
    dist = np.full(n, INF_DIST, np.int32)
    done = np.zeros(n, bool)
    dist[source] = 0
    heap = [(0, int(source))]
    row_ptr, col_idx, wts = graph.row_ptr, graph.col_idx, graph.weights
    while heap:
        d, u = heapq.heappop(heap)
        if done[u]:
            continue
        done[u] = True
        for j in range(int(row_ptr[u]), int(row_ptr[u + 1])):
            v = int(col_idx[j])
            nd = d + int(wts[j])
            if not done[v] and nd < dist[v]:
                dist[v] = nd
                heapq.heappush(heap, (nd, v))
    return dist


#: Kind -> oracle. Only kinds with a full distance row are auditable
#: here; metadata-only kinds (cc/khop) are covered by the structural
#: tier and the fuzz oracle, not per-generation replay.
ORACLES = {"bfs": oracle_bfs, "sssp": oracle_sssp}


class StalenessAuditor:
    """The ring + sampled per-generation replay. The serve flip path
    calls :meth:`push_generation` after every applied mutation batch;
    the extraction worker calls :meth:`observe_batch` after every
    resolved batch."""

    def __init__(self, *, rate: float, seed: int = 0, bound: int = 0,
                 window: int = DEFAULT_WINDOW, on_over_bound=None,
                 log=None):
        from tpu_bfs.integrity.shadow import AuditSampler

        self.bound = max(int(bound), 0)
        self.window = max(int(window), 2)
        # Decorrelated from the shadow sampler (seed + 1): the two
        # audits should not always pick the same queries.
        self._sampler = AuditSampler(rate, seed + 1)
        self._on_over_bound = on_over_bound or (lambda **kw: None)
        self._log = log or (lambda msg: None)
        self._lock = threading.Lock()
        self._ring: OrderedDict = OrderedDict()  # guarded-by: _lock — gen -> Graph
        self._oracle_cache: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._audits = 0  # guarded-by: _lock
        self._stale = 0  # guarded-by: _lock — matched an OLDER generation
        self._over_bound = 0  # guarded-by: _lock
        self._unmatched = 0  # guarded-by: _lock — corruption, not staleness
        self._errors = 0  # guarded-by: _lock

    # --- the flip-path hook -----------------------------------------------

    def push_generation(self, generation: int, graph) -> None:
        """Adopt ``graph`` as generation ``generation``'s host truth
        (the DynamicGraph's materialized from-scratch twin). Evicts past
        the window; drops the oracle memo rows of evicted generations."""
        with self._lock:
            self._ring[int(generation)] = graph
            self._ring.move_to_end(int(generation))
            while len(self._ring) > self.window:
                old, _ = self._ring.popitem(last=False)
                for key in [k for k in self._oracle_cache if k[0] == old]:
                    del self._oracle_cache[key]

    # --- the extraction-worker hook ---------------------------------------

    def observe_batch(self, pending) -> None:
        """Sampled replay of one resolved batch. Sealed: never lets an
        exception reach the serving path."""
        served_gen = int(getattr(pending, "generation", 0))
        for q in pending.queries:
            try:
                r = q.result(0)
                if not r.ok or r.kind not in ORACLES:
                    continue
                if getattr(r, "distances", None) is None:
                    continue
                if not self._sampler.should_sample():
                    continue
                self._audit_one(q, r, served_gen)
            except Exception as exc:  # noqa: BLE001 — the integrity seal
                with self._lock:
                    self._errors += 1
                self._log(
                    f"staleness audit errored (query "
                    f"{getattr(q, 'id', None)!r}): "
                    f"{type(exc).__name__}: {str(exc)[:200]}"
                )

    def _oracle_row(self, generation: int, kind: str,
                    source: int) -> np.ndarray | None:
        with self._lock:
            graph = self._ring.get(generation)
            key = (generation, kind, int(source))
            row = self._oracle_cache.get(key)
        if graph is None:
            return None
        if row is None:
            row = ORACLES[kind](graph, int(source))
            with self._lock:
                self._oracle_cache[key] = row
                while len(self._oracle_cache) > 4 * self.window:
                    self._oracle_cache.popitem(last=False)
        return row

    def _audit_one(self, q, r, served_gen: int) -> None:
        with self._lock:
            self._audits += 1
            gens = list(self._ring)
        served = np.asarray(r.distances, np.int32)
        # Newest first: the common case (staleness 0) matches on the
        # first replay and pays exactly one oracle traversal.
        for gen in sorted(gens, reverse=True):
            if gen > served_gen:
                continue
            truth = self._oracle_row(gen, r.kind, r.source)
            if truth is None or truth.shape != served.shape:
                continue
            if not np.array_equal(truth, served):
                continue
            staleness = served_gen - gen
            if staleness <= 0:
                return
            with self._lock:
                self._stale += 1
                over = staleness > self.bound
                if over:
                    self._over_bound += 1
            if over:
                self._on_over_bound(
                    query_id=q.id, kind=r.kind, source=r.source,
                    served_generation=served_gen, matched_generation=gen,
                    staleness=staleness,
                    detail=(
                        f"{r.kind} answer stamped generation "
                        f"{served_gen} reproduces generation {gen}'s "
                        f"oracle ({staleness} flip(s) stale, bound "
                        f"{self.bound})"
                    ),
                )
            return
        # No ringed generation reproduces it: that is a wrong answer,
        # not a stale one — count it and leave the indictment to the
        # shadow/structural detectors (which compare against the LIVE
        # config and own rung quarantine).
        with self._lock:
            self._unmatched += 1
        self._log(
            f"staleness audit: query {q.id!r} ({r.kind}) matches no "
            f"generation in the window {sorted(gens)} — corruption "
            f"territory, deferred to the shadow/structural tier"
        )

    # --- introspection ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "audits": self._audits,
                "stale": self._stale,
                "over_bound": self._over_bound,
                "unmatched": self._unmatched,
                "errors": self._errors,
                "bound": self.bound,
                "window": len(self._ring),
            }
