"""Structural result audits: the validate.py / graph500.py tree
predicates as fused device kernels, run in-band on served batches.

The one-shot paths validate against a CPU golden (``tpu_bfs/validate``)
or the Graph500 property checks (``graph500.py``) — both host-side,
O(E) NumPy passes that only ever run in bench/one-shot mode. The serve
tier needs the same predicates CONTINUOUSLY and cheaply: this module
compiles them as one fused gather-compare-reduce over the graph's edge
list held on device, so auditing a lane costs one [V] host->device
transfer plus a scalar readback — no O(E) host arithmetic on the
extraction worker, and the device copy doubles as the far side of the
``audit_checksum`` wire check (integrity/wire.py: the host and device
folds over the same row must agree, or the transfer corrupted it).

Per kind:

- **bfs** — ``dist[source] == 0``, reached-count agreement, and the
  Graph500 edge-level property (``dist[v] <= dist[u] + 1`` over every
  directed edge slot with ``u`` reached — validate.check_edge_levels,
  fused).
- **sssp** — the weighted relaxation property ``dist[v] <= dist[u] +
  w(u, v)`` (the Bellman-Ford fixed-point certificate), plus the source
  row.
- **p2p** — path validity on host (paths are O(levels), not O(E)):
  endpoints, length == distance, every consecutive pair an edge of the
  graph (binary search over the packed sorted edge keys, built lazily
  once).
- **cc / khop** — range/consistency sanity over the extras (label in
  range, component size == reached, k echoed; these kinds answer from
  reductions with no per-vertex table to check structurally).

A finding means the SERVED ANSWER violates a property every correct
answer satisfies — corruption, not noise; the quarantine path treats it
as confirmed. Audit-infrastructure failures (the kernel itself erroring)
are reported separately and never quarantine.
"""

from __future__ import annotations

import threading

import numpy as np

from tpu_bfs.graph.csr import INF_DIST


class StructuralFinding(Exception):
    """One confirmed structural violation in a served answer."""


class StructuralAuditor:
    """Fused device-side structure checks over one graph.

    Thread-safe for the single extraction-worker caller the serve tier
    has; the lazy device tables are built under a lock so a second
    auditor thread (tests) cannot double-transfer the edge list."""

    def __init__(self, graph, *, checksum: bool = False):
        self._g = graph
        self._checksum = bool(checksum)
        self._lock = threading.Lock()
        self._dev = None  # guarded-by: _lock — lazy device edge tables
        self._kern = {}  # guarded-by: _lock — jitted check kernels
        self._csum = None  # guarded-by: _lock — device checksum kernel
        self._edge_keys = None  # guarded-by: _lock — sorted int64 edge keys
        self._bind_token = 0  # guarded-by: _lock — bumped by rebind()

    # --- lazy device state ------------------------------------------------

    def rebind(self, graph) -> None:
        """Swap the audited graph (ISSUE 19: the serve flip path rebinds
        the auditor to each new generation's materialized twin). Drops
        the cached device edge tables, the host edge-key set, AND the
        jitted check kernels — they close over the edge tables as
        compile-time constants, and E changes across generations anyway.
        The V-shaped checksum kernel survives (V never changes).
        Everything rebuilds lazily on the next audit."""
        with self._lock:
            self._g = graph
            self._dev = None
            self._edge_keys = None
            self._kern = {}
            self._bind_token += 1

    def prepare(self) -> None:
        """Pay the one-time costs NOW (the integrity tier calls this on
        the cold-start path): the device edge tables (a 2-3 x E x 4-byte
        host->device transfer that must not stall the extraction worker
        mid-traffic — and a real HBM cost next to the engines' own
        tables, documented in README "Result integrity") and the check/
        checksum kernel compiles for the kinds that use them."""
        import jax.numpy as jnp

        self._edges_dev()
        # One dummy row through each kernel: jax.jit compiles at first
        # CALL, so constructing alone would still leave the compile on
        # the first audited batch.
        zero = jnp.zeros(self._g.num_vertices, jnp.int32)
        self._kernel("bfs")(zero)
        if self._g.weights is not None:
            self._kernel("sssp")(zero)
        if self._checksum:
            self._checksum_kernel()(zero)

    def _edges_dev(self):
        import jax.numpy as jnp

        with self._lock:
            if self._dev is None:
                src, dst = self._g.coo
                w = self._g.weights
                self._dev = (
                    jnp.asarray(src.astype(np.int32)),
                    jnp.asarray(dst.astype(np.int32)),
                    None if w is None else jnp.asarray(w.astype(np.int32)),
                )
            return self._dev

    def _kernel(self, kind: str):
        import jax
        import jax.numpy as jnp

        with self._lock:
            k = self._kern.get(kind)
            token = self._bind_token
        if k is not None:
            return k
        srcv, dstv, wv = self._edges_dev()

        if kind == "sssp":
            @jax.jit
            def check(dist):
                du = dist[srcv]
                dv = dist[dstv]
                bad = (du != INF_DIST) & (dv > du + wv)
                return jnp.sum(bad.astype(jnp.int32))
        else:
            @jax.jit
            def check(dist):
                du = dist[srcv]
                dv = dist[dstv]
                bad = (du != INF_DIST) & (dv > du + 1)
                return jnp.sum(bad.astype(jnp.int32))

        with self._lock:
            # A rebind() racing this build means the captured tables may
            # be the superseded generation's — usable for THIS call
            # (the caller's generation gate decides), but never cached.
            if self._bind_token == token:
                self._kern[kind] = check
        return check

    def _checksum_kernel(self):
        from tpu_bfs.integrity.wire import make_i32_checksum

        with self._lock:
            if self._csum is None:
                self._csum = make_i32_checksum(self._g.num_vertices)
            return self._csum

    def _edge_key_set(self) -> np.ndarray:
        with self._lock:
            if self._edge_keys is None:
                src, dst = self._g.coo
                n = np.int64(self._g.num_vertices)
                self._edge_keys = np.sort(
                    src.astype(np.int64) * n + dst.astype(np.int64)
                )
            return self._edge_keys

    def _has_edge(self, u: int, v: int) -> bool:
        keys = self._edge_key_set()
        q = np.int64(u) * np.int64(self._g.num_vertices) + np.int64(v)
        j = np.searchsorted(keys, q)
        return j < len(keys) and keys[j] == q

    # --- the audit --------------------------------------------------------

    def audit(self, kind: str, result) -> None:
        """Check one served :class:`~tpu_bfs.serve.scheduler.QueryResult`.
        Raises :class:`StructuralFinding` on a confirmed violation;
        returns quietly when the answer satisfies every checkable
        property. Any other exception is an audit-infrastructure error
        (the caller counts it; it never quarantines)."""
        from tpu_bfs import faults as _faults

        if _faults.ACTIVE is not None:
            # Chaos site: a transient/slow kind scheduled here targets
            # the audit tier itself — the tier must degrade to an audit
            # error, never to a serving failure (tests pin it).
            _faults.ACTIVE.hit("audit_structural", lanes=0)
        if kind in ("bfs", "sssp") and result.distances is not None:
            self._audit_distances(kind, result)
        elif kind == "p2p":
            self._audit_p2p(result)
        elif kind == "cc":
            self._audit_cc(result)
        elif kind == "khop":
            self._audit_khop(result)
        else:
            # Metadata-only bfs/sssp (no distance table to check):
            # range sanity is all that exists.
            self._sanity(result)

    def _wire_verify(self, dist_np: np.ndarray, dev) -> None:
        """The audit_checksum half (integrity/wire.py): the device copy
        just transferred and the host row it came from must fold to the
        same checksum, or the host->device wire corrupted the audit's
        input. ``corrupt_wire`` fault rules flip a bit of the host copy
        between the two folds, driving this red deterministically."""
        from tpu_bfs import faults as _faults
        from tpu_bfs.integrity.wire import words_checksum_np

        host = dist_np
        if _faults.ACTIVE is not None and _faults.ACTIVE.take(
            "fetch", "corrupt_wire", n=len(dist_np)
        ):
            host = dist_np.copy()
            fin = np.flatnonzero(host != INF_DIST)
            i = fin[len(fin) // 2] if len(fin) else 0
            host[i] ^= 1
        dev_sum = int(self._checksum_kernel()(dev))
        host_sum = words_checksum_np(host.astype(np.int32))
        if dev_sum != host_sum:
            raise StructuralFinding(
                f"wire checksum mismatch on the audited distance row: "
                f"device fold {dev_sum:#010x} != host fold "
                f"{host_sum:#010x} — the transfer corrupted the data"
            )

    def _audit_distances(self, kind: str, result) -> None:
        import jax.numpy as jnp

        dist = np.asarray(result.distances)
        if dist.shape != (self._g.num_vertices,):
            raise StructuralFinding(
                f"distance row is {dist.shape}, graph has "
                f"{self._g.num_vertices} vertices"
            )
        if int(dist[result.source]) != 0:
            raise StructuralFinding(
                f"source {result.source} at distance "
                f"{int(dist[result.source])}, not 0"
            )
        reached = int((dist != INF_DIST).sum())
        if result.reached is not None and reached != int(result.reached):
            raise StructuralFinding(
                f"reached count {result.reached} disagrees with the "
                f"distance row's {reached} finite entries"
            )
        dev = jnp.asarray(dist.astype(np.int32))
        if self._checksum:
            self._wire_verify(dist, dev)
        bad = int(self._kernel(kind)(dev))
        if bad:
            raise StructuralFinding(
                f"{bad} edge(s) violate the "
                + ("weighted relaxation property (dist[v] > dist[u] + w)"
                   if kind == "sssp"
                   else "level property (dist[v] > dist[u] + 1)")
                + f" for {kind} from source {result.source}"
            )

    def _audit_p2p(self, result) -> None:
        ex = result.extras or {}
        met = ex.get("met")
        distance = ex.get("distance")
        path = ex.get("path")
        target = ex.get("target")
        if not met:
            if distance is not None or path is not None:
                raise StructuralFinding(
                    "unmet p2p answer carries a distance/path"
                )
            return
        if path is None or distance is None:
            raise StructuralFinding("met p2p answer without a path")
        if len(path) != distance + 1:
            raise StructuralFinding(
                f"p2p path length {len(path)} disagrees with distance "
                f"{distance}"
            )
        if path[0] != result.source or (
            target is not None and path[-1] != target
        ):
            raise StructuralFinding(
                f"p2p path endpoints ({path[0]}, {path[-1]}) are not "
                f"(source={result.source}, target={target})"
            )
        for u, v in zip(path, path[1:]):
            if not self._has_edge(int(u), int(v)):
                raise StructuralFinding(
                    f"p2p path edge ({u}, {v}) is not in the graph"
                )

    def _audit_cc(self, result) -> None:
        ex = result.extras or {}
        label = ex.get("component")
        size = ex.get("component_size")
        total = ex.get("components")
        v = self._g.num_vertices
        if label is None or not (0 <= int(label) < v):
            raise StructuralFinding(f"cc label {label!r} out of range")
        if size is None or not (1 <= int(size) <= v):
            raise StructuralFinding(f"cc component size {size!r} out of range")
        if result.reached is not None and int(size) != int(result.reached):
            raise StructuralFinding(
                f"cc component size {size} disagrees with reached "
                f"{result.reached}"
            )
        if total is None or not (1 <= int(total) <= v):
            raise StructuralFinding(f"cc component count {total!r} invalid")

    def _audit_khop(self, result) -> None:
        ex = result.extras or {}
        k = ex.get("k")
        if k is None or int(k) < 0:
            raise StructuralFinding(f"khop answer with invalid k={k!r}")
        self._sanity(result)

    def _sanity(self, result) -> None:
        v = self._g.num_vertices
        if result.reached is not None and not (
            1 <= int(result.reached) <= v
        ):
            raise StructuralFinding(
                f"reached count {result.reached} outside [1, {v}]"
            )
        # levels is hop-count for bfs/khop but WEIGHTED eccentricity for
        # sssp (legitimately > V); only negativity is universally wrong.
        if result.levels is not None and int(result.levels) < 0:
            raise StructuralFinding(f"negative levels {result.levels}")
