"""Wire checksums: an order-sensitive uint32 fold over packed words.

The exchanges (PR 5/7) move uint32 word streams; result extraction moves
distance words device -> host. Neither path carries any in-band
integrity: a flipped bit on the interconnect (or in an HBM word between
kernel and DMA) arrives as a perfectly well-formed word and serves as a
wrong answer. This module is the shared checksum codec the integrity
tier folds over both:

- :func:`words_checksum_np` / :func:`make_words_checksum` — a
  multiply-accumulate fold with per-position odd multipliers
  (splitmix-derived). Position-dependent, so swapped words are caught;
  every multiplier is odd, so flipping ANY single bit of ANY word
  changes the fold (odd x 2^b is never 0 mod 2^32 — the
  single-bit-flip guarantee the unit tests pin exhaustively). One
  definition, two implementations that agree bit-for-bit: the jit
  kernel (device side of a transfer) and the NumPy fold (host side).
- :func:`append_checksum` / :func:`split_verify` — the +1-word wire
  frame for exchange chunks: sender appends the fold, receiver strips
  and recomputes. Cost is exactly 4 bytes per chunk per hop, proven
  from the compiled HLO in ``utils/wirecheck.check_wire_checksum``.
- :func:`checksummed_ring_or` — the reference checksummed exchange: a
  packed ring reduce-scatter-OR (the PR 5 wire shape) with every hop's
  chunk framed, returning ``(result, bad_hops)`` so an engine can
  surface wire corruption at fetch time. This is the flag-gated form
  the HLO byte proof compiles; engines adopt it as their exchanges
  migrate (the serve tier's ``audit_checksum`` flag meanwhile folds the
  same codec over the extraction transfer — integrity/structural.py).

int32/uint32 only throughout (the analysis pass 4 dtype lint bans
64-bit device words); the host fold uses a uint64 accumulator off
device, masked back to 32 bits.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def _mults_np(n: int) -> np.ndarray:
    """Per-position odd multipliers: a splitmix32-style hash of the word
    index, forced odd. Host reference; the device fold reuses this exact
    table as a compile-time constant, so the two stay bit-identical."""
    x = (np.arange(n, dtype=np.uint64) * np.uint64(0x9E3779B9)) & np.uint64(
        0xFFFFFFFF
    )
    x = x.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x = (
        (x.astype(np.uint64) * np.uint64(0x85EBCA6B)) & np.uint64(0xFFFFFFFF)
    ).astype(np.uint32)
    x ^= x >> np.uint32(13)
    return x | np.uint32(1)


def words_checksum_np(arr: np.ndarray) -> int:
    """Host fold: uint32 checksum of ``arr``'s bytes (any integer dtype;
    the flat byte view is zero-padded to whole uint32 words)."""
    raw = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
    pad = (-len(raw)) % 4
    if pad:
        raw = np.concatenate([raw, np.zeros(pad, np.uint8)])
    w = raw.view(np.uint32)
    m = _mults_np(len(w))
    return int((w.astype(np.uint64) * m.astype(np.uint64)).sum()
               & np.uint64(0xFFFFFFFF))


def _fold(w, mults):
    """Traced uint32 multiply-accumulate, 32-bit end to end: lo/hi
    16-bit partial products in wraparound uint32 (the dtype lint bans a
    64-bit accumulator on device; wraparound sums commute, so the split
    matches the host's masked 64-bit fold exactly)."""
    w = w.astype(jnp.uint32)
    lo = (w & jnp.uint32(0xFFFF)) * mults
    hi = ((w >> jnp.uint32(16)) * mults) << jnp.uint32(16)
    return jnp.sum(lo + hi, dtype=jnp.uint32)


def make_words_checksum(n_words: int):
    """Device twin of :func:`words_checksum_np` over a flat uint32
    ``[n_words]`` array -> uint32 scalar. Built per length so the
    multiplier table is a baked constant."""
    mults = jnp.asarray(_mults_np(n_words))

    @jax.jit
    def checksum(words):
        return _fold(words, mults)

    return checksum


def make_i32_checksum(n: int):
    """Device checksum over an int32 ``[n]`` array (distance rows): the
    int32 bits reinterpreted as uint32 words, same fold — so the host
    side simply calls :func:`words_checksum_np` on the int32 array."""
    mults = jnp.asarray(_mults_np(n))

    @jax.jit
    def checksum(arr):
        return _fold(jax.lax.bitcast_convert_type(arr, jnp.uint32), mults)

    return checksum


def append_checksum(words):
    """Frame one exchange chunk: ``[n] uint32 -> [n+1]`` with the fold in
    the last word. Traceable; the +1 word is the whole wire cost
    (4 bytes/chunk/hop, HLO-pinned in wirecheck)."""
    n = int(words.shape[-1])
    mults = jnp.asarray(_mults_np(n))
    w = words.astype(jnp.uint32)
    return jnp.concatenate([w, _fold(w, mults)[None]])


def split_verify(framed):
    """Strip one frame: ``[n+1] -> ([n] payload, ok bool scalar)``. The
    receiver recomputes the fold over the payload it actually received;
    ``ok`` is False exactly when the wire changed any bit of the frame
    (payload or checksum word)."""
    payload = framed[:-1]
    n = int(payload.shape[-1])
    mults = jnp.asarray(_mults_np(n))
    return payload, _fold(payload, mults) == framed[-1]


def checksummed_ring_or(chunks, axis_name: str, *, wire_check: bool = True):
    """Packed ring reduce-scatter-OR with per-hop chunk checksums.

    ``chunks``: ``[P, words] uint32`` — this shard's per-destination
    pieces. Returns ``(own [words] uint32, bad_hops int32 scalar)``:
    ``own`` is the OR over all shards of their piece for this shard,
    ``bad_hops`` counts hops whose received frame failed verification
    (0 on a healthy wire — a nonzero count at fetch is the corruption
    finding the serve tier quarantines on). With ``wire_check=False``
    the frames are skipped entirely — byte-identical to the plain
    packed ring, the A/B ``check_wire_checksum`` compiles.

    The ring is the standard one: the piece for destination ``d``
    starts at shard ``d+1`` and accumulates each visited shard's chunk
    over ``P-1`` hops (unrolled, so the HLO proof counts the permutes
    individually). Cost with checksums: ``(P-1) * 4`` extra bytes per
    shard per exchange — one word per hop."""
    p = int(chunks.shape[0])
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]
    # Before any hop this shard holds the partial for destination idx-1.
    buf = jax.lax.dynamic_index_in_dim(
        chunks, jnp.mod(idx - 1, p), keepdims=False
    )
    bad = jnp.int32(0)
    for k in range(p - 1):
        if wire_check:
            framed = jax.lax.ppermute(append_checksum(buf), axis_name, perm)
            received, ok = split_verify(framed)
            bad = bad + jnp.where(ok, jnp.int32(0), jnp.int32(1))
        else:
            received = jax.lax.ppermute(buf, axis_name, perm)
        # Received: the partial for destination idx-k-2; fold in this
        # shard's own piece for it and keep forwarding. At the last hop
        # the destination is idx itself and the fold completes.
        d = jnp.mod(idx - k - 2, p)
        buf = received | jax.lax.dynamic_index_in_dim(
            chunks, d, keepdims=False
        )
    return buf, bad
