// Native graph-ingestion fast paths for tpu_bfs, exposed via ctypes.
//
// The reference's loader is C++ (readGraphFromFile, bfs.cu:829-880: ifstream
// `f >> u >> v` over m edge lines). This implementation replaces the
// formatted-stream parse with a single read() + branch-light integer scanner
// (~100x faster on multi-GB edge lists), handles '%'/'#' comment lines and
// 1-indexed MatrixMarket bodies, and returns raw endpoint arrays; CSR
// construction stays in NumPy (vectorized counting sort).
//
// Exported C ABI (see tpu_bfs/utils/native.py):
//   tpubfs_parse_edge_list(path, &n, &m, &u, &v) -> 0 on success
//   tpubfs_free(ptr)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

struct Scanner {
  const char* p;
  const char* end;

  void skip_ws_and_comments() {
    while (p < end) {
      char c = *p;
      if (c == ' ' || c == '\t' || c == '\r' || c == '\n') {
        ++p;
      } else if (c == '%' || c == '#') {
        while (p < end && *p != '\n') ++p;
      } else {
        break;
      }
    }
  }

  // Parses a non-negative number; tolerates a floating-point tail (.5e3) by
  // consuming and ignoring it (MatrixMarket weight columns).
  bool next_int(int64_t* out) {
    skip_ws_and_comments();
    if (p >= end) return false;
    int64_t v = 0;
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
      v = v * 10 + (*p - '0');
      any = true;
      ++p;
    }
    if (!any) return false;
    // Swallow a fractional / exponent tail so weighted .mtx rows parse.
    if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) {
      while (p < end && *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r') ++p;
    }
    *out = v;
    return true;
  }

  // Count how many whitespace-separated tokens remain on the current line.
  int tokens_on_line() const {
    const char* q = p;
    int count = 0;
    bool in_tok = false;
    while (q < end && *q != '\n') {
      bool ws = (*q == ' ' || *q == '\t' || *q == '\r');
      if (!ws && !in_tok) {
        ++count;
        in_tok = true;
      } else if (ws) {
        in_tok = false;
      }
      ++q;
    }
    return count;
  }
};

}  // namespace

extern "C" {

// Returns 0 on success; 1 open failure; 2 parse failure; 3 alloc failure.
int64_t tpubfs_parse_edge_list(const char* path, int64_t* out_n,
                                 int64_t* out_m, int64_t** out_u,
                                 int64_t** out_v) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  if (fseek(f, 0, SEEK_END) != 0) {  // unseekable (FIFO/pipe): refuse cleanly
    fclose(f);
    return 1;
  }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return 1;
  }
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf) {
    fclose(f);
    return 3;
  }
  size_t got = fread(buf, 1, size, f);
  fclose(f);
  buf[got] = '\0';

  Scanner sc{buf, buf + got};
  sc.skip_ws_and_comments();
  int header_tokens = sc.tokens_on_line();
  int64_t n = 0, m = 0;
  bool one_indexed = false;
  if (header_tokens == 3) {
    // MatrixMarket size line: rows cols nnz (1-indexed body).
    int64_t rows, cols;
    if (!sc.next_int(&rows) || !sc.next_int(&cols) || !sc.next_int(&m)) {
      free(buf);
      return 2;
    }
    n = rows > cols ? rows : cols;
    one_indexed = true;
  } else if (header_tokens == 2) {
    // Reference format: "n m" (bfs.cu:845), 0-indexed body.
    if (!sc.next_int(&n) || !sc.next_int(&m)) {
      free(buf);
      return 2;
    }
  } else {
    free(buf);
    return 2;
  }

  int64_t* u = static_cast<int64_t*>(malloc(sizeof(int64_t) * (m ? m : 1)));
  int64_t* v = static_cast<int64_t*>(malloc(sizeof(int64_t) * (m ? m : 1)));
  if (!u || !v) {
    free(buf);
    free(u);
    free(v);
    return 3;
  }

  // Edge rows may carry a weight column; detect per-file from the first row.
  int row_tokens = 0;
  {
    Scanner probe = sc;
    probe.skip_ws_and_comments();
    row_tokens = probe.tokens_on_line();
  }
  bool has_weight = (row_tokens >= 3);

  int64_t base = one_indexed ? 1 : 0;
  for (int64_t i = 0; i < m; ++i) {
    int64_t a, b, w;
    if (!sc.next_int(&a) || !sc.next_int(&b)) {
      free(buf);
      free(u);
      free(v);
      return 2;
    }
    if (has_weight && !sc.next_int(&w)) {
      free(buf);
      free(u);
      free(v);
      return 2;
    }
    a -= base;
    b -= base;
    if (a < 0 || a >= n || b < 0 || b >= n) {
      free(buf);
      free(u);
      free(v);
      return 2;
    }
    u[i] = a;
    v[i] = b;
  }
  free(buf);
  *out_n = n;
  *out_m = m;
  *out_u = u;
  *out_v = v;
  return 0;
}

void tpubfs_free(int64_t* ptr) { free(ptr); }

}  // extern "C"

extern "C" {

// Stable two-pass counting sort of pairs: returns the permutation that orders
// by (major, minor) ascending — the O(E) replacement for np.lexsort((minor,
// major)) in CSR construction and partitioning. Keys must lie in [0, n_major)
// / [0, n_minor). Returns 0 on success, 3 on allocation failure.
int64_t tpubfs_lexsort_pairs(const int64_t* major, const int64_t* minor,
                             int64_t e, int64_t n_major, int64_t n_minor,
                             int64_t* out_perm) {
  // Reject out-of-range keys up front: the counting passes below index the
  // count array by key and would corrupt the heap on bad input (returning
  // nonzero triggers the caller's np.lexsort fallback instead).
  for (int64_t i = 0; i < e; ++i) {
    if (major[i] < 0 || major[i] >= n_major || minor[i] < 0 ||
        minor[i] >= n_minor) {
      return 2;
    }
  }
  int64_t* tmp = static_cast<int64_t*>(malloc(sizeof(int64_t) * (e ? e : 1)));
  int64_t nc = (n_major > n_minor ? n_major : n_minor) + 1;
  int64_t* count = static_cast<int64_t*>(calloc(nc, sizeof(int64_t)));
  if (!tmp || !count) {
    free(tmp);
    free(count);
    return 3;
  }
  // Pass 1: stable sort by minor -> tmp.
  for (int64_t i = 0; i < e; ++i) ++count[minor[i] + 1];
  for (int64_t i = 0; i < n_minor; ++i) count[i + 1] += count[i];
  for (int64_t i = 0; i < e; ++i) tmp[count[minor[i]]++] = i;
  // Pass 2: stable sort by major over tmp -> out_perm.
  memset(count, 0, sizeof(int64_t) * nc);
  for (int64_t i = 0; i < e; ++i) ++count[major[i] + 1];
  for (int64_t i = 0; i < n_major; ++i) count[i + 1] += count[i];
  for (int64_t i = 0; i < e; ++i) {
    int64_t idx = tmp[i];
    out_perm[count[major[idx]]++] = idx;
  }
  free(tmp);
  free(count);
  return 0;
}

}  // extern "C"
