// Native Graph500-style RMAT edge generator, exposed via ctypes.
//
// The reference has no generator beyond seeded uniform edges (readGraph,
// bfs.cu:892-907); the BASELINE.json scale targets need Kronecker/RMAT
// graphs whose NumPy generation costs ~2 minutes at scale 21. This threaded
// implementation produces the same distribution in seconds.
//
// Determinism: edge index space is split into fixed 64K-edge blocks; each
// block's RNG is seeded by splitmix64(seed, block), so the output depends
// only on (scale, edge_factor, seed, a, b, c) — never on the thread count.
//
// Exported C ABI (see tpu_bfs/utils/native.py):
//   tpubfs_rmat_edges(scale, m, seed, a, b, c, out_u, out_v) -> 0 on success

#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kBlock = 1 << 16;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Xoshiro256pp {
  uint64_t s[4];

  explicit Xoshiro256pp(uint64_t seed) {
    for (int i = 0; i < 4; ++i) {
      seed = splitmix64(seed);
      s[i] = seed;
    }
  }

  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }

  inline uint64_t next() {
    uint64_t result = rotl(s[0] + s[3], 23) + s[0];
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }

  // Uniform double in [0, 1) with 53 bits.
  inline double uniform() { return (next() >> 11) * 0x1.0p-53; }
};

}  // namespace

extern "C" {

int64_t tpubfs_rmat_edges(int64_t scale, int64_t m, int64_t seed, double a,
                          double b, double c, int64_t* out_u, int64_t* out_v) {
  if (scale < 1 || scale > 40 || m < 0) return 2;
  // Quadrant probabilities must leave room for d = 1-a-b-c > 0; a+b >= 1
  // would divide by zero (or flip sign) in c_norm below and emit silently
  // wrong edges with rc=0. Phrased positively so NaNs fail too.
  if (!(a > 0.0 && b >= 0.0 && c >= 0.0 && a + b + c < 1.0)) return 3;
  const double ab = a + b;
  const double a_norm = a / ab;
  const double c_norm = c / (1.0 - ab);

  const int64_t nblocks = (m + kBlock - 1) / kBlock;
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = hw ? static_cast<int>(hw) : 4;
  if (nthreads > nblocks) nthreads = static_cast<int>(nblocks ? nblocks : 1);

  auto work = [&](int t) {
    for (int64_t blk = t; blk < nblocks; blk += nthreads) {
      Xoshiro256pp rng(splitmix64(static_cast<uint64_t>(seed) * 0x100000001b3ULL +
                                  static_cast<uint64_t>(blk)));
      const int64_t lo = blk * kBlock;
      const int64_t hi = lo + kBlock < m ? lo + kBlock : m;
      for (int64_t e = lo; e < hi; ++e) {
        int64_t u = 0, v = 0;
        for (int64_t lvl = 0; lvl < scale; ++lvl) {
          const double ru = rng.uniform();
          const double rv = rng.uniform();
          const bool u_bit = ru > ab;
          const bool v_bit = rv > (u_bit ? c_norm : a_norm);
          u = (u << 1) | (u_bit ? 1 : 0);
          v = (v << 1) | (v_bit ? 1 : 0);
        }
        out_u[e] = u;
        out_v[e] = v;
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
