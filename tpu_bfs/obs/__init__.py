"""Unified telemetry: span tracing, per-level engine traces, exporters,
and the flight recorder (ISSUE 6).

One ACTIVE-guard discipline, copied from :mod:`tpu_bfs.faults`: every
production instrumentation site is a single module-attribute check
(``if obs.ACTIVE is not None``) against a global that stays ``None``
unless a recorder was explicitly armed — via ``--obs``/``--trace-out``
(CLI and serve), the ``TPU_BFS_OBS`` env var, or :func:`arm` in tests.
The un-armed hot path pays one attribute read per site and allocates
nothing (tests/test_obs.py pins that with a spy counter, mirroring the
faults determinism tests).

What the armed recorder collects:

- **spans/events** (:class:`~tpu_bfs.obs.recorder.Recorder`): a
  thread-safe ring buffer of ``time.monotonic``-stamped records wired
  through the full serve lifecycle (admit -> enqueue -> coalesce ->
  dispatch -> fetch -> extract -> resolve, plus registry build/warm and
  every retry/degrade/shed), keyed so each query id's chain carries its
  batch id, width rung, and attempt history;
- **per-level engine traces** (:mod:`~tpu_bfs.obs.engine_trace`): the
  packed dispatch/fetch halves and the distributed engines expose
  ``last_run_trace`` — per BFS level: frontier population, push/pull
  direction, gated-tile skips, cap-ladder exchange choice, and modeled
  wire bytes priced from ``wire_bytes_per_level()``;
- **flight recorder**: the ring buffer auto-dumps its last
  ``window_s`` seconds to a timestamped JSONL file on watchdog trip,
  breaker open, requeue shed, uncaught executor error, or SIGTERM
  drain — every chaos-harness failure becomes a replayable artifact;
- **exporters** (:mod:`~tpu_bfs.obs.exporters`): Chrome/Perfetto
  trace-event JSON (``--trace-out``), Prometheus-style text
  (``/metricz`` via ``BfsService.metricz`` and ``--metricz-out``), and
  plain JSONL.

Spec grammar (``--obs`` / ``TPU_BFS_OBS``)::

    spec  := "1" | "true" | "0" | "off" | kv ("," kv)*
    kv    := "capacity=" INT | "window=" FLOAT (seconds)
           | "dump_dir=" PATH | "max_dumps=" INT

Example: ``TPU_BFS_OBS=dump_dir=/tmp/flightrec,window=60``. Falsy
values (``0``/``false``/``off``/``no``) leave telemetry DISARMED — a
fleet-standard disable value must never kill the process (the same
never-die-on-an-env-knob rule bench._env_bool keeps).
"""

from __future__ import annotations

import contextlib
import os

from tpu_bfs.obs.recorder import Recorder

__all__ = [
    "ACTIVE",
    "ENV_VAR",
    "Recorder",
    "arm",
    "arm_for_run",
    "arm_from_env",
    "arm_from_spec",
    "arm_from_spec_or_env",
    "disarm",
    "maybe_span",
]

# THE guard production sites check: None (the default) keeps every
# instrumentation site a single attribute test with no further work.
ACTIVE: Recorder | None = None

ENV_VAR = "TPU_BFS_OBS"

_TRUTHY = ("1", "true", "on", "yes")
_FALSY = ("0", "false", "off", "no")


def _parse_spec(spec: str) -> dict:
    spec = spec.strip()
    kw: dict = {}
    if not spec or spec.lower() in _TRUTHY:
        return kw
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        k, eq, v = item.partition("=")
        k = k.strip()
        try:
            if k == "capacity":
                kw["capacity"] = int(v)
            elif k == "window":
                kw["window_s"] = float(v)
            elif k == "dump_dir":
                kw["dump_dir"] = v.strip()
            elif k == "max_dumps":
                kw["max_dumps"] = int(v)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad obs spec item {item!r} (capacity=INT, window=FLOAT, "
                f"dump_dir=PATH, max_dumps=INT)"
            ) from None
        if not eq:
            raise ValueError(f"obs spec item {item!r} must be key=value")
    return kw


def arm(recorder: Recorder | None = None, **kw) -> Recorder:
    """Install ``recorder`` (or a fresh one built from ``kw``) as the
    process-wide ACTIVE recorder. Idempotent-friendly: re-arming replaces
    the previous recorder (its events are dropped with it)."""
    global ACTIVE
    ACTIVE = recorder if recorder is not None else Recorder(**kw)
    return ACTIVE


def arm_from_spec(spec: str) -> Recorder | None:
    """Arm from one spec string; an explicitly-falsy spec (``0``,
    ``false``, ``off``, ``no``) returns None WITHOUT arming — and, via
    arm_from_spec_or_env, without falling through to the env var (an
    explicit ``--obs 0`` overrides a fleet-set TPU_BFS_OBS)."""
    if spec.strip().lower() in _FALSY:
        return None
    return arm(**_parse_spec(spec))


def arm_from_env(env: str = ENV_VAR) -> Recorder | None:
    spec = os.environ.get(env, "").strip()
    return arm_from_spec(spec) if spec else None


def arm_from_spec_or_env(spec: str | None, env: str = ENV_VAR) -> Recorder | None:
    """The entry points' shared precedence (same contract as
    faults.arm_from_spec_or_env): an explicit ``--obs`` spec wins over the
    environment variable; neither set = stay disarmed."""
    return arm_from_spec(spec) if spec is not None else arm_from_env(env)


def arm_for_run(spec: str | None, trace_out: str | None = None,
                env: str = ENV_VAR) -> Recorder | None:
    """The shared entry-point arming (cli.py and serve): an explicit
    ``--obs`` spec wins, else the env var; ``--trace-out`` needs a
    recorder, so it arms one with defaults when nothing else did."""
    rec = arm_from_spec_or_env(spec, env)
    if rec is None and trace_out:
        rec = arm()
    return rec


def maybe_span(name: str, span_id: str, *, cat: str = "span", **args):
    """``ACTIVE.span(...)`` when armed, a no-op context otherwise — for
    COLD paths (graph load, engine build/warm) where the armed/disarmed
    fork would otherwise be written out twice. Hot loops keep the
    explicit ``if obs.ACTIVE is not None`` guard: one attribute read,
    no context-manager allocation (tests/test_obs.py pins that)."""
    rec = ACTIVE
    if rec is None:
        return contextlib.nullcontext()
    return rec.span(name, span_id, cat=cat, **args)


def disarm() -> None:
    global ACTIVE
    ACTIVE = None
