"""Per-level engine traces: ``last_run_trace`` assembly and summaries.

Two sources, matching what each engine family can observe without
adding device work to its level loop:

- the DISTRIBUTED SINGLE-SOURCE loops (1D ``DistBfsEngine``, 2D
  ``Dist2DBfsEngine``) already compute a per-level new-frontier popcount
  (their termination psum) and, on the 1D sparse path, the per-level
  cap-ladder branch; both now land in small fixed-size carry arrays
  (:data:`TRACE_LEVELS` slots) that :func:`assemble_dist_trace` prices
  with ``wire_bytes_per_level()`` — so every per-level row carries
  frontier count, direction, exchange choice, and modeled wire bytes;

- the PACKED MS engines record per-level gate skips
  (``last_gate_level_counts``) and exact per-branch exchange level
  counts (``last_exchange_level_counts``); :func:`assemble_packed_trace`
  folds those into per-level rows. Their loops compute no per-level
  frontier popcount (only an ``any``), so packed rows carry
  ``frontier=None`` and, when a sparse run mixed branches, the exchange
  choice ``"mixed"`` with the exact per-branch counts in the trace
  summary — observability must not add reductions to the hot loop the
  serve bench times.

Every row is one plain dict::

    {"level": int,            # the level being EXPANDED
     "frontier": int|None,    # vertices claimed by this expansion
     "direction": str,        # "push" | "pull" | "pull-gated" | ...
     "gated_tiles": int|None, # blocks the pull gate skipped
     "exchange": str|None,    # "sparse[cap]" | "dense" | "mixed" | None
     "wire_bytes": float|None}# modeled off-chip bytes, this level

``engine.last_run_trace`` holds the rows of the engine's most recent
core invocation (a checkpoint-resumed chunk covers that chunk's levels).
"""

from __future__ import annotations

import numpy as np

# Per-level recording depth of the distributed single-source loop
# carries. Deeper traversals clamp into the last slot: its frontier is
# the exact SUM over the clamped levels (the loops accumulate with
# .add, so frontier_total never undercounts), its branch/wire columns
# are the LAST clamped level's, and the assembled row marks itself
# truncated. 64 levels covers every power-law serving graph by a wide
# margin.
TRACE_LEVELS = 64


def branch_label(branch: int, caps) -> str:
    """Human form of a cap-ladder branch index (ascending caps, then the
    dense fallback — the collectives.cap_ladder_select convention)."""
    caps = sorted(caps or ())
    if 0 <= branch < len(caps):
        return f"sparse[{caps[branch]}]"
    return "dense"


def assemble_dist_trace(
    engine, levels: int, front_seq, branch_seq, *, direction: str,
    level0: int = 0,
) -> list[dict]:
    """Per-level rows for the distributed single-source engines from
    their loop-carry recordings. ``front_seq``/``branch_seq`` are the
    [TRACE_LEVELS] arrays (branch -1 = slot never written); pricing
    comes from ``engine.wire_bytes_per_level()`` so the trace can never
    disagree with the exchange accounting. ``levels`` counts the levels
    THIS invocation ran; ``level0`` re-offsets a checkpoint-resumed
    chunk's rows to absolute traversal levels. Past ``TRACE_LEVELS`` the
    last row aggregates: exact frontier sum of the clamped tail,
    last-written branch/wire, and a ``truncated_levels`` marker."""
    front = np.asarray(front_seq)
    branch = np.asarray(branch_seq)
    per_level = [float(x) for x in engine.wire_bytes_per_level()]
    # Engines with a richer branch space (the ISSUE 7 planner: delta
    # rungs, sieved variants, predicted-dense) publish their own
    # index-aligned label list via exchange_branch_labels(); without the
    # hook, the cap-ladder labels apply to the sparse exchange and ring/
    # allreduce runs have one branch, labeled by the impl itself (the
    # engines keep sparse_caps populated either way, so the caps alone
    # cannot distinguish the modes).
    hook = getattr(engine, "exchange_branch_labels", None)
    labels = hook() if callable(hook) else None
    mode = getattr(engine, "_exchange", None)
    caps = tuple(getattr(engine, "sparse_caps", ()) or ())
    if mode != "sparse":
        caps = ()
    n = min(int(levels), TRACE_LEVELS)
    rows = []
    for lvl in range(n):
        b = int(branch[lvl])
        known = 0 <= b < len(per_level)
        if labels is not None:
            label = labels[b] if known and b < len(labels) else None
        else:
            label = branch_label(b, caps) if known else None
        if label == "dense" and mode not in (None, "sparse"):
            label = mode
        rows.append({
            "level": int(level0) + lvl,
            "frontier": int(front[lvl]),
            "direction": direction,
            "gated_tiles": None,
            "exchange": label,
            "wire_bytes": per_level[b] if known else None,
        })
    if int(levels) > TRACE_LEVELS:
        rows[-1]["truncated_levels"] = int(levels) - TRACE_LEVELS + 1
    return rows


def assemble_packed_trace(engine, levels: int) -> list[dict]:
    """Per-level rows for a packed MS engine's last run, from its
    host-visible artifacts (gate counters, per-branch exchange counts).
    Exchange choice is exact when the whole run used one branch (always
    true for dense exchanges); a mixed sparse run labels rows "mixed"
    and the exact split lives in :func:`trace_summary`."""
    n = int(levels)
    gc = getattr(engine, "last_gate_level_counts", None)
    if gc is not None:
        gc = np.asarray(gc)
    direction = "pull-gated" if getattr(engine, "pull_gate", False) else "pull"
    if getattr(engine, "_adaptive_push", None) or getattr(
        engine, "adaptive_push", None
    ):
        direction = "pull+adaptive-push"
    counts = getattr(engine, "last_exchange_level_counts", None)
    caps = tuple(getattr(engine, "sparse_caps", ()) or ())
    hook = getattr(engine, "exchange_branch_labels", None)
    labels = hook() if callable(hook) else None
    exchange = None
    wire_each = None
    if counts is not None:
        counts = np.asarray(counts)
        wb = getattr(engine, "wire_bytes_per_level", None)
        per_level = [float(x) for x in wb()] if wb is not None else None
        used = np.flatnonzero(counts)
        if len(used) == 1:
            b = int(used[0])
            if labels is not None and b < len(labels):
                exchange = labels[b]
            else:
                exchange = branch_label(b, caps) if len(counts) > 1 else "dense"
            if per_level is not None:
                wire_each = per_level[b]
        elif len(used) > 1:
            exchange = "mixed"
    rows = []
    for lvl in range(n):
        rows.append({
            "level": lvl,
            "frontier": None,
            "direction": direction,
            "gated_tiles": int(gc[lvl]) if gc is not None and lvl < len(gc)
            else None,
            "exchange": exchange,
            "wire_bytes": wire_each,
        })
    return rows


def trace_summary(trace, engine=None) -> dict:
    """Compact verdict/statsz form of one ``last_run_trace``: the keys
    bench.py folds into its JSON line (BENCHMARKS.md "Trace summary")."""
    trace = trace or []
    out: dict = {"levels": len(trace)}
    fronts = [r["frontier"] for r in trace if r.get("frontier") is not None]
    if fronts:
        out["frontier_total"] = int(sum(fronts))
        out["frontier_peak"] = int(max(fronts))
    directions = sorted({r["direction"] for r in trace if r.get("direction")})
    if directions:
        out["directions"] = directions
    gates = [r["gated_tiles"] for r in trace if r.get("gated_tiles") is not None]
    if gates:
        out["gated_tiles_total"] = int(sum(gates))
    exchanges: dict = {}
    for r in trace:
        ex = r.get("exchange")
        if ex is not None:
            exchanges[ex] = exchanges.get(ex, 0) + 1
    if exchanges:
        out["exchange_levels"] = exchanges
    wires = [r["wire_bytes"] for r in trace if r.get("wire_bytes") is not None]
    if wires:
        out["wire_bytes_total"] = float(sum(wires))
    if engine is not None:
        counts = getattr(engine, "last_exchange_level_counts", None)
        if counts is not None:
            out["exchange_branch_counts"] = [int(x) for x in np.asarray(counts)]
        wbytes = getattr(engine, "last_exchange_bytes", None)
        if wbytes is not None:
            # The accounting's figure wins (covers levels past the trace
            # clamp and mixed-branch packed runs).
            out["wire_bytes_total"] = float(wbytes)
    return out


def record_packed_run(engine, levels: int, *, recorder=None,
                      label: str | None = None) -> list[dict]:
    """Assemble and store ``engine.last_run_trace`` for a finished packed
    batch, emitting one per-level obs event per row when a recorder is
    given. Called only under the obs ACTIVE guard (the assembly reads
    ``last_gate_level_counts``, a device array — transferring it per
    batch must not tax the un-instrumented serve hot path)."""
    trace = assemble_packed_trace(engine, levels)
    engine.last_run_trace = trace
    if recorder is not None:
        name = label or type(engine).__name__
        recorder.event(
            "engine.run_trace", cat="engine", engine=name,
            summary=trace_summary(trace, engine),
        )
    return trace
