"""Telemetry exporters: Chrome/Perfetto trace JSON, Prometheus text, JSONL.

All three consume the plain record dicts :class:`tpu_bfs.obs.recorder.
Recorder` emits — no recorder import needed, so these also format
records replayed from a flight-recorder dump. Stdlib-only.
"""

from __future__ import annotations

import json

# ---------------------------------------------------------------------------
# Chrome trace-event JSON (Perfetto / chrome://tracing loadable).
#
# Instant records map to ph="i" (thread-scoped), span begin/end to the
# ASYNC event pair ph="b"/"e" (matched on cat+id+name, which is exactly
# the recorder's span contract — async events are the right encoding
# because one logical span crosses threads: a query is admitted on a
# client thread and resolved on the extraction worker). Timestamps are
# microseconds relative to the recorder epoch.


def trace_events(events, *, t0: float = 0.0, pid: int = 0) -> list[dict]:
    """Recorder records -> Chrome trace-event dicts."""
    out = []
    tids: dict = {}
    for ev in events:
        tname = ev.get("tid", "main")
        tid = tids.get(tname)
        if tid is None:
            tid = tids[tname] = len(tids) + 1
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        ts = max(ev["t"] - t0, 0.0) * 1e6
        rec = {
            "name": ev["name"],
            "cat": ev.get("cat", "event"),
            "ph": ev["ph"],
            "ts": round(ts, 3),
            "pid": pid,
            "tid": tid,
            "args": dict(ev.get("args") or {}),
        }
        if ev["ph"] in ("b", "e"):
            rec["id"] = str(ev.get("id"))
        elif ev["ph"] == "i":
            rec["s"] = "t"  # thread-scoped instant
        out.append(rec)
    return out


def level_trace_events(trace, *, t0_us: float = 0.0, label: str = "engine",
                       pid: int = 0, tid: int = 0) -> list[dict]:
    """Per-level engine-trace rows (``engine.last_run_trace``) as one
    synthetic Perfetto track: one instant event per BFS level carrying
    frontier count, direction, gated tiles, exchange choice, and modeled
    wire bytes in ``args``. Levels have no host timestamps (the level
    loop is one device dispatch), so rows are spaced 1 us apart from
    ``t0_us`` — a logical axis, documented in README "Observability"."""
    out = [{
        "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
        "args": {"name": f"{label} levels"},
    }]
    for row in trace or ():
        out.append({
            "name": f"level {row.get('level')}",
            "cat": "engine.level",
            "ph": "i",
            "ts": round(t0_us + float(row.get("level", 0)), 3),
            "pid": pid,
            "tid": tid,
            "s": "t",
            "args": dict(row),
        })
    return out


def write_perfetto(events, path: str, *, t0: float = 0.0,
                   level_traces=(), meta: dict | None = None) -> str:
    """Write one Perfetto-loadable JSON file: the recorder's events plus
    any number of ``(label, last_run_trace)`` pairs as extra level
    tracks. Returns ``path``."""
    evs = trace_events(events, t0=t0)
    tid = 1000  # level tracks sit far from real thread ids
    t_end = max((e["ts"] for e in evs if "ts" in e), default=0.0)
    for label, trace in level_traces:
        evs.extend(level_trace_events(
            trace, t0_us=t_end, label=label, tid=tid,
        ))
        tid += 1
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if meta:
        doc["metadata"] = meta
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def write_jsonl(events, path: str) -> str:
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path


# ---------------------------------------------------------------------------
# Prometheus-style text exposition.


def _metric_name(prefix: str, key: str) -> str:
    return f"{prefix}_{key}".replace(".", "_").replace("-", "_")


# Snapshot keys that are monotonic counters (TYPE counter); everything
# else numeric exports as a gauge. Keys whose value is None are skipped
# (e.g. p50_ms before the first completion).
_COUNTER_KEYS = frozenset((
    "completed", "batches", "rejected", "expired", "errors", "shutdown",
    "retries", "oom_degrades", "requeued", "watchdog_trips",
    "requeue_shed", "padded_lanes_total", "breaker_opens",
    "lanes_used", "lanes_offered",
    "mesh_faults", "mesh_degrades", "query_resumes", "resume_snapshots",
    "audits_run", "audit_failures", "audit_errors", "audit_dropped",
    "quarantines",
    # Answer cache + landmark tier (ISSUE 18). cache_bytes is the
    # resident-payload gauge and deliberately absent here.
    "cache_hits", "cache_misses", "cache_evictions", "cache_quarantines",
    "single_flight_collapses", "landmark_exact", "landmark_bounded",
    "landmark_fallback",
))


def prometheus_text(snapshot: dict, *, histograms: dict | None = None,
                    prefix: str = "tpu_bfs_serve") -> str:
    """Render one ServeMetrics snapshot (plus optional
    ``{name: Log2Histogram}``) as Prometheus exposition text — the
    /metricz payload, replacing ad-hoc statsz string munging as the
    machine-readable form (the stderr statsz line renders the same
    snapshot, so the two always agree).

    Dict-valued snapshot keys become labeled series (e.g. the routing
    histogram -> ``..._routing_batches{width="128"}``); list-valued keys
    export their length; None values are skipped."""
    lines: list[str] = []

    def emit(key: str, value, mtype: str) -> None:
        name = _metric_name(prefix, key)
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {value:g}" if isinstance(value, float)
                     else f"{name} {value}")

    for key in sorted(snapshot):
        value = snapshot[key]
        if value is None:
            continue
        if isinstance(value, bool):
            emit(key, int(value), "gauge")
        elif isinstance(value, (int, float)):
            emit(key, value, "counter" if key in _COUNTER_KEYS else "gauge")
        elif isinstance(value, dict):
            name = _metric_name(prefix, key)
            num = {k: v for k, v in value.items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)}
            if not num:
                continue
            label = "width" if key == "routing" else "key"
            lines.append(f"# TYPE {name} gauge")
            for k in sorted(num):
                lines.append(f'{name}{{{label}="{k}"}} {num[k]}')
        elif isinstance(value, (list, tuple)):
            emit(f"{key}_count", len(value), "gauge")
    for hname in sorted(histograms or {}):
        hist = histograms[hname]
        name = _metric_name(prefix, hname)
        lines.append(f"# TYPE {name} histogram")
        for le, cum in hist.cumulative_buckets():
            bound = "+Inf" if le is None else f"{le:g}"
            lines.append(f'{name}_bucket{{le="{bound}"}} {cum}')
        lines.append(f"{name}_sum {hist.total:g}")
        lines.append(f"{name}_count {hist.count}")
    return "\n".join(lines) + "\n"


def write_metricz(text: str, path: str) -> None:
    """Atomic-replace write of the periodic /metricz text file, so a
    scraper mid-read never sees a torn exposition."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
