"""The span/event ring buffer and the flight recorder.

Stdlib-only at import time (the same constraint :mod:`tpu_bfs.faults`
keeps): arming telemetry must not drag jax/numpy into processes that
only wanted the guard. One lock serializes writers — scheduler thread,
extraction worker, client threads, engine dispatch — which is fine
because every record is one dict append at human-noise rates next to a
device dispatch.

Record shape (one dict per event, the JSONL/Perfetto exporters consume
it directly)::

    {"seq": int,            # process-wide monotonic ordinal
     "t": float,            # time.monotonic() at record time
     "ph": "i"|"b"|"e",     # instant | span begin | span end
     "name": str,           # e.g. "query", "dispatch", "fault_injected"
     "cat": str,            # e.g. "serve.query", "serve.batch", "engine"
     "id": str|None,        # span correlation id ("q7", "b3", ...)
     "tid": str,            # recording thread's name
     "args": dict}          # site context (query/batch/width/attempt/...)

Span ids are caller-chosen strings so one logical span can cross
threads (a query is admitted on a client thread and resolved on the
extraction worker); ``begin``/``end`` pairs match on (cat, id, name).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 65536
DEFAULT_WINDOW_S = 30.0
DEFAULT_MAX_DUMPS = 16


class Recorder:
    """Thread-safe bounded event recorder with flight-dump support.

    ``capacity`` bounds the ring (oldest events drop first);
    ``window_s`` is how far back a flight dump reaches; ``dump_dir`` is
    where dumps land (created on first dump); ``max_dumps`` bounds how
    many dump files one process may write (a chaos soak tripping the
    watchdog per batch must not fill the disk); ``now`` is injectable
    for tests."""

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 window_s: float = DEFAULT_WINDOW_S,
                 dump_dir: str = ".", max_dumps: int = DEFAULT_MAX_DUMPS,
                 now=time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._now = now
        self.t0 = now()
        self.window_s = float(window_s)
        self.dump_dir = dump_dir
        self.max_dumps = int(max_dumps)
        self.capacity = int(capacity)  # immutable; lock-free reads OK
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)  # guarded-by: _lock
        self._seq = itertools.count(1)  # itertools.count is GIL-atomic
        self.dropped = 0  # guarded-by: _lock — events pushed out of the ring
        self.dumps: list[str] = []  # guarded-by: _lock — dump paths written
        self._dumps_started = 0  # guarded-by: _lock — reserved at trigger

    # --- recording --------------------------------------------------------

    def _push(self, ph: str, name: str, cat: str, span_id, args: dict) -> dict:
        ev = {
            "seq": next(self._seq),
            "t": self._now(),
            "ph": ph,
            "name": name,
            "cat": cat,
            "id": span_id,
            "tid": threading.current_thread().name,
            "args": args,
        }
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
        return ev

    def event(self, name: str, *, cat: str = "event", id=None, **args):
        """One instant event."""
        return self._push("i", name, cat, id, args)

    def begin(self, name: str, span_id: str, *, cat: str = "span", **args):
        """Open one span; close it with :meth:`end` (any thread)."""
        return self._push("b", name, cat, span_id, args)

    def end(self, name: str, span_id: str, *, cat: str = "span", **args):
        return self._push("e", name, cat, span_id, args)

    @contextlib.contextmanager
    def span(self, name: str, span_id: str, *, cat: str = "span", **args):
        self.begin(name, span_id, cat=cat, **args)
        try:
            yield
        finally:
            self.end(name, span_id, cat=cat)

    # --- reading ----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """A consistent copy of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    def events_since(self, t: float) -> list[dict]:
        with self._lock:
            return [ev for ev in self._events if ev["t"] >= t]

    def query_chain(self, qid) -> list[dict]:
        """Every event belonging to one query id's span chain: events on
        span ``q<qid>`` plus events whose args name the query (the batch
        events a query rode). Test/debug helper — exporters do their own
        filtering."""
        sid = f"q{qid}"
        out = []
        with self._lock:
            for ev in self._events:
                if ev["id"] == sid or ev["args"].get("query") == qid:
                    out.append(ev)
                elif qid in (ev["args"].get("queries") or ()):
                    out.append(ev)
        return out

    def counts_by_name(self) -> dict:
        with self._lock:
            out: dict = {}
            for ev in self._events:
                out[ev["name"]] = out.get(ev["name"], 0) + 1
            return out

    # --- flight recorder --------------------------------------------------

    def flight_dump(self, reason: str, *, path: str | None = None) -> str | None:
        """Write the last ``window_s`` seconds of events to a timestamped
        JSONL file and record the trigger as an event itself (so later
        dumps see earlier trips). Returns the path, or None when the
        per-process ``max_dumps`` budget is spent (the budget exists so a
        wedged device tripping the watchdog per batch cannot fill the
        disk). Best-effort: an unwritable dump dir is reported as an
        event, never raised into the serving path that tripped it."""
        with self._lock:
            if self._dumps_started >= self.max_dumps:
                return None
            self._dumps_started += 1
            n = self._dumps_started
            # Captured under the lock: the header below is built outside
            # it (the lock lint in tpu_bfs/analysis pins the discipline).
            dropped = self.dropped
        self.event("flight_dump", cat="obs", reason=reason, n=n)
        now = self._now()
        events = self.events_since(now - self.window_s)
        if path is None:
            stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
            safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
            path = os.path.join(
                self.dump_dir,
                f"flightrec-{stamp}-{safe}-p{os.getpid()}-{n}.jsonl",
            )
        header = {
            "flight_recorder": reason,
            "t": now,
            "t0": self.t0,
            "window_s": self.window_s,
            "wall_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "events": len(events),
            "dropped": dropped,
        }
        try:
            os.makedirs(self.dump_dir or ".", exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps(header) + "\n")
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
        except OSError as exc:
            self.event("flight_dump_failed", cat="obs", reason=reason,
                       error=repr(exc))
            return None
        with self._lock:
            self.dumps.append(path)
        return path
