"""Pallas TPU kernel: fused gated bucketed-ELL expansion (the pull tier).

Every packed engine's hot loop is the bucketed-ELL pull expansion
(_packed_common.make_fori_expand): per bucket, a fori loop of chained
row gathers OR-accumulated (or min-plus for SSSP) into an [n, w] table.
XLA materializes that accumulator in HBM on every fori step — k HBM
round-trips of the full bucket output per level. This kernel is the
ROADMAP item 3 answer (BLEST's recast-the-inner-loop argument, arXiv
2512.21967): one grid step per 128-row output tile that

- applies the PR 1 settled-mask gate INSIDE the kernel: a prefetched
  per-tile need word skips the whole tile's index-slab DMA and row
  gathers, writing the combine identity instead (bit-identical — a
  settled row's claim is empty on every active lane);
- double-buffers the per-slot row-gather DMAs (slab kk+1's HBM reads
  start before slab kk's combine), so gather latency hides behind the
  VPU combine;
- keeps the accumulator resident in VMEM across all k bucket slots and
  writes each row tile's words to HBM exactly once per level instead of
  once per fori step.

The index tables are the gate tier's sentinel-padded whole-block tables
(graph/ell.pad_gate_blocks, [k, nb*128]): the sentinel gathers the
engine's identity row (all-zero for BFS, all-INF for SSSP), so padding
is absorbed by the combine exactly as in the XLA path.

Combine ops (the make_fori_expand combine/identity contract, symbolic
because a kernel cannot close over a jnp callable):

- ``or``       bitwise OR over uint32, identity 0 (BFS frontiers)
- ``min``      minimum over uint32, identity 0xFFFFFFFF (parent keys)
- ``minplus``  min(acc, dist + weight) over int32, identity INF_W
               (SSSP; takes a weight table slot-for-slot with the
               indices, pad slots weight 0 — the sentinel row is INF)

Works under ``interpret=True`` on CPU (the tier-1 and fuzz proof path);
on a real TPU the frontier width must be a multiple of 128 words
(Mosaic's DMA minor-dim tiling — same constraint as ops/tile_spmm.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 128  # output rows per grid step == the pull gate's GATE_TILE

#: SSSP "unreached" identity (workloads/sssp.INF_W asserts equality):
#: sums the kernel forms stay < 2**30, far from int32 overflow.
MINPLUS_IDENT = 1 << 29

#: op name -> (identity, table dtype)
KERNEL_OPS = {
    "or": (0, jnp.uint32),
    "min": (0xFFFFFFFF, jnp.uint32),
    "minplus": (MINPLUS_IDENT, jnp.int32),
}


class KernelWidthError(ValueError):
    """A Pallas kernel was asked for a frontier width its DMA tiling
    cannot express on real hardware (legal widths named in the message)."""


def validate_kernel_width(w: int, interpret: bool, *, kernel: str) -> None:
    """Call-boundary width check shared by the Pallas kernels: any
    ``w >= 1`` under ``interpret=True`` (the CPU test path); on a real
    TPU, Mosaic requires every DMA'd frontier slab's minor dimension to
    be 128-aligned, so legal widths are exactly the multiples of 128
    words (4096-lane steps). Fails here with the legal widths named
    instead of deep inside Mosaic lowering."""
    if not isinstance(w, (int, np.integer)) or w < 1:
        raise KernelWidthError(
            f"{kernel}: width must be a positive word count, got {w!r}"
        )
    if not interpret and w % TILE:
        raise KernelWidthError(
            f"{kernel}: w={w} words is not DMA-tileable on TPU — legal "
            f"widths are multiples of {TILE} words ({TILE * 32}-lane "
            f"steps); any width works under interpret=True"
        )


def _ell_expand_kernel(*refs, k: int, w: int, op: str, has_wt: bool):
    """One grid step = one 128-row output tile of one bucket.

    Refs (has_wt inserts wt_ref/wt_buf): need_ref [nb] i32 scalar
    prefetch; gt_ref [k, nb*TILE] i32 and fw_ref [rows, w] stay in HBM;
    out_ref is the [TILE, w] VMEM block; scratch = idx_buf SMEM [k,
    TILE] (slab of row ids — DMA start offsets must be scalar reads),
    (wt_buf VMEM [k, TILE],) row_buf VMEM [2, TILE, w] (double-buffered
    gather landing zone), sems DMA[4] (0 idx slab, 1 wt slab, 2/3 the
    two row slots — each row slot streams TILE same-size copies through
    one semaphore and waits them in issue order)."""
    if has_wt:
        (need_ref, gt_ref, wt_ref, fw_ref, out_ref,
         idx_buf, wt_buf, row_buf, sems) = refs
    else:
        (need_ref, gt_ref, fw_ref, out_ref, idx_buf, row_buf, sems) = refs
        wt_ref = wt_buf = None
    j = pl.program_id(0)
    ident_val, _ = KERNEL_OPS[op]
    dt = out_ref.dtype
    ident = jnp.full((TILE, w), ident_val, dt)

    # Gated-out tile: the identity write is the whole cost — no index
    # DMA, no gathers, no combine (the in-kernel form of the PR 1 skip).
    @pl.when(need_ref[j] == 0)
    def _():
        out_ref[:] = ident

    @pl.when(need_ref[j] != 0)
    def _():
        idx_cp = pltpu.make_async_copy(
            gt_ref.at[:, pl.ds(j * TILE, TILE)], idx_buf, sems.at[0]
        )
        idx_cp.start()
        if has_wt:
            wt_cp = pltpu.make_async_copy(
                wt_ref.at[:, pl.ds(j * TILE, TILE)], wt_buf, sems.at[1]
            )
            wt_cp.start()
            wt_cp.wait()
        idx_cp.wait()

        def row_cp(kk, r, slot):
            # One gathered frontier row: fw[gt[kk, j*TILE + r]] -> the
            # landing slot. Same descriptor rebuilt for start and wait.
            return pltpu.make_async_copy(
                fw_ref.at[pl.ds(idx_buf[kk, r], 1), :],
                row_buf.at[slot, pl.ds(r, 1), :],
                sems.at[2 + slot],
            )

        def start_slab(kk):
            slot = kk % 2

            def sbody(r, carry):
                row_cp(kk, r, slot).start()
                return carry

            jax.lax.fori_loop(0, TILE, sbody, 0)

        def wait_slab(kk):
            slot = kk % 2

            def wbody(r, carry):
                row_cp(kk, r, slot).wait()
                return carry

            jax.lax.fori_loop(0, TILE, wbody, 0)

        out_ref[:] = ident
        start_slab(0)
        # k is static (the bucket's ELL width): unrolling keeps every
        # slot id and weight-column slice static for Mosaic.
        for kk in range(k):
            if kk + 1 < k:
                start_slab(kk + 1)  # hide slab kk+1's gathers behind kk
            wait_slab(kk)
            rows = row_buf[kk % 2]
            if op == "or":
                out_ref[:] = out_ref[:] | rows
            elif op == "min":
                out_ref[:] = jnp.minimum(out_ref[:], rows)
            else:  # minplus: per-output-row weight add, then min
                wcol = wt_buf[kk, :].reshape(TILE, 1)
                out_ref[:] = jnp.minimum(out_ref[:], rows + wcol)


@functools.partial(jax.jit, static_argnames=("w", "op", "interpret"))
def ell_expand(need_blk, gt, fw, wt=None, *, w: int, op: str = "or",
               interpret: bool = False):
    """Gated gather-combine over one bucket's padded ELL table.

    ``gt`` [k, nb*TILE] int32 (pad_gate_blocks layout, sentinel pads),
    ``fw`` [rows, w] (uint32 for or/min, int32 for minplus), ``need_blk``
    [nb] int32 per-output-tile gate (nonzero = compute; pass all-ones
    for an ungated pass), ``wt`` [k, nb*TILE] int32 per-slot weights
    (minplus only). Returns [nb*TILE, w]: row r is
    ``combine_kk fw[gt[kk, r]]`` (+ wt for minplus) where need_blk
    allows, else the op identity."""
    if op not in KERNEL_OPS:
        raise ValueError(f"op must be one of {sorted(KERNEL_OPS)}, got {op!r}")
    validate_kernel_width(w, interpret, kernel="ell_expand")
    k, ncols = gt.shape
    if ncols % TILE:
        raise ValueError(
            f"gt minor dim {ncols} is not a multiple of {TILE} "
            "(use graph/ell.pad_gate_blocks)"
        )
    nb = ncols // TILE
    has_wt = wt is not None
    if (op == "minplus") != has_wt:
        raise ValueError("minplus requires wt; or/min take none")
    _, dt = KERNEL_OPS[op]
    if fw.shape[1] != w or fw.dtype != dt:
        raise ValueError(
            f"fw must be [rows, {w}] {np.dtype(dt).name}, got "
            f"{fw.shape} {fw.dtype}"
        )
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * (2 + has_wt)
    scratch = [pltpu.SMEM((k, TILE), jnp.int32)]
    if has_wt:
        scratch.append(pltpu.VMEM((k, TILE), jnp.int32))
    scratch += [
        pltpu.VMEM((2, TILE, w), dt),
        pltpu.SemaphoreType.DMA((4,)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (TILE, w), lambda j, *_: (j, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=scratch,
    )
    args = (need_blk, gt, wt, fw) if has_wt else (need_blk, gt, fw)
    return pl.pallas_call(
        functools.partial(
            _ell_expand_kernel, k=k, w=w, op=op, has_wt=has_wt
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nb * TILE, w), dt),
        interpret=interpret,
    )(*args)


def ell_expand_reference(need_blk, gt, fw, wt=None, *, w: int,
                         op: str = "or") -> np.ndarray:
    """NumPy oracle for :func:`ell_expand` (tests pin the kernel to it)."""
    need_blk = np.asarray(need_blk)
    gt = np.asarray(gt)
    fw = np.asarray(fw)
    ident_val, dt = KERNEL_OPS[op]
    dt = np.dtype(np.uint32 if dt == jnp.uint32 else np.int32)
    k, ncols = gt.shape
    nb = ncols // TILE
    out = np.full((nb * TILE, w), ident_val, dt)
    for j in range(nb):
        if not need_blk[j]:
            continue
        sl = slice(j * TILE, (j + 1) * TILE)
        acc = np.full((TILE, w), ident_val, dt)
        for kk in range(k):
            rows = fw[gt[kk, sl]]
            if op == "or":
                acc |= rows
            elif op == "min":
                acc = np.minimum(acc, rows)
            else:
                acc = np.minimum(
                    acc, rows + np.asarray(wt)[kk, sl][:, None]
                )
        out[sl] = acc
    return out


def ell_expand_hbm_bytes(k: int, n: int, w: int, *,
                         active_tiles: int | None = None,
                         weighted: bool = False) -> int:
    """Analytic HBM bytes one bucket's kernel pass must move (the
    roofline's per-kernel attribution, utils/roofline.py): per computed
    tile, the index slab ([k, TILE] i32), k*TILE gathered rows of w
    words (+ the weight slab when minplus), and ONE [TILE, w] output
    write — the VMEM-resident bound the kernel is built to meet (the
    XLA fori form writes the accumulator back per slot, k times).
    Gated-out tiles pay only their identity output write."""
    nb = -(-n // TILE)
    at = nb if active_tiles is None else min(active_tiles, nb)
    per_tile = k * TILE * 4 + k * TILE * w * 4 + TILE * w * 4
    if weighted:
        per_tile += k * TILE * 4
    return at * per_tile + (nb - at) * TILE * w * 4
