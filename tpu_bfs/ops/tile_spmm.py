"""Pallas TPU kernel: dense-tile frontier expansion on the MXU.

On a degree-sorted power-law graph, a large fraction of edges concentrates in
a small set of dense 128x128 tiles of the adjacency matrix (measured on RMAT
scale-21: tiles holding >= 64 edges cover 57% of all edges in ~2% of the
tile area). For those tiles, boolean frontier expansion

    hit[r, l] = OR_c  A[r, c] & frontier[c, l]

is an int8 matrix product ``acc = A @ F; hit = acc > 0`` — MXU work at
~0.7 us per tile instead of 128 x 13 ns of random-gather tax per tile on the
VPU path. This kernel fuses, per 128-row output tile:

    HBM DMA (A tile int8, frontier slab u32) -> in-VMEM bit-unpack ->
    MXU matmul-accumulate over the row-tile's dense blocks -> threshold ->
    in-VMEM bit-pack -> one output write

so no unpacked [*, lanes] intermediate ever touches HBM (the pure-XLA
formulation of the same computation materializes them and is ~30x slower).

Internal lane layout: the kernel unpacks a [128, W] slab to int8 [128, 32*W]
with internal column ``bit * W + word`` — 32 contiguous (frontier >> bit) & 1
slices — and packs the mirror image, so no strided or sub-128-lane ops occur
anywhere. Because pack inverts unpack exactly, every (word, bit) position of
the input table maps to the same (word, bit) of the output: callers may
assign batch lanes to (word, bit) coordinates however they like.

This is the TPU answer to the reference's edge-walking CUDA kernels
(queueBfs, bfs.cu:134-165 / multiBfs, bfs.cu:101-130): where CUDA hides
irregularity behind per-thread divergence, the TPU reformulation turns the
dense part of the irregularity into systolic-array matmuls and leaves only
the sparse tail to gathers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_bfs.ops.ell_expand import validate_kernel_width

TILE = 128  # tile edge (rows and cols) == MXU systolic dimension
AW = TILE // 32  # u32 words per packed A-tile row


def _unpack_bits(slab_u32, w: int):
    """[128, w] u32 -> [128, 32*w] int8 of 0/1, bit-major lane order."""
    parts = [
        ((slab_u32 >> jnp.uint32(bit)) & jnp.uint32(1)).astype(jnp.int8)
        for bit in range(32)
    ]
    return jnp.concatenate(parts, axis=1)


def _pack_bits(acc_i32, w: int):
    """[128, 32*w] int32 counts -> [128, w] u32 of (count > 0) bits."""
    out = jnp.zeros((TILE, w), jnp.uint32)
    for bit in range(32):
        hit = (acc_i32[:, bit * w : (bit + 1) * w] > 0).astype(jnp.uint32)
        out = out | (hit << jnp.uint32(bit))
    return out


def _tile_spmm_kernel(
    # scalar prefetch
    row_start_ref,  # [NR+1] i32: tiles of row-tile j are [row_start[j], row_start[j+1])
    col_tile_ref,  # [NT] i32: column-tile index per dense tile
    # array inputs (stay in HBM; DMA'd manually)
    a_ref,  # [NT, AW, TILE] u32 — bit-packed: A[r, c] at [t, r % AW, c] bit r // AW
    fw_ref,  # [VT*TILE, w] u32
    # output
    out_ref,  # block [TILE, w] u32 for row-tile j
    # scratch
    a_buf,  # [2, AW, TILE] u32
    fw_buf,  # [2, TILE, w] u32
    acc_ref,  # [TILE, 32*w] i32
    sems,  # DMA sems [2, 2]
    *,
    w: int,
):
    j = pl.program_id(0)
    start = row_start_ref[j]
    nb = row_start_ref[j + 1] - start

    def a_dma(slot, b):
        return pltpu.make_async_copy(a_ref.at[b], a_buf.at[slot], sems.at[slot, 0])

    def fw_dma(slot, b):
        row0 = col_tile_ref[b] * TILE
        return pltpu.make_async_copy(
            fw_ref.at[pl.ds(row0, TILE), :], fw_buf.at[slot], sems.at[slot, 1]
        )

    # Empty row-tiles (the common case on a mostly-sparse grid) pay only a
    # zero-fill of their output block — no acc init, no pack.
    @pl.when(nb == 0)
    def _():
        out_ref[:] = jnp.zeros((TILE, w), jnp.uint32)

    @pl.when(nb > 0)
    def _():
        acc_ref[:] = jnp.zeros((TILE, 32 * w), jnp.int32)
        a_dma(0, start).start()
        fw_dma(0, start).start()

        def body(i, _):
            slot = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < nb)
            def _():
                a_dma(nxt, start + i + 1).start()
                fw_dma(nxt, start + i + 1).start()

            a_dma(slot, start + i).wait()
            fw_dma(slot, start + i).wait()
            f_i8 = _unpack_bits(fw_buf[slot], w)
            # A rows are bit-packed along the SUBLANE axis ([AW, TILE] with
            # A[r, c] at word r % AW, bit r // AW): unpacking along axis 0
            # rebuilds A in standard [row, col] orientation, so the matmul
            # contracts dim 1 — the MXU-native form (contracting dim 0 of a
            # transposed operand costs an internal relayout, measured ~2x
            # slower per tile).
            a_parts = [
                ((a_buf[slot] >> jnp.uint32(bit)) & jnp.uint32(1)).astype(jnp.int8)
                for bit in range(32)
            ]
            a_i8 = jnp.concatenate(a_parts, axis=0)  # [TILE(r), TILE(c)]
            acc_ref[:] += jax.lax.dot_general(
                a_i8,
                f_i8,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return 0

        jax.lax.fori_loop(0, nb, body, 0)
        out_ref[:] = _pack_bits(acc_ref[:], w)


@functools.partial(jax.jit, static_argnames=("num_row_tiles", "w", "interpret"))
def tile_spmm(
    row_start,  # [NR+1] i32 (host or device)
    col_tile,  # [NT] i32
    a_tiles,  # [NT, AW, TILE] u32 bit-packed (see pack_a_tiles)
    fw,  # [VT*TILE, w] u32 — bit-major packed frontier
    *,
    num_row_tiles: int,
    w: int = 128,
    interpret: bool = False,
):
    """hit contribution [NR*TILE, w] u32 of all dense tiles (bit-major lanes).

    Width contract at the call boundary (shared with ops/ell_expand):
    any ``w >= 1`` under ``interpret=True``; on a real TPU ``w`` must be
    a multiple of 128 (the Mosaic lane tiling the VMEM blocks and DMA
    slices are laid out in). A bad width raises ``KernelWidthError``
    naming the legal widths HERE instead of a Mosaic lowering error
    from inside the compiled program.
    """
    validate_kernel_width(w, interpret, kernel="tile_spmm")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_row_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (TILE, w), lambda j, *_: (j, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, AW, TILE), jnp.uint32),
            pltpu.VMEM((2, TILE, w), jnp.uint32),
            pltpu.VMEM((TILE, 32 * w), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_tile_spmm_kernel, w=w),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_row_tiles * TILE, w), jnp.uint32),
        interpret=interpret,
    )(row_start, col_tile, a_tiles, fw)


def tile_spmm_reference(row_start, col_tile, a_tiles, fw, *, num_row_tiles, w=128):
    """NumPy oracle for the kernel (bit-major lane convention)."""
    row_start = np.asarray(row_start)
    col_tile = np.asarray(col_tile)
    a_tiles = np.asarray(a_tiles)
    fw = np.asarray(fw)
    out = np.zeros((num_row_tiles * TILE, w), np.uint32)
    for j in range(num_row_tiles):
        acc = np.zeros((TILE, 32 * w), np.int64)
        for b in range(row_start[j], row_start[j + 1]):
            slab = fw[col_tile[b] * TILE : (col_tile[b] + 1) * TILE]  # [TILE, w]
            f = np.concatenate(
                [((slab >> np.uint32(bit)) & 1).astype(np.int64) for bit in range(32)],
                axis=1,
            )
            a = unpack_a_tile(a_tiles[b])
            acc += a.astype(np.int64) @ f
        words = np.zeros((TILE, w), np.uint32)
        for bit in range(32):
            words |= ((acc[:, bit * w : (bit + 1) * w] > 0).astype(np.uint32)) << np.uint32(bit)
        out[j * TILE : (j + 1) * TILE] = words
    return out


def pack_a_tiles(a_dense: np.ndarray) -> np.ndarray:
    """[NT, TILE, TILE] 0/1 -> bit-packed [NT, AW, TILE] u32, rows-in-bits.

    A[t, r, c] lives at ``out[t, r % AW, c]`` bit ``r // AW``: the minor
    dimension stays the 128 columns (Mosaic requires DMA slices aligned to
    the 128-lane tiling) and the kernel's axis-0 unpack rebuilds A in
    standard row/col orientation."""
    nt = a_dense.shape[0]
    out = np.zeros((nt, AW, TILE), np.uint32)
    for bit in range(32):
        # rows bit*AW .. bit*AW+AW-1 -> words 0..AW-1 at this bit
        rows = a_dense[:, bit * AW : (bit + 1) * AW, :].astype(np.uint32)
        out |= rows << np.uint32(bit)
    return out


def unpack_a_tile(a_bits: np.ndarray) -> np.ndarray:
    """[AW, TILE] u32 -> [TILE, TILE] 0/1 int8 (inverse of pack_a_tiles)."""
    parts = [
        ((a_bits >> np.uint32(bit)) & 1).astype(np.int8) for bit in range(32)
    ]
    return np.concatenate(parts, axis=0)
