"""Collective primitives for the frontier exchange.

The reference's exchange layer is `cudaMemcpyPeer` between per-destination
frontier buckets intra-node (bfs.cu:604-606) and CUDA-aware `MPI_Sendrecv` +
`MPI_Allreduce` inter-node (bfs_mpi.cu:607-621). On TPU both collapse into one
primitive: a reduce-scatter of each chip's full-size contribution buffer over
the mesh axis — XLA routes it over ICI within a slice and DCN across slices,
so one code path replaces the reference's two forked files.

Two implementations, selectable and cross-checked in tests:

- ``ring``: P-1 `lax.ppermute` hops, each combining one vloc-sized chunk —
  the classic bandwidth-optimal ring reduce-scatter, expressed manually
  because XLA's built-in reduce-scatter (psum_scatter) only sums, and the
  frontier combine is OR / parent combine is MIN.
- ``allreduce``: whole-buffer `lax.psum`/`pmin` + local slice. Simpler,
  ~2x the bytes on the wire.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _chunk(x_full, c, size):
    return lax.dynamic_slice_in_dim(x_full, c * size, size)


def ring_reduce_scatter(x_full, axis_name: str, num_devices: int, op):
    """Reduce-scatter ``x_full`` ([P*n] per chip) down to this chip's [n]
    chunk, combining with ``op`` around a ring of `ppermute`s.

    Invariant: after s combine steps, chip i holds the partial reduction of
    chunk (i - 1 - s) mod P over chips (i-s..i); after P-1 steps that is the
    full reduction of chunk i.
    """
    p = num_devices
    if p == 1:
        return x_full
    n = x_full.shape[0] // p
    i = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p) for j in range(p)]
    acc = _chunk(x_full, (i - 1) % p, n)

    def step(s, acc):
        acc = lax.ppermute(acc, axis_name, perm)
        return op(acc, _chunk(x_full, (i - 1 - s) % p, n))

    return lax.fori_loop(1, p, step, acc, unroll=True)


def reduce_scatter_or(x_full, axis_name: str, num_devices: int, *, impl: str = "ring"):
    """OR-reduce-scatter of a boolean contribution buffer (frontier exchange)."""
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.logical_or)
    n = x_full.shape[0] // num_devices
    summed = lax.psum(x_full.astype(jnp.int32), axis_name)
    return _chunk(summed, lax.axis_index(axis_name), n) > 0


def reduce_scatter_min(x_full, axis_name: str, num_devices: int, *, impl: str = "ring"):
    """MIN-reduce-scatter of an int32 contribution buffer (parent merge —
    the analog of the reference's elementwise min result merge, bfs.cu:426-438)."""
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.minimum)
    n = x_full.shape[0] // num_devices
    m = lax.pmin(x_full, axis_name)
    return _chunk(m, lax.axis_index(axis_name), n)
