"""Collective primitives for the frontier exchange.

The reference's exchange layer is `cudaMemcpyPeer` between per-destination
frontier buckets intra-node (bfs.cu:604-606) and CUDA-aware `MPI_Sendrecv` +
`MPI_Allreduce` inter-node (bfs_mpi.cu:607-621). On TPU both collapse into one
primitive: a reduce-scatter of each chip's full-size contribution buffer over
the mesh axis — XLA routes it over ICI within a slice and DCN across slices,
so one code path replaces the reference's two forked files.

Two implementations, selectable and cross-checked in tests:

- ``ring``: P-1 `lax.ppermute` hops, each combining one vloc-sized chunk —
  the classic bandwidth-optimal ring reduce-scatter, expressed manually
  because XLA's built-in reduce-scatter (psum_scatter) only sums, and the
  frontier combine is OR / parent combine is MIN.
- ``allreduce``: whole-buffer `lax.psum`/`pmin` + local slice. Simpler,
  ~2x the bytes on the wire.
- ``sparse`` (`sparse_exchange_or`): two-phase queue-style exchange — the
  TPU form of the reference's per-destination frontier buckets. Moves only
  actual frontier ids when every bucket fits a static cap; falls back to
  the dense ring bitmap level-by-level otherwise.

Wire format (ISSUE 5): every boolean exchange additionally has a
``wire_pack`` form that ships uint32 words, 32 vertices per word
(:func:`pack_bits` / :func:`unpack_bits`), instead of the unpacked
dtypes — pred chunks on the ring (ONE byte per vertex per hop) and s32
on the allreduce path (FOUR bytes per vertex). Packing is pure compute:
the packed programs emit the same collective instruction count as the
unpacked ones, moving 1/8 (ring) and 1/32 (allreduce operand) the bytes
— proven from the compiled HLO by utils/wirecheck.check_packed_exchange.
The sparse exchange's per-level sparse-ids/dense decision (the Buluç &
Madduri format flip, arXiv:1104.4518) is the shared
:func:`cap_ladder_select`; under ``wire_pack`` its dense fallback is the
packed ring and the cap ladder is recalibrated against the packed dense
cost (``default_sparse_caps``).

Sparse wire format (ISSUE 7, "Compression and Sieve", arXiv:1208.5542):
the id buffers themselves compress. The cumsum compaction already emits
ascending ids per destination chunk, so :func:`delta_encode_ids` ships
first-id + fixed-width bit-packed deltas (8/16-bit fields in uint32
words — XLA-friendly static shapes, not varints), the width picked by
the same mesh-uniform pmax discipline as the cap rungs (the max
consecutive-id gap rides the SAME scalar all-reduce as the max bucket
count, as an s32[2] pair). :func:`planned_sparse_exchange_or` composes
that with a backward visited sieve (each receiver's packed ``vis``
chunk all-gathered once — :func:`sieve_wire_bytes` — so senders drop
already-visited ids before compaction) and a history-predictive
selector: mesh-uniform carried scalars from prior levels (previous
``biggest``, frontier growth) let confidently-dense mid-BFS levels skip
the per-level pmax entirely, direction-optimizing style. The per-level
choice becomes sparse-delta / sparse-plain / packed-dense / sieved
(:func:`planned_branch_labels`), each priced exactly by
:func:`planned_sparse_wire_bytes_per_level` and HLO-audited by
utils/wirecheck.check_planned_sparse.
"""

from __future__ import annotations

import threading
from functools import partial, reduce as _reduce

import jax.numpy as jnp
import numpy as np
from jax import lax


def _chunk(x_full, c, size):
    return lax.dynamic_slice_in_dim(x_full, c * size, size)


def packed_words(n: int) -> int:
    """uint32 words needed to carry ``n`` booleans (32 vertices/word)."""
    return -(-n // 32)


def pack_bits(x):
    """Pack a boolean array's LAST axis into uint32 words, 32 vertices per
    word (vertex ``32*j + i`` -> bit ``i`` of word ``j``).

    Tail semantics: when the axis length ``n`` is not a multiple of 32 the
    final word's top ``32*ceil(n/32) - n`` bits are ZERO — the identity of
    bitwise_or — so packed buffers from different chips combine with word
    OR exactly as the bools would, and ``unpack_bits(.., n)`` recovers the
    mask without a tail mask. The padded bits are disjoint per word, so
    the packing sum cannot carry."""
    n = x.shape[-1]
    pad = packed_words(n) * 32 - n
    xb = x.astype(jnp.uint32)
    if pad:
        xb = jnp.concatenate(
            [xb, jnp.zeros(x.shape[:-1] + (pad,), jnp.uint32)], axis=-1
        )
    xb = xb.reshape(x.shape[:-1] + (packed_words(n), 32))
    return jnp.sum(
        xb << jnp.arange(32, dtype=jnp.uint32), axis=-1, dtype=jnp.uint32
    )


def unpack_bits(words, n: int):
    """Inverse of :func:`pack_bits`: the last axis of uint32 words back to
    ``n`` booleans (tail-padding bits are dropped)."""
    nw = words.shape[-1]
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    return bits.reshape(words.shape[:-1] + (nw * 32,))[..., :n] != 0


def _packed_reduce_scatter_or(x_full, axis_name: str, num_devices: int, impl: str):
    """Bit-packed OR-reduce-scatter: the uint32 wire format of both dense
    exchange impls.

    ``ring``: pack each destination chunk to ``ceil(n/32)`` words and run
    the same P-1-hop ring with ``bitwise_or`` as the word combine — 1/8
    the bytes of the pred ring, hop for hop. ``allreduce``: `lax.psum`
    cannot OR (word sums carry across bit positions), and max on words is
    not OR either — but the allreduce path only ever kept its own chunk of
    the psum, i.e. it IS a reduce-scatter; so the packed form is ONE
    `all_to_all` of the per-destination word chunks plus a local OR fold.
    Same collective instruction count (one), 1/32 the collective operand
    bytes of the s32 psum — and it sheds the psum's all-gather half on
    top, so the modeled wire bytes equal the packed ring's
    (``dense_or_wire_bytes``)."""
    p = num_devices
    if p == 1:
        return x_full
    n = x_full.shape[0] // p
    words = pack_bits(x_full.reshape(p, n))  # [p, nw], per-chunk packed
    if impl == "ring":
        out = ring_reduce_scatter(
            words.reshape(-1), axis_name, p, jnp.bitwise_or
        )
    else:
        recv = lax.all_to_all(words, axis_name, 0, 0, tiled=True)  # [p, nw]
        out = _reduce(jnp.bitwise_or, [recv[j] for j in range(p)])
    return unpack_bits(out, n)


def ring_reduce_scatter(x_full, axis_name: str, num_devices: int, op):
    """Reduce-scatter ``x_full`` ([P*n] per chip) down to this chip's [n]
    chunk, combining with ``op`` around a ring of `ppermute`s.

    Invariant: after s combine steps, chip i holds the partial reduction of
    chunk (i - 1 - s) mod P over chips (i-s..i); after P-1 steps that is the
    full reduction of chunk i.
    """
    p = num_devices
    if p == 1:
        return x_full
    n = x_full.shape[0] // p
    i = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p) for j in range(p)]
    acc = _chunk(x_full, (i - 1) % p, n)

    def step(s, acc):
        acc = lax.ppermute(acc, axis_name, perm)
        return op(acc, _chunk(x_full, (i - 1 - s) % p, n))

    return lax.fori_loop(1, p, step, acc, unroll=True)


def _check_impl(impl: str) -> None:
    # Loud rejection: an unknown impl (typo, or 'sparse' reaching an engine
    # that only does dense reduce-scatter) must not silently run allreduce.
    if impl not in ("ring", "allreduce"):
        raise ValueError(
            f"unknown reduce-scatter impl {impl!r}; have 'ring', 'allreduce' "
            "(the queue-style exchange is sparse_exchange_or, wired only "
            "through engines that accept exchange='sparse')"
        )


def reduce_scatter_or(
    x_full, axis_name: str, num_devices: int, *, impl: str = "ring",
    wire_pack: bool = False,
):
    """OR-reduce-scatter of a boolean contribution buffer (frontier exchange).

    Wire dtypes, pinned to the compiled HLO by tests/test_wirecheck.py:
    ``ring`` ships each chunk as PRED — one byte per vertex per hop;
    ``allreduce`` ships the whole buffer as S32 — four bytes per vertex.
    ``wire_pack=True`` ships uint32 words instead, 32 vertices per word
    (see :func:`_packed_reduce_scatter_or`)."""
    _check_impl(impl)
    if wire_pack:
        return _packed_reduce_scatter_or(x_full, axis_name, num_devices, impl)
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.logical_or)
    n = x_full.shape[0] // num_devices
    summed = lax.psum(x_full.astype(jnp.int32), axis_name)
    return _chunk(summed, lax.axis_index(axis_name), n) > 0


def reduce_scatter_min(x_full, axis_name: str, num_devices: int, *, impl: str = "ring"):
    """MIN-reduce-scatter of an int32 contribution buffer (parent merge —
    the analog of the reference's elementwise min result merge, bfs.cu:426-438)."""
    _check_impl(impl)
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.minimum)
    n = x_full.shape[0] // num_devices
    m = lax.pmin(x_full, axis_name)
    return _chunk(m, lax.axis_index(axis_name), n)


def dense_or_wire_bytes(
    p: int, n: int, impl: str, *, wire_pack: bool = False
) -> float:
    """Off-chip bytes one chip moves per level for the dense bitmap exchange.

    Dtypes per branch (each pinned to the compiled program by
    tests/test_wirecheck.py::test_packed_exchange_proof): ``ring`` sends
    P-1 chunks of n PRED elements — one BYTE per vertex per hop, not one
    bit; ``allreduce`` psums an S32 [P*n] buffer — four bytes per vertex,
    2*(P-1)*n int32 per chip at bandwidth-optimal allreduce cost. With
    ``wire_pack`` both impls ship uint32 words, ceil(n/32) per chunk: the
    ring as P-1 word-chunk hops, the allreduce path as one all_to_all
    that keeps the self chunk local — (P-1)*4*ceil(n/32) either way.

    The per-level termination psum (4 B scalar) is outside this model's
    scope by convention (see utils/wirecheck.py); only the SPARSE models
    carry a flat +4, for the phase-1 pmax scalar that exists only on that
    path."""
    if p == 1:
        return 0.0
    if wire_pack:
        return float((p - 1) * 4 * packed_words(n))
    return float(2 * (p - 1) * n * 4 if impl == "allreduce" else (p - 1) * n)


def dense_2d_wire_bytes(
    rows: int, cols: int, w: int, impl: str, *, wire_pack: bool = False
) -> float:
    """Off-chip bytes one chip moves per level in the 2D engine's level
    loop: the column all-gather over the 'r' axis (ring: each chip sends
    its [w] pred slice rows-1 times; packed: its ceil(w/32) uint32 words)
    plus the row reduce-scatter over 'c' (same shapes as the 1D dense
    exchange, dense_or_wire_bytes). Modeled, like every wire-byte figure
    here."""
    return column_gather_wire_bytes(
        rows, w, wire_pack=wire_pack
    ) + dense_or_wire_bytes(cols, w, impl, wire_pack=wire_pack)


def normalize_caps(caps) -> tuple[int, ...]:
    """Canonical cap ladder: ascending and DEDUPLICATED. Every consumer of
    a caps tuple (the `lax.cond` ladder, the per-branch byte models, the
    engines' branch-count arrays) must agree on one rung list — a
    caller-provided duplicate rung would otherwise build a dead cond
    branch and skew the branch-index accounting between them."""
    return tuple(sorted({int(c) for c in caps}))


def default_sparse_caps(
    vloc: int, *, wire_pack: bool = False, delta_bits: tuple[int, ...] = ()
) -> tuple[int, ...]:
    """Two-tier cap ladder: a tight cap for trickle levels (BFS start/tail,
    high-diameter graphs) and a wide one that still undercuts the dense
    bitmap's wire bytes by ~2x.

    The ladder calibrates against the dense fallback it competes with and
    the per-entry cost of the id encoding it ships: the break-even entry
    count is dense_bytes / entry_bytes, the wide rung half of it (the ~2x
    undercut), the tight rung 1/16. Unpacked dense costs vloc bytes and
    plain ids 4 bytes each -> rungs vloc/8 and vloc/64; the PACKED dense
    bitmap (``wire_pack``) costs vloc/8, dropping break-even 8x (rungs
    vloc/64, vloc/512); delta-encoded ids (ISSUE 7) cost
    min(delta_bits)/8 bytes per entry, RAISING break-even by the same
    ratio — at 8-bit deltas ids stay competitive to 4x denser frontiers
    (the header word is ignored as a rounding term)."""
    dense_bytes = vloc // 8 if wire_pack else vloc
    entry_bits = min(delta_bits) if delta_bits else 32
    be = dense_bytes * 8 // entry_bits
    return tuple(sorted({max(16, be // 16), max(16, be // 2)}))


def cap_ladder_select(biggest, caps: tuple[int, ...], make_sparse, dense_path):
    """The level-adaptive exchange selector shared by every queue-style
    exchange (``sparse_exchange_or``, ``sparse_rows_gather``): one
    mesh-uniform population scalar (a pmax already paid by phase 1) picks,
    level by level, the smallest rung of the ascending ``caps`` ladder
    that covers every chip — or ``dense_path`` when all overflow. This is
    the Buluç & Madduri sparse-ids/dense-bitmap format flip
    (arXiv:1104.4518) as one reusable `lax.cond` ladder: the scalar is
    identical on every chip, so all chips take the same branch and the
    collectives stay matched. ``make_sparse(cap, idx)`` returns the branch
    body for one rung; branch index = rung position (in the
    :func:`normalize_caps` order — ascending, deduped) or
    ``len(normalize_caps(caps))`` for dense."""
    ladder = normalize_caps(caps)
    step = dense_path
    for idx in range(len(ladder) - 1, -1, -1):
        step = partial(
            lax.cond, biggest <= ladder[idx], make_sparse(ladder[idx], idx), step
        )
    return step(None)


# --- delta-encoded sparse id chunks (ISSUE 7) -------------------------------

#: The static delta bit-width ladder (ascending; each must divide 32 so
#: fields never straddle word boundaries): 8-bit deltas cover gaps <= 255
#: between consecutive frontier ids, 16-bit <= 65535; wider gaps fall back
#: to plain 4-byte ids at the same cap rung.
DELTA_BITS_DEFAULT = (8, 16)
_DELTA_BITS_ALLOWED = (4, 8, 16)


def check_delta_bits(delta_bits) -> tuple[int, ...]:
    """Validate + canonicalize a delta bit-width ladder (ascending,
    deduped, each dividing 32 — {4, 8, 16})."""
    out = tuple(sorted({int(b) for b in delta_bits}))
    bad = [b for b in out if b not in _DELTA_BITS_ALLOWED]
    if bad:
        raise ValueError(
            f"delta_bits must be drawn from {_DELTA_BITS_ALLOWED} "
            f"(fixed-width fields packed into uint32 words), got {bad}"
        )
    return out


def delta_words(cap: int, bits: int) -> int:
    """uint32 words one destination's delta-encoded id chunk ships: one
    header word (the first id, full width) + ceil(cap*bits/32) words of
    fixed-width bit-packed deltas."""
    return 1 + -(-cap * bits // 32)


def delta_encode_ids(buf, sentinel: int, bits: int):
    """Delta-encode ascending id chunks into uint32 words.

    ``buf`` is [..., cap] int32 with each chunk's valid ids STRICTLY
    ascending in a contiguous prefix and ``sentinel`` after (the layout
    the cumsum compaction in :func:`sparse_exchange_or` emits). Output
    [..., delta_words(cap, bits)]: word 0 carries the first id verbatim
    (``sentinel`` for an empty chunk), then cap ``bits``-wide deltas
    packed LSB-first, 32//bits per word. Valid deltas are >= 1 (strict
    ascent), tail positions pack 0 — so the decoder recovers validity
    without a length field, and an all-zero payload round-trips the
    empty chunk. The caller guarantees every valid delta fits ``bits``
    bits (the pmax'd max-gap scalar picks the rung; see
    :func:`max_id_gap`)."""
    cap = buf.shape[-1]
    valid = buf < sentinel
    prev = jnp.concatenate([buf[..., :1], buf[..., :-1]], axis=-1)
    prev_valid = jnp.concatenate(
        [jnp.zeros_like(valid[..., :1]), valid[..., :-1]], axis=-1
    )
    d = jnp.where(valid & prev_valid, buf - prev, 0)
    per = 32 // bits
    pad = -cap % per
    if pad:
        d = jnp.concatenate(
            [d, jnp.zeros(d.shape[:-1] + (pad,), d.dtype)], axis=-1
        )
    du = d.astype(jnp.uint32).reshape(d.shape[:-1] + (-1, per))
    words = jnp.sum(
        du << (jnp.arange(per, dtype=jnp.uint32) * bits), axis=-1,
        dtype=jnp.uint32,
    )
    return jnp.concatenate([buf[..., :1].astype(jnp.uint32), words], axis=-1)


def delta_decode_ids(words, cap: int, bits: int):
    """Inverse of :func:`delta_encode_ids`: [..., delta_words(cap, bits)]
    uint32 -> ([..., cap] int32 ids, [..., cap] bool valid). Tail
    positions replicate the last valid id (their deltas are 0) and report
    invalid; an empty chunk decodes every position to the encoder's
    sentinel (also position 0, whose validity the CALLER must additionally
    gate on ``ids < sentinel`` when it matters — OR-scatters with a drop
    sentinel need neither mask, duplicates and the sentinel are both
    harmless there)."""
    first = words[..., :1].astype(jnp.int32)
    per = 32 // bits
    fields = (
        words[..., 1:, None] >> (jnp.arange(per, dtype=jnp.uint32) * bits)
    ) & jnp.uint32((1 << bits) - 1)
    d = fields.reshape(words.shape[:-1] + (-1,))[..., :cap].astype(jnp.int32)
    ids = first + jnp.cumsum(d, axis=-1)
    valid = jnp.concatenate(
        [jnp.ones_like(d[..., :1], dtype=bool), d[..., 1:] > 0], axis=-1
    )
    return ids, valid


def max_id_gap(rem):
    """Largest gap between consecutive set bits within each row of a
    [..., n] boolean chunk matrix — the widest delta a delta-encoded id
    stream of those rows must carry — maxed over every row. Rows with
    fewer than two set bits contribute 0 (the first id rides the header
    word, not a delta)."""
    n = rem.shape[-1]
    idx = jnp.arange(n, dtype=jnp.int32)
    last = lax.cummax(jnp.where(rem, idx, -1), axis=rem.ndim - 1)
    prev = jnp.concatenate(
        [jnp.full(rem.shape[:-1] + (1,), -1, jnp.int32), last[..., :-1]],
        axis=-1,
    )
    return jnp.max(jnp.where(rem & (prev >= 0), idx - prev, 0))


def sparse_exchange_or(
    x_full, axis_name: str, num_devices: int, *, caps: tuple[int, ...],
    wire_pack: bool = False,
):
    """Two-phase sparse (queue-style) frontier exchange.

    The TPU-native form of the reference's per-destination frontier buckets:
    `queueBfs` appends claimed vertices into per-destination-device buckets
    (bfs.cu:148-150), the driver peer-copies `nextQueueSize[j][i]` entries
    per pair (bfs.cu:604-606), and the MPI fork discovers variable receive
    sizes with `MPI_Sendrecv` + `MPI_Get_count` (bfs_mpi.cu:615-617). XLA has
    no variable-size messages (SURVEY.md §7.4), so sizes go first:

    - phase 1: `pmax` of the largest per-destination chunk popcount — one
      scalar — picks, level by level, the smallest cap in the static
      ascending ``caps`` ladder that covers every bucket;
    - phase 2a (some cap fits): compact each destination chunk's set bits
      into a static ``[P, cap]`` id buffer (cumsum compaction — the
      reference's dead scan-BFS queue generation, bfs.cu:706-781, as one
      XLA program), `all_to_all` it, and scatter-OR the received ids into
      the local chunk;
    - phase 2b (every cap overflows): dense ring bitmap reduce-scatter —
      on heavy mid-BFS levels of power-law graphs the bitmap IS the compact
      encoding.

    The per-level branch decision is the shared :func:`cap_ladder_select`
    (one mesh-uniform pmax scalar, every chip takes the same branch, so
    the collectives stay matched). Returns ``(hit [n] bool, branch int32)``
    — ``branch`` is the index of the cap that ran (ascending ladder order)
    or ``len(caps)`` for the dense fallback; callers accumulate exact
    int32 per-branch level counts and convert to wire bytes on the host
    (``sparse_wire_bytes_per_level``), so the traffic accounting never
    loses small sparse levels to float rounding.

    ``wire_pack=True`` swaps the dense fallback for the bit-packed ring
    (uint32 words, 1/8 the bytes); pair it with
    ``default_sparse_caps(vloc, wire_pack=True)`` so the ladder is
    calibrated against the packed dense cost (ids only win below vloc/32
    entries then).
    """
    p = num_devices
    n = x_full.shape[0] // p
    ladder = normalize_caps(caps)
    if p == 1:
        return x_full, jnp.int32(len(ladder))
    i = lax.axis_index(axis_name)
    chunks = x_full.reshape(p, n)
    # The self-destination bucket never crosses the wire: it ORs in locally
    # below and is excluded from cap selection, so partition-aligned frontier
    # growth (community/grid graphs expanding within one chip's range) stays
    # on the cheap sparse path instead of tripping the dense fallback.
    self_row = jnp.arange(p, dtype=jnp.int32)[:, None] == i  # [p, 1]
    remote = chunks & ~self_row
    counts = jnp.sum(remote.astype(jnp.int32), axis=1)
    biggest = lax.pmax(jnp.max(counts), axis_name)
    rows = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[:, None], (p, n))
    local_ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (p, n))

    def make_sparse(cap, idx):
        def sparse_path(_):
            pos = jnp.cumsum(remote.astype(jnp.int32), axis=1)
            slot = jnp.where(remote, pos - 1, cap)  # unset/self -> dropped
            buf = jnp.full((p, cap), n, jnp.int32)  # n = "no entry" sentinel
            buf = buf.at[rows, slot].set(local_ids, mode="drop")
            recv = lax.all_to_all(buf, axis_name, 0, 0, tiled=True)  # [p, cap]
            hit = (
                jnp.zeros((n,), jnp.bool_)
                .at[recv.reshape(-1)]
                .set(True, mode="drop")
            )
            return hit | jnp.take(chunks, i, axis=0), jnp.int32(idx)

        return sparse_path

    def dense_path(_):
        if wire_pack:
            hit = _packed_reduce_scatter_or(x_full, axis_name, p, "ring")
        else:
            hit = ring_reduce_scatter(x_full, axis_name, p, jnp.logical_or)
        return hit, jnp.int32(len(ladder))

    return cap_ladder_select(biggest, caps, make_sparse, dense_path)


# --- the ISSUE 7 exchange planner -------------------------------------------


def planned_branch_count(caps, delta_bits) -> int:
    """Flat branch-index space of :func:`planned_sparse_exchange_or`:
    with K = len(normalize_caps(caps)) rungs and W = len(delta_bits)
    encodings-per-rung-plus-plain, B = K*(W+1) sparse branches appear
    twice (unsieved then sieved), plus unsieved-dense, sieved-dense, and
    the history-predicted dense that skipped the pmax — 2B+3 total (see
    :func:`planned_branch_labels` for the exact order)."""
    b = len(normalize_caps(caps)) * (len(delta_bits) + 1)
    return 2 * b + 3


def _rung_names(caps, delta_bits) -> list[str]:
    """The per-rung label list every branch layout is built from — per
    cap c, each delta width then plain ids; index-aligned with the
    encoding order the `lax.cond` ladders compile."""
    names = []
    for c in normalize_caps(caps):
        names += [f"delta{b}[{c}]" for b in delta_bits]
        names.append(f"sparse[{c}]")
    return names


def planned_branch_labels(caps, delta_bits) -> list[str]:
    """Human labels of the planner's flat branch layout, index-aligned
    with :func:`planned_sparse_wire_bytes_per_level` and the branch ids
    :func:`planned_sparse_exchange_or` returns: per rung cap c, each
    delta width then plain ids; the dense fallback; the same rungs
    sieved; sieved-dense; and the predicted-dense branch that paid no
    pmax at all."""
    names = _rung_names(caps, delta_bits)
    return (
        names + ["dense"] + [f"sieved-{s}" for s in names]
        + ["sieved-dense", "dense-predicted"]
    )


def sieve_wire_bytes(p: int, n: int) -> float:
    """Per-chip wire bytes of the sieve's backward vis transfer: ONE
    all-gather of each receiver's packed [ceil(n/32)] uint32 vis chunk —
    the ~n/8-byte cost the selector's modeled id savings must beat
    before the sieve branch is taken."""
    return 0.0 if p == 1 else float((p - 1) * 4 * packed_words(n))


def planned_sparse_wire_bytes_per_level(
    p: int, n: int, caps, delta_bits, *, wire_pack: bool = False
) -> list[float]:
    """Host-side off-chip bytes per level for each planner branch, in
    :func:`planned_branch_labels` order. Measured levels pay 8 bytes for
    the phase-1 pmax PAIR (one s32[2] all-reduce: max bucket count + max
    id gap); sieved levels pay it twice (post-sieve re-measure) plus the
    vis transfer; the predicted-dense branch pays no scalar at all —
    skipping it is the predictor's whole point."""
    nb = planned_branch_count(caps, delta_bits)
    if p == 1:
        return [0.0] * nb
    sparse = []
    for c in normalize_caps(caps):
        sparse += [float((p - 1) * 4 * delta_words(c, b)) for b in delta_bits]
        sparse.append(float((p - 1) * 4 * c))
    dense = dense_or_wire_bytes(p, n, "ring", wire_pack=wire_pack)
    sv = sieve_wire_bytes(p, n)
    return (
        [s + 8.0 for s in sparse] + [dense + 8.0]
        + [s + sv + 16.0 for s in sparse] + [dense + sv + 16.0]
        + [dense]
    )


def planned_sparse_exchange_or(
    x_full, axis_name: str, num_devices: int, *, caps: tuple[int, ...],
    delta_bits: tuple[int, ...] = (), sieve: bool = False, visited=None,
    visited_total=None, predict: bool = False, prev_biggest=None,
    growing=None, wire_pack: bool = False,
):
    """:func:`sparse_exchange_or` generalized into the ISSUE 7 exchange
    planner: per level the choice becomes sparse-delta / sparse-plain /
    packed-dense / sieved, driven by mesh-uniform scalars so every chip
    takes matching branches and the collectives stay paired.

    Three cooperating pieces on top of the cap ladder:

    - **delta-encoded ids** (``delta_bits``, ascending widths): the
      compacted id chunks are already ascending, so each destination
      ships first-id + ``b``-bit bit-packed deltas in uint32 words
      (:func:`delta_encode_ids`) — ``delta_words(cap, b)`` words instead
      of ``cap`` int32s. The width rung is picked by the max
      consecutive-id gap, pmax'd as an s32[2] PAIR with the max bucket
      count (one scalar all-reduce covers both ladders); gaps past the
      widest ladder rung fall back to plain 4-byte ids at the same cap.
    - **visited sieve** (``sieve=True``; needs ``visited`` — this chip's
      own [n] bool chunk — and ``visited_total``, a mesh-uniform carried
      scalar): when the modeled id savings (visited-density x biggest x
      4 id bytes per destination) beat the vis transfer's own
      ~n/8-byte cost (:func:`sieve_wire_bytes`) and a smaller rung is
      even reachable, each receiver's packed vis chunk is all-gathered
      backward ONCE and senders drop already-visited ids before
      compaction. The sieved ``hit`` is therefore NOT the raw OR of
      contributions: it agrees with it exactly on this chip's unvisited
      positions (plus its own full contribution) — precisely what the
      claim ``new = hit & ~visited`` consumes, so traversal results stay
      bit-identical (fuzz-pinned).
    - **history prediction** (``predict=True``; needs ``prev_biggest``
      and ``growing``, mesh-uniform loop-carried scalars): when the
      previous measured level overflowed every cap AND the frontier is
      still growing, this level is confidently dense mid-BFS — take the
      dense path immediately and skip the pmax entirely
      (direction-optimizing-style prediction). The carry exits
      prediction the first shrinking level, which re-measures.

    Returns ``(hit [n] bool, branch int32, biggest int32)``: ``branch``
    indexes :func:`planned_branch_labels`; ``biggest`` is the scalar to
    carry into the next level's predictor (the measured pmax, or the
    stale carry on predicted levels — still above every cap, which is
    what keeps the prediction armed)."""
    p = num_devices
    n = x_full.shape[0] // p
    ladder = normalize_caps(caps)
    delta_bits = check_delta_bits(delta_bits)
    K, W = len(ladder), len(delta_bits)
    B = K * (W + 1)
    if p == 1:
        return x_full, jnp.int32(B), jnp.int32(0)
    i = lax.axis_index(axis_name)
    chunks = x_full.reshape(p, n)
    self_row = jnp.arange(p, dtype=jnp.int32)[:, None] == i
    remote = chunks & ~self_row
    own = jnp.take(chunks, i, axis=0)
    rows = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[:, None], (p, n))
    local_ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (p, n))

    def dense_hit():
        if wire_pack:
            return _packed_reduce_scatter_or(x_full, axis_name, p, "ring")
        return ring_reduce_scatter(x_full, axis_name, p, jnp.logical_or)

    def measure(rem):
        counts = jnp.sum(rem.astype(jnp.int32), axis=1)
        mx = lax.pmax(jnp.stack([jnp.max(counts), max_id_gap(rem)]), axis_name)
        return mx[0], mx[1]

    def scatter_hit(ids):
        # Drop-mode OR-scatter: the sentinel n (empty chunks) drops, tail
        # positions replicate an already-set id — neither needs a mask.
        return (
            jnp.zeros((n,), jnp.bool_)
            .at[ids.reshape(-1)]
            .set(True, mode="drop")
        )

    def encode_ladder(rem, biggest, dmax, base):
        """Cap rungs x encodings over one remote matrix; flat branch ids
        start at ``base`` (0 unsieved, B+1 sieved)."""

        def make_rung(cap, ri):
            def rung(_):
                pos = jnp.cumsum(rem.astype(jnp.int32), axis=1)
                slot = jnp.where(rem, pos - 1, cap)
                buf = jnp.full((p, cap), n, jnp.int32)
                buf = buf.at[rows, slot].set(local_ids, mode="drop")

                def plain(_):
                    recv = lax.all_to_all(buf, axis_name, 0, 0, tiled=True)
                    return (
                        scatter_hit(recv),
                        jnp.int32(base + ri * (W + 1) + W),
                    )

                step = plain
                for e in range(W - 1, -1, -1):
                    def enc(_, bits=delta_bits[e], e=e):
                        words = delta_encode_ids(buf, n, bits)
                        recv = lax.all_to_all(words, axis_name, 0, 0, tiled=True)
                        ids, _ = delta_decode_ids(recv, cap, bits)
                        return (
                            scatter_hit(ids),
                            jnp.int32(base + ri * (W + 1) + e),
                        )

                    step = partial(
                        lax.cond, dmax <= (1 << delta_bits[e]) - 1, enc, step
                    )
                return step(None)

            return rung

        def dense_leaf(_):
            return dense_hit(), jnp.int32(base + B)

        return cap_ladder_select(biggest, ladder, make_rung, dense_leaf)

    def measured(_):
        biggest, dmax = measure(remote)

        def unsieved(_):
            h, br = encode_ladder(remote, biggest, dmax, 0)
            return h, br, biggest

        if not sieve:
            return unsieved(None)

        def sieved(_):
            allv = lax.all_gather(pack_bits(visited), axis_name)  # [p, nw]
            rem2 = remote & ~unpack_bits(allv, n)
            b2, d2 = measure(rem2)
            h, br = encode_ladder(rem2, b2, d2, B + 1)
            return h, br, biggest

        # Sieve when modeled id savings beat the vis transfer's own cost:
        # visited-density rho x biggest x 4 id bytes per destination vs
        # the packed vis chunk's 4*ceil(n/32) bytes — and only when a
        # smaller rung is even reachable (biggest above the tightest
        # cap). float32 over mesh-uniform ints stays mesh-uniform, so
        # every chip takes the same cond branch.
        rho = visited_total.astype(jnp.float32) / float(p * n)
        gain = rho * biggest.astype(jnp.float32) * 4.0
        sieve_on = (gain > 4.0 * packed_words(n)) & (biggest > ladder[0])
        return lax.cond(sieve_on, sieved, unsieved, None)

    if predict:
        def predicted(_):
            return dense_hit(), jnp.int32(2 * B + 2), prev_biggest

        pred = (prev_biggest > ladder[-1]) & growing
        hit, branch, biggest = lax.cond(pred, predicted, measured, None)
    else:
        hit, branch, biggest = measured(None)
    return hit | own, branch, biggest


def merge_exchange_counts(prev, counts, resumed_level: int):
    """Accumulate per-branch exchange level counts across the chunks of one
    checkpointed traversal. The consistency test is ``prev.sum() ==
    resumed_level`` — the previous counters cover exactly levels
    [0, resumed_level) iff they belong to this chain. Callers gate ``prev``
    through :func:`chained_prev_counts` first, which keys the chain on the
    checkpoint's identity nonce, so counters left by an UNRELATED traversal
    can no longer merge by level-count coincidence; chains whose earlier
    chunks ran in another process simply restart the count (covering the
    levels run here). Shared by every engine with exchange accounting.

    A ``prev`` whose branch-count LENGTH differs from the current ladder's
    (the caps / wire_pack / delta / sieve config changed across a
    checkpoint resume, reshaping the branch space) cannot merge — the
    indices no longer mean the same branches and ``counts + prev`` would
    be a shape error; the count restarts instead, covering the levels run
    under the current config."""
    counts = np.asarray(counts)
    if resumed_level > 0 and prev is not None:
        prev = np.asarray(prev)
        if prev.shape == counts.shape and prev.sum() == resumed_level:
            return counts + prev
    return counts


def chained_prev_counts(prev, resumed_level: int, prev_nonce, nonce):
    """Identity gate for chunked-traversal exchange accounting.

    The previous counters belong to the chain being resumed only if the
    engine last recorded under the SAME chain nonce (stamped into the
    checkpoint at start(), utils/checkpoint.py). A None nonce (old
    checkpoint format, or a fresh non-checkpointed run) never chains —
    the count restarts, covering the levels run here."""
    if resumed_level > 0 and (nonce is None or prev_nonce != nonce):
        return None
    return prev


def gate_and_stamp_chain(engine, resumed_level: int, chain_nonce):
    """The gate-and-stamp step every ``_record_exchange`` shares: gate the
    engine's previous counters through :func:`chained_prev_counts` and
    stamp the engine with the new chain nonce. Returns the gated ``prev``
    for the caller's merge + pricing."""
    prev = chained_prev_counts(
        engine.last_exchange_level_counts, resumed_level,
        getattr(engine, "_exchange_chain_nonce", None), chain_nonce,
    )
    engine._exchange_chain_nonce = chain_nonce
    return prev


def rows_gather_branch_count(caps, delta_bits) -> int:
    """Flat branch space of :func:`sparse_rows_gather`: per cap rung each
    delta width then plain ids, plus the dense slab — K*(W+1)+1 (no sieve
    or prediction on the row gather; the lane words ARE the payload)."""
    return len(normalize_caps(caps)) * (len(delta_bits) + 1) + 1


def rows_gather_branch_labels(caps, delta_bits) -> list[str]:
    """Labels for the row-gather branch layout (index-aligned with
    :func:`sparse_rows_wire_bytes_per_level`); with no delta ladder this
    is the legacy ``sparse[c]``.. + ``dense`` list."""
    return _rung_names(caps, delta_bits) + ["dense"]


def sparse_rows_gather(
    nxt, axis_name: str, *, caps: tuple[int, ...],
    out_rows: int, gid_of, dense_fn,
    delta_bits: tuple[int, ...] = (), gid_of_src=None,
):
    """Queue-style frontier gather for the packed MS engines, shared by the
    distributed wide and hybrid engines (which differ only in their
    local-row -> global-row maps and dense slab layouts).

    When every chip's new-frontier row count fits a ``caps`` rung (decided
    by one mesh-uniform `pmax`, so every chip takes the same `lax.cond`
    branch and the collectives stay matched), the level gathers
    (global row id + lane words) pairs and rebuilds the full [out_rows, w]
    table with one drop-mode scatter; otherwise ``dense_fn()`` gathers the
    full packed slab — on dense mid-BFS levels the slab IS the compact
    encoding. ``gid_of(local_ids)`` maps this chip's local row ids to
    global table rows; it IS called on the nonzero-fill ids (= the local
    row count) too — this function masks those to the ``out_rows`` drop
    sentinel afterwards, so the map must merely not crash on them (pure
    arithmetic maps are fine).

    ``delta_bits`` (ISSUE 7): the nonzero-compacted row ids are ascending,
    so each chip can ship first-id + fixed-width bit-packed deltas
    (:func:`delta_encode_ids` over LOCAL ids — local gaps stay small where
    global round-robin ids would stride by P) instead of 4-byte global
    ids; the receiver decodes and applies ``gid_of_src(ids, src)`` (the
    two-arg form of the row map, ``src`` = sender's mesh index — required
    when delta_bits is set) per gathered chunk. Decoded tail duplicates
    and empty chunks are masked to the drop sentinel — the value scatter
    is a SET, so a duplicate id must not let a zeroed tail row clobber a
    real one. The width rung rides the same pmax as the row count (one
    s32[2] pair).

    Returns ``(table [out_rows, w], branch int32)`` — branch indexes the
    :func:`rows_gather_branch_labels` layout (with no delta ladder: the
    taken rung in ascending caps order, or ``len(caps)`` for dense).
    """
    rows_loc, w = nxt.shape
    any_row = jnp.any(nxt != 0, axis=1)  # [rows_loc]
    if not delta_bits:
        biggest = lax.pmax(jnp.sum(any_row.astype(jnp.int32)), axis_name)

        def make_sparse(cap, idx):
            def sparse_fn(_):
                (ids,) = jnp.nonzero(any_row, size=cap, fill_value=rows_loc)
                ok = ids < rows_loc
                vals = jnp.where(ok[:, None], nxt[jnp.where(ok, ids, 0)], 0)
                gids = jnp.where(ok, gid_of(ids), out_rows)
                ag_ids = lax.all_gather(gids, axis_name).reshape(-1)
                ag_vals = lax.all_gather(vals, axis_name).reshape(-1, w)
                table = (
                    jnp.zeros((out_rows, w), jnp.uint32)
                    .at[ag_ids]
                    .set(ag_vals, mode="drop")  # sentinel out_rows drops
                )
                return table, jnp.int32(idx)

            return sparse_fn

        def dense_branch(_):
            return dense_fn(), jnp.int32(len(normalize_caps(caps)))

        return cap_ladder_select(biggest, caps, make_sparse, dense_branch)

    if gid_of_src is None:
        raise ValueError(
            "delta-encoded sparse_rows_gather needs gid_of_src(ids, src) — "
            "the receiver decodes LOCAL ids and must map them per sender"
        )
    delta_bits = check_delta_bits(delta_bits)
    ladder = normalize_caps(caps)
    K, W = len(ladder), len(delta_bits)
    mx = lax.pmax(
        jnp.stack([
            jnp.sum(any_row.astype(jnp.int32)),
            max_id_gap(any_row[None, :]),
        ]),
        axis_name,
    )
    biggest, dmax = mx[0], mx[1]

    def make_rung(cap, ri):
        def rung(_):
            (ids,) = jnp.nonzero(any_row, size=cap, fill_value=rows_loc)
            ok = ids < rows_loc
            vals = jnp.where(ok[:, None], nxt[jnp.where(ok, ids, 0)], 0)
            ag_vals = lax.all_gather(vals, axis_name).reshape(-1, w)

            def plain(_):
                gids = jnp.where(ok, gid_of(ids), out_rows)
                ag_ids = lax.all_gather(gids, axis_name).reshape(-1)
                return ag_ids, jnp.int32(ri * (W + 1) + W)

            step = plain
            for e in range(W - 1, -1, -1):
                def enc(_, bits=delta_bits[e], e=e):
                    words = delta_encode_ids(ids[None, :], rows_loc, bits)[0]
                    ag_w = lax.all_gather(words, axis_name)  # [p, dw]
                    dec, valid = delta_decode_ids(ag_w, cap, bits)
                    src = jnp.arange(ag_w.shape[0], dtype=jnp.int32)[:, None]
                    okd = valid & (dec < rows_loc)
                    gids = jnp.where(okd, gid_of_src(dec, src), out_rows)
                    return gids.reshape(-1), jnp.int32(ri * (W + 1) + e)

                step = partial(
                    lax.cond, dmax <= (1 << delta_bits[e]) - 1, enc, step
                )
            ag_ids, br = step(None)
            table = (
                jnp.zeros((out_rows, w), jnp.uint32)
                .at[ag_ids]
                .set(ag_vals, mode="drop")
            )
            return table, br

        return rung

    def dense_leaf(_):
        return dense_fn(), jnp.int32(K * (W + 1))

    return cap_ladder_select(biggest, ladder, make_rung, dense_leaf)


def default_row_gather_caps(
    rows_loc: int, w: int, delta_bits: tuple[int, ...] = ()
) -> tuple[int, ...]:
    """Width-aware cap ladder for sparse_rows_gather: each gathered row
    costs an id (4 bytes plain, min(delta_bits)/8 delta-encoded) + 4w
    payload bytes vs the dense slab's 4w per row, so the byte win holds
    below rows_loc*32w/(32w + id_bits) rows; two tiers as in
    default_sparse_caps (tight rung for trickle levels, half break-even).
    The payload dominates at serving widths, so the delta recalibration
    barely moves the rungs — it exists so the ladder stays honest at
    w=1."""
    id_bits = min(delta_bits) if delta_bits else 32
    be = (rows_loc * 32 * w) // (32 * w + id_bits)
    return tuple(sorted({max(1, be // 16), max(1, be // 2)}))


def dense_rows_wire_bytes(p: int, rows_loc: int, w: int) -> float:
    """Off-chip bytes one chip moves per level gathering the full packed
    [rows_loc, w] u32 slab from every peer — the packed MS engines' dense
    exchange (and the sliced rotation's per-level total, which moves the
    same slab in P-1 ring hops). The single source for this figure:
    exchange accounting, the sparse ladder's dense rung, and
    roofline.phase_bytes all price from here."""
    return 0.0 if p == 1 else float((p - 1) * rows_loc * 4 * w)


def sparse_rows_wire_bytes_per_level(
    p: int, rows_loc: int, w: int, caps: tuple[int, ...],
    delta_bits: tuple[int, ...] = (),
) -> list[float]:
    """Modeled off-chip bytes per level per sparse_rows_gather branch, in
    :func:`rows_gather_branch_labels` order. With no delta ladder every
    branch pays the 4-byte pmax scalar (legacy layout); with one, the
    8-byte s32[2] pair (row count + max id gap) and each delta rung ships
    ``delta_words(c, b)`` id words instead of ``c`` int32s (the 4w-byte
    lane payload per row is encoding-invariant). A 1-device mesh moves
    nothing."""
    nb = rows_gather_branch_count(caps, delta_bits)
    if p == 1:
        return [0.0] * nb
    if not delta_bits:
        return [
            float((p - 1) * c * (4 + 4 * w) + 4) for c in normalize_caps(caps)
        ] + [dense_rows_wire_bytes(p, rows_loc, w) + 4.0]
    out = []
    for c in normalize_caps(caps):
        out += [
            float((p - 1) * (4 * delta_words(c, b) + 4 * c * w) + 8)
            for b in delta_bits
        ]
        out.append(float((p - 1) * c * (4 + 4 * w) + 8))
    return out + [dense_rows_wire_bytes(p, rows_loc, w) + 8.0]


def record_row_gather_exchange(
    prev, branch_counts, resumed_level: int, *, exchange: str, p: int,
    rows_loc: int, w: int, caps: tuple[int, ...],
    delta_bits: tuple[int, ...] = (),
):  # ``prev`` is pre-gated by chained_prev_counts in the engine mixin.
    """The packed MS engines' complete exchange accounting step: merge the
    per-branch level counts into the chunked-traversal chain, then price
    them with the row-gather byte model (dense impls have the single slab
    entry). Returns (counts, bytes) for the engine to store.

    Known modeling gap: an engine whose cap-boundary truncation probe
    itself gathers a frontier (the distributed hybrid's claim-free
    ``deeper`` check) moves one extra uncounted gather on truncated runs —
    at most once per traversal, only when the plane cap was hit."""
    counts = merge_exchange_counts(prev, branch_counts, resumed_level)
    if exchange == "sparse":
        per = sparse_rows_wire_bytes_per_level(p, rows_loc, w, caps, delta_bits)
    else:
        per = [dense_rows_wire_bytes(p, rows_loc, w)]
    return counts, float(np.dot(counts, per))


class RowGatherExchangeAccounting:
    """Mixin for the distributed packed MS engines: the per-branch counter
    bookkeeping shared by both (record + the checkpoint-resume core
    wrapper). Hosts set ``_exchange``, ``sparse_caps``, ``w``,
    ``_gather_p``, ``_gather_rows_loc``, ``_core_from_jit``, and the two
    ``last_exchange_*`` attributes.

    Recording is DEFERRED (ISSUE 11): ``_record_exchange`` runs inside
    the async dispatch half (``_core`` is called by
    ``dispatch_packed_batch``), and the branch counters are while-loop
    outputs — an eager ``np.asarray`` there would block dispatch on the
    whole level loop, serializing the serve pipeline's overlap. The
    record therefore stashes the device array and the chain bookkeeping;
    the first reader of either ``last_exchange_*`` attribute (fetch-side
    telemetry, engine traces, roofline) pays the transfer, by which time
    the loop has long finished. Pending records flush strictly in
    dispatch order, so chunked-traversal chains merge exactly as the
    eager path did."""

    def _exchange_state(self):
        d = self.__dict__
        if "_exchange_pending" not in d:
            d["_exchange_pending"] = []
            d["_exchange_flush_lock"] = threading.Lock()
        return d

    def _record_exchange(
        self, branch_counts, resumed_level: int, chain_nonce=None
    ) -> None:
        st = self._exchange_state()
        with st["_exchange_flush_lock"]:
            st["_exchange_pending"].append(
                (branch_counts, int(resumed_level), chain_nonce)
            )

    @staticmethod
    def _counters_ready(bc) -> bool:
        """Is a pending record's device counter array materialized (its
        level loop finished)? Readers flush only the READY prefix: a
        pipelined serve fetch of batch N must neither block on batch
        N+1's still-running loop nor adopt its figures. Arrays without
        an ``is_ready`` probe (older jax, plain numpy) count as ready —
        the flush then blocks exactly like the pre-deferral path."""
        probe = getattr(bc, "is_ready", None)
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 — readiness is an optimization
            return True

    def _flush_exchange(self, *, ready_only: bool = False) -> None:
        st = self._exchange_state()
        with st["_exchange_flush_lock"]:
            pending = st["_exchange_pending"]
            if ready_only:
                # In-order prefix: stop at the first record whose loop is
                # still running — order is the chain-merge invariant.
                take = 0
                for bc, _lvl, _nonce in pending:
                    if not self._counters_ready(bc):
                        break
                    take += 1
                pending, st["_exchange_pending"] = (
                    pending[:take], pending[take:]
                )
            else:
                st["_exchange_pending"] = []
            for bc, lvl, nonce in pending:
                prev = chained_prev_counts(
                    self.__dict__.get("_lec_raw"), lvl,
                    self.__dict__.get("_exchange_chain_nonce"), nonce,
                )
                self.__dict__["_exchange_chain_nonce"] = nonce
                counts, price = record_row_gather_exchange(
                    prev, bc, lvl,
                    exchange=self._exchange, p=self._gather_p,
                    rows_loc=self._gather_rows_loc, w=self.w,
                    caps=self.sparse_caps,
                    delta_bits=getattr(self, "delta_bits", ()),
                )
                self.__dict__["_lec_raw"] = counts
                self.__dict__["_leb_raw"] = price

    def completed_exchange_record(self):
        """``(counts, bytes)`` of the newest COMPLETED record, flushing
        only pending records whose loops have finished — the serve
        pipeline's reader: fetch of batch N must neither block on batch
        N+1's still-running loop nor wait for it. NB when batches N and
        N+1 both completed before the read, the newest wins — adjacent
        batches on one engine share per-level prices, so the residual
        misattribution is bounded telemetry noise, not a wrong model."""
        self._flush_exchange(ready_only=True)
        return self.__dict__.get("_lec_raw"), self.__dict__.get("_leb_raw")

    @property
    def last_exchange_level_counts(self):
        self._flush_exchange()
        return self.__dict__.get("_lec_raw")

    @last_exchange_level_counts.setter
    def last_exchange_level_counts(self, value) -> None:
        # Hosts initialize to None; the roofline's trace overwrite and
        # tests assign too. An assignment supersedes anything pending.
        st = self._exchange_state()
        with st["_exchange_flush_lock"]:
            st["_exchange_pending"] = []
            self.__dict__["_lec_raw"] = value

    @property
    def last_exchange_bytes(self):
        self._flush_exchange()
        return self.__dict__.get("_leb_raw")

    @last_exchange_bytes.setter
    def last_exchange_bytes(self, value) -> None:
        # Same supersede contract as the counts setter: an assignment
        # must not be silently overwritten by a later read's flush.
        st = self._exchange_state()
        with st["_exchange_flush_lock"]:
            st["_exchange_pending"] = []
            self.__dict__["_leb_raw"] = value

    def exchange_branch_labels(self) -> list[str] | None:
        """Branch labels index-aligned with the engine's counters — the
        engine-trace hook (obs/engine_trace reads this when present)."""
        if self._exchange != "sparse":
            return None
        return rows_gather_branch_labels(
            self.sparse_caps, getattr(self, "delta_bits", ())
        )

    def wire_bytes_per_level(self) -> list[float]:
        """Modeled off-chip bytes per level per exchange branch, labels
        aligned with :meth:`exchange_branch_labels` — the same API the
        1D/2D/sssp dist engines expose, so the bench's per-kind wire
        table prices every serving engine uniformly."""
        if self._exchange == "sparse":
            return sparse_rows_wire_bytes_per_level(
                self._gather_p, self._gather_rows_loc, self.w,
                self.sparse_caps, getattr(self, "delta_bits", ()),
            )
        return [dense_rows_wire_bytes(
            self._gather_p, self._gather_rows_loc, self.w
        )]

    def _core_from(self, arrs, fw, vis, planes, level0, max_levels):
        fw_f, vis_f, planes_f, level, alive, bc = self._core_from_jit(
            arrs, fw, vis, planes, level0, max_levels
        )
        # advance_packed_batch stamps the resumed checkpoint's chain nonce
        # here before calling (read, not popped: the cap-boundary probe is
        # a second _core_from of the same advance and must chain too).
        self._record_exchange(
            bc, int(level0), getattr(self, "_pending_chain_nonce", None)
        )
        return fw_f, vis_f, planes_f, level, alive

    def _core_from_donate(self, arrs, fw, vis, planes, level0, max_levels):
        """The donating resume entry (ISSUE 13, analysis pass 5): the
        same sharded loop re-jitted lazily with the carry donated, plus
        the exchange accounting of :meth:`_core_from`. advance's
        converted checkpoint carries are dead after the call, so the
        loop's outputs alias their buffers instead of doubling the
        sharded table residency per chunk; the cap-boundary probe and
        roofline keep the copying ``_core_from``/``_core_from_jit``
        (they re-read their carries)."""
        import jax

        fn = self.__dict__.get("_core_from_donate_jit")
        if fn is None:
            # Gated dist engines have no plain _core_from_jit (their
            # gated raw takes the lane-mask argument); they — and any
            # test double without a raw traceable — keep the copying
            # entry.
            inner = getattr(self, "_core_from_jit", None)
            raw = getattr(inner, "__wrapped__", None)
            if raw is None:
                return self._core_from(
                    arrs, fw, vis, planes, level0, max_levels
                )
            fn = jax.jit(raw, donate_argnums=(1, 2, 3))
            fn._donate_argnums = (1, 2, 3)
            self.__dict__["_core_from_donate_jit"] = fn
        fw_f, vis_f, planes_f, level, alive, bc = fn(
            arrs, fw, vis, planes, level0, max_levels
        )
        self._record_exchange(
            bc, int(level0), getattr(self, "_pending_chain_nonce", None)
        )
        return fw_f, vis_f, planes_f, level, alive


def sparse_wire_bytes_per_level(
    p: int, n: int, caps: tuple[int, ...], *, wire_pack: bool = False
) -> list[float]:
    """Host-side off-chip bytes per level for each sparse_exchange_or branch,
    in branch-index order (normalize_caps order, then the dense ring
    fallback — the bit-packed ring under ``wire_pack``). Each branch pays
    4 bytes for the phase-1 pmax scalar. (The ISSUE 7 planner's richer
    branch space prices via :func:`planned_sparse_wire_bytes_per_level`.)"""
    ladder = normalize_caps(caps)
    if p == 1:
        return [0.0] * (len(ladder) + 1)
    return [float((p - 1) * c * 4 + 4) for c in ladder] + [
        dense_or_wire_bytes(p, n, "ring", wire_pack=wire_pack) + 4.0
    ]


def column_gather_wire_bytes(rows: int, w: int, *, wire_pack: bool = False) -> float:
    """Off-chip bytes one chip moves in the 2D engine's per-level column
    all-gather over 'r' (each chip sends its [w] pred slice rows-1 times;
    ceil(w/32) uint32 words packed). The single source for this term:
    dense_2d_wire_bytes and the 2D sparse models both price from here."""
    if rows <= 1:
        return 0.0
    return float((rows - 1) * 4 * packed_words(w)) if wire_pack else float(
        (rows - 1) * w
    )


# --- the (min, +) value-exchange family (ISSUE 20) --------------------------
#
# The OR exchanges above move BITMAPS (a vertex is reached or not); the
# workload kinds that carry a value per vertex — sssp distances, cc
# min-labels — exchange int32 WORDS under elementwise min instead. Min is
# associative-commutative with an identity (the caller's INF sentinel), so
# every structural trick transfers verbatim: the dense paths become
# reduce_scatter_min / pmin, the queue-style path ships (row id, value row)
# pairs with the SAME delta id codec and cap ladder as sparse_rows_gather,
# and the receiver folds with a drop-mode scatter-MIN — which, unlike the
# OR gather's SET, is duplicate-safe by construction.


def minplus_rows_branch_count(caps, delta_bits, *, predict: bool = False) -> int:
    """Flat branch space of :func:`sparse_rows_exchange_min`: the row-gather
    layout (per cap rung each delta width then plain ids, plus dense), with
    one extra trailing branch when history prediction is armed — the dense
    level that skipped the pmax entirely."""
    return rows_gather_branch_count(caps, delta_bits) + (1 if predict else 0)


def minplus_rows_branch_labels(
    caps, delta_bits, *, predict: bool = False
) -> list[str]:
    """Labels for the min-exchange branch layout, index-aligned with
    :func:`minplus_rows_wire_bytes_per_level` and the branch ids
    :func:`sparse_rows_exchange_min` returns."""
    labels = rows_gather_branch_labels(caps, delta_bits)
    return labels + ["dense-predicted"] if predict else labels


def dense_min_wire_bytes(p: int, rows_loc: int, lanes: int) -> float:
    """Off-chip bytes one chip moves per round in the dense min exchange of
    a replicated [p*rows_loc, lanes] int32 value table: the ring impl
    reduce-scatters P-1 [rows_loc, lanes] chunks then all-gathers the
    reduced chunks back (each chip's chunk crosses the wire P-1 times), the
    allreduce impl pmins the whole buffer at the same bandwidth-optimal
    2*(P-1)/P cost — 2*(p-1)*rows_loc*4*lanes either way. The per-round
    light-sweep convergence psum (4 B scalar) is outside this model by the
    same convention as :func:`dense_or_wire_bytes`."""
    return 0.0 if p == 1 else float(2 * (p - 1) * rows_loc * 4 * lanes)


def minplus_rows_wire_bytes_per_level(
    p: int, rows_loc: int, lanes: int, caps: tuple[int, ...],
    delta_bits: tuple[int, ...] = (), *, predict: bool = False,
) -> list[float]:
    """Modeled off-chip bytes per round per :func:`sparse_rows_exchange_min`
    branch, in :func:`minplus_rows_branch_labels` order. The sparse rungs
    are the row-gather model with the lane payload reinterpreted: a changed
    row ships ``lanes`` int32 distance words (4*lanes bytes) instead of
    ``w`` packed uint32 frontier words (4*w bytes) — numerically the same
    formula, so :func:`sparse_rows_wire_bytes_per_level` is the single
    source. The predicted-dense branch (when armed) pays the dense
    all-gather with NO measurement scalar — skipping it is the predictor's
    whole point."""
    base = sparse_rows_wire_bytes_per_level(p, rows_loc, lanes, caps, delta_bits)
    if not predict:
        return base
    extra = 0.0 if p == 1 else dense_rows_wire_bytes(p, rows_loc, lanes)
    return base + [extra]


def sparse_rows_exchange_min(
    new_loc, own_prev, prev_full, axis_name: str, *, caps: tuple[int, ...],
    out_rows: int, gid_of, dense_fn, ident, delta_bits: tuple[int, ...] = (),
    gid_of_src=None, predict: bool = False, prev_biggest=None, growing=None,
):
    """Queue-style id+value exchange under elementwise min — the (min, +)
    twin of :func:`sparse_rows_gather`, shared by the distributed
    delta-stepping engines.

    ``new_loc`` [rows_loc, lanes] int32 is this chip's updated owned-row
    values, elementwise <= ``own_prev`` (its rows of the replicated
    previous table ``prev_full`` [out_rows, lanes]); a row crosses the wire
    iff some lane improved. When every chip's changed-row count fits a
    ``caps`` rung (one mesh-uniform pmax — an s32[2] pair with the max id
    gap when ``delta_bits`` is set), each chip all-gathers (global row id,
    int32 value row) pairs and every receiver folds them into its replica
    with one drop-mode scatter-min; otherwise ``dense_fn()`` rebuilds the
    table densely (the callers' all-gather of every chip's owned rows —
    on heavy rounds the slab IS the compact encoding). Ids delta-encode
    exactly as the OR gather (LOCAL ids, :func:`delta_encode_ids`, the
    receiver maps per sender via ``gid_of_src``); values ride alongside at
    fixed width — min's identity ``ident`` fills invalid slots, so decoded
    tail duplicates are harmless even before the sentinel-id drop.

    ``predict=True`` arms the ISSUE 7 history predictor: when the previous
    measured round overflowed every cap (``prev_biggest``, mesh-uniform
    carry) AND the update set is still growing (``growing``), the round is
    confidently dense — take ``dense_fn()`` immediately and skip the pmax.

    Returns ``(table [out_rows, lanes] int32, branch int32, biggest
    int32)`` — branch indexes :func:`minplus_rows_branch_labels`;
    ``biggest`` is the measured pmax (stale carry on predicted rounds) for
    the next round's predictor."""
    rows_loc, lanes = new_loc.shape
    ladder = normalize_caps(caps)
    delta_bits = check_delta_bits(delta_bits)
    if delta_bits and gid_of_src is None:
        raise ValueError(
            "delta-encoded sparse_rows_exchange_min needs gid_of_src(ids, "
            "src) — the receiver decodes LOCAL ids and must map them per "
            "sender"
        )
    K, W = len(ladder), len(delta_bits)
    any_row = jnp.any(new_loc < own_prev, axis=1)  # [rows_loc]

    def make_rung_ladder(dmax):
        def make_rung(cap, ri):
            def rung(_):
                (ids,) = jnp.nonzero(any_row, size=cap, fill_value=rows_loc)
                ok = ids < rows_loc
                vals = jnp.where(
                    ok[:, None], new_loc[jnp.where(ok, ids, 0)], ident
                )
                ag_vals = lax.all_gather(vals, axis_name).reshape(-1, lanes)

                def plain(_):
                    gids = jnp.where(ok, gid_of(ids), out_rows)
                    ag_ids = lax.all_gather(gids, axis_name).reshape(-1)
                    return ag_ids, jnp.int32(ri * (W + 1) + W)

                step = plain
                for e in range(W - 1, -1, -1):
                    def enc(_, bits=delta_bits[e], e=e):
                        words = delta_encode_ids(ids[None, :], rows_loc, bits)[0]
                        ag_w = lax.all_gather(words, axis_name)  # [p, dw]
                        dec, valid = delta_decode_ids(ag_w, cap, bits)
                        src = jnp.arange(ag_w.shape[0], dtype=jnp.int32)[:, None]
                        okd = valid & (dec < rows_loc)
                        gids = jnp.where(okd, gid_of_src(dec, src), out_rows)
                        return gids.reshape(-1), jnp.int32(ri * (W + 1) + e)

                    step = partial(
                        lax.cond, dmax <= (1 << delta_bits[e]) - 1, enc, step
                    )
                ag_ids, br = step(None)
                table = prev_full.at[ag_ids].min(ag_vals, mode="drop")
                return table, br

            return rung

        return make_rung

    def measured(_):
        cnt = jnp.sum(any_row.astype(jnp.int32))
        if not delta_bits:
            biggest, dmax = lax.pmax(cnt, axis_name), None
        else:
            mx = lax.pmax(
                jnp.stack([cnt, max_id_gap(any_row[None, :])]), axis_name
            )
            biggest, dmax = mx[0], mx[1]

        def dense_leaf(_):
            return dense_fn(), jnp.int32(K * (W + 1))

        table, br = cap_ladder_select(
            biggest, ladder, make_rung_ladder(dmax), dense_leaf
        )
        return table, br, biggest

    if not predict:
        return measured(None)
    if prev_biggest is None or growing is None:
        raise ValueError(
            "predictive sparse_rows_exchange_min needs the mesh-uniform "
            "prev_biggest and growing carries"
        )

    def predicted(_):
        return dense_fn(), jnp.int32(K * (W + 1) + 1), prev_biggest

    pred = (prev_biggest > ladder[-1]) & growing
    return lax.cond(pred, predicted, measured, None)
