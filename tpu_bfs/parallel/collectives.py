"""Collective primitives for the frontier exchange.

The reference's exchange layer is `cudaMemcpyPeer` between per-destination
frontier buckets intra-node (bfs.cu:604-606) and CUDA-aware `MPI_Sendrecv` +
`MPI_Allreduce` inter-node (bfs_mpi.cu:607-621). On TPU both collapse into one
primitive: a reduce-scatter of each chip's full-size contribution buffer over
the mesh axis — XLA routes it over ICI within a slice and DCN across slices,
so one code path replaces the reference's two forked files.

Two implementations, selectable and cross-checked in tests:

- ``ring``: P-1 `lax.ppermute` hops, each combining one vloc-sized chunk —
  the classic bandwidth-optimal ring reduce-scatter, expressed manually
  because XLA's built-in reduce-scatter (psum_scatter) only sums, and the
  frontier combine is OR / parent combine is MIN.
- ``allreduce``: whole-buffer `lax.psum`/`pmin` + local slice. Simpler,
  ~2x the bytes on the wire.
- ``sparse`` (`sparse_exchange_or`): two-phase queue-style exchange — the
  TPU form of the reference's per-destination frontier buckets. Moves only
  actual frontier ids when every bucket fits a static cap; falls back to
  the dense ring bitmap level-by-level otherwise.

Wire format (ISSUE 5): every boolean exchange additionally has a
``wire_pack`` form that ships uint32 words, 32 vertices per word
(:func:`pack_bits` / :func:`unpack_bits`), instead of the unpacked
dtypes — pred chunks on the ring (ONE byte per vertex per hop) and s32
on the allreduce path (FOUR bytes per vertex). Packing is pure compute:
the packed programs emit the same collective instruction count as the
unpacked ones, moving 1/8 (ring) and 1/32 (allreduce operand) the bytes
— proven from the compiled HLO by utils/wirecheck.check_packed_exchange.
The sparse exchange's per-level sparse-ids/dense decision (the Buluç &
Madduri format flip, arXiv:1104.4518) is the shared
:func:`cap_ladder_select`; under ``wire_pack`` its dense fallback is the
packed ring and the cap ladder is recalibrated against the packed dense
cost (``default_sparse_caps``).
"""

from __future__ import annotations

from functools import partial, reduce as _reduce

import jax.numpy as jnp
import numpy as np
from jax import lax


def _chunk(x_full, c, size):
    return lax.dynamic_slice_in_dim(x_full, c * size, size)


def packed_words(n: int) -> int:
    """uint32 words needed to carry ``n`` booleans (32 vertices/word)."""
    return -(-n // 32)


def pack_bits(x):
    """Pack a boolean array's LAST axis into uint32 words, 32 vertices per
    word (vertex ``32*j + i`` -> bit ``i`` of word ``j``).

    Tail semantics: when the axis length ``n`` is not a multiple of 32 the
    final word's top ``32*ceil(n/32) - n`` bits are ZERO — the identity of
    bitwise_or — so packed buffers from different chips combine with word
    OR exactly as the bools would, and ``unpack_bits(.., n)`` recovers the
    mask without a tail mask. The padded bits are disjoint per word, so
    the packing sum cannot carry."""
    n = x.shape[-1]
    pad = packed_words(n) * 32 - n
    xb = x.astype(jnp.uint32)
    if pad:
        xb = jnp.concatenate(
            [xb, jnp.zeros(x.shape[:-1] + (pad,), jnp.uint32)], axis=-1
        )
    xb = xb.reshape(x.shape[:-1] + (packed_words(n), 32))
    return jnp.sum(
        xb << jnp.arange(32, dtype=jnp.uint32), axis=-1, dtype=jnp.uint32
    )


def unpack_bits(words, n: int):
    """Inverse of :func:`pack_bits`: the last axis of uint32 words back to
    ``n`` booleans (tail-padding bits are dropped)."""
    nw = words.shape[-1]
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    return bits.reshape(words.shape[:-1] + (nw * 32,))[..., :n] != 0


def _packed_reduce_scatter_or(x_full, axis_name: str, num_devices: int, impl: str):
    """Bit-packed OR-reduce-scatter: the uint32 wire format of both dense
    exchange impls.

    ``ring``: pack each destination chunk to ``ceil(n/32)`` words and run
    the same P-1-hop ring with ``bitwise_or`` as the word combine — 1/8
    the bytes of the pred ring, hop for hop. ``allreduce``: `lax.psum`
    cannot OR (word sums carry across bit positions), and max on words is
    not OR either — but the allreduce path only ever kept its own chunk of
    the psum, i.e. it IS a reduce-scatter; so the packed form is ONE
    `all_to_all` of the per-destination word chunks plus a local OR fold.
    Same collective instruction count (one), 1/32 the collective operand
    bytes of the s32 psum — and it sheds the psum's all-gather half on
    top, so the modeled wire bytes equal the packed ring's
    (``dense_or_wire_bytes``)."""
    p = num_devices
    if p == 1:
        return x_full
    n = x_full.shape[0] // p
    words = pack_bits(x_full.reshape(p, n))  # [p, nw], per-chunk packed
    if impl == "ring":
        out = ring_reduce_scatter(
            words.reshape(-1), axis_name, p, jnp.bitwise_or
        )
    else:
        recv = lax.all_to_all(words, axis_name, 0, 0, tiled=True)  # [p, nw]
        out = _reduce(jnp.bitwise_or, [recv[j] for j in range(p)])
    return unpack_bits(out, n)


def ring_reduce_scatter(x_full, axis_name: str, num_devices: int, op):
    """Reduce-scatter ``x_full`` ([P*n] per chip) down to this chip's [n]
    chunk, combining with ``op`` around a ring of `ppermute`s.

    Invariant: after s combine steps, chip i holds the partial reduction of
    chunk (i - 1 - s) mod P over chips (i-s..i); after P-1 steps that is the
    full reduction of chunk i.
    """
    p = num_devices
    if p == 1:
        return x_full
    n = x_full.shape[0] // p
    i = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p) for j in range(p)]
    acc = _chunk(x_full, (i - 1) % p, n)

    def step(s, acc):
        acc = lax.ppermute(acc, axis_name, perm)
        return op(acc, _chunk(x_full, (i - 1 - s) % p, n))

    return lax.fori_loop(1, p, step, acc, unroll=True)


def _check_impl(impl: str) -> None:
    # Loud rejection: an unknown impl (typo, or 'sparse' reaching an engine
    # that only does dense reduce-scatter) must not silently run allreduce.
    if impl not in ("ring", "allreduce"):
        raise ValueError(
            f"unknown reduce-scatter impl {impl!r}; have 'ring', 'allreduce' "
            "(the queue-style exchange is sparse_exchange_or, wired only "
            "through engines that accept exchange='sparse')"
        )


def reduce_scatter_or(
    x_full, axis_name: str, num_devices: int, *, impl: str = "ring",
    wire_pack: bool = False,
):
    """OR-reduce-scatter of a boolean contribution buffer (frontier exchange).

    Wire dtypes, pinned to the compiled HLO by tests/test_wirecheck.py:
    ``ring`` ships each chunk as PRED — one byte per vertex per hop;
    ``allreduce`` ships the whole buffer as S32 — four bytes per vertex.
    ``wire_pack=True`` ships uint32 words instead, 32 vertices per word
    (see :func:`_packed_reduce_scatter_or`)."""
    _check_impl(impl)
    if wire_pack:
        return _packed_reduce_scatter_or(x_full, axis_name, num_devices, impl)
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.logical_or)
    n = x_full.shape[0] // num_devices
    summed = lax.psum(x_full.astype(jnp.int32), axis_name)
    return _chunk(summed, lax.axis_index(axis_name), n) > 0


def reduce_scatter_min(x_full, axis_name: str, num_devices: int, *, impl: str = "ring"):
    """MIN-reduce-scatter of an int32 contribution buffer (parent merge —
    the analog of the reference's elementwise min result merge, bfs.cu:426-438)."""
    _check_impl(impl)
    if impl == "ring":
        return ring_reduce_scatter(x_full, axis_name, num_devices, jnp.minimum)
    n = x_full.shape[0] // num_devices
    m = lax.pmin(x_full, axis_name)
    return _chunk(m, lax.axis_index(axis_name), n)


def dense_or_wire_bytes(
    p: int, n: int, impl: str, *, wire_pack: bool = False
) -> float:
    """Off-chip bytes one chip moves per level for the dense bitmap exchange.

    Dtypes per branch (each pinned to the compiled program by
    tests/test_wirecheck.py::test_packed_exchange_proof): ``ring`` sends
    P-1 chunks of n PRED elements — one BYTE per vertex per hop, not one
    bit; ``allreduce`` psums an S32 [P*n] buffer — four bytes per vertex,
    2*(P-1)*n int32 per chip at bandwidth-optimal allreduce cost. With
    ``wire_pack`` both impls ship uint32 words, ceil(n/32) per chunk: the
    ring as P-1 word-chunk hops, the allreduce path as one all_to_all
    that keeps the self chunk local — (P-1)*4*ceil(n/32) either way.

    The per-level termination psum (4 B scalar) is outside this model's
    scope by convention (see utils/wirecheck.py); only the SPARSE models
    carry a flat +4, for the phase-1 pmax scalar that exists only on that
    path."""
    if p == 1:
        return 0.0
    if wire_pack:
        return float((p - 1) * 4 * packed_words(n))
    return float(2 * (p - 1) * n * 4 if impl == "allreduce" else (p - 1) * n)


def dense_2d_wire_bytes(
    rows: int, cols: int, w: int, impl: str, *, wire_pack: bool = False
) -> float:
    """Off-chip bytes one chip moves per level in the 2D engine's level
    loop: the column all-gather over the 'r' axis (ring: each chip sends
    its [w] pred slice rows-1 times; packed: its ceil(w/32) uint32 words)
    plus the row reduce-scatter over 'c' (same shapes as the 1D dense
    exchange, dense_or_wire_bytes). Modeled, like every wire-byte figure
    here."""
    if rows > 1:
        ag = float((rows - 1) * 4 * packed_words(w)) if wire_pack else float(
            (rows - 1) * w
        )
    else:
        ag = 0.0
    return ag + dense_or_wire_bytes(cols, w, impl, wire_pack=wire_pack)


def default_sparse_caps(vloc: int, *, wire_pack: bool = False) -> tuple[int, ...]:
    """Two-tier cap ladder: a tight cap for trickle levels (BFS start/tail,
    high-diameter graphs) and a wide one that still undercuts the dense
    bitmap's wire bytes by ~2x (ids cost 4 bytes each).

    Against the PACKED dense bitmap (vloc/8 bytes on the wire instead of
    vloc) the break-even density falls 8x: ids only win below vloc/32
    entries, so the packed ladder is the unpacked one shifted three
    octaves down — wide rung vloc/64 (the same ~2x undercut of the packed
    dense cost), tight rung vloc/512."""
    if wire_pack:
        return tuple(sorted({max(16, vloc // 512), max(16, vloc // 64)}))
    return tuple(sorted({max(16, vloc // 64), max(16, vloc // 8)}))


def cap_ladder_select(biggest, caps: tuple[int, ...], make_sparse, dense_path):
    """The level-adaptive exchange selector shared by every queue-style
    exchange (``sparse_exchange_or``, ``sparse_rows_gather``): one
    mesh-uniform population scalar (a pmax already paid by phase 1) picks,
    level by level, the smallest rung of the ascending ``caps`` ladder
    that covers every chip — or ``dense_path`` when all overflow. This is
    the Buluç & Madduri sparse-ids/dense-bitmap format flip
    (arXiv:1104.4518) as one reusable `lax.cond` ladder: the scalar is
    identical on every chip, so all chips take the same branch and the
    collectives stay matched. ``make_sparse(cap, idx)`` returns the branch
    body for one rung; branch index = rung position (ascending) or
    ``len(caps)`` for dense."""
    ladder = sorted(caps)
    step = dense_path
    for idx in range(len(ladder) - 1, -1, -1):
        step = partial(
            lax.cond, biggest <= ladder[idx], make_sparse(ladder[idx], idx), step
        )
    return step(None)


def sparse_exchange_or(
    x_full, axis_name: str, num_devices: int, *, caps: tuple[int, ...],
    wire_pack: bool = False,
):
    """Two-phase sparse (queue-style) frontier exchange.

    The TPU-native form of the reference's per-destination frontier buckets:
    `queueBfs` appends claimed vertices into per-destination-device buckets
    (bfs.cu:148-150), the driver peer-copies `nextQueueSize[j][i]` entries
    per pair (bfs.cu:604-606), and the MPI fork discovers variable receive
    sizes with `MPI_Sendrecv` + `MPI_Get_count` (bfs_mpi.cu:615-617). XLA has
    no variable-size messages (SURVEY.md §7.4), so sizes go first:

    - phase 1: `pmax` of the largest per-destination chunk popcount — one
      scalar — picks, level by level, the smallest cap in the static
      ascending ``caps`` ladder that covers every bucket;
    - phase 2a (some cap fits): compact each destination chunk's set bits
      into a static ``[P, cap]`` id buffer (cumsum compaction — the
      reference's dead scan-BFS queue generation, bfs.cu:706-781, as one
      XLA program), `all_to_all` it, and scatter-OR the received ids into
      the local chunk;
    - phase 2b (every cap overflows): dense ring bitmap reduce-scatter —
      on heavy mid-BFS levels of power-law graphs the bitmap IS the compact
      encoding.

    The per-level branch decision is the shared :func:`cap_ladder_select`
    (one mesh-uniform pmax scalar, every chip takes the same branch, so
    the collectives stay matched). Returns ``(hit [n] bool, branch int32)``
    — ``branch`` is the index of the cap that ran (ascending ladder order)
    or ``len(caps)`` for the dense fallback; callers accumulate exact
    int32 per-branch level counts and convert to wire bytes on the host
    (``sparse_wire_bytes_per_level``), so the traffic accounting never
    loses small sparse levels to float rounding.

    ``wire_pack=True`` swaps the dense fallback for the bit-packed ring
    (uint32 words, 1/8 the bytes); pair it with
    ``default_sparse_caps(vloc, wire_pack=True)`` so the ladder is
    calibrated against the packed dense cost (ids only win below vloc/32
    entries then).
    """
    p = num_devices
    n = x_full.shape[0] // p
    ladder = sorted(caps)
    if p == 1:
        return x_full, jnp.int32(len(ladder))
    i = lax.axis_index(axis_name)
    chunks = x_full.reshape(p, n)
    # The self-destination bucket never crosses the wire: it ORs in locally
    # below and is excluded from cap selection, so partition-aligned frontier
    # growth (community/grid graphs expanding within one chip's range) stays
    # on the cheap sparse path instead of tripping the dense fallback.
    self_row = jnp.arange(p, dtype=jnp.int32)[:, None] == i  # [p, 1]
    remote = chunks & ~self_row
    counts = jnp.sum(remote.astype(jnp.int32), axis=1)
    biggest = lax.pmax(jnp.max(counts), axis_name)
    rows = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[:, None], (p, n))
    local_ids = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (p, n))

    def make_sparse(cap, idx):
        def sparse_path(_):
            pos = jnp.cumsum(remote.astype(jnp.int32), axis=1)
            slot = jnp.where(remote, pos - 1, cap)  # unset/self -> dropped
            buf = jnp.full((p, cap), n, jnp.int32)  # n = "no entry" sentinel
            buf = buf.at[rows, slot].set(local_ids, mode="drop")
            recv = lax.all_to_all(buf, axis_name, 0, 0, tiled=True)  # [p, cap]
            hit = (
                jnp.zeros((n,), jnp.bool_)
                .at[recv.reshape(-1)]
                .set(True, mode="drop")
            )
            return hit | jnp.take(chunks, i, axis=0), jnp.int32(idx)

        return sparse_path

    def dense_path(_):
        if wire_pack:
            hit = _packed_reduce_scatter_or(x_full, axis_name, p, "ring")
        else:
            hit = ring_reduce_scatter(x_full, axis_name, p, jnp.logical_or)
        return hit, jnp.int32(len(ladder))

    return cap_ladder_select(biggest, caps, make_sparse, dense_path)


def merge_exchange_counts(prev, counts, resumed_level: int):
    """Accumulate per-branch exchange level counts across the chunks of one
    checkpointed traversal. The consistency test is ``prev.sum() ==
    resumed_level`` — the previous counters cover exactly levels
    [0, resumed_level) iff they belong to this chain. Callers gate ``prev``
    through :func:`chained_prev_counts` first, which keys the chain on the
    checkpoint's identity nonce, so counters left by an UNRELATED traversal
    can no longer merge by level-count coincidence; chains whose earlier
    chunks ran in another process simply restart the count (covering the
    levels run here). Shared by every engine with exchange accounting."""
    counts = np.asarray(counts)
    if resumed_level > 0 and prev is not None and prev.sum() == resumed_level:
        return counts + prev
    return counts


def chained_prev_counts(prev, resumed_level: int, prev_nonce, nonce):
    """Identity gate for chunked-traversal exchange accounting.

    The previous counters belong to the chain being resumed only if the
    engine last recorded under the SAME chain nonce (stamped into the
    checkpoint at start(), utils/checkpoint.py). A None nonce (old
    checkpoint format, or a fresh non-checkpointed run) never chains —
    the count restarts, covering the levels run here."""
    if resumed_level > 0 and (nonce is None or prev_nonce != nonce):
        return None
    return prev


def gate_and_stamp_chain(engine, resumed_level: int, chain_nonce):
    """The gate-and-stamp step every ``_record_exchange`` shares: gate the
    engine's previous counters through :func:`chained_prev_counts` and
    stamp the engine with the new chain nonce. Returns the gated ``prev``
    for the caller's merge + pricing."""
    prev = chained_prev_counts(
        engine.last_exchange_level_counts, resumed_level,
        getattr(engine, "_exchange_chain_nonce", None), chain_nonce,
    )
    engine._exchange_chain_nonce = chain_nonce
    return prev


def sparse_rows_gather(
    nxt, axis_name: str, *, caps: tuple[int, ...],
    out_rows: int, gid_of, dense_fn,
):
    """Queue-style frontier gather for the packed MS engines, shared by the
    distributed wide and hybrid engines (which differ only in their
    local-row -> global-row maps and dense slab layouts).

    When every chip's new-frontier row count fits a ``caps`` rung (decided
    by one mesh-uniform `pmax`, so every chip takes the same `lax.cond`
    branch and the collectives stay matched), the level gathers
    (global row id + lane words) pairs and rebuilds the full [out_rows, w]
    table with one drop-mode scatter; otherwise ``dense_fn()`` gathers the
    full packed slab — on dense mid-BFS levels the slab IS the compact
    encoding. ``gid_of(local_ids)`` maps this chip's local row ids to
    global table rows; it IS called on the nonzero-fill ids (= the local
    row count) too — this function masks those to the ``out_rows`` drop
    sentinel afterwards, so the map must merely not crash on them (pure
    arithmetic maps are fine).

    Returns ``(table [out_rows, w], branch int32)`` — branch indexes the
    taken rung (ascending caps order) or ``len(caps)`` for dense.
    """
    rows_loc, w = nxt.shape
    any_row = jnp.any(nxt != 0, axis=1)  # [rows_loc]
    biggest = lax.pmax(jnp.sum(any_row.astype(jnp.int32)), axis_name)

    def make_sparse(cap, idx):
        def sparse_fn(_):
            (ids,) = jnp.nonzero(any_row, size=cap, fill_value=rows_loc)
            ok = ids < rows_loc
            vals = jnp.where(ok[:, None], nxt[jnp.where(ok, ids, 0)], 0)
            gids = jnp.where(ok, gid_of(ids), out_rows)
            ag_ids = lax.all_gather(gids, axis_name).reshape(-1)
            ag_vals = lax.all_gather(vals, axis_name).reshape(-1, w)
            table = (
                jnp.zeros((out_rows, w), jnp.uint32)
                .at[ag_ids]
                .set(ag_vals, mode="drop")  # sentinel out_rows drops
            )
            return table, jnp.int32(idx)

        return sparse_fn

    def dense_branch(_):
        return dense_fn(), jnp.int32(len(caps))

    return cap_ladder_select(biggest, caps, make_sparse, dense_branch)


def default_row_gather_caps(rows_loc: int, w: int) -> tuple[int, ...]:
    """Width-aware cap ladder for sparse_rows_gather: each gathered row
    costs 4 id + 4w payload bytes vs the dense slab's 4w per row, so the
    byte win holds below rows_loc*w/(w+1) rows; two tiers as in
    default_sparse_caps (tight rung for trickle levels, half break-even)."""
    be = (rows_loc * w) // (w + 1)
    return tuple(sorted({max(1, be // 16), max(1, be // 2)}))


def dense_rows_wire_bytes(p: int, rows_loc: int, w: int) -> float:
    """Off-chip bytes one chip moves per level gathering the full packed
    [rows_loc, w] u32 slab from every peer — the packed MS engines' dense
    exchange (and the sliced rotation's per-level total, which moves the
    same slab in P-1 ring hops). The single source for this figure:
    exchange accounting, the sparse ladder's dense rung, and
    roofline.phase_bytes all price from here."""
    return 0.0 if p == 1 else float((p - 1) * rows_loc * 4 * w)


def sparse_rows_wire_bytes_per_level(
    p: int, rows_loc: int, w: int, caps: tuple[int, ...]
) -> list[float]:
    """Modeled off-chip bytes per level per sparse_rows_gather branch
    (ascending caps, then the dense slab); every branch pays the 4-byte
    pmax scalar. A 1-device mesh moves nothing."""
    if p == 1:
        return [0.0] * (len(caps) + 1)
    return [float((p - 1) * c * (4 + 4 * w) + 4) for c in sorted(caps)] + [
        dense_rows_wire_bytes(p, rows_loc, w) + 4.0
    ]


def record_row_gather_exchange(
    prev, branch_counts, resumed_level: int, *, exchange: str, p: int,
    rows_loc: int, w: int, caps: tuple[int, ...],
):  # ``prev`` is pre-gated by chained_prev_counts in the engine mixin.
    """The packed MS engines' complete exchange accounting step: merge the
    per-branch level counts into the chunked-traversal chain, then price
    them with the row-gather byte model (dense impls have the single slab
    entry). Returns (counts, bytes) for the engine to store.

    Known modeling gap: an engine whose cap-boundary truncation probe
    itself gathers a frontier (the distributed hybrid's claim-free
    ``deeper`` check) moves one extra uncounted gather on truncated runs —
    at most once per traversal, only when the plane cap was hit."""
    counts = merge_exchange_counts(prev, branch_counts, resumed_level)
    if exchange == "sparse":
        per = sparse_rows_wire_bytes_per_level(p, rows_loc, w, caps)
    else:
        per = [dense_rows_wire_bytes(p, rows_loc, w)]
    return counts, float(np.dot(counts, per))


class RowGatherExchangeAccounting:
    """Mixin for the distributed packed MS engines: the per-branch counter
    bookkeeping shared by both (record + the checkpoint-resume core
    wrapper). Hosts set ``_exchange``, ``sparse_caps``, ``w``,
    ``_gather_p``, ``_gather_rows_loc``, ``_core_from_jit``, and the two
    ``last_exchange_*`` attributes."""

    def _record_exchange(
        self, branch_counts, resumed_level: int, chain_nonce=None
    ) -> None:
        prev = gate_and_stamp_chain(self, resumed_level, chain_nonce)
        self.last_exchange_level_counts, self.last_exchange_bytes = (
            record_row_gather_exchange(
                prev, branch_counts, resumed_level,
                exchange=self._exchange, p=self._gather_p,
                rows_loc=self._gather_rows_loc, w=self.w,
                caps=self.sparse_caps,
            )
        )

    def _core_from(self, arrs, fw, vis, planes, level0, max_levels):
        fw_f, vis_f, planes_f, level, alive, bc = self._core_from_jit(
            arrs, fw, vis, planes, level0, max_levels
        )
        # advance_packed_batch stamps the resumed checkpoint's chain nonce
        # here before calling (read, not popped: the cap-boundary probe is
        # a second _core_from of the same advance and must chain too).
        self._record_exchange(
            bc, int(level0), getattr(self, "_pending_chain_nonce", None)
        )
        return fw_f, vis_f, planes_f, level, alive


def sparse_wire_bytes_per_level(
    p: int, n: int, caps: tuple[int, ...], *, wire_pack: bool = False
) -> list[float]:
    """Host-side off-chip bytes per level for each sparse_exchange_or branch,
    in branch-index order (ascending caps, then the dense ring fallback —
    the bit-packed ring under ``wire_pack``). Each branch pays 4 bytes for
    the phase-1 pmax scalar."""
    if p == 1:
        return [0.0] * (len(caps) + 1)
    return [float((p - 1) * c * 4 + 4) for c in sorted(caps)] + [
        dense_or_wire_bytes(p, n, "ring", wire_pack=wire_pack) + 4.0
    ]
