"""Version-gated shard_map entry point.

The engines target the public ``jax.shard_map`` (jax >= 0.6, keyword
``check_vma``); older jax only ships ``jax.experimental.shard_map`` with
the same semantics under the keyword ``check_rep``. One wrapper keeps
every distributed engine importable on both — without it, a jax
downgrade silently takes out the whole parallel/ layer at call time
(the shape of the round-5 seed: every distributed test dead on
``AttributeError: module 'jax' has no attribute 'shard_map'``).
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` when available, else the experimental spelling
    (``check_vma`` -> ``check_rep`` — the pre-0.6 name for the same
    replication/varying-manual-axes check)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
