"""Multi-chip distributed BFS.

The TPU-native replacement for BOTH reference drivers — single-process
multi-GPU ``runCudaQueueBfs`` (bfs.cu:542-629) and the MPI fork
(bfs_mpi.cu:549-643) — as ONE code path: a `lax.while_loop` level loop inside
`jax.shard_map` over a 1D device mesh. Per level, each chip:

  1. expands its owned frontier over its local (source-sharded) edges into a
     full-size contribution bitmap (the analog of the per-destination buckets,
     bfs.cu:148-150),
  2. reduce-scatters the bitmaps with OR over the mesh axis (replacing
     cudaMemcpyPeer, bfs.cu:604-606, and MPI_Sendrecv, bfs_mpi.cu:615),
  3. claims unvisited vertices in its owned slice (replacing the atomicMin
     claim, bfs.cu:146),
  4. psums the new-frontier popcount for global termination (replacing
     MPI_Allreduce, bfs_mpi.cu:621, and the host-side queueSize sum,
     bfs.cu:569).

No host round-trips during the traversal — the reference crosses host<->device
four times per level (SURVEY.md §3.1).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.parallel.compat import shard_map

from tpu_bfs.algorithms.bfs import BfsResult
from tpu_bfs.algorithms.frontier import (
    INT32_MAX,
    EdgeData,
    default_dopt_caps,
    expand_or,
    make_dopt_expand,
)
from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.parallel.collectives import (
    check_delta_bits,
    default_sparse_caps,
    dense_or_wire_bytes,
    gate_and_stamp_chain,
    merge_exchange_counts,
    normalize_caps,
    planned_branch_count,
    planned_branch_labels,
    planned_sparse_exchange_or,
    planned_sparse_wire_bytes_per_level,
    reduce_scatter_or,
    reduce_scatter_min,
    rows_gather_branch_labels,
    sparse_exchange_or,
    sparse_wire_bytes_per_level,
)
from tpu_bfs.obs.engine_trace import TRACE_LEVELS, assemble_dist_trace
from tpu_bfs.parallel.partition import out_csr_1d, partition_1d
from tpu_bfs.utils.timing import run_timed


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1D device mesh over the vertex-partition axis 'v'.

    Runtime-configurable, unlike the reference's compile-time DeviceNum
    (bfs.cu:19 — changing device count means recompiling)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested {num_devices} devices, only {len(devices)} available"
                )
            devices = devices[:num_devices]
    return Mesh(np.array(devices), ("v",))


def _dist_bfs_fn(
    mesh: Mesh, p: int, vloc: int, exchange: str, backend: str,
    sparse_caps: tuple[int, ...], dopt_caps: tuple[int, ...] = (),
    wire_pack: bool = False, delta_bits: tuple[int, ...] = (),
    sieve: bool = False, predict: bool = False,
):
    """Build the shard_map'd BFS level loop for a fixed mesh/partition.

    ``exchange='sparse'`` swaps the dense bitmap reduce-scatter for the
    two-phase queue-style exchange (collectives.sparse_exchange_or — the
    analog of the reference's per-destination buckets, bfs.cu:148-150).
    The loop carry counts, per exchange branch, how many levels ran it
    (exact int32 — wire bytes are reconstructed on the host, immune to the
    float rounding a byte accumulator would hit at scale).

    ``backend='dopt'`` runs the direction-optimizing expansion per chip:
    each chip independently picks the sparse top-down branch when its OWN
    frontier's local out-degree sum fits a ``dopt_caps`` rung (the branch
    is collective-free, so per-chip divergence is safe — exchange and
    termination collectives sit outside the `lax.cond`).

    ``wire_pack=True`` ships every boolean exchange bit-packed (uint32
    words, 32 vertices/word — collectives.pack_bits): the dense ring/
    allreduce paths and the sparse exchange's dense fallback; the sparse
    id rungs already move 4-byte ids. Same collective count, 1/8-1/32 the
    bytes (wirecheck.check_packed_exchange proves it from the HLO).

    ``delta_bits`` / ``sieve`` / ``predict`` (ISSUE 7, sparse exchange
    only) swap the cap ladder for the full exchange planner
    (collectives.planned_sparse_exchange_or): delta-encoded id chunks, a
    backward visited sieve, and history-predictive dense selection. The
    loop carry gains three mesh-uniform scalars for it — the previous
    measured ``biggest``, the previous frontier popcount (growth), and
    the cumulative visited total (all derived from psum/pmax outputs, so
    every chip carries identical values and the planner's branches stay
    matched).

    The carry also records two tiny per-level arrays for the engine trace
    (tpu_bfs/obs/engine_trace, ISSUE 6): the new-frontier popcount and
    the exchange-branch index of each level, in [TRACE_LEVELS] int32
    slots (levels past the window clamp into the last slot). Both reuse
    scalars the loop already computes — the termination psum and the
    ladder branch — so the recording is two dynamic-updates of 256-byte
    replicated arrays per level, collective-free."""
    planned = exchange == "sparse" and bool(delta_bits or sieve or predict)
    if planned:
        nb = planned_branch_count(sparse_caps, delta_bits)
    else:
        nb = len(sparse_caps) + 1 if exchange == "sparse" else 1
    dopt = backend == "dopt"

    def local_loop(
        src_e, dst_e, rp_e, aux, frontier, visited, dist, level0, max_levels
    ):
        # Blocks: src_e/dst_e [1, ep], rp_e [1, vp+1], vertex arrays [vloc].
        src_e = src_e[0]
        dst_e = dst_e[0]
        rp_e = rp_e[0]
        k = lax.axis_index("v")
        src_local = src_e - k * vloc  # sources are owned: always in [0, vloc)
        vp = p * vloc

        def dense_fn(frontier):
            active = frontier[src_local]
            return expand_or(
                active, dst_e, rp_e, vp, backend="scan" if dopt else backend
            )

        if dopt:
            edata = EdgeData(
                src=src_e, dst=dst_e, in_rp=rp_e,
                out_rp=aux[0][0],  # [vloc+1] CSR-by-local-src
                nbr_sm=aux[1][0],  # [ep] global padded dst, src-major
            )
            expand_local = make_dopt_expand(
                edata, dopt_caps, vert_limit=vloc, out_size=vp,
                dense_fn=dense_fn,
            )
        else:
            expand_local = dense_fn

        def cond(state):
            front_count, level = state[4], state[3]
            return (front_count > 0) & (level < max_levels)

        def body(state):
            # The planner's history scalars extend the carry ONLY when a
            # planner feature is on — the legacy programs stay carry-for-
            # carry identical (compile time and HLO unchanged).
            if planned:
                (frontier, visited, dist, level, front_count, branch_counts,
                 front_seq, branch_seq, prev_biggest, prev_count,
                 vis_total) = state
            else:
                (frontier, visited, dist, level, front_count, branch_counts,
                 front_seq, branch_seq) = state
            contrib = expand_local(frontier)
            if planned:
                hit, branch, biggest = planned_sparse_exchange_or(
                    contrib, "v", p, caps=sparse_caps, delta_bits=delta_bits,
                    sieve=sieve, visited=visited, visited_total=vis_total,
                    predict=predict, prev_biggest=prev_biggest,
                    growing=front_count >= prev_count, wire_pack=wire_pack,
                )
            elif exchange == "sparse":
                hit, branch = sparse_exchange_or(
                    contrib, "v", p, caps=sparse_caps, wire_pack=wire_pack
                )
            else:
                hit = reduce_scatter_or(
                    contrib, "v", p, impl=exchange, wire_pack=wire_pack
                )
                branch = jnp.int32(0)
            branch_counts = branch_counts + (
                jnp.arange(nb, dtype=jnp.int32) == branch
            )
            new = hit & ~visited
            dist = jnp.where(new, level + 1, dist)
            visited = visited | new
            count = lax.psum(jnp.sum(new.astype(jnp.int32)), "v")
            # Engine-trace slot for the level just EXPANDED (relative to
            # this invocation's resume point; the assembler re-offsets).
            # Frontier counts ADD so the clamp slot aggregates every
            # level past the window (frontier_total stays exact); the
            # branch index is last-write-wins there (documented in
            # engine_trace.assemble_dist_trace).
            slot = jnp.minimum(level - level0, TRACE_LEVELS - 1)
            front_seq = front_seq.at[slot].add(count)
            branch_seq = branch_seq.at[slot].set(branch)
            out = (new, visited, dist, level + 1, count, branch_counts,
                   front_seq, branch_seq)
            if planned:
                out = out + (biggest, front_count, vis_total + count)
            return out

        init_count = lax.psum(jnp.sum(frontier.astype(jnp.int32)), "v")
        init = (frontier, visited, dist, jnp.int32(level0), init_count,
                jnp.zeros(nb, jnp.int32),
                jnp.zeros(TRACE_LEVELS, jnp.int32),
                jnp.full(TRACE_LEVELS, -1, jnp.int32))
        if planned:
            # Planner history seeds: biggest unknown (-1 blocks prediction
            # until the first measured level), no previous frontier, and
            # the cumulative visited popcount (psum'd, so mesh-uniform
            # like every carried planner scalar).
            init = init + (
                jnp.int32(-1), jnp.int32(0),
                lax.psum(jnp.sum(visited.astype(jnp.int32)), "v"),
            )
        out = lax.while_loop(cond, body, init)
        (frontier, visited, dist, level, _, branch_counts, front_seq,
         branch_seq) = out[:8]
        return frontier, visited, dist, level, branch_counts, front_seq, branch_seq

    aux_specs = (P("v", None), P("v", None)) if dopt else ()
    # The carry (frontier/visited/dist, argnums 4-6) is DONATED (ISSUE
    # 13, analysis pass 5): every call site constructs it fresh —
    # _init_state and advance's device_put both materialize distinct
    # buffers per call, and the serve adapter's chunked relaunch reads
    # its snapshot BEFORE handing the carry back in — so the loop's
    # outputs alias the inputs instead of doubling the sharded vectors'
    # residency. The analyzer's transfer-guard drive copies donated args
    # per invocation (analysis/transfer.py keys on _donate_argnums).
    fn = jax.jit(
        shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(
                P("v", None),
                P("v", None),
                P("v", None),
                aux_specs,
                P("v"),
                P("v"),
                P("v"),
                P(),
                P(),
            ),
            out_specs=(P("v"), P("v"), P("v"), P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(4, 5, 6),
    )
    fn._donate_argnums = (4, 5, 6)
    return fn


def _dist_parents_fn(mesh: Mesh, p: int, vloc: int, exchange: str):
    """Post-loop deterministic parent extraction, distributed.

    Each chip all-gathers the final (padded-id) distance vector once — the
    analog of the reference's result merge download (finalizeCudaBfs,
    bfs.cu:424-441) — then scatter-mins parent candidates from its local
    edges and reduce-scatter-mins back to owners."""

    def local_parents(src_e, dst_e, dist_loc):
        src_e = src_e[0]
        dst_e = dst_e[0]
        vp = p * vloc
        dist_full = lax.all_gather(dist_loc, "v", tiled=True)  # [vp]
        du = dist_full[src_e]
        ok = (du != INT32_MAX) & (du + 1 == dist_full[dst_e])
        cand = jnp.where(ok, src_e, INT32_MAX)
        contrib = (
            jnp.full((vp,), INT32_MAX, jnp.int32).at[dst_e].min(cand, mode="drop")
        )
        parent_loc = reduce_scatter_min(contrib, "v", p, impl=exchange)
        parent_loc = jnp.where(parent_loc == INT32_MAX, -1, parent_loc)
        return jnp.where(dist_loc == INT32_MAX, -1, parent_loc)

    return jax.jit(
        shard_map(
            local_parents,
            mesh=mesh,
            in_specs=(P("v", None), P("v", None), P("v")),
            out_specs=P("v"),
            check_vma=False,
        )
    )


class VertexCheckpointMixin:
    """Checkpoint/resume shared by the distributed single-source engines
    (1D vertex partition and 2D edge partition; SURVEY.md §5: the
    reference has none).

    Checkpoints hold real-id [V] arrays, portable across engines, mesh
    shapes AND partition topologies — a traversal checkpointed under the
    1D partition resumes under the 2D edge partition mid-flight (elastic
    restart; the reference's compile-time DeviceNum, bfs.cu:19, and fixed
    2-rank world, bfs_mpi.cu:615, have no analog). Engines provide
    ``part`` (to_padded/unshard/vp), ``_num_real_vertices``,
    ``_vec_sharding``, ``_package``, and ``_advance_loop(f, vis, d,
    level0, cap)`` — the engine-specific jitted loop invocation plus its
    exchange accounting, returning (frontier, visited, dist, level)."""

    def start(self, source: int):
        """Level-0 traversal state as a host checkpoint (real vertex ids)."""
        from tpu_bfs.utils.checkpoint import initial_checkpoint

        return initial_checkpoint(self._num_real_vertices, source)

    def _pad_state(self, ckpt):
        """Real-id [V] checkpoint arrays -> padded-id [vp] arrays."""
        part = self.part
        if not hasattr(self, "_pids"):  # constant for the engine's lifetime
            self._pids = part.to_padded(np.arange(self._num_real_vertices))
        pids = self._pids
        f = np.zeros(part.vp, dtype=bool)
        f[pids] = ckpt.frontier
        vis = np.zeros(part.vp, dtype=bool)
        vis[pids] = ckpt.visited
        d = np.full(part.vp, INF_DIST, dtype=np.int32)
        d[pids] = ckpt.distance
        return f, vis, d

    def advance(self, ckpt, levels: int | None = None):
        """Run at most ``levels`` more levels across the mesh from a checkpoint."""
        from tpu_bfs.utils.checkpoint import BfsCheckpoint

        part = self.part
        if len(ckpt.frontier) != self._num_real_vertices:
            raise ValueError(
                f"checkpoint has {len(ckpt.frontier)} vertices, graph has "
                f"{self._num_real_vertices}"
            )
        f0, vis0, d0 = self._pad_state(ckpt)
        put = partial(jax.device_put, device=self._vec_sharding)
        cap = ckpt.level + levels if levels is not None else part.vp
        frontier, visited, dist, level = self._advance_loop(
            put(f0), put(vis0), put(d0), ckpt.level, min(cap, part.vp),
            chain_nonce=getattr(ckpt, "nonce", None),
        )
        return BfsCheckpoint(
            source=ckpt.source,
            level=int(level),
            frontier=part.unshard(np.asarray(frontier)),
            visited=part.unshard(np.asarray(visited)),
            distance=part.unshard(np.asarray(dist)),
            nonce=getattr(ckpt, "nonce", None),  # chain identity survives chunks
        )

    def finish(self, ckpt, *, with_parents: bool = True):
        """Convert a (finished or partial) checkpoint into a BfsResult."""
        _, _, d0 = self._pad_state(ckpt)
        put = partial(jax.device_put, device=self._vec_sharding)
        return self._package(put(d0), ckpt.source, with_parents, None)


class DistBfsEngine(VertexCheckpointMixin):
    """Multi-chip BFS over a 1D vertex partition.

    Usage mirrors BfsEngine but scales over a mesh; with a 1-device mesh it
    degrades to the single-chip path (the reference instead forks a whole
    second file for multi-node, bfs_mpi.cu)."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh | None = None,
        *,
        num_devices: int | None = None,
        exchange: str = "ring",
        backend: str = "scan",
        sparse_caps: int | tuple[int, ...] | None = None,
        dopt_caps: tuple[int, ...] | None = None,
        wire_pack: bool = False,
        delta_bits: tuple[int, ...] = (),
        sieve: bool = False,
        predict: bool = False,
    ):
        if exchange not in ("ring", "allreduce", "sparse"):
            # Before the partition/device_put work, so a typo fails instantly.
            raise ValueError(
                f"unknown exchange {exchange!r}; have 'ring', 'allreduce', 'sparse'"
            )
        if (delta_bits or sieve or predict) and exchange != "sparse":
            raise ValueError(
                "delta_bits/sieve/predict reshape the SPARSE exchange "
                f"(the ISSUE 7 planner); exchange={exchange!r} has no id "
                "buffers to compress — use exchange='sparse'"
            )
        self._exchange = exchange
        #: bit-packed wire format (ISSUE 5): boolean exchanges ship uint32
        #: words, 32 vertices/word; results are bit-identical to unpacked
        #: (fuzz-pinned), only the wire encoding changes. Default OFF until
        #: chip-measured, like the pull gate.
        self.wire_pack = bool(wire_pack)
        #: ISSUE 7 exchange planner knobs (sparse exchange only; all
        #: default OFF until chip-measured, like wire_pack): delta-encoded
        #: id chunks, the backward visited sieve, and history-predictive
        #: dense selection. Results stay bit-identical to the plain sparse
        #: exchange (fuzz-pinned); only wire encoding and scalar traffic
        #: change.
        self.delta_bits = check_delta_bits(delta_bits)
        self.sieve = bool(sieve)
        self.predict = bool(predict)
        self._planned = exchange == "sparse" and bool(
            self.delta_bits or self.sieve or self.predict
        )
        self.mesh = mesh if mesh is not None else make_mesh(num_devices)
        self.p = self.mesh.devices.size
        self.graph_meta = (graph.num_input_edges, graph.undirected)
        part, src_stacked, dst_stacked, rp_stacked = partition_1d(graph, self.p)
        self.part = part
        self._degrees = graph.degrees  # host copy for TEPS accounting
        edge_sharding = NamedSharding(self.mesh, P("v", None))
        self.src = jax.device_put(src_stacked, edge_sharding)
        self.dst = jax.device_put(dst_stacked, edge_sharding)
        self.rp = jax.device_put(rp_stacked, edge_sharding)
        self._vec_sharding = NamedSharding(self.mesh, P("v"))
        self._aux = ()
        if backend == "dopt":
            # Src-major per-chip view + caps ladder for the top-down branch
            # (same rungs as BfsEngine's, scaled to the per-chip shard).
            out_rp, nbr = out_csr_1d(part, src_stacked, dst_stacked)
            self._aux = (
                jax.device_put(out_rp, edge_sharding),
                jax.device_put(nbr, edge_sharding),
            )
            if dopt_caps is None:
                dopt_caps = default_dopt_caps(part.ep_chip)
        self.dopt_caps = tuple(sorted(set(dopt_caps))) if dopt_caps else ()
        if sparse_caps is None:
            # The ladder calibrates against the dense fallback it competes
            # with AND the id encoding's per-entry cost: the packed bitmap
            # costs 1/8 (rungs three octaves lower), delta-encoded ids
            # cost min(delta_bits)/32 of plain (rungs shifted back up) —
            # collectives.default_sparse_caps.
            sparse_caps = default_sparse_caps(
                part.vloc, wire_pack=self.wire_pack,
                delta_bits=self.delta_bits,
            )
        elif isinstance(sparse_caps, int):
            sparse_caps = (sparse_caps,)
        self.sparse_caps = normalize_caps(sparse_caps)
        self._loop = _dist_bfs_fn(
            self.mesh, self.p, part.vloc, exchange, backend, self.sparse_caps,
            self.dopt_caps, self.wire_pack, self.delta_bits, self.sieve,
            self.predict,
        )
        # Parent merge is a one-shot int32 MIN reduce-scatter — queue-style
        # exchange does not apply; 'sparse' rides the ring there.
        parent_impl = "ring" if exchange == "sparse" else exchange
        self._parents = _dist_parents_fn(self.mesh, self.p, part.vloc, parent_impl)
        #: per-branch level counts of the last traversal (ascending sparse
        #: caps then dense fallback; dense impls have the single entry) and
        #: the off-chip bytes one chip moved — set by distances_padded/advance.
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None
        # Raw loop carries of the last core invocation; the per-level
        # rows assemble lazily on first last_run_trace access (property
        # below) so the device->host transfers and row building stay out
        # of run_timed's wall clock.
        self._trace_pending: tuple | None = None
        self._trace_cache: list[dict] | None = None
        self._direction = "dopt" if backend == "dopt" else "push"
        self._warmed = False

    def wire_bytes_per_level(self) -> list[float]:
        """Modeled off-chip bytes one chip moves per level, per exchange
        branch (ascending sparse caps then the dense fallback; the dense
        impls have the single entry; the ISSUE 7 planner's full layout
        when delta/sieve/predict are on — ``exchange_branch_labels()``
        names the entries) — the price list behind
        ``last_exchange_bytes``, and the feed for the bench verdict's
        ``wire_bytes_per_level`` key (TPU_BFS_BENCH_MODE=dist) and the
        BENCHMARKS.md "Exchange bytes" table."""
        if self._planned:
            return planned_sparse_wire_bytes_per_level(
                self.p, self.part.vloc, self.sparse_caps, self.delta_bits,
                wire_pack=self.wire_pack,
            )
        if self._exchange == "sparse":
            return sparse_wire_bytes_per_level(
                self.p, self.part.vloc, self.sparse_caps,
                wire_pack=self.wire_pack,
            )
        return [
            dense_or_wire_bytes(
                self.p, self.part.vloc, self._exchange,
                wire_pack=self.wire_pack,
            )
        ]

    def exchange_branch_labels(self) -> list[str] | None:
        """Branch labels index-aligned with ``wire_bytes_per_level()`` /
        ``last_exchange_level_counts`` — the engine-trace hook
        (obs/engine_trace reads this when present); None for the dense
        impls (one branch, labeled by the impl itself)."""
        if self._planned:
            return planned_branch_labels(self.sparse_caps, self.delta_bits)
        if self._exchange == "sparse":
            return rows_gather_branch_labels(self.sparse_caps, ())
        return None

    def _record_exchange(
        self, branch_counts, *, resumed_level: int = 0, chain_nonce=None
    ) -> None:
        prev = gate_and_stamp_chain(self, resumed_level, chain_nonce)
        counts = merge_exchange_counts(prev, branch_counts, resumed_level)
        self.last_exchange_level_counts = counts
        self.last_exchange_bytes = float(np.dot(counts, self.wire_bytes_per_level()))

    @property
    def last_run_trace(self) -> list[dict] | None:
        """Per-level rows of the last core invocation (frontier count,
        direction, exchange choice, modeled wire bytes) — the unified
        engine-trace contract (tpu_bfs/obs/engine_trace, ISSUE 6).
        Assembled lazily from the stashed loop carries so the timed path
        pays nothing for the trace."""
        pend = self._trace_pending
        if pend is not None:
            level, front_seq, branch_seq, level0 = pend
            self._trace_pending = None
            self._trace_cache = assemble_dist_trace(
                self, int(level) - level0, front_seq, branch_seq,
                direction=self._direction, level0=level0,
            )
        return self._trace_cache

    @last_run_trace.setter
    def last_run_trace(self, rows: list[dict] | None) -> None:
        # The roofline walk overwrites the trace with its own (richer,
        # exact-frontier) rows — honor direct assignment.
        self._trace_pending = None
        self._trace_cache = rows

    def _init_state(self, source: int):
        part = self.part
        pid = int(part.to_padded(source))
        frontier0 = np.zeros(part.vp, dtype=bool)
        frontier0[pid] = True
        dist0 = np.full(part.vp, INF_DIST, dtype=np.int32)
        dist0[pid] = 0
        put = partial(jax.device_put, device=self._vec_sharding)
        return put(frontier0), put(frontier0.copy()), put(dist0)

    def analysis_programs(self):
        """Jit entry points + device-resident example args for the static
        analyzer (tpu_bfs/analysis): the level loop whose branch
        uniformity the taint pass proves, and the parent merge. Scalars
        are pre-placed replicated so the transfer-guard drive sees only
        what a real run transfers."""
        f0, vis0, d0 = self._init_state(0)
        rep = NamedSharding(self.mesh, P())
        l0, ml = (
            jax.device_put(jnp.int32(0), rep),
            jax.device_put(jnp.int32(64), rep),
        )
        return [
            ("level_loop", self._loop,
             (self.src, self.dst, self.rp, self._aux, f0, vis0, d0, l0, ml)),
            ("parents", self._parents, (self.src, self.dst, d0)),
        ]

    def distances_padded(self, source: int, *, max_levels: int | None = None):
        """Device (padded-id, sharded) distance vector + level counter."""
        frontier0, visited0, dist0 = self._init_state(source)
        ml = jnp.int32(max_levels if max_levels is not None else self.part.vp)
        _, _, dist, level, branch_counts, front_seq, branch_seq = self._loop(
            self.src, self.dst, self.rp, self._aux, frontier0, visited0, dist0,
            jnp.int32(0), ml,
        )
        self._record_exchange(branch_counts)
        self._trace_pending = (level, front_seq, branch_seq, 0)
        self._trace_cache = None
        return dist, level

    # --- checkpoint/resume: VertexCheckpointMixin provides
    # start/advance/finish over this hook. ---

    @property
    def _num_real_vertices(self) -> int:
        return self.part.num_vertices

    def _advance_loop(self, f0, vis0, d0, level0: int, cap: int, *, chain_nonce=None):
        frontier, visited, dist, level, branch_counts, front_seq, branch_seq = (
            self._loop(
                self.src, self.dst, self.rp, self._aux, f0, vis0, d0,
                jnp.int32(level0), jnp.int32(cap),
            )
        )
        self._record_exchange(
            branch_counts, resumed_level=level0, chain_nonce=chain_nonce
        )
        self._trace_pending = (level, front_seq, branch_seq, level0)
        self._trace_cache = None
        return frontier, visited, dist, level

    def run(
        self,
        source: int,
        *,
        max_levels: int | None = None,
        with_parents: bool = True,
        time_it: bool = False,
    ) -> BfsResult:
        part = self.part
        if not (0 <= source < part.num_vertices):
            raise ValueError(f"source {source} out of range")
        elapsed = None
        if time_it:
            (dist_dev, _), elapsed = run_timed(
                lambda: self.distances_padded(source, max_levels=max_levels),
                warm=not self._warmed,
            )
            self._warmed = True
        else:
            dist_dev, _ = self.distances_padded(source, max_levels=max_levels)
        return self._package(dist_dev, source, with_parents, elapsed)

    def _package(self, dist_dev, source, with_parents, elapsed) -> BfsResult:
        part = self.part
        parent = None
        if with_parents:
            parent_dev = self._parents(self.src, self.dst, dist_dev)
            parent_pad = part.unshard(np.asarray(parent_dev))
            # Padded ids -> real ids; -1 passes through; source -> itself.
            parent = np.where(
                parent_pad >= 0, part.from_padded(np.abs(parent_pad)), -1
            ).astype(np.int32)
            parent[source] = source

        dist = part.unshard(np.asarray(dist_dev))
        reached_mask = dist != INF_DIST
        reached = int(reached_mask.sum())
        num_levels = int(dist[reached_mask].max()) if reached else 0
        m_in, undirected = self.graph_meta
        # TEPS numerator from reached degrees: sum of degrees over reached
        # vertices counts each traversed slot once from its source side.
        slots = int(self._degrees[reached_mask].sum()) if reached else 0
        edges = slots // 2 if undirected else slots
        return BfsResult(
            source=source,
            distance=dist,
            parent=parent,
            num_levels=num_levels,
            reached=reached,
            edges_traversed=edges,
            elapsed_s=elapsed,
        )
