"""Multi-chip BFS over a 2D (R x C) edge partition.

The scale-out path the reference lacks (its only distribution mode replicates
the full CSR per device and partitions ownership 1D, bfs.cu:29-32, 346-351;
SURVEY.md §2c flags 2D partitioning as the gap to close for Graph500 scales).
Level structure (see partition2d):

    col all-gather (ICI, 'r' axis)  ->  local expand  ->
    row OR-reduce-scatter (ICI, 'c' axis)  ->  claim owned slice  ->
    psum termination over the whole mesh

Both collectives move O(vp/mesh-dimension) bits per chip instead of the 1D
exchange's O(vp).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.parallel.compat import shard_map

from tpu_bfs.algorithms.bfs import BfsResult
from tpu_bfs.algorithms.frontier import (
    INT32_MAX,
    EdgeData,
    default_dopt_caps,
    expand_or,
    make_dopt_expand,
)
from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.parallel.collectives import (
    dense_2d_wire_bytes,
    gate_and_stamp_chain,
    merge_exchange_counts,
    pack_bits,
    reduce_scatter_min,
    reduce_scatter_or,
    unpack_bits,
)
from tpu_bfs.obs.engine_trace import TRACE_LEVELS, assemble_dist_trace
from tpu_bfs.parallel.dist_bfs import VertexCheckpointMixin
from tpu_bfs.parallel.partition2d import out_csr_2d, partition_2d
from tpu_bfs.utils.timing import run_timed


def make_mesh_2d(rows: int, cols: int, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if rows * cols > len(devices):
        raise ValueError(f"mesh {rows}x{cols} needs {rows * cols} devices")
    arr = np.array(devices[: rows * cols]).reshape(rows, cols)
    return Mesh(arr, ("r", "c"))


def _dist2d_bfs_fn(mesh: Mesh, rows: int, cols: int, w: int, exchange: str,
                   backend: str, dopt_caps: tuple[int, ...] = (),
                   wire_pack: bool = False):
    """2D level loop. ``backend='dopt'`` = the BASELINE scale-26 config
    ("2D edge partition + direction-optimizing BFS"): after the column
    all-gather, each chip independently runs the sparse top-down branch
    when its column frontier's local out-degree sum fits a ``dopt_caps``
    rung — the branch is collective-free (both collectives sit outside the
    `lax.cond`), so per-chip divergence is safe.

    ``wire_pack=True`` bit-packs BOTH per-level collectives (ISSUE 5): the
    column all-gather over 'r' ships each chip's [w] slice as ceil(w/32)
    uint32 words, and the row reduce-scatter over 'c' runs the packed
    dense exchange — same collective count, 1/8+ the bytes."""
    row_block = cols * w
    col_block = rows * w
    dopt = backend == "dopt"

    def local_loop(
        src_g, dst_l, rp_l, aux, frontier, visited, dist, level0, max_levels
    ):
        src_g = src_g[0, 0]
        dst_l = dst_l[0, 0]
        rp_l = rp_l[0, 0]

        def dense_fn(col_frontier):
            active = col_frontier[src_g]
            return expand_or(
                active, dst_l, rp_l, row_block,
                backend="scan" if dopt else backend,
            )

        if dopt:
            edata = EdgeData(
                src=src_g, dst=dst_l, in_rp=rp_l,
                out_rp=aux[0][0, 0],  # [R*w+1] CSR by col-gather-local src
                nbr_sm=aux[1][0, 0],  # [ep2] row-block-local dst, src-major
            )
            expand_local = make_dopt_expand(
                edata, dopt_caps, vert_limit=col_block, out_size=row_block,
                dense_fn=dense_fn,
            )
        else:
            expand_local = dense_fn

        def cond(state):
            _, _, _, level, count, _ = state
            return (count > 0) & (level < max_levels)

        def body(state):
            frontier, visited, dist, level, _, front_seq = state
            # Column exchange: assemble this mesh column's frontier slices.
            if wire_pack and rows > 1:
                # Packed wire: gather uint32 words (one per 32 vertices of
                # each chip's slice), unpack per chunk after landing.
                gw = lax.all_gather(pack_bits(frontier), "r", tiled=True)
                col_frontier = unpack_bits(gw.reshape(rows, -1), w).reshape(
                    rows * w
                )
            else:
                col_frontier = lax.all_gather(frontier, "r", tiled=True)  # [R*w]
            contrib = expand_local(col_frontier)
            # Row exchange: combine row-block contributions, keep own chunk.
            hit = reduce_scatter_or(
                contrib, "c", cols, impl=exchange, wire_pack=wire_pack
            )
            new = hit & ~visited
            dist = jnp.where(new, level + 1, dist)
            visited = visited | new
            count = lax.psum(jnp.sum(new.astype(jnp.int32)), ("r", "c"))
            # Engine-trace slot (tpu_bfs/obs/engine_trace): the 2D loop
            # has no exchange ladder, so only the frontier popcount —
            # already paid by the termination psum — is recorded. ADD,
            # not set: the clamp slot aggregates levels past the window.
            slot = jnp.minimum(level - level0, TRACE_LEVELS - 1)
            front_seq = front_seq.at[slot].add(count)
            return new, visited, dist, level + 1, count, front_seq

        init = lax.psum(jnp.sum(frontier.astype(jnp.int32)), ("r", "c"))
        frontier, visited, dist, level, _, front_seq = lax.while_loop(
            cond, body,
            (frontier, visited, dist, jnp.int32(level0), init,
             jnp.zeros(TRACE_LEVELS, jnp.int32)),
        )
        return frontier, visited, dist, level, front_seq

    aux_specs = (P("r", "c", None), P("r", "c", None)) if dopt else ()
    return jax.jit(
        shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(
                P("r", "c", None),
                P("r", "c", None),
                P("r", "c", None),
                aux_specs,
                P(("r", "c")),
                P(("r", "c")),
                P(("r", "c")),
                P(),
                P(),
            ),
            out_specs=(P(("r", "c")), P(("r", "c")), P(("r", "c")), P(), P()),
            check_vma=False,
        )
    )


def _dist2d_parents_fn(mesh: Mesh, rows: int, cols: int, w: int, exchange: str):
    row_block = cols * w

    def local_parents(src_g, dst_l, dist_loc):
        src_g = src_g[0, 0]
        dst_l = dst_l[0, 0]
        i = lax.axis_index("r")
        j = lax.axis_index("c")
        dist_full = lax.all_gather(dist_loc, ("r", "c"), tiled=True)  # [vp]
        # Reconstruct global padded src ids from column-gather-local indices.
        src_global = ((src_g // w) * cols + j) * w + src_g % w
        dst_global = i * row_block + dst_l
        du = dist_full[src_global]
        ok = (du != INT32_MAX) & (du + 1 == dist_full[dst_global])
        cand = jnp.where(ok, src_global, INT32_MAX)
        contrib = (
            jnp.full((row_block,), INT32_MAX, jnp.int32)
            .at[dst_l]
            .min(cand, mode="drop")
        )
        parent_loc = reduce_scatter_min(contrib, "c", cols, impl=exchange)
        parent_loc = jnp.where(parent_loc == INT32_MAX, -1, parent_loc)
        return jnp.where(dist_loc == INT32_MAX, -1, parent_loc)

    return jax.jit(
        shard_map(
            local_parents,
            mesh=mesh,
            in_specs=(P("r", "c", None), P("r", "c", None), P(("r", "c"))),
            out_specs=P(("r", "c")),
            check_vma=False,
        )
    )


class Dist2DBfsEngine(VertexCheckpointMixin):
    """BFS over an R x C mesh with 2D edge partitioning.

    API mirrors DistBfsEngine; use for meshes large enough that the 1D
    exchange's O(vp) per-chip traffic dominates."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh | None = None,
        *,
        rows: int | None = None,
        cols: int | None = None,
        exchange: str = "ring",
        backend: str = "scan",
        dopt_caps: tuple[int, ...] | None = None,
        wire_pack: bool = False,
    ):
        if mesh is None:
            mesh = make_mesh_2d(rows or 1, cols or 1)
        if tuple(mesh.axis_names) != ("r", "c"):
            raise ValueError("2D engine needs a mesh with axes ('r', 'c')")
        if exchange not in ("ring", "allreduce"):
            # Reject loudly at build time (not deep inside shard_map tracing):
            # in particular 'sparse' is a 1D-engine feature — the 2D row/col
            # collectives already move O(vp/dim) bits per chip.
            raise ValueError(
                f"unknown exchange {exchange!r} for the 2D engine; "
                "have 'ring', 'allreduce'"
            )
        self.mesh = mesh
        self.rows, self.cols = (
            mesh.devices.shape[0],
            mesh.devices.shape[1],
        )
        self.graph_meta = (graph.num_input_edges, graph.undirected)
        self._degrees = graph.degrees
        part, src_gidx, dst_stacked, rp_stacked = partition_2d(
            graph, self.rows, self.cols
        )
        self.part = part
        edge_sharding = NamedSharding(mesh, P("r", "c", None))
        self.src_g = jax.device_put(src_gidx, edge_sharding)
        self.dst_l = jax.device_put(dst_stacked, edge_sharding)
        self.rp = jax.device_put(rp_stacked, edge_sharding)
        self._vec_sharding = NamedSharding(mesh, P(("r", "c")))
        self._aux = ()
        if backend == "dopt":
            out_rp, nbr = out_csr_2d(part, src_gidx, dst_stacked)
            self._aux = (
                jax.device_put(out_rp, edge_sharding),
                jax.device_put(nbr, edge_sharding),
            )
            if dopt_caps is None:
                dopt_caps = default_dopt_caps(src_gidx.shape[2])
        self.dopt_caps = tuple(sorted(set(dopt_caps))) if dopt_caps else ()
        self._exchange = exchange
        #: bit-packed wire format (ISSUE 5): both per-level collectives
        #: (column all-gather, row reduce-scatter) ship uint32 words.
        #: Bit-identical results; default OFF until chip-measured.
        self.wire_pack = bool(wire_pack)
        self._loop = _dist2d_bfs_fn(
            mesh, self.rows, self.cols, part.w, exchange, backend,
            self.dopt_caps, self.wire_pack,
        )
        self._parents = _dist2d_parents_fn(mesh, self.rows, self.cols, part.w, exchange)
        #: level count of the last traversal (one branch — the 2D loop has
        #: no cap ladder) and the modeled off-chip bytes one chip moved in
        #: it (column all-gather + row reduce-scatter per level) — the 2D
        #: analog of DistBfsEngine's exchange accounting.
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None
        # Raw loop carries of the last core invocation; the per-level
        # rows assemble lazily on first last_run_trace access (same
        # contract as DistBfsEngine.last_run_trace).
        self._trace_pending: tuple | None = None
        self._trace_cache: list[dict] | None = None
        self._direction = "dopt" if backend == "dopt" else "push"
        self._warmed = False

    def wire_bytes_per_level(self) -> list[float]:
        """Modeled off-chip bytes one chip moves per level (single entry —
        the 2D loop has no cap ladder): column all-gather + row
        reduce-scatter, packed or plain per ``wire_pack``. Same contract
        as DistBfsEngine.wire_bytes_per_level."""
        return [
            dense_2d_wire_bytes(
                self.rows, self.cols, self.part.w, self._exchange,
                wire_pack=self.wire_pack,
            )
        ]

    def _record_exchange(
        self, levels_run: int, *, resumed_level: int = 0, chain_nonce=None
    ) -> None:
        prev = gate_and_stamp_chain(self, resumed_level, chain_nonce)
        counts = merge_exchange_counts(
            prev, np.array([levels_run], dtype=np.int64), resumed_level
        )
        self.last_exchange_level_counts = counts
        self.last_exchange_bytes = float(counts[0] * self.wire_bytes_per_level()[0])

    def _init_state(self, source: int):
        part = self.part
        pid = int(part.to_padded(source))
        frontier0 = np.zeros(part.vp, dtype=bool)
        frontier0[pid] = True
        dist0 = np.full(part.vp, INF_DIST, dtype=np.int32)
        dist0[pid] = 0
        put = partial(jax.device_put, device=self._vec_sharding)
        return put(frontier0), put(frontier0.copy()), put(dist0)

    def distances_padded(self, source: int, *, max_levels: int | None = None):
        frontier0, visited0, dist0 = self._init_state(source)
        ml = jnp.int32(max_levels if max_levels is not None else self.part.vp)
        _, _, dist, level, front_seq = self._loop(
            self.src_g, self.dst_l, self.rp, self._aux,
            frontier0, visited0, dist0, jnp.int32(0), ml,
        )
        self._record_exchange(int(level))
        self._record_trace(front_seq, int(level), 0)
        return dist, level

    # --- checkpoint/resume: VertexCheckpointMixin (dist_bfs.py) provides
    # start/advance/finish; checkpoints are real-id [V] arrays shared with
    # the 1D engine, so traversals resume across partition topologies. ---

    @property
    def _num_real_vertices(self) -> int:
        return self.part.base.num_vertices

    def _advance_loop(self, f0, vis0, d0, level0: int, cap: int, *, chain_nonce=None):
        frontier, visited, dist, level, front_seq = self._loop(
            self.src_g, self.dst_l, self.rp, self._aux, f0, vis0, d0,
            jnp.int32(level0), jnp.int32(cap),
        )
        self._record_exchange(
            int(level) - level0, resumed_level=level0, chain_nonce=chain_nonce
        )
        self._record_trace(front_seq, int(level) - level0, level0)
        return frontier, visited, dist, level

    def _record_trace(self, front_seq, levels_run: int, level0: int) -> None:
        self._trace_pending = (front_seq, int(levels_run), int(level0))
        self._trace_cache = None

    @property
    def last_run_trace(self) -> list[dict] | None:
        """Per-level rows of the last core invocation — assembled lazily
        (same contract and rationale as DistBfsEngine.last_run_trace;
        tpu_bfs/obs/engine_trace)."""
        pend = self._trace_pending
        if pend is not None:
            front_seq, levels_run, level0 = pend
            self._trace_pending = None
            # The 2D loop has one exchange branch (no cap ladder): every
            # recorded level ran branch 0, levels past the trace window
            # stay -1 so the assembler prices only what was recorded.
            branch_seq = np.where(
                np.arange(TRACE_LEVELS) < min(levels_run, TRACE_LEVELS), 0, -1
            ).astype(np.int32)
            self._trace_cache = assemble_dist_trace(
                self, levels_run, front_seq, branch_seq,
                direction=self._direction, level0=level0,
            )
        return self._trace_cache

    @last_run_trace.setter
    def last_run_trace(self, rows: list[dict] | None) -> None:
        self._trace_pending = None
        self._trace_cache = rows

    def run(
        self,
        source: int,
        *,
        max_levels: int | None = None,
        with_parents: bool = True,
        time_it: bool = False,
    ) -> BfsResult:
        part = self.part
        if not (0 <= source < part.base.num_vertices):
            raise ValueError(f"source {source} out of range")
        elapsed = None
        if time_it:
            (dist_dev, _), elapsed = run_timed(
                lambda: self.distances_padded(source, max_levels=max_levels),
                warm=not self._warmed,
            )
            self._warmed = True
        else:
            dist_dev, _ = self.distances_padded(source, max_levels=max_levels)
        return self._package(dist_dev, source, with_parents, elapsed)

    def _package(self, dist_dev, source, with_parents, elapsed) -> BfsResult:
        part = self.part
        parent = None
        if with_parents:
            parent_dev = self._parents(self.src_g, self.dst_l, dist_dev)
            parent_pad = part.unshard(np.asarray(parent_dev))
            parent = np.where(
                parent_pad >= 0, part.from_padded(np.abs(parent_pad)), -1
            ).astype(np.int32)
            parent[source] = source

        dist = part.unshard(np.asarray(dist_dev))
        reached_mask = dist != INF_DIST
        reached = int(reached_mask.sum())
        num_levels = int(dist[reached_mask].max()) if reached else 0
        _, undirected = self.graph_meta
        slots = int(self._degrees[reached_mask].sum()) if reached else 0
        return BfsResult(
            source=source,
            distance=dist,
            parent=parent,
            num_levels=num_levels,
            reached=reached,
            edges_traversed=slots // 2 if undirected else slots,
            elapsed_s=elapsed,
        )
