"""Multi-chip BFS over a 2D (R x C) edge partition.

The scale-out path the reference lacks (its only distribution mode replicates
the full CSR per device and partitions ownership 1D, bfs.cu:29-32, 346-351;
SURVEY.md §2c flags 2D partitioning as the gap to close for Graph500 scales).
Level structure (see partition2d):

    col all-gather (ICI, 'r' axis)  ->  local expand  ->
    row OR-reduce-scatter (ICI, 'c' axis)  ->  claim owned slice  ->
    psum termination over the whole mesh

Both collectives move O(vp/mesh-dimension) bits per chip instead of the 1D
exchange's O(vp).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.parallel.compat import shard_map

from tpu_bfs.algorithms.bfs import BfsResult
from tpu_bfs.algorithms.frontier import (
    INT32_MAX,
    EdgeData,
    default_dopt_caps,
    expand_or,
    make_dopt_expand,
)
from tpu_bfs.graph.csr import Graph, INF_DIST
from tpu_bfs.parallel.collectives import (
    check_delta_bits,
    column_gather_wire_bytes,
    default_sparse_caps,
    dense_2d_wire_bytes,
    gate_and_stamp_chain,
    merge_exchange_counts,
    normalize_caps,
    pack_bits,
    planned_branch_count,
    planned_branch_labels,
    planned_sparse_exchange_or,
    planned_sparse_wire_bytes_per_level,
    reduce_scatter_min,
    reduce_scatter_or,
    rows_gather_branch_labels,
    sparse_exchange_or,
    sparse_wire_bytes_per_level,
    unpack_bits,
)
from tpu_bfs.obs.engine_trace import TRACE_LEVELS, assemble_dist_trace
from tpu_bfs.parallel.dist_bfs import VertexCheckpointMixin
from tpu_bfs.parallel.partition2d import out_csr_2d, partition_2d
from tpu_bfs.utils.aot import AotProgramProtocol
from tpu_bfs.utils.timing import run_timed


def make_mesh_2d(rows: int, cols: int, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if rows * cols > len(devices):
        raise ValueError(f"mesh {rows}x{cols} needs {rows * cols} devices")
    arr = np.array(devices[: rows * cols]).reshape(rows, cols)
    return Mesh(arr, ("r", "c"))


def _dist2d_bfs_fn(mesh: Mesh, rows: int, cols: int, w: int, exchange: str,
                   backend: str, dopt_caps: tuple[int, ...] = (),
                   wire_pack: bool = False,
                   sparse_caps: tuple[int, ...] = (),
                   delta_bits: tuple[int, ...] = (), sieve: bool = False,
                   predict: bool = False):
    """2D level loop. ``backend='dopt'`` = the BASELINE scale-26 config
    ("2D edge partition + direction-optimizing BFS"): after the column
    all-gather, each chip independently runs the sparse top-down branch
    when its column frontier's local out-degree sum fits a ``dopt_caps``
    rung — the branch is collective-free (both collectives sit outside the
    `lax.cond`), so per-chip divergence is safe.

    ``wire_pack=True`` bit-packs BOTH per-level collectives (ISSUE 5): the
    column all-gather over 'r' ships each chip's [w] slice as ceil(w/32)
    uint32 words, and the row reduce-scatter over 'c' runs the packed
    dense exchange — same collective count, 1/8+ the bytes.

    ``exchange='sparse'`` (ISSUE 7) runs the ROW exchange over 'c' as the
    queue-style id exchange — the row contribution buffer has exactly the
    1D exchange's [cols * w] per-destination-chunk shape, so the same
    machinery applies chunk for chunk; ``delta_bits``/``sieve``/
    ``predict`` upgrade it to the full planner
    (collectives.planned_sparse_exchange_or). The column all-gather stays
    dense (its [w] slices have no id form to win with). The carry counts
    the per-branch levels exactly like the 1D loop; the history scalars
    ride the termination psum, already mesh-global over ('r','c')."""
    row_block = cols * w
    col_block = rows * w
    dopt = backend == "dopt"
    planned = exchange == "sparse" and bool(delta_bits or sieve or predict)
    if exchange == "sparse":
        nb = (
            planned_branch_count(sparse_caps, delta_bits)
            if planned else len(normalize_caps(sparse_caps)) + 1
        )
    else:
        nb = 1

    def local_loop(
        src_g, dst_l, rp_l, aux, frontier, visited, dist, level0, max_levels
    ):
        src_g = src_g[0, 0]
        dst_l = dst_l[0, 0]
        rp_l = rp_l[0, 0]

        def dense_fn(col_frontier):
            active = col_frontier[src_g]
            return expand_or(
                active, dst_l, rp_l, row_block,
                backend="scan" if dopt else backend,
            )

        if dopt:
            edata = EdgeData(
                src=src_g, dst=dst_l, in_rp=rp_l,
                out_rp=aux[0][0, 0],  # [R*w+1] CSR by col-gather-local src
                nbr_sm=aux[1][0, 0],  # [ep2] row-block-local dst, src-major
            )
            expand_local = make_dopt_expand(
                edata, dopt_caps, vert_limit=col_block, out_size=row_block,
                dense_fn=dense_fn,
            )
        else:
            expand_local = dense_fn

        sparse_mode = exchange == "sparse"

        def cond(state):
            count, level = state[4], state[3]
            return (count > 0) & (level < max_levels)

        def body(state):
            # Dense impls keep the legacy 6-element carry (their single
            # branch is synthesized after the loop); the sparse row
            # exchange carries its branch arrays, and the planner its
            # history scalars on top — legacy programs stay carry-for-
            # carry identical.
            if planned:
                (frontier, visited, dist, level, front_count, front_seq,
                 branch_counts, branch_seq, prev_biggest, prev_count,
                 vis_total) = state
            elif sparse_mode:
                (frontier, visited, dist, level, front_count, front_seq,
                 branch_counts, branch_seq) = state
            else:
                (frontier, visited, dist, level, front_count,
                 front_seq) = state
            # Column exchange: assemble this mesh column's frontier slices.
            if wire_pack and rows > 1:
                # Packed wire: gather uint32 words (one per 32 vertices of
                # each chip's slice), unpack per chunk after landing.
                gw = lax.all_gather(pack_bits(frontier), "r", tiled=True)
                col_frontier = unpack_bits(gw.reshape(rows, -1), w).reshape(
                    rows * w
                )
            else:
                col_frontier = lax.all_gather(frontier, "r", tiled=True)  # [R*w]
            contrib = expand_local(col_frontier)
            # Row exchange: combine row-block contributions, keep own chunk.
            if planned:
                # The planner's selection scalars (biggest, max gap,
                # sieve/predict decisions) are pmax'd over 'c' ONLY:
                # uniform within each mesh row — which is all the row
                # exchange's per-row collectives need to stay matched —
                # but rows may take DIFFERENT branches at the same level.
                # The sieve density normalizes by the planner's own
                # [cols*w] row block; vis_total counts the whole
                # rows*cols*w mesh, so scale it down by the row count
                # (that one IS mesh-uniform — every chip divides the
                # same psum).
                hit, branch, biggest = planned_sparse_exchange_or(
                    contrib, "c", cols, caps=sparse_caps,
                    delta_bits=delta_bits, sieve=sieve, visited=visited,
                    visited_total=vis_total // rows, predict=predict,
                    prev_biggest=prev_biggest,
                    growing=front_count >= prev_count, wire_pack=wire_pack,
                )
            elif exchange == "sparse":
                hit, branch = sparse_exchange_or(
                    contrib, "c", cols, caps=sparse_caps, wire_pack=wire_pack
                )
            else:
                hit = reduce_scatter_or(
                    contrib, "c", cols, impl=exchange, wire_pack=wire_pack
                )
                branch = None
            new = hit & ~visited
            dist = jnp.where(new, level + 1, dist)
            visited = visited | new
            count = lax.psum(jnp.sum(new.astype(jnp.int32)), ("r", "c"))
            # Engine-trace slots (tpu_bfs/obs/engine_trace): frontier
            # popcount — already paid by the termination psum — and, in
            # sparse mode, the row-exchange branch. ADD, not set, on the
            # frontier so the clamp slot aggregates levels past the
            # window.
            slot = jnp.minimum(level - level0, TRACE_LEVELS - 1)
            front_seq = front_seq.at[slot].add(count)
            out = (new, visited, dist, level + 1, count, front_seq)
            if sparse_mode:
                if rows > 1:
                    # The recorded branch must be MESH-uniform (it leaves
                    # through replicated out_specs — without this, the
                    # host would read an arbitrary device's row-local
                    # view): record the row-MAX branch index, a single
                    # deterministic representative when rows split. Pure
                    # telemetry, outside the wire-byte models' stated
                    # scope like the termination psum.
                    branch = lax.pmax(branch, "r")
                branch_counts = branch_counts + (
                    jnp.arange(nb, dtype=jnp.int32) == branch
                )
                branch_seq = branch_seq.at[slot].set(branch)
                out = out + (branch_counts, branch_seq)
            if planned:
                # The planner's history scalars: the 2D visited total
                # counts the WHOLE mesh's claims, but the sieve prices
                # against this row's [cols*w] chunks — both mesh-uniform
                # either way, and the density ratio is partition-
                # invariant in expectation.
                out = out + (biggest, front_count, vis_total + count)
            return out

        init_count = lax.psum(jnp.sum(frontier.astype(jnp.int32)), ("r", "c"))
        init = (frontier, visited, dist, jnp.int32(level0), init_count,
                jnp.zeros(TRACE_LEVELS, jnp.int32))
        if sparse_mode:
            init = init + (
                jnp.zeros(nb, jnp.int32),
                jnp.full(TRACE_LEVELS, -1, jnp.int32),
            )
        if planned:
            init = init + (
                jnp.int32(-1), jnp.int32(0),
                lax.psum(jnp.sum(visited.astype(jnp.int32)), ("r", "c")),
            )
        out = lax.while_loop(cond, body, init)
        frontier, visited, dist, level, _, front_seq = out[:6]
        if sparse_mode:
            branch_counts, branch_seq = out[6], out[7]
        else:
            # Single dense branch: every run level took it — synthesized
            # outside the loop so the legacy carry stays untouched.
            levels_run = level - level0
            branch_counts = levels_run[None].astype(jnp.int32)
            branch_seq = jnp.where(
                jnp.arange(TRACE_LEVELS) < jnp.minimum(levels_run, TRACE_LEVELS),
                0, -1,
            ).astype(jnp.int32)
        return frontier, visited, dist, level, front_seq, branch_counts, branch_seq

    aux_specs = (P("r", "c", None), P("r", "c", None)) if dopt else ()
    # Carry donation, same contract as the 1D loop (dist_bfs.py): every
    # caller hands in fresh buffers — _init_state copies, advance
    # device_puts, and the serve adapter's chunked drive reads its
    # snapshot to host BEFORE relaunching from the device outputs — so
    # argnums 4-6 alias out instead of doubling per-chunk residency.
    fn = jax.jit(
        shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(
                P("r", "c", None),
                P("r", "c", None),
                P("r", "c", None),
                aux_specs,
                P(("r", "c")),
                P(("r", "c")),
                P(("r", "c")),
                P(),
                P(),
            ),
            out_specs=(P(("r", "c")), P(("r", "c")), P(("r", "c")), P(), P(),
                       P(), P()),
            check_vma=False,
        ),
        donate_argnums=(4, 5, 6),
    )
    fn._donate_argnums = (4, 5, 6)
    return fn


def _dist2d_parents_fn(mesh: Mesh, rows: int, cols: int, w: int, exchange: str):
    row_block = cols * w

    def local_parents(src_g, dst_l, dist_loc):
        src_g = src_g[0, 0]
        dst_l = dst_l[0, 0]
        i = lax.axis_index("r")
        j = lax.axis_index("c")
        dist_full = lax.all_gather(dist_loc, ("r", "c"), tiled=True)  # [vp]
        # Reconstruct global padded src ids from column-gather-local indices.
        src_global = ((src_g // w) * cols + j) * w + src_g % w
        dst_global = i * row_block + dst_l
        du = dist_full[src_global]
        ok = (du != INT32_MAX) & (du + 1 == dist_full[dst_global])
        cand = jnp.where(ok, src_global, INT32_MAX)
        contrib = (
            jnp.full((row_block,), INT32_MAX, jnp.int32)
            .at[dst_l]
            .min(cand, mode="drop")
        )
        parent_loc = reduce_scatter_min(contrib, "c", cols, impl=exchange)
        parent_loc = jnp.where(parent_loc == INT32_MAX, -1, parent_loc)
        return jnp.where(dist_loc == INT32_MAX, -1, parent_loc)

    return jax.jit(
        shard_map(
            local_parents,
            mesh=mesh,
            in_specs=(P("r", "c", None), P("r", "c", None), P(("r", "c"))),
            out_specs=P(("r", "c")),
            check_vma=False,
        )
    )


class Dist2DBfsEngine(VertexCheckpointMixin, AotProgramProtocol):
    """BFS over an R x C mesh with 2D edge partitioning.

    API mirrors DistBfsEngine; use for meshes large enough that the 1D
    exchange's O(vp) per-chip traffic dominates."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh | None = None,
        *,
        rows: int | None = None,
        cols: int | None = None,
        exchange: str = "ring",
        backend: str = "scan",
        dopt_caps: tuple[int, ...] | None = None,
        wire_pack: bool = False,
        sparse_caps: int | tuple[int, ...] | None = None,
        delta_bits: tuple[int, ...] = (),
        sieve: bool = False,
        predict: bool = False,
    ):
        if mesh is None:
            mesh = make_mesh_2d(rows or 1, cols or 1)
        if tuple(mesh.axis_names) != ("r", "c"):
            raise ValueError("2D engine needs a mesh with axes ('r', 'c')")
        if exchange not in ("ring", "allreduce", "sparse"):
            # Reject loudly at build time (not deep inside shard_map tracing).
            raise ValueError(
                f"unknown exchange {exchange!r} for the 2D engine; "
                "have 'ring', 'allreduce', 'sparse' (the queue-style row "
                "exchange, ISSUE 7)"
            )
        if (delta_bits or sieve or predict) and exchange != "sparse":
            raise ValueError(
                "delta_bits/sieve/predict reshape the SPARSE row exchange "
                f"(the ISSUE 7 planner); exchange={exchange!r} has no id "
                "buffers to compress — use exchange='sparse'"
            )
        self.mesh = mesh
        self.rows, self.cols = (
            mesh.devices.shape[0],
            mesh.devices.shape[1],
        )
        self.graph_meta = (graph.num_input_edges, graph.undirected)
        self._degrees = graph.degrees
        part, src_gidx, dst_stacked, rp_stacked = partition_2d(
            graph, self.rows, self.cols
        )
        self.part = part
        edge_sharding = NamedSharding(mesh, P("r", "c", None))
        self.src_g = jax.device_put(src_gidx, edge_sharding)
        self.dst_l = jax.device_put(dst_stacked, edge_sharding)
        self.rp = jax.device_put(rp_stacked, edge_sharding)
        self._vec_sharding = NamedSharding(mesh, P(("r", "c")))
        self._aux = ()
        if backend == "dopt":
            out_rp, nbr = out_csr_2d(part, src_gidx, dst_stacked)
            self._aux = (
                jax.device_put(out_rp, edge_sharding),
                jax.device_put(nbr, edge_sharding),
            )
            if dopt_caps is None:
                dopt_caps = default_dopt_caps(src_gidx.shape[2])
        self.dopt_caps = tuple(sorted(set(dopt_caps))) if dopt_caps else ()
        self._exchange = exchange
        #: bit-packed wire format (ISSUE 5): both per-level collectives
        #: (column all-gather, row reduce-scatter) ship uint32 words.
        #: Bit-identical results; default OFF until chip-measured.
        self.wire_pack = bool(wire_pack)
        #: ISSUE 7 planner knobs for the sparse ROW exchange (same
        #: contract as DistBfsEngine; all default OFF until chip-measured).
        self.delta_bits = check_delta_bits(delta_bits)
        self.sieve = bool(sieve)
        self.predict = bool(predict)
        self._planned = exchange == "sparse" and bool(
            self.delta_bits or self.sieve or self.predict
        )
        if exchange == "sparse":
            if sparse_caps is None:
                sparse_caps = default_sparse_caps(
                    part.w, wire_pack=self.wire_pack,
                    delta_bits=self.delta_bits,
                )
            elif isinstance(sparse_caps, int):
                sparse_caps = (sparse_caps,)
            self.sparse_caps = normalize_caps(sparse_caps)
        else:
            self.sparse_caps = ()
        self._loop = _dist2d_bfs_fn(
            mesh, self.rows, self.cols, part.w, exchange, backend,
            self.dopt_caps, self.wire_pack, self.sparse_caps,
            self.delta_bits, self.sieve, self.predict,
        )
        # The parent merge is a one-shot int32 MIN reduce-scatter over
        # 'c' — queue-style ids don't apply; 'sparse' rides the ring
        # there (the 1D engine's convention).
        parent_impl = "ring" if exchange == "sparse" else exchange
        self._parents = _dist2d_parents_fn(
            mesh, self.rows, self.cols, part.w, parent_impl
        )
        #: level count of the last traversal (one branch — the 2D loop has
        #: no cap ladder) and the modeled off-chip bytes one chip moved in
        #: it (column all-gather + row reduce-scatter per level) — the 2D
        #: analog of DistBfsEngine's exchange accounting.
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None
        # Raw loop carries of the last core invocation; the per-level
        # rows assemble lazily on first last_run_trace access (same
        # contract as DistBfsEngine.last_run_trace).
        self._trace_pending: tuple | None = None
        self._trace_cache: list[dict] | None = None
        self._direction = "dopt" if backend == "dopt" else "push"
        self._warmed = False

    def wire_bytes_per_level(self) -> list[float]:
        """Modeled off-chip bytes one chip moves per level, per
        row-exchange branch (single entry for the dense impls; the sparse
        ladder's branches — or the ISSUE 7 planner's full layout — each
        plus the per-level column all-gather, which runs on EVERY branch).
        Same contract as DistBfsEngine.wire_bytes_per_level — with the 2D
        caveat that sparse branch selection is per mesh ROW (pmax over
        'c'); when rows split at a level, the recorded branch is the
        row-MAX index (the loop uniformizes it), so the priced bytes are
        one deterministic representative rather than an exact per-chip
        figure."""
        if self._exchange != "sparse":
            return [
                dense_2d_wire_bytes(
                    self.rows, self.cols, self.part.w, self._exchange,
                    wire_pack=self.wire_pack,
                )
            ]
        ag = column_gather_wire_bytes(
            self.rows, self.part.w, wire_pack=self.wire_pack
        )
        if self._planned:
            per = planned_sparse_wire_bytes_per_level(
                self.cols, self.part.w, self.sparse_caps, self.delta_bits,
                wire_pack=self.wire_pack,
            )
        else:
            per = sparse_wire_bytes_per_level(
                self.cols, self.part.w, self.sparse_caps,
                wire_pack=self.wire_pack,
            )
        return [ag + x for x in per]

    def exchange_branch_labels(self) -> list[str] | None:
        """Branch labels for the sparse row exchange (engine-trace hook);
        None for the dense impls."""
        if self._planned:
            return planned_branch_labels(self.sparse_caps, self.delta_bits)
        if self._exchange == "sparse":
            return rows_gather_branch_labels(self.sparse_caps, ())
        return None

    def _record_exchange(
        self, branch_counts, *, resumed_level: int = 0, chain_nonce=None
    ) -> None:
        prev = gate_and_stamp_chain(self, resumed_level, chain_nonce)
        counts = merge_exchange_counts(prev, branch_counts, resumed_level)
        self.last_exchange_level_counts = counts
        self.last_exchange_bytes = float(
            np.dot(counts, self.wire_bytes_per_level())
        )

    def _init_state(self, source: int):
        part = self.part
        pid = int(part.to_padded(source))
        frontier0 = np.zeros(part.vp, dtype=bool)
        frontier0[pid] = True
        dist0 = np.full(part.vp, INF_DIST, dtype=np.int32)
        dist0[pid] = 0
        put = partial(jax.device_put, device=self._vec_sharding)
        return put(frontier0), put(frontier0.copy()), put(dist0)

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the 2D level loop —
        whose sparse row-exchange branches are uniform per mesh ROW (pmax
        over 'c'), exactly what the taint pass verifies — and the parent
        merge. Same contract as DistBfsEngine.analysis_programs."""
        f0, vis0, d0 = self._init_state(0)
        rep = NamedSharding(self.mesh, P())
        l0, ml = (
            jax.device_put(jnp.int32(0), rep),
            jax.device_put(jnp.int32(64), rep),
        )
        return [
            ("level_loop", self._loop,
             (self.src_g, self.dst_l, self.rp, self._aux, f0, vis0, d0,
              l0, ml)),
            ("parents", self._parents, (self.src_g, self.dst_l, d0)),
        ]

    def export_programs(self):
        """AOT inventory (ISSUE 9/11; utils/aot.py): the sharded 2D level
        loop under the dist engines' shared ``dist_core`` name — the
        compile a mesh replica's ``--preheat`` skips. The serve adapter
        dispatches this exact signature (scalars included), so the
        adopted executable's shape precheck passes on every serving
        call."""
        return [
            ("dist_core", "_loop", fn, args)
            for name, fn, args in self.analysis_programs()
            if name == "level_loop"
        ]

    def distances_padded(self, source: int, *, max_levels: int | None = None):
        frontier0, visited0, dist0 = self._init_state(source)
        ml = jnp.int32(max_levels if max_levels is not None else self.part.vp)
        _, _, dist, level, front_seq, branch_counts, branch_seq = self._loop(
            self.src_g, self.dst_l, self.rp, self._aux,
            frontier0, visited0, dist0, jnp.int32(0), ml,
        )
        self._record_exchange(branch_counts)
        self._record_trace(front_seq, branch_seq, int(level), 0)
        return dist, level

    # --- checkpoint/resume: VertexCheckpointMixin (dist_bfs.py) provides
    # start/advance/finish; checkpoints are real-id [V] arrays shared with
    # the 1D engine, so traversals resume across partition topologies. ---

    @property
    def _num_real_vertices(self) -> int:
        return self.part.base.num_vertices

    def _advance_loop(self, f0, vis0, d0, level0: int, cap: int, *, chain_nonce=None):
        frontier, visited, dist, level, front_seq, branch_counts, branch_seq = (
            self._loop(
                self.src_g, self.dst_l, self.rp, self._aux, f0, vis0, d0,
                jnp.int32(level0), jnp.int32(cap),
            )
        )
        self._record_exchange(
            branch_counts, resumed_level=level0, chain_nonce=chain_nonce
        )
        self._record_trace(front_seq, branch_seq, int(level) - level0, level0)
        return frontier, visited, dist, level

    def _record_trace(
        self, front_seq, branch_seq, levels_run: int, level0: int
    ) -> None:
        self._trace_pending = (front_seq, branch_seq, int(levels_run),
                               int(level0))
        self._trace_cache = None

    @property
    def last_run_trace(self) -> list[dict] | None:
        """Per-level rows of the last core invocation — assembled lazily
        (same contract and rationale as DistBfsEngine.last_run_trace;
        tpu_bfs/obs/engine_trace). The branch column is the loop-carried
        row-exchange branch (always 0 for the dense impls; the sparse
        ladder / planner index otherwise)."""
        pend = self._trace_pending
        if pend is not None:
            front_seq, branch_seq, levels_run, level0 = pend
            self._trace_pending = None
            self._trace_cache = assemble_dist_trace(
                self, levels_run, front_seq, branch_seq,
                direction=self._direction, level0=level0,
            )
        return self._trace_cache

    @last_run_trace.setter
    def last_run_trace(self, rows: list[dict] | None) -> None:
        self._trace_pending = None
        self._trace_cache = rows

    def run(
        self,
        source: int,
        *,
        max_levels: int | None = None,
        with_parents: bool = True,
        time_it: bool = False,
    ) -> BfsResult:
        part = self.part
        if not (0 <= source < part.base.num_vertices):
            raise ValueError(f"source {source} out of range")
        elapsed = None
        if time_it:
            (dist_dev, _), elapsed = run_timed(
                lambda: self.distances_padded(source, max_levels=max_levels),
                warm=not self._warmed,
            )
            self._warmed = True
        else:
            dist_dev, _ = self.distances_padded(source, max_levels=max_levels)
        return self._package(dist_dev, source, with_parents, elapsed)

    def _package(self, dist_dev, source, with_parents, elapsed) -> BfsResult:
        part = self.part
        parent = None
        if with_parents:
            parent_dev = self._parents(self.src_g, self.dst_l, dist_dev)
            parent_pad = part.unshard(np.asarray(parent_dev))
            parent = np.where(
                parent_pad >= 0, part.from_padded(np.abs(parent_pad)), -1
            ).astype(np.int32)
            parent[source] = source

        dist = part.unshard(np.asarray(dist_dev))
        reached_mask = dist != INF_DIST
        reached = int(reached_mask.sum())
        num_levels = int(dist[reached_mask].max()) if reached else 0
        _, undirected = self.graph_meta
        slots = int(self._degrees[reached_mask].sum()) if reached else 0
        return BfsResult(
            source=source,
            distance=dist,
            parent=parent,
            num_levels=num_levels,
            reached=reached,
            edges_traversed=slots // 2 if undirected else slots,
            elapsed_s=elapsed,
        )


# --- serving adapter (ISSUE 11) -------------------------------------------


@dataclasses.dataclass
class _Pending2D:
    """An in-flight 2D serving batch: one async level-loop launch per
    UNIQUE source (JAX dispatch is async; nothing host-side has blocked),
    plus the lane -> unique-run map that rebuilds the padded batch.

    With level-checkpointed resume armed (ISSUE 12), ``cursors`` carries
    each run's chunk state — the launched chunk's start level, the chain
    nonce, and the drive's wall-clock origin — and ``stats`` holds None
    until the final chunk completes in ``fetch``."""

    sources: np.ndarray  # [S] the padded lane sources
    uniq: np.ndarray  # [U] unique sources actually launched
    inv: np.ndarray  # [S] lane -> unique-run index
    runs: list  # per-unique raw loop outputs (device)
    stats: list  # per-unique (reached, ecc, edges) device scalars
    cursors: list | None = None  # per-unique chunk state (resume mode)
    total_cap: int = 0  # absolute level cap of the whole query


class Dist2DServeResult:
    """Serving-protocol result over the unique 2D runs: lazy per-lane
    distance extraction (one unshard per UNIQUE source, cached), with the
    on-device ``reached``/``ecc``/``edges_traversed`` summaries the
    executor's metadata-only path reads without ever pulling an O(V)
    row."""

    def __init__(self, part, uniq_dists, inv, sources, reached, ecc,
                 edges):
        self._part = part
        self._uniq_dists = uniq_dists  # [U] device dist arrays
        self._inv = inv
        self.sources = sources
        self.reached = reached  # [S] int64, lane-mapped
        self.ecc = ecc  # [S] int32 eccentricity (levels) per lane
        self.edges_traversed = edges  # [S] int64
        self._cache: dict = {}

    def _dist_of(self, u: int) -> np.ndarray:
        d = self._cache.get(u)
        if d is None:
            d = self._part.unshard(np.asarray(self._uniq_dists[u]))
            self._cache[u] = d
        return d

    def distances_int32(self, i: int) -> np.ndarray:
        """[V] int32 distances of lane ``i`` (INF_DIST unreached) — the
        2D loop labels int32 distances natively, so no plane decode."""
        if not (0 <= i < len(self.sources)):
            raise IndexError(i)
        return self._dist_of(int(self._inv[i]))


class Dist2DServeEngine:
    """The 2D engine behind the serve executor's batch protocol.

    The packed MS engines answer a ``lanes``-wide batch in ONE sharded
    level loop; the 2D engine is single-source, so this adapter maps a
    coalesced batch onto one async loop launch per UNIQUE source (the
    executor pads partial batches by repeating a real source, so a
    3-query batch padded to 32 lanes runs 3 loops, not 32). ``dispatch``
    launches every run without blocking; ``fetch`` blocks, records the
    exchange accounting per run, and assembles a result whose per-lane
    views index the unique runs. ``backend='dopt'`` is the default — the
    paper's baseline scale-26 configuration (2D edge partition +
    direction-optimizing BFS).

    ``resume_levels=K`` (ISSUE 12) arms LEVEL-CHECKPOINTED RESUME: each
    run drives the SAME compiled loop K levels at a time (new level
    bounds, no retrace) and snapshots its carry at every chunk boundary
    into the process-wide per-graph resume cache
    (tpu_bfs/resilience/resume — host real-id checkpoints through the
    PR 4 CRC machinery, portable across mesh shapes). A later dispatch
    of the same source — e.g. the service's re-admission after a mesh
    fault, on an engine rebuilt over a DEGRADED mesh — starts from the
    last intact level instead of the source: bounded recompute <= K
    levels. Completed runs drop their snapshots."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh,
        *,
        lanes: int = 32,
        exchange: str = "ring",
        backend: str = "dopt",
        wire_pack: bool = False,
        delta_bits: tuple[int, ...] = (),
        sieve: bool = False,
        predict: bool = False,
        resume_levels: int = 0,
    ):
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if resume_levels < 0:
            raise ValueError(
                f"resume_levels must be >= 0, got {resume_levels}"
            )
        self.lanes = int(lanes)
        self.resume_levels = int(resume_levels)
        if resume_levels:
            from tpu_bfs.resilience.resume import (
                ResumePolicy,
                cache_for_graph,
            )

            self._resume = ResumePolicy(every_levels=int(resume_levels))
            self._resume_cache = cache_for_graph(graph)
        else:
            self._resume = None
            self._resume_cache = None
        self.engine = Dist2DBfsEngine(
            graph, mesh, exchange=exchange, backend=backend,
            wire_pack=wire_pack, delta_bits=delta_bits, sieve=sieve,
            predict=predict,
        )
        eng = self.engine
        self._undirected = graph.undirected
        # Per-run on-device summaries: padded phantoms are never reached,
        # so the reductions over the padded space equal the real-vertex
        # figures; the sums ride GSPMD all-reduces, not host pulls.
        part = eng.part
        deg_pad = np.zeros(part.vp, dtype=np.uint32)
        deg_pad[part.to_padded(np.arange(graph.num_vertices))] = (
            graph.degrees.astype(np.uint32)
        )
        deg_dev = jax.device_put(deg_pad, eng._vec_sharding)

        @jax.jit
        def run_stats(dist):
            # 32-bit on purpose (the analysis dtype lint bans 64-bit
            # avals): reached <= V < 2^31 fits int32; the edge-slot sum
            # rides uint32, which holds the Graph500 scale-26 slot count
            # (2E ~ 2^31.1) — revisit past scale 27.
            fin = dist != INT32_MAX
            reached = jnp.sum(fin.astype(jnp.int32))
            ecc = jnp.max(jnp.where(fin, dist, 0))
            edges = jnp.sum(jnp.where(fin, deg_dev, jnp.uint32(0)))
            return reached, ecc, edges

        self._run_stats = run_stats
        #: modeled off-chip bytes one chip moved for the LAST fetched
        #: batch (summed over its unique runs) — the serve tier's
        #: wire-bytes-per-query record.
        self.last_exchange_bytes: float | None = None

    # --- passthroughs the serve/obs/analysis layers read ------------------

    @property
    def mesh(self):
        return self.engine.mesh

    @property
    def num_vertices(self) -> int:
        return self.engine.part.base.num_vertices

    @property
    def max_levels_cap(self) -> int:
        """Deepest level bound a dispatch can run (the khop adapter's
        clamp point, ISSUE 20). The 2D loop labels int32 distances with
        no plane cap, so the bound is the padded vertex count — the
        trivial upper bound on any eccentricity."""
        return int(self.engine.part.vp)

    @property
    def last_run_trace(self):
        return self.engine.last_run_trace

    @property
    def _aot_adopted(self):
        return getattr(self.engine, "_aot_adopted", ())

    def exchange_branch_labels(self):
        return self.engine.exchange_branch_labels()

    def wire_bytes_per_level(self):
        return self.engine.wire_bytes_per_level()

    def analysis_programs(self):
        return self.engine.analysis_programs()

    def export_programs(self):
        return self.engine.export_programs()

    def adopt_programs(self, programs: dict) -> list:
        return self.engine.adopt_programs(programs)

    # --- the dispatch/fetch serving protocol ------------------------------

    @property
    def _devices_n(self) -> int:
        from tpu_bfs.faults import mesh_devices

        return mesh_devices(self)

    def dispatch(self, sources, *, max_levels: int | None = None) -> _Pending2D:
        from tpu_bfs import faults as _faults

        eng = self.engine
        if _faults.ACTIVE is not None:
            # Mesh-site chaos consultation (ISSUE 12): device_lost /
            # collective_hang / backend_restart rules target this
            # engine's launches; devices context feeds rank qualifiers.
            _faults.ACTIVE.hit(
                "dispatch", lanes=self.lanes, devices=self._devices_n
            )
        sources = np.asarray(sources, dtype=np.int64)
        if len(sources) > self.lanes:
            raise ValueError(
                f"batch of {len(sources)} exceeds {self.lanes} lanes"
            )
        nv = self.num_vertices
        if sources.size and (sources.min() < 0 or sources.max() >= nv):
            raise ValueError(f"source out of range [0, {nv})")
        uniq, inv = np.unique(sources, return_inverse=True)
        total_cap = int(max_levels if max_levels is not None else eng.part.vp)
        runs, stats = [], []
        if self._resume is None:
            for s in uniq:
                f0, vis0, d0 = eng._init_state(int(s))
                out = eng._loop(
                    eng.src_g, eng.dst_l, eng.rp, eng._aux, f0, vis0, d0,
                    jnp.int32(0), jnp.int32(total_cap),
                )
                runs.append(out)
                stats.append(self._run_stats(out[2]))
            return _Pending2D(sources=sources, uniq=uniq, inv=inv,
                              runs=runs, stats=stats, total_cap=total_cap)
        # Resume mode: launch each run's FIRST chunk async (K levels);
        # fetch drives the remaining chunks. A source with an intact
        # snapshot — typically left by a mesh-faulted predecessor engine
        # over the same graph — starts from its last checkpointed level.
        from tpu_bfs.utils.checkpoint import _new_nonce

        k = self._resume.every_levels
        cursors = []
        for s in uniq:
            s = int(s)
            start, nonce = 0, _new_nonce()
            f0 = vis0 = d0 = None
            ckpt = self._resume_cache.get(s)
            if (
                ckpt is not None and ckpt.source == s
                and len(ckpt.frontier) == nv
                # A snapshot DEEPER than this call's level cap cannot be
                # adopted: the capped loop would no-op and hand back
                # levels/distances beyond the requested bound. Start
                # over instead (max_levels-capped calls are the one-shot
                # API's; the serve path always runs to termination).
                and int(ckpt.level) <= total_cap
            ):
                fh, vh, dh = eng._pad_state(ckpt)
                put = partial(jax.device_put, device=eng._vec_sharding)
                f0, vis0, d0 = put(fh), put(vh), put(dh)
                start = int(ckpt.level)
                nonce = ckpt.nonce
                self._resume_cache.mark_resumed(s)
            if f0 is None:
                f0, vis0, d0 = eng._init_state(s)
            cap = min(start + k, total_cap)
            out = eng._loop(
                eng.src_g, eng.dst_l, eng.rp, eng._aux, f0, vis0, d0,
                jnp.int32(start), jnp.int32(cap),
            )
            runs.append(out)
            stats.append(None)  # final-chunk stats land in fetch
            cursors.append({
                "source": s, "start": start, "nonce": nonce,
                "t0": time.monotonic(),
            })
        return _Pending2D(sources=sources, uniq=uniq, inv=inv, runs=runs,
                          stats=stats, cursors=cursors, total_cap=total_cap)

    def _drive_chunks(self, pend: _Pending2D, u: int):
        """Complete run ``u``: block each chunk, snapshot the carry at
        chunk boundaries (the resume cache's CRC-checkpoint machinery),
        relaunch from the DEVICE outputs (no host round trip for the
        carry itself), and return the final ``(loop outputs, stats)``.
        A mesh kind injected at the fetch site fires here mid-query —
        after >= 1 snapshot — so the failover's re-dispatch proves the
        bounded-recompute contract."""
        from tpu_bfs import faults as _faults
        from tpu_bfs.utils.checkpoint import BfsCheckpoint

        eng = self.engine
        cur = pend.cursors[u]
        k = self._resume.every_levels
        out = pend.runs[u]
        clock0 = cur["t0"]
        while True:
            if _faults.ACTIVE is not None:
                # ``level`` context = the in-flight chunk's start level,
                # so a schedule can target "the chunk after level N"
                # deterministically (scripts/mesh_chaos_smoke.py).
                _faults.ACTIVE.hit(
                    "fetch", lanes=self.lanes, devices=self._devices_n,
                    level=cur["start"],
                )
            frontier, visited, dist, level, front_seq, bc, bs = out
            level_i = int(level)  # blocks until the chunk finishes
            eng._record_exchange(
                bc, resumed_level=cur["start"], chain_nonce=cur["nonce"]
            )
            eng._record_trace(
                front_seq, bs, level_i - cur["start"], cur["start"]
            )
            f_host = np.asarray(frontier)
            if not f_host.any() or level_i >= pend.total_cap:
                self._resume_cache.drop(cur["source"])
                return out, self._run_stats(dist)
            if self._resume.should_snapshot(
                level_i, time.monotonic() - clock0
            ):
                part = eng.part
                self._resume_cache.put(cur["source"], BfsCheckpoint(
                    source=cur["source"], level=level_i,
                    frontier=part.unshard(f_host),
                    visited=part.unshard(np.asarray(visited)),
                    distance=part.unshard(np.asarray(dist)),
                    nonce=cur["nonce"],
                ))
            cur["start"] = level_i
            out = eng._loop(
                eng.src_g, eng.dst_l, eng.rp, eng._aux,
                frontier, visited, dist,
                jnp.int32(level_i),
                jnp.int32(min(level_i + k, pend.total_cap)),
            )

    def fetch(self, pend: _Pending2D, *, check_cap: bool = True,
              **_ignored) -> Dist2DServeResult:
        # ``check_cap`` is accepted for dispatch/fetch protocol
        # uniformity (the khop adapter passes it): the 2D loop's level
        # bound defaults to the padded vertex count, above any
        # eccentricity, so a capped run here is always the CALLER's
        # explicit max_levels — stopping at it is the point, never a
        # truncation to flag.
        from tpu_bfs import faults as _faults

        if _faults.ACTIVE is not None:
            # The blocking half's mesh-site consultation (no ``level``
            # context here — the chunked drive below consults per chunk
            # for level-targeted rules).
            _faults.ACTIVE.hit(
                "fetch", lanes=self.lanes, devices=self._devices_n
            )
        eng = self.engine
        u_count = len(pend.uniq)
        reached_u = np.empty(u_count, dtype=np.int64)
        ecc_u = np.empty(u_count, dtype=np.int32)
        edges_u = np.empty(u_count, dtype=np.int64)
        dists = []
        wire = 0.0
        for u, (out, st) in enumerate(zip(pend.runs, pend.stats)):
            if pend.cursors is not None:
                # Chunked resume drive: accounting is recorded per chunk
                # inside (chain-nonce-merged across chunks, so
                # last_exchange_* covers the whole query).
                out, st = self._drive_chunks(pend, u)
                dist = out[2]
            else:
                _, _, dist, level, front_seq, branch_counts, branch_seq = out
                # Per-run accounting: the branch counters price this
                # run's exchange; the LAST run's trace stands for the
                # batch (the unified last_run_trace contract).
                eng._record_exchange(branch_counts)
                eng._record_trace(front_seq, branch_seq, int(level), 0)
            wire += float(eng.last_exchange_bytes or 0.0)
            reached_u[u] = int(st[0])
            ecc_u[u] = int(st[1])
            edges_u[u] = int(st[2])
            dists.append(dist)
        self.last_exchange_bytes = wire
        inv = pend.inv
        edges = edges_u[inv]
        if self._undirected:
            edges = edges // 2
        return Dist2DServeResult(
            eng.part, dists, inv, pend.sources,
            reached_u[inv], ecc_u[inv], edges,
        )

    def run(self, sources, *, max_levels: int | None = None,
            time_it: bool = False) -> Dist2DServeResult:
        """Blocking batch entry (registry warm-up and one-shot callers);
        ``time_it`` is accepted for protocol uniformity."""
        return self.fetch(self.dispatch(sources, max_levels=max_levels))
