"""Distributed bit-packed multi-source BFS over a 1D device mesh.

The multi-chip analog of PackedMsBfsEngine. Compared to the reference's
distribution (full CSR replicated to every device, bfs.cu:346-351; only
distance *ownership* is split), this shards the expensive thing — the edge
structure — and replicates the cheap thing — the packed frontier words
([v_pad, W] uint32, i.e. V * 4W bytes regardless of edge count):

- Vertices (in degree-sorted rank space) are dealt round-robin to shards, so
  every shard holds the same degree mix — the contiguous ``getDev`` split
  (bfs.cu:29-32) would give shard 0 all the hubs.
- Per level, each chip expands only its owned rows through its ELL shard
  (tpu_bfs/graph/ell.py: build_ell_sharded), claims ``& ~visited`` on owned
  words, then ``all_gather`` over the mesh rebuilds the replicated frontier —
  replacing the reference's per-destination bucket exchange
  (cudaMemcpyPeer, bfs.cu:604-606 / MPI_Sendrecv, bfs_mpi.cu:615).
- Termination reads the gathered frontier directly — every chip computes the
  same ``any(frontier)``, so there is no extra Allreduce (bfs_mpi.cu:621) and
  the whole level loop stays in one ``lax.while_loop`` on device.

The same code path serves intra-slice (ICI) and cross-slice (DCN) meshes —
XLA routes the all_gather — collapsing the reference's two near-identical
source files (bfs.cu vs bfs_mpi.cu) into one driver.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.algorithms.msbfs_packed import (
    MAX_LEVELS,
    PackedBfsResult,
    make_packed_expand,
    ripple_increment,
)
from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import ShardedEllGraph, build_ell_sharded
from tpu_bfs.parallel.dist_bfs import make_mesh


def _make_dist_core(sell: ShardedEllGraph, w: int, mesh: Mesh):
    p_count = sell.num_shards
    v_loc = sell.v_loc
    v_pad = sell.v_pad
    # Owned-row expansion: fw is the replicated [v_pad+1, W] table; the result
    # is this chip's [v_loc, W] rows in local (rank // P) order. Same bucketed
    # kernel as the single-chip engine, instantiated per shard.
    expand = make_packed_expand(
        w=w,
        kcap=sell.kcap,
        fold_steps=sell.fold_steps,
        num_virtual=sell.num_virtual,
        light_meta=[(k, blocks.shape[1]) for k, blocks in sell.light],
        heavy=sell.heavy_per_shard > 0,
        tail_rows=sell.tail_rows,
    )
    heavy = sell.heavy_per_shard > 0

    def chip_fn(arrs, fw0, max_levels):
        # Block specs keep a leading axis of size 1; drop it.
        arrs = {k: a[0] for k, a in arrs.items()}
        p = jax.lax.axis_index("v")
        own = lambda full: jax.lax.dynamic_index_in_dim(
            full[:v_pad].reshape(v_loc, p_count, w), p, axis=1, keepdims=False
        )
        vis0 = own(fw0)
        planes0 = tuple(jnp.zeros((v_loc, w), jnp.uint32) for _ in range(8))

        def cond(carry):
            _, _, _, level, alive = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _ = carry
            hit = expand(arrs, fw)
            nxt = hit & ~vis
            vis2 = vis | nxt
            planes = ripple_increment(planes, ~vis2)
            gathered = jax.lax.all_gather(nxt, "v")  # [P, v_loc, W]
            fw_flat = gathered.transpose(1, 0, 2).reshape(v_pad, w)
            fw_next = jnp.concatenate([fw_flat, jnp.zeros((1, w), jnp.uint32)])
            alive = jnp.any(fw_flat != 0)
            return fw_next, vis2, planes, level + 1, alive

        fw_f, vis_f, planes_f, levels, _ = jax.lax.while_loop(
            cond, body, (fw0, vis0, planes0, jnp.int32(0), jnp.bool_(True))
        )
        # Emit per-chip results with a leading axis for the P('v') out spec.
        return (
            tuple(pl[None] for pl in planes_f),
            vis_f[None],
            levels,
        )

    arr_specs = {
        "virtual_t": P("v"),
        "fold_pad_map": P("v"),
        "heavy_pick": P("v"),
    }
    n_arrs = {}
    if heavy:
        # Transposed column layout so each unrolled gather reads one row.
        n_arrs["virtual_t"] = np.ascontiguousarray(sell.virtual.transpose(0, 2, 1))
        n_arrs["fold_pad_map"] = sell.fold_pad_map
        n_arrs["heavy_pick"] = sell.heavy_pick
    for i, (k, blocks) in enumerate(sell.light):
        n_arrs[f"light{i}_t"] = np.ascontiguousarray(blocks.transpose(0, 2, 1))
        arr_specs[f"light{i}_t"] = P("v")
    arr_specs = {k: arr_specs.get(k, P("v")) for k in n_arrs}

    core = jax.jit(
        jax.shard_map(
            chip_fn,
            mesh=mesh,
            in_specs=(arr_specs, P(), P()),
            out_specs=(tuple(P("v") for _ in range(8)), P("v"), P()),
            check_vma=False,
        )
    )
    device_arrs = {
        k: jax.device_put(v, NamedSharding(mesh, arr_specs[k]))
        for k, v in n_arrs.items()
    }
    return core, device_arrs


class DistPackedMsBfsEngine:
    """Multi-chip packed MS-BFS: sharded ELL, replicated frontier words."""

    def __init__(
        self,
        graph: Graph | ShardedEllGraph,
        mesh: Mesh | int | None = None,
        *,
        lanes: int = 256,
        kcap: int = 64,
    ):
        if lanes % 32:
            raise ValueError("lanes must be a multiple of 32")
        self.w = lanes // 32
        self.lanes = lanes
        self.mesh = mesh if isinstance(mesh, Mesh) else make_mesh(mesh)
        p_count = self.mesh.devices.size
        if isinstance(graph, Graph):
            self.sell = build_ell_sharded(graph, p_count, kcap=kcap)
        else:
            self.sell = graph
        if self.sell.num_shards != p_count:
            raise ValueError(
                f"ELL built for {self.sell.num_shards} shards, mesh has {p_count}"
            )
        self.undirected = self.sell.undirected
        self._core, self.arrs = _make_dist_core(self.sell, self.w, self.mesh)
        # Unpacks chip-major [v_pad, w] planes (see run() for the row order).
        self._extract = _make_extract(self.sell.v_pad, self.w)
        self._warmed = False

    def _seed(self, sources: np.ndarray) -> np.ndarray:
        sell = self.sell
        fw0 = np.zeros((sell.v_pad + 1, self.w), np.uint32)
        for i, r in enumerate(sell.rank[sources]):
            fw0[r, i // 32] |= np.uint32(1 << (i % 32))
        return fw0

    def run(
        self, sources, *, max_levels: int = MAX_LEVELS, time_it: bool = False
    ) -> PackedBfsResult:
        sell = self.sell
        sources = np.asarray(sources, dtype=np.int64)
        if sources.ndim != 1 or len(sources) == 0 or len(sources) > self.lanes:
            raise ValueError(f"need 1..{self.lanes} sources, got {sources.shape}")
        if sources.min() < 0 or sources.max() >= sell.num_vertices:
            raise ValueError("source out of range")
        max_levels = min(max_levels, MAX_LEVELS)

        fw0 = jnp.asarray(self._seed(sources))
        if time_it and not self._warmed:
            int(self._core(self.arrs, fw0, jnp.int32(max_levels))[2])
        t0 = time.perf_counter()
        planes, vis, levels = self._core(self.arrs, fw0, jnp.int32(max_levels))
        levels = int(levels)
        elapsed = (time.perf_counter() - t0) if time_it else None
        self._warmed = True

        # The P('v') out-spec concatenates per-chip [1, v_loc, w] blocks into
        # [P, v_loc, w]; flatten to chip-major [v_pad, w], where row
        # p * v_loc + l holds rank l * P + p.
        p_count, v_loc = sell.num_shards, sell.v_loc
        planes = tuple(pl.reshape(sell.v_pad, self.w) for pl in planes)
        vis = vis.reshape(sell.v_pad, self.w)
        src_cm = (
            fw0[: sell.v_pad]
            .reshape(v_loc, p_count, self.w)
            .transpose(1, 0, 2)
            .reshape(sell.v_pad, self.w)
        )
        dist_cm = np.asarray(self._extract(planes, vis, src_cm))
        ranks = sell.rank.astype(np.int64)
        row_of_old = (ranks % p_count) * v_loc + ranks // p_count
        s = len(sources)
        dist = np.ascontiguousarray(dist_cm[row_of_old][:, :s].T)

        reached_mask = dist != np.uint8(255)
        if reached_mask.any():
            levels = int(dist[reached_mask].max())
        reached = reached_mask.sum(axis=1).astype(np.int64)
        slot_sum = reached_mask @ sell.in_degree
        edges = slot_sum // 2 if self.undirected else slot_sum
        return PackedBfsResult(
            sources=sources.astype(np.int32),
            distance_u8=dist,
            num_levels=levels,
            reached=reached,
            edges_traversed=edges.astype(np.int64),
            elapsed_s=elapsed,
        )


def _make_extract(v: int, w: int):
    """Unpack bit-sliced counters to per-lane uint8 distances [v, 32w]."""
    from tpu_bfs.algorithms.msbfs_packed import UNREACHED

    @jax.jit
    def extract(planes, vis, src_bits):
        shifts = jnp.arange(32, dtype=jnp.uint32)
        cols = []
        for wi in range(w):
            cnt = jnp.zeros((v, 32), jnp.uint8)
            for i, p in enumerate(planes):
                bit = ((p[:, wi, None] >> shifts) & 1).astype(jnp.uint8)
                cnt = cnt + (bit << i)
            visw = ((vis[:, wi, None] >> shifts) & 1) != 0
            srcw = ((src_bits[:, wi, None] >> shifts) & 1) != 0
            dist_w = jnp.where(
                srcw,
                jnp.uint8(0),
                jnp.where(visw, cnt + jnp.uint8(1), UNREACHED),
            )
            cols.append(dist_w)
        return jnp.concatenate(cols, axis=1)

    return extract
