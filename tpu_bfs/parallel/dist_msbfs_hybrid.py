"""Distributed hybrid (MXU dense tiles + gather residual) multi-source BFS.

The multi-chip form of the flagship HybridMsBfsEngine. Ownership is split
per concern, which keeps every piece reusable:

- **dense part**: global 128x128 tile selection (same rule as build_hybrid),
  row-tiles dealt round-robin to chips (row-tile t -> chip t % P, so the
  hub-heavy top tiles spread evenly); each chip runs the tile_spmm Pallas
  kernel over its own tiles against the replicated rank0 frontier table.
- **residual part**: the leftover edges form their own graph, sharded with
  build_ell_sharded (round-robin over residual-degree-sorted rows — its own
  row space); neighbor ids are remapped at build time to point into the
  rank0 frontier table, and one static permutation per level routes the
  gathered residual output back to rank0.
- **state**: the frontier and visited tables are replicated (V * 4W bytes,
  cheap); the bit-sliced distance planes — the big state — are sharded in
  contiguous rank0 chunks, so the reassembled planes are already in rank0
  order and the single-chip lazy extraction applies unchanged.

Per level each chip computes its dense + residual contributions, two
all_gathers assemble the full hit table, the claim ``& ~visited`` runs
replicated (identical on every chip, so termination needs no extra
collective — the reference needs an MPI_Allreduce per level,
bfs_mpi.cu:621), and each chip ripples only its plane chunk.

Like the single-chip hybrid, the dense kernel fixes the lane count at 4096
(w=128); unlike it, sharding the planes and edge structure lets that width
fit graphs a single chip cannot hold.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.graph.csr import Graph, build_csr
from tpu_bfs.graph.ell import build_ell_sharded, rank_by_in_degree
from tpu_bfs.algorithms.msbfs_packed import ripple_increment
from tpu_bfs.algorithms._packed_common import (
    ExpandSpec,
    make_fori_expand,
    make_state_kernels,
    run_packed_batch,
)
from tpu_bfs.algorithms.msbfs_hybrid import fill_a_tiles, select_dense_tiles
from tpu_bfs.ops.tile_spmm import AW, TILE, tile_spmm
from tpu_bfs.parallel.dist_bfs import make_mesh

W = 128
LANES = 32 * W


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def build_dist_hybrid(
    g: Graph,
    num_shards: int,
    *,
    kcap: int = 64,
    tile_thr: int = 64,
    a_budget_bytes: int = int(0.2e9),
):
    """Build the sharded dense tiles + sharded residual ELL + glue maps.

    Returns a dict of host arrays (see DistHybridMsBfsEngine for the layout).
    """
    p_count = num_shards
    v = g.num_vertices
    src, dst = g.coo
    in_deg, rank_order, rank = rank_by_in_degree(dst, v)

    vt = _round_up(-(-(v + 1) // TILE), p_count)  # row-tiles, multiple of P
    rows = vt * TILE
    r = rank[dst]
    c = rank[src]
    dense_edge, dense_uniq, tid = select_dense_tiles(
        r, c, vt, tile_thr=tile_thr, a_budget_bytes=a_budget_bytes
    )

    # --- per-chip dense arrays (owner of tile = row_tile % P) ---
    nt = len(dense_uniq)
    g_row_tile = dense_uniq // vt
    g_col_tile = (dense_uniq % vt).astype(np.int32)
    owner = (g_row_tile % p_count).astype(np.int64)
    nrt = vt // p_count  # local row-tiles per chip
    nt_max = max(int(np.bincount(owner, minlength=p_count).max(initial=0)), 1)
    row_start_s = np.zeros((p_count, nrt + 1), np.int32)
    col_tile_s = np.zeros((p_count, nt_max), np.int32)
    a_tiles_s = np.zeros((p_count, nt_max, AW, TILE), np.uint32)

    if nt:
        # Fill A bits globally, then scatter into per-chip slots.
        a_global = fill_a_tiles(dense_edge, dense_uniq, tid, r, c)
        for p in range(p_count):
            mine = np.flatnonzero(owner == p)
            local_rt = (g_row_tile[mine] // p_count).astype(np.int64)
            # dense_uniq is (row_tile, col) sorted; the filtered subsequence
            # is sorted by local row-tile already.
            row_start_s[p] = np.searchsorted(
                local_rt, np.arange(nrt + 1)
            ).astype(np.int32)
            col_tile_s[p, : len(mine)] = g_col_tile[mine]
            a_tiles_s[p, : len(mine)] = a_global[mine]

    # --- residual: its own sharded ELL over the leftover edges ---
    re_mask = ~dense_edge
    res_g = build_csr(
        src[re_mask].astype(np.int64),
        dst[re_mask].astype(np.int64),
        v,
        sort_neighbors=False,
        undirected=False,
    )
    sell = build_ell_sharded(res_g, p_count, kcap=kcap)

    # Remap ELL neighbor ids (residual-rank space, sentinel = its v_pad) to
    # rank0 frontier rows (sentinel = rows - 1, a zero pad row).
    sentinel0 = rows - 1
    trans = np.full(sell.v_pad + 1, sentinel0, dtype=np.int32)
    trans[sell.rank] = rank

    def remap(idx):
        return trans[idx]

    res_arrs = {}
    if sell.heavy_per_shard > 0:
        res_arrs["virtual_t"] = remap(
            np.ascontiguousarray(sell.virtual.transpose(0, 2, 1))
        )
        res_arrs["fold_pad_map"] = sell.fold_pad_map
        res_arrs["heavy_pick"] = sell.heavy_pick
    for i, (k, blocks) in enumerate(sell.light):
        res_arrs[f"light{i}_t"] = remap(np.ascontiguousarray(blocks.transpose(0, 2, 1)))

    # rank0 row -> residual-rank row of the same vertex (the all_gathered
    # residual output is reassembled in residual-rank order). Pad rank0 rows
    # point at residual row v_pad-1 — a pad there too unless P divides V
    # exactly; the level loop masks pad rows regardless (``valid``), which
    # also keeps the rank0 sentinel row (rows-1) permanently zero.
    inv_perm = np.full(rows, sell.v_pad - 1, dtype=np.int32)
    inv_perm[rank] = sell.rank
    valid = np.zeros((rows, 1), dtype=np.uint32)
    valid[rank, 0] = np.uint32(0xFFFFFFFF)

    return {
        "num_vertices": v,
        "num_edges": g.num_edges,
        "undirected": g.undirected,
        "vt": vt,
        "rows": rows,
        "rank": rank,
        "old_of_new": rank_order,
        "in_degree": in_deg,
        "num_dense_edges": int(dense_edge.sum()),
        "num_tiles": nt,
        "row_start_s": row_start_s,
        "col_tile_s": col_tile_s,
        "a_tiles_s": a_tiles_s,
        "sell": sell,
        "res_arrs": res_arrs,
        "inv_perm": inv_perm,
        "valid": valid,
    }


def _make_dist_core(hd, w: int, num_planes: int, mesh: Mesh, interpret: bool):
    p_count = mesh.devices.size
    rows = hd["rows"]
    rows_loc = rows // p_count
    nrt = hd["vt"] // p_count
    sell = hd["sell"]
    spec = ExpandSpec(
        kcap=sell.kcap,
        heavy=sell.heavy_per_shard > 0,
        num_virtual=sell.num_virtual,
        fold_steps=sell.fold_steps,
        light_meta=tuple((k, blocks.shape[1]) for k, blocks in sell.light),
        tail_rows=sell.tail_rows,
    )
    expand = make_fori_expand(spec, w)
    has_dense = hd["num_tiles"] > 0
    v_pad_res = sell.v_pad

    replicated = ("inv_perm", "valid")

    def chip_fn(arrs, fw0, max_levels):
        arrs = {
            k: (a if k in replicated else a[0]) for k, a in arrs.items()
        }
        p = lax.axis_index("v")

        def hit_of(fw):
            # Residual: this chip's residual-rank rows -> all_gather ->
            # residual-rank order -> permute to rank0.
            res_own = expand(arrs, fw)  # [v_loc_res, w]
            ag_r = lax.all_gather(res_own, "v")  # [P, v_loc, w]
            res_full = (
                ag_r.transpose(1, 0, 2).reshape(v_pad_res, w)[arrs["inv_perm"]]
            )
            if has_dense:
                # Dense: this chip's row-tiles -> all_gather -> interleave
                # back (global row-tile t = local j * P + chip p).
                hit_d = tile_spmm(
                    arrs["row_start"], arrs["col_tile"], arrs["a_tiles"], fw,
                    num_row_tiles=nrt, w=w, interpret=interpret,
                )  # [nrt*TILE, w]
                ag_d = lax.all_gather(hit_d.reshape(nrt, TILE, w), "v")
                res_full = res_full | ag_d.transpose(1, 0, 2, 3).reshape(rows, w)
            # Pad rank0 rows never hit (keeps the sentinel row zero).
            return res_full & arrs["valid"]

        def own(full):  # this chip's contiguous plane chunk
            return lax.dynamic_slice(full, (p * rows_loc, 0), (rows_loc, w))

        planes0 = tuple(
            jnp.zeros((rows_loc, w), jnp.uint32) for _ in range(num_planes)
        )

        def cond(carry):
            _, _, _, level, alive = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _ = carry
            nxt = hit_of(fw) & ~vis  # replicated: identical on every chip
            vis2 = vis | nxt
            planes = ripple_increment(planes, ~own(vis2))
            alive = jnp.any(nxt != 0)
            return nxt, vis2, planes, level + 1, alive

        fw_f, vis_f, planes_f, levels, alive = lax.while_loop(
            cond, body, (fw0, fw0, planes0, jnp.int32(0), jnp.bool_(True))
        )

        def deeper():
            return jnp.any((hit_of(fw_f) & ~vis_f) != 0)

        truncated = lax.cond(
            alive & (levels >= max_levels), deeper, lambda: jnp.bool_(False)
        )
        return (
            tuple(pl[None] for pl in planes_f),
            vis_f,
            levels,
            alive,
            truncated,
        )

    def build(n_arrs):
        specs = {
            k: (P() if k in replicated else P("v")) for k in n_arrs
        }
        core = jax.jit(
            jax.shard_map(
                chip_fn,
                mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(
                    tuple(P("v") for _ in range(num_planes)),
                    P(),
                    P(),
                    P(),
                    P(),
                ),
                check_vma=False,
            )
        )
        device_arrs = {}
        for k, a in n_arrs.items():
            sh = NamedSharding(mesh, P() if k in replicated else P("v"))
            device_arrs[k] = jax.device_put(a, sh)
        return core, device_arrs

    return build


class DistHybridMsBfsEngine:
    """Multi-chip 4096-lane hybrid MS-BFS: dense MXU tiles + gather residual.

    API mirrors HybridMsBfsEngine; the dense kernel's 4096-lane requirement
    holds, but sharded planes/edges let it fit graphs one chip cannot.
    """

    def __init__(
        self,
        graph: Graph | dict,
        mesh: Mesh | int | None = None,
        *,
        kcap: int = 64,
        tile_thr: int = 64,
        a_budget_bytes: int = int(0.2e9),
        num_planes: int = 5,
        interpret: bool | None = None,
    ):
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        self.w = W
        self.lanes = LANES
        self.num_planes = num_planes
        self.max_levels_cap = min(1 << num_planes, 254)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.mesh = mesh if isinstance(mesh, Mesh) else make_mesh(mesh)
        p_count = self.mesh.devices.size
        hd = (
            build_dist_hybrid(
                graph, p_count, kcap=kcap, tile_thr=tile_thr,
                a_budget_bytes=a_budget_bytes,
            )
            if isinstance(graph, Graph)
            else graph
        )
        if hd["sell"].num_shards != p_count:
            raise ValueError(
                f"built for {hd['sell'].num_shards} shards, mesh has {p_count}"
            )
        if hd["rows"] % p_count:
            raise ValueError("padded rows not divisible by mesh size")
        self.hd = hd
        self.undirected = hd["undirected"]

        n_arrs = dict(hd["res_arrs"])
        n_arrs["inv_perm"] = hd["inv_perm"]
        n_arrs["valid"] = hd["valid"]
        if hd["num_tiles"]:
            n_arrs["row_start"] = hd["row_start_s"]
            n_arrs["col_tile"] = hd["col_tile_s"]
            n_arrs["a_tiles"] = hd["a_tiles_s"]
        build = _make_dist_core(hd, self.w, num_planes, self.mesh, interpret)
        self._dist_core, self.arrs = build(n_arrs)

        self._rank = hd["rank"].astype(np.int64)
        # Ranks are < V, so the first V entries carry every real vertex —
        # exactly the rows lane_stats scans (make_state_kernels v=V).
        in_deg_r = np.zeros(hd["rows"], dtype=np.float32)
        in_deg_r[self._rank] = hd["in_degree"].astype(np.float32)
        self._in_deg_ranked = jnp.asarray(in_deg_r[: hd["num_vertices"]])
        self._seed_k, self._lane_stats, self._extract_word = make_state_kernels(
            hd["num_vertices"], hd["rows"], self.w, num_planes
        )
        self._warmed = False

    @property
    def num_vertices(self) -> int:
        return self.hd["num_vertices"]

    # Word-major lane map, same as the single-chip engines.
    @staticmethod
    def _word_col(i: int):
        return i // 32, i % 32

    @staticmethod
    def _lane_order(mat: np.ndarray) -> np.ndarray:
        return mat.reshape(-1)

    def _seed_dev(self, sources: np.ndarray):
        ranks = self.hd["rank"][np.asarray(sources, dtype=np.int64)].astype(np.int32)
        lanes = np.arange(len(sources), dtype=np.int32)
        words = (lanes // 32).astype(np.int32)
        bits = np.uint32(1) << (lanes % 32).astype(np.uint32)
        return self._seed_k(jnp.asarray(ranks), jnp.asarray(words), jnp.asarray(bits))

    def _core(self, arrs, fw0, max_levels):
        planes, vis, levels, alive, truncated = self._dist_core(arrs, fw0, max_levels)
        # Contiguous chunks concatenate back into plain rank0 order.
        planes = tuple(pl.reshape(self.hd["rows"], self.w) for pl in planes)
        return planes, vis, levels, alive, truncated

    def run(self, sources, *, max_levels=None, time_it=False, check_cap=True):
        return run_packed_batch(
            self, sources, max_levels=max_levels, time_it=time_it,
            check_cap=check_cap,
        )
