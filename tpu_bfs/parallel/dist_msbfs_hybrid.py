"""Distributed hybrid (MXU dense tiles + gather residual) multi-source BFS.

The multi-chip form of the flagship HybridMsBfsEngine, with **fully sharded
traversal state** — the design the reference could not express: it replicates
the whole graph per device and allocates full-size distance/frontier arrays
per device (bfs.cu:346-351, 339-344), so adding GPUs never adds capacity.
Here every O(V)-row table is sharded, and per-chip memory shrinks as the
mesh grows:

- **Ownership**: row-tiles (128 rank0 rows each) are dealt round-robin to
  chips (tile t -> chip t % P), so the hub-heavy top tiles spread evenly —
  the load balance the reference's contiguous getDev split lacks
  (bfs.cu:29-32). One ownership map covers the dense tiles, the residual
  rows, the frontier/visited shards, and the plane shards.
- **dense part**: global 128x128 tile selection (same rule as build_hybrid);
  each chip runs the tile_spmm Pallas kernel over its own row-tiles against
  the transient all-gathered frontier, producing hits for exactly the rows
  it owns.
- **residual part**: each chip gets a bucketed ELL over the residual
  in-edges of its own rows, with bucket shapes padded to a common maximum
  across chips so one jitted program serves every chip under shard_map; a
  per-chip static permutation routes bucket outputs to local row order.
- **state**: frontier, visited, and the bit-sliced distance planes are all
  sharded [rows/P, w] per chip. Per level, the GATHER layout (default) runs
  one all_gather that materializes the full frontier transiently (discarded
  after expansion); claim, visited update, and plane ripple run on owned
  rows only. Termination is a psum of local claim popcounts — one
  collective per level, like the reference's MPI_Allreduce (bfs_mpi.cu:621)
  but compiled into the on-device loop.
- **sliced layout** (``exchange='sliced'``): the graph-world ring-attention
  move (SURVEY.md §5). Edges regroup by (source chip, ring step); each chip
  expands against its RESIDENT frontier shard while an [A/P, w] accumulator
  rotates the ring, landing home after P partial accumulations — no
  gathered frontier ever exists, every edge still processed once per level,
  and the wire bytes equal the ring all-gather's. The O(A) transient below
  becomes O(A/P): adding chips then genuinely reaches bigger graphs.

Per-chip memory (at the default w=128 words = 4096 lanes, row bytes 4w =
512 B; A = active rows — scale row bytes linearly for wider ``lanes``):
  persistent: (num_planes + 2) * A/P * 512 B     (planes + visited + frontier)
  transient:  gather layout: A * 512 B (gathered frontier) + A/P * 512 B
              sliced layout: 2 * A/P * 512 B (rotating accumulator + hits)
  structures: dense tiles (2 KB each) + residual ELL slots / P
so with the sliced layout EVERY term falls as 1/P — see BENCHMARKS.md for
the Graph500 scale-26 budget on v5p.

Like the single-chip hybrid, the dense kernel constrains the lane count to
multiples of 4096 (w % 128 == 0; default 4096, ``lanes`` raises it); unlike
it, sharding lets that width fit graphs one chip cannot hold.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.parallel.compat import shard_map

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import (
    _ell_fill,
    gate_forward_map,
    pad_gate_blocks,
    pad_heavy_shards,
    rank_vertices,
)
from tpu_bfs.algorithms.msbfs_packed import ripple_increment
from tpu_bfs.algorithms._packed_common import (
    AotProgramProtocol,
    ExpandSpec,
    PackedRunProtocol,
    PullGateHost,
    lazy_full_parent_ell,
    make_expand,
    make_gated_expand,
    make_state_kernels,
    seed_scatter_args,
    validate_expand_impl,
)
from tpu_bfs.algorithms.msbfs_hybrid import fill_a_tiles, select_dense_tiles
from tpu_bfs.ops.tile_spmm import AW, TILE, tile_spmm
from tpu_bfs.parallel.collectives import (
    RowGatherExchangeAccounting,
    check_delta_bits,
    default_row_gather_caps,
    normalize_caps,
    rows_gather_branch_count,
    sparse_rows_gather,
)
from tpu_bfs.parallel.dist_bfs import make_mesh

W = 128
LANES = 32 * W
# Same width generalization as the single-chip engines (msbfs_hybrid):
# wider rows in 4096-lane steps, opt-in via ``lanes``. The DISTRIBUTED
# default stays 4096 (the single-chip default moved to 8192 after the
# round-4 sweep): the scale-26 per-chip budget below is written for
# 128-word rows, so width here is an explicit memory trade, not a default
# (see dist_msbfs_wide.py for the same rationale).
from tpu_bfs.algorithms.msbfs_hybrid import MAX_LANES  # noqa: E402


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _build_residual_groups(
    groups,
    rows_loc: int,
    n_minor: int,
    sentinel: int,
    kcap: int,
):
    """Common-shape bucketed ELL over an explicit list of edge groups.

    ``groups`` is a list of ``(ldst, nbr)`` pairs — per-group local
    destination rows (in [0, rows_loc)) and neighbor ids (any id space;
    ``n_minor`` bounds them for the sort, ``sentinel`` pads ELL slots).
    Every bucket shape is padded to the maximum across groups so one jitted
    program serves all groups under shard_map/scan. This is the group-
    generic core of both the per-chip residual shards (P groups, neighbor
    ids global rank0) and the ring-sliced pair shards (P*P groups, neighbor
    ids local to the source chip's frontier shard).
    Returns (spec, res_arrs stacks [G, ...], perm [G, rows_loc]).
    """
    from tpu_bfs.graph.csr import _lexsort_pairs

    per_chip = []
    for ldst, nbr in groups:
        lens_local = np.bincount(ldst, minlength=rows_loc).astype(np.int64)
        order_rows = np.argsort(-lens_local, kind="stable").astype(np.int64)
        pos_of_row = np.empty(rows_loc, dtype=np.int64)
        pos_of_row[order_rows] = np.arange(rows_loc)
        # Neighbors grouped by (sorted row, src) for determinism. Minor-key
        # values live in the caller's id space, hence the separate n_minor
        # bound (rows_loc alone could make the native sort reject calls).
        order_e = _lexsort_pairs(
            pos_of_row[ldst], nbr.astype(np.int64), rows_loc, n_minor
        )
        nbrs = nbr[order_e].astype(np.int32)
        lens = lens_local[order_rows]
        rp = np.zeros(rows_loc + 1, dtype=np.int64)
        np.cumsum(lens, out=rp[1:])
        per_chip.append((lens, nbrs, rp, order_rows))

    # --- Common heavy-section shapes (shared pyramid-padding helper). ---
    nh_p = [int(np.searchsorted(-t[0], -kcap, side="left")) for t in per_chip]
    (
        nh, num_virtual, fold_steps, _m2,
        virtual_s, fold_pad_map_s, heavy_pick_s,
    ) = pad_heavy_shards(
        [t[0][:n] for t, n in zip(per_chip, nh_p)],
        [t[1][: int(t[2][n])] for t, n in zip(per_chip, nh_p)],
        kcap,
        sentinel,
    )
    heavy = nh > 0

    # --- Common light ladder: union of buckets, counts padded to max. ---
    nz_p = [int(np.searchsorted(-t[0], 0, side="left")) for t in per_chip]
    bounds_p = []  # per chip: list of (k, lo, hi) sorted-row ranges
    for (lens, _, _, _), n_h, nz in zip(per_chip, nh_p, nz_p):
        row = n_h
        k = kcap
        b = {}
        while row < nz and k >= 1:
            hi = int(np.searchsorted(-lens, -(k // 2 + 1), side="right"))
            if k == 1:
                hi = nz
            if hi > row:
                b[k] = (row, hi)
                row = hi
            k //= 2
        bounds_p.append(b)
    ks = [
        k
        for k in (kcap >> i for i in range(kcap.bit_length()))
        if k >= 1 and any(k in b for b in bounds_p)
    ]
    n_of_k = {
        k: max(b[k][1] - b[k][0] if k in b else 0 for b in bounds_p) for k in ks
    }
    light_s = []
    for k in ks:
        blocks = []
        for (lens, nbrs, rp, _), b in zip(per_chip, bounds_p):
            lo, hi = b.get(k, (0, 0))
            flat = nbrs[int(rp[lo]) : int(rp[hi])]
            filled = _ell_fill(lens[lo:hi], flat, k, sentinel)
            pad = np.full((n_of_k[k] - (hi - lo), k), sentinel, np.int32)
            blocks.append(np.concatenate([filled, pad]) if len(pad) else filled)
        light_s.append((k, np.stack(blocks)))

    # --- Per-chip permutation: local row -> bucket-output position. ---
    out_height = nh + sum(n_of_k[k] for k in ks) + 1  # +1 zero row
    zero_pos = out_height - 1
    perms = []
    for (lens, _, _, order_rows), n_h, nz, b in zip(
        per_chip, nh_p, nz_p, bounds_p
    ):
        pos_of_sorted = np.full(rows_loc, zero_pos, dtype=np.int32)
        pos_of_sorted[:n_h] = np.arange(n_h, dtype=np.int32)
        off = nh
        for k in ks:
            lo, hi = b.get(k, (0, 0))
            pos_of_sorted[lo:hi] = off + np.arange(hi - lo, dtype=np.int32)
            off += n_of_k[k]
        perm = np.empty(rows_loc, dtype=np.int32)
        perm[order_rows] = pos_of_sorted  # rows with deg 0 -> zero_pos
        perms.append(perm)

    spec = ExpandSpec(
        kcap=kcap,
        heavy=heavy,
        num_virtual=num_virtual,
        fold_steps=fold_steps,
        light_meta=tuple((k, n_of_k[k]) for k in ks),
        tail_rows=1,
    )
    res_arrs = {}
    if heavy:
        res_arrs["virtual_t"] = np.ascontiguousarray(
            virtual_s.transpose(0, 2, 1)
        )
        res_arrs["fold_pad_map"] = fold_pad_map_s
        res_arrs["heavy_pick"] = heavy_pick_s
    for i, (k, blocks) in enumerate(light_s):
        res_arrs[f"light{i}_t"] = np.ascontiguousarray(
            blocks.transpose(0, 2, 1)
        )
    return spec, res_arrs, np.stack(perms)


def _build_residual_shards(
    res_dst: np.ndarray,
    res_src_rank: np.ndarray,
    p_count: int,
    nrt: int,
    rows: int,
    kcap: int,
):
    """Per-chip bucketed ELL over each chip's own residual in-edges.

    ``res_dst``/``res_src_rank`` are rank0-space endpoints of the residual
    edges. Chip p owns local rows of the row-tiles {t : t % P == p}; its
    rows sort by residual degree and bucket exactly like the single-chip
    hybrid, but bucket shapes are padded to the maximum across chips so one
    jitted program serves every chip. Neighbor ids stay global rank0 rows
    (sentinel ``rows - 1``, a pad row kept all-zero by the valid mask).
    Returns (spec, res_arrs [P,...] stacks, perm [P, nrt*128]) where perm
    routes each chip's bucket-output rows back to local row order.
    """
    rows_loc = nrt * TILE

    # Global row -> (owner chip, local row).
    g_tile = res_dst // TILE
    owner = g_tile % p_count
    local_row = (g_tile // p_count) * TILE + res_dst % TILE
    groups = []
    for p in range(p_count):
        sel = np.flatnonzero(owner == p)
        groups.append((local_row[sel], res_src_rank[sel]))
    return _build_residual_groups(groups, rows_loc, rows, rows - 1, kcap)


def _build_residual_pair_shards(
    res_dst: np.ndarray,
    res_src_rank: np.ndarray,
    p_count: int,
    nrt: int,
    kcap: int,
):
    """Ring-sliced residual layout: P*P edge groups, one per (source chip,
    ring step).

    Group (p, s) holds the residual edges whose SOURCE row lives in chip
    p's frontier shard and whose DESTINATION row is owned by chip
    d = (p - s - 1) mod P — the accumulator-rotation schedule: at step s
    chip p ORs its contribution into the accumulator destined for shard d,
    then passes it along the ring; after P steps each accumulator lands on
    its home chip. Neighbor ids are LOCAL to the source chip's frontier
    shard (sentinel ``rows_loc`` -> the appended all-zero row), so the
    expansion reads only the chip-resident frontier — no gathered table
    exists at any point, which is the whole memory win (O(A/P) transients,
    VERDICT r2 #4).
    Returns (spec, res_arrs [P, P, ...], perm [P, P, rows_loc]).
    """
    rows_loc = nrt * TILE

    d_tile = res_dst // TILE
    dst_owner = d_tile % p_count
    dst_local = (d_tile // p_count) * TILE + res_dst % TILE
    s_tile = res_src_rank // TILE
    src_owner = s_tile % p_count
    src_local = (s_tile // p_count) * TILE + res_src_rank % TILE

    groups = []
    for p in range(p_count):
        for s in range(p_count):
            d = (p - s - 1) % p_count
            sel = np.flatnonzero((src_owner == p) & (dst_owner == d))
            groups.append((dst_local[sel], src_local[sel]))
    spec, res_arrs, perm = _build_residual_groups(
        groups, rows_loc, rows_loc + 1, rows_loc, kcap
    )
    res_arrs = {
        k: a.reshape((p_count, p_count) + a.shape[1:]) for k, a in res_arrs.items()
    }
    return spec, res_arrs, perm.reshape(p_count, p_count, rows_loc)


def build_dist_hybrid(
    g: Graph,
    num_shards: int,
    *,
    kcap: int = 64,
    tile_thr: int = 64,
    a_budget_bytes: int = int(0.2e9),
    layout: str = "gather",
):
    """Build sharded dense tiles + per-chip residual ELL + glue maps.

    ``layout='gather'`` (default): destination-sharded structures expanded
    against a transiently gathered full frontier (O(A) transient/level).
    ``layout='sliced'``: ring-sliced pair structures — each chip's edges
    grouped by (source chip, ring step), expanded against the chip-resident
    frontier shard while an O(A/P) accumulator rotates (the graph-world
    ring-attention move, SURVEY.md §5; every edge still processed exactly
    once per level).
    Returns a dict of host arrays (see DistHybridMsBfsEngine).
    """
    if layout not in ("gather", "sliced"):
        raise ValueError(f"unknown layout {layout!r}; have 'gather', 'sliced'")
    p_count = num_shards
    v = g.num_vertices
    src, dst = g.coo
    in_deg, num_active, rank_order, rank = rank_vertices(src, dst, v)

    # Row-tiles over active rows only (isolated vertices get no row), padded
    # to a multiple of P so every chip owns the same tile count.
    vt = _round_up(-(-(num_active + 1) // TILE), p_count)
    rows = vt * TILE
    nrt = vt // p_count
    r = rank[dst]
    c = rank[src]
    dense_edge, dense_uniq, tid = select_dense_tiles(
        r, c, vt, tile_thr=tile_thr, a_budget_bytes=a_budget_bytes
    )

    # --- dense tile grouping ---
    nt = len(dense_uniq)
    g_row_tile = dense_uniq // vt
    g_col_tile = (dense_uniq % vt).astype(np.int32)
    a_global = (
        fill_a_tiles(dense_edge, dense_uniq, tid, r, c)
        if nt
        else np.zeros((1, AW, TILE), np.uint32)
    )
    if layout == "gather":
        # Per-chip: owner of tile = row_tile % P; columns index the
        # gathered full frontier.
        owner = (g_row_tile % p_count).astype(np.int64)
        nt_max = max(int(np.bincount(owner, minlength=p_count).max(initial=0)), 1)
        row_start_s = np.zeros((p_count, nrt + 1), np.int32)
        col_tile_s = np.zeros((p_count, nt_max), np.int32)
        a_tiles_s = np.zeros((p_count, nt_max, AW, TILE), np.uint32)
        if nt:
            for p in range(p_count):
                mine = np.flatnonzero(owner == p)
                local_rt = (g_row_tile[mine] // p_count).astype(np.int64)
                # dense_uniq is (row_tile, col) sorted; the filtered
                # subsequence is sorted by local row-tile already.
                row_start_s[p] = np.searchsorted(
                    local_rt, np.arange(nrt + 1)
                ).astype(np.int32)
                col_tile_s[p, : len(mine)] = g_col_tile[mine]
                a_tiles_s[p, : len(mine)] = a_global[mine]
    else:
        # Sliced: tile lives with its SOURCE columns (owner = col_tile % P),
        # grouped by ring step s = (p - d - 1) mod P toward the accumulator
        # of destination shard d = row_tile % P; columns index the
        # chip-RESIDENT frontier shard (local col tile = col_tile // P).
        src_own = (g_col_tile % p_count).astype(np.int64)
        dst_own = (g_row_tile % p_count).astype(np.int64)
        step = (src_own - dst_own - 1) % p_count
        pair = src_own * p_count + step
        nt_max = max(
            int(np.bincount(pair, minlength=p_count * p_count).max(initial=0)), 1
        )
        row_start_s = np.zeros((p_count, p_count, nrt + 1), np.int32)
        col_tile_s = np.zeros((p_count, p_count, nt_max), np.int32)
        a_tiles_s = np.zeros((p_count, p_count, nt_max, AW, TILE), np.uint32)
        if nt:
            for p in range(p_count):
                for s in range(p_count):
                    mine = np.flatnonzero(pair == p * p_count + s)
                    local_rt = (g_row_tile[mine] // p_count).astype(np.int64)
                    order = np.argsort(local_rt, kind="stable")
                    mine, local_rt = mine[order], local_rt[order]
                    row_start_s[p, s] = np.searchsorted(
                        local_rt, np.arange(nrt + 1)
                    ).astype(np.int32)
                    col_tile_s[p, s, : len(mine)] = g_col_tile[mine] // p_count
                    a_tiles_s[p, s, : len(mine)] = a_global[mine]

    # --- residual ELL ---
    re_mask = ~dense_edge
    if layout == "gather":
        spec, res_arrs, perm_s = _build_residual_shards(
            r[re_mask].astype(np.int64),
            c[re_mask].astype(np.int32),
            p_count,
            nrt,
            rows,
            kcap,
        )
    else:
        spec, res_arrs, perm_s = _build_residual_pair_shards(
            r[re_mask].astype(np.int64),
            c[re_mask].astype(np.int64),
            p_count,
            nrt,
            kcap,
        )

    # Valid mask: real active rows of each chip (global rank0 row < active).
    rows_loc = nrt * TILE
    j = np.arange(rows_loc) // TILE  # local tile
    i = np.arange(rows_loc) % TILE
    g_rows = (j[None, :] * p_count + np.arange(p_count)[:, None]) * TILE + i
    valid_s = ((g_rows < num_active).astype(np.uint32) * np.uint32(0xFFFFFFFF))[
        :, :, None
    ]

    # Vertex -> tau row (the sharded tables' global order: chip-major, then
    # local rows). Isolated vertices (rank >= active) -> rows (no row).
    g_tile_of = rank // TILE
    tau = (
        (g_tile_of % p_count).astype(np.int64) * rows_loc
        + (g_tile_of // p_count).astype(np.int64) * TILE
        + rank % TILE
    )
    tau_of_vertex = np.where(rank < num_active, tau, rows).astype(np.int64)

    return {
        "layout": layout,
        "num_vertices": v,
        "num_active": num_active,
        "num_edges": g.num_edges,
        "undirected": g.undirected,
        "num_shards": p_count,
        "vt": vt,
        "rows": rows,
        "rank": rank,
        "old_of_new": rank_order,
        "in_degree": in_deg,
        "tau_of_vertex": tau_of_vertex,
        "num_dense_edges": int(dense_edge.sum()),
        "num_tiles": nt,
        "row_start_s": row_start_s,
        "col_tile_s": col_tile_s,
        "a_tiles_s": a_tiles_s,
        "res_spec": spec,
        "res_arrs": res_arrs,
        "perm_s": perm_s,
        "valid_s": valid_s,
    }


def _make_dist_core(
    hd, w: int, num_planes: int, mesh: Mesh, interpret: bool,
    exchange: str = "dense", sparse_caps: tuple[int, ...] = (),
    gate_levels: int = 0, delta_bits: tuple[int, ...] = (),
    expand_impl: str = "xla",
):
    p_count = mesh.devices.size
    rows = hd["rows"]
    nrt = hd["vt"] // p_count
    rows_loc = nrt * TILE
    expand = make_expand(
        hd["res_spec"], w, impl=expand_impl, interpret=interpret
    )
    has_dense = hd["num_tiles"] > 0
    nb = (
        rows_gather_branch_count(sparse_caps, delta_bits)
        if exchange == "sparse" else 1
    )
    sliced = hd.get("layout", "gather") == "sliced"
    # Pull gate (ISSUE 1): gate_levels > 0 makes the cores take a trailing
    # replicated lane-mask argument and return a trailing per-chip
    # [1, gate_levels] skipped-block array (host-summed — deliberately NOT
    # psum'd, so the gated program adds no collective the ungated one
    # lacks; utils/wirecheck.check_gated_hybrid audits exactly that).
    # Gating keys differ by layout: the gather layout skips residual
    # bucket blocks whose destination rows all settled (chip-resident vis
    # decides, same rule as the single-chip engines); the sliced layout
    # skips a chip's contribution computes outright on levels where its
    # RESIDENT frontier shard is empty — destination settledness lives on
    # the accumulator's home chip there, so source-side emptiness is the
    # gate that composes with the rotation without new exchange. The ring
    # ppermutes themselves always run: a collective inside a per-chip cond
    # would deadlock chips that disagree — that is the "where legal" line.
    gated = gate_levels > 0
    gated_expand = (
        make_gated_expand(
            hd["res_spec"], w, impl=expand_impl, interpret=interpret
        )
        if gated and not sliced else None
    )

    def _global_any(x):
        return lax.psum(jnp.any(x != 0).astype(jnp.int32), "v") > 0

    def _make_loop_sliced(arrs, max_levels, lane_mask=None):
        """Ring-sliced level machinery: no gathered frontier ever exists.

        Each chip expands its (source-resident) edge groups against its own
        frontier shard while an [rows_loc, w] accumulator rotates around
        the ring — after P partial accumulations the accumulator for shard
        p lands on chip p (schedule: at step s chip p feeds the accumulator
        of shard (p - s - 1) mod P; see _build_residual_pair_shards). The
        per-level transient is O(A/P) instead of the gather layout's O(A);
        wire bytes match the ring all-gather exactly ((P-1) rotations of
        one shard) — the win is memory, not traffic, and every edge is
        still processed exactly once per level."""
        res_keys = [
            k for k in arrs
            if k.startswith("light")
            or k in ("virtual_t", "virtual_gt", "fold_pad_map", "heavy_pick")
        ]
        step_keys = res_keys + ["perm"] + (
            ["row_start", "col_tile", "a_tiles"] if has_dense else []
        )
        ring = [(i, (i + 1) % p_count) for i in range(p_count)]

        def contrib(fw, fw_ext, s_arrs):
            out = expand({k: s_arrs[k] for k in res_keys}, fw_ext)[s_arrs["perm"]]
            if has_dense:
                out = out | tile_spmm(
                    s_arrs["row_start"], s_arrs["col_tile"], s_arrs["a_tiles"],
                    fw, num_row_tiles=nrt, w=w, interpret=interpret,
                )
            return out

        def hit_claim(fw, vis):
            """(hit_own, skipped_contribs). Gated: a chip whose resident
            frontier shard is empty contributes identity at every ring
            step, so its P contribution computes (gathers + tiles) are
            skipped under lax.cond; the rotation itself still runs on
            every chip (see _make_dist_core's gating note)."""
            fw_ext = jnp.concatenate([fw, jnp.zeros((1, w), jnp.uint32)])
            if gated:
                empty = ~jnp.any(fw != 0)

                def step(s_arrs):
                    return lax.cond(
                        empty,
                        lambda: jnp.zeros((rows_loc, w), jnp.uint32),
                        lambda: contrib(fw, fw_ext, s_arrs),
                    )
            else:
                def step(s_arrs):
                    return contrib(fw, fw_ext, s_arrs)

            acc = step({k: arrs[k][0] for k in step_keys})

            def sbody(acc, xs):
                acc = lax.ppermute(acc, "v", ring)
                return acc | step(xs), None

            if p_count > 1:
                acc, _ = lax.scan(
                    sbody, acc, {k: arrs[k][1:] for k in step_keys}
                )
            skipped = (
                jnp.where(empty, p_count, 0) if gated else jnp.int32(0)
            )
            return acc & arrs["valid"], skipped

        def body_claim(fw, vis):
            hit, skipped = hit_claim(fw, vis)
            return hit, jnp.int32(0), skipped

        return _make_run_from(body_claim, max_levels), hit_claim

    def _make_run_from(body_claim, max_levels):
        """The shared while-loop shell of both layouts: ``body_claim(fw,
        vis) -> (hit_own, exchange_branch, skipped)`` plugs in the
        per-layout expansion; the carry grows the per-level skipped-block
        array in gated mode."""

        def cond(carry):
            level, alive = carry[3], carry[4]
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _, bc = carry[:6]
            hit, branch, skipped = body_claim(fw, vis)
            nxt = hit & ~vis
            vis2 = vis | nxt
            planes = ripple_increment(planes, ~vis2)
            bc = bc + (jnp.arange(nb, dtype=jnp.int32) == branch)
            # One psum per level is the whole termination protocol (the
            # reference needs a host-visible MPI_Allreduce, bfs_mpi.cu:621).
            alive = _global_any(nxt)
            out = (nxt, vis2, planes, level + 1, alive, bc)
            if gated:
                gc = carry[6].at[
                    jnp.minimum(level, gate_levels - 1)
                ].set(skipped)
                out = out + (gc,)
            return out

        def run_from(fw, vis, planes, level0):
            init = (fw, vis, planes, level0, jnp.bool_(True),
                    jnp.zeros(nb, jnp.int32))
            if gated:
                init = init + (jnp.zeros(gate_levels, jnp.int32),)
            return lax.while_loop(cond, body, init)

        return run_from

    def _make_loop(arrs, max_levels, lane_mask=None):
        """This chip's level machinery over its stripped arrays: returns
        (run_from, hit_claim) — shared by the fresh and resume entries.
        ``hit_claim(fw, vis) -> (hit_own, skipped)``; vis/lane_mask are
        only consulted in gated mode."""
        if sliced:
            return _make_loop_sliced(arrs, max_levels, lane_mask)

        def dense_gather(fw_own):
            # Transient full frontier in global rank0 order: global tile
            # t = local j * P + chip p, so the transpose interleaves.
            ag = lax.all_gather(fw_own.reshape(nrt, TILE, w), "v")
            return ag.transpose(1, 0, 2, 3).reshape(rows, w)

        def sparse_gather(fw_own):
            # collectives.sparse_rows_gather with this engine's tau row map:
            # local row l = tile j*TILE + r is global rank0 row
            # (j * P + chip) * TILE + r. The gathered table feeds the MXU
            # tiles and residual gathers exactly like the dense slab.
            p = lax.axis_index("v")
            return sparse_rows_gather(
                fw_own, "v",
                caps=sparse_caps,
                out_rows=rows,
                gid_of=lambda ids: ((ids // TILE) * p_count + p) * TILE
                + ids % TILE,
                dense_fn=lambda: dense_gather(fw_own),
                delta_bits=delta_bits,
                gid_of_src=lambda ids, src: (
                    ((ids // TILE) * p_count + src) * TILE + ids % TILE
                ),
            )

        def gather_frontier(fw_own):
            if exchange == "sparse":
                return sparse_gather(fw_own)
            return dense_gather(fw_own), jnp.int32(0)

        def hit_of_gathered(fw_g, vis):
            if gated:
                # Destination-settled gating, chip-resident: this chip's
                # vis shard covers exactly the rows its buckets produce.
                valid_rows = arrs["valid"][:, 0] != 0
                need = (
                    jnp.any((~vis & lane_mask[None, :]) != 0, axis=1)
                    & valid_rows
                )
                need_ext = jnp.concatenate([need, jnp.zeros((1,), bool)])
                res, skipped = gated_expand(
                    arrs, fw_g, need_ext[arrs["gate_fwd"]]
                )
                hit = res[arrs["perm"]]
            else:
                hit = expand(arrs, fw_g)[arrs["perm"]]  # own rows, local
                skipped = jnp.int32(0)
            if has_dense:
                hit = hit | tile_spmm(
                    arrs["row_start"], arrs["col_tile"], arrs["a_tiles"], fw_g,
                    num_row_tiles=nrt, w=w, interpret=interpret,
                )
            return hit & arrs["valid"], skipped

        def hit_claim(fw_own, vis):
            return hit_of_gathered(gather_frontier(fw_own)[0], vis)

        def body_claim(fw, vis):
            fw_g, branch = gather_frontier(fw)
            hit, skipped = hit_of_gathered(fw_g, vis)
            return hit, branch, skipped

        return _make_run_from(body_claim, max_levels), hit_claim

    def chip_fn(arrs, fw0, max_levels, *mask):
        arrs = {k: a[0] for k, a in arrs.items()}  # strip this chip's P axis
        run_from, hit_claim = _make_loop(arrs, max_levels, *mask)
        planes0 = tuple(
            jnp.zeros((rows_loc, w), jnp.uint32) for _ in range(num_planes)
        )
        out = run_from(fw0, fw0, planes0, jnp.int32(0))
        fw_f, vis_f, planes_f, levels, alive, branch_counts = out[:6]

        def deeper():
            return _global_any(hit_claim(fw_f, vis_f)[0] & ~vis_f)

        truncated = lax.cond(
            alive & (levels >= max_levels), deeper, lambda: jnp.bool_(False)
        )
        res = (planes_f, vis_f, levels, alive, truncated, branch_counts)
        if gated:
            res = res + (out[6][None],)  # [1, L]; host sums the chip axis
        return res

    def chip_fn_from(arrs, fw, vis, planes, level0, max_levels, *mask):
        # Checkpoint-resume entry: the while-loop carry (all in the same
        # sharded tau row space) restored mid-traversal — bit-identical to
        # never having stopped (_packed_common.advance_packed_batch).
        arrs = {k: a[0] for k, a in arrs.items()}
        run_from, _ = _make_loop(arrs, max_levels, *mask)
        out = run_from(fw, vis, planes, level0)
        return out[:6] + ((out[6][None],) if gated else ())

    def build(n_arrs):
        mask_in = (P(),) if gated else ()  # replicated lane mask
        gc_out = (P("v"),) if gated else ()  # [P, L] per-chip counters
        core = jax.jit(
            shard_map(
                chip_fn,
                mesh=mesh,
                in_specs=({k: P("v") for k in n_arrs}, P("v"), P())
                + mask_in,
                out_specs=(
                    tuple(P("v") for _ in range(num_planes)),
                    P("v"),
                    P(),
                    P(),
                    P(),
                    P(),
                )
                + gc_out,
                check_vma=False,
            )
        )
        core_from = jax.jit(
            shard_map(
                chip_fn_from,
                mesh=mesh,
                in_specs=(
                    {k: P("v") for k in n_arrs},
                    P("v"),
                    P("v"),
                    tuple(P("v") for _ in range(num_planes)),
                    P(),
                    P(),
                )
                + mask_in,
                out_specs=(
                    P("v"),
                    P("v"),
                    tuple(P("v") for _ in range(num_planes)),
                    P(),
                    P(),
                    P(),
                )
                + gc_out,
                check_vma=False,
            )
        )
        device_arrs = {
            k: jax.device_put(a, NamedSharding(mesh, P("v")))
            for k, a in n_arrs.items()
        }
        return core, core_from, device_arrs

    return build


class DistHybridMsBfsEngine(
    PackedRunProtocol, RowGatherExchangeAccounting, PullGateHost,
    AotProgramProtocol,
):
    """Multi-chip 4096-lane hybrid MS-BFS: dense MXU tiles + gather residual.

    API mirrors HybridMsBfsEngine; frontier/visited/planes are all sharded
    [rows/P, w] per chip (tau order: chip-major, then each chip's local
    row-tiles), so per-chip state memory falls as the mesh grows — the
    scaling the reference's full-replication design forecloses
    (bfs.cu:346-351).

    ``pull_gate=True`` works on every exchange; NB the unit of
    ``last_gate_level_counts`` differs by layout: gather/sparse count
    skipped 128-row bucket blocks (chip-summed, like the single-chip
    engines), while the ring-sliced layout counts skipped per-chip
    CONTRIBUTION COMPUTES (<= P per level — a chip with an empty resident
    frontier shard skips all P of its expansion steps). Compare gated
    counters within one layout only.
    """

    def __init__(
        self,
        graph: Graph | dict,
        mesh: Mesh | int | None = None,
        *,
        kcap: int = 64,
        tile_thr: int = 64,
        a_budget_bytes: int = int(0.2e9),
        num_planes: int = 5,
        interpret: bool | None = None,
        exchange: str = "dense",
        sparse_caps: int | tuple[int, ...] | None = None,
        lanes: int = LANES,
        pull_gate: bool = False,
        wire_pack: bool = False,
        delta_bits: tuple[int, ...] = (),
        expand_impl: str = "xla",
    ):
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        validate_expand_impl(expand_impl)
        self.expand_impl = expand_impl
        if delta_bits and exchange != "sparse":
            raise ValueError(
                "delta_bits compresses the SPARSE row gather's id stream "
                f"(ISSUE 7); exchange={exchange!r} ships whole slabs — "
                "use exchange='sparse'"
            )
        # Wire format (ISSUE 5): every exchange this engine runs — the
        # dense/sparse row gathers AND the sliced layout's rotating
        # source-contribution accumulators — already moves uint32 lane
        # words, one BIT per (vertex, source) pair; bit-packing is the
        # packed MS representation itself, so there is nothing left to
        # compress. The flag is accepted for knob uniformity with the
        # single-source engines (CLI --wire-pack, bench A/B) and pinned
        # to a no-op by the fuzz suite.
        self.wire_pack = bool(wire_pack)
        if exchange not in ("dense", "sparse", "sliced"):
            raise ValueError(
                f"unknown exchange {exchange!r}; have 'dense', 'sparse', "
                "'sliced'"
            )
        if lanes % LANES or not (LANES <= lanes <= MAX_LANES):
            # The dense kernel runs on every shard, so the distributed
            # engine takes whole 4096-lane steps only (no narrow fallback
            # here — per-chip state already scales 1/P; shard wider
            # instead of narrowing).
            raise ValueError(
                f"lanes must be a multiple of {LANES} in [{LANES}, "
                f"{MAX_LANES}]"
            )
        self.w = lanes // 32
        self.lanes = lanes
        self.num_planes = num_planes
        self.max_levels_cap = min(1 << num_planes, 254)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.mesh = mesh if isinstance(mesh, Mesh) else make_mesh(mesh)
        p_count = self.mesh.devices.size
        layout = "sliced" if exchange == "sliced" else "gather"
        hd = (
            build_dist_hybrid(
                graph, p_count, kcap=kcap, tile_thr=tile_thr,
                a_budget_bytes=a_budget_bytes, layout=layout,
            )
            if isinstance(graph, Graph)
            else graph
        )
        if hd["num_shards"] != p_count:
            raise ValueError(
                f"built for {hd['num_shards']} shards, mesh has {p_count}"
            )
        if hd.get("layout", "gather") != layout:
            raise ValueError(
                f"prebuilt shard dict has layout {hd.get('layout', 'gather')!r} "
                f"but exchange {exchange!r} needs {layout!r}"
            )
        self.hd = hd
        self._parent_kcap = kcap
        # Host-side edge list for post-loop parent extraction
        # (PackedBatchResult.parents_int32); a prebuilt shard dict dropped it.
        self.host_graph = graph if isinstance(graph, Graph) else None
        self.undirected = hd["undirected"]
        rows = hd["rows"]

        n_arrs = dict(hd["res_arrs"])
        n_arrs["perm"] = hd["perm_s"]
        n_arrs["valid"] = hd["valid_s"]
        if hd["num_tiles"]:
            n_arrs["row_start"] = hd["row_start_s"]
            n_arrs["col_tile"] = hd["col_tile_s"]
            n_arrs["a_tiles"] = hd["a_tiles_s"]
        rows_loc = (hd["vt"] // hd["num_shards"]) * TILE
        #: delta-encoded sparse row-gather ids (ISSUE 7; sparse exchange
        #: only, default OFF until chip-measured).
        self.delta_bits = check_delta_bits(delta_bits)
        if sparse_caps is None:
            sparse_caps = default_row_gather_caps(
                rows_loc, self.w, self.delta_bits
            )
        elif isinstance(sparse_caps, int):
            sparse_caps = (sparse_caps,)
        self._exchange = exchange
        self.sparse_caps = normalize_caps(sparse_caps)
        # RowGatherExchangeAccounting host attributes (see collectives.py).
        self._gather_p = hd["num_shards"]
        self._gather_rows_loc = rows_loc
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None
        self.pull_gate = pull_gate
        if pull_gate and layout == "gather":
            # Per-chip gate tables (common shapes, like every other array
            # under shard_map): sentinel-padded whole-block bucket indices
            # + the forward routing map bucket-position -> local row.
            spec = hd["res_spec"]
            sentinel = rows - 1
            for i, (_k, _n) in enumerate(spec.light_meta):
                lt = hd["res_arrs"][f"light{i}_t"]  # [P, k, n]
                n_arrs[f"light{i}_gt"] = np.stack(
                    [pad_gate_blocks(lt[p], sentinel) for p in range(p_count)]
                )
            nh = (
                hd["res_arrs"]["heavy_pick"].shape[1] if spec.heavy else 0
            )
            out_height = nh + sum(n for _, n in spec.light_meta) + spec.tail_rows
            num_real = out_height - 1  # the shared zero row is last
            n_arrs["gate_fwd"] = np.stack([
                gate_forward_map(hd["perm_s"][p], out_height, num_real)
                for p in range(p_count)
            ])
        if pull_gate:
            self._lane_mask_dev = jnp.full((self.w,), 0xFFFFFFFF, jnp.uint32)
        if expand_impl == "pallas":
            # Kernel-side whole-block index tables, per shard (gather
            # layout: [P, k, nb*T], sentinel = the gathered table's pad
            # row rows-1) or per (shard, ring step) (sliced layout:
            # [P, P, k, nb*T], sentinel = the appended zero row rows_loc).
            # The pull-gate block above builds the gather layout's light
            # tables identically when both tiers are on.
            spec = hd["res_spec"]
            sentinel = rows_loc if layout == "sliced" else rows - 1

            def _gt_stack(tbl):
                if layout == "sliced":
                    return np.stack([
                        np.stack([
                            pad_gate_blocks(tbl[p, s], sentinel)
                            for s in range(p_count)
                        ])
                        for p in range(p_count)
                    ])
                return np.stack([
                    pad_gate_blocks(tbl[p], sentinel) for p in range(p_count)
                ])

            if spec.heavy:
                n_arrs["virtual_gt"] = _gt_stack(hd["res_arrs"]["virtual_t"])
            for i, (_k, _n) in enumerate(spec.light_meta):
                n_arrs[f"light{i}_gt"] = _gt_stack(
                    hd["res_arrs"][f"light{i}_t"]
                )
        build = _make_dist_core(
            hd, self.w, num_planes, self.mesh, interpret, exchange,
            self.sparse_caps,
            gate_levels=self.max_levels_cap if pull_gate else 0,
            delta_bits=self.delta_bits, expand_impl=expand_impl,
        )
        if pull_gate:
            # The raw jitted resume loop takes the extra lane-mask arg and
            # returns the counter array; keep it OFF the _core_from_jit
            # name so the generic cap-boundary probe and the exchange-
            # accounting wrapper can't mis-call it (PullGateHost).
            self._dist_core, self._gate_core_from_jit, self.arrs = build(
                n_arrs
            )
        else:
            self._dist_core, self._core_from_jit, self.arrs = build(n_arrs)
        self._table_rows = hd["rows"]

        # Extraction maps vertices through tau (vertex -> sharded-table row);
        # isolated vertices map to `rows` and are masked host-side (_act).
        self._rank = hd["tau_of_vertex"]
        self._act = rows
        in_deg_tau = np.zeros(rows, dtype=np.int32)
        valid_v = hd["tau_of_vertex"] < rows
        in_deg_tau[hd["tau_of_vertex"][valid_v]] = hd["in_degree"][
            valid_v
        ].astype(np.int32)
        _, self._lane_stats, self._extract_word, self._lane_ecc = (
            make_state_kernels(
                rows, rows, self.w, num_planes, in_deg_host=in_deg_tau
            )
        )
        sharded = NamedSharding(self.mesh, P("v"))
        w_ = self.w

        @partial(jax.jit, out_shardings=sharded)
        def seed(rws, words, bits):
            fw0 = jnp.zeros((rows, w_), jnp.uint32)
            return fw0.at[rws, words].add(bits)

        self._seed_k = seed
        self._warmed = False

    @property
    def num_vertices(self) -> int:
        return self.hd["num_vertices"]

    # Word-major lane map, same as the single-chip engines.
    @staticmethod
    def _word_col(i: int):
        return i // 32, i % 32

    @staticmethod
    def _lane_order(mat: np.ndarray) -> np.ndarray:
        return mat.reshape(-1)

    def _iso_of(self, sources: np.ndarray):
        return self.hd["rank"][np.asarray(sources, np.int64)] >= self.hd[
            "num_active"
        ]

    def _seed_dev(self, sources: np.ndarray):
        tau = self.hd["tau_of_vertex"][np.asarray(sources, np.int64)]
        return self._seed_k(*seed_scatter_args(tau, self._act))

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the distributed core
        (gated form carries the lane-mask arg). Same contract as
        DistBfsEngine.analysis_programs. The seed table is pre-replicated
        (per-batch seed movement is inherent to dispatch; the transfer
        guard watches the loop, not the input staging)."""
        rep = NamedSharding(self.mesh, P())
        fw0 = jax.device_put(self._seed_dev(np.asarray([0])), rep)
        ml = jax.device_put(jnp.int32(32), rep)
        args = (self.arrs, fw0, ml)
        if self.pull_gate:
            args = args + (jax.device_put(self._lane_mask_dev, rep),)
        return [("dist_core", self._dist_core, args)]

    def export_programs(self):
        """AOT inventory (ISSUE 9; utils/aot.py): the sharded level-loop
        core (gated form carries the lane-mask arg), reusing the
        analysis hook's replicated example args."""
        return [
            ("dist_core", "_dist_core", fn, args)
            for name, fn, args in self.analysis_programs()
            if name == "dist_core"
        ]

    def _core(self, arrs, fw0, max_levels):
        if self.pull_gate:
            planes, vis, levels, alive, truncated, bc, gc = self._dist_core(
                arrs, fw0, max_levels, self._lane_mask_dev
            )
            # [P, L] per-chip skipped blocks; the chip-axis sum stays a
            # DEVICE reduction (no collective was added for it — wirecheck
            # check_gated_hybrid pins that) and, like the exchange
            # counters, is not np.asarray'd here: _core runs inside the
            # async dispatch half, and readers pay the transfer.
            self.last_gate_level_counts = gc.sum(axis=0)
        else:
            planes, vis, levels, alive, truncated, bc = self._dist_core(
                arrs, fw0, max_levels
            )
        self._record_exchange(bc, 0)
        return planes, vis, levels, alive, truncated

    def _core_from(self, arrs, fw, vis, planes, level0, max_levels):
        if not self.pull_gate:
            return super()._core_from(
                arrs, fw, vis, planes, level0, max_levels
            )
        fw_f, vis_f, planes_f, level, alive, bc, gc = (
            self._gate_core_from_jit(
                arrs, fw, vis, planes, level0, max_levels,
                self._lane_mask_dev,
            )
        )
        self._record_exchange(
            bc, int(level0), getattr(self, "_pending_chain_nonce", None)
        )
        self.last_gate_level_counts = gc.sum(axis=0)
        return fw_f, vis_f, planes_f, level, alive

    def _full_parent_ell(self):
        """Batched device parent scan structure (parent_scan.py): neither
        the dense tiles nor the per-chip residual shards concatenate into
        one coverage structure, so build a fresh full in-neighbor ELL; the
        scan's row-space perm maps this engine's tau-ordered extraction
        tables into it. Owned tables — released after the export."""
        return lazy_full_parent_ell(self.host_graph, self._parent_kcap)

    # run/dispatch/fetch come from PackedRunProtocol (_packed_common).

    # --- checkpoint/resume: every table lives in one (tau, sharded) row
    # space, so the generic real-id protocol applies unchanged — and since
    # checkpoints are real-id, a batch checkpointed here resumes on the
    # single-chip engines (or a different mesh size: elastic restart).

    def start(self, sources):
        from tpu_bfs.algorithms._packed_common import start_packed_batch

        return start_packed_batch(self, sources)

    def advance(self, ckpt, levels: int | None = None):
        from tpu_bfs.algorithms._packed_common import advance_packed_batch

        return advance_packed_batch(self, ckpt, levels)

    def finish(self, ckpt):
        from tpu_bfs.algorithms._packed_common import finish_packed_batch

        return finish_packed_batch(self, ckpt)
