"""Distributed 4096-lane bit-packed multi-source BFS over a 1D device mesh.

The multi-chip form of the wide engine (tpu_bfs/algorithms/msbfs_wide.py),
sharing its batch driver and lazy extraction through _packed_common. Compared
to the reference's distribution — full CSR replicated to every device
(initCuda2, bfs.cu:346-351), with only distance *ownership* split — this
shards the expensive thing (the ELL edge structure, dealt round-robin over
degree-sorted rows so every chip gets the same degree mix) and replicates the
cheap thing (the packed frontier words, V * 4W bytes regardless of E):

- per level each chip expands only its owned rows through its ELL shard,
  claims ``& ~visited`` on owned words, and ``all_gather`` over the mesh
  rebuilds the replicated frontier (replacing cudaMemcpyPeer, bfs.cu:604-606,
  and MPI_Sendrecv, bfs_mpi.cu:615);
- termination reads the gathered frontier, so no extra Allreduce
  (bfs_mpi.cu:621) and zero host round-trips inside the level loop;
- the same shard_map program serves ICI and DCN meshes, collapsing the
  reference's two near-identical source files into one driver.

Row layout after the run is chip-major: row ``p * v_loc + l`` of the
reassembled tables holds global rank ``l * P + p``; ``_rank`` maps original
vertex ids straight to chip-major rows so the shared lazy extraction works
unchanged.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs.parallel.compat import shard_map

from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import ShardedEllGraph, build_ell_sharded
from tpu_bfs.algorithms.msbfs_packed import ripple_increment
from tpu_bfs.algorithms._packed_common import (
    AotProgramProtocol,
    ExpandSpec,
    PackedRunProtocol,
    lazy_full_parent_ell,
    make_expand,
    make_state_kernels,
    validate_expand_impl,
)
from tpu_bfs.parallel.collectives import (
    RowGatherExchangeAccounting,
    check_delta_bits,
    default_row_gather_caps,
    normalize_caps,
    rows_gather_branch_count,
    sparse_rows_gather,
)
from tpu_bfs.parallel.dist_bfs import make_mesh

W = 128
LANES = 32 * W
# Width generalization mirrors the single-chip wide engine: any multiple
# of 32 lanes up to MAX_LANES is legal (the sharded tables are [rows_loc,
# w] blocks — width-agnostic). The DISTRIBUTED default stays at 4096 even
# though the single-chip engines moved to 8192 after the round-4 sweep:
# the scale-26 per-chip HBM budget (BENCHMARKS.md) is written for 128-word
# rows, and doubling row bytes would halve the largest graph a given mesh
# can hold — width here is an explicit trade (``lanes=8192``), not a
# default.
from tpu_bfs.algorithms.msbfs_wide import MAX_LANES  # noqa: E402


def _make_dist_core(
    sell: ShardedEllGraph, w: int, num_planes: int, mesh: Mesh,
    exchange: str = "dense", sparse_caps: tuple[int, ...] = (),
    delta_bits: tuple[int, ...] = (),
    expand_impl: str = "xla", interpret: bool = False,
):
    p_count = sell.num_shards
    v_loc = sell.v_loc
    v_pad = sell.v_pad
    nb = (
        rows_gather_branch_count(sparse_caps, delta_bits)
        if exchange == "sparse" else 1
    )
    spec = ExpandSpec(
        kcap=sell.kcap,
        heavy=sell.heavy_per_shard > 0,
        num_virtual=sell.num_virtual,
        fold_steps=sell.fold_steps,
        light_meta=tuple((k, blocks.shape[1]) for k, blocks in sell.light),
        tail_rows=sell.tail_rows,
    )
    expand = make_expand(spec, w, impl=expand_impl, interpret=interpret)

    def _dense_gather(nxt):
        gathered = lax.all_gather(nxt, "v")  # [P, v_loc, W]
        return gathered.transpose(1, 0, 2).reshape(v_pad, w)

    def _sparse_gather(nxt):
        # The MS-engine form of the reference's per-destination buckets
        # (bfs.cu:148-150): collectives.sparse_rows_gather with this
        # engine's round-robin row map (local row l on chip q holds global
        # rank l*P + q). ``delta_bits`` ships the local row ids
        # delta-encoded (ISSUE 7); the receiver then applies the same map
        # per sender via the two-arg form.
        p = lax.axis_index("v")
        return sparse_rows_gather(
            nxt, "v",
            caps=sparse_caps,
            out_rows=v_pad,
            gid_of=lambda ids: ids * p_count + p,
            dense_fn=lambda: _dense_gather(nxt),
            delta_bits=delta_bits,
            gid_of_src=lambda ids, src: ids * p_count + src,
        )

    def _make_loop(arrs, max_levels):
        """This chip's level machinery (run_from + deeper probe pieces),
        shared by the fresh and checkpoint-resume entries."""

        def cond(carry):
            _, _, _, level, alive, _ = carry
            return alive & (level < max_levels)

        def body(carry):
            fw, vis, planes, level, _, branch_counts = carry
            hit = expand(arrs, fw)
            nxt = hit & ~vis
            vis2 = vis | nxt
            planes = ripple_increment(planes, ~vis2)
            if exchange == "sparse":
                fw_flat, branch = _sparse_gather(nxt)
            else:
                fw_flat, branch = _dense_gather(nxt), jnp.int32(0)
            branch_counts = branch_counts + (
                jnp.arange(nb, dtype=jnp.int32) == branch
            )
            fw_next = jnp.concatenate([fw_flat, jnp.zeros((1, w), jnp.uint32)])
            alive = jnp.any(fw_flat != 0)
            return fw_next, vis2, planes, level + 1, alive, branch_counts

        def run_from(fw, vis, planes, level0):
            return lax.while_loop(
                cond, body,
                (fw, vis, planes, level0, jnp.bool_(True),
                 jnp.zeros(nb, jnp.int32)),
            )

        return run_from

    def chip_fn(arrs, fw0, max_levels):
        # Block specs keep a leading shard axis of size 1; drop it.
        arrs = {k: a[0] for k, a in arrs.items()}
        p = lax.axis_index("v")
        own = lambda full: lax.dynamic_index_in_dim(
            full[:v_pad].reshape(v_loc, p_count, w), p, axis=1, keepdims=False
        )
        planes0 = tuple(jnp.zeros((v_loc, w), jnp.uint32) for _ in range(num_planes))
        run_from = _make_loop(arrs, max_levels)
        fw_f, vis_f, planes_f, levels, alive, branch_counts = run_from(
            fw0, own(fw0), planes0, jnp.int32(0)
        )

        # Claim-free truncation probe (see msbfs_wide): one more expand, only
        # when the loop exited at the cap with a live frontier.
        def deeper():
            local = jnp.any((expand(arrs, fw_f) & ~vis_f) != 0)
            return lax.psum(local.astype(jnp.int32), "v") > 0

        truncated = lax.cond(
            alive & (levels >= max_levels), deeper,
            lambda: lax.psum(jnp.int32(0), "v") > 0,
        )
        return (
            tuple(pl[None] for pl in planes_f),
            vis_f[None],
            levels,
            alive,
            truncated,
            branch_counts,
        )

    def chip_fn_from(arrs, fw, vis, planes, level0, max_levels):
        # Checkpoint-resume entry. Layouts match the loop carry: ``fw`` is
        # the replicated rank-order [v_pad+1, w] table (+ the ELL sentinel
        # row), ``vis``/``planes`` are this chip's [v_loc, w] blocks of the
        # chip-major tables (chip-major row p*v_loc+l IS shard p's row l,
        # so P('v') over the chip-major axis hands each chip its block).
        arrs = {k: a[0] for k, a in arrs.items()}
        run_from = _make_loop(arrs, max_levels)
        return run_from(fw, vis, planes, level0)

    def build(n_arrs):
        specs = {k: P("v") for k in n_arrs}
        core = jax.jit(
            shard_map(
                chip_fn,
                mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(
                    tuple(P("v") for _ in range(num_planes)),
                    P("v"),
                    P(),
                    P(),
                    P(),
                    P(),
                ),
                check_vma=False,
            )
        )
        core_from = jax.jit(
            shard_map(
                chip_fn_from,
                mesh=mesh,
                in_specs=(
                    specs,
                    P(),
                    P("v"),
                    tuple(P("v") for _ in range(num_planes)),
                    P(),
                    P(),
                ),
                out_specs=(
                    P(),
                    P("v"),
                    tuple(P("v") for _ in range(num_planes)),
                    P(),
                    P(),
                    P(),
                ),
                check_vma=False,
            )
        )
        device_arrs = {
            k: jax.device_put(v, NamedSharding(mesh, P("v")))
            for k, v in n_arrs.items()
        }
        return core, core_from, device_arrs

    return build


class DistWideMsBfsEngine(PackedRunProtocol, RowGatherExchangeAccounting,
                          AotProgramProtocol):
    """Multi-chip 4096-lane packed MS-BFS: sharded ELL, replicated frontier.

    Per-chip HBM is O(V * W/8 * num_planes) for the packed state plus the
    chip's edge shard — frontier replication is the scalability ceiling (use
    fewer lanes or more planes-frugal settings for very large V).
    """

    def __init__(
        self,
        graph: Graph | ShardedEllGraph,
        mesh: Mesh | int | None = None,
        *,
        lanes: int = LANES,
        kcap: int = 64,
        num_planes: int = 5,
        exchange: str = "dense",
        sparse_caps: int | tuple[int, ...] | None = None,
        wire_pack: bool = False,
        delta_bits: tuple[int, ...] = (),
        expand_impl: str = "xla",
        interpret: bool | None = None,
    ):
        if not (1 <= num_planes <= 8):
            raise ValueError("num_planes must be in [1, 8]")
        validate_expand_impl(expand_impl)
        self.expand_impl = expand_impl
        if interpret is None:
            # Same resolution as the hybrid engines' kernels: emulate the
            # Pallas tier off-TPU so the CPU fuzz drives the real kernel
            # inside shard_map.
            interpret = jax.default_backend() != "tpu"
        self._interpret = bool(interpret)
        if exchange not in ("dense", "sparse"):
            raise ValueError(
                f"unknown exchange {exchange!r}; have 'dense', 'sparse'"
            )
        if delta_bits and exchange != "sparse":
            raise ValueError(
                "delta_bits compresses the SPARSE row gather's id stream "
                f"(ISSUE 7); exchange={exchange!r} ships whole slabs — "
                "use exchange='sparse'"
            )
        # Wire format (ISSUE 5): this engine's exchange already ships
        # uint32 lane words — one BIT per (vertex, source) pair, the
        # information content — so there is nothing left to pack. The
        # flag is accepted so one --wire-pack / bench knob sweeps every
        # distributed engine uniformly; the fuzz suite pins it to a
        # no-op (bit-identical results either way).
        self.wire_pack = bool(wire_pack)
        if lanes % 32 or not (32 <= lanes <= MAX_LANES):
            raise ValueError(
                f"lanes must be a multiple of 32 in [32, {MAX_LANES}]"
            )
        self.w = lanes // 32
        self.lanes = lanes
        self.num_planes = num_planes
        self.max_levels_cap = min(1 << num_planes, 254)
        self.mesh = mesh if isinstance(mesh, Mesh) else make_mesh(mesh)
        p_count = self.mesh.devices.size
        self.sell = (
            build_ell_sharded(graph, p_count, kcap=kcap)
            if isinstance(graph, Graph)
            else graph
        )
        if self.sell.num_shards != p_count:
            raise ValueError(
                f"ELL built for {self.sell.num_shards} shards, mesh has {p_count}"
            )
        sell = self.sell
        # Host-side edge list for post-loop parent extraction
        # (PackedBatchResult.parents_int32); a prebuilt shard set dropped it.
        self.host_graph = graph if isinstance(graph, Graph) else None
        self.undirected = sell.undirected
        # Isolated-source convention (cross-engine checkpoints): real-id
        # checkpoints store no bits for sources that appear in NO edge (the
        # trimmed engines have no row for them), and the finishing engine
        # patches those lanes (reached=1). Every vertex has a row HERE, so
        # this engine's own runs don't need the patch — but finishing a
        # checkpoint started on a trimmed engine does. Exact from a Graph;
        # for a prebuilt undirected shard set in_degree==0 is equivalent; a
        # prebuilt directed one cannot distinguish out-only vertices (None
        # here) — but checkpoints persist the starting engine's exact mask
        # (PackedCheckpoint.iso), which finish_packed_batch prefers, so
        # even this engine patches resumed lanes correctly; None only
        # degrades its own fresh runs' iso reckoning.
        if isinstance(graph, Graph):
            src, dst = graph.coo
            seen = np.zeros(graph.num_vertices, dtype=bool)
            seen[src] = True
            seen[dst] = True
            self._iso_mask = ~seen
        elif sell.undirected:
            self._iso_mask = sell.in_degree == 0
        else:
            self._iso_mask = None

        w = self.w
        n_arrs = {}
        if sell.heavy_per_shard > 0:
            n_arrs["virtual_t"] = np.ascontiguousarray(sell.virtual.transpose(0, 2, 1))
            n_arrs["fold_pad_map"] = sell.fold_pad_map
            n_arrs["heavy_pick"] = sell.heavy_pick
        for i, (k, blocks) in enumerate(sell.light):
            n_arrs[f"light{i}_t"] = np.ascontiguousarray(blocks.transpose(0, 2, 1))
        if expand_impl == "pallas":
            from tpu_bfs.graph.ell import pad_gate_blocks
            from tpu_bfs.ops.ell_expand import validate_kernel_width

            validate_kernel_width(
                w, self._interpret, kernel="dist-wide expand_impl='pallas'"
            )
            # Per-shard sentinel-padded whole-block tables (stacked on the
            # shard axis like every other n_arrs entry; sentinel = the
            # replicated frontier's all-zero row v_pad).
            if sell.heavy_per_shard > 0:
                n_arrs["virtual_gt"] = np.stack([
                    pad_gate_blocks(n_arrs["virtual_t"][p], sell.v_pad)
                    for p in range(sell.num_shards)
                ])
            for i, (k, blocks) in enumerate(sell.light):
                n_arrs[f"light{i}_gt"] = np.stack([
                    pad_gate_blocks(n_arrs[f"light{i}_t"][p], sell.v_pad)
                    for p in range(sell.num_shards)
                ])
        #: delta-encoded sparse row-gather ids (ISSUE 7; sparse exchange
        #: only, default OFF until chip-measured).
        self.delta_bits = check_delta_bits(delta_bits)
        if sparse_caps is None:
            sparse_caps = default_row_gather_caps(
                sell.v_loc, self.w, self.delta_bits
            )
        elif isinstance(sparse_caps, int):
            sparse_caps = (sparse_caps,)
        self._exchange = exchange
        self.sparse_caps = normalize_caps(sparse_caps)
        # RowGatherExchangeAccounting host attributes (see collectives.py).
        self._gather_p = sell.num_shards
        self._gather_rows_loc = sell.v_loc
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None
        build = _make_dist_core(
            sell, w, num_planes, self.mesh, exchange, self.sparse_caps,
            self.delta_bits, expand_impl=expand_impl,
            interpret=self._interpret,
        )
        self._dist_core, self._core_from_jit, self.arrs = build(n_arrs)
        # Checkpoint-conversion metadata: _rank (below) is the chip-major
        # vertex->row map the result tables use; every vertex has a row.
        self._table_rows = sell.v_pad
        self._act = sell.v_pad

        # Chip-major row of global rank r is (r % P) * v_loc + r // P.
        ranks = sell.rank.astype(np.int64)
        self._rank = ((ranks % p_count) * sell.v_loc + ranks // p_count).astype(
            np.int64
        )
        in_deg_cm = np.zeros(sell.v_pad, dtype=np.int32)
        in_deg_cm[self._rank] = sell.in_degree.astype(np.int32)
        # Stats/extraction over the reassembled chip-major tables: every row
        # participates (pad rows are never visited, so they contribute zero).
        _, self._lane_stats, self._extract_word, self._lane_ecc = (
            make_state_kernels(
                sell.v_pad, sell.v_pad, self.w, num_planes,
                in_deg_host=in_deg_cm,
            )
        )
        # Seed table is one row taller (the ELL sentinel row at v_pad).
        rows_seed, w = sell.v_pad + 1, self.w
        self._seed_k = jax.jit(
            lambda r, wd, b: jnp.zeros((rows_seed, w), jnp.uint32).at[r, wd].add(b)
        )
        self._warmed = False

    @property
    def num_vertices(self) -> int:
        return self.sell.num_vertices

    # Word-major lane map (same as the single-chip wide engine).
    @staticmethod
    def _word_col(i: int):
        return i // 32, i % 32

    @staticmethod
    def _lane_order(mat: np.ndarray) -> np.ndarray:
        return mat.reshape(-1)

    def _iso_of(self, sources: np.ndarray):
        if self._iso_mask is None:
            return None
        return self._iso_mask[np.asarray(sources, np.int64)]

    def _seed_dev(self, sources: np.ndarray):
        # The loop consumes the replicated [v_pad+1, w] table in RANK order
        # (the `own` selector and ELL neighbor ids are rank-space). Seed via
        # the device scatter — a host-built table would be ~1 GiB per run at
        # bench scale.
        sell = self.sell
        ranks = sell.rank[np.asarray(sources, dtype=np.int64)].astype(np.int32)
        lanes = np.arange(len(sources), dtype=np.int32)
        words = lanes // 32
        bits = np.uint32(1) << (lanes % 32).astype(np.uint32)
        return self._seed_k(
            jnp.asarray(ranks), jnp.asarray(words), jnp.asarray(bits)
        )

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the distributed core
        whose sparse row-gather branch uniformity the taint pass proves.
        Same contract as DistBfsEngine.analysis_programs. The seed table
        is pre-replicated: per-batch seed movement is inherent to
        dispatch (fresh sources every batch), so the analyzer's
        transfer guard watches the LOOP, not the input staging."""
        rep = NamedSharding(self.mesh, P())
        fw0 = jax.device_put(self._seed_dev(np.asarray([0])), rep)
        ml = jax.device_put(jnp.int32(32), rep)
        return [("dist_core", self._dist_core, (self.arrs, fw0, ml))]

    def export_programs(self):
        """AOT inventory (ISSUE 9; utils/aot.py): the sharded level-loop
        core — THE multi-chip compile a preheat exists to skip — reusing
        the analysis hook's replicated example args (the sharded-export
        plumbing the Buluç & Madduri-style partitioned paths need)."""
        return [
            ("dist_core", "_dist_core", fn, args)
            for name, fn, args in self.analysis_programs()
            if name == "dist_core"
        ]

    def _src_bits_view(self, fw0):
        """Rank-order seed table -> chip-major view matching planes/vis."""
        sell = self.sell
        p = sell.num_shards
        return (
            fw0[: sell.v_pad]
            .reshape(sell.v_loc, p, self.w)
            .transpose(1, 0, 2)
            .reshape(sell.v_pad, self.w)
        )

    def _core(self, arrs, fw0, max_levels):
        planes, vis, levels, alive, truncated, bc = self._dist_core(
            arrs, fw0, max_levels
        )
        self._record_exchange(bc, 0)
        # [P, v_loc, w] blocks -> chip-major [v_pad, w] tables.
        planes = tuple(pl.reshape(self.sell.v_pad, self.w) for pl in planes)
        vis = vis.reshape(self.sell.v_pad, self.w)
        return planes, vis, levels, alive, truncated

    def _full_parent_ell(self):
        """Batched device parent scan structure (parent_scan.py): the
        sharded ELL's per-chip buckets don't concatenate into one coverage
        structure, so build a fresh single-device full ELL; the scan's
        row-space perm maps this engine's chip-major extraction tables
        into it. Owned tables — released after the export."""
        return lazy_full_parent_ell(self.host_graph, self.sell.kcap)

    # run/dispatch/fetch come from PackedRunProtocol (_packed_common).

    # --- checkpoint/resume. Checkpoints are real-vertex-id (portable to the
    # single-chip engines and other mesh sizes — elastic restart); the only
    # engine-specific pieces are the frontier layout hooks consumed by
    # _packed_common (the loop carries the frontier replicated in rank
    # order + ELL sentinel row, unlike the chip-major visited/planes).

    def _fw_table_from_real(self, real):
        sell = self.sell
        if real.shape != (self.num_vertices, self.w):
            raise ValueError(
                f"checkpoint table is {real.shape}, engine expects "
                f"({self.num_vertices}, {self.w}) — lane count and graph "
                "must match the engine the checkpoint resumes on"
            )
        t = np.zeros((sell.v_pad + 1, self.w), np.uint32)  # + sentinel row
        t[sell.rank] = real
        return jnp.asarray(t)

    def _fw_real_from_table(self, fw_rank):
        return np.asarray(fw_rank)[self.sell.rank]

    def start(self, sources):
        from tpu_bfs.algorithms._packed_common import start_packed_batch

        return start_packed_batch(self, sources)

    def advance(self, ckpt, levels: int | None = None):
        from tpu_bfs.algorithms._packed_common import advance_packed_batch

        return advance_packed_batch(self, ckpt, levels)

    def finish(self, ckpt):
        from tpu_bfs.algorithms._packed_common import finish_packed_batch

        return finish_packed_batch(self, ckpt)
