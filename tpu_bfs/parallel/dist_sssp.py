"""Distributed bucketed delta-stepping SSSP over a 1D/2D device mesh.

The multi-chip form of the workload engine (tpu_bfs/workloads/sssp.py),
built on the same substrate as the distributed wide MS-BFS
(parallel/dist_msbfs_wide.py): the sharded bucketized ELL (round-robin
over degree-sorted rows, so every chip sees the same degree mix) plus a
sharded WEIGHTS plane slot-aligned with it
(graph/ell.build_ell_weights_sharded), a replicated rank-order int32
tentative-distance table [v_pad+1, L] (+ the all-INF sentinel row the
pad slots gather), and a per-round value exchange under elementwise min.

Per delta-stepping round each chip relaxes only its OWNED rows through
its ELL+weights shard (the single-chip min-plus expansion runs verbatim
on the local tiles — after shard_map's leading-axis drop the per-shard
arrays have exactly the single-chip key layout), then the mesh rebuilds
the replicated table through one of the (min, +) exchange family
(parallel/collectives.py, ISSUE 20):

- ``ring``: substitute the owned rows into the previous replica and
  ring-reduce-scatter with elementwise min + tiled all-gather;
- ``allreduce``: the same contribution through ``pmin`` — on a 2D mesh
  this factors hierarchically (min over the row axis, then the column
  axis), the 2D partition's two-phase exchange;
- ``sparse``: the queue-style id+value exchange
  (``sparse_rows_exchange_min``) — changed rows ship (id, int32 distance
  row) pairs under the same cap ladder / delta id codec as the OR row
  gather, with optional history prediction (``predict=True``) skipping
  the measurement pmax on confidently-dense rounds.

The delta-stepping control flow is the single-chip loop with its two
scalar decisions made mesh-uniform: the light-sweep convergence test is
one psum per round (the only collective beyond the exchange — the
post-exchange ``changed``/``unsettled`` tests read the REPLICATED table,
so they cost nothing, exactly like the OR engines' gathered-frontier
termination); the bucket close runs under a `lax.cond` whose predicate
every chip shares, so the exchange stays outside the cond and the
collectives stay matched. Round count and the distance table are
bit-identical to the single-chip engine (fuzz-pinned).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_bfs import faults as _faults
from tpu_bfs.algorithms._packed_common import ExpandSpec
from tpu_bfs.graph.csr import Graph
from tpu_bfs.graph.ell import build_ell_sharded, build_ell_weights_sharded
from tpu_bfs.parallel.collectives import (
    check_delta_bits,
    default_row_gather_caps,
    dense_min_wire_bytes,
    minplus_rows_branch_count,
    minplus_rows_branch_labels,
    minplus_rows_wire_bytes_per_level,
    normalize_caps,
    ring_reduce_scatter,
    sparse_rows_exchange_min,
)
from tpu_bfs.parallel.compat import shard_map
from tpu_bfs.parallel.dist_bfs import make_mesh
from tpu_bfs.utils.aot import AotProgramProtocol
from tpu_bfs.workloads.sssp import (
    INF_W,
    SsspBatchResult,
    _check_kernel_ident,
    _make_min_plus_expand,
    _make_summaries,
)

#: Exchange impls of the distributed delta-stepping engine. ``sparse``
#: (and its predictive form) is 1D-only: the queue-style gather is an
#: all-gather over the single partition axis; the 2D mesh exchanges
#: hierarchically through ``allreduce``.
EXCHANGES = ("ring", "allreduce", "sparse")


def _make_dist_sssp_core(
    sell, L: int, mesh: Mesh, exchange: str, sparse_caps, delta_bits,
    delta: int, predict: bool, expand_light, expand_full,
):
    p_count = sell.num_shards
    v_loc = sell.v_loc
    v_pad = sell.v_pad
    axes = tuple(mesh.axis_names)
    nb = (
        minplus_rows_branch_count(sparse_caps, delta_bits, predict=predict)
        if exchange == "sparse" else 1
    )
    delta_i = jnp.int32(delta)

    def psum_all(x):
        for ax in axes:
            x = lax.psum(x, ax)
        return x

    def pmin_all(x):
        for ax in axes:
            x = lax.pmin(x, ax)
        return x

    def chip_fn(arrs, dist0, max_rounds):
        # Block specs keep a leading shard axis of size 1; drop it — the
        # per-shard arrays then carry the single-chip expansion's exact
        # key layout, so _make_min_plus_expand runs on local tiles.
        arrs = {k: a[0] for k, a in arrs.items()}
        if len(axes) == 1:
            p = lax.axis_index(axes[0])
        else:
            p = lax.axis_index(axes[0]) * mesh.shape[axes[1]] + lax.axis_index(
                axes[1]
            )

        def own(full):
            # Global rank r lives on chip r % P at local row r // P.
            return lax.dynamic_index_in_dim(
                full[:v_pad].reshape(v_loc, p_count, L), p, axis=1,
                keepdims=False,
            )

        def contrib_of(new_loc, prev_tbl):
            # The previous replica with this chip's own rows substituted:
            # pmin/ring-min across chips then yields the updated table
            # (new <= prev at own rows; every other chip holds prev there).
            return lax.dynamic_update_index_in_dim(
                prev_tbl.reshape(v_loc, p_count, L), new_loc, p, axis=1
            ).reshape(v_pad, L)

        def dense_gather(new_loc):
            # All chips' owned rows together cover every row with the
            # updated values — one all-gather rebuilds rank order.
            g = lax.all_gather(new_loc, axes[0])  # [P, v_loc, L]
            return g.transpose(1, 0, 2).reshape(v_pad, L)

        def do_exchange(new_loc, prev_tbl, own_prev, prev_biggest, growing):
            if exchange == "sparse":
                return sparse_rows_exchange_min(
                    new_loc, own_prev, prev_tbl, axes[0],
                    caps=sparse_caps, out_rows=v_pad,
                    gid_of=lambda ids: ids * p_count + p,
                    dense_fn=lambda: dense_gather(new_loc),
                    ident=INF_W, delta_bits=delta_bits,
                    gid_of_src=lambda ids, src: ids * p_count + src,
                    predict=predict,
                    prev_biggest=prev_biggest if predict else None,
                    growing=growing if predict else None,
                )
            contrib = contrib_of(new_loc, prev_tbl)
            if exchange == "ring":
                rs = ring_reduce_scatter(contrib, axes[0], p_count, jnp.minimum)
                full = lax.all_gather(rs, axes[0], tiled=True)
            else:
                full = pmin_all(contrib)
            return full, jnp.int32(0), prev_biggest

        def cond(carry):
            _, _, alive, rounds = carry[:4]
            return alive & (rounds < max_rounds)

        def body(carry):
            dist, hi, _, rounds, bcs, pb, pc, ppc = carry
            # Current bucket + settled rows relax out; later buckets mask
            # to INF (the delta-stepping invariant, workloads/sssp.py).
            masked = jnp.where(dist < hi, dist, INF_W)
            own_prev = own(dist)
            new_loc = jnp.minimum(own_prev, expand_light(arrs, masked))
            # The light-sweep convergence test must be mesh-uniform (it
            # gates the close cond): the one per-round scalar psum.
            changed_l = psum_all(
                jnp.any(new_loc < own_prev).astype(jnp.int32)
            ) > 0
            # Bucket stabilized: one relaxation over ALL edges before the
            # bound advances. When changed_l is false new_loc == own_prev
            # globally, so closing over the pre-light ``masked`` equals
            # the single-chip close over the post-light table exactly.
            new2 = lax.cond(
                changed_l,
                lambda: new_loc,
                lambda: jnp.minimum(new_loc, expand_full(arrs, masked)),
            )
            growing = pc > ppc
            full2, branch, biggest = do_exchange(
                new2, dist[:v_pad], own_prev, pb, growing
            )
            bcs = bcs + (jnp.arange(nb, dtype=jnp.int32) == branch)
            # Post-exchange decisions read the REPLICATED table — free of
            # collectives, like the OR engines' gathered-frontier tests.
            prev_tbl = dist[:v_pad]
            changed_rows = jnp.sum(
                jnp.any(full2 < prev_tbl, axis=1).astype(jnp.int32)
            )
            hi2 = jnp.where(changed_l, hi, hi + delta_i)
            unsettled = jnp.any((full2 < INF_W) & (full2 >= hi2))
            dist_next = jnp.concatenate(
                [full2, jnp.full((1, L), INF_W, jnp.int32)]
            )
            return (
                dist_next, hi2, (changed_rows > 0) | unsettled, rounds + 1,
                bcs, biggest, changed_rows, pc,
            )

        dist, _, alive, rounds, bcs, _, _, _ = lax.while_loop(
            cond, body,
            (
                dist0, delta_i, jnp.bool_(True), jnp.int32(0),
                jnp.zeros(nb, jnp.int32), jnp.int32(0), jnp.int32(0),
                jnp.int32(0),
            ),
        )
        return dist, rounds, alive, bcs

    def build(n_arrs):
        shard_spec = P(axes) if len(axes) > 1 else P(axes[0])
        specs = {k: shard_spec for k in n_arrs}
        core = jax.jit(
            shard_map(
                chip_fn,
                mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )
        )
        device_arrs = {
            k: jax.device_put(v, NamedSharding(mesh, shard_spec))
            for k, v in n_arrs.items()
        }
        return core, device_arrs

    return build


class _DistSsspDispatch:
    """An in-flight distributed SSSP batch (async device references;
    fetch blocks). The dist form additionally carries the exchange
    branch counters — a while-loop output priced at fetch."""

    __slots__ = ("sources", "dist", "rounds", "alive", "bc", "t0")

    def __init__(self, sources, dist, rounds, alive, bc, t0):
        self.sources = sources
        self.dist = dist
        self.rounds = rounds
        self.alive = alive
        self.bc = bc
        self.t0 = t0


class DistSsspEngine(AotProgramProtocol):
    """Multi-chip delta-stepping SSSP: sharded ELL + weights, replicated
    distance table.

    Bit-identical to the single-chip :class:`SsspEngine` (same rounds,
    same distances — fuzz-pinned); per-chip HBM is O(v_pad * 4L) for the
    replicated table plus the chip's edge+weight shard. A 1D mesh takes
    any of :data:`EXCHANGES`; a 2D mesh exchanges hierarchically
    (``allreduce`` over both axes) — its partition benefit is the halved
    per-axis collective span, not a different byte volume."""

    kind = "sssp"

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh | int | None = None,
        *,
        lanes: int = 32,
        kcap: int = 64,
        delta: int = 0,
        max_rounds: int = 4096,
        exchange: str = "ring",
        sparse_caps: int | tuple[int, ...] | None = None,
        delta_bits: tuple[int, ...] = (),
        predict: bool = False,
        expand_impl: str = "xla",
        interpret: bool | None = None,
    ):
        from tpu_bfs.algorithms._packed_common import validate_expand_impl

        validate_expand_impl(expand_impl)
        self.expand_impl = expand_impl
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self._interpret = bool(interpret)
        if not isinstance(graph, Graph):
            raise ValueError(
                "DistSsspEngine needs the host Graph (the weights plane "
                "and result extraction both read it)"
            )
        if graph.weights is None:
            raise ValueError(
                "sssp needs a weighted graph (generate with weights=W or "
                "attach a weights plane)"
            )
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if exchange not in EXCHANGES:
            raise ValueError(
                f"unknown exchange {exchange!r}; have {EXCHANGES}"
            )
        self.mesh = mesh if isinstance(mesh, Mesh) else make_mesh(mesh)
        axes = tuple(self.mesh.axis_names)
        if len(axes) > 1 and exchange != "allreduce":
            raise ValueError(
                f"a 2D mesh exchanges hierarchically — exchange="
                f"'allreduce', not {exchange!r} (the queue-style and ring "
                "forms are defined over the single 1D partition axis)"
            )
        if delta_bits and exchange != "sparse":
            raise ValueError(
                "delta_bits compresses the SPARSE id+value exchange's id "
                f"stream (ISSUE 7); exchange={exchange!r} ships whole "
                "slabs — use exchange='sparse'"
            )
        if predict and exchange != "sparse":
            raise ValueError(
                "predict arms the sparse exchange's history predictor — "
                "use exchange='sparse'"
            )
        p_count = self.mesh.devices.size
        self.sell = build_ell_sharded(graph, p_count, kcap=kcap)
        sell = self.sell
        self.host_graph = graph
        self.lanes = int(lanes)
        self.num_vertices = graph.num_vertices
        self.undirected = graph.undirected
        self.max_rounds = int(max_rounds)
        self._exchange = exchange
        self.predict = bool(predict)
        wmax = int(graph.weights.max()) if len(graph.weights) else 1
        self.wmax = wmax
        if delta <= 0:
            delta = max(1, int(round(float(graph.weights.mean())))) \
                if len(graph.weights) else 1
        self.delta = int(delta)
        # The replicated table is RANK-order (row of vertex v = rank[v]);
        # unlike the packed dist engines there is no chip-major reassembly
        # — the loop's output is already the full replica.
        self._act = sell.v_pad
        self._rank = sell.rank.astype(np.int64)
        self._table_rows = sell.v_pad + 1  # + the all-INF sentinel row
        src, dst = graph.coo
        seen = np.zeros(graph.num_vertices, dtype=bool)
        seen[src] = True
        seen[dst] = True
        self._iso_mask = ~seen

        self.delta_bits = check_delta_bits(delta_bits)
        if sparse_caps is None:
            sparse_caps = default_row_gather_caps(
                sell.v_loc, self.lanes, self.delta_bits
            )
        elif isinstance(sparse_caps, int):
            sparse_caps = (sparse_caps,)
        self.sparse_caps = normalize_caps(sparse_caps)
        self.last_exchange_level_counts: np.ndarray | None = None
        self.last_exchange_bytes: float | None = None

        spec = ExpandSpec(
            kcap=sell.kcap,
            heavy=sell.heavy_per_shard > 0,
            num_virtual=sell.num_virtual,
            fold_steps=sell.fold_steps,
            light_meta=tuple((k, blk.shape[1]) for k, blk in sell.light),
            tail_rows=sell.tail_rows,
        )
        n_arrs = self._build_arrays()
        if expand_impl == "pallas":
            from tpu_bfs.algorithms._packed_common import make_pallas_expand
            from tpu_bfs.ops.ell_expand import validate_kernel_width

            _check_kernel_ident()
            validate_kernel_width(
                self.lanes, self._interpret,
                kernel="dist-sssp expand_impl='pallas'",
            )
            expand_light = make_pallas_expand(
                spec, self.lanes, op="minplus", wsuf="wl",
                interpret=self._interpret,
            )
            expand_full = make_pallas_expand(
                spec, self.lanes, op="minplus", wsuf="w",
                interpret=self._interpret,
            )
        else:
            expand_light = _make_min_plus_expand(spec, self.lanes, "wl")
            expand_full = _make_min_plus_expand(spec, self.lanes, "w")
        build = _make_dist_sssp_core(
            sell, self.lanes, self.mesh, exchange, self.sparse_caps,
            self.delta_bits, self.delta, self.predict, expand_light,
            expand_full,
        )
        self._dist_core, self.arrs = build(n_arrs)
        rows_seed, L = sell.v_pad + 1, self.lanes
        self._seed_k = jax.jit(
            lambda r, c: jnp.full((rows_seed, L), INF_W, jnp.int32)
            .at[r, c]
            .min(jnp.int32(0))
        )
        self._summaries = _make_summaries(sell.v_pad)
        self._warmed = False

    def _build_arrays(self) -> dict:
        """Per-shard expansion arrays, stacked on the shard axis: the
        index slabs exactly as the dist-wide engine builds them, plus the
        sharded weight planes slot-aligned with them (``virtual_w``/
        ``virtual_wl``, ``light{i}_w``/``light{i}_wl`` — after the
        shard-axis drop these are the single-chip min-plus expansion's
        exact keys)."""
        sell = self.sell
        pallas = self.expand_impl == "pallas"
        n_arrs = {}
        if sell.heavy_per_shard > 0:
            n_arrs["virtual_t"] = np.ascontiguousarray(
                sell.virtual.transpose(0, 2, 1)
            )
            n_arrs["fold_pad_map"] = sell.fold_pad_map
            n_arrs["heavy_pick"] = sell.heavy_pick
        for i, (k, blocks) in enumerate(sell.light):
            n_arrs[f"light{i}_t"] = np.ascontiguousarray(
                blocks.transpose(0, 2, 1)
            )
        vw, lw = build_ell_weights_sharded(self.host_graph, sell, pad=0)
        delta = self.delta

        def _weight_planes(prefix, wt):
            # wt: [P, k, n] transposed like the index slabs. Light plane:
            # heavy-edge slots absorb under min; pad slots (weight 0)
            # gather the all-INF sentinel row either way.
            n_arrs[f"{prefix}_w"] = wt
            n_arrs[f"{prefix}_wl"] = np.where(wt <= delta, wt, INF_W).astype(
                np.int32
            )

        if vw is not None:
            _weight_planes(
                "virtual",
                np.ascontiguousarray(vw.transpose(0, 2, 1)).astype(np.int32),
            )
        for i, w in enumerate(lw):
            _weight_planes(
                f"light{i}",
                np.ascontiguousarray(w.transpose(0, 2, 1)).astype(np.int32),
            )
        if pallas:
            from tpu_bfs.graph.ell import pad_gate_blocks

            # Per-shard sentinel-padded whole-block tables (index sentinel
            # = the all-INF row v_pad; weight pad 0 — INF + 0 stays the
            # min identity), stacked on the shard axis like everything.
            for name in ["virtual_t"] if sell.heavy_per_shard > 0 else []:
                n_arrs["virtual_gt"] = np.stack([
                    pad_gate_blocks(n_arrs[name][p], sell.v_pad)
                    for p in range(sell.num_shards)
                ])
            for i in range(len(sell.light)):
                n_arrs[f"light{i}_gt"] = np.stack([
                    pad_gate_blocks(n_arrs[f"light{i}_t"][p], sell.v_pad)
                    for p in range(sell.num_shards)
                ])
            for prefix in (
                ["virtual"] if sell.heavy_per_shard > 0 else []
            ) + [f"light{i}" for i in range(len(sell.light))]:
                for suf in ("w", "wl"):
                    n_arrs[f"{prefix}_{suf}_gt"] = np.stack([
                        pad_gate_blocks(n_arrs[f"{prefix}_{suf}"][p], 0)
                        for p in range(sell.num_shards)
                    ])
        return n_arrs

    def wire_bytes_per_level(self) -> list[float]:
        """Modeled off-chip bytes per round per exchange branch,
        index-aligned with the dispatched loop's branch counters."""
        p = self.sell.num_shards
        if self._exchange == "sparse":
            return minplus_rows_wire_bytes_per_level(
                p, self.sell.v_loc, self.lanes, self.sparse_caps,
                self.delta_bits, predict=self.predict,
            )
        return [dense_min_wire_bytes(p, self.sell.v_loc, self.lanes)]

    def exchange_branch_labels(self) -> list[str]:
        if self._exchange == "sparse":
            return minplus_rows_branch_labels(
                self.sparse_caps, self.delta_bits, predict=self.predict
            )
        return ["dense"]

    def _iso_of(self, sources: np.ndarray):
        # Every vertex has a row here, so results are already correct;
        # the mask only labels the extras symmetric with the single-chip
        # engine's row-less isolated sources.
        return self._iso_mask[np.asarray(sources, np.int64)]

    def _seed_dev(self, sources: np.ndarray):
        rows = self._rank[np.asarray(sources, dtype=np.int64)].astype(np.int32)
        lanes_idx = np.arange(len(sources), dtype=np.int32)
        return self._seed_k(jnp.asarray(rows), jnp.asarray(lanes_idx))

    def dispatch(self, sources, **_ignored) -> _DistSsspDispatch:
        if _faults.ACTIVE is not None:
            # Chaos-harness injection site: the same workload site as the
            # single-chip engine (tpu_bfs/faults.py).
            _faults.ACTIVE.hit("sssp_dispatch", lanes=self.lanes)
        sources = np.asarray(sources, dtype=np.int64)
        if sources.ndim != 1 or not (1 <= len(sources) <= self.lanes):
            raise ValueError(
                f"need 1..{self.lanes} sources, got {sources.shape}"
            )
        if sources.min() < 0 or sources.max() >= self.num_vertices:
            raise ValueError("source out of range")
        dist0 = self._seed_dev(sources)
        t0 = time.perf_counter()
        dist, rounds, alive, bc = self._dist_core(
            self.arrs, dist0, jnp.int32(self.max_rounds)
        )
        return _DistSsspDispatch(sources, dist, rounds, alive, bc, t0)

    def fetch(self, pend: _DistSsspDispatch, *, check_cap: bool = True,
              time_it: bool = False) -> SsspBatchResult:
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.hit("sssp_fetch", lanes=self.lanes)
        rounds = int(pend.rounds)  # blocks until the loop finishes
        elapsed = (time.perf_counter() - pend.t0) if time_it else None
        self._warmed = True
        if check_cap and bool(pend.alive):
            raise RuntimeError(
                f"sssp still relaxing after {rounds} rounds "
                f"(max_rounds={self.max_rounds}) — raise max_rounds or "
                f"delta for this graph"
            )
        # Exchange accounting: the loop finished (rounds read), so the
        # counters are ready — price them with the (min, +) byte model.
        counts = np.asarray(pend.bc)
        self.last_exchange_level_counts = counts
        self.last_exchange_bytes = float(
            np.dot(counts, self.wire_bytes_per_level())
        )
        reached, ecc = self._summaries(pend.dist)
        iso = self._iso_of(pend.sources)
        return SsspBatchResult(
            self, pend.sources, pend.dist, rounds, reached, ecc,
            iso if iso.any() else None, elapsed_s=elapsed,
        )

    def run(self, sources, *, time_it: bool = False, check_cap: bool = True,
            **_ignored) -> SsspBatchResult:
        if time_it and not self._warmed:
            int(self.dispatch(sources).rounds)
        return self.fetch(
            self.dispatch(sources), check_cap=check_cap, time_it=time_it
        )

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the sharded
        delta-stepping loop whose min-exchange branch uniformity the
        taint pass proves (plus the replicated summaries reduction). The
        seed table is pre-replicated — per-batch seed movement is
        inherent to dispatch, so the transfer guard watches the LOOP."""
        rep = NamedSharding(self.mesh, P())
        dist0 = jax.device_put(self._seed_dev(np.asarray([0])), rep)
        ml = jax.device_put(jnp.int32(64), rep)
        return [
            ("dist_sssp_core", self._dist_core, (self.arrs, dist0, ml)),
            ("sssp_summaries", self._summaries, (dist0,)),
        ]

    def export_programs(self):
        """AOT inventory (ISSUE 9; utils/aot.py): the sharded
        delta-stepping core — the multi-chip compile a preheat skips."""
        return [
            ("dist_sssp_core", "_dist_core", fn, args)
            for name, fn, args in self.analysis_programs()
            if name == "dist_sssp_core"
        ]
